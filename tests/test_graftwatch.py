"""graftwatch trajectory schema + regression gate (tools/graftwatch.py).

Pure-host lanes (no lowering): the run-record schema validator, the
bench-history backfill audit (every entry of ``bench_suite.json`` and
the ``BENCH_r0*.json`` attempt logs is schema-valid or explicitly
grandfathered with its missing fields listed — ISSUE 7 satellite), and
the rolling-baseline gate — including the acceptance-criterion negative
test: an injected synthetic 2x-slower record exits nonzero.
"""

import json

import pytest

from tools import graftwatch as gw

_FP = "cpu8-test-c2"
_DEV = {"platform": "cpu", "n_devices": 8, "device_kind": "cpu"}


def _record(ts: str, eps: float, p50_ms: float = 1.0):
    return gw.make_record(
        plane="a2a",
        config={"mesh": "2x4", "batch": 256, "dim": 8, "steps": 4,
                "blocks": 3, "source": "graftwatch-quick"},
        eps=eps, eps_min=eps * 0.95, eps_max=eps * 1.05,
        scope={stage: {"calls": 12, "p50_ms": p50_ms,
                       "p95_ms": p50_ms * 1.3, "expected_bytes": 4096,
                       "gbps_p50": 0.1} for stage in ("pull", "push")},
        memory={"pull": {"argument_bytes": 1 << 20, "output_bytes": 1024,
                         "temp_bytes": 2048, "alias_bytes": 0,
                         "generated_code_bytes": 0,
                         "peak_bytes": (1 << 20) + 3072},
                "push": None},
        fingerprint=_FP, device=_DEV, ts=ts)


# --- schema ------------------------------------------------------------------

def test_record_schema_roundtrip():
    rec = _record("2026-08-01T00:00:00+00:00", 1000.0)
    assert gw.validate_record(rec) == []
    # provenance fields are live (sha + versions resolved at build time)
    assert rec["schema_version"] == gw.SCHEMA_VERSION
    assert rec["git_sha"] and rec["jax"] and rec["jaxlib"]
    # survives a JSON roundtrip (the JSONL on-disk form)
    assert gw.validate_record(json.loads(json.dumps(rec))) == []


@pytest.mark.parametrize("mutate,fragment", [
    (lambda r: r.pop("git_sha"), "git_sha"),
    (lambda r: r.pop("fingerprint"), "fingerprint"),
    (lambda r: r.update(schema_version=99), "schema_version"),
    (lambda r: r.update(eps=-1.0), "eps"),
    (lambda r: r.update(eps=True), "eps"),
    (lambda r: r.update(eps_min=r["eps_max"] * 2), "band"),
    (lambda r: r.update(device={"platform": "cpu"}), "n_devices"),
    (lambda r: r["scope"]["pull"].pop("p50_ms"), "p50_ms"),
])
def test_record_schema_lists_each_problem(mutate, fragment):
    rec = _record("2026-08-01T00:00:00+00:00", 1000.0)
    mutate(rec)
    problems = gw.validate_record(rec)
    assert problems and any(fragment in p for p in problems), problems


def test_append_refuses_invalid_record(tmp_path):
    rec = _record("2026-08-01T00:00:00+00:00", 1000.0)
    del rec["ts"]
    with pytest.raises(ValueError, match="schema-invalid"):
        gw.append_record(str(tmp_path / "t.jsonl"), rec)


def test_load_trajectory_rejects_corrupt_lines(tmp_path):
    path = tmp_path / "t.jsonl"
    good = _record("2026-08-01T00:00:00+00:00", 1000.0)
    path.write_text(json.dumps(good) + "\nnot json\n")
    with pytest.raises(ValueError, match="invalid record"):
        gw.load_trajectory(str(path))
    assert gw.load_trajectory(str(tmp_path / "missing.jsonl")) == []


# --- bench-history backfill (satellite) --------------------------------------

def test_bench_history_all_readable():
    """Every committed bench entry passes the schema or is explicitly
    grandfathered with its missing fields listed — no silently
    unreadable history."""
    invalid, lines = gw.validate_bench_files()
    assert invalid == 0, [ln for ln in lines if ln.startswith("INVALID")]
    assert any(ln.startswith("ok") for ln in lines)
    for ln in lines:
        if ln.startswith("grandfathered"):
            assert "missing [" in ln and "missing []" not in ln, ln


def test_classify_bench_entry_shapes():
    ok, missing = gw.classify_bench_entry(
        {"metric": "m", "value": 1.0, "unit": "examples/s",
         "vs_baseline": 1.0, "config": {}, "ts": "2026-01-01T00:00:00"})
    assert ok == "ok" and missing == []
    # honest error records are first-class bench history
    assert gw.classify_bench_entry(
        {"metric": "m", "error": "device wedged"}) == ("ok", [])
    status, missing = gw.classify_bench_entry({"metric": "m", "value": 1.0})
    assert status == "grandfathered" and "ts" in missing
    # the legacy driver attempt logs grandfather whole, with a reason
    status, missing = gw.classify_bench_entry(
        {"n": 1, "cmd": "python bench.py", "rc": 0, "tail": "..."})
    assert status == "grandfathered" and missing
    assert gw.classify_bench_entry([1, 2])[0] == "invalid"
    assert gw.classify_bench_entry({"value": 1.0})[0] == "invalid"


def test_record_from_bench_conversion():
    entry = {"metric": "deepfm_dim9_examples_per_sec_cpu8",
             "value": 1000.0, "unit": "examples/s", "vs_baseline": 0.01,
             "eps_min": 900.0, "eps_max": 1100.0,
             "config": {"plane": "a2a+grouped", "batch": 4096, "dim": 9},
             "ts": "2026-08-01T00:00:00+00:00"}
    rec = gw.record_from_bench(entry, fingerprint=_FP, device=_DEV)
    assert rec is not None and gw.validate_record(rec) == []
    assert rec["plane"] == "a2a+grouped" and rec["eps"] == 1000.0
    assert rec["config"]["source"] == "bench"
    assert rec["scope"] is None          # bench entries carry no spans
    # inconvertible shapes: errors, non-throughput units, missing band
    assert gw.record_from_bench({"metric": "m", "error": "x"}) is None
    assert gw.record_from_bench(
        {"metric": "m", "value": 1.0, "unit": "GB/s"}) is None
    assert gw.record_from_bench(
        {"metric": "m", "value": 1.0, "unit": "examples/s"}) is None


def _ingest_entry():
    return {"metric": "deepfm_dim9_ingest_ab_examples_per_sec_cpu8",
            "value": 1800.0, "unit": "examples/s", "vs_baseline": 0.01,
            "eps_min": 1700.0, "eps_max": 1900.0,
            "stream_vs_mem": 0.97,
            "ingest": {"stall_p95_ms": 0.0, "stall_p99_ms": 0.0,
                       "bad_rows": 0, "pops": 15},
            "config": {"kind": "ingest_ab", "batch": 4096, "dim": 9},
            "ts": "2026-08-01T00:00:00+00:00"}


def test_record_from_bench_ingest_kind():
    """Ingest A/B entries convert to the synthetic `ingest` plane with
    the stall/bad-row evidence attached and schema-validated."""
    rec = gw.record_from_bench(_ingest_entry(), fingerprint=_FP,
                               device=_DEV)
    assert rec is not None and gw.validate_record(rec) == []
    assert rec["plane"] == "ingest" and rec["eps"] == 1800.0
    assert rec["ingest"]["stall_p95_ms"] == 0.0
    assert rec["ingest"]["stream_vs_mem"] == 0.97
    assert rec["ingest"]["bad_rows"] == 0


@pytest.mark.parametrize("mutate, fragment", [
    (lambda i: i.__setitem__("stall_p95_ms", -1.0),
     "ingest.stall_p95_ms"),
    (lambda i: i.__setitem__("stall_p99_ms", "zero"),
     "ingest.stall_p99_ms"),
    (lambda i: i.__setitem__("bad_rows", -2), "ingest.bad_rows"),
    (lambda i: i.__setitem__("bad_rows", 1.5), "ingest.bad_rows"),
    (lambda i: i.__setitem__("pops", None), "ingest.pops"),
    (lambda i: i.__setitem__("stream_vs_mem", 0.0),
     "ingest.stream_vs_mem"),
])
def test_ingest_record_schema_lists_problems(mutate, fragment):
    rec = gw.record_from_bench(_ingest_entry(), fingerprint=_FP,
                               device=_DEV)
    mutate(rec["ingest"])
    problems = gw.validate_record(rec)
    assert problems and any(fragment in p for p in problems), problems


def test_ingest_record_missing_evidence_fails_loudly():
    """A bench entry missing the A/B ratio or stall evidence must be
    REJECTED, not defaulted to the perfect value the gate verifies
    (stream_vs_mem=1.0 / stall_p95_ms=0.0 are exactly those)."""
    e = _ingest_entry()
    del e["stream_vs_mem"]
    with pytest.raises(ValueError, match="stream_vs_mem"):
        gw.record_from_bench(e, fingerprint=_FP, device=_DEV)
    e = _ingest_entry()
    del e["ingest"]["stall_p95_ms"]
    with pytest.raises(ValueError, match="stall_p95_ms"):
        gw.record_from_bench(e, fingerprint=_FP, device=_DEV)


def test_ingest_record_non_dict_section_rejected():
    rec = gw.record_from_bench(_ingest_entry(), fingerprint=_FP,
                               device=_DEV)
    rec["ingest"] = ["not", "a", "dict"]
    assert any("ingest:" in p for p in gw.validate_record(rec))


# --- the regression gate -----------------------------------------------------

def _trajectory():
    return [_record("2026-08-01T00:00:00+00:00", 1000.0),
            _record("2026-08-02T00:00:00+00:00", 1050.0),
            _record("2026-08-03T00:00:00+00:00", 980.0)]


def test_gate_healthy_and_soft_pass():
    failures, lines = gw.gate(_trajectory())
    assert failures == 0
    assert any("ok" in ln and "a2a/eps" in ln for ln in lines)
    # a single record (first run on new hardware) soft-passes with a warn
    failures, lines = gw.gate(_trajectory()[:1])
    assert failures == 0 and "no baseline" in lines[0]
    # an empty trajectory warns instead of passing silently
    failures, lines = gw.gate([])
    assert failures == 0 and "empty" in lines[0]


def test_gate_catches_injected_2x_regression(tmp_path):
    """THE acceptance-criterion negative test: a synthetic 2x-slower
    record (eps halved, p50 doubled) against a healthy baseline exits
    nonzero through the CLI."""
    records = _trajectory()
    records.append(_record("2026-08-04T00:00:00+00:00", 500.0,
                           p50_ms=2.0))
    failures, lines = gw.gate(records)
    assert failures >= 1
    assert any("REGRESSION" in ln and "eps" in ln for ln in lines)
    assert any("REGRESSION" in ln and "p50_ms" in ln for ln in lines)
    path = tmp_path / "t.jsonl"
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    assert gw.main(["--gate", "--trajectory", str(path)]) == 1
    # drop the injected record -> the same CLI invocation is clean
    with open(path, "w") as f:
        for r in records[:-1]:
            f.write(json.dumps(r) + "\n")
    assert gw.main(["--gate", "--trajectory", str(path)]) == 0


def test_gate_noise_band_derived_from_eps_spread():
    """A wide measured band (noisy box) must widen the gate: the same
    -40% delta that fails a tight-band group passes a wide-band one."""
    tight = _trajectory()
    tight.append(_record("2026-08-04T00:00:00+00:00", 600.0))
    failures, _ = gw.gate(tight)
    assert failures >= 1                      # 40% drop vs ~35% band
    noisy = []
    for i, eps in enumerate((1000.0, 1050.0, 980.0, 600.0)):
        r = _record(f"2026-08-0{i + 1}T00:00:00+00:00", eps)
        r["eps_min"], r["eps_max"] = eps * 0.6, eps * 1.4   # 80% spread
        noisy.append(r)
    failures, lines = gw.gate(noisy)
    assert failures == 0, lines


def test_gate_groups_by_fingerprint():
    """Records from different hardware never gate each other."""
    records = _trajectory()
    slow = _record("2026-08-04T00:00:00+00:00", 100.0)
    slow["fingerprint"] = "tpu8-real-device"
    records.append(slow)
    failures, lines = gw.gate(records)
    assert failures == 0
    assert any("no baseline" in ln and "tpu8-real-device" in ln
               for ln in lines)


def test_committed_trajectory_gates_clean():
    """The repo's own BENCH_trajectory.jsonl must load schema-valid and
    gate clean — a PR that lands a regressing record (or corrupts the
    file) fails here before CI's gate even runs."""
    records = gw.load_trajectory(gw.TRAJECTORY_FILE)
    assert records, "committed trajectory is missing or empty"
    failures, lines = gw.gate(records)
    assert failures == 0, lines


# --- recovery records (graftload --respawn / chaos_smoke lanes) --------------

_RECOVERY_CFG = {"lane": "kill-mid-fit", "autosave_every": 2,
                 "source": "chaos_smoke"}


def _recovery_record(ts: str, mttr_s: float):
    return gw.make_recovery_record(
        mttr_s=mttr_s, steps_lost=1, bytes_replayed=4096,
        config=_RECOVERY_CFG, fingerprint=_FP, device=_DEV, ts=ts)


def test_recovery_record_schema_roundtrip():
    rec = _recovery_record("2026-08-01T00:00:00+00:00", 2.5)
    assert gw.validate_record(rec) == []
    assert rec["plane"] == "recovery"
    # eps is recoveries/s so the throughput gate reads MTTR directly
    assert rec["eps"] == pytest.approx(1.0 / 2.5)
    assert rec["recovery"]["mttr_s"] == 2.5
    assert gw.validate_record(json.loads(json.dumps(rec))) == []


def test_make_recovery_record_rejects_nonpositive_mttr():
    for bad in (0.0, -1.0):
        with pytest.raises(ValueError, match="mttr_s"):
            gw.make_recovery_record(
                mttr_s=bad, steps_lost=0, bytes_replayed=0,
                config=_RECOVERY_CFG, fingerprint=_FP, device=_DEV)


@pytest.mark.parametrize("mutate, fragment", [
    (lambda r: r["recovery"].__setitem__("mttr_s", 0.0),
     "recovery.mttr_s"),
    (lambda r: r["recovery"].__setitem__("mttr_s", "fast"),
     "recovery.mttr_s"),
    (lambda r: r["recovery"].__setitem__("mttr_s", True),
     "recovery.mttr_s"),
    (lambda r: r["recovery"].__setitem__("steps_lost", -1),
     "recovery.steps_lost"),
    (lambda r: r["recovery"].__setitem__("steps_lost", 1.5),
     "recovery.steps_lost"),
    (lambda r: r["recovery"].__setitem__("bytes_replayed", None),
     "recovery.bytes_replayed"),
    (lambda r: r.__setitem__("recovery", ["not", "a", "dict"]),
     "recovery:"),
])
def test_recovery_record_schema_lists_problems(mutate, fragment):
    rec = _recovery_record("2026-08-01T00:00:00+00:00", 2.5)
    mutate(rec)
    problems = gw.validate_record(rec)
    assert problems and any(fragment in p for p in problems), problems


def test_gate_catches_slower_recovery():
    """eps = 1/MTTR by construction, so a 2x-slower respawn trips the
    SAME rolling gate as a throughput regression — no recovery-specific
    gate code to rot."""
    records = [_recovery_record(f"2026-08-0{i + 1}T00:00:00+00:00", m)
               for i, m in enumerate((2.0, 2.1, 1.9))]
    failures, _ = gw.gate(records)
    assert failures == 0
    records.append(_recovery_record("2026-08-04T00:00:00+00:00", 4.0))
    failures, lines = gw.gate(records)
    assert failures >= 1, lines
    assert any("recovery" in ln for ln in lines)
