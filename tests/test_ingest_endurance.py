"""Endurance mini-lane: streaming ingest + offload tier + delta
checkpoints composed under one real data feed (the ROADMAP item-5
"month-long online learning" story compressed into a slow-lane run).

>= 2000 steps are trained from on-disk TSV shards through the parallel
reader pool, with one feature offloaded (host store + bounded HBM
cache, its own persist path) and the in-HBM features delta-checkpointed
every chunk. Asserted along the way / at the end:

* the ``oe_mem_*`` memory-ledger gauges stay FLAT: the ingest ring is
  bounded (batches + bytes), the offload store/book byte gauges do not
  grow, the resident-row count stays within the cache capacity — no
  component leaks host memory as a function of steps;
* the delta chain verifies clean at the end (every committed entry
  checksums, no torn tail) and a fresh chain restore reproduces the
  live tracked rows EXACTLY;
* the offload tier's own persist commits and its cache EVICTED during
  the run (the working set exceeds the cache — the composition is only
  a statement if the eviction path was actually inside it);
* the stream never failed a reader, and post-warmup ingest stalls are
  zero at this step rate.
"""

import itertools

import numpy as np
import pytest

pytestmark = pytest.mark.slow

FEATURES = ("C1", "C2", "C3")
KEEP = set(FEATURES) | {f + ":linear" for f in FEATURES}

STEPS = 2000
CHUNK = 250
BATCH = 64
VOCAB = 1 << 14
CACHE = 1 << 10


def _prune(batch):
    return {**batch, "sparse": {k: v for k, v in batch["sparse"].items()
                                if k in KEEP}}


def test_endurance_ingest_offload_delta(tmp_path):
    import jax
    import optax
    from openembedding_tpu import (EmbeddingCollection, EmbeddingSpec,
                                   EmbeddingVariableMeta, Trainer)
    from openembedding_tpu import checkpoint as ckpt
    from openembedding_tpu import checkpoint_delta as cdel
    from openembedding_tpu.data import stream
    from openembedding_tpu.models import deepctr
    from openembedding_tpu.offload import ShardedOffloadedTable
    from openembedding_tpu.parallel.mesh import create_mesh
    from openembedding_tpu.utils import observability

    mesh = create_mesh(1, len(jax.devices()))
    shard_dir = str(tmp_path / "shards")
    stream.write_synthetic_shards(shard_dir, num_shards=4,
                                  rows_per_shard=4096, seed=13)
    opt = {"category": "adagrad", "learning_rate": 0.01}
    init = {"category": "constant", "value": 0.01}
    # C1 rides the offload tier (host store >> HBM cache, its own
    # persist path); C2/C3 (+linears) are in-HBM and delta-tracked
    uid = ShardedOffloadedTable(
        "C1", EmbeddingVariableMeta(embedding_dim=4,
                                    vocabulary_size=VOCAB),
        opt, init, vocab=VOCAB, cache_capacity=CACHE, mesh=mesh,
        backing_dir=str(tmp_path / "store"))
    lin = ShardedOffloadedTable(
        "C1:linear", EmbeddingVariableMeta(embedding_dim=1,
                                           vocabulary_size=VOCAB),
        opt, init, vocab=VOCAB, cache_capacity=CACHE, mesh=mesh,
        backing_dir=str(tmp_path / "store"))
    specs = [uid.embedding_spec(), lin.embedding_spec()]
    for n in ("C2", "C3"):
        specs.append(EmbeddingSpec(name=n, input_dim=VOCAB,
                                   output_dim=4, optimizer=opt,
                                   initializer=init))
        specs.append(EmbeddingSpec(name=n + ":linear", input_dim=VOCAB,
                                   output_dim=1, optimizer=opt,
                                   initializer=init))
    coll = EmbeddingCollection(tuple(specs), mesh)
    tracked = [n for n in coll.specs if not n.startswith("C1")]
    # offload vars are excluded from the delta chain: their TrainState
    # entry is a transient HBM cache with its OWN persist path below
    coll.enable_dirty_tracking(names=tracked)
    trainer = Trainer(deepctr.DeepFM(feature_names=FEATURES), coll,
                      optax.adagrad(0.01),
                      offload={"C1": uid, "C1:linear": lin})

    src = stream.ShardStream(shard_dir, batch_size=BATCH, readers=2,
                             epochs=None, num_buckets=VOCAB,
                             add_linear=True, transform=_prune,
                             name="endurance")
    ddir = str(tmp_path / "delta")
    pdir = str(tmp_path / "persist")
    gauge_series = []   # (source, field) -> value per sampled chunk
    try:
        it = iter(src)
        first = next(it)
        state = trainer.init(jax.random.PRNGKey(0),
                             trainer.shard_batch(first))
        # base full save arms the chain before training
        ckpt.save_checkpoint(ddir, coll, state.emb, mode="delta", step=0)
        steps = 0
        chunk_i = 0
        state, m = trainer.fit(state, [first])
        steps += 1
        while steps < STEPS:
            n = min(CHUNK, STEPS - steps)
            state, m = trainer.fit(state, itertools.islice(it, n))
            steps += n
            chunk_i += 1
            info = cdel.save_delta(ddir, coll, state.emb, step=steps,
                                   background_compact=False)
            assert info.get("mode", "delta") == "delta", info
            uid.persist(state.emb["C1"], pdir)
            gauge_series.append(observability.memory_stats())
        src_stalls = src.stall_summary()
        reader_err = False
    finally:
        src.close()
        for t in (uid, lin):
            t.finish()
    assert steps == STEPS and not reader_err

    # --- memory-ledger gauges flat (no monotone growth) -----------------
    def series(source, field):
        return [s[source][field] for s in gauge_series
                if source in s and field in s[source]]

    ring_cap = series("ingest/endurance", "ring_capacity_batches")[0]
    for v in series("ingest/endurance", "ring_batches"):
        assert v <= ring_cap
    # byte gauges: settled value (post chunk 2) never grows past 5%
    for source, field in (("offload/C1", "store_bytes"),
                          ("offload/C1", "book_bytes"),
                          ("offload/C1:linear", "store_bytes"),
                          ("ingest/endurance", "ring_bytes")):
        s = series(source, field)
        assert len(s) >= 4, (source, field)
        settled = max(s[1:3])
        assert max(s[3:]) <= settled * 1.05 + 1024, (source, field, s)
    for v in series("offload/C1", "resident_rows"):
        assert v <= CACHE
    # the composition statement includes the eviction path
    assert series("offload/C1", "evictions")[-1] > 0

    # --- ingest evidence -----------------------------------------------
    assert src.bad_rows() == 0
    assert src_stalls["pops"] >= STEPS
    # post-warmup the ring kept ahead of the ~ms-scale cpu step; allow
    # the first chunk (compile warmup) any stalls it likes
    late = src.stall_stats()[2 * CHUNK:]
    assert float(np.percentile(late, 95)) == 0.0

    # --- delta chain verifies clean + exact restore ---------------------
    manifest = cdel.read_manifest(ddir)
    assert manifest is not None
    entries, dropped_last = cdel.verify_chain(ddir, manifest,
                                              keep_payloads=False)
    assert not dropped_last
    # the foreground compactor may have folded the chain into the base
    # mid-run (that IS the endurance story working); seqs burn
    # monotonically across folds, so every chunk's save is accounted
    assert int(manifest.get("last_seq", 0)) == chunk_i
    assert len(entries) <= chunk_i
    loaded = ckpt.load_checkpoint(ddir, coll)
    probe = np.arange(2048, dtype=np.int32)
    import jax.numpy as jnp
    pk = jnp.asarray(probe)
    for n in ("C2", "C3", "C2:linear"):
        live = np.asarray(coll.pull(state.emb, {n: pk},
                                    batch_sharded=False)[n])
        rest = np.asarray(coll.pull(loaded, {n: pk},
                                    batch_sharded=False)[n])
        np.testing.assert_array_equal(live, rest)
