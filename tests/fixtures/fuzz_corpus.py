"""Pinned regression corpus for the graftfuzz gate — known-bad shapes.

Companion of ``openembedding_tpu/analysis/fuzz.py`` (which owns the
deterministic BUILDERS, keyed by ``name`` in ``CORPUS_BUILDERS``): each
entry here pins the EXPECTED per-reader disposition of one known-bad
checkpoint shape — the PR-12 crafted npz headers (name_len SIGSEGV,
uint32 local-header-offset overflow), graftchaos torn writes (torn
final entry, mid-chain hole), the compacted-dir version contract, the
native deflate/zip64 codec refusals, crc-valid-but-wrong payloads and
the int64 seq-overflow parity case. ``python -m tools.graftfuzz
--regress`` and the tier-1 pytest lane replay every entry through all
three readers (Python loader, Python delta reader, native reader under
plain + ASan + UBSan builds) and fail unless each produces EXACTLY its
pinned disposition. This is how fuzzer-found bugs STAY fixed: each fix
lands with its triggering shape pinned here.

Disposition grammar, per reader (``python_full`` / ``python_delta`` /
``native`` — the native pin must hold under every build variant):

* ``{"outcome": "refuse", "match": <substring>}`` — typed refusal whose
  message contains ``match`` (case-insensitive).
* ``{"outcome": "load", ...}`` — loads; ``version`` pins the replayed
  seq for the loaders, ``deltas``/``seqs`` pin the delta reader's view.

Pure data, stdlib-only, loaded standalone by the CLI (no package
import) — same fixture discipline as ``graftproto_violations.py``: the
iterator VALIDATES each entry and refuses the fixture loudly when one
is malformed, so a typo'd pin can never silently pass.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List

_READERS = ("python_full", "python_delta", "native")
_REQUIRED = ("name", "expect", "why")
_OUTCOMES = ("load", "refuse")

CORPUS: List[Dict[str, Any]] = [
    {
        # PR-12's crafted central-directory name_len (the original
        # native SIGSEGV): the native reader must refuse at the central
        # directory; Python's zipfile tolerates THIS shape (the damaged
        # length field sits where truncation ends the scan) and
        # recovers the identical rows — an allowed refusal divergence,
        # never a wrong-rows divergence.
        "name": "name_len_overflow",
        "expect": {
            "python_full": {"outcome": "load", "version": 2},
            "python_delta": {"outcome": "load", "deltas": 2},
            "native": {"outcome": "refuse",
                       "match": "corrupt npz central directory"},
        },
        "why": "PR-12 crafted name_len read past the central directory "
               "(native SIGSEGV before the bounds fix)",
    },
    {
        # PR-12's uint32 local-header-offset overflow: both sides must
        # refuse typed (native bounds-checks the offset, Python wraps
        # zipfile's BadZipFile into DeltaDecodeError).
        "name": "offset_overflow",
        "expect": {
            "python_full": {"outcome": "refuse",
                            "match": "npz is unparseable"},
            "python_delta": {"outcome": "refuse",
                             "match": "npz is unparseable"},
            "native": {"outcome": "refuse",
                       "match": "corrupt npz local header"},
        },
        "why": "PR-12 uint32 offset overflow jumped the local-header "
               "read far past the mapping",
    },
    {
        # 0xFFFFFFFF size marker: zip64 is documented as REFUSED by the
        # dependency-free native reader, never misread as 4 GiB.
        "name": "zip64_marker",
        "expect": {
            "python_full": {"outcome": "refuse",
                            "match": "npz is unparseable"},
            "python_delta": {"outcome": "refuse",
                             "match": "npz is unparseable"},
            "native": {"outcome": "refuse",
                       "match": "zip64 npz member unsupported"},
        },
        "why": "zip64 markers must hit the documented refusal, not the "
               "size arithmetic",
    },
    {
        # Deflated npz members are valid bytes the Python readers
        # handle; the native reader serves mmap'd stored entries only
        # and documents deflate as refused — the canonical ALLOWED
        # divergence (a refusal, never wrong rows).
        "name": "deflate_refusal",
        "expect": {
            "python_full": {"outcome": "load", "version": 2},
            "python_delta": {"outcome": "load", "deltas": 2},
            "native": {"outcome": "refuse",
                       "match": "deflated npz member"},
        },
        "why": "codec support asymmetry must surface as a native "
               "refusal, never as divergent rows",
    },
    {
        # graftchaos torn_write, FINAL entry: the documented recovery
        # contract — loaders drop the torn entry WHOLE and serve the
        # last complete delta; the publisher refuses to ship bytes that
        # fail their checksum.
        "name": "torn_final",
        "expect": {
            "python_full": {"outcome": "load", "version": 1},
            "python_delta": {"outcome": "refuse", "match": "checksum"},
            "native": {"outcome": "load", "version": 1},
        },
        "why": "torn FINAL entry recovers to the previous complete "
               "delta in BOTH loaders (graftchaos torn_write contract)",
    },
    {
        # graftchaos torn_write, MID-chain: later deltas build on the
        # hole, so every reader must fail loudly — recovery here would
        # serve rows with a missing update in the middle.
        "name": "torn_midchain",
        "expect": {
            "python_full": {"outcome": "refuse",
                            "match": "torn mid-chain"},
            "python_delta": {"outcome": "refuse",
                             "match": "no such file"},
            "native": {"outcome": "refuse", "match": "torn mid-chain"},
        },
        "why": "a mid-chain hole must never be skipped over "
               "(silent-loss shape from the graftchaos fault matrix)",
    },
    {
        # Compacted dir: the chain is folded into the base, the
        # manifest chain is empty — content_seq must keep reporting the
        # true version (the graftproto compact_zero_version regression)
        # and the delta reader correctly has nothing left to publish.
        "name": "compacted_dir",
        "expect": {
            "python_full": {"outcome": "load", "version": 2},
            "python_delta": {"outcome": "load", "deltas": 0},
            "native": {"outcome": "load", "version": 2},
        },
        "why": "compaction burns the chain but not the version "
               "(content_seq carries it across the fold)",
    },
    {
        # 2000-deep JSON nesting: the native parser caps recursion
        # depth (stack overflow before the fix); Python's json raises
        # RecursionError, which must surface typed, not as a crash.
        "name": "deep_json_manifest",
        "expect": {
            "python_full": {"outcome": "refuse",
                            "match": "maximum recursion depth"},
            "python_delta": {"outcome": "refuse",
                             "match": "maximum recursion depth"},
            "native": {"outcome": "refuse", "match": "not valid JSON"},
        },
        "why": "deep nesting must exhaust a BOUNDED parser depth, "
               "never the native stack (C-stack overflow shape)",
    },
    {
        # One per-chunk checksum perturbed, whole-file crc intact: the
        # chunk layer must catch it in BOTH loaders (native ignored
        # chunk_crc entirely before this gate) and tear back to seq 1;
        # the delta reader serves the crc-valid file bytes untouched —
        # its whole-file checksum genuinely passes.
        "name": "chunk_crc_corrupt",
        "expect": {
            "python_full": {"outcome": "load", "version": 1},
            "python_delta": {"outcome": "load", "deltas": 2,
                             "seqs": [1, 2]},
            "native": {"outcome": "load", "version": 1},
        },
        "why": "chunk checksums must be VERIFIED, not just stored "
               "(native skipped them before this gate)",
    },
    {
        # Two payload files' bytes swapped AND their manifest crcs
        # re-stamped: the whole-file checksum now passes on wrong
        # payloads — only the chunk-crc/payload-kind layer stands
        # between this and serving another variable's rows.
        "name": "payload_swap_crc_preserved",
        "expect": {
            "python_full": {"outcome": "load", "version": 1},
            "python_delta": {"outcome": "load", "deltas": 2},
            "native": {"outcome": "load", "version": 1},
        },
        "why": "crc-PRESERVING payload swap: the inner integrity layer "
               "must tear, or wrong rows serve with a green checksum",
    },
    {
        # seq = 1e300: Python bignums would happily replay to version
        # 10^300 while native int64 refuses — the _seq_ok parity guard
        # makes BOTH refuse structurally (divergence shape found by the
        # fuzzer's manifest_json_garbage class during development).
        "name": "seq_int64_overflow",
        "expect": {
            "python_full": {"outcome": "refuse",
                            "match": "corrupt delta chain entry"},
            "python_delta": {"outcome": "refuse",
                             "match": "corrupt delta chain"},
            "native": {"outcome": "refuse",
                       "match": "corrupt delta chain entry"},
        },
        "why": "a past-int64 seq must refuse in BOTH readers — Python "
               "bignums vs native int64 was a silent version-divergence "
               "shape",
    },
]


def iter_corpus() -> Iterator[Dict[str, Any]]:
    """Validated iteration — malformed entries fail the whole fixture.

    A corpus entry whose expectation is missing or mistyped would
    otherwise pass vacuously; this mirrors ``graftproto_violations``'
    fixture discipline (reject, never skip)."""
    seen = set()
    for i, entry in enumerate(CORPUS):
        if not isinstance(entry, dict):
            raise ValueError(f"corpus[{i}] is not a dict")
        missing = [k for k in _REQUIRED if k not in entry]
        if missing:
            raise ValueError(f"corpus[{i}] missing keys {missing}")
        unknown = [k for k in entry if k not in _REQUIRED]
        if unknown:
            raise ValueError(f"corpus[{i}] unknown keys {unknown}")
        name = entry["name"]
        if name in seen:
            raise ValueError(f"corpus[{i}] duplicate name {name!r}")
        seen.add(name)
        expect = entry["expect"]
        if not isinstance(expect, dict) or \
                sorted(expect) != sorted(_READERS):
            raise ValueError(
                f"corpus[{i}] ({name}): expect must pin exactly "
                f"{_READERS}, got {sorted(expect) if isinstance(expect, dict) else expect}")
        for reader, want in expect.items():
            if not isinstance(want, dict) or \
                    want.get("outcome") not in _OUTCOMES:
                raise ValueError(
                    f"corpus[{i}] ({name}): {reader} outcome must be "
                    f"one of {_OUTCOMES}")
            if want["outcome"] == "refuse" and not want.get("match"):
                raise ValueError(
                    f"corpus[{i}] ({name}): {reader} refusal pins no "
                    f"'match' substring — a vacuous expectation")
        if not entry["why"]:
            raise ValueError(f"corpus[{i}] ({name}): empty why")
        yield entry
