"""Seeded graftlint violations: every rule must fire exactly where marked.

NOT imported by anything — ``tests/test_graftlint.py`` lints this file's
SOURCE and asserts each ``# expect: JGxxx`` line is reported (and each
``# graftlint: disable`` line is not). The shapes mirror the real
mistakes the linter exists to catch: the PR-1 hot-cache design keeps the
FreqSketch/counters outside the jitted step — these are the ways that
discipline gets broken by accident.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

COUNTERS = {}
HISTORY = []


class Sketch:
    def __init__(self):
        self.seen = 0

    def observe(self, keys):
        self.seen += int(keys.size)


SKETCH = Sketch()


def step_fn(state, batch):
    COUNTERS.update(steps=1)            # expect: JG001
    HISTORY.append(batch)               # expect: JG001
    SKETCH.observe(batch)               # expect: JG001
    loss = batch.sum().item()           # expect: JG002
    noise = np.log(loss + 1.0)          # expect: JG002
    if state > 0:                       # expect: JG003
        state = state + noise
    return state + batch.mean()


train = jax.jit(step_fn)                # expect: JG004


@jax.jit                                # expect: JG004
def decorated_step(state):
    return state * 2


class Loop:
    def __init__(self):
        self.calls = 0

    def body(self, carry, x):
        self.calls += 1                 # expect: JG001
        while carry > 0:                # expect: JG003
            carry = carry - x
        return carry, x


def make_scan(loop: Loop):
    return lambda xs: lax.scan(loop.body, jnp.float32(8.0), xs)


# --- sanctioned patterns: must NOT be reported -------------------------------

def quiet_step_fn(state):
    COUNTERS["x"] = 1  # graftlint: disable=JG001
    return state * 2


quiet = jax.jit(quiet_step_fn, donate_argnums=(0,))


def callback_host(v):
    # handed to jax.debug.callback below: host by construction, free to
    # mutate whatever it wants
    COUNTERS["cb"] = COUNTERS.get("cb", 0) + 1


def traced_with_callback(x):
    jax.debug.callback(callback_host, x.sum())
    acc = jnp.zeros_like(x)
    acc = acc.at[0].add(x[0])           # functional .at update: clean
    local = {}
    local.update(n=1)                   # local dict: clean
    if x.ndim == 2:                     # metadata predicate: clean
        x = x.reshape(-1)
    return acc.sum() + x.sum()


pulled = jax.jit(traced_with_callback)
