"""Seeded graftrace violations: every rule must fire exactly where marked.

``tests/test_graftrace.py`` analyzes this file's SOURCE and asserts each
``# expect: JGxxx`` line is reported (and each ``# graftrace: disable``
line is not). The shapes mirror the real host-plane mistakes the
analyzer exists to catch: offload's writer/persister discipline, the
serving registry's async loaders, the REST accept loop.

``tests/test_interleaving.py`` also IMPORTS this module and drives
:class:`LossyCounter` through the deterministic interleaving harness —
the seeded JG101 race is not just reported, it is REPRODUCED (a lost
update forced on every run via the ``fixture.race.gap`` sync point).
"""

import threading
import time
import urllib.request

from openembedding_tpu.analysis.concurrency import sync_point

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()


class LossyCounter:
    """JG101: ``total`` is guarded in ``snapshot`` but the worker threads
    read-modify-write it lock-free — the classic lost update."""

    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0

    def snapshot(self) -> int:
        with self._lock:
            return self.total

    def _work(self, n: int) -> None:
        for _ in range(n):
            v = self.total                            # expect: JG101
            sync_point("fixture.race.gap")
            self.total = v + 1                        # expect: JG101

    def spawn(self, workers: int, n: int) -> None:
        ts = [threading.Thread(target=self._work, args=(n,),
                               name=f"racer-{i}")
              for i in range(workers)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()


class OrderInverter:
    """JG102: transfer() takes a then b, reconcile() takes b then a —
    run concurrently they deadlock; the static lock-order graph has the
    cycle either way."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.items = []

    def transfer(self):
        with self._a:
            with self._b:                             # expect: JG102
                self.items.append(1)

    def reconcile(self):
        with self._b:
            with self._a:                             # expect: JG102
                self.items.append(2)


class SlowPath:
    """JG103: blocking calls while holding the lock — every other thread
    needing ``_lock`` stalls behind the sleep/RPC."""

    def __init__(self):
        self._lock = threading.Lock()
        self.rows = {}

    def refresh(self, url):
        with self._lock:
            time.sleep(0.01)                          # expect: JG103
            self.rows["latest"] = urllib.request.urlopen(url)  # expect: JG103


def publish(url):
    """JG103 with a MODULE-level lock."""
    with LOCK_A:
        urllib.request.urlopen(url)                   # expect: JG103


class FireAndForget:
    """JG104: daemon threads nothing joins — they die with the
    interpreter mid-work and their exceptions are never observed
    (the bug offload's writer/persister had before the flush/finish
    join fix)."""

    def __init__(self):
        self.stopping = threading.Event()
        self._pump = threading.Thread(                # expect: JG104
            target=self._run, daemon=True)
        self._pump.start()
        threading.Thread(target=self._run, daemon=True).start()  # expect: JG104

    def _run(self):
        while not self.stopping.wait(0.01):
            pass


WATCHER = threading.Thread(target=print, daemon=True)  # expect: JG104


# --- sanctioned patterns: must NOT be reported -------------------------------

def quiet_publish(url):
    with LOCK_B:
        time.sleep(0.01)  # graftrace: disable=JG103
        return url


class Sanctioned:
    """Suppressed JG104 (a true fire-and-forget by design) plus clean
    discipline everywhere else: consistent guard, non-daemon worker
    joined at close."""

    def __init__(self):
        self._lock = threading.Lock()
        self.pending = []
        self._beat = threading.Thread(  # graftrace: disable=JG104
            target=print, daemon=True)
        self._worker = threading.Thread(target=self._drain,
                                        name="sanctioned-drain")
        self._worker.start()

    def _drain(self):
        with self._lock:
            self.pending.clear()

    def put(self, item):
        with self._lock:
            self.pending.append(item)

    def close(self):
        self._worker.join()
