"""Seeded graftproto mutation models: every one must model-check to
exactly one (minimal) counterexample, with the expected invariant named.

Mirror of the graftlint/graftrace seeded-violation fixtures, one level
up: where those plant violating *source*, this plants violating
*protocols* — each mutation is a shipped protocol minus one load-bearing
line (the seq gate, the payload-before-manifest order, the claim
restore, the one-lock commit), built by passing the matching flag to the
shipped model builder in ``openembedding_tpu/analysis/protomodel.py``.
``tests/test_graftproto.py`` asserts each fires its expected invariant
and that every UNMUTATED shipped model checks clean;
``tests/test_graftproto_replay.py`` replays the exported counterexample
schedules against the real implementation.

Entries are pure data so ``tools/graftproto.py --mutations`` can load
this file standalone (no package / jax import):

    (name, builder, kwargs, expected_invariant, what the mutation drops)

``full_save_resets_seq`` and ``compact_zero_version`` are the PRE-FIX
shipped behaviors this PR's modeling uncovered and fixed — kept as
mutations so the checker guards the fixes forever.
"""

MUTATIONS = [
    ("drop_seq_gate", "hot_swap", {"seq_gate": False},
     "version_covers_exactly_applied_deltas",
     "apply_delta without the gap refusal: a reordered delta applies "
     "over a hole and the skipped delta's rows are silently lost"),
    ("inplace_publish", "hot_swap", {"atomic_publish": False},
     "reader_sees_one_version",
     "patching the served states in place instead of building "
     "functionally and publishing one reference: a concurrent lookup "
     "snapshots a half-patched model"),
    ("skip_claim_restore", "dirty_tracker", {"restore_on_failure": False},
     "no_dirty_chunk_lost_to_completed_chain",
     "a failed delta writer that drops its claim instead of restoring "
     "it: the claimed chunks' changes vanish from bitmap and chain"),
    ("manifest_before_payload", "delta_chain",
     {"commit_order": "manifest_first"},
     "no_silent_commit_loss",
     "committing the manifest before the payload file: a crash in "
     "between leaves a committed entry with no bytes, which a load "
     "silently drops as if it were a torn tail"),
    ("full_save_resets_seq", "delta_chain", {"carry_seq_on_full": False},
     "seqs_never_reused",
     "re-arming a full save at last_seq=0: the next delta reuses a "
     "burned seq, serving replicas ack it as stale and stop updating "
     "(pre-fix shipped behavior)"),
    ("compact_zero_version", "delta_chain",
     {"compact_content_seq": False},
     "load_version_matches_content",
     "compacting without recording the folded content version: "
     "applied_seq reports 0, every later delta push is refused as a "
     "gap (pre-fix shipped behavior)"),
    ("resume_cursor_from_zero", "delta_chain", {"resume_cursor": "zero"},
     "trainer_neither_reapplies_nor_skips_rows",
     "a resumed trainer that restores the checkpoint state but re-reads "
     "the stream from position zero: batches already folded into the "
     "committed checkpoint are applied a second time (the naive-restart "
     "behavior ShardStream.skip_batches exists to prevent)"),
    ("resume_cursor_skips_a_step", "delta_chain",
     {"resume_cursor": "skip"},
     "trainer_neither_reapplies_nor_skips_rows",
     "a resume that seeks the stream one batch past the committed "
     "cursor: the skipped batch's rows are in no checkpoint and no "
     "replay — silently lost from the trained model"),
    ("normal_before_install", "ha_registry", {"atomic_commit": False},
     "normal_status_implies_model_installed",
     "publishing status=NORMAL before installing the model object: "
     "find_model hands a lookup a missing model inside the window"),
    ("resnapshot_per_pull", "serving_batcher",
     {"snapshot_per_flush": False},
     "batch_serves_one_version",
     "re-reading the live model reference at every per-variable pull "
     "instead of snapshotting once per flush: a hot-swap landing "
     "between two groups' pulls answers one batch from two versions"),
    ("drop_queue_on_shutdown", "serving_batcher",
     {"drain_on_shutdown": False},
     "no_request_lost_at_shutdown",
     "shutdown discarding the accepted queue instead of draining it: "
     "enqueued requests never get their response and hang forever"),
]


def build(protomodel, name):
    """Construct one mutated model by fixture name."""
    for n, builder, kwargs, _inv, _why in MUTATIONS:
        if n == name:
            return getattr(protomodel, builder)(**kwargs)
    raise KeyError(name)
