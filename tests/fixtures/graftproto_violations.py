"""Seeded graftproto mutation models: every one must model-check to
exactly one (minimal) counterexample, with the expected property named.

Mirror of the graftlint/graftrace seeded-violation fixtures, one level
up: where those plant violating *source*, this plants violating
*protocols* — each mutation is a shipped protocol minus one load-bearing
line (the seq gate, the payload-before-manifest order, the claim
restore, the one-lock commit, the verify-all-acks barrier, the fence
before a shard grant, the copy-then-release order), built by passing
the matching flag to the model builder in
``openembedding_tpu/analysis/protomodel.py``.
``tests/test_graftproto.py`` asserts each fires its expected property
and that every UNMUTATED shipped model checks clean;
``tests/test_graftproto_replay.py`` replays the exported counterexample
schedules against the real implementation.

Entries are pure data so ``tools/graftproto.py --mutations`` can load
this file standalone (no package / jax import). Schema (all fields
REQUIRED except ``kind``, which defaults to ``"invariant"`` —
``iter_mutations`` REJECTS an entry missing ``expected_invariant`` or
any other field, so a new mutation cannot land without pinning what it
must fire):

    {"name": ...,            # unique fixture id
     "builder": ...,         # protomodel builder function name
     "kwargs": {...},        # the one dropped-line flag
     "expected_invariant": ..., # invariant (or obligation) that fires
     "kind": "invariant" | "liveness",  # which checker catches it
     "why": ...}             # what the mutation drops, in prose

``full_save_resets_seq`` and ``compact_zero_version`` are the PRE-FIX
shipped behaviors PR 11's modeling uncovered and fixed — kept as
mutations so the checker guards the fixes forever. The ``kind:
liveness`` entries counterexample through ``check_liveness`` (the
bounded ``Obligation`` lane) rather than the safety BFS.
"""

MUTATIONS = [
    {"name": "drop_seq_gate", "builder": "hot_swap",
     "kwargs": {"seq_gate": False},
     "expected_invariant": "version_covers_exactly_applied_deltas",
     "why": "apply_delta without the gap refusal: a reordered delta "
            "applies over a hole and the skipped delta's rows are "
            "silently lost"},
    {"name": "inplace_publish", "builder": "hot_swap",
     "kwargs": {"atomic_publish": False},
     "expected_invariant": "reader_sees_one_version",
     "why": "patching the served states in place instead of building "
            "functionally and publishing one reference: a concurrent "
            "lookup snapshots a half-patched model"},
    {"name": "skip_claim_restore", "builder": "dirty_tracker",
     "kwargs": {"restore_on_failure": False},
     "expected_invariant": "no_dirty_chunk_lost_to_completed_chain",
     "why": "a failed delta writer that drops its claim instead of "
            "restoring it: the claimed chunks' changes vanish from "
            "bitmap and chain"},
    {"name": "manifest_before_payload", "builder": "delta_chain",
     "kwargs": {"commit_order": "manifest_first"},
     "expected_invariant": "no_silent_commit_loss",
     "why": "committing the manifest before the payload file: a crash "
            "in between leaves a committed entry with no bytes, which "
            "a load silently drops as if it were a torn tail"},
    {"name": "full_save_resets_seq", "builder": "delta_chain",
     "kwargs": {"carry_seq_on_full": False},
     "expected_invariant": "seqs_never_reused",
     "why": "re-arming a full save at last_seq=0: the next delta "
            "reuses a burned seq, serving replicas ack it as stale and "
            "stop updating (pre-fix shipped behavior)"},
    {"name": "compact_zero_version", "builder": "delta_chain",
     "kwargs": {"compact_content_seq": False},
     "expected_invariant": "load_version_matches_content",
     "why": "compacting without recording the folded content version: "
            "applied_seq reports 0, every later delta push is refused "
            "as a gap (pre-fix shipped behavior)"},
    {"name": "resume_cursor_from_zero", "builder": "delta_chain",
     "kwargs": {"resume_cursor": "zero"},
     "expected_invariant": "trainer_neither_reapplies_nor_skips_rows",
     "why": "a resumed trainer that restores the checkpoint state but "
            "re-reads the stream from position zero: batches already "
            "folded into the committed checkpoint are applied a second "
            "time (the naive-restart behavior ShardStream.skip_batches "
            "exists to prevent)"},
    {"name": "resume_cursor_skips_a_step", "builder": "delta_chain",
     "kwargs": {"resume_cursor": "skip"},
     "expected_invariant": "trainer_neither_reapplies_nor_skips_rows",
     "why": "a resume that seeks the stream one batch past the "
            "committed cursor: the skipped batch's rows are in no "
            "checkpoint and no replay — silently lost from the trained "
            "model"},
    {"name": "normal_before_install", "builder": "ha_registry",
     "kwargs": {"atomic_commit": False},
     "expected_invariant": "normal_status_implies_model_installed",
     "why": "publishing status=NORMAL before installing the model "
            "object: find_model hands a lookup a missing model inside "
            "the window"},
    {"name": "resnapshot_per_pull", "builder": "serving_batcher",
     "kwargs": {"snapshot_per_flush": False},
     "expected_invariant": "batch_serves_one_version",
     "why": "re-reading the live model reference at every per-variable "
            "pull instead of snapshotting once per flush: a hot-swap "
            "landing between two groups' pulls answers one batch from "
            "two versions"},
    {"name": "drop_queue_on_shutdown", "builder": "serving_batcher",
     "kwargs": {"drain_on_shutdown": False},
     "expected_invariant": "no_request_lost_at_shutdown",
     "why": "shutdown discarding the accepted queue instead of "
            "draining it: enqueued requests never get their response "
            "and hang forever"},
    # --- multi-host models (ROADMAP item 3, models-first) ---------------
    {"name": "commit_on_partial", "builder": "multihost_delta",
     "kwargs": {"verify_all": False},
     "expected_invariant": "no_torn_cross_host_publish",
     "why": "the coordinator commits the manifest on a quorum of "
            "hosts-1 acks (the one-straggler shortcut): the missing "
            "host's shard payload is torn out of the published "
            "cross-host version"},
    {"name": "ack_before_write", "builder": "multihost_delta",
     "kwargs": {"durable_ack": False},
     "expected_invariant": "no_torn_cross_host_publish",
     "why": "a host acks the round before its payload is durable "
            "(ack races the fsync): the coordinator counts an ack "
            "whose bytes never land and publishes a torn version"},
    {"name": "assign_without_release", "builder": "training_membership",
     "kwargs": {"fenced_reassign": False},
     "expected_invariant": "shard_never_trained_by_two_live_workers",
     "why": "granting a suspect's shard on mere suspicion without the "
            "confirmed-dead fence or the release: a falsely suspected "
            "live worker and the grantee both train the shard"},
    {"name": "no_failure_detect", "builder": "training_membership",
     "kwargs": {"failure_detect": False},
     "expected_invariant": "every_shard_regains_a_live_owner",
     "kind": "liveness",
     "why": "dropping the failure detector: a dead worker's shards are "
            "never granted to a live one — the run ends with orphaned "
            "shards (the bounded-liveness obligation fires, not a "
            "safety invariant)"},
    {"name": "release_before_apply", "builder": "reshard",
     "kwargs": {"apply_before_release": False},
     "expected_invariant": "no_row_lost",
     "why": "releasing the source copy before the destination "
            "persisted the row: a destination crash in the window "
            "leaves the row in NO host"},
    {"name": "double_apply", "builder": "reshard",
     "kwargs": {"idempotent_apply": False},
     "expected_invariant": "no_row_double_applied",
     "why": "crash recovery re-folds an already-applied row into the "
            "destination (no idempotence check): optimizer state for "
            "the row is applied twice"},
]

_REQUIRED = ("name", "builder", "kwargs", "expected_invariant", "why")
_KINDS = ("invariant", "liveness")


def iter_mutations():
    """Validated view of ``MUTATIONS``: every entry must carry every
    required field (non-empty) — in particular an explicit
    ``expected_invariant`` — and a known ``kind``. Raises ``ValueError``
    on the first malformed entry, so a mutation can't land without
    pinning exactly what it must fire."""
    seen = set()
    for e in MUTATIONS:
        if not isinstance(e, dict):
            raise ValueError(f"mutation entry is not a dict: {e!r}")
        for f in _REQUIRED:
            if not e.get(f) and e.get(f) != {}:
                raise ValueError(
                    f"mutation {e.get('name', e)!r}: missing required "
                    f"field {f!r} (every seeded mutation must declare "
                    f"the property it fires)")
        kind = e.get("kind", "invariant")
        if kind not in _KINDS:
            raise ValueError(
                f"mutation {e['name']!r}: unknown kind {kind!r} "
                f"(must be one of {_KINDS})")
        if e["name"] in seen:
            raise ValueError(f"duplicate mutation name {e['name']!r}")
        seen.add(e["name"])
        yield {**e, "kind": kind}


def build(protomodel, name):
    """Construct one mutated model by fixture name."""
    for e in iter_mutations():
        if e["name"] == name:
            return getattr(protomodel, e["builder"])(**e["kwargs"])
    raise KeyError(name)
