"""Serving plane: registry lifecycle, read-only lookups, REST controller.

Mirrors the reference's serving flow (SURVEY §3.5): dump a trained model,
create it in the serving cluster with a sign, look up variables read-only,
model CRUD over HTTP (controller.cc endpoints)."""

import json
import http.client

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
from openembedding_tpu import checkpoint as ckpt
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.serving.registry import ModelRegistry
from openembedding_tpu.serving.rest import ControllerServer

VOCAB, DIM = 32, 4


@pytest.fixture()
def dumped_model(devices8, tmp_path):
    mesh = create_mesh(2, 4, devices8)
    specs = (EmbeddingSpec(name="arr", input_dim=VOCAB, output_dim=DIM),
             EmbeddingSpec(name="hsh", input_dim=-1, output_dim=DIM,
                           hash_capacity=256))
    coll = EmbeddingCollection(
        specs, mesh,
        default_optimizer={"category": "sgd", "learning_rate": 1.0})
    states = coll.init(jax.random.PRNGKey(0))
    idx = {"arr": jnp.arange(8, dtype=jnp.int32),
           "hsh": jnp.arange(8, dtype=jnp.int32) * 31 + 5}
    rows = coll.pull(states, idx, batch_sharded=False)
    states = coll.apply_gradients(
        states, idx, {k: jnp.ones_like(v) for k, v in rows.items()},
        batch_sharded=False)
    path = str(tmp_path / "model")
    ckpt.save_checkpoint(path, coll, states, model_sign="uuid-3")
    expected = coll.pull(states, idx, batch_sharded=False, read_only=True)
    return mesh, path, idx, expected


def test_registry_lifecycle_and_lookup(dumped_model):
    mesh, path, idx, expected = dumped_model
    reg = ModelRegistry(mesh, default_hash_capacity=256)
    sign = reg.create_model(path, replica_num=3)
    assert sign == "uuid-3"
    info = reg.show_model(sign)
    assert info["model_status"] == "NORMAL"
    assert info["replica_num"] == 3

    model = reg.find_model(sign)
    rows = model.lookup("arr", np.asarray(idx["arr"]))
    np.testing.assert_allclose(np.asarray(rows), np.asarray(expected["arr"]),
                               rtol=1e-6)
    # lookup by variable_id too (reference find_model_variable signature)
    rows2 = model.lookup(model.collection.variable_id("hsh"),
                         np.asarray(idx["hsh"]))
    np.testing.assert_allclose(np.asarray(rows2), np.asarray(expected["hsh"]),
                               rtol=1e-6)
    # read-only: unknown hash key -> zeros, and the table is unchanged
    zero = model.lookup("hsh", np.array([999999], np.int32))
    np.testing.assert_array_equal(np.asarray(zero), np.zeros((1, DIM)))

    reg.delete_model(sign)
    with pytest.raises(KeyError):
        reg.find_model(sign)


def test_registry_error_paths(dumped_model, tmp_path):
    mesh, path, _, _ = dumped_model
    reg = ModelRegistry(mesh)
    with pytest.raises(FileNotFoundError):
        reg.create_model(str(tmp_path / "nope"))
    with pytest.raises(KeyError):
        reg.show_model("ghost")


def test_rest_controller(dumped_model):
    mesh, path, idx, expected = dumped_model
    reg = ModelRegistry(mesh, default_hash_capacity=256)
    srv = ControllerServer(reg, port=0).start()
    try:
        c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)

        def req(method, url, body=None):
            c.request(method, url,
                      json.dumps(body) if body is not None else None)
            r = c.getresponse()
            return r.status, json.loads(r.read() or b"null"), dict(
                r.getheaders())

        # create (block so the test is deterministic)
        code, obj, headers = req("POST", "/models",
                                 {"model_uri": path, "block": True})
        assert code == 201 and obj["model_sign"] == "uuid-3"
        assert headers.get("Location") == "/models/uuid-3"
        # list + show
        code, models, _ = req("GET", "/models")
        assert code == 200 and models[0]["model_status"] == "NORMAL"
        code, one, _ = req("GET", "/models/uuid-3")
        assert code == 200 and one["model_uri"] == path
        # nodes
        code, nodes, _ = req("GET", "/nodes")
        assert code == 200 and len(nodes) == 8
        code, node, _ = req("GET", f"/nodes/{nodes[0]['node_id']}")
        assert code == 200
        code, _, _ = req("DELETE", f"/nodes/{nodes[0]['node_id']}")
        assert code == 501
        # lookup
        code, obj, _ = req("POST", "/models/uuid-3/lookup",
                           {"variable": "arr",
                            "indices": np.asarray(idx["arr"]).tolist()})
        assert code == 200
        np.testing.assert_allclose(np.asarray(obj["rows"], np.float32),
                                   np.asarray(expected["arr"]), rtol=1e-5)
        # unknown model 404-ish errors
        code, obj, _ = req("GET", "/models/ghost")
        assert code == 404
        # delete
        code, obj, _ = req("DELETE", "/models/uuid-3")
        assert code == 200
        code, obj, _ = req("GET", "/models/uuid-3")
        assert code == 404
    finally:
        srv.stop()


@pytest.mark.slow
def test_health_reports_applied_seq(dumped_model):
    """/health carries ``applied_seq`` — the newest delta seq this
    replica has applied across models — so one liveness read is enough
    for a recovery probe (graftload --respawn, graftchaos) to judge
    catch-up after a kill."""
    from openembedding_tpu.checkpoint_delta import Delta
    mesh, path, _idx, _expected = dumped_model
    reg = ModelRegistry(mesh, default_hash_capacity=256)
    srv = ControllerServer(reg, port=0).start()
    try:
        c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)

        def health():
            c.request("GET", "/health")
            r = c.getresponse()
            return r.status, json.loads(r.read())

        code, obj = health()
        assert code == 200 and obj["ok"] is True
        assert obj["models"] == [] and obj["applied_seq"] == 0
        reg.create_model(path, block=True)
        code, obj = health()
        assert code == 200 and obj["applied_seq"] == 0
        payload = {
            "weights": np.full((VOCAB, DIM), 2.0, np.float32),
            "chunks": np.array([0], np.int64),
            "rows_per_chunk": np.array(VOCAB, np.int64),
            "vocab": np.array(VOCAB, np.int64),
        }
        out = reg.apply_delta(
            "uuid-3", Delta(seq=1, step=1, vars={"arr": payload}))
        assert out["applied"] and out["version"] == 1
        code, obj = health()
        assert code == 200 and obj["applied_seq"] == 1
        assert [m["version"] for m in obj["models"]] == [1]
    finally:
        srv.stop()
        reg.close()
