"""graftlint: every seeded violation fires, the shipped package is clean.

The fixture file (tests/fixtures/graftlint_violations.py) marks each
intended violation with an ``# expect: JGxxx`` comment; the linter must
report EXACTLY that set — nothing missed (rules work), nothing extra
(sanctioned patterns: ``.at[...]`` updates, local mutation, metadata
branches, ``jax.debug.callback`` host functions, inline suppressions).
"""

import os
import re

import pytest

from openembedding_tpu.analysis import lint

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
FIXTURE = os.path.join(HERE, "fixtures", "graftlint_violations.py")


def _expected(source):
    out = set()
    for i, line in enumerate(source.splitlines(), start=1):
        for rule in re.findall(r"# expect: (JG\d+)", line):
            out.add((i, rule))
    return out


def test_every_seeded_violation_fires():
    with open(FIXTURE) as fh:
        src = fh.read()
    expected = _expected(src)
    assert len(expected) >= 8          # all four code rules represented
    # JG000 (parse failure) cannot live in a parseable fixture; it has
    # its own unit test below
    assert {r for _ln, r in expected} == set(lint.RULES) - {"JG000"}
    got = {(v.line, v.rule) for v in lint.lint_source(src, FIXTURE)}
    assert got == expected, (
        f"missed: {expected - got}; spurious: {got - expected}")


def test_shipped_package_is_clean():
    """The tier-1 lint gate, enforced from inside the suite as well:
    zero violations in openembedding_tpu/ (suppressions included)."""
    pkg = os.path.join(ROOT, "openembedding_tpu")
    violations = lint.lint_paths([pkg])
    assert violations == [], "\n".join(str(v) for v in violations)


def test_cli_exit_codes():
    from tools.graftlint import main
    assert main([os.path.join(ROOT, "openembedding_tpu")]) == 0
    assert main([FIXTURE]) == 1
    # rule filtering: JG004 only
    assert main([FIXTURE, "--rules", "JG004"]) == 1


def test_suppression_scopes():
    src = (
        "import jax\n"
        "C = {}\n"
        "def step_fn(s):\n"
        "    C['a'] = 1  # graftlint: disable=JG001\n"
        "    C['b'] = 2  # graftlint: disable\n"
        "    C['c'] = 3\n"
        "    return s\n"
        "f = jax.jit(step_fn)  # graftlint: disable=JG004\n")
    got = lint.lint_source(src)
    assert [(v.line, v.rule) for v in got] == [(6, "JG001")]


def test_def_line_suppression_covers_body():
    src = (
        "import jax\n"
        "C = {}\n"
        "def step_fn(s):  # graftlint: disable=JG001,JG004\n"
        "    C['a'] = 1\n"
        "    return s\n"
        "f = jax.jit(step_fn, donate_argnums=(0,))\n")
    assert lint.lint_source(src) == []


def test_host_fn_decorator_exempts():
    src = (
        "import jax\n"
        "from openembedding_tpu.analysis.lint import host_fn\n"
        "C = {}\n"
        "@host_fn\n"
        "def prep(batch):\n"
        "    C['n'] = 1\n"
        "    return batch\n"
        "g = jax.jit(prep)\n")
    assert lint.lint_source(src) == []


def test_parse_failure_is_jg000_and_unfilterable(tmp_path):
    got = lint.lint_source("def broken(:\n", "bad.py")
    assert [v.rule for v in got] == ["JG000"]
    # --rules filtering must never hide an unparseable file
    from tools.graftlint import main
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    assert main([str(bad), "--rules", "JG004"]) == 1


def test_decorated_step_requires_donation():
    src = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, donate_argnums=(0,))\n"
        "def good_step(s):\n"
        "    return s\n"
        "@jax.jit\n"
        "def eval_fn(s):\n"
        "    return s\n")
    assert lint.lint_source(src) == []


def test_partial_jit_is_not_invisible():
    """partial(jax.jit, ...) decorators mark the function traced (JG001
    applies to its body) AND undonated step-named ones trip JG004 — the
    repo's own pallas entry points use this form."""
    src = (
        "import jax\n"
        "import functools\n"
        "C = {}\n"
        "@functools.partial(jax.jit, static_argnums=(1,))\n"
        "def train_step(s, n):\n"
        "    C['k'] = 1\n"
        "    return s\n")
    got = {(v.line, v.rule) for v in lint.lint_source(src)}
    assert got == {(6, "JG001"), (4, "JG004")}, got


def test_host_fn_is_runtime_noop():
    @lint.host_fn
    def f(x):
        return x + 1

    assert f(1) == 2 and f.__graftlint_host__
