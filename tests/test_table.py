"""Core table semantics: pull/apply, dedup, initializer behavior."""

import numpy as np

import jax
import jax.numpy as jnp

from openembedding_tpu import (EmbeddingVariableMeta, apply_gradients,
                               create_table, make_optimizer, pull)
from openembedding_tpu.ops import dedup


def make(vocab=16, dim=4, opt="sgd", init=None):
    meta = EmbeddingVariableMeta(embedding_dim=dim, vocabulary_size=vocab)
    optimizer = make_optimizer(opt)
    return meta, optimizer, create_table(meta, optimizer, init,
                                         rng=jax.random.PRNGKey(0))


def test_pull_shapes():
    _, _, state = make()
    out = pull(state, jnp.array([[1, 2], [3, 3]]))
    assert out.shape == (2, 2, 4)
    np.testing.assert_array_equal(out[1, 0], out[1, 1])


def test_initializers_deterministic_and_ranged():
    meta = EmbeddingVariableMeta(embedding_dim=8, vocabulary_size=100)
    opt = make_optimizer("default")
    a = create_table(meta, opt, {"category": "uniform", "minval": -0.5, "maxval": 0.5},
                     rng=jax.random.PRNGKey(7))
    b = create_table(meta, opt, {"category": "uniform", "minval": -0.5, "maxval": 0.5},
                     rng=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a.weights), np.asarray(b.weights))
    assert float(a.weights.min()) >= -0.5 and float(a.weights.max()) <= 0.5
    c = create_table(meta, opt, {"category": "constant", "value": 2.5})
    assert float(c.weights.min()) == float(c.weights.max()) == 2.5
    n = create_table(meta, opt, {"category": "normal", "stddev": 0.1, "truncated": True})
    assert float(jnp.abs(n.weights).max()) <= 0.2 + 1e-6


def test_untouched_rows_unchanged():
    _, opt, state = make(opt={"category": "sgd", "learning_rate": 1.0})
    before = np.asarray(state.weights).copy()
    idx = jnp.array([2, 5])
    g = jnp.ones((2, 4))
    state2 = apply_gradients(state, opt, idx, g)
    after = np.asarray(state2.weights)
    touched = {2, 5}
    for r in range(16):
        if r in touched:
            assert not np.allclose(before[r], after[r])
        else:
            np.testing.assert_array_equal(before[r], after[r])


def test_duplicates_summed_once():
    # one update with summed grad, not N momentum updates
    _, opt, state = make(opt={"category": "sgd", "learning_rate": 0.1, "momentum": 0.9})
    idx = jnp.array([3, 3, 3])
    g = jnp.ones((3, 4))
    state2 = apply_gradients(state, opt, idx, g)
    # moment = 0*0.9 + 0.1*3 = 0.3 ; weight -= 0.3
    np.testing.assert_allclose(np.asarray(state2.slots["moment"])[3],
                               np.full(4, 0.3), rtol=1e-6)
    delta = np.asarray(state.weights - state2.weights)[3]
    np.testing.assert_allclose(delta, np.full(4, 0.3), rtol=1e-6)


def test_dedup_capacity_padding():
    idx = jnp.array([5, 1, 5, 9, 1, 1], dtype=jnp.int32)
    uniq, inverse, valid = dedup.unique_indices(idx, capacity=6)
    assert uniq.shape == (6,)
    assert int(valid.sum()) == 3
    np.testing.assert_array_equal(np.asarray(uniq)[np.asarray(inverse)],
                                  np.asarray(idx))
    g = jnp.ones((6, 2))
    summed, counts = dedup.combine_gradients(g, inverse, 6)
    got = {int(u): int(c) for u, c, v in
           zip(np.asarray(uniq), np.asarray(counts), np.asarray(valid)) if v}
    assert got == {1: 3, 5: 2, 9: 1}
    assert float(summed.sum()) == 12.0


def test_jit_apply_under_vocab_smaller_than_batch():
    _, opt, state = make(vocab=4, opt={"category": "adagrad", "learning_rate": 0.1})
    idx = jnp.array([0, 1, 2, 3, 0, 1, 2, 3, 0])
    g = jnp.ones((9, 4))
    step = jax.jit(lambda s: apply_gradients(s, opt, idx, g))
    state2 = step(state)
    assert np.isfinite(np.asarray(state2.weights)).all()


def test_negative_index_dropped_not_wrapped():
    _, opt, state = make(vocab=8, opt={"category": "sgd", "learning_rate": 1.0})
    before = np.asarray(state.weights).copy()
    state2 = apply_gradients(state, opt, jnp.array([-3]), jnp.ones((1, 4)))
    np.testing.assert_array_equal(before, np.asarray(state2.weights))


def test_bool_config_strings():
    from openembedding_tpu import make_initializer
    assert make_optimizer({"category": "sgd", "nesterov": "true"}).nesterov is True
    assert make_optimizer({"category": "sgd", "nesterov": "false"}).nesterov is False
    assert make_initializer({"category": "normal", "truncated": "false"}).truncated is False


def test_bfloat16_adam_beta_slots_float32():
    meta = EmbeddingVariableMeta(datatype="bfloat16", embedding_dim=4,
                                 vocabulary_size=8)
    opt = make_optimizer("adam")
    state = create_table(meta, opt)
    assert state.weights.dtype == jnp.bfloat16
    assert state.slots["beta_1_t"].dtype == jnp.float32
    state2 = apply_gradients(state, opt, jnp.array([1]), jnp.ones((1, 4), jnp.bfloat16))
    assert state2.weights.dtype == jnp.bfloat16
    assert state2.slots["beta_2_t"].dtype == jnp.float32
    np.testing.assert_allclose(float(state2.slots["beta_2_t"][1, 0]), 0.999)


def test_float64_requires_x64():
    import pytest as _pytest
    meta = EmbeddingVariableMeta(datatype="float64", embedding_dim=2,
                                 vocabulary_size=4)
    with _pytest.raises(ValueError, match="x64"):
        create_table(meta, make_optimizer("sgd"))
