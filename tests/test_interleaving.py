"""Deterministic interleaving harness: the raciest host-plane schedules,
replayed exactly, every run.

Three layers:

* harness semantics — SerialSchedule replays a prescribed cross-thread
  order; PointGate parks named threads at named points (the
  race-observation window).
* the SEEDED race — tests/fixtures/graftrace_violations.py's
  LossyCounter (the JG101 fixture class) is driven to a lost update on
  EVERY run: both workers parked after their reads, then released.
  The same schedule pressure against a guarded counter stays correct.
* the REAL planes — offload's writer-vs-step and writer-error paths,
  and the serving registry's async-load-vs-lookup window, pinned at
  the sync points instrumented in this PR.
"""

import importlib.util
import os
import threading

import numpy as np
import pytest

import jax

from openembedding_tpu.analysis import concurrency
from openembedding_tpu.analysis.concurrency import (
    PointGate, SerialSchedule, clear_schedule, install_schedule, sync_point)

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURE = os.path.join(HERE, "fixtures", "graftrace_violations.py")


def _load_fixture():
    spec = importlib.util.spec_from_file_location("graftrace_fixture",
                                                  FIXTURE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _no_leftover_schedule():
    yield
    clear_schedule()


# --- harness semantics -------------------------------------------------------

def test_serial_schedule_replays_prescribed_order():
    for want in (["b", "a"], ["a", "b"]):
        order = []
        for name in want:
            order += [f"{name}/enter", f"{name}/exit"]
        sched = SerialSchedule(order)
        install_schedule(sched)
        out = []

        def work():
            sync_point("enter")
            out.append(threading.current_thread().name)
            sync_point("exit")

        ts = [threading.Thread(target=work, name=n) for n in ("a", "b")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        clear_schedule()
        assert sched.done()
        assert out == want


def test_serial_schedule_wedge_raises_not_hangs():
    sched = SerialSchedule(["other/p", "me/p"], timeout=0.2)
    with pytest.raises(TimeoutError, match="wedged"):
        sched.sync("me/p", "p")


def test_point_gate_parks_and_releases():
    gate = PointGate(["stop.here"], timeout=10)
    install_schedule(gate)
    out = []

    def work():
        sync_point("free.point")      # unlisted: passes through
        sync_point("stop.here")
        out.append(1)

    t = threading.Thread(target=work)
    t.start()
    assert gate.wait_arrival("stop.here")
    assert out == []                  # provably parked at the point
    gate.open("stop.here")
    t.join(10)
    assert out == [1]


# --- the seeded race, reproduced ---------------------------------------------

def test_seeded_race_reproduces_deterministically():
    """The fixture's JG101 is not just reported — it is REALIZED, every
    run: both racers parked between read and write, then released, so
    one increment is always lost (total 1, never 2)."""
    mod = _load_fixture()
    for _ in range(3):
        gate = PointGate(["racer-0/fixture.race.gap",
                          "racer-1/fixture.race.gap"])
        install_schedule(gate)
        c = mod.LossyCounter()
        t = threading.Thread(target=c.spawn, args=(2, 1))
        t.start()
        assert gate.wait_arrival("racer-0/fixture.race.gap")
        assert gate.wait_arrival("racer-1/fixture.race.gap")
        # both workers hold total==0 in hand; both writes now land
        gate.open_all()
        t.join(30)
        clear_schedule()
        assert c.total == 1
        assert c.snapshot() == 1


def test_guarded_counter_survives_the_same_schedule():
    """The JG101 fix (read-modify-write under the lock) under identical
    schedule pressure: parking one worker inside its critical section
    just queues the other on the lock — nothing is lost."""
    mod = _load_fixture()

    class GuardedCounter(mod.LossyCounter):
        def _work(self, n):
            for _ in range(n):
                with self._lock:
                    v = self.total
                    sync_point("fixture.race.gap")
                    self.total = v + 1

    gate = PointGate(["racer-0/fixture.race.gap"])
    install_schedule(gate)
    c = GuardedCounter()
    t = threading.Thread(target=c.spawn, args=(2, 1))
    t.start()
    assert gate.wait_arrival("racer-0/fixture.race.gap")
    gate.open("racer-0/fixture.race.gap")
    t.join(30)
    clear_schedule()
    assert c.total == 2


# --- offload: writer vs step thread ------------------------------------------

def _make_offload(mesh, vocab=256, cache=64):
    from openembedding_tpu import EmbeddingVariableMeta
    from openembedding_tpu.offload import ShardedOffloadedTable
    meta = EmbeddingVariableMeta(embedding_dim=4, vocabulary_size=vocab)
    return ShardedOffloadedTable(
        "off", meta, {"category": "sgd", "learning_rate": 0.1},
        {"category": "constant", "value": 0.25},
        vocab=vocab, cache_capacity=cache, mesh=mesh)


def test_offload_update_during_writeback_stays_dirty(devices8):
    """The _dirty discipline under the raciest schedule: flush clears the
    marks eagerly, the writer parks BEFORE scattering, the step thread
    re-dirties a row mid-writeback — the re-mark must survive (next
    flush covers it), and the parked writeback must still land."""
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(2, 4, devices8)
    table = _make_offload(mesh)
    cache = table.create_cache()
    ids = np.arange(8, dtype=np.int32)
    cache = table.prepare(cache, ids)
    table.note_update(ids)

    gate = PointGate(["offload.writeback.scatter"])
    install_schedule(gate)
    assert table.flush(cache) == ids.size
    assert gate.wait_arrival("offload.writeback.scatter")
    # mid-writeback, the step thread dirties a row: the eager clear must
    # not eat this mark
    table.note_update(np.array([3], np.int32))
    gate.open("offload.writeback.scatter")
    table._join_writeback()
    clear_schedule()
    assert (table.host_work_id[ids] > 0).all()
    with table._book:
        assert bool(table._dirty[3]) and not bool(table._dirty[5])
    assert table.flush(cache) == 1     # exactly the re-dirtied row


def test_offload_writer_error_surfaces_at_next_flush(devices8):
    """The satellite fix, pinned: a writeback that dies on its thread is
    not silent — the stored exception raises at the NEXT flush (or
    finish), and the failed rows are re-marked dirty so a later flush
    retries them."""
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(2, 4, devices8)
    table = _make_offload(mesh)
    cache = table.create_cache()
    ids = np.arange(6, dtype=np.int32)
    cache = table.prepare(cache, ids)
    table.note_update(ids)

    gate = PointGate(["offload.writeback.run"])
    install_schedule(gate)
    assert table.flush(cache) == ids.size
    writer = table._writer
    assert gate.wait_arrival("offload.writeback.run")
    real_get = jax.device_get

    def boom(*a, **kw):
        raise RuntimeError("injected device loss")

    jax.device_get = boom
    try:
        gate.open("offload.writeback.run")
        writer.join(30)
    finally:
        jax.device_get = real_get
    clear_schedule()
    with pytest.raises(RuntimeError, match="async writeback failed"):
        table.flush(cache)
    # the failed rows came back dirty: the retry covers all of them
    assert table.flush(cache) == ids.size
    table._join_writeback()
    assert (table.host_work_id[ids] > 0).all()
    table.finish()                     # and finish() is clean again


def test_offload_finish_raises_stored_writer_error(devices8):
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(2, 4, devices8)
    table = _make_offload(mesh)
    cache = table.create_cache()
    ids = np.arange(4, dtype=np.int32)
    cache = table.prepare(cache, ids)
    table.note_update(ids)

    gate = PointGate(["offload.writeback.run"])
    install_schedule(gate)
    table.flush(cache)
    writer = table._writer
    assert gate.wait_arrival("offload.writeback.run")
    real_get = jax.device_get
    jax.device_get = lambda *a, **kw: (_ for _ in ()).throw(
        RuntimeError("injected device loss"))
    try:
        gate.open("offload.writeback.run")
        writer.join(30)
    finally:
        jax.device_get = real_get
    clear_schedule()
    # finish (the fit() epilogue) surfaces it — before this PR the
    # daemon writer died silently and finish() returned success
    with pytest.raises(RuntimeError, match="async writeback failed"):
        table.finish()


# --- serving registry: async load vs lookup ----------------------------------

def test_registry_load_vs_find_window(devices8, tmp_path):
    """The CREATING window, held open deterministically: lookups and
    duplicate creates are rejected while the loader is parked pre-commit;
    after release + join_loads the model serves."""
    import jax.numpy as jnp
    from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
    from openembedding_tpu import checkpoint as ckpt
    from openembedding_tpu.meta import ModelStatus
    from openembedding_tpu.parallel.mesh import create_mesh
    from openembedding_tpu.serving.registry import ModelRegistry

    mesh = create_mesh(2, 4, devices8)
    spec = EmbeddingSpec(name="arr", input_dim=16, output_dim=2)
    coll = EmbeddingCollection(
        (spec,), mesh,
        default_optimizer={"category": "sgd", "learning_rate": 1.0})
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, model_sign="sign-1")

    reg = ModelRegistry(mesh, default_hash_capacity=64)
    gate = PointGate(["registry.load.commit"])
    install_schedule(gate)
    sign = reg.create_model(path, block=False)
    assert gate.wait_arrival("registry.load.commit")
    # parked pre-commit: status CREATING, pulls + duplicate creates bounce
    assert reg.show_model(sign)["model_status"] == ModelStatus.CREATING
    with pytest.raises(RuntimeError, match="CREATING"):
        reg.find_model(sign)
    with pytest.raises(ValueError, match="already being created"):
        reg.create_model(path, block=False)
    gate.open("registry.load.commit")
    reg.join_loads()
    clear_schedule()
    assert reg.show_model(sign)["model_status"] == ModelStatus.NORMAL
    model = reg.find_model(sign)
    rows = model.lookup("arr", np.arange(4, dtype=np.int32))
    assert np.asarray(rows).shape == (4, 2)
    reg.close()


def test_controller_server_graceful_stop(devices8):
    """The JG104 fix applied to serving: stop() joins the accept-loop
    thread (and quiesces registry loaders) instead of leaving a daemon
    to die with the interpreter."""
    from openembedding_tpu.parallel.mesh import create_mesh
    from openembedding_tpu.serving.registry import ModelRegistry
    from openembedding_tpu.serving.rest import ControllerServer

    mesh = create_mesh(2, 4, devices8)
    srv = ControllerServer(ModelRegistry(mesh), port=0).start()
    assert srv._thread.is_alive()
    srv.stop()
    assert not srv._thread.is_alive()
    # never-started server: stop() must return, not wedge on the
    # serve_forever event that was never set (cleanup-after-failure path)
    srv2 = ControllerServer(ModelRegistry(mesh), port=0)
    srv2.stop()
    assert not srv2._thread.is_alive()
