"""Elastic trainer recovery: ``fit(autosave_every=, resume_from=)``.

The acceptance lane for the graftchaos tentpole's elasticity leg: a
trainer killed mid-``fit`` resumes from the delta chain BIT-IDENTICAL
to the uninterrupted run — for an in-memory batch list AND a live
``ShardStream`` (whose ``skip_batches`` provides the exact-positioning
cursor the manifest extra records). Identity is compared through the
logical id space (full-vocab pulls) plus the dense params/opt leaves
and the step counter; physical padding rows re-init from a fresh rng
stream on load and are not comparable.
"""

import os

import numpy as np
import pytest

import jax

from openembedding_tpu.analysis import chaos

FEATURES = ("c0", "c1", "c2")
VOCAB, DIM, B = 48, 4, 8
N_BATCHES, INTERRUPT, AUTOSAVE = 6, 4, 2


def _synthetic_batches(n, seed=0):
    from openembedding_tpu.models import deepctr
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        sparse, raw = {}, {}
        for f in FEATURES:
            ids = rng.randint(0, VOCAB, size=B).astype(np.int32)
            raw[f] = ids
            sparse[f] = ids
            sparse[f + deepctr.LINEAR_SUFFIX] = ids
        label = ((raw["c0"] + raw["c1"]) % 2).astype(np.float32)
        dense = rng.randn(B, 4).astype(np.float32)
        out.append({"label": label, "dense": dense, "sparse": sparse})
    return out


def _build_trainer(mesh):
    import optax
    from openembedding_tpu import EmbeddingCollection, Trainer
    from openembedding_tpu.models import deepctr
    specs = deepctr.make_feature_specs(FEATURES, VOCAB, DIM)
    coll = EmbeddingCollection(
        specs, mesh,
        default_optimizer={"category": "adagrad", "learning_rate": 0.1})
    coll.enable_dirty_tracking(target_chunks=8)
    model = deepctr.build_model("deepfm", FEATURES)
    return Trainer(model, coll, optax.adam(1e-2))


def _fingerprint(tr, state):
    out = [np.asarray(int(state.step))]
    for leaf in jax.tree.leaves((state.params, state.opt_state)):
        out.append(np.asarray(jax.device_get(leaf)))
    allv = np.arange(VOCAB, dtype=np.int32)
    names = list(tr.collection.specs)
    pulls = tr.collection.pull(state.emb, {n: allv for n in names},
                               batch_sharded=False)
    for n in names:
        out.append(np.asarray(pulls[n]))
    return out


def _assert_identical(a, b):
    assert len(a) == len(b)
    for i, (x, y) in enumerate(zip(a, b)):
        np.testing.assert_array_equal(x, y, err_msg=f"leaf {i}")


@pytest.fixture(scope="module")
def world(devices8):
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(2, 4, devices8)
    batches = _synthetic_batches(N_BATCHES)
    tr = _build_trainer(mesh)
    s0 = tr.init(jax.random.PRNGKey(0), tr.shard_batch(batches[0]))
    s1, _ = tr.fit(s0, list(batches))
    return {"mesh": mesh, "batches": batches,
            "baseline": _fingerprint(tr, s1)}


@pytest.mark.slow
def test_interrupted_fit_resumes_bit_identical(world, tmp_path):
    """Stop after INTERRUPT batches (autosaving every AUTOSAVE), then a
    FRESH trainer resumes over the full list and must land exactly on
    the uninterrupted baseline."""
    ck = str(tmp_path / "auto")
    tr1 = _build_trainer(world["mesh"])
    s1 = tr1.init(jax.random.PRNGKey(0),
                  tr1.shard_batch(world["batches"][0]))
    tr1.fit(s1, list(world["batches"][:INTERRUPT]),
            autosave_every=AUTOSAVE, autosave_dir=ck)

    tr2 = _build_trainer(world["mesh"])
    s2 = tr2.init(jax.random.PRNGKey(0),
                  tr2.shard_batch(world["batches"][0]))
    s2b, _ = tr2.fit(s2, list(world["batches"]), resume_from=ck,
                     autosave_every=AUTOSAVE, autosave_dir=ck)
    _assert_identical(world["baseline"], _fingerprint(tr2, s2b))


@pytest.mark.slow
def test_resume_from_missing_dir_is_a_fresh_start(world, tmp_path):
    """``resume_from`` a path with no manifest trains from scratch —
    the same invocation works for launch and relaunch (elastic
    restart loop)."""
    ck = str(tmp_path / "never-written")
    tr = _build_trainer(world["mesh"])
    s0 = tr.init(jax.random.PRNGKey(0),
                 tr.shard_batch(world["batches"][0]))
    s1, _ = tr.fit(s0, list(world["batches"]), resume_from=ck,
                   autosave_every=0)
    _assert_identical(world["baseline"], _fingerprint(tr, s1))


@pytest.mark.slow
def test_chaos_kill_mid_fit_then_resume(world, tmp_path):
    """The headline robustness round: a ChaosKill (the in-process
    SIGKILL analogue) lands at the trainer.fit.step sync point mid-run;
    a fresh trainer resumes from whatever the chain committed and is
    bit-identical to the uninterrupted baseline."""
    ck = str(tmp_path / "auto")
    plan = chaos.FaultPlan(
        [chaos.FaultSpec(point="trainer.fit.step",
                         action="kill_thread", hit=INTERRUPT)])
    tr1 = _build_trainer(world["mesh"])
    s1 = tr1.init(jax.random.PRNGKey(0),
                  tr1.shard_batch(world["batches"][0]))
    with chaos.active_plan(plan):
        with pytest.raises(chaos.ChaosKill):
            tr1.fit(s1, list(world["batches"]),
                    autosave_every=AUTOSAVE, autosave_dir=ck)
    assert plan.injected, "the kill must actually have fired"

    tr2 = _build_trainer(world["mesh"])
    s2 = tr2.init(jax.random.PRNGKey(0),
                  tr2.shard_batch(world["batches"][0]))
    s2b, _ = tr2.fit(s2, list(world["batches"]), resume_from=ck,
                     autosave_every=AUTOSAVE, autosave_dir=ck)
    _assert_identical(world["baseline"], _fingerprint(tr2, s2b))


def test_autosave_records_trained_cursor(world, tmp_path):
    """The manifest extra holds the count of batches whose gradients
    the committed state contains — the exact stream position a resume
    seeks to (graftproto ``trainer_restart``: neither reapply nor
    skip)."""
    from openembedding_tpu import checkpoint_delta as cd
    ck = str(tmp_path / "auto")
    tr1 = _build_trainer(world["mesh"])
    s1 = tr1.init(jax.random.PRNGKey(0),
                  tr1.shard_batch(world["batches"][0]))
    tr1.fit(s1, list(world["batches"][:INTERRUPT]),
            autosave_every=AUTOSAVE, autosave_dir=ck)
    cd.join_compactor(ck)
    manifest = cd.read_manifest(ck)
    verified, _dropped = cd.verify_chain(ck, manifest)
    extra = cd.resume_extra(manifest, verified)
    fit = extra["fit"]
    assert fit["cursor"] == INTERRUPT
    assert fit["step"] >= INTERRUPT


# --- streamed source: cursor exactness through ShardStream -------------------

STREAM_FEATURES = ("C1", "C2", "C3")
STREAM_VOCAB = 1 << 10
STREAM_BATCH = 64


def _prune(batch):
    keep = set(STREAM_FEATURES) | {f + ":linear"
                                   for f in STREAM_FEATURES}
    return {**batch,
            "sparse": {k: v for k, v in batch["sparse"].items()
                       if k in keep}}


def _build_stream_trainer(mesh):
    import optax
    from openembedding_tpu import EmbeddingCollection, Trainer
    from openembedding_tpu.models import deepctr
    specs = deepctr.make_feature_specs(STREAM_FEATURES, STREAM_VOCAB, 4)
    coll = EmbeddingCollection(
        specs, mesh,
        default_optimizer={"category": "adagrad",
                           "learning_rate": 0.05})
    coll.enable_dirty_tracking(target_chunks=16)
    model = deepctr.build_model("deepfm", STREAM_FEATURES)
    return Trainer(model, coll, optax.adam(1e-2))


def _open_stream(shard_dir):
    from openembedding_tpu.data import stream
    return stream.ShardStream(shard_dir, batch_size=STREAM_BATCH,
                              readers=2, epochs=1,
                              num_buckets=STREAM_VOCAB,
                              add_linear=True, transform=_prune)


@pytest.mark.slow
def test_streamed_resume_skips_exactly_the_trained_batches(
        devices8, tmp_path):
    """Kill mid-fit over a LIVE ShardStream, resume over a FRESH stream
    of the same shards: ``fit`` must seek via ``skip_batches`` to the
    committed cursor (no re-apply, no skip) and land bit-identical on
    the uninterrupted streamed baseline."""
    from openembedding_tpu.data import stream
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(2, 4, devices8)
    shard_dir = str(tmp_path / "shards")
    stream.write_synthetic_shards(shard_dir, num_shards=2,
                                  rows_per_shard=256, seed=5)

    # uninterrupted streamed baseline
    src = _open_stream(shard_dir)
    try:
        it = iter(src)
        first = next(it)
        tr = _build_stream_trainer(mesh)
        s0 = tr.init(jax.random.PRNGKey(0), tr.shard_batch(first))
        s1, _ = tr.fit(s0, _chain(first, it))
        total = src.cursor()
    finally:
        src.close()
    baseline = _fingerprint_stream(tr, s1)
    assert total == (2 * 256) // STREAM_BATCH

    # interrupted run: chaos kill mid-stream, autosaving every 2
    ck = str(tmp_path / "auto")
    plan = chaos.FaultPlan(
        [chaos.FaultSpec(point="trainer.fit.step", action="kill_thread",
                         hit=5)])
    src = _open_stream(shard_dir)
    try:
        it = iter(src)
        first = next(it)
        tr1 = _build_stream_trainer(mesh)
        s0 = tr1.init(jax.random.PRNGKey(0), tr1.shard_batch(first))
        with chaos.active_plan(plan):
            with pytest.raises(chaos.ChaosKill):
                tr1.fit(s0, _chain(first, it), autosave_every=2,
                        autosave_dir=ck)
        assert plan.injected
    finally:
        src.close()

    # resume over a FRESH stream of the same shards
    src = _open_stream(shard_dir)
    try:
        tr2 = _build_stream_trainer(mesh)
        it = iter(src)
        first = next(it)
        s0 = tr2.init(jax.random.PRNGKey(0), tr2.shard_batch(first))
        # init consumed batch 0 for shapes only; rewind the accounting
        # by handing fit the reconstructed full stream
        s2, _ = tr2.fit(s0, _chain(first, it), resume_from=ck,
                        autosave_every=2, autosave_dir=ck)
        assert src.cursor() == total
    finally:
        src.close()
    _assert_identical(baseline, _fingerprint_stream(tr2, s2))


def test_shardstream_skip_batches_is_exact(tmp_path):
    """Cursor satellite: ``skip_batches(n)`` advances the stream to
    exactly the batch a fresh stream reaches after n pops — same ids,
    same order, and ``cursor()`` counts delivered batches."""
    from openembedding_tpu.data import stream
    shard_dir = str(tmp_path / "shards")
    stream.write_synthetic_shards(shard_dir, num_shards=2,
                                  rows_per_shard=128, seed=3)

    def open_s():
        return stream.ShardStream(shard_dir, batch_size=32, readers=2,
                                  epochs=1, num_buckets=256)

    a = open_s()
    try:
        popped = [next(iter(a)) for _ in range(3)]
        assert a.cursor() == 3
        rest_a = [b for b in a]
    finally:
        a.close()

    b = open_s()
    try:
        assert b.skip_batches(3) == 3
        assert b.cursor() == 3
        rest_b = [x for x in b]
        assert b.cursor() == 3 + len(rest_b)
    finally:
        b.close()

    assert len(rest_a) == len(rest_b) > 0
    for x, y in zip(rest_a, rest_b):
        np.testing.assert_array_equal(x["label"], y["label"])
        for k in x["sparse"]:
            np.testing.assert_array_equal(x["sparse"][k],
                                          y["sparse"][k])
    del popped


def _chain(first, it):
    import itertools
    return itertools.chain([first], it)


def _fingerprint_stream(tr, state):
    out = [np.asarray(int(state.step))]
    for leaf in jax.tree.leaves((state.params, state.opt_state)):
        out.append(np.asarray(jax.device_get(leaf)))
    allv = np.arange(STREAM_VOCAB, dtype=np.int32)
    names = list(tr.collection.specs)
    pulls = tr.collection.pull(state.emb, {n: allv for n in names},
                               batch_sharded=False)
    for n in names:
        out.append(np.asarray(pulls[n]))
    return out
