"""Sharded-table parity: distributed pull/apply must match the single-shard core.

Mirrors the reference's multi-node matrix tests (c_api_test.h: nodes x shard
configs cross-checked against a local replica) — here the "cluster" is the
8-device CPU mesh and ground truth is the single-device table code.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import (EmbeddingVariableMeta, apply_gradients,
                               create_table, make_optimizer, pull)
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.parallel import sharded_table as st

VOCAB, DIM = 50, 4


@pytest.mark.parametrize("layout", ["mod", "div"])
@pytest.mark.parametrize("data,model", [(1, 8), (2, 4), (8, 1)])
def test_sharded_matches_single(devices8, layout, data, model):
    mesh = create_mesh(data, model, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=VOCAB)
    opt = make_optimizer({"category": "adagrad", "learning_rate": 0.1})
    spec = st.make_sharding_spec(meta, mesh, layout=layout)

    sharded = st.create_sharded_table(meta, opt, {"category": "constant", "value": 0.5},
                                      mesh=mesh, spec=spec)
    single = create_table(meta, opt, {"category": "constant", "value": 0.5},
                          capacity=spec.padded_vocab)

    rng = np.random.RandomState(0)
    B = 16  # divisible by all data sizes
    for step in range(3):
        idx = rng.randint(0, VOCAB, size=B).astype(np.int32)
        grads = rng.randn(B, DIM).astype(np.float32)
        jidx, jg = jnp.asarray(idx), jnp.asarray(grads)

        got_rows = st.pull_sharded(sharded, jidx, mesh=mesh, spec=spec)
        # single-shard ground truth uses logical ids directly
        shard, local = spec.shard_and_local(jidx)
        phys = shard * spec.rows_per_shard + local
        want_rows = pull(single, phys)
        np.testing.assert_allclose(np.asarray(got_rows), np.asarray(want_rows),
                                   rtol=1e-6, atol=1e-6)

        sharded = st.apply_gradients_sharded(sharded, opt, jidx, jg,
                                             mesh=mesh, spec=spec)
        single = apply_gradients(single, opt, phys, jg)

    np.testing.assert_allclose(np.asarray(sharded.weights),
                               np.asarray(single.weights), rtol=1e-5, atol=1e-5)
    for k in single.slots:
        np.testing.assert_allclose(np.asarray(sharded.slots[k]),
                                   np.asarray(single.slots[k]), rtol=1e-5, atol=1e-5)


def test_batch_sharded_consistency(devices8):
    """Sharded-batch path == replicated-batch path."""
    mesh = create_mesh(4, 2, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=VOCAB)
    opt = make_optimizer({"category": "sgd", "learning_rate": 0.5, "momentum": 0.9})
    spec = st.make_sharding_spec(meta, mesh)
    t1 = st.create_sharded_table(meta, opt, mesh=mesh, spec=spec,
                                 rng=jax.random.PRNGKey(5))
    t2 = jax.tree.map(jnp.copy, t1)

    idx = jnp.arange(16, dtype=jnp.int32) % VOCAB
    g = jnp.ones((16, DIM)) * jnp.arange(16)[:, None]

    r1 = st.pull_sharded(t1, idx, mesh=mesh, spec=spec, batch_sharded=True)
    r2 = st.pull_sharded(t2, idx, mesh=mesh, spec=spec, batch_sharded=False)
    np.testing.assert_allclose(np.asarray(r1), np.asarray(r2), rtol=1e-6)

    t1 = st.apply_gradients_sharded(t1, opt, idx, g, mesh=mesh, spec=spec,
                                    batch_sharded=True)
    t2 = st.apply_gradients_sharded(t2, opt, idx, g, mesh=mesh, spec=spec,
                                    batch_sharded=False)
    np.testing.assert_allclose(np.asarray(t1.weights), np.asarray(t2.weights),
                               rtol=1e-6, atol=1e-6)


def test_mod_layout_spreads_hot_rows(devices8):
    """Sequential hot ids 0..7 land on 8 different shards under mod layout."""
    mesh = create_mesh(1, 8, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=2, vocabulary_size=64)
    spec = st.make_sharding_spec(meta, mesh, layout="mod")
    shard, _ = spec.shard_and_local(jnp.arange(8))
    assert sorted(np.asarray(shard).tolist()) == list(range(8))


def test_out_of_range_index_zero_row_and_dropped(devices8):
    mesh = create_mesh(1, 8, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=VOCAB)
    opt = make_optimizer({"category": "sgd", "learning_rate": 1.0})
    spec = st.make_sharding_spec(meta, mesh)
    t = st.create_sharded_table(meta, opt, {"category": "constant", "value": 1.0},
                                mesh=mesh, spec=spec)
    bad = jnp.array([spec.padded_vocab, spec.padded_vocab + 9, -1], dtype=jnp.int32)
    rows = st.pull_sharded(t, bad, mesh=mesh, spec=spec, batch_sharded=False)
    np.testing.assert_array_equal(np.asarray(rows), np.zeros((3, DIM)))
    before = np.asarray(t.weights).copy()
    t2 = st.apply_gradients_sharded(t, opt, bad, jnp.ones((3, DIM)),
                                    mesh=mesh, spec=spec, batch_sharded=False)
    np.testing.assert_array_equal(before, np.asarray(t2.weights))


def test_bfloat16_table_trains_sharded(devices8):
    """bf16 storage with f32 optimizer math, on the a2a plane end-to-end
    (the README-advertised bfloat16 path; reference stores f32/f64 only —
    bf16 halves HBM, a TPU-native win)."""
    mesh = create_mesh(2, 4, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=8, vocabulary_size=128,
                                 datatype="bfloat16")
    opt = make_optimizer({"category": "adagrad", "learning_rate": 0.5})
    spec = st.make_sharding_spec(meta, mesh)
    state = st.create_sharded_table(
        meta, opt, {"category": "constant", "value": 0.25},
        mesh=mesh, spec=spec)
    assert state.weights.dtype == jnp.bfloat16
    # the at-rest precision-ladder contract (parallel/precision.py):
    # bf16 WEIGHTS halve the HBM-dominant array, optimizer SLOTS store
    # at f32 (master-statistics rule — accumulator drift in bf16 would
    # compound every step; the update math was already f32, table.py)
    assert all(s.dtype == jnp.float32
               for s in jax.tree.leaves(state.slots))
    idx = jnp.asarray(np.arange(16, dtype=np.int32))
    for _ in range(3):
        rows = st.pull_sharded(state, idx, mesh=mesh, spec=spec,
                               batch_sharded=False)
        assert rows.dtype == jnp.bfloat16
        g = jnp.ones((16, 8), jnp.bfloat16) * 0.5
        state = st.apply_gradients_sharded(state, opt, idx, g, mesh=mesh,
                                           spec=spec, batch_sharded=False)
    rows = np.asarray(st.pull_sharded(state, idx, mesh=mesh, spec=spec,
                                      batch_sharded=False)).astype(np.float32)
    # weights moved (adagrad with constant grads): must differ from init
    # and be finite, identical across the batch (same update everywhere)
    assert np.isfinite(rows).all()
    assert (rows < 0.25 - 0.1).all()
    np.testing.assert_allclose(rows, np.broadcast_to(rows[0], rows.shape),
                               rtol=1e-2)
