"""Numeric parity tests for the 9 row-sparse optimizers.

Method mirrors the reference's test/optimizer_test.py: apply the same random
gradient streams to (a) an independent per-row numpy simulation of the
documented update rule and (b) the framework's table apply path, over many
steps with duplicate keys and partial row coverage, then compare. Ground truth
is implemented standalone in numpy (not via the framework) so a transcription
bug in the JAX path can't self-verify.
"""

import zlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import (EmbeddingVariableMeta, apply_gradients,
                               create_table, make_optimizer, pull)

ROWS, DIM = 37, 8


def numpy_reference_update(category, hp, w, state, g, count):
    """One-row update rule, straight from the documented reference semantics."""
    w = w.copy()
    if category == "default":
        return w - hp["learning_rate"] * g, state
    if category == "adadelta":
        acc, accu = state
        acc = acc * hp["rho"] + g * g * (1 - hp["rho"])
        upd = g * np.sqrt(accu + hp["epsilon"]) / np.sqrt(acc + hp["epsilon"])
        accu = accu * hp["rho"] + upd * upd * (1 - hp["rho"])
        return w - hp["learning_rate"] * upd, (acc, accu)
    if category == "adagrad":
        acc, = state
        acc = acc + g * g
        return w - hp["learning_rate"] * g / (np.sqrt(acc) + hp["epsilon"]), (acc,)
    if category == "adam":
        m, v, b1t, b2t = state
        b1t, b2t = b1t * hp["beta_1"], b2t * hp["beta_2"]
        lr = hp["learning_rate"] * np.sqrt(1 - b2t) / (1 - b1t)
        m = m * hp["beta_1"] + g * (1 - hp["beta_1"])
        v = v * hp["beta_2"] + g * g * (1 - hp["beta_2"])
        return w - lr * m / (np.sqrt(v) + hp["epsilon"]), (m, v, b1t, b2t)
    if category == "adamax":
        m, v, b1t = state
        b1t = b1t * hp["beta_1"]
        lr = hp["learning_rate"] / (1 - b1t)
        m = m * hp["beta_1"] + g * (1 - hp["beta_1"])
        v = np.maximum(np.abs(g), v * hp["beta_2"])
        return w - lr * m / (v + hp["epsilon"]), (m, v, b1t)
    if category == "ftrl":
        acc, lin = state
        lr = hp["learning_rate"]
        adj_l2 = hp["l2_regularization_strength"] + hp["beta"] / lr / 2
        gg = g + 2 * hp["l2_shrinkage_regularization_strength"] * w
        acc_new = acc + g * g
        p = -hp["learning_rate_power"]
        sigma = (acc_new ** p - acc ** p) / lr
        lin = lin + gg - sigma * w
        quad = acc_new ** p / lr + 2 * adj_l2
        l1 = hp["l1_regularization_strength"]
        adj = np.clip(lin, -l1, l1)
        return (adj - lin) / quad, (acc_new, lin)
    if category == "rmsprop":
        acc, mom = state
        acc = acc * hp["rho"] + g * g * (1 - hp["rho"])
        mom = mom * hp["momentum"] + hp["learning_rate"] * g / np.sqrt(acc + hp["epsilon"])
        return w - mom, (acc, mom)
    if category == "sgd":
        mom, = state
        mom = mom * hp["momentum"] + hp["learning_rate"] * g
        if hp["nesterov"]:
            return w - (mom * hp["momentum"] + hp["learning_rate"] * g), (mom,)
        return w - mom, (mom,)
    if category == "test":
        st, = state
        st = hp["flip"] - st
        return w + hp["learning_rate"] * g / count + st, (st,)
    raise ValueError(category)


def init_numpy_state(category, hp, dim):
    if category == "default":
        return ()
    if category in ("adadelta", "rmsprop"):
        return (np.zeros(dim), np.zeros(dim))
    if category == "adagrad":
        return (np.full(dim, hp["initial_accumulator_value"]),)
    if category == "adam":
        return (np.zeros(dim), np.zeros(dim), 1.0, 1.0)
    if category == "adamax":
        return (np.zeros(dim), np.zeros(dim), 1.0)
    if category == "ftrl":
        return (np.full(dim, hp["initial_accumulator_value"]), np.zeros(dim))
    if category == "sgd":
        return (np.zeros(dim),)
    if category == "test":
        return (np.array([hp["init"]]),)
    raise ValueError(category)


CONFIGS = [
    {"category": "default", "learning_rate": 0.05},
    {"category": "adadelta", "learning_rate": 0.01, "rho": 0.9, "epsilon": 1e-6},
    {"category": "adagrad", "learning_rate": 0.01, "initial_accumulator_value": 0.2,
     "epsilon": 1e-7},
    {"category": "adam", "learning_rate": 0.002, "beta_1": 0.9, "beta_2": 0.995,
     "epsilon": 1e-7},
    {"category": "adamax", "learning_rate": 0.002},
    {"category": "ftrl", "learning_rate": 0.02, "initial_accumulator_value": 0.1,
     "l1_regularization_strength": 0.01, "l2_regularization_strength": 0.01,
     "beta": 0.1},
    {"category": "ftrl", "learning_rate": 0.02, "learning_rate_power": -0.7},
    {"category": "rmsprop", "learning_rate": 0.005, "rho": 0.92, "momentum": 0.5},
    {"category": "sgd", "learning_rate": 0.05, "momentum": 0.9},
    {"category": "sgd", "learning_rate": 0.05, "momentum": 0.9, "nesterov": True},
    {"category": "test", "learning_rate": 0.1, "flip": 100.0, "init": 0.0},
]


@pytest.mark.parametrize("config", CONFIGS,
                         ids=lambda c: c["category"] + str(zlib.crc32(repr(c).encode()) % 1000))
@pytest.mark.parametrize("steps", [
    1, pytest.param(10, marks=pytest.mark.slow)])
def test_optimizer_matches_numpy_reference(config, steps):
    rng = np.random.RandomState(zlib.crc32(repr(config).encode()) % 2**31)
    opt = make_optimizer(config)
    hp = {**{f: getattr(opt, f) for f in vars(opt)}}
    category = config["category"]

    meta = EmbeddingVariableMeta(datatype="float32", embedding_dim=DIM,
                                 vocabulary_size=ROWS)
    state = create_table(meta, opt, {"category": "uniform", "minval": -1, "maxval": 1},
                         rng=jax.random.PRNGKey(3))
    w_np = np.asarray(state.weights, dtype=np.float64)
    st_np = [init_numpy_state(category, hp, DIM) for _ in range(ROWS)]

    step = jax.jit(lambda s, i, g: apply_gradients(s, opt, i, g))

    for _ in range(steps):
        n = rng.randint(3, 20)
        idx = rng.randint(0, ROWS, size=n).astype(np.int32)
        grads = rng.randn(n, DIM).astype(np.float32)

        state = step(state, jnp.asarray(idx), jnp.asarray(grads))

        # numpy side: pre-sum duplicates, then one update per touched row
        for row in np.unique(idx):
            mask = idx == row
            g = grads[mask].sum(axis=0).astype(np.float64)
            w_np[row], st_np[row] = numpy_reference_update(
                category, hp, w_np[row], st_np[row], g, int(mask.sum()))

    got = np.asarray(pull(state, jnp.arange(ROWS)))
    np.testing.assert_allclose(got, w_np, rtol=2e-4, atol=2e-4)


def test_unknown_category_rejected():
    with pytest.raises(ValueError):
        make_optimizer({"category": "nadam"})
    with pytest.raises(ValueError):
        make_optimizer({"category": "adam", "amsgrad": True})


def test_state_dim_layout():
    # reference state_dim contract: adam = 2*dim+2, adamax = 2*dim+1, ...
    dims = {"default": 0, "adagrad": DIM, "sgd": DIM, "adadelta": 2 * DIM,
            "ftrl": 2 * DIM, "rmsprop": 2 * DIM, "adam": 2 * DIM + 2,
            "adamax": 2 * DIM + 1, "test": 1}
    for cat, expect in dims.items():
        assert make_optimizer(cat).state_dim(DIM) == expect, cat
