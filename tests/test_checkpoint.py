"""Checkpoint round-trips: save -> reload (same and different mesh shape),
optimizer-state preservation, include_optimizer=False, dense export, and meta
validation — the reference's dump/load matrix (c_api_test.h:303-343 state
round trip; Model.cpp meta check; exb.py:506-547 dense export)."""

import os
import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from openembedding_tpu import EmbeddingCollection, EmbeddingSpec, Trainer
from openembedding_tpu import checkpoint as ckpt
from openembedding_tpu.models import deepctr
from openembedding_tpu.parallel.mesh import create_mesh

VOCAB, DIM = 64, 4


def make_coll(mesh, vocab=VOCAB):
    specs = (EmbeddingSpec(name="arr", input_dim=vocab, output_dim=DIM),
             EmbeddingSpec(name="hsh", input_dim=-1, output_dim=DIM,
                           hash_capacity=512),)
    return EmbeddingCollection(
        specs, mesh,
        default_optimizer={"category": "adam", "learning_rate": 0.05})


def train_a_bit(coll, states, steps=4, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        idx = {"arr": jnp.asarray(rng.randint(0, VOCAB, 16).astype(np.int32)),
               "hsh": jnp.asarray(rng.randint(0, 2**30, 16).astype(np.int32))}
        rows = coll.pull(states, idx, batch_sharded=False)
        grads = {k: jnp.ones_like(v) * 0.1 for k, v in rows.items()}
        states = coll.apply_gradients(states, idx, grads, batch_sharded=False)
    return states, idx


def test_roundtrip_same_mesh(devices8, tmp_path):
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states, idx = train_a_bit(coll, coll.init(jax.random.PRNGKey(0)))
    before = coll.pull(states, idx, batch_sharded=False)

    ckpt.save_checkpoint(str(tmp_path / "m"), coll, states, model_sign="s-1")
    loaded = ckpt.load_checkpoint(str(tmp_path / "m"), coll)
    after = coll.pull(loaded, idx, batch_sharded=False)
    for k in before:
        np.testing.assert_allclose(np.asarray(before[k]), np.asarray(after[k]),
                                   rtol=1e-6, atol=1e-7)
    # optimizer state survives: one more identical step matches exactly
    s1, _ = train_a_bit(coll, states, steps=1, seed=9)
    s2, _ = train_a_bit(coll, loaded, steps=1, seed=9)
    np.testing.assert_allclose(np.asarray(s1["arr"].weights),
                               np.asarray(s2["arr"].weights), rtol=1e-6)


@pytest.mark.slow
def test_roundtrip_resharded(devices8, tmp_path):
    """Checkpoint from a 4-shard mesh loads onto an 8-shard mesh."""
    mesh_a = create_mesh(2, 4, devices8)
    coll_a = make_coll(mesh_a)
    states, idx = train_a_bit(coll_a, coll_a.init(jax.random.PRNGKey(0)))
    before = coll_a.pull(states, idx, batch_sharded=False)
    ckpt.save_checkpoint(str(tmp_path / "m"), coll_a, states)

    mesh_b = create_mesh(1, 8, devices8)
    coll_b = make_coll(mesh_b)
    loaded = ckpt.load_checkpoint(str(tmp_path / "m"), coll_b)
    after = coll_b.pull(loaded, idx, batch_sharded=False)
    for k in before:
        np.testing.assert_allclose(np.asarray(before[k]), np.asarray(after[k]),
                                   rtol=1e-6, atol=1e-7)


def test_reshard_non_divisible_vocab(devices8, tmp_path):
    """Vocab 10 on a 4-shard mesh (padded 12) loads onto 8 shards (padded 16):
    padded-row counts differ across topologies and must not crash or shift."""
    vocab = 10
    mesh_a = create_mesh(2, 4, devices8)
    specs_a = (EmbeddingSpec(name="arr", input_dim=vocab, output_dim=DIM),)
    coll_a = EmbeddingCollection(
        specs_a, mesh_a,
        default_optimizer={"category": "adagrad", "learning_rate": 0.1})
    states = coll_a.init(jax.random.PRNGKey(0))
    idx = {"arr": jnp.arange(vocab, dtype=jnp.int32)}
    rows = coll_a.pull(states, idx, batch_sharded=False)
    states = coll_a.apply_gradients(
        states, idx, {"arr": jnp.ones((vocab, DIM))}, batch_sharded=False)
    before = coll_a.pull(states, idx, batch_sharded=False)
    ckpt.save_checkpoint(str(tmp_path / "m"), coll_a, states)

    mesh_b = create_mesh(1, 8, devices8)
    coll_b = EmbeddingCollection(
        (EmbeddingSpec(name="arr", input_dim=vocab, output_dim=DIM),), mesh_b,
        default_optimizer={"category": "adagrad", "learning_rate": 0.1})
    loaded = ckpt.load_checkpoint(str(tmp_path / "m"), coll_b)
    after = coll_b.pull(loaded, idx, batch_sharded=False)
    np.testing.assert_allclose(np.asarray(before["arr"]),
                               np.asarray(after["arr"]), rtol=1e-6, atol=1e-7)


def test_without_optimizer_state(devices8, tmp_path):
    mesh = create_mesh(1, 8, devices8)
    coll = make_coll(mesh)
    states, idx = train_a_bit(coll, coll.init(jax.random.PRNGKey(0)))
    ckpt.save_checkpoint(str(tmp_path / "m"), coll, states,
                         include_optimizer=False)
    loaded = ckpt.load_checkpoint(str(tmp_path / "m"), coll)
    # weights preserved
    before = coll.pull(states, idx, batch_sharded=False)
    after = coll.pull(loaded, idx, batch_sharded=False)
    np.testing.assert_allclose(np.asarray(before["arr"]),
                               np.asarray(after["arr"]), rtol=1e-6)
    # adam moments reset to fresh init
    assert float(jnp.abs(loaded["arr"].slots["m"]).max()) == 0.0
    assert float(jnp.abs(states["arr"].slots["m"]).max()) > 0.0


def test_meta_mismatch_rejected(devices8, tmp_path):
    mesh = create_mesh(1, 8, devices8)
    coll = make_coll(mesh)
    states = coll.init()
    ckpt.save_checkpoint(str(tmp_path / "m"), coll, states)
    other = EmbeddingCollection(
        (EmbeddingSpec(name="arr", input_dim=VOCAB, output_dim=DIM + 2),
         EmbeddingSpec(name="hsh", input_dim=-1, output_dim=DIM,
                       hash_capacity=512)), mesh)
    with pytest.raises(ValueError, match="meta mismatch"):
        ckpt.load_checkpoint(str(tmp_path / "m"), other)


def test_dense_export(devices8, tmp_path):
    mesh = create_mesh(1, 8, devices8)
    specs = (EmbeddingSpec(name="arr", input_dim=VOCAB, output_dim=DIM),)
    coll = EmbeddingCollection(specs, mesh)
    states = coll.init(jax.random.PRNGKey(3))
    dense = ckpt.export_dense(coll, states)
    assert dense["arr"].shape == (VOCAB, DIM)
    rows = coll.pull(states, {"arr": jnp.arange(VOCAB, dtype=jnp.int32)},
                     batch_sharded=False)
    np.testing.assert_allclose(dense["arr"], np.asarray(rows["arr"]),
                               rtol=1e-6)
    # hash vars are rejected like the reference
    coll_h = make_coll(mesh)
    with pytest.raises(ValueError, match="hash"):
        ckpt.export_dense(coll_h, coll_h.init())


@pytest.mark.slow
def test_trainer_dense_state_roundtrip(devices8, tmp_path):
    """Full TrainState (dense params + optax) rides next to the sparse dump."""
    mesh = create_mesh(2, 4, devices8)
    feats = ("c0", "c1")
    specs = deepctr.make_feature_specs(feats, VOCAB, DIM)
    coll = EmbeddingCollection(specs, mesh)
    trainer = Trainer(deepctr.build_model("deepfm", feats), coll,
                      optax.adam(1e-2))
    rng = np.random.RandomState(0)
    batch = {"label": (rng.rand(16) > 0.5).astype(np.float32),
             "dense": rng.randn(16, 3).astype(np.float32),
             "sparse": {n: rng.randint(0, VOCAB, 16).astype(np.int32)
                        for n in [f for f in feats] +
                        [f + deepctr.LINEAR_SUFFIX for f in feats]}}
    state = trainer.init(jax.random.PRNGKey(0), trainer.shard_batch(batch))
    state, _ = trainer.train_step(state, batch)
    dense_pack = {"params": state.params, "opt_state": state.opt_state,
                  "step": state.step}
    ckpt.save_checkpoint(str(tmp_path / "m"), coll, state.emb,
                         dense_state=dense_pack)
    emb2, dense2 = ckpt.load_checkpoint(
        str(tmp_path / "m"), coll, dense_state_template=dense_pack)
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(state.params)[0]),
        np.asarray(jax.tree.leaves(dense2["params"])[0]), rtol=1e-6)
    assert int(dense2["step"]) == 1


def test_streaming_blocks_roundtrip(devices8, tmp_path, monkeypatch):
    """Force many sub-shard blocks: a tiny block size must not change the
    bytes on disk or the reload (the reference's ~1MB line streaming)."""
    monkeypatch.setattr(ckpt, "_BLOCK_BYTES", 64)  # a handful of rows
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states, idx = train_a_bit(coll, coll.init(jax.random.PRNGKey(0)))
    before = coll.pull(states, idx, batch_sharded=False)
    ckpt.save_checkpoint(str(tmp_path / "m"), coll, states)
    loaded = ckpt.load_checkpoint(str(tmp_path / "m"), coll)
    after = coll.pull(loaded, idx, batch_sharded=False)
    for k in before:
        np.testing.assert_allclose(np.asarray(before[k]),
                                   np.asarray(after[k]),
                                   rtol=1e-6, atol=1e-7)


def test_legacy_npz_checkpoint_loads(devices8, tmp_path):
    """Round-1 checkpoints (one npz per variable) still load."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states, idx = train_a_bit(coll, coll.init(jax.random.PRNGKey(0)))
    before = coll.pull(states, idx, batch_sharded=False)
    path = tmp_path / "m"
    ckpt.save_checkpoint(str(path), coll, states)
    # repackage each var dir into the legacy single-npz layout
    import shutil
    for name in ("arr", "hsh"):
        vid = coll.variable_id(name)
        vdir = path / ckpt._var_dir(vid, name)
        arrays = {f[:-4]: np.load(vdir / f) for f in os.listdir(vdir)}
        np.savez(path / ckpt._var_file(vid, name), **arrays)
        shutil.rmtree(vdir)
    loaded = ckpt.load_checkpoint(str(path), coll)
    after = coll.pull(loaded, idx, batch_sharded=False)
    for k in before:
        np.testing.assert_allclose(np.asarray(before[k]),
                                   np.asarray(after[k]),
                                   rtol=1e-6, atol=1e-7)


@pytest.mark.slow
def test_psum_plane_checkpoint_roundtrip(devices8, tmp_path):
    """psum-plane tables are replicated over the data axis; the streaming
    dump must emit each shard once (replica_id filter), not once per copy."""
    mesh = create_mesh(2, 4, devices8)
    specs = (EmbeddingSpec(name="arr", input_dim=VOCAB, output_dim=DIM,
                           plane="psum"),
             EmbeddingSpec(name="hsh", input_dim=-1, output_dim=DIM,
                           hash_capacity=512, plane="psum"),)
    coll = EmbeddingCollection(
        specs, mesh,
        default_optimizer={"category": "adam", "learning_rate": 0.05})
    states, idx = train_a_bit(coll, coll.init(jax.random.PRNGKey(0)))
    before = coll.pull(states, idx, batch_sharded=False)
    ckpt.save_checkpoint(str(tmp_path / "m"), coll, states)
    loaded = ckpt.load_checkpoint(str(tmp_path / "m"), coll)
    after = coll.pull(loaded, idx, batch_sharded=False)
    for k in before:
        np.testing.assert_allclose(np.asarray(before[k]),
                                   np.asarray(after[k]),
                                   rtol=1e-6, atol=1e-7)


def test_resave_clears_stale_slot_files(devices8, tmp_path):
    """Re-saving under an optimizer with fewer slots must not leave the old
    slot files behind for a later load to mistake for state."""
    mesh = create_mesh(2, 4, devices8)
    path = str(tmp_path / "m")
    adam = EmbeddingCollection(
        (EmbeddingSpec(name="arr", input_dim=VOCAB, output_dim=DIM),), mesh,
        default_optimizer={"category": "adam", "learning_rate": 0.05})
    ckpt.save_checkpoint(path, adam, adam.init(jax.random.PRNGKey(0)))
    vdir = tmp_path / "m" / ckpt._var_dir(0, "arr")
    assert (vdir / "slot_m.npy").exists()
    sgd = EmbeddingCollection(
        (EmbeddingSpec(name="arr", input_dim=VOCAB, output_dim=DIM),), mesh,
        default_optimizer={"category": "sgd", "learning_rate": 0.1})
    ckpt.save_checkpoint(path, sgd, sgd.init(jax.random.PRNGKey(1)))
    assert not (vdir / "slot_m.npy").exists()


def test_remote_fsspec_roundtrip(devices8):
    """Checkpoints stream to/from fsspec URIs (memory:// stands in for
    gs://, s3://, hdfs:// — the reference dumps straight to HDFS via piped
    hadoop IO, EmbeddingShardFile.h:57-63). Remote dumps use the keyed
    sequential part format; loads stream chunks and deliver rows to the
    owning devices — no memmaps, no local spool."""
    import uuid
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states, idx = train_a_bit(coll, coll.init(jax.random.PRNGKey(0)))
    before = coll.pull(states, idx, batch_sharded=False)

    uri = f"memory://ckpt-{uuid.uuid4().hex}/m"
    ckpt.save_checkpoint(uri, coll, states, model_sign="s-1")
    loaded = ckpt.load_checkpoint(uri, coll)
    after = coll.pull(loaded, idx, batch_sharded=False)
    for k in before:
        np.testing.assert_allclose(np.asarray(before[k]),
                                   np.asarray(after[k]),
                                   rtol=1e-6, atol=1e-7)
    # optimizer state survives the remote round trip bit-for-bit
    s1, _ = train_a_bit(coll, states, steps=1, seed=9)
    s2, _ = train_a_bit(coll, loaded, steps=1, seed=9)
    np.testing.assert_allclose(np.asarray(s1["arr"].weights),
                               np.asarray(s2["arr"].weights), rtol=1e-6)
    for sname in s1["arr"].slots:
        np.testing.assert_allclose(np.asarray(s1["arr"].slots[sname]),
                                   np.asarray(s2["arr"].slots[sname]),
                                   rtol=1e-6)


def test_remote_load_onto_different_mesh(devices8):
    """A remote dump re-shards at load like the local keyed format."""
    import uuid
    mesh8 = create_mesh(2, 4, devices8)
    coll8 = make_coll(mesh8)
    states, idx = train_a_bit(coll8, coll8.init(jax.random.PRNGKey(0)))
    before = coll8.pull(states, idx, batch_sharded=False)
    uri = f"memory://ckpt-{uuid.uuid4().hex}/m"
    ckpt.save_checkpoint(uri, coll8, states)

    mesh2 = create_mesh(1, 2, devices8[:2])
    coll2 = make_coll(mesh2)
    loaded = ckpt.load_checkpoint(uri, coll2)
    after = coll2.pull(loaded, idx, batch_sharded=False)
    for k in before:
        np.testing.assert_allclose(np.asarray(before[k]),
                                   np.asarray(after[k]),
                                   rtol=1e-6, atol=1e-7)


@pytest.mark.slow
def test_remote_bfloat16_roundtrip(devices8):
    """bf16 tables survive the remote stream path: numpy serializes
    ml_dtypes bfloat16 as an opaque '<V2' descr, and the streaming loader
    must view the raw chunks back under the model meta's true dtype."""
    import uuid
    mesh = create_mesh(2, 4, devices8)
    specs = (EmbeddingSpec(name="arr", input_dim=VOCAB, output_dim=DIM,
                           dtype="bfloat16"),
             EmbeddingSpec(name="hsh", input_dim=-1, output_dim=DIM,
                           dtype="bfloat16", hash_capacity=512),)
    coll = EmbeddingCollection(
        specs, mesh,
        default_optimizer={"category": "adagrad", "learning_rate": 0.1})
    states, idx = train_a_bit(coll, coll.init(jax.random.PRNGKey(0)))
    before = coll.pull(states, idx, batch_sharded=False)
    uri = f"memory://ckpt-{uuid.uuid4().hex}/m"
    ckpt.save_checkpoint(uri, coll, states)
    loaded = ckpt.load_checkpoint(uri, coll)
    assert loaded["arr"].weights.dtype == jnp.bfloat16
    after = coll.pull(loaded, idx, batch_sharded=False)
    for k in before:
        np.testing.assert_array_equal(np.asarray(before[k], np.float32),
                                      np.asarray(after[k], np.float32))


def test_local_dump_copied_to_remote_loads(devices8, tmp_path):
    """A single-host (logical-order, no ids files) dump copied to object
    storage still loads: the streaming loader synthesizes ids from row
    positions instead of demanding the keyed part format."""
    import uuid
    import fsspec
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states, idx = train_a_bit(coll, coll.init(jax.random.PRNGKey(0)))
    before = coll.pull(states, idx, batch_sharded=False)
    local = str(tmp_path / "m")
    ckpt.save_checkpoint(local, coll, states)
    # copy the dump byte-for-byte into the memory filesystem
    uri = f"memory://copied-{uuid.uuid4().hex}/m"
    fsmem, _ = fsspec.core.url_to_fs(uri)
    for dirpath, _dirs, files in os.walk(local):
        rel = os.path.relpath(dirpath, local)
        for fn in files:
            dst = uri + ("/" if rel == "." else f"/{rel}/") + fn
            with open(os.path.join(dirpath, fn), "rb") as fsrc, \
                    fsmem.open(dst, "wb") as fdst:
                fdst.write(fsrc.read())
    loaded = ckpt.load_checkpoint(uri, coll)
    after = coll.pull(loaded, idx, batch_sharded=False)
    for k in before:
        np.testing.assert_allclose(np.asarray(before[k]),
                                   np.asarray(after[k]),
                                   rtol=1e-6, atol=1e-7)


@pytest.mark.slow
def test_category_hotswap_array_to_hash(devices8, tmp_path):
    """An ARRAY dump loads into a HASH variable (bounded-vocab growth):
    logical row ids become keys, weights bit-equal, matching-optimizer
    slots restored — the reference's copy_from streaming conversion
    (EmbeddingVariable.cpp:29-60)."""
    mesh = create_mesh(2, 4, devices8)
    arr_specs = (EmbeddingSpec(name="v", input_dim=VOCAB, output_dim=DIM,
                               optimizer={"category": "adam",
                                          "learning_rate": 0.05}),)
    coll_a = EmbeddingCollection(arr_specs, mesh)
    states = coll_a.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    for _ in range(3):
        idx = {"v": jnp.asarray(rng.randint(0, VOCAB, 16).astype(np.int32))}
        rows = coll_a.pull(states, idx, batch_sharded=False)
        states = coll_a.apply_gradients(
            states, idx, {"v": jnp.ones_like(rows["v"]) * 0.2},
            batch_sharded=False)
    p = str(tmp_path / "m")
    ckpt.save_checkpoint(p, coll_a, states)

    hash_specs = (EmbeddingSpec(name="v", input_dim=-1, output_dim=DIM,
                                hash_capacity=4 * VOCAB,
                                optimizer={"category": "adam",
                                           "learning_rate": 0.05}),)
    coll_h = EmbeddingCollection(hash_specs, mesh)
    loaded = ckpt.load_checkpoint(p, coll_h)
    allv = jnp.arange(VOCAB, dtype=jnp.int32)
    want = coll_a.pull(states, {"v": allv}, batch_sharded=False)["v"]
    got = coll_h.pull(loaded, {"v": allv}, batch_sharded=False,
                      read_only=True)["v"]
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # adam slots came along: one identical step matches the array twin
    g = jnp.ones((VOCAB, DIM), jnp.float32) * 0.1
    s_a = coll_a.apply_gradients(states, {"v": allv}, {"v": g},
                                 batch_sharded=False)
    s_h = coll_h.apply_gradients(loaded, {"v": allv}, {"v": g},
                                 batch_sharded=False)
    wa = coll_a.pull(s_a, {"v": allv}, batch_sharded=False)["v"]
    wh = coll_h.pull(s_h, {"v": allv}, batch_sharded=False,
                     read_only=True)["v"]
    np.testing.assert_allclose(np.asarray(wh), np.asarray(wa),
                               rtol=1e-6, atol=1e-7)


@pytest.mark.slow
def test_category_hotswap_hash_to_array(devices8, tmp_path):
    """A HASH dump whose keys fit the bounded vocab loads into an ARRAY
    variable; out-of-range keys fail the load (deliver-or-fail)."""
    mesh = create_mesh(2, 4, devices8)
    hash_specs = (EmbeddingSpec(name="v", input_dim=-1, output_dim=DIM,
                                hash_capacity=512,
                                optimizer={"category": "adagrad",
                                           "learning_rate": 0.1}),)
    coll_h = EmbeddingCollection(hash_specs, mesh)
    states = coll_h.init(jax.random.PRNGKey(1))
    keys = jnp.asarray(np.arange(0, VOCAB, 3, dtype=np.int32))
    rows = coll_h.pull(states, {"v": keys}, batch_sharded=False)
    states = coll_h.apply_gradients(
        states, {"v": keys}, {"v": jnp.ones_like(rows["v"])},
        batch_sharded=False)
    p = str(tmp_path / "m")
    ckpt.save_checkpoint(p, coll_h, states)

    arr_specs = (EmbeddingSpec(name="v", input_dim=VOCAB, output_dim=DIM,
                               optimizer={"category": "adagrad",
                                          "learning_rate": 0.1}),)
    coll_a = EmbeddingCollection(arr_specs, mesh)
    loaded = ckpt.load_checkpoint(p, coll_a)
    want = coll_h.pull(states, {"v": keys}, batch_sharded=False,
                       read_only=True)["v"]
    got = coll_a.pull(loaded, {"v": keys}, batch_sharded=False)["v"]
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # untouched ids hold the array-table fill (zeros), not garbage
    miss = coll_a.pull(loaded, {"v": jnp.asarray([1], jnp.int32)},
                       batch_sharded=False)["v"]
    np.testing.assert_array_equal(np.asarray(miss), 0.0)

    # a key beyond the bounded vocab must fail the conversion
    big = jnp.asarray([VOCAB + 7], jnp.int32)
    rows = coll_h.pull(states, {"v": big}, batch_sharded=False)
    states = coll_h.apply_gradients(
        states, {"v": big}, {"v": jnp.ones_like(rows["v"])},
        batch_sharded=False)
    p2 = str(tmp_path / "m2")
    ckpt.save_checkpoint(p2, coll_h, states)
    with pytest.raises(ValueError, match="outside the bounded vocab"):
        ckpt.load_checkpoint(p2, coll_a)


def test_bounded_vocab_mismatch_still_rejected(devices8, tmp_path):
    """Category swap is allowed; bounded->bounded resize is not."""
    mesh = create_mesh(2, 4, devices8)
    coll = EmbeddingCollection(
        (EmbeddingSpec(name="v", input_dim=VOCAB, output_dim=DIM),), mesh)
    states = coll.init(jax.random.PRNGKey(0))
    p = str(tmp_path / "m")
    ckpt.save_checkpoint(p, coll, states)
    coll2 = EmbeddingCollection(
        (EmbeddingSpec(name="v", input_dim=2 * VOCAB, output_dim=DIM),),
        mesh)
    with pytest.raises(ValueError, match="meta mismatch"):
        ckpt.load_checkpoint(p, coll2)


@pytest.mark.slow
def test_wide_key_collection_roundtrip(devices8, tmp_path):
    """key_dtype='wide' hash variables (64-bit pair keys, x64 off) train
    through the collection and survive a checkpoint round trip."""
    from openembedding_tpu import hash_table as hl
    mesh = create_mesh(2, 4, devices8)
    specs = (EmbeddingSpec(name="w", input_dim=-1, output_dim=DIM,
                           hash_capacity=2048, key_dtype="wide",
                           optimizer={"category": "adagrad",
                                      "learning_rate": 0.1}),)
    coll = EmbeddingCollection(specs, mesh)
    states = coll.init(jax.random.PRNGKey(0))
    assert states["w"].keys.ndim == 2
    rng = np.random.RandomState(2)
    k64 = (rng.randint(0, 1 << 20, 32).astype(np.int64)
           + (rng.randint(0, 1 << 20, 32).astype(np.int64) << 32))
    pairs = jnp.asarray(hl.split64(k64))
    for _ in range(2):
        rows = coll.pull(states, {"w": pairs}, batch_sharded=False)
        states = coll.apply_gradients(
            states, {"w": pairs}, {"w": jnp.ones_like(rows["w"]) * 0.1},
            batch_sharded=False)
    want = coll.pull(states, {"w": pairs}, batch_sharded=False,
                     read_only=True)["w"]
    p = str(tmp_path / "m")
    ckpt.save_checkpoint(p, coll, states)
    loaded = ckpt.load_checkpoint(p, coll)
    got = coll.pull(loaded, {"w": pairs}, batch_sharded=False,
                    read_only=True)["w"]
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # keys sharing lo words stay distinct through the round trip
    probe = jnp.asarray(hl.split64(np.asarray(
        [int(k64[0]), int(k64[0]) ^ (1 << 40)], np.int64)))
    r = np.asarray(coll.pull(loaded, {"w": probe}, batch_sharded=False,
                             read_only=True)["w"])
    assert (np.abs(r[0] - r[1]) > 1e-9).any() or (r[1] == 0).all()


def test_category_hotswap_array_to_wide_hash(devices8, tmp_path):
    """Array dump -> WIDE-key hash variable: logical ids become (lo, hi=0)
    pairs; weights bit-equal; and a wide hash dump converts back to a
    bounded variable via joined 64-bit ids."""
    from openembedding_tpu import hash_table as hl
    mesh = create_mesh(2, 4, devices8)
    coll_a = EmbeddingCollection(
        (EmbeddingSpec(name="v", input_dim=VOCAB, output_dim=DIM,
                       initializer={"category": "normal", "stddev": 1.0},
                       optimizer={"category": "sgd",
                                  "learning_rate": 0.5}),), mesh)
    states = coll_a.init(jax.random.PRNGKey(4))
    p = str(tmp_path / "m")
    ckpt.save_checkpoint(p, coll_a, states)

    coll_w = EmbeddingCollection(
        (EmbeddingSpec(name="v", input_dim=-1, output_dim=DIM,
                       hash_capacity=4 * VOCAB, key_dtype="wide",
                       optimizer={"category": "sgd",
                                  "learning_rate": 0.5}),), mesh)
    loaded = ckpt.load_checkpoint(p, coll_w)
    allv = jnp.arange(VOCAB, dtype=jnp.int32)
    want = np.asarray(
        coll_a.pull(states, {"v": allv}, batch_sharded=False)["v"])
    pairs = jnp.asarray(hl.split64(np.arange(VOCAB, dtype=np.int64)))
    got = np.asarray(coll_w.pull(loaded, {"v": pairs}, batch_sharded=False,
                                 read_only=True)["v"])
    np.testing.assert_array_equal(got, want)

    # wide hash dump -> bounded array (keys joined back to logical ids)
    p2 = str(tmp_path / "m2")
    ckpt.save_checkpoint(p2, coll_w, loaded)
    loaded_a = ckpt.load_checkpoint(p2, coll_a)
    got_a = np.asarray(
        coll_a.pull(loaded_a, {"v": allv}, batch_sharded=False)["v"])
    np.testing.assert_array_equal(got_a, want)


@pytest.mark.slow
def test_wide_key_dump_shard_slices(devices8, tmp_path):
    """Serving shard slices over WIDE-key dumps: each slice holds exactly
    the keys with ``joined_id % G == k`` (owner on the 64-bit value) —
    the at-scale combination the reference serves unconditionally
    (client/Model.cpp:153-186). Also covers the array-dump->wide-hash +
    slice combination (the slice applies to the int64 ids BEFORE the pair
    conversion)."""
    from openembedding_tpu import hash_table as hl
    mesh = create_mesh(2, 4, devices8)
    serve_mesh = create_mesh(1, 1, jax.devices()[:1])
    G = 3
    # -- wide hash dump, sliced --------------------------------------------
    coll_w = EmbeddingCollection(
        (EmbeddingSpec(name="v", input_dim=-1, output_dim=DIM,
                       hash_capacity=512, key_dtype="wide",
                       initializer={"category": "constant", "value": 0.0},
                       optimizer={"category": "sgd",
                                  "learning_rate": 1.0}),), mesh)
    states = coll_w.init(jax.random.PRNGKey(0))
    keys64 = np.concatenate([
        (3 << 60) + np.arange(1, 17, dtype=np.int64),
        (3 << 60) + (np.arange(1, 17, dtype=np.int64) << 32)])
    pairs = jnp.asarray(hl.split64(keys64))
    g = jnp.broadcast_to(
        (np.arange(1, 33, dtype=np.float32) / 10.0)[:, None],
        (32, DIM))
    states = coll_w.apply_gradients(states, {"v": pairs}, {"v": g},
                                    batch_sharded=False)
    want = np.asarray(coll_w.pull(states, {"v": pairs},
                                  batch_sharded=False, read_only=True)["v"])
    p = str(tmp_path / "wide")
    ckpt.save_checkpoint(p, coll_w, states)
    owners = keys64 % G
    for k in range(G):
        coll_k = EmbeddingCollection(
            (EmbeddingSpec(name="v", input_dim=-1, output_dim=DIM,
                           hash_capacity=512, key_dtype="wide",
                           optimizer={"category": "default"}),), serve_mesh)
        loaded = ckpt.load_checkpoint(p, coll_k, shard_slice=(k, G))
        got = np.asarray(coll_k.pull(
            loaded, {"v": pairs}, batch_sharded=False, read_only=True)["v"])
        # owned keys: exact rows; non-owned: zero rows (absent)
        np.testing.assert_array_equal(got[owners == k], want[owners == k])
        np.testing.assert_array_equal(got[owners != k], 0.0)
        # the slice holds exactly its share of live rows
        assert int(jax.device_get(loaded["v"].num_used())) \
            == int((owners == k).sum())

    # -- array dump -> wide hash table, sliced (slice before pair split) ----
    coll_a = EmbeddingCollection(
        (EmbeddingSpec(name="v", input_dim=VOCAB, output_dim=DIM,
                       initializer={"category": "normal", "stddev": 1.0},
                       optimizer={"category": "sgd",
                                  "learning_rate": 0.5}),), mesh)
    st_a = coll_a.init(jax.random.PRNGKey(4))
    pa = str(tmp_path / "arr")
    ckpt.save_checkpoint(pa, coll_a, st_a)
    allv = np.arange(VOCAB, dtype=np.int64)
    want_a = np.asarray(
        coll_a.pull(st_a, {"v": jnp.arange(VOCAB, dtype=jnp.int32)},
                    batch_sharded=False)["v"])
    coll_k = EmbeddingCollection(
        (EmbeddingSpec(name="v", input_dim=-1, output_dim=DIM,
                       hash_capacity=4 * VOCAB, key_dtype="wide",
                       optimizer={"category": "default"}),), serve_mesh)
    loaded = ckpt.load_checkpoint(pa, coll_k, shard_slice=(1, G))
    ap = jnp.asarray(hl.split64(allv))
    got = np.asarray(coll_k.pull(loaded, {"v": ap}, batch_sharded=False,
                                 read_only=True)["v"])
    np.testing.assert_array_equal(got[allv % G == 1], want_a[allv % G == 1])
    np.testing.assert_array_equal(got[allv % G != 1], 0.0)


@pytest.mark.slow
def test_hash_key_width_migration(devices8, tmp_path):
    """int32-key hash dumps load into key_dtype='wide' variables (key-space
    migration) and wide dumps refuse narrow tables when keys overflow."""
    from openembedding_tpu import hash_table as hl
    mesh = create_mesh(2, 4, devices8)
    n32 = EmbeddingCollection(
        (EmbeddingSpec(name="h", input_dim=-1, output_dim=DIM,
                       hash_capacity=1024, key_dtype="int32",
                       initializer={"category": "constant", "value": 0.0},
                       optimizer={"category": "sgd",
                                  "learning_rate": 1.0}),), mesh)
    s32 = n32.init(jax.random.PRNGKey(0))
    keys = jnp.asarray([11, -7, 12345], jnp.int32)
    rows = n32.pull(s32, {"h": keys}, batch_sharded=False)
    s32 = n32.apply_gradients(s32, {"h": keys},
                              {"h": jnp.ones_like(rows["h"])},
                              batch_sharded=False)
    p = str(tmp_path / "m")
    ckpt.save_checkpoint(p, n32, s32)

    wide = EmbeddingCollection(
        (EmbeddingSpec(name="h", input_dim=-1, output_dim=DIM,
                       hash_capacity=1024, key_dtype="wide",
                       optimizer={"category": "sgd",
                                  "learning_rate": 1.0}),), mesh)
    loaded = ckpt.load_checkpoint(p, wide)
    pairs = jnp.asarray(hl.split64(np.asarray([11, -7, 12345], np.int64)))
    got = wide.pull(loaded, {"h": pairs}, batch_sharded=False,
                    read_only=True)["h"]
    want = n32.pull(s32, {"h": keys}, batch_sharded=False,
                    read_only=True)["h"]
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    # wide dump with a key past 2^31 must refuse a narrow table
    sw = wide.init(jax.random.PRNGKey(1))
    big = jnp.asarray(hl.split64(np.asarray([5 + (1 << 40)], np.int64)))
    rows = wide.pull(sw, {"h": big}, batch_sharded=False)
    sw = wide.apply_gradients(sw, {"h": big},
                              {"h": jnp.ones_like(rows["h"])},
                              batch_sharded=False)
    p2 = str(tmp_path / "m2")
    ckpt.save_checkpoint(p2, wide, sw)
    with pytest.raises(ValueError, match="outside the table's"):
        ckpt.load_checkpoint(p2, n32)


def test_int64_dump_empty_band_refused(devices8, tmp_path):
    """int64-key dumps holding keys in [-2^63, -2^63+2^32) cannot migrate
    to a wide table (they would split to the EMPTY sentinel and read as
    free slots) — the load must fail, not silently drop rows."""
    import os
    from openembedding_tpu import hash_table as hl
    mesh = create_mesh(2, 4, devices8)
    wide = EmbeddingCollection(
        (EmbeddingSpec(name="h", input_dim=-1, output_dim=DIM,
                       hash_capacity=512, key_dtype="wide"),), mesh)
    # craft a dump dir with an int64 keys file containing a banded key:
    # reuse a real int32 dump's layout, then rewrite keys as int64
    n32 = EmbeddingCollection(
        (EmbeddingSpec(name="h", input_dim=-1, output_dim=DIM,
                       hash_capacity=512, key_dtype="int32",
                       optimizer={"category": "sgd",
                                  "learning_rate": 1.0}),), mesh)
    s = n32.init(jax.random.PRNGKey(0))
    keys = jnp.asarray([5, 9], jnp.int32)
    rows = n32.pull(s, {"h": keys}, batch_sharded=False)
    s = n32.apply_gradients(s, {"h": keys}, {"h": jnp.ones_like(rows["h"])},
                            batch_sharded=False)
    p = str(tmp_path / "m")
    ckpt.save_checkpoint(p, n32, s)
    vdir = [d for d in os.listdir(p) if d.endswith(".d")][0]
    kpath = os.path.join(p, vdir, "keys.npy")
    k = np.load(kpath).astype(np.int64)
    k[0] = -(1 << 63) + 5  # in the excluded band
    np.save(kpath, k)
    with pytest.raises(ValueError, match="EMPTY band"):
        ckpt.load_checkpoint(p, wide)
