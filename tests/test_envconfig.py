"""EnvConfig typed tree: defaults, layering, validation — the reference's
EnvConfig.cpp per-field default+checker behavior."""

import json

import pytest

from openembedding_tpu.utils.envconfig import (A2AConfig, EnvConfig,
                                               OffloadConfig, ServingConfig)


def test_defaults():
    cfg = EnvConfig.load(env={})
    assert cfg.serving.port == 8010          # reference controller.cc
    assert cfg.serving.replica_num == 3      # reference c_api.cc:332-341
    assert cfg.a2a.slack == 2.0
    assert cfg.report.report_interval == 0.0


def test_layering_file_env_dict(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({"serving": {"port": 9000, "replica_num": 5},
                             "a2a": {"slack": 3.0}}))
    cfg = EnvConfig.load(
        config={"serving": {"port": 9100}},
        path=str(p),
        env={"OE_SERVING_REPLICA_NUM": "7",
             "OE_REPORT_EVALUATE_PERFORMANCE": "true"})
    assert cfg.serving.port == 9100          # dict beats env beats file
    assert cfg.serving.replica_num == 7      # env beats file
    assert cfg.a2a.slack == 3.0              # file beats defaults
    assert cfg.report.evaluate_performance is True  # bool coercion


def test_unknown_keys_rejected(tmp_path):
    with pytest.raises(ValueError, match="unknown config section"):
        EnvConfig.load(config={"rpc": {}}, env={})
    with pytest.raises(ValueError, match="unknown serving options"):
        EnvConfig.load(config={"serving": {"portt": 1}}, env={})


def test_field_checkers():
    with pytest.raises(ValueError, match="must be > 0"):
        A2AConfig(slack=0.0)
    with pytest.raises(ValueError, match=r"in \(0, 1\]"):
        OffloadConfig(occupancy_threshold=1.5)
    with pytest.raises(ValueError, match="port"):
        ServingConfig(port=99999)
    with pytest.raises(ValueError, match=">= 1"):
        EnvConfig.load(config={"serving": {"replica_num": 0}}, env={})


def test_round_trip():
    cfg = EnvConfig.load(config={"offload": {"cache_capacity": 512}}, env={})
    j = cfg.to_json()
    assert j["offload"]["cache_capacity"] == 512
    assert EnvConfig.load(config=j, env={}) == cfg


def test_trace_locks_wires_the_runtime_detector():
    from openembedding_tpu.analysis import concurrency

    cfg = EnvConfig.load(env={})
    assert cfg.report.trace_locks is False
    cfg = EnvConfig.load(env={"OE_REPORT_TRACE_LOCKS": "1"})
    assert cfg.report.trace_locks is True
    try:
        cfg.apply_report()
        assert concurrency.trace_locks_enabled()
        assert isinstance(concurrency.make_lock("envcfg.probe"),
                          concurrency.TracedLock)
    finally:
        concurrency.set_trace_locks(None)
        concurrency.reset_runtime()
