"""sparse_as_dense hybrid path: split rule, parity, end-to-end training.

Mirrors the reference's "Cache" mode contract (exb.py:100-104,617-632): a
feature must behave identically whichever path serves it — same lookup
contract (invalid ids -> zero rows) and, under plain SGD, identical updates.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from openembedding_tpu import EmbeddingCollection, EmbeddingSpec, Trainer
from openembedding_tpu.hybrid import (DenseEmbeddings, split_sparse_dense,
                                      to_dense_spec)
from openembedding_tpu.models import deepctr
from openembedding_tpu.parallel.mesh import create_mesh

DIM = 4


def _specs(vocabs):
    return tuple(
        EmbeddingSpec(name=f"f{i}", input_dim=v, output_dim=DIM,
                      initializer={"category": "constant", "value": 0.1},
                      optimizer={"category": "sgd", "learning_rate": 0.5})
        for i, v in enumerate(vocabs))


def test_split_rule_matches_reference():
    specs = _specs([8, 64, 65, 4096]) + (
        EmbeddingSpec(name="h", input_dim=-1, output_dim=DIM),)
    sparse, dense = split_sparse_dense(specs, sparse_as_dense_size=64)
    assert [s.name for s in dense] == ["f0", "f1"]
    assert [s.name for s in sparse] == ["f2", "f3", "h"]
    # batch_size rule: vocab < batch also goes dense (exb.py:602)
    sparse, dense = split_sparse_dense(specs, 64, batch_size=1024)
    assert [s.name for s in dense] == ["f0", "f1", "f2"]
    # hash variables can never be dense-kept
    with pytest.raises(ValueError, match="hash"):
        to_dense_spec(EmbeddingSpec(name="h", input_dim=-1, output_dim=DIM))


def test_dense_embeddings_invalid_index_contract(devices8):
    mod = DenseEmbeddings(
        (to_dense_spec(_specs([16])[0]),))
    params = mod.init(jax.random.PRNGKey(0),
                      {"f0": jnp.zeros((4,), jnp.int32)})
    idx = jnp.asarray([0, -1, 15, 16], jnp.int32)
    rows = mod.apply(params, {"f0": idx})["f0"]
    rows = np.asarray(rows)
    np.testing.assert_allclose(rows[0], 0.1, rtol=1e-6)
    np.testing.assert_allclose(rows[1], 0.0)   # negative -> zeros
    np.testing.assert_allclose(rows[2], 0.1, rtol=1e-6)
    np.testing.assert_allclose(rows[3], 0.0)   # out of range -> zeros


def _run_lr(devices8, dense_kept: bool, steps=4):
    """Train the LR model with both features on one path or the other."""
    mesh = create_mesh(2, 4, devices8)
    specs = _specs([32, 32])
    # need_linear-style dim-1 specs for the LR model
    lin = tuple(
        EmbeddingSpec(name=s.name + ":linear", input_dim=s.input_dim,
                      output_dim=1,
                      initializer={"category": "constant", "value": 0.0},
                      optimizer={"category": "sgd", "learning_rate": 0.5})
        for s in specs)
    all_specs = specs + lin
    if dense_kept:
        sparse_specs, dense_specs = split_sparse_dense(all_specs, 64)
        assert not sparse_specs and len(dense_specs) == 4
    else:
        sparse_specs, dense_specs = all_specs, ()
    coll = EmbeddingCollection(sparse_specs, mesh)
    model = deepctr.LogisticRegression(feature_names=("f0", "f1"))
    trainer = Trainer(model, coll, optax.sgd(0.5),
                      sparse_as_dense=dense_specs or None)
    rng = np.random.RandomState(0)

    def batch():
        sparse = {}
        for s in all_specs:
            base = s.name.split(":")[0]
            if base not in sparse:
                sparse[base] = rng.randint(0, 32, 16).astype(np.int32)
        cols = {s.name: sparse[s.name.split(":")[0]] for s in all_specs}
        label = (sparse["f0"] % 2).astype(np.float32)
        return {"label": label, "dense": None, "sparse": cols}

    state = trainer.init(jax.random.PRNGKey(1), trainer.shard_batch(batch()))
    losses = []
    for _ in range(steps):
        state, m = trainer.train_step(state, batch())
        losses.append(float(m["loss"]))
    probe = {s.name: jnp.arange(32, dtype=jnp.int32) for s in all_specs}
    if dense_kept:
        demb = state.params["sparse_as_dense"]
        got = {name: np.asarray(demb[name]) for name in demb}
    else:
        pulled = coll.pull(state.emb, probe, batch_sharded=False)
        got = {name: np.asarray(pulled[name]) for name in probe}
    return losses, got


@pytest.mark.slow
def test_hybrid_sgd_parity(devices8):
    """Plain SGD: dense-kept and sharded paths produce identical tables."""
    losses_s, rows_s = _run_lr(devices8, dense_kept=False)
    losses_d, rows_d = _run_lr(devices8, dense_kept=True)
    np.testing.assert_allclose(losses_s, losses_d, rtol=1e-5, atol=1e-6)
    for name in rows_s:
        np.testing.assert_allclose(rows_s[name], rows_d[name],
                                   rtol=1e-5, atol=1e-6)


def test_hybrid_deepfm_trains(devices8):
    """Mixed split: small vocabs dense-kept, big vocab + hash sharded."""
    mesh = create_mesh(2, 4, devices8)
    names = ("small", "big")
    specs = (
        EmbeddingSpec(name="small", input_dim=16, output_dim=DIM,
                      initializer={"category": "constant", "value": 0.1}),
        EmbeddingSpec(name="big", input_dim=4096, output_dim=DIM,
                      initializer={"category": "constant", "value": 0.1}),
        EmbeddingSpec(name="small:linear", input_dim=16, output_dim=1),
        EmbeddingSpec(name="big:linear", input_dim=4096, output_dim=1),
    )
    sparse_specs, dense_specs = split_sparse_dense(specs, 64)
    assert {s.name for s in dense_specs} == {"small", "small:linear"}
    coll = EmbeddingCollection(sparse_specs, mesh)
    trainer = Trainer(deepctr.DeepFM(feature_names=names), coll,
                      optax.adagrad(0.1), sparse_as_dense=dense_specs)
    rng = np.random.RandomState(3)

    def batch():
        small = rng.randint(0, 16, 32).astype(np.int32)
        big = rng.randint(0, 4096, 32).astype(np.int32)
        cols = {"small": small, "big": big,
                "small:linear": small, "big:linear": big}
        label = ((small + big) % 2).astype(np.float32)
        return {"label": label,
                "dense": rng.randn(32, 3).astype(np.float32),
                "sparse": cols}

    state = trainer.init(jax.random.PRNGKey(0), trainer.shard_batch(batch()))
    before = np.asarray(state.params["sparse_as_dense"]["small"]).copy()
    for _ in range(3):
        state, m = trainer.train_step(state, batch())
        assert np.isfinite(float(m["loss"]))
    after = np.asarray(state.params["sparse_as_dense"]["small"])
    assert not np.allclose(before, after), "dense-kept table never updated"
    # eval path works too
    scores = trainer.eval_step(state, batch())
    assert scores.shape == (32,)
