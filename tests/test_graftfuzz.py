"""graftfuzz gate: differential fuzzing + sanitizer coverage (tier 1).

Four layers, mirroring the gate's own structure:

* the pinned regression corpus (``tests/fixtures/fuzz_corpus.py``) —
  every known-bad checkpoint shape must produce EXACTLY its pinned
  disposition through all three readers, with the native reader probed
  under BOTH the plain and the ASan-instrumented build (each probe in
  its own subprocess). This is also the tier-1 coverage for native
  refusal paths no Python test could previously reach: the deflate and
  zip64 refusal messages, the crafted name_len central-directory
  refusal, the mid-chain tear, and ``oe_model_version`` on a compacted
  chain.
* ``DeltaDecodeError`` surfacing — truncated / bit-flipped /
  wrong-magic wire frames refuse typed from ``decode_delta``, and the
  REST ``POST /models/<sign>/delta`` handler maps that refusal to 400
  (never a 500 from a raw ``struct.error``/``zlib.error``).
* harness determinism — two wire-lane runs with the same seed produce
  byte-identical reports, and the full class list is declared.
* the ingest lane — mutated TFRecord/TSV shards through ShardStream
  must skip-and-count or fail loudly within the deadline, never hang.

The heavier randomized sweep runs in CI (`python -m tools.graftfuzz`,
per-PR fixed-seed smoke + weekly randomized long run), not here.
"""

import http.client
import importlib.util
import json
import os
import shutil
import subprocess

import numpy as np
import pytest

import jax

from openembedding_tpu import checkpoint_delta as cd
from openembedding_tpu.analysis import fuzz
from openembedding_tpu.serving import native as native_mod

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURE = os.path.join(HERE, "fixtures", "fuzz_corpus.py")

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="native toolchain (g++) required")


def _load_fixture():
    spec = importlib.util.spec_from_file_location("fuzz_corpus_fixture",
                                                  FIXTURE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def ctx(tmp_path_factory):
    return fuzz.SeedContext(str(tmp_path_factory.mktemp("graftfuzz")))


@pytest.fixture(scope="module")
def libs():
    # plain + ASan: the sanitizer leg of the matrix that tier 1 pays
    # for; the UBSan leg rides in the CI smoke (tools/graftfuzz.py)
    return {"": native_mod.build_library(),
            "asan": native_mod.build_library(variant="asan")}


CORPUS_NAMES = [e["name"] for e in _load_fixture().iter_corpus()]


# --- the pinned corpus, through all three readers ---------------------------

@pytest.mark.parametrize("name", CORPUS_NAMES)
def test_corpus_disposition(ctx, libs, tmp_path, name):
    """Each known-bad shape produces exactly its pinned disposition —
    refusal-message substring or recover-to version — in the Python
    loader, the Python delta reader, and the native reader under both
    the plain and the ASan build."""
    entry = next(e for e in _load_fixture().iter_corpus()
                 if e["name"] == name)
    d = fuzz.build_corpus_dir(name, ctx, str(tmp_path))
    expect = entry["expect"]
    failures = []
    for variant, lib in sorted(libs.items()):
        oc = fuzz.probe_native(d, lib, ctx.native_vars, sanitizer=variant)
        bad = fuzz._check_disposition(f"native[{variant or 'plain'}]",
                                      oc, expect["native"])
        if bad:
            failures.append(bad)
    for reader, probe in (("python_full", fuzz.probe_python_full),
                          ("python_delta", fuzz.probe_python_delta)):
        oc = probe(ctx, d)
        bad = fuzz._check_disposition(reader, oc, expect[reader])
        if bad:
            failures.append(bad)
    assert not failures, f"{name} ({entry['why']}): {failures}"


def test_corpus_fixture_rejects_malformed():
    """The iterator refuses malformed entries instead of skipping them
    (a typo'd pin must fail the fixture, never pass vacuously)."""
    mod = _load_fixture()
    good = dict(next(mod.iter_corpus()))

    def with_corpus(entries):
        mod.CORPUS = entries
        return list(mod.iter_corpus())

    orig = list(mod.CORPUS)
    try:
        for broken, why in [
            ({k: v for k, v in good.items() if k != "expect"}, "missing"),
            (dict(good, bogus=1), "unknown key"),
            (dict(good, expect={"python_full": good["expect"][
                "python_full"]}), "incomplete readers"),
            (dict(good, expect=dict(
                good["expect"],
                native={"outcome": "refuse"})), "refusal without match"),
            (dict(good, expect=dict(
                good["expect"],
                native={"outcome": "explode"})), "bad outcome"),
        ]:
            with pytest.raises(ValueError):
                with_corpus([broken])
        with pytest.raises(ValueError):
            with_corpus([good, dict(good)])     # duplicate name
    finally:
        mod.CORPUS = orig


# --- native refusal paths unreachable from the Python bindings --------------

def test_native_truncated_member_refusal(ctx, libs, tmp_path):
    """A stored member whose data runs past the mapping must refuse
    ("truncated npz member"), not read out of bounds — asserted under
    ASan, where an over-read would abort the probe."""
    d = os.path.join(str(tmp_path), "d")
    shutil.copytree(ctx.seed_dir, d)
    m = fuzz._load_m(d)
    rec = m["chain"][-1]["vars"]["arr"]
    p = os.path.join(d, rec["file"])
    with open(p, "rb") as f:
        buf = bytearray(f.read())
    ents, _ = fuzz._central_entries(buf)
    # grow the last member's sizes past EOF, keep the zip walkable
    e = max(ents, key=lambda x: x["lho"])
    grow = len(buf)
    fuzz._p32(buf, e["csize_off"], fuzz._u32(buf, e["csize_off"]) + grow)
    fuzz._p32(buf, e["usize_off"], fuzz._u32(buf, e["usize_off"]) + grow)
    lho = e["lho"]
    assert buf[lho:lho + 4] == b"PK\x03\x04"
    fuzz._p32(buf, lho + 18, fuzz._u32(buf, lho + 18) + grow)
    fuzz._p32(buf, lho + 22, fuzz._u32(buf, lho + 22) + grow)
    with open(p, "wb") as f:
        f.write(buf)
    fuzz._refresh_crc(d, m, rec["file"])
    fuzz._store_m(d, m)
    oc = fuzz.probe_native(d, libs["asan"], ctx.native_vars,
                           sanitizer="asan")
    assert oc["outcome"] == "refuse", oc
    assert "truncated npz member" in oc["error"], oc


def test_native_key_dtype_refusal(ctx, libs, tmp_path):
    """Narrowing a hash payload's KEY descr ('<i4' -> '<i2') must hit
    the typed dtype refusal, not reinterpret the key bytes (the
    garbage-read shape the keys_dtype guard closed). keys.npy is the
    only '<i4' member of an hsh delta (weights/accums are '<f4',
    chunk ids '<i8')."""
    d = os.path.join(str(tmp_path), "d")
    shutil.copytree(ctx.seed_dir, d)
    m = fuzz._load_m(d)
    hit = None
    for _, name, rec in fuzz._chain_recs(m):
        if name != "hsh":
            continue
        p = os.path.join(d, rec["file"])
        with open(p, "rb") as f:
            buf = bytearray(f.read())
        i = bytes(buf).find(b"'<i4'")
        if i < 0:
            continue
        buf[i:i + 5] = b"'<i2'"
        with open(p, "wb") as f:
            f.write(buf)
        fuzz._refresh_crc(d, m, rec["file"])
        hit = rec["file"]
        break
    assert hit, "no '<i4' key descr found in any hsh payload"
    fuzz._store_m(d, m)
    oc = fuzz.probe_native(d, libs["asan"], ctx.native_vars,
                           sanitizer="asan")
    assert oc["outcome"] == "refuse", oc
    assert "dtype" in oc["error"], oc


# --- DeltaDecodeError surfacing ---------------------------------------------

def _frame(ctx):
    return ctx.wire_frames[0]


def test_decode_delta_truncated_refuses_typed(ctx):
    frame = _frame(ctx)
    for keep in (0, 1, len(frame) // 2, len(frame) - 1):
        with pytest.raises(cd.DeltaDecodeError) as ei:
            cd.decode_delta(frame[:keep])
        assert str(ei.value)        # carries context, never empty

def test_decode_delta_bitflip_refuses_or_roundtrips(ctx):
    """Bit flips anywhere in the frame either refuse typed or decode
    deterministically — decode_delta never raises anything but
    DeltaDecodeError (struct.error/zlib.error escaping raw was the
    pre-gate behavior)."""
    frame = _frame(ctx)
    rng = np.random.RandomState(0)
    for _ in range(64):
        buf = bytearray(frame)
        i = int(rng.randint(len(buf)))
        buf[i] ^= 1 << int(rng.randint(8))
        try:
            d1 = cd.decode_delta(bytes(buf))
            d2 = cd.decode_delta(bytes(buf))
        except cd.DeltaDecodeError:
            continue
        assert d1.seq == d2.seq and sorted(d1.vars) == sorted(d2.vars)


def test_decode_delta_wrong_magic_refuses_typed(ctx):
    for garbage in (b"\x89PNG\r\n" + _frame(ctx), b"PK\x03\x04etc",
                    b"", b"\x00" * 64,
                    b'{"seq": 1}'):            # header but no newline
        with pytest.raises(cd.DeltaDecodeError):
            cd.decode_delta(garbage)


def test_decode_delta_error_is_valueerror():
    """The REST mapping contract: DeltaDecodeError IS a ValueError, so
    the handler's existing (KeyError, ValueError) -> 400 arm covers
    corrupt frames with no rest.py special case."""
    assert issubclass(cd.DeltaDecodeError, ValueError)


def test_rest_delta_post_corrupt_body_maps_400(devices8, tmp_path):
    """End to end over HTTP: a corrupt delta POST answers 400 (typed
    refusal), a valid frame still applies (200) — the fuzzer's REST
    surfacing satellite."""
    from openembedding_tpu import (EmbeddingCollection, EmbeddingSpec,
                                   checkpoint as ckpt)
    from openembedding_tpu.parallel.mesh import create_mesh
    from openembedding_tpu.serving.registry import ModelRegistry
    from openembedding_tpu.serving.rest import ControllerServer
    vocab, dim = 32, 4
    mesh = create_mesh(2, 4, devices8)
    coll = EmbeddingCollection(
        (EmbeddingSpec(name="arr", input_dim=vocab, output_dim=dim),),
        mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "model")
    ckpt.save_checkpoint(path, coll, states, model_sign="fz-1")
    reg = ModelRegistry(mesh)
    reg.create_model(path, block=True)
    srv = ControllerServer(reg, port=0).start()
    try:
        c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)

        def post(body):
            c.request("POST", "/models/fz-1/delta", body)
            r = c.getresponse()
            return r.status, json.loads(r.read() or b"null")

        good = cd.encode_delta(cd.Delta(seq=1, step=1, vars={"arr": {
            "weights": np.full((vocab, dim), 2.0, np.float32),
            "chunks": np.array([0], np.int64),
            "rows_per_chunk": np.array(vocab, np.int64),
            "vocab": np.array(vocab, np.int64),
        }}))
        for corrupt in (good[: len(good) // 2],      # truncated body
                        b"\x89PNG\r\n" + good,       # wrong magic
                        good.split(b"\n", 1)[0]):    # header, no body
            code, obj = post(corrupt)
            assert code == 400, (code, obj)
        buf = bytearray(good)
        buf[len(buf) - 8] ^= 0x40                    # payload bit flip
        code, obj = post(bytes(buf))
        assert code in (200, 400), (code, obj)
        code, obj = post(good)
        if code == 200:                              # not already applied
            assert obj["version"] == 1
        code, obj = post(good[:0])                   # empty body
        assert code == 400, (code, obj)
    finally:
        srv.stop()
        reg.close()


# --- harness determinism + coverage accounting ------------------------------

def test_wire_lane_deterministic_and_covered(ctx):
    """Two same-seed wire-lane runs produce byte-identical reports,
    every wire class fires, zero violations; a short run leaves the
    unfired classes marked silent (ok=False)."""
    kw = dict(seed=7, lanes=("wire",), ctx=ctx, libs={}, build=False)
    a = fuzz.run_fuzz(**kw)
    b = fuzz.run_fuzz(**kw)
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    assert a["ok"], a["violations"] or a["silent_classes"]
    assert sorted(a["classes"]) == sorted(fuzz.WIRE_CLASSES)
    assert all(c["fired"] for c in a["classes"].values())
    short = fuzz.run_fuzz(seed=7, iters=1, lanes=("wire",), ctx=ctx,
                          libs={}, build=False)
    assert short["silent_classes"] and not short["ok"]


def test_declared_classes_span_all_lanes():
    names = fuzz.all_classes()
    assert set(names) == (set(fuzz.CKPT_CLASSES) | set(fuzz.WIRE_CLASSES)
                          | set(fuzz.INGEST_CLASSES))
    assert len(names) >= 24     # the declared mutator grammar floor
    assert fuzz.NATIVE_ONLY_CLASSES <= set(fuzz.CKPT_CLASSES)


# --- the ingest lane ---------------------------------------------------------

def test_ingest_lane_skips_or_fails_loudly(ctx):
    """Every ingest mutation class: the mutated shard either streams to
    completion (damage skipped AND counted) or dies with a typed error
    — never a hang, never an untyped escape, pool still usable."""
    report = fuzz.run_fuzz(seed=3, lanes=("ingest",), ctx=ctx, libs={},
                           build=False, deadline=60.0)
    assert report["ok"], (report["violations"]
                          or report["silent_classes"])
    assert sorted(report["classes"]) == sorted(fuzz.INGEST_CLASSES)
    outcomes = {k for c in report["classes"].values()
                for k in c["outcomes"]}
    assert outcomes <= {"stream:load", "stream:refuse"}, outcomes
