"""Test harness: simulate an 8-device TPU mesh on CPU.

The reference's tests simulate an N-node cluster by forking N processes in one
box (core::MultiProcess, reference entry/c_api_test.h:194). The JAX-native
equivalent is XLA's virtual host devices: 8 CPU devices in one process, so all
shard_map/pjit collective paths execute for real without TPU hardware.
"""

import os

# force CPU even if the environment preselects a TPU platform: the test suite
# exercises collective paths on a virtual 8-device mesh. A sitecustomize may
# import jax before this file runs, so set the config directly as well.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older JAX: only the XLA_FLAGS path set above exists
    pass

import time  # noqa: E402

import pytest  # noqa: E402

_SESSION_T0 = time.time()

# Tier-1 wall-time guard: the CI window hard-kills the `not slow` lane at
# 870 s, which once silently truncated it mid-serving — every test past
# the cut reported neither pass nor fail. With OE_TIER1_BUDGET_S set
# (CI: 750) the session itself gets loud *before* the window does:
# a banner plus, with OE_TIER1_BUDGET_HARD=1, a nonzero exit so the lane
# FAILS instead of silently shrinking. Pair with --durations=10 so the
# offenders to slow-mark are in the same log.


def _tier1_budget() -> float:
    try:
        return float(os.environ.get("OE_TIER1_BUDGET_S", "0") or 0)
    except ValueError:
        return 0.0


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    budget = _tier1_budget()
    if not budget:
        return
    elapsed = time.time() - _SESSION_T0
    if elapsed <= budget:
        terminalreporter.write_line(
            f"tier-1 budget: {elapsed:.0f}s of {budget:.0f}s used")
        return
    terminalreporter.write_sep(
        "=", f"TIER-1 BUDGET EXCEEDED: {elapsed:.0f}s > {budget:.0f}s",
        red=True, bold=True)
    terminalreporter.write_line(
        "the 870s CI window will truncate this lane mid-run; slow-mark "
        "the top --durations offenders (see above) to get back under "
        "budget", red=True)


def pytest_sessionfinish(session, exitstatus):
    budget = _tier1_budget()
    if (budget and time.time() - _SESSION_T0 > budget
            and os.environ.get("OE_TIER1_BUDGET_HARD")):
        session.exitstatus = 3


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {devs}"
    return devs[:8]
