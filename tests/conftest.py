"""Test harness: simulate an 8-device TPU mesh on CPU.

The reference's tests simulate an N-node cluster by forking N processes in one
box (core::MultiProcess, reference entry/c_api_test.h:194). The JAX-native
equivalent is XLA's virtual host devices: 8 CPU devices in one process, so all
shard_map/pjit collective paths execute for real without TPU hardware.
"""

import os

# force CPU even if the environment preselects a TPU platform: the test suite
# exercises collective paths on a virtual 8-device mesh. A sitecustomize may
# import jax before this file runs, so set the config directly as well.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older JAX: only the XLA_FLAGS path set above exists
    pass

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {devs}"
    return devs[:8]
