"""End-to-end training stack: EmbeddingCollection + Trainer + model zoo.

The analogue of the reference's examples-as-tests strategy (SURVEY §4:
build.sh unit_test runs the example models end to end): synthetic criteo-like
batches through every model family on a (data, model) mesh, asserting the
jitted step runs, loss decreases, and mixed array+hash collections work.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from openembedding_tpu import EmbeddingCollection, EmbeddingSpec, Trainer
from openembedding_tpu.models import deepctr
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.utils import jaxcompat

FEATURES = ("c0", "c1", "c2")
VOCAB = 100
DIM = 8
B = 16


def synthetic_batches(n, seed=0, hash_keys=False):
    """Clickable synthetic task: label depends on feature parity."""
    rng = np.random.RandomState(seed)
    for _ in range(n):
        sparse = {}
        raw = {}
        for f in FEATURES:
            ids = rng.randint(0, VOCAB, size=B).astype(np.int32)
            raw[f] = ids
            key = ((ids.astype(np.int64) * 2654435761) % (2**31)
                   if hash_keys else ids)
            sparse[f] = key.astype(np.int32)
            sparse[f + deepctr.LINEAR_SUFFIX] = sparse[f]
        label = ((raw["c0"] + raw["c1"]) % 2).astype(np.float32)
        dense = rng.randn(B, 4).astype(np.float32)
        yield {"label": label, "dense": dense, "sparse": sparse}


def build_trainer(model_name, mesh, vocab=VOCAB, **spec_kw):
    specs = deepctr.make_feature_specs(FEATURES, vocab, DIM, **spec_kw)
    coll = EmbeddingCollection(
        specs, mesh,
        default_optimizer={"category": "adagrad", "learning_rate": 0.1})
    model = deepctr.build_model(model_name, FEATURES)
    return Trainer(model, coll, optax.adam(1e-2))


@pytest.mark.parametrize("model_name", [
    pytest.param("lr", marks=pytest.mark.xfail(
        strict=False,
        reason="jax 0.4.37: lr loss drifts upward (0.80->0.81) instead of "
               "decreasing — the synthetic label (c0+c1)%2 is XOR parity, "
               "which a linear model cannot fit (no interaction term; the "
               "deep models memorize it through their towers); earlier jax "
               "images passed on init/optimizer noise. A learnable-task lr "
               "check lives in test_auc_lift_on_learnable_task.")),
    "deepfm",
    # tier-1 budget (COVERAGE.md): deepfm exercises the shared
    # linear+fields+MLP path; the variant towers ride the slow lane
    pytest.param("wdl", marks=pytest.mark.slow),
    pytest.param("xdeepfm", marks=pytest.mark.slow),
    pytest.param("dcn", marks=pytest.mark.slow)])
def test_model_zoo_trains(devices8, model_name):
    mesh = create_mesh(2, 4, devices8)
    trainer = build_trainer(model_name, mesh)
    batches = list(synthetic_batches(30))
    state = trainer.init(jax.random.PRNGKey(0), trainer.shard_batch(batches[0]))
    losses = []
    for b in batches:
        state, m = trainer.train_step(state, b)
        losses.append(float(m["loss"]))
    assert int(state.step) == 30
    first, last = np.mean(losses[:5]), np.mean(losses[-5:])
    assert last < first, (first, last)
    # eval produces probabilities
    p = np.asarray(trainer.eval_step(state, batches[0]))
    assert p.shape == (B,) and (p >= 0).all() and (p <= 1).all()


@pytest.mark.slow
def test_hash_collection_trains(devices8):
    """input_dim=-1 features ride the hash-table path inside the same step.
    Slow lane (tier-1 budget): the fused hash path trains in tier-1 via
    test_fused.py::test_fused_hash_training."""
    mesh = create_mesh(2, 4, devices8)
    trainer = build_trainer("deepfm", mesh, vocab=-1, hash_capacity=4096)
    batches = list(synthetic_batches(20, hash_keys=True))
    state = trainer.init(jax.random.PRNGKey(0), trainer.shard_batch(batches[0]))
    losses = []
    for b in batches:
        state, m = trainer.train_step(state, b)
        losses.append(float(m["loss"]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    for f in FEATURES:
        assert int(state.emb[f].insert_failures) == 0


def test_mixed_array_and_hash(devices8):
    mesh = create_mesh(1, 8, devices8)
    specs = (EmbeddingSpec(name="a", input_dim=VOCAB, output_dim=DIM),
             EmbeddingSpec(name="b", input_dim=-1, output_dim=DIM,
                           hash_capacity=1024))
    coll = EmbeddingCollection(specs, mesh)
    states = coll.init(jax.random.PRNGKey(1))
    idx = {"a": jnp.arange(8, dtype=jnp.int32),
           "b": jnp.arange(8, dtype=jnp.int32) * 7 + 3}
    rows = coll.pull(states, idx, batch_sharded=False)
    assert rows["a"].shape == (8, DIM) and rows["b"].shape == (8, DIM)
    grads = {k: jnp.ones_like(v) for k, v in rows.items()}
    new_states = coll.apply_gradients(states, idx, grads, batch_sharded=False)
    # both variables actually moved
    for k in ("a", "b"):
        assert not np.allclose(np.asarray(rows[k]),
                               np.asarray(coll.pull(new_states, idx,
                                                    batch_sharded=False)[k]))


def test_int64_keys_require_int64_table(devices8):
    """int64 queries against an EXPLICIT int32-keyed table must refuse,
    not alias mod 2^32; the DEFAULT (wide) table accepts them at full
    width — even from a host int64 column with x64 OFF."""
    mesh = create_mesh(1, 8, devices8)
    specs = (EmbeddingSpec(name="h", input_dim=-1, output_dim=4,
                           hash_capacity=64, key_dtype="int32"),)
    coll = EmbeddingCollection(specs, mesh)
    states = coll.init()
    big = np.array([2**33 + 7], dtype=np.int64)
    # without x64, jnp.asarray itself truncates int64 -> int32 before the
    # table ever sees the key, so the aliasing guard only engages under x64
    with jaxcompat.enable_x64(True):
        with pytest.raises(ValueError, match="key_dtype"):
            coll.pull(states, {"h": jnp.asarray(big)}, batch_sharded=False)

    # the wide DEFAULT holds the full key: a host int64 column splits on
    # host (x64 off) and addresses the same row as explicit split64 pairs
    from openembedding_tpu import hash_table as hl
    wcoll = EmbeddingCollection(
        (EmbeddingSpec(name="h", input_dim=-1, output_dim=4,
                       hash_capacity=64,
                       initializer={"category": "normal", "stddev": 1.0},
                       optimizer={"category": "sgd",
                                  "learning_rate": 1.0}),), mesh)
    assert wcoll.specs["h"].key_dtype == "wide"
    ws = wcoll.init()
    ws = wcoll.apply_gradients(ws, {"h": big},
                               {"h": jnp.ones((1, 4), jnp.float32)},
                               batch_sharded=False)
    keys = np.asarray(jax.device_get(ws["h"].keys))
    live = keys[keys[..., 1] != hl.empty_key(np.int32)]
    assert set(hl.join64(live.reshape(-1, 2))) == {2**33 + 7}  # not 7!
    via_col = wcoll.pull(ws, {"h": big}, batch_sharded=False)["h"]
    via_pairs = wcoll.pull(ws, {"h": jnp.asarray(hl.split64(big))},
                           batch_sharded=False)["h"]
    np.testing.assert_array_equal(np.asarray(via_col),
                                  np.asarray(via_pairs))


def test_collection_meta_and_duplicate_names(devices8):
    mesh = create_mesh(1, 8, devices8)
    specs = deepctr.make_feature_specs(FEATURES, VOCAB, DIM)
    coll = EmbeddingCollection(specs, mesh)
    meta = coll.model_meta(model_sign="sig-1")
    assert len(meta.variables) == 6  # 3 features x (emb + linear)
    assert [v.variable_id for v in meta.variables] == list(range(6))
    with pytest.raises(ValueError, match="duplicate"):
        EmbeddingCollection(list(specs) + [specs[0]], mesh)


def test_auc_lift_on_learnable_task(devices8):
    """Eval path proves learning: AUC rises well above chance on a task the
    model can memorize (VERDICT: loss-decrease checks alone are weak)."""
    import optax
    from openembedding_tpu import EmbeddingCollection, EmbeddingSpec, Trainer
    from openembedding_tpu.models import deepctr
    from openembedding_tpu.parallel.mesh import create_mesh
    from openembedding_tpu.utils.observability import StreamingAUC

    mesh = create_mesh(2, 4, devices8)
    specs = (
        EmbeddingSpec(name="f", input_dim=256, output_dim=8,
                      optimizer={"category": "adagrad",
                                 "learning_rate": 0.5}),
        EmbeddingSpec(name="f:linear", input_dim=256, output_dim=1,
                      optimizer={"category": "adagrad",
                                 "learning_rate": 0.5}),
    )
    coll = EmbeddingCollection(specs, mesh)
    trainer = Trainer(deepctr.LogisticRegression(feature_names=("f",)),
                      coll, optax.adam(1e-2))
    rng = np.random.RandomState(0)

    def batch():
        ids = rng.randint(0, 256, 256).astype(np.int32)
        label = ((ids.astype(np.int64) * 2654435761) % 3 == 0).astype(np.float32)
        return {"label": label, "dense": None,
                "sparse": {"f": ids, "f:linear": ids}}

    state = trainer.init(jax.random.PRNGKey(0), trainer.shard_batch(batch()))
    auc0 = StreamingAUC()
    for _ in range(4):
        b = batch()
        auc0.update(b["label"], np.asarray(trainer.eval_step(state, b)))
    state, _ = trainer.fit(state, (batch() for _ in range(60)))
    auc1 = StreamingAUC()
    for _ in range(4):
        b = batch()
        auc1.update(b["label"], np.asarray(trainer.eval_step(state, b)))
    assert auc0.result() < 0.6, f"untrained AUC {auc0.result():.3f}"
    assert auc1.result() > 0.9, f"trained AUC {auc1.result():.3f}"
