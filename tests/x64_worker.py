"""Worker for the int64-hash-key test: runs with jax_enable_x64.

The reference's hash key space is 2^62 (tf.strings.to_hash_bucket_fast into
int64, exb.py input_dim=-1 -> 2^63 vocab). int64 keys need the global x64
flag, which changes dtypes program-wide — hence a dedicated process (the
documented deployment shape for full-width key spaces).
"""

import os
import sys


def main() -> int:
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax
    from openembedding_tpu.utils.jaxcompat import set_num_cpu_devices
    jax.config.update("jax_platforms", "cpu")
    set_num_cpu_devices(4)
    jax.config.update("jax_enable_x64", True)

    import numpy as np
    import jax.numpy as jnp
    from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
    from openembedding_tpu import checkpoint as ckpt
    from openembedding_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(2, 2)
    spec = EmbeddingSpec(name="h", input_dim=-1, output_dim=4,
                         hash_capacity=1024, key_dtype="int64",
                         initializer={"category": "constant", "value": 0.5},
                         optimizer={"category": "sgd", "learning_rate": 1.0})
    coll = EmbeddingCollection((spec,), mesh)
    states = coll.init(jax.random.PRNGKey(0))

    # keys far beyond int32 range: distinct keys that would collide if
    # anything truncated to 32 bits
    base = np.int64(1) << 40
    keys = np.asarray([base + 1, base + 2, (np.int64(1) << 45) + 1,
                       base + 1], np.int64)
    jk = jnp.asarray(keys)
    rows = coll.pull(states, {"h": jk}, batch_sharded=True)["h"]
    np.testing.assert_allclose(np.asarray(rows), 0.5, rtol=1e-6)
    g = jnp.ones((4, 4), jnp.float32)
    states = coll.apply_gradients(states, {"h": jk}, {"h": g})
    assert int(states["h"].insert_failures) == 0
    rows = np.asarray(coll.pull(states, {"h": jk},
                                batch_sharded=True)["h"])
    # duplicate key (rows 0 and 3) got grad sum 2; distinct keys 1 each
    np.testing.assert_allclose(rows[0], 0.5 - 2.0, rtol=1e-6)
    np.testing.assert_allclose(rows[1], 0.5 - 1.0, rtol=1e-6)
    np.testing.assert_allclose(rows[2], 0.5 - 1.0, rtol=1e-6)
    np.testing.assert_allclose(rows[3], rows[0], rtol=1e-6)
    # 3 distinct rows materialized (no 32-bit aliasing)
    assert int(jax.device_get(states["h"].num_used())) == 3

    # checkpoint round trip preserves 64-bit keys
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        ckpt.save_checkpoint(d, coll, states)
        loaded = ckpt.load_checkpoint(d, coll)
        got = np.asarray(coll.pull(loaded, {"h": jk},
                                   batch_sharded=True)["h"])
        np.testing.assert_allclose(got, rows, rtol=1e-6)

    print("x64 worker: ok", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
