"""Compressed exchange (parallel/precision.py): parity matrix, int8_ef
error-feedback trajectory, byte-halving contracts (positive + negative),
at-rest bf16 HBM shrink, checkpoint precision migrations, quantization
observability, and the EnvConfig exchange section.

Tolerance derivations (documented, not guessed):

* bf16 wire rows: each pulled row crosses the wire through exactly ONE
  round-to-nearest bf16 cast (the residue accumulator fills every entry
  once — alltoall.exchange_pull), so |err| <= 2^-9 * |x| (8 explicit
  mantissa bits, RN). Asserted at 2^-8 relative for a 2x margin plus a
  tiny atol for subnormals.
* bf16 push: the pre-reduced gradient row is cast once before the
  owner's f32 optimizer math; adagrad's update is 1-Lipschitz in g up
  to the lr/sqrt(accum) factor, so one step's weight deviation is
  bounded by lr * 2^-8 * max|g| per element (same 2x margin).
* int8_ef: per-row max-abs/127 scale => one step's quantization error
  <= scale/2 per element. Error feedback recirculates it, so over a
  REPEATED batch the drift vs f32 stays O(one quantization step)
  instead of growing linearly — asserted empirically with margin, and
  asserted no worse than the feedback-free (fresh-residual) ablation.
"""

import os

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax                                    # noqa: E402
import jax.numpy as jnp                       # noqa: E402

from openembedding_tpu import checkpoint as ckpt              # noqa: E402
from openembedding_tpu.embedding import (EmbeddingCollection,  # noqa: E402
                                         EmbeddingSpec)
from openembedding_tpu.parallel import precision               # noqa: E402
from openembedding_tpu.parallel import sharded_table as st     # noqa: E402
from openembedding_tpu.parallel.mesh import create_mesh        # noqa: E402
from openembedding_tpu.utils import observability              # noqa: E402

VOCAB = 1024
DIM = 16
BATCH = 256

# |bf16(x) - x| <= 2^-9 |x| round-to-nearest; asserted with 2x margin
BF16_RTOL = 2.0 ** -8
BF16_ATOL = 1e-7


def _world(mesh, plane, *, dtype="float32", dim=DIM, vocab=VOCAB, **kw):
    spec = EmbeddingSpec(
        name="t", input_dim=vocab, output_dim=dim, dtype=dtype, plane=plane,
        optimizer={"category": "adagrad", "learning_rate": 0.1}, **kw)
    coll = EmbeddingCollection((spec,), mesh)
    return coll, coll.init(jax.random.PRNGKey(0))


def _hash_world(mesh, plane, *, dim=DIM, **kw):
    spec = EmbeddingSpec(
        name="t", input_dim=-1, output_dim=dim, hash_capacity=1 << 14,
        plane=plane,
        optimizer={"category": "adagrad", "learning_rate": 0.1}, **kw)
    coll = EmbeddingCollection((spec,), mesh)
    return coll, coll.init(jax.random.PRNGKey(0))


def _batch(rng, n=BATCH, vocab=VOCAB, dim=DIM, dtype=np.int32):
    idx = rng.randint(0, vocab, size=n).astype(dtype)
    g = rng.randn(n, dim).astype(np.float32)
    return idx, g


# --- plane-token grammar / spec validation -----------------------------------

def test_plane_token_parsing():
    assert precision.parse_plane("a2a+bf16") == ("a2a", "bf16", "bf16")
    assert precision.parse_plane("a2a+int8") == ("a2a", "bf16", "int8_ef")
    assert precision.parse_plane("a2a+grouped+bf16") == \
        ("a2a+grouped", "bf16", "bf16")
    assert precision.parse_plane("a2a") == ("a2a", "f32", "f32")
    assert precision.plane_label("a2a", "bf16", "f32") == "a2a+bf16"
    assert precision.plane_label("a2a", "bf16", "int8_ef") == "a2a+int8"
    assert precision.plane_label("psum", "f32", "f32") == "psum"


def test_spec_normalizes_plane_suffix():
    spec = EmbeddingSpec(name="x", input_dim=8, output_dim=2,
                         plane="a2a+pipelined+bf16")
    assert spec.plane == "a2a+pipelined"
    assert spec.exchange_precision == "bf16"
    assert spec.push_precision == "bf16"


def test_illegal_precision_combos_raise():
    with pytest.raises(ValueError, match="psum"):
        EmbeddingSpec(name="x", input_dim=8, output_dim=2, plane="psum",
                      exchange_precision="bf16")
    with pytest.raises(ValueError, match="int8_ef"):
        EmbeddingSpec(name="x", input_dim=8, output_dim=2,
                      plane="a2a+grouped", push_precision="int8_ef")
    with pytest.raises(ValueError, match="int8_ef"):
        EmbeddingSpec(name="x", input_dim=8, output_dim=2,
                      plane="a2a+cache", push_precision="int8_ef")
    with pytest.raises(ValueError, match="explicitly"):
        # suffix vs explicit field conflict
        EmbeddingSpec(name="x", input_dim=8, output_dim=2,
                      plane="a2a+int8", push_precision="bf16")
    with pytest.raises(ValueError, match="unknown exchange_precision"):
        EmbeddingSpec(name="x", input_dim=8, output_dim=2,
                      exchange_precision="fp8")


# --- parity matrix -----------------------------------------------------------

def test_precision_f32_is_the_same_plane(devices8):
    """The f32 rung compiles the EXACT shipped program: same plane
    label (same lru-cached program object) and bitwise-equal results."""
    mesh = create_mesh(2, 4, devices8)
    c0, s0 = _world(mesh, "a2a")
    c1, s1 = _world(mesh, "a2a", exchange_precision="f32",
                    push_precision="f32")
    assert c1.sharding_spec("t").plane_label == "a2a"
    assert c0.sharding_spec("t") == c1.sharding_spec("t")
    rng = np.random.RandomState(0)
    idx, g = _batch(rng)
    r0 = c0.pull(s0, {"t": idx}, batch_sharded=False)["t"]
    r1 = c1.pull(s1, {"t": idx}, batch_sharded=False)["t"]
    np.testing.assert_array_equal(np.asarray(r0), np.asarray(r1))
    n0 = c0.apply_gradients(s0, {"t": idx}, {"t": g}, batch_sharded=False)
    n1 = c1.apply_gradients(s1, {"t": idx}, {"t": g}, batch_sharded=False)
    np.testing.assert_array_equal(np.asarray(n0["t"].weights),
                                  np.asarray(n1["t"].weights))


def test_bf16_pull_allclose_derived_tolerance(devices8):
    """bf16 wire rows: one RN cast per pulled row => |err| <= 2^-9|x|,
    asserted at 2^-8; and the wire is REALLY quantized (not f32)."""
    mesh = create_mesh(2, 4, devices8)
    c0, s0 = _world(mesh, "a2a")
    c1, s1 = _world(mesh, "a2a+bf16")
    rng = np.random.RandomState(1)
    idx, _ = _batch(rng)
    r0 = np.asarray(c0.pull(s0, {"t": idx}, batch_sharded=False)["t"])
    r1 = np.asarray(c1.pull(s1, {"t": idx}, batch_sharded=False)["t"])
    assert (np.abs(r1 - r0) <= np.abs(r0) * BF16_RTOL + BF16_ATOL).all()
    # exactly the bf16 rounding of the f32 rows — the wire carried bf16
    np.testing.assert_array_equal(
        r1, np.asarray(r0, dtype=jnp.bfloat16).astype(np.float32))
    assert not (r1 == r0).all()


def test_bf16_push_one_step_parity(devices8):
    mesh = create_mesh(2, 4, devices8)
    c0, s0 = _world(mesh, "a2a")
    c1, s1 = _world(mesh, "a2a+bf16")
    rng = np.random.RandomState(2)
    idx, g = _batch(rng)
    n0 = c0.apply_gradients(s0, {"t": idx}, {"t": g}, batch_sharded=False)
    n1 = c1.apply_gradients(s1, {"t": idx}, {"t": g}, batch_sharded=False)
    w0 = np.asarray(n0["t"].weights)
    w1 = np.asarray(n1["t"].weights)
    # adagrad: |dw| <= lr * |dg| / sqrt(accum0) with accum0 = 0.1 =>
    # bound = 0.1 * 2^-8 * max|g-sum| / sqrt(0.1) (2x-margined rtol)
    gmax = np.abs(g).max() * 4        # duplicate pre-reduce headroom
    bound = 0.1 * BF16_RTOL * gmax / np.sqrt(0.1)
    assert np.abs(w1 - w0).max() <= bound, (np.abs(w1 - w0).max(), bound)


@pytest.mark.slow
def test_bf16_parity_hash_wide(devices8):
    mesh = create_mesh(2, 4, devices8)
    c0, s0 = _hash_world(mesh, "a2a")
    c1, s1 = _hash_world(mesh, "a2a+bf16")
    rng = np.random.RandomState(3)
    idx = rng.randint(0, 1 << 40, size=BATCH).astype(np.int64)
    g = rng.randn(BATCH, DIM).astype(np.float32)
    r0 = np.asarray(c0.pull(s0, {"t": idx}, batch_sharded=False)["t"])
    r1 = np.asarray(c1.pull(s1, {"t": idx}, batch_sharded=False)["t"])
    assert (np.abs(r1 - r0) <= np.abs(r0) * BF16_RTOL + BF16_ATOL).all()
    n0 = c0.apply_gradients(s0, {"t": idx}, {"t": g}, batch_sharded=False)
    n1 = c1.apply_gradients(s1, {"t": idx}, {"t": g}, batch_sharded=False)
    w0 = np.asarray(n0["t"].weights, np.float32)
    w1 = np.asarray(n1["t"].weights, np.float32)
    bound = 0.1 * BF16_RTOL * np.abs(g).max() * 4 / np.sqrt(0.1)
    assert np.abs(w1 - w0).max() <= bound


@pytest.mark.slow
def test_bf16_parity_grouped(devices8):
    """The wire composes with the grouped plane: one bf16 round per
    GROUP, per-table rows still within the one-cast tolerance."""
    mesh = create_mesh(2, 4, devices8)

    def world(plane):
        specs = tuple(
            EmbeddingSpec(name=f"t{i}", input_dim=4096 + 64 * i,
                          output_dim=8, plane=plane,
                          optimizer={"category": "adagrad",
                                     "learning_rate": 0.1})
            for i in range(3))
        coll = EmbeddingCollection(specs, mesh)
        return coll, coll.init(jax.random.PRNGKey(0))

    rng = np.random.RandomState(4)
    idx = {f"t{i}": rng.randint(0, 4000, size=BATCH).astype(np.int32)
           for i in range(3)}
    g = {f"t{i}": rng.randn(BATCH, 8).astype(np.float32) for i in range(3)}
    c0, s0 = world("a2a+grouped")
    c1, s1 = world("a2a+grouped+bf16")
    r0 = c0.pull(s0, idx, batch_sharded=False)
    r1 = c1.pull(s1, idx, batch_sharded=False)
    for k in r0:
        a, b = np.asarray(r0[k]), np.asarray(r1[k])
        assert (np.abs(b - a) <= np.abs(a) * BF16_RTOL + BF16_ATOL).all()
    n1 = c1.apply_gradients(s1, idx, g, batch_sharded=False)
    assert set(n1) == set(s1)


# --- int8 error-feedback -----------------------------------------------------

def _drift_after(coll, states, idx, g, steps, *, reset_ef=False):
    for _ in range(steps):
        if reset_ef and isinstance(states["t"], precision.EFState):
            # feedback-free ablation: drop the residual every step
            states = dict(states)
            states["t"] = precision.unwrap(states["t"])
        states = coll.apply_gradients(states, {"t": idx}, {"t": g},
                                      batch_sharded=False)
    return states


def test_int8_ef_optimizer_trajectory_bound(devices8):
    """10-step fixed-batch trajectory: int8_ef drift vs f32 stays
    O(one quantization step) — and is never worse than the
    feedback-free ablation (the residual genuinely recirculates)."""
    mesh = create_mesh(2, 4, devices8)
    c0, s0 = _world(mesh, "a2a")
    c1, s1 = _world(mesh, "a2a+int8")
    rng = np.random.RandomState(5)
    idx, g = _batch(rng)
    steps = 10
    s0 = _drift_after(c0, s0, idx, g, steps)
    ef = _drift_after(c1, s1, idx, g, steps)
    c2, s2 = _world(mesh, "a2a+int8")
    noef = _drift_after(c2, s2, idx, g, steps, reset_ef=True)
    w0 = np.asarray(s0["t"].weights)
    wef = np.asarray(precision.unwrap(ef["t"]).weights)
    wno = np.asarray(precision.unwrap(noef["t"]).weights)
    d_ef = np.abs(wef - w0).max()
    d_no = np.abs(wno - w0).max()
    # one quantization step of the dequantized gradient reaching the
    # optimizer: scale/2 = max|g-row-sum|/254; through adagrad's
    # lr/sqrt(accum) that is at most lr * (4*gmax/254) / sqrt(0.1).
    # EF keeps the CUMULATIVE drift within a few such steps (errors
    # cancel instead of accumulating); 8x covers optimizer nonlinearity
    q = 0.1 * (4 * np.abs(g).max() / 254) / np.sqrt(0.1)
    assert d_ef <= 8 * q, (d_ef, q)
    # feedback must not hurt (equality possible on lucky seeds)
    assert d_ef <= d_no + 0.25 * q, (d_ef, d_no)
    # and the trajectory is meaningfully close to f32 overall
    assert d_ef <= 0.05 * max(1e-6, np.abs(w0).max())


def test_int8_ef_trajectory_hash_wide(devices8):
    """The wide-key (64-bit pair) residual matcher — mix/sort/verify in
    alltoall._match_prev_keys — driven over a repeated batch: drift vs
    f32 bounded AND no worse than the feedback-free ablation, so a
    matcher bug (wrong candidate, mix overflow) cannot ship silently as
    'int8 without feedback'."""
    mesh = create_mesh(2, 4, devices8)
    c0, s0 = _hash_world(mesh, "a2a", dim=8)
    c1, s1 = _hash_world(mesh, "a2a+int8", dim=8)
    c2, s2 = _hash_world(mesh, "a2a+int8", dim=8)
    rng = np.random.RandomState(13)
    idx = rng.randint(0, 1 << 40, size=128).astype(np.int64)
    g = rng.randn(128, 8).astype(np.float32)
    steps = 6

    def run(coll, states, reset_ef=False):
        for _ in range(steps):
            if reset_ef and isinstance(states["t"], precision.EFState):
                states = dict(states)
                states["t"] = precision.unwrap(states["t"])
            states = coll.apply_gradients(states, {"t": idx}, {"t": g},
                                          batch_sharded=False)
        return states

    s0 = run(c0, s0)
    ef = run(c1, s1)
    noef = run(c2, s2, reset_ef=True)
    assert isinstance(ef["t"], precision.EFState)
    assert ef["t"].keys.ndim == 2 and ef["t"].keys.shape[1] == 2
    assert float(jnp.abs(ef["t"].resid).max()) > 0
    w0 = np.asarray(s0["t"].weights, np.float32)
    wef = np.asarray(precision.unwrap(ef["t"]).weights, np.float32)
    wno = np.asarray(precision.unwrap(noef["t"]).weights, np.float32)
    d_ef = np.abs(wef - w0).max()
    d_no = np.abs(wno - w0).max()
    q = 0.1 * (4 * np.abs(g).max() / 254) / np.sqrt(0.1)
    assert d_ef <= 8 * q, (d_ef, q)
    assert d_ef <= d_no + 0.25 * q, (d_ef, d_no)


def test_int8_ef_state_threading(devices8):
    """EFState wraps the table after the first push, keeps a stable
    buffer across same-shape steps, and re-sizes on a batch change."""
    mesh = create_mesh(2, 4, devices8)
    coll, states = _world(mesh, "a2a+int8")
    assert isinstance(states["t"], precision.EFState)   # attached empty
    assert states["t"].keys.shape[0] == 0
    rng = np.random.RandomState(6)
    idx, g = _batch(rng)
    s1 = coll.apply_gradients(states, {"t": idx}, {"t": g},
                              batch_sharded=False)
    ef = s1["t"]
    assert isinstance(ef, precision.EFState)
    assert ef.keys.shape[0] > 0 and ef.resid.shape == \
        (ef.keys.shape[0], DIM)
    s2 = coll.apply_gradients(s1, {"t": idx}, {"t": g},
                              batch_sharded=False)
    assert s2["t"].keys.shape == ef.keys.shape
    # nonzero residual was actually stored (quantization is lossy)
    assert float(jnp.abs(s2["t"].resid).max()) > 0
    # batch-size change re-sizes the buffer instead of crashing
    idx2, g2 = _batch(rng, n=128)
    s3 = coll.apply_gradients(s2, {"t": idx2}, {"t": g2},
                              batch_sharded=False)
    assert s3["t"].keys.shape[0] != ef.keys.shape[0]
    # pulls read through the wrapper
    rows = coll.pull(s3, {"t": idx}, batch_sharded=False)["t"]
    assert rows.shape == (BATCH, DIM)


def test_quant_observability_counters(devices8):
    mesh = create_mesh(2, 4, devices8)
    observability.GLOBAL.reset()
    observability.set_evaluate_performance(True)
    try:
        coll, states = _world(mesh, "a2a+int8")
        rng = np.random.RandomState(7)
        idx, g = _batch(rng)
        for _ in range(2):
            states = coll.apply_gradients(states, {"t": idx}, {"t": g},
                                          batch_sharded=False)
        jax.block_until_ready(jax.tree.leaves(states))
        import time
        time.sleep(0.2)     # debug.callback drains asynchronously
        snap = observability.GLOBAL.snapshot()
        assert snap.get("quant_residual_norm", {}).get("count", 0) > 0
        assert snap.get("quant_error_max", {}).get("count", 0) > 0
        text = observability.prometheus_text()
        assert "oe_quant_residual_norm_total" in text
        assert "oe_quant_error_max_total" in text
    finally:
        observability.set_evaluate_performance(False)
        observability.GLOBAL.reset()


# --- byte-halving contracts --------------------------------------------------

def test_compressed_byte_contracts_array(devices8):
    """Compiled-HLO-measured: bf16/int8 exchange bytes <= 0.55x the f32
    plane's, pull and push separately (the acceptance-criteria audit,
    same code path tools.graftcheck runs in CI)."""
    from openembedding_tpu.analysis import contracts, programs
    mesh = create_mesh(2, 4, devices8)
    dim, batch = 64, 256      # the ratio binds at dim >= 32 (registry)
    base = {}
    for prog, lower in (("pull", programs.lower_pull),
                        ("push", programs.lower_push)):
        base[prog], _ = lower(mesh, "a2a", batch=batch, dim=dim)
    for plane in ("a2a+bf16", "a2a+int8"):
        for prog, lower in (("pull", programs.lower_pull),
                            ("push", programs.lower_push)):
            txt, params = lower(mesh, plane, batch=batch, dim=dim)
            res = contracts.check_compressed_program(
                txt, base[prog], plane, prog, **params)
            assert res["ratio"] <= 0.55
    # int8 push is far below even the halving bound
    txt, params = programs.lower_push(mesh, "a2a+int8", batch=batch,
                                      dim=dim)
    res = contracts.check_compressed_program(txt, base["push"],
                                             "a2a+int8", "push", **params)
    assert res["ratio"] <= 0.35


def test_f32_plane_under_compressed_bound_is_caught(devices8):
    """The negative the acceptance criteria demand: an f32 program
    registered under a compressed contract must FAIL — both via the
    wire-width inventory bound and via the byte-halving ratio."""
    from openembedding_tpu.analysis import contracts, programs
    mesh = create_mesh(2, 4, devices8)
    txt, params = programs.lower_pull(mesh, "a2a", batch=256, dim=64)
    params = dict(params)
    params["wire_itemsize"] = 2
    with pytest.raises(contracts.ContractViolation):
        contracts.check_program(txt, "a2a+bf16", "pull", **params)
    with pytest.raises(contracts.ContractViolation, match="NOT compress"):
        contracts.check_byte_halving(txt, txt, label="f32-as-bf16")


@pytest.mark.slow
def test_compressed_byte_contracts_hash(devices8):
    from openembedding_tpu.analysis import contracts, programs
    mesh = create_mesh(2, 4, devices8)
    dim, batch = 64, 256
    for prog, lower in (("pull", programs.lower_pull),
                        ("push", programs.lower_push)):
        base, _ = lower(mesh, "a2a", batch=batch, dim=dim, use_hash=True)
        for plane in ("a2a+bf16", "a2a+int8"):
            txt, params = lower(mesh, plane, batch=batch, dim=dim,
                                use_hash=True)
            res = contracts.check_compressed_program(
                txt, base, plane, prog, **params)
            assert res["ratio"] <= 0.55


# --- at-rest bf16 ------------------------------------------------------------

def test_at_rest_bf16_halves_weight_hbm(devices8):
    """bf16 tables + f32 slots: the memwatch-ledger shrink — weight
    bytes halve, slot bytes stay f32, and the exchange still runs."""
    mesh = create_mesh(2, 4, devices8)
    cf, sf = _world(mesh, "a2a", dtype="float32")
    cb, sb = _world(mesh, "a2a", dtype="bfloat16")
    wf, wb = sf["t"].weights, sb["t"].weights
    assert wb.dtype == jnp.bfloat16 and wb.nbytes * 2 == wf.nbytes
    for k in sf["t"].slots:
        assert sb["t"].slots[k].dtype == jnp.float32
        assert sb["t"].slots[k].nbytes == sf["t"].slots[k].nbytes
    rng = np.random.RandomState(8)
    idx, g = _batch(rng)
    rows = cb.pull(sb, {"t": idx}, batch_sharded=False)["t"]
    assert rows.dtype == jnp.bfloat16
    nb = cb.apply_gradients(sb, {"t": idx},
                            {"t": g.astype(jnp.bfloat16)},
                            batch_sharded=False)
    assert nb["t"].weights.dtype == jnp.bfloat16
    assert all(v.dtype == jnp.float32 for v in nb["t"].slots.values())


def test_at_rest_bf16_memory_ledger_shrink(devices8):
    """The compiled-program argument bytes (memwatch ledger axis)
    shrink by the weights' half when the table goes bf16."""
    from openembedding_tpu.analysis import programs
    from openembedding_tpu.utils import jaxcompat
    mesh = create_mesh(2, 4, devices8)

    def arg_bytes(dtype):
        import jax as _jax
        coll = EmbeddingCollection(
            (EmbeddingSpec(name="t", input_dim=1 << 14, output_dim=16,
                           dtype=dtype,
                           optimizer={"category": "default"}),), mesh)
        states = coll.init(_jax.random.PRNGKey(0))
        return sum(x.nbytes for x in _jax.tree.leaves(states))

    f32 = arg_bytes("float32")
    bf16 = arg_bytes("bfloat16")
    # the stateless optimizer has no slots: state = weights -> exact half
    assert bf16 * 2 == f32


# --- checkpoint format (tpu-2) -----------------------------------------------

def _two_var_coll(mesh, dtype):
    specs = (EmbeddingSpec(name="arr", input_dim=512, output_dim=8,
                           dtype=dtype),
             EmbeddingSpec(name="hsh", input_dim=-1, output_dim=8,
                           dtype=dtype, hash_capacity=512))
    return EmbeddingCollection(
        specs, mesh,
        default_optimizer={"category": "adagrad", "learning_rate": 0.1})


def _trained(coll):
    rng = np.random.RandomState(9)
    idx = {"arr": rng.randint(0, 512, size=64).astype(np.int32),
           "hsh": rng.randint(0, 10000, size=64).astype(np.int64)}
    g = {k: rng.randn(64, 8).astype(np.float32) for k in idx}
    states = coll.init(jax.random.PRNGKey(0))
    states = coll.apply_gradients(states, idx, g, batch_sharded=False)
    return states, idx


def test_bf16_checkpoint_local_roundtrip(devices8, tmp_path):
    """The LOCAL memmap dump of a bf16 table (numpy stores '<V2' void
    rows) round-trips bit-exactly — the storage_dtypes record added in
    meta format tpu-2."""
    mesh = create_mesh(2, 4, devices8)
    coll = _two_var_coll(mesh, "bfloat16")
    states, idx = _trained(coll)
    before = coll.pull(states, idx, batch_sharded=False)
    ckpt.save_checkpoint(str(tmp_path / "m"), coll, states)
    loaded = ckpt.load_checkpoint(str(tmp_path / "m"), coll)
    after = coll.pull(loaded, idx, batch_sharded=False)
    for k in before:
        np.testing.assert_array_equal(np.asarray(before[k], np.float32),
                                      np.asarray(after[k], np.float32))
    assert loaded["arr"].weights.dtype == jnp.bfloat16
    assert all(v.dtype == jnp.float32
               for v in loaded["arr"].slots.values())


def test_bf16_dump_routes_through_compress(devices8, tmp_path):
    """compress='zlib' sends the bf16 rows through utils/compress.py's
    framed .npyz streams; the loader views the V2 frames back under the
    recorded true dtype."""
    mesh = create_mesh(2, 4, devices8)
    coll = _two_var_coll(mesh, "bfloat16")
    states, idx = _trained(coll)
    before = coll.pull(states, idx, batch_sharded=False)
    ckpt.save_checkpoint(str(tmp_path / "m"), coll, states,
                         compress="zlib")
    vdir = tmp_path / "m" / "var_0_arr.d"
    names = os.listdir(vdir)
    assert any(f.endswith(".npyz") for f in names), names
    loaded = ckpt.load_checkpoint(str(tmp_path / "m"), coll)
    after = coll.pull(loaded, idx, batch_sharded=False)
    for k in before:
        np.testing.assert_array_equal(np.asarray(before[k], np.float32),
                                      np.asarray(after[k], np.float32))


def test_precision_migration_and_tpu1_compat(devices8, tmp_path):
    """(1) an OLD 'tpu-1' f32 checkpoint (no storage_dtypes) loads
    transparently; (2) f32 dump -> bf16 table downcasts; (3) bf16 dump
    -> f32 table upcasts exactly."""
    import json
    mesh = create_mesh(2, 4, devices8)
    cf = _two_var_coll(mesh, "float32")
    sf, idx = _trained(cf)
    before = cf.pull(sf, idx, batch_sharded=False)
    p = tmp_path / "old"
    ckpt.save_checkpoint(str(p), cf, sf)
    meta = json.loads((p / "model_meta").read_text())
    assert meta["version"] == "tpu-2"
    # rewrite as a legacy tpu-1 checkpoint: old version, no dtype record
    meta["version"] = "tpu-1"
    meta["extra"].pop("storage_dtypes")
    (p / "model_meta").write_text(json.dumps(meta))
    loaded = ckpt.load_checkpoint(str(p), cf)
    after = cf.pull(loaded, idx, batch_sharded=False)
    for k in before:
        np.testing.assert_array_equal(np.asarray(before[k]),
                                      np.asarray(after[k]))
    # f32 (legacy) dump -> bf16 collection: transparent downcast
    cb = _two_var_coll(mesh, "bfloat16")
    down = ckpt.load_checkpoint(str(p), cb)
    assert down["arr"].weights.dtype == jnp.bfloat16
    # bf16 dump -> f32 collection: transparent upcast, exact values
    sb, idxb = _trained(cb)
    beforeb = cb.pull(sb, idxb, batch_sharded=False)
    p2 = tmp_path / "bf16"
    ckpt.save_checkpoint(str(p2), cb, sb)
    up = ckpt.load_checkpoint(str(p2), cf)
    assert up["arr"].weights.dtype == jnp.float32
    afterb = cf.pull(up, idxb, batch_sharded=False)
    for k in beforeb:
        np.testing.assert_array_equal(np.asarray(beforeb[k], np.float32),
                                      np.asarray(afterb[k]))


def test_int8_ef_state_never_checkpointed(devices8, tmp_path):
    """EFState is derived: the dump holds only the table; a restore
    re-attaches an empty residual (one step of feedback forfeited)."""
    mesh = create_mesh(2, 4, devices8)
    spec = EmbeddingSpec(name="t", input_dim=512, output_dim=8,
                         plane="a2a+int8",
                         optimizer={"category": "adagrad",
                                    "learning_rate": 0.1})
    coll = EmbeddingCollection((spec,), mesh)
    states = coll.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(10)
    idx = rng.randint(0, 512, size=64).astype(np.int32)
    g = rng.randn(64, 8).astype(np.float32)
    states = coll.apply_gradients(states, {"t": idx}, {"t": g},
                                  batch_sharded=False)
    assert float(jnp.abs(states["t"].resid).max()) > 0
    before = coll.pull(states, {"t": idx}, batch_sharded=False)["t"]
    ckpt.save_checkpoint(str(tmp_path / "m"), coll, states)
    loaded = ckpt.load_checkpoint(str(tmp_path / "m"), coll)
    assert isinstance(loaded["t"], precision.EFState)
    assert loaded["t"].keys.shape[0] == 0          # fresh residual
    after = coll.pull(loaded, {"t": idx}, batch_sharded=False)["t"]
    np.testing.assert_array_equal(np.asarray(before), np.asarray(after))


def test_int8_ef_single_shard_structure_stable(devices8):
    """On a single-device mesh the push has no wire: int8_ef degrades
    to the exact masked-local program, and the state pytree STRUCTURE
    must not flip (EFState at init -> TableState after a push would
    force a retrace of a donated step jit every second step)."""
    mesh = create_mesh(1, 1, devices8[:1])
    spec = EmbeddingSpec(name="t", input_dim=64, output_dim=4,
                         plane="a2a+int8",
                         optimizer={"category": "adagrad",
                                    "learning_rate": 0.1})
    coll = EmbeddingCollection((spec,), mesh)
    states = coll.init(jax.random.PRNGKey(0))
    assert not isinstance(states["t"], precision.EFState)
    rng = np.random.RandomState(11)
    idx = rng.randint(0, 64, size=16).astype(np.int32)
    g = rng.randn(16, 4).astype(np.float32)
    new = coll.apply_gradients(states, {"t": idx}, {"t": g},
                               batch_sharded=False)
    assert type(new["t"]) is type(states["t"])


def test_legacy_tpu1_bf16_slot_dump_loads(devices8, tmp_path):
    """A PRE-ladder tpu-1 dump of a bf16 table stored its SLOTS at the
    table dtype (bf16, opaque '<V2') — today's slot target is f32, so
    the decoder must fall back to the dump's table dtype, not fail on
    the itemsize mismatch."""
    import glob
    import json
    import ml_dtypes
    mesh = create_mesh(2, 4, devices8)
    coll = EmbeddingCollection(
        (EmbeddingSpec(name="arr", input_dim=256, output_dim=8,
                       dtype="bfloat16"),), mesh,
        default_optimizer={"category": "adagrad", "learning_rate": 0.1})
    states = coll.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(12)
    idx = rng.randint(0, 256, size=32).astype(np.int32)
    g = rng.randn(32, 8).astype(np.float32)
    states = coll.apply_gradients(states, {"arr": idx}, {"arr": g},
                                  batch_sharded=False)
    p = tmp_path / "m"
    ckpt.save_checkpoint(str(p), coll, states)
    for f in glob.glob(str(p / "var_0_arr.d" / "slot_*.npy")):
        np.save(f, np.load(f).astype(ml_dtypes.bfloat16))
    meta = json.loads((p / "model_meta").read_text())
    meta["version"] = "tpu-1"
    meta["extra"].pop("storage_dtypes")
    (p / "model_meta").write_text(json.dumps(meta))
    loaded = ckpt.load_checkpoint(str(p), coll)
    acc = loaded["arr"].slots["accum"]
    assert acc.dtype == jnp.float32
    # the values are the stored bf16 accum, upcast — not garbage bits
    want = np.asarray(jax.device_get(states["arr"].slots["accum"])
                      ).astype(ml_dtypes.bfloat16).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(jax.device_get(acc)), want)


# --- EnvConfig ---------------------------------------------------------------

def test_envconfig_exchange_section():
    from openembedding_tpu.utils.envconfig import EnvConfig
    cfg = EnvConfig.load(config={"exchange": {"precision": "bf16",
                                              "push_precision": "int8_ef"}})
    assert cfg.exchange.spec_kwargs() == {
        "exchange_precision": "bf16", "push_precision": "int8_ef"}
    spec = EmbeddingSpec(name="x", input_dim=8, output_dim=2,
                         **cfg.exchange.spec_kwargs())
    assert spec.push_precision == "int8_ef"
    with pytest.raises(ValueError, match="bf16"):
        EnvConfig.load(config={"exchange": {"precision": "fp8"}})
    env = {"OE_EXCHANGE_PRECISION": "bf16"}
    assert EnvConfig.load(env=env).exchange.precision == "bf16"


# --- model-zoo AUC parity (slow) ---------------------------------------------

@pytest.mark.slow
def test_auc_parity_compressed_zoo(devices8):
    """Compressed vs f32 on the learnable task: the fully-compressed
    plane (bf16 pull + int8_ef push) trains to the same AUC within
    0.002 absolute — the end-to-end quality gate of the ladder."""
    import optax
    from openembedding_tpu import Trainer
    from openembedding_tpu.models import deepctr
    from openembedding_tpu.utils.observability import StreamingAUC

    mesh = create_mesh(2, 4, devices8)

    def run(plane):
        specs = deepctr.make_feature_specs(
            ("f",), 256, 8, plane=plane,
            optimizer={"category": "adagrad", "learning_rate": 0.5})
        coll = EmbeddingCollection(specs, mesh)
        trainer = Trainer(deepctr.build_model("deepfm", ("f",)), coll,
                          optax.adam(1e-2))
        rng = np.random.RandomState(0)

        def batch():
            ids = rng.randint(0, 256, 256).astype(np.int32)
            label = ((ids.astype(np.int64) * 2654435761) % 3
                     == 0).astype(np.float32)
            return {"label": label,
                    "dense": rng.randn(256, 4).astype(np.float32) * 0,
                    "sparse": {"f": ids, "f:linear": ids}}

        state = trainer.init(jax.random.PRNGKey(0),
                             trainer.shard_batch(batch()))
        state, _ = trainer.fit(state, (batch() for _ in range(60)))
        auc = StreamingAUC()
        rng2 = np.random.RandomState(42)
        for _ in range(4):
            ids = rng2.randint(0, 256, 256).astype(np.int32)
            label = ((ids.astype(np.int64) * 2654435761) % 3
                     == 0).astype(np.float32)
            b = {"label": label,
                 "dense": np.zeros((256, 4), np.float32),
                 "sparse": {"f": ids, "f:linear": ids}}
            auc.update(label, np.asarray(trainer.eval_step(state, b)))
        return auc.result()

    auc_f32 = run("a2a")
    auc_c = run("a2a+int8")
    assert auc_f32 > 0.9, f"f32 zoo run did not learn: {auc_f32:.4f}"
    assert abs(auc_c - auc_f32) <= 0.002, (auc_c, auc_f32)
