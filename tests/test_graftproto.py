"""graftproto static plane: checker semantics, the four shipped models
exhaustively clean, every seeded mutation model counterexamples with the
expected invariant, the CLI exit codes, and the model<->code sync-point
bridge.

The executable half of the bridge — counterexample schedules replayed
against the real implementation — lives in
``tests/test_graftproto_replay.py``.
"""

import importlib.util
import json
import os

import pytest

from openembedding_tpu.analysis import protomodel as pm

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
FIXTURE = os.path.join(HERE, "fixtures", "graftproto_violations.py")


def _load_fixture():
    spec = importlib.util.spec_from_file_location("graftproto_fixture",
                                                  FIXTURE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --- checker semantics on tiny synthetic models ------------------------------

def _counter_model(*, bound=3, bad_at=None, stuck_at=None):
    """A one-counter model: inc to ``bound``; optionally a long detour
    path exists so BFS minimality is observable."""
    def inc_guard(s):
        return s["n"] < bound

    def inc_apply(s):
        s["n"] += 1

    def detour_guard(s):
        return s["d"] < 10

    def detour_apply(s):
        s["d"] += 1

    inv = [("n_below_bad", lambda s: bad_at is None or s["n"] < bad_at)]
    done = (lambda s: stuck_at is None) if stuck_at is None else \
        (lambda s: False)
    actions = [pm.Action("inc", "p", inc_guard, inc_apply,
                         syncs=("point.inc",)),
               pm.Action("detour", "q", detour_guard, detour_apply)]
    if stuck_at is not None:
        # replace: inc stops early and nothing else is enabled
        actions = [pm.Action("inc", "p",
                             lambda s: s["n"] < stuck_at, inc_apply)]
    return pm.make_model("counter", {"n": 0, "d": 0}, actions, inv, done)


def test_bfs_counterexample_is_minimal_length():
    # bad at n==2: reachable in exactly 2 inc steps; detour steps pad
    # every other path — BFS must return the 2-step trace
    res = pm.check(_counter_model(bad_at=2))
    assert not res.ok and res.counterexample.kind == "invariant"
    assert res.counterexample.invariant == "n_below_bad"
    labels = [l for l, _s in res.counterexample.trace]
    assert labels == ["<init>", "inc", "inc"]


def test_invariant_checked_at_init():
    res = pm.check(_counter_model(bad_at=0))
    assert not res.ok and len(res.counterexample.trace) == 1


def test_deadlock_detected_and_accepting_states_are_not():
    stuck = pm.check(_counter_model(stuck_at=2))
    assert not stuck.ok and stuck.counterexample.kind == "deadlock"
    clean = pm.check(_counter_model())
    assert clean.ok and clean.complete


def test_state_dedup_and_exhaustive_count():
    # product space is exactly 4 x 11 states
    res = pm.check(_counter_model())
    assert res.ok and res.explored == 44


def test_nondet_branches_and_state_budget():
    def fork(s):
        return [dict(s, n=s["n"] + 1), dict(s, n=s["n"] + 2)]

    m = pm.make_model(
        "fork", {"n": 0},
        [pm.Action("fork", "p", lambda s: s["n"] < 6, fork)],
        [("no_neg", lambda s: s["n"] >= 0)], lambda s: True)
    res = pm.check(m)
    assert res.ok and res.explored == 8    # n in 0..7
    cut = pm.check(m, max_states=3)
    assert cut.ok and not cut.complete


def test_format_and_schedule_export():
    res = pm.check(_counter_model(bad_at=1))
    m = _counter_model(bad_at=1)
    text = pm.format_result(res, m)
    assert "INVARIANT VIOLATED: n_below_bad" in text
    assert "point.inc" in text             # sync names printed in traces
    sched = pm.trace_schedule(m, res.counterexample.trace)
    assert sched == ["point.inc"]


def test_freeze_rejects_unhashable_state_values():
    with pytest.raises(TypeError):
        pm.make_model("bad", {"x": [1, 2]}, [], [], lambda s: True)


# --- shipped models ----------------------------------------------------------

SHIPPED_MIN_STATES = {"delta_chain": 100_000, "hot_swap": 40,
                      "dirty_tracker": 100, "ha_registry": 200,
                      "serving_batcher": 2_000}


@pytest.mark.parametrize("model", pm.shipped_models(),
                         ids=lambda m: m.name)
def test_shipped_model_checks_clean_and_exhaustively(model):
    res = pm.check(model)
    assert res.ok and res.complete, pm.format_result(res, model)
    # the exploration must stay EXHAUSTIVE: a refactor that silently
    # guards away most of the space would "pass" while checking nothing
    assert res.explored >= SHIPPED_MIN_STATES[model.name], res.explored


@pytest.mark.parametrize("model", pm.shipped_models(),
                         ids=lambda m: m.name)
def test_model_sync_points_exist_in_package_source(model):
    """The fidelity tripwire: every sync point a model action claims to
    correspond to must still be emitted by the package source."""
    assert pm.missing_sync_points(model) == []
    assert pm.model_sync_points(model)     # and the bridge is non-empty


def test_sample_traces_are_replayable_schedules():
    for model in (pm.hot_swap(), pm.dirty_tracker()):
        traces = pm.sample_traces(model)
        assert traces
        for t in traces:
            assert t[0][0] == "<init>"
            # a sampled trace maps onto at least one real sync point
            assert isinstance(pm.trace_schedule(model, t), list)


# --- seeded mutations --------------------------------------------------------

def test_every_seeded_mutation_fires_its_invariant():
    fixture = _load_fixture()
    names = [m[0] for m in fixture.MUTATIONS]
    assert len(names) == len(set(names))
    # every shipped protocol has at least one seeded mutation
    assert {m[1] for m in fixture.MUTATIONS} == \
        {m.name for m in pm.shipped_models()}
    for name, builder, kwargs, expect_inv, _why in fixture.MUTATIONS:
        model = getattr(pm, builder)(**kwargs)
        res = pm.check(model)
        cex = res.counterexample
        assert cex is not None and cex.kind == "invariant", \
            f"mutation {name} produced no counterexample"
        assert cex.invariant == expect_inv, \
            f"mutation {name}: fired {cex.invariant!r}"
        # minimal-length trace exists and is replayable
        assert len(cex.trace) >= 2
        assert isinstance(pm.trace_schedule(model, cex.trace), list)


def test_mutation_builder_helper():
    fixture = _load_fixture()
    m = fixture.build(pm, "drop_seq_gate")
    assert m.name == "hot_swap"
    with pytest.raises(KeyError):
        fixture.build(pm, "nope")


# --- CLI ---------------------------------------------------------------------

def test_cli_exit_codes(tmp_path):
    from tools.graftproto import main
    assert main([]) == 0
    assert main(["--model", "delta_chain"]) == 0
    assert main(["--model", "nope"]) == 2
    assert main(["--mutations"]) == 1      # seeded bugs MUST fire
    # a budget too small to finish a shipped model fails the gate
    assert main(["--model", "delta_chain", "--max-states", "100"]) == 1


def test_cli_emit_schedules(tmp_path, capsys):
    from tools.graftproto import main
    out = tmp_path / "sched.json"
    assert main(["--emit-schedules", str(out)]) == 0
    capsys.readouterr()
    data = json.loads(out.read_text())
    assert set(data["models"]) == {m.name for m in pm.shipped_models()}
    for entry in data["models"].values():
        assert entry["explored"] > 0 and entry["schedules"]
    fixture = _load_fixture()
    assert set(data["mutations"]) == {m[0] for m in fixture.MUTATIONS}
    for name, _b, _k, expect_inv, _why in fixture.MUTATIONS:
        mut = data["mutations"][name]
        assert mut["invariant"] == expect_inv
        assert mut["actions"]              # the replayable trace
