"""graftproto static plane: checker semantics (BFS + the v2 reductions
and bounded liveness), the eight shipped models exhaustively clean with
reduction-soundness cross-checks, every seeded mutation model
counterexampling with the expected property, the POR-unsoundness
negative test, the CLI exit codes, and the model<->code sync-point
bridge.

The executable half of the bridge — counterexample schedules replayed
against the real implementation — lives in
``tests/test_graftproto_replay.py``.
"""

import importlib.util
import json
import os

import pytest

from openembedding_tpu.analysis import protomodel as pm

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
FIXTURE = os.path.join(HERE, "fixtures", "graftproto_violations.py")


def _load_fixture():
    spec = importlib.util.spec_from_file_location("graftproto_fixture",
                                                  FIXTURE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --- checker semantics on tiny synthetic models ------------------------------

def _counter_model(*, bound=3, bad_at=None, stuck_at=None):
    """A one-counter model: inc to ``bound``; optionally a long detour
    path exists so BFS minimality is observable."""
    def inc_guard(s):
        return s["n"] < bound

    def inc_apply(s):
        s["n"] += 1

    def detour_guard(s):
        return s["d"] < 10

    def detour_apply(s):
        s["d"] += 1

    inv = [("n_below_bad", lambda s: bad_at is None or s["n"] < bad_at)]
    done = (lambda s: stuck_at is None) if stuck_at is None else \
        (lambda s: False)
    actions = [pm.Action("inc", "p", inc_guard, inc_apply,
                         syncs=("point.inc",)),
               pm.Action("detour", "q", detour_guard, detour_apply)]
    if stuck_at is not None:
        # replace: inc stops early and nothing else is enabled
        actions = [pm.Action("inc", "p",
                             lambda s: s["n"] < stuck_at, inc_apply)]
    return pm.make_model("counter", {"n": 0, "d": 0}, actions, inv, done)


def test_bfs_counterexample_is_minimal_length():
    # bad at n==2: reachable in exactly 2 inc steps; detour steps pad
    # every other path — BFS must return the 2-step trace
    res = pm.check(_counter_model(bad_at=2))
    assert not res.ok and res.counterexample.kind == "invariant"
    assert res.counterexample.invariant == "n_below_bad"
    labels = [l for l, _s in res.counterexample.trace]
    assert labels == ["<init>", "inc", "inc"]


def test_invariant_checked_at_init():
    res = pm.check(_counter_model(bad_at=0))
    assert not res.ok and len(res.counterexample.trace) == 1


def test_deadlock_detected_and_accepting_states_are_not():
    stuck = pm.check(_counter_model(stuck_at=2))
    assert not stuck.ok and stuck.counterexample.kind == "deadlock"
    clean = pm.check(_counter_model())
    assert clean.ok and clean.complete


def test_state_dedup_and_exhaustive_count():
    # product space is exactly 4 x 11 states, all stored unreduced; the
    # counter model declares no footprints or symmetry, so the only
    # reduction that engages is forced-sequence fusion (the tail where
    # just one action stays enabled stores endpoints only)
    full = pm.check(_counter_model(), reduce=False)
    assert full.ok and full.explored == 44
    res = pm.check(_counter_model())
    assert res.ok and res.explored <= 44 and res.stats["fused"] > 0


def test_nondet_branches_and_state_budget():
    def fork(s):
        return [dict(s, n=s["n"] + 1), dict(s, n=s["n"] + 2)]

    m = pm.make_model(
        "fork", {"n": 0},
        [pm.Action("fork", "p", lambda s: s["n"] < 6, fork)],
        [("no_neg", lambda s: s["n"] >= 0)], lambda s: True)
    res = pm.check(m)
    assert res.ok and res.explored == 8    # n in 0..7
    cut = pm.check(m, max_states=3)
    assert cut.ok and not cut.complete


def test_format_and_schedule_export():
    res = pm.check(_counter_model(bad_at=1))
    m = _counter_model(bad_at=1)
    text = pm.format_result(res, m)
    assert "INVARIANT VIOLATED: n_below_bad" in text
    assert "point.inc" in text             # sync names printed in traces
    sched = pm.trace_schedule(m, res.counterexample.trace)
    assert sched == ["point.inc"]


def test_freeze_rejects_unhashable_state_values():
    with pytest.raises(TypeError):
        pm.make_model("bad", {"x": [1, 2]}, [], [], lambda s: True)


# --- v2 reductions: symmetry, ample sets, collapse ---------------------------

def _sym_pair_model(*, declare=True):
    """Two interchangeable workers racing to grab one token: the full
    graph distinguishes who holds it, the symmetric quotient does not."""
    def grab(w):
        def guard(s):
            return s["holder"] == "" and s[f"{w}_pc"] == "idle"

        def apply(s, w=w):
            s["holder"] = w
            s[f"{w}_pc"] = "got"
        return pm.Action(f"{w}_grab", w, guard, apply)

    return pm.make_model(
        "sym_pair", {"holder": "", "w0_pc": "idle", "w1_pc": "idle"},
        [grab("w0"), grab("w1")],
        [("one_holder", lambda s: True)], lambda s: s["holder"] != "",
        symmetry=(("w0", "w1"),) if declare else ())


def test_symmetry_reduction_merges_interchangeable_identities():
    red = pm.check(_sym_pair_model())
    full = pm.check(_sym_pair_model(), reduce=False)
    assert red.ok and full.ok
    # w0-holds and w1-holds canonicalize to one state
    assert red.explored < full.explored
    assert red.stats["sym"] > 0


@pytest.mark.parametrize(
    "model", [m for m in pm.shipped_models() if m.symmetry],
    ids=lambda m: m.name)
def test_symmetry_declaring_models_check_at_strictly_fewer_states(model):
    """The tentpole soundness harness: every symmetry-declaring shipped
    model re-checks both ways with identical verdicts (cross_check
    asserts that internally) at STRICTLY fewer states."""
    xc = pm.cross_check(model)
    assert xc["reduced"].explored < xc["full"].explored, model.name
    assert xc["reduced"].stats["sym"] > 0, model.name


def _por_trap_model():
    """The seeded POR-unsoundness trap: ``advance`` moves the pc key
    ``x`` that ``poison``'s guard reads, so expanding only ``advance``
    disables ``poison`` forever and hides the only violation. The
    sound ample rule must refuse the singleton {advance}; a naive rule
    that skips the dependence closure takes it and reports clean."""
    def adv_apply(s):
        s["x"] = "hi"

    def poison_apply(s):
        s["bad"] = True

    acts = [
        pm.Action("advance", "a", lambda s: s["x"] == "lo", adv_apply,
                  pc=(("x", "lo"),), greads=(), reads=(), writes=("x",)),
        pm.Action("poison", "b", lambda s: s["x"] == "lo", poison_apply,
                  pc=(("x", "lo"),), greads=(), reads=(),
                  writes=("bad",)),
    ]
    return pm.make_model(
        "por_trap", {"x": "lo", "bad": False}, acts,
        [("never_bad", lambda s: not s["bad"])],
        lambda s: s["x"] == "hi", inv_reads=("bad",))


def test_por_sound_rule_refuses_the_hiding_reduction():
    res = pm.check(_por_trap_model())
    assert not res.ok
    assert res.counterexample.invariant == "never_bad"


def test_por_naive_rule_would_hide_the_counterexample(monkeypatch):
    """Negative test for the ample-set dependence closure: with the
    closure skipped, the reduction is UNSOUND — the checker declares
    the trap model clean. This pins that the closure, not luck, is
    what keeps the reduced verdicts honest."""
    monkeypatch.setattr(pm, "_AMPLE_SKIP_DEPENDENCE", True)
    naive = pm.check(_por_trap_model())
    assert naive.ok and naive.complete     # the seeded bug is HIDDEN
    monkeypatch.setattr(pm, "_AMPLE_SKIP_DEPENDENCE", False)
    assert not pm.check(_por_trap_model()).ok


def test_collapse_declaration_validated_statically():
    # an invariant reads the collapsed key: the declaration is unsound
    # and check() must refuse to run with it
    def push(s):
        s["box"] = ("full", s["n"])
        s["n"] += 1

    m = pm.make_model(
        "bad_collapse", {"box": ("empty",), "n": 0},
        [pm.Action("push", "p", lambda s: s["n"] < 2, push,
                   greads=("n",), reads=("n",), writes=("box", "n"))],
        [("payload_small", lambda s: len(s["box"]) < 9)],
        lambda s: True,
        inv_reads=("box",), collapse=(("box", "full"),))
    with pytest.raises(ValueError, match="collapse"):
        pm.check(m)
    # the same model unreduced ignores collapse and checks fine
    assert pm.check(m, reduce=False).ok


# --- bounded liveness --------------------------------------------------------

def _liveness_model(*, within=5, loop=False, give_up=False):
    """Counter to 3 with optional postponement knobs: ``loop`` adds a
    pred-avoiding cycle (lasso), ``give_up`` adds an early accepting
    exit (the run just ends)."""
    acts = [pm.Action("inc", "p",
                      lambda s: s["n"] < 3 and not s["q"],
                      lambda s: s.__setitem__("n", s["n"] + 1))]
    if loop:
        acts.append(pm.Action("spin", "q", lambda s: True,
                              lambda s: s.__setitem__(
                                  "t", (s["t"] + 1) % 2)))
    if give_up:
        acts.append(pm.Action("quit", "q", lambda s: not s["q"],
                              lambda s: s.__setitem__("q", True)))
    return pm.make_model(
        "live", {"n": 0, "t": 0, "q": False}, acts, [],
        lambda s: s["n"] == 3 or s.get("q"),
        obligations=(pm.Obligation("reaches_three",
                                   lambda s: s["n"] == 3, within),))


def test_liveness_clean_pass():
    res = pm.check_liveness(_liveness_model())
    assert res.ok and res.complete


def test_liveness_bound_counterexample():
    # 3 inc steps needed, bound of 2: a within-step avoiding path
    res = pm.check_liveness(_liveness_model(within=2))
    assert not res.ok
    cex = res.counterexample
    assert cex.kind == "liveness" and cex.invariant == "reaches_three"
    assert res.stats["liveness"] == "bound"


def test_liveness_lasso_counterexample():
    # the spin cycle postpones the eventuality forever
    res = pm.check_liveness(_liveness_model(loop=True))
    assert not res.ok
    assert res.counterexample.kind == "liveness"
    assert res.stats["liveness"] == "lasso"


def test_liveness_run_ends_counterexample():
    # quit is accepting but n never reaches 3 on that run
    res = pm.check_liveness(_liveness_model(give_up=True))
    assert not res.ok
    assert res.stats["liveness"] == "run ends"
    assert "(run ends)" in res.counterexample.trace[-1][0]


def test_liveness_trigger_gated_by_after():
    # with after= never true, there is no trigger and nothing to prove
    m = _liveness_model(loop=True)
    gated = pm.Obligation("reaches_three", lambda s: s["n"] == 3, 5,
                          after=lambda s: False)
    m = pm.make_model("live", dict(m.init), m.actions, [], m.is_done,
                      obligations=(gated,))
    assert pm.check_liveness(m).ok


# --- shipped models ----------------------------------------------------------

# REDUCED exhaustive floors (~10% under current counts): a guard
# refactor that silently hollows out the reachable space must fail
SHIPPED_MIN_STATES = {"delta_chain": 58_000, "hot_swap": 120,
                      "dirty_tracker": 70, "ha_registry": 210,
                      "serving_batcher": 3_000, "multihost_delta": 140,
                      "training_membership": 160, "reshard": 60}

# PR 11's plain-BFS delta_chain count — the baseline the v2 engine's
# >=1.5x reduction acceptance criterion is measured against
PR11_DELTA_CHAIN_STATES = 141_649


@pytest.mark.parametrize("model", pm.shipped_models(),
                         ids=lambda m: m.name)
def test_shipped_model_checks_clean_and_exhaustively(model):
    res = pm.check(model)
    assert res.ok and res.complete, pm.format_result(res, model)
    # the exploration must stay EXHAUSTIVE: a refactor that silently
    # guards away most of the space would "pass" while checking nothing
    assert res.explored >= SHIPPED_MIN_STATES[model.name], res.explored


@pytest.mark.parametrize("model", pm.shipped_models(),
                         ids=lambda m: m.name)
def test_shipped_model_footprints_audit_clean(model):
    """Every declared guard/apply/invariant footprint must cover what
    the code actually reads and writes — the POR soundness input."""
    assert pm.audit_footprints(model) == []


@pytest.mark.parametrize("model",
                         [m for m in pm.shipped_models()
                          if m.name != "delta_chain"],
                         ids=lambda m: m.name)
def test_reduction_verdicts_identical_to_full_expansion(model):
    """cross_check asserts reduced/full verdict equality internally and
    that reduction never expands the graph."""
    xc = pm.cross_check(model)
    assert xc["ratio"] >= 1.0


def test_delta_chain_reduction_beats_pr11_baseline():
    """The acceptance criterion: >=1.5x state reduction on delta_chain
    vs the plain-BFS shipped baseline (the v2 engine's footprint-driven
    payload hygiene + quiescent collapse + ample fusion land 2.1x+;
    same-model reduced-vs-full is ~1.4x on top of the collapsed
    encoding, cross-checked weekly in CI)."""
    xc = pm.cross_check(pm.delta_chain())
    red = xc["reduced"].explored
    assert red * 3 <= PR11_DELTA_CHAIN_STATES * 2, red   # >= 1.5x
    assert xc["ratio"] > 1.0


@pytest.mark.parametrize("model",
                         [m for m in pm.shipped_models()
                          if m.obligations],
                         ids=lambda m: m.name)
def test_shipped_model_obligations_hold(model):
    res = pm.check_liveness(model)
    assert res.ok and res.complete, pm.format_result(res, model)


def test_all_three_multihost_models_shipped_with_obligations():
    byname = {m.name: m for m in pm.shipped_models()}
    for name in ("multihost_delta", "training_membership", "reshard"):
        assert name in byname
        assert byname[name].obligations, name


@pytest.mark.parametrize("model", pm.shipped_models(),
                         ids=lambda m: m.name)
def test_model_sync_points_exist_in_package_source(model):
    """The fidelity tripwire: every sync point a model action claims to
    correspond to must still be emitted by the package source (or be an
    explicitly reserved design-only point for the multi-host models)."""
    assert pm.missing_sync_points(model) == []
    assert pm.model_sync_points(model)     # and the bridge is non-empty


def test_reserved_sync_points_are_design_only():
    # reserved names must NOT leak into the package source unnoticed:
    # once implemented, the reservation must be retired
    byname = {m.name: m for m in pm.shipped_models()}
    assert pm.reserved_sync_points(byname["multihost_delta"])
    assert pm.reserved_sync_points(byname["reshard"])
    assert pm.reserved_sync_points(byname["delta_chain"]) == []


def test_sample_traces_are_replayable_schedules():
    for model in (pm.hot_swap(), pm.dirty_tracker()):
        traces = pm.sample_traces(model)
        assert traces
        for t in traces:
            assert t[0][0] == "<init>"
            # a sampled trace maps onto at least one real sync point
            assert isinstance(pm.trace_schedule(model, t), list)


# --- seeded mutations --------------------------------------------------------

def test_every_seeded_mutation_fires_its_expected_property():
    fixture = _load_fixture()
    muts = list(fixture.iter_mutations())
    # every shipped protocol has at least one seeded mutation, and each
    # multi-host model at least two
    builders = [m["builder"] for m in muts]
    assert set(builders) == {m.name for m in pm.shipped_models()}
    for name in ("multihost_delta", "training_membership", "reshard"):
        assert builders.count(name) >= 2, name
    for mut in muts:
        model = getattr(pm, mut["builder"])(**mut["kwargs"])
        if mut["kind"] == "liveness":
            res = pm.check_liveness(model)
            want_kind = "liveness"
        else:
            res = pm.check(model)
            want_kind = "invariant"
        cex = res.counterexample
        assert cex is not None and cex.kind == want_kind, \
            f"mutation {mut['name']} produced no {want_kind} cex"
        assert cex.invariant == mut["expected_invariant"], \
            f"mutation {mut['name']}: fired {cex.invariant!r}"
        # minimal-length trace exists and is replayable
        assert len(cex.trace) >= 2
        assert isinstance(pm.trace_schedule(model, cex.trace), list)


def test_fixture_loader_rejects_missing_expected_invariant():
    fixture = _load_fixture()
    good = list(fixture.iter_mutations())
    assert good
    orig = fixture.MUTATIONS
    try:
        bad = dict(orig[0], name="no_expectation")
        del bad["expected_invariant"]
        fixture.MUTATIONS = orig + [bad]
        with pytest.raises(ValueError, match="expected_invariant"):
            list(fixture.iter_mutations())
        fixture.MUTATIONS = orig + [dict(orig[0], name="bad_kind",
                                         kind="eventually")]
        with pytest.raises(ValueError, match="kind"):
            list(fixture.iter_mutations())
        fixture.MUTATIONS = orig + [dict(orig[0])]
        with pytest.raises(ValueError, match="duplicate"):
            list(fixture.iter_mutations())
    finally:
        fixture.MUTATIONS = orig


def test_mutation_builder_helper():
    fixture = _load_fixture()
    m = fixture.build(pm, "drop_seq_gate")
    assert m.name == "hot_swap"
    with pytest.raises(KeyError):
        fixture.build(pm, "nope")


# --- CLI ---------------------------------------------------------------------

def test_cli_exit_codes(tmp_path):
    from tools.graftproto import main
    assert main(["--model", "reshard"]) == 0
    assert main(["--model", "nope"]) == 2
    # a budget too small to finish a shipped model fails the gate
    assert main(["--model", "delta_chain", "--max-states", "100"]) == 1


def test_cli_check_sync(capsys):
    from tools.graftproto import main
    assert main(["--check-sync"]) == 0
    out = capsys.readouterr().out
    assert "reserved, design-only" in out
    assert "DRIFT" not in out


def test_cli_json_report(tmp_path, capsys):
    from tools.graftproto import main
    out = tmp_path / "gate.json"
    assert main(["--model", "multihost_delta", "--cross-check",
                 "--json", str(out)]) == 0
    capsys.readouterr()
    data = json.loads(out.read_text())
    entry = data["models"]["multihost_delta"]
    assert entry["ok"] and entry["complete"]
    assert entry["explored"] >= SHIPPED_MIN_STATES["multihost_delta"]
    assert entry["stats"]["reduce"] is True
    assert entry["cross_check"]["ratio"] >= 1.0
    assert entry["liveness_ok"] is True


def test_cli_mutations_exit_one():
    from tools.graftproto import main
    assert main(["--mutations"]) == 1      # seeded bugs MUST fire


def test_cli_emit_schedules(tmp_path, capsys):
    from tools.graftproto import main
    out = tmp_path / "sched.json"
    assert main(["--emit-schedules", str(out)]) == 0
    capsys.readouterr()
    data = json.loads(out.read_text())
    assert set(data["models"]) == {m.name for m in pm.shipped_models()}
    for entry in data["models"].values():
        assert entry["explored"] > 0 and entry["schedules"]
    fixture = _load_fixture()
    muts = list(fixture.iter_mutations())
    assert set(data["mutations"]) == {m["name"] for m in muts}
    for mut in muts:
        got = data["mutations"][mut["name"]]
        assert got["invariant"] == mut["expected_invariant"]
        assert got["kind"] == mut["kind"]
        assert got["actions"]              # the replayable trace
