"""Fused feature groups: id mapping correctness, semantic parity with
per-feature variables, end-to-end training on a mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from openembedding_tpu import (EmbeddingCollection, Trainer, make_fused_specs)
from openembedding_tpu.fused import FusedMapper
from openembedding_tpu.models import deepctr
from openembedding_tpu.parallel.mesh import create_mesh

FEATURES = ("c0", "c1", "c2")


def test_mapper_bounded_offsets():
    m = FusedMapper(FEATURES, (10, 20, 30))
    assert m.offsets.tolist() == [0, 10, 30]
    assert m.total_vocab == 60
    sparse = {"c0": np.array([0, 9]), "c1": np.array([0, 19]),
              "c2": np.array([0, 29])}
    fused = m.fuse(sparse)["fields"]
    np.testing.assert_array_equal(fused, [[0, 10, 30], [9, 29, 59]])
    # out-of-range ids become -1 (invalid) instead of bleeding into the
    # next feature's row range
    bad = m.fuse({"c0": np.array([10]), "c1": np.array([-1]),
                  "c2": np.array([5])})["fields"]
    np.testing.assert_array_equal(bad, [[-1, -1, 35]])


def test_mapper_hash_disjoint():
    from openembedding_tpu import hash_table as hl
    # DEFAULT hash fusion is wide: [B, F, 2] pair keys = the interleaved
    # full 64-bit space, exactly key*F+f with no truncation
    m = FusedMapper(FEATURES, (-1, -1, -1))
    assert m.use_hash and m.key_dtype == "wide"
    sparse = {f: np.array([123, 456], dtype=np.int32) for f in FEATURES}
    fused = m.fuse(sparse)["fields"]
    assert fused.shape == (2, 3, 2)
    joined = hl.join64(fused)
    # same raw key in different features maps to distinct fused keys
    assert len(set(joined[0].tolist())) == 3
    np.testing.assert_array_equal(joined[0], 123 * 3 + np.arange(3))

    # int32 opt-in: mixed into 31 bits, still disjoint per feature
    m32 = FusedMapper(FEATURES, (-1, -1, -1), key_dtype="int32")
    fused32 = m32.fuse(sparse)["fields"]
    assert fused32.dtype == np.int32 and fused32.shape == (2, 3)
    assert len(set(fused32[0].tolist())) == 3


def test_mixed_hash_bounded_rejected():
    with pytest.raises(ValueError, match="fuse"):
        make_fused_specs(FEATURES, [10, -1, 30], 4)


def test_fused_parity_with_per_feature(devices8):
    """Same ids, same optimizer: fused pull/apply must behave exactly like
    per-feature variables modulo initialization (constant init => exact)."""
    mesh = create_mesh(1, 8, devices8)
    vocabs = (40, 56, 24)
    init = {"category": "constant", "value": 0.5}
    opt = {"category": "adagrad", "learning_rate": 0.1}

    fspecs, mapper = make_fused_specs(FEATURES, list(vocabs), 4,
                                      need_linear=False, optimizer=opt,
                                      initializer=init)
    fcoll = EmbeddingCollection(fspecs, mesh)
    fstates = fcoll.init()

    pspecs = deepctr.make_feature_specs(FEATURES, list(vocabs), 4,
                                        need_linear=False, optimizer=opt,
                                        initializer=init)
    pcoll = EmbeddingCollection(pspecs, mesh)
    pstates = pcoll.init()

    rng = np.random.RandomState(0)
    for step in range(3):
        sparse = {f: rng.randint(0, v, 16).astype(np.int32)
                  for f, v in zip(FEATURES, vocabs)}
        fused_in = mapper.fuse(sparse)
        frows = fcoll.pull(fstates, fused_in, batch_sharded=False)["fields"]
        prows = pcoll.pull(pstates, sparse, batch_sharded=False)
        for j, f in enumerate(FEATURES):
            np.testing.assert_allclose(np.asarray(frows[:, j]),
                                       np.asarray(prows[f]),
                                       rtol=1e-6, atol=1e-7)
        g = rng.randn(16, len(FEATURES), 4).astype(np.float32)
        fstates = fcoll.apply_gradients(
            fstates, fused_in, {"fields": jnp.asarray(g)},
            batch_sharded=False)
        pstates = pcoll.apply_gradients(
            pstates, sparse, {f: jnp.asarray(g[:, j])
                              for j, f in enumerate(FEATURES)},
            batch_sharded=False)


@pytest.mark.slow
def test_fused_training_end_to_end(devices8):
    mesh = create_mesh(2, 4, devices8)
    specs, mapper = make_fused_specs(
        FEATURES, 100, 8,
        optimizer={"category": "adagrad", "learning_rate": 0.1})
    coll = EmbeddingCollection(specs, mesh)
    trainer = Trainer(deepctr.build_model("deepfm", FEATURES), coll,
                      optax.adam(1e-2))
    rng = np.random.RandomState(0)

    def batches(n):
        for _ in range(n):
            raw = {f: rng.randint(0, 100, 16).astype(np.int32)
                   for f in FEATURES}
            label = ((raw["c0"] + raw["c1"]) % 2).astype(np.float32)
            yield mapper.fuse_batch({
                "label": label,
                "dense": rng.randn(16, 4).astype(np.float32),
                "sparse": raw})

    bs = list(batches(30))
    state = trainer.init(jax.random.PRNGKey(0), trainer.shard_batch(bs[0]))
    losses = []
    for b in bs:
        state, m = trainer.train_step(state, b)
        losses.append(float(m["loss"]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


def test_fused_hash_training(devices8):
    mesh = create_mesh(2, 4, devices8)
    specs, mapper = make_fused_specs(
        FEATURES, -1, 8, hash_capacity=4096,
        optimizer={"category": "adagrad", "learning_rate": 0.1})
    coll = EmbeddingCollection(specs, mesh)
    trainer = Trainer(deepctr.build_model("wdl", FEATURES), coll,
                      optax.adam(1e-2))
    rng = np.random.RandomState(0)

    def batches(n):
        for _ in range(n):
            raw = {f: rng.randint(0, 10**6, 16).astype(np.int32)
                   for f in FEATURES}
            label = (rng.rand(16) > 0.5).astype(np.float32)
            yield mapper.fuse_batch({
                "label": label,
                "dense": None,
                "sparse": raw})

    bs = list(batches(10))
    state = trainer.init(jax.random.PRNGKey(0), trainer.shard_batch(bs[0]))
    for b in bs:
        state, m = trainer.train_step(state, b)
    assert np.isfinite(float(m["loss"]))
    assert int(state.emb["fields"].insert_failures) == 0


def test_fused_wide_keys(devices8):
    """Hash fusion with key_dtype='wide': [B, F, 2] pair keys keep the
    full 64-bit interleaved key space (no 31-bit truncation) with the
    global x64 flag OFF."""
    import jax
    from openembedding_tpu import EmbeddingCollection
    from openembedding_tpu.fused import make_fused_specs
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(2, 4, devices8)
    feats = ("a", "b", "c")
    specs, mapper = make_fused_specs(feats, -1, 4, hash_capacity=2048,
                                     key_dtype="wide", need_linear=False)
    coll = EmbeddingCollection(specs, mesh)
    states = coll.init(jax.random.PRNGKey(0))
    assert states["fields"].keys.ndim == 2
    rng = np.random.RandomState(0)
    # ids above 2^31: would truncate/alias under int32 fusion
    sparse = {f: (rng.randint(0, 1 << 20, 16).astype(np.int64)
                  + (1 << 40)) for f in feats}
    fused = mapper.fuse(sparse)["fields"]
    assert fused.shape == (16, 3, 2)
    jb = jnp.asarray(fused)
    rows = coll.pull(states, {"fields": jb}, batch_sharded=False)
    assert rows["fields"].shape == (16, 3, 4)
    states = coll.apply_gradients(
        states, {"fields": jb},
        {"fields": jnp.ones_like(rows["fields"])}, batch_sharded=False)
    # same feature value in different columns maps to different rows
    # (interleaving preserved at full width)
    s2 = {f: np.full(1, 12345 + (1 << 33), np.int64) for f in feats}
    f2 = jnp.asarray(mapper.fuse(s2)["fields"])
    r2 = np.asarray(coll.pull(states, {"fields": f2},
                              batch_sharded=False)["fields"])[0]
    from openembedding_tpu import hash_table as hl
    j = hl.join64(np.asarray(f2)[0])
    assert len(set(j.tolist())) == 3  # three distinct fused keys


def test_fused_wide_empty_band_remap():
    """Fused keys that wrap into the wide EMPTY band (hi == INT32_MIN,
    reachable for ids near 2^63/F) are remapped up one hi step instead of
    being silently treated as free slots by the table."""
    from openembedding_tpu import hash_table as hl
    from openembedding_tpu.fused import FusedMapper
    m = FusedMapper(feature_names=("a", "b", "c"), vocab_sizes=(-1, -1, -1),
                    key_dtype="wide", need_linear=False)
    big = (1 << 63) // 3  # id whose fused key wraps to hi == INT32_MIN
    sp = {f: np.asarray([big, 7], np.int64) for f in ("a", "b", "c")}
    out = m.fuse(sp)["fields"]
    assert (out[..., 1] != hl.empty_key(np.int32)).all()
    # normal ids untouched
    np.testing.assert_array_equal(
        out[1], hl.split64(np.asarray([7 * 3, 7 * 3 + 1, 7 * 3 + 2],
                                      np.int64)))
