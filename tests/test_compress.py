"""Message/stream compression across the binary planes.

The reference selects a payload codec via ``server.message_compress``
(client/EnvConfig.cpp:27-34) and applies it in the zero-copy view path
(server/RpcView.h:63-105) and pull responses
(server/EmbeddingPullOperator.cpp:149-205). Here the knob covers serving
``lookup_bin`` responses, peer-restore row pages, and checkpoint block
streams (the framed ``.npyz`` container).
"""

import json
import socket
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
from openembedding_tpu import checkpoint as ckpt
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.utils import compress as C
from openembedding_tpu.utils import fs


def test_codec_roundtrip_and_validation():
    data = np.arange(4096, dtype=np.float32).tobytes()
    assert C.decompress("zlib", C.compress("zlib", data)) == data
    assert C.compress("", data) == data
    assert len(C.compress("zlib", data)) < len(data)
    with pytest.raises(ValueError, match="known"):
        C.check("snappy")
    # zstd is config-time gated on an importable binding
    if C._zstd() is None:
        with pytest.raises(ValueError, match="zstd"):
            C.check("zstd")
    assert C.check("") == "" and C.check("zlib") == "zlib"


def test_npyz_roundtrip_rebuffered(tmp_path):
    """Frames written at one granularity read back at any other."""
    path = str(tmp_path / "x.npyz")
    rows = np.arange(1000 * 3, dtype=np.float32).reshape(1000, 3)
    with fs.NpyzWriter(path, np.float32, (1000, 3)) as w:
        for lo in range(0, 1000, 100):
            w.write(rows[lo:lo + 100])
    dtype, shape = fs.npyz_shape(path)
    assert dtype == np.float32 and tuple(shape) == (1000, 3)
    got = np.concatenate(list(fs.iter_npyz_chunks(path, 37)))
    np.testing.assert_array_equal(got, rows)
    # every yielded chunk except the last is exactly the asked size
    sizes = [c.shape[0] for c in fs.iter_npyz_chunks(path, 37)]
    assert all(s == 37 for s in sizes[:-1]) and sum(sizes) == 1000


def test_npyz_short_write_fails(tmp_path):
    w = fs.NpyzWriter(str(tmp_path / "s.npyz"), np.int32, (10,))
    w.write(np.arange(4, dtype=np.int32))
    with pytest.raises(IOError, match="promised"):
        w.close()


@pytest.mark.slow
def test_compressed_checkpoint_round_trip(devices8, tmp_path):
    """compress='zlib' dumps load back identical to the raw dump —
    array, int32 hash, and wide hash variables."""
    from openembedding_tpu import hash_table as hl
    mesh = create_mesh(2, 4, devices8)
    specs = (
        EmbeddingSpec(name="arr", input_dim=256, output_dim=8,
                      initializer={"category": "normal", "stddev": 1.0}),
        EmbeddingSpec(name="hsh", input_dim=-1, output_dim=4,
                      hash_capacity=512, key_dtype="int32",
                      optimizer={"category": "sgd", "learning_rate": 1.0}),
        EmbeddingSpec(name="wid", input_dim=-1, output_dim=4,
                      hash_capacity=512, key_dtype="wide",
                      optimizer={"category": "sgd", "learning_rate": 1.0}),
    )
    coll = EmbeddingCollection(specs, mesh)
    states = coll.init(jax.random.PRNGKey(2))
    hkeys = jnp.asarray(np.arange(1, 33, dtype=np.int32))
    wkeys = jnp.asarray(hl.split64((7 << 60) + np.arange(1, 33,
                                                         dtype=np.int64)))
    g4 = jnp.ones((32, 4), jnp.float32)
    _ = coll.pull(states, {"hsh": hkeys, "wid": wkeys}, batch_sharded=False)
    states = coll.apply_gradients(states, {"hsh": hkeys, "wid": wkeys},
                                  {"hsh": g4, "wid": 2 * g4},
                                  batch_sharded=False)
    raw, packed = str(tmp_path / "raw"), str(tmp_path / "zlib")
    ckpt.save_checkpoint(raw, coll, states, model_sign="m")
    ckpt.save_checkpoint(packed, coll, states, model_sign="m",
                         compress="zlib")
    # compressed dumps really are framed streams, not renamed .npy
    import os
    names = []
    for root, _, files in os.walk(packed):
        names += files
    assert any(f.endswith(".npyz") for f in names)
    assert not any(f.endswith(".npy") for f in names)

    c2 = EmbeddingCollection(specs, mesh)
    s_raw = ckpt.load_checkpoint(raw, c2)
    s_z = ckpt.load_checkpoint(packed, c2)
    probes = {"arr": jnp.arange(256, dtype=jnp.int32), "hsh": hkeys,
              "wid": wkeys}
    r_raw = c2.pull(s_raw, probes, batch_sharded=False, read_only=True)
    r_z = c2.pull(s_z, probes, batch_sharded=False, read_only=True)
    for name in probes:
        np.testing.assert_array_equal(np.asarray(r_raw[name]),
                                      np.asarray(r_z[name]))
    with pytest.raises(ValueError, match="known"):
        ckpt.save_checkpoint(str(tmp_path / "bad"), coll, states,
                             compress="lz77")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_serving_planes_compressed(devices8, tmp_path):
    """One replica with message_compress=zlib: binary lookups compress
    when (and only when) the client advertises the codec; row pages pack
    on request; values identical to the raw plane."""
    from openembedding_tpu.serving import ha
    mesh = create_mesh(1, 1, jax.devices()[:1])
    spec = EmbeddingSpec(name="emb", input_dim=512, output_dim=16,
                         initializer={"category": "normal", "stddev": 1.0})
    coll = EmbeddingCollection((spec,), mesh)
    states = coll.init(jax.random.PRNGKey(9))
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, model_sign="zm")
    want = np.asarray(coll.pull(states,
                                {"emb": jnp.arange(512, dtype=jnp.int32)},
                                batch_sharded=False)["emb"])

    port = _free_port()
    ep = f"127.0.0.1:{port}"
    proc = ha.spawn_replica(port, load=[f"zm={path}"], compress="zlib")
    try:
        assert ha.wait_ready(ep, sign="zm", timeout=180.0)
        idx = np.arange(512, dtype=np.int32)

        plain = ha.RoutingClient([ep], timeout=15.0)
        packed = ha.RoutingClient([ep], timeout=15.0, compress="zlib")
        np.testing.assert_allclose(plain.lookup("zm", "emb", idx), want,
                                   rtol=1e-6)
        np.testing.assert_allclose(packed.lookup("zm", "emb", idx), want,
                                   rtol=1e-6)

        # the wire really is compressed iff advertised
        def raw_response(accept):
            head = {"variable": "emb", "dtype": "int32",
                    "shape": [int(idx.size)]}
            if accept:
                head["accept_compress"] = [accept]
            body = json.dumps(head).encode() + b"\n" + idx.tobytes()
            req = urllib.request.Request(
                f"http://{ep}/models/zm/lookup_bin", data=body,
                method="POST",
                headers={"Content-Type": "application/octet-stream"})
            with urllib.request.urlopen(req, timeout=15) as r:
                raw = r.read()
            nl = raw.index(b"\n")
            return json.loads(raw[:nl]), raw[nl + 1:]

        h, payload = raw_response("zlib")
        assert h.get("compress") == "zlib"
        assert len(payload) < want.nbytes  # normal rows compress
        h, payload = raw_response(None)
        assert "compress" not in h and len(payload) == want.nbytes

        # peer-restore row pages: &compress= packs the page body
        ids_r, rows_r, total = ha.fetch_rows_page(ep, "zm", "emb", 0, 512)
        ids_z, rows_z, total_z = ha.fetch_rows_page(ep, "zm", "emb", 0, 512,
                                                    compress="zlib")
        assert total == total_z == 512
        np.testing.assert_array_equal(ids_r, ids_z)
        np.testing.assert_array_equal(rows_r, rows_z)
    finally:
        proc.kill()


def test_envconfig_message_compress():
    from openembedding_tpu.utils.envconfig import EnvConfig
    cfg = EnvConfig.load({"serving": {"message_compress": "zlib"}})
    assert cfg.serving.message_compress == "zlib"
    with pytest.raises(ValueError, match="zlib"):
        EnvConfig.load({"serving": {"message_compress": "snappy"}})
