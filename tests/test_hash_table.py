"""Hash-table embedding: probe/insert correctness, reference pull/update
semantics (deferred materialization), sharded parity with the local table.

Mirrors the reference's hash-variable paths in c_api_test.h (dense/hash
matrix) — ground truth here is a Python dict replica updated with the same
deterministic rules, plus single-vs-sharded cross-checks.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import EmbeddingVariableMeta, make_optimizer
from openembedding_tpu import hash_table as ht
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.parallel import sharded_hash as sh
from openembedding_tpu.utils import jaxcompat

DIM = 4
META = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=2**63)
INIT = {"category": "constant", "value": 0.25}


def test_meta_selects_hash():
    assert META.use_hash_table


def test_pull_missing_returns_init_and_is_deterministic():
    t = ht.create_hash_table(META, "default", capacity=64)
    keys = jnp.array([7, 123456, -5], dtype=jnp.int32)
    rows1 = ht.pull(t, keys, {"category": "uniform", "minval": -1, "maxval": 1})
    rows2 = ht.pull(t, keys, {"category": "uniform", "minval": -1, "maxval": 1})
    np.testing.assert_array_equal(np.asarray(rows1), np.asarray(rows2))
    # distinct keys get distinct init rows
    assert not np.allclose(np.asarray(rows1)[0], np.asarray(rows1)[1])


def test_insert_then_find():
    t = ht.create_hash_table(META, {"category": "sgd", "learning_rate": 1.0},
                             capacity=128)
    opt = make_optimizer({"category": "sgd", "learning_rate": 1.0})
    keys = jnp.array([3, 900001, 42, 3], dtype=jnp.int32)
    grads = jnp.ones((4, DIM), dtype=jnp.float32)
    t = ht.apply_gradients(t, opt, INIT, keys, grads)
    assert int(t.num_used()) == 3
    assert int(t.insert_failures) == 0
    # present keys now pull their stored (updated) rows: init 0.25 - lr*sum
    rows = np.asarray(ht.pull(t, jnp.array([3, 42], jnp.int32), INIT))
    np.testing.assert_allclose(rows[0], 0.25 - 2.0, rtol=1e-6)  # key 3 dup x2
    np.testing.assert_allclose(rows[1], 0.25 - 1.0, rtol=1e-6)


def test_pull_update_consistency_vs_dict_replica():
    """Random pull/push stream against a host dict replica (SGD, exact)."""
    lr = 0.5
    opt = make_optimizer({"category": "sgd", "learning_rate": lr})
    t = ht.create_hash_table(META, opt, capacity=512)
    replica = {}
    rng = np.random.RandomState(1)
    for step in range(5):
        keys = rng.randint(0, 10**9, size=32).astype(np.int32)
        grads = rng.randn(32, DIM).astype(np.float32)
        jk, jg = jnp.asarray(keys), jnp.asarray(grads)
        rows = np.asarray(ht.pull(t, jk, INIT))
        for i, k in enumerate(keys):
            want = replica.get(int(k), np.full(DIM, 0.25, np.float32))
            np.testing.assert_allclose(rows[i], want, rtol=1e-5, atol=1e-6)
        t = ht.apply_gradients(t, opt, INIT, jk, jg)
        # replicate: dedup-sum then single momentumless sgd step
        summed = {}
        for i, k in enumerate(keys):
            summed[int(k)] = summed.get(int(k), np.zeros(DIM, np.float32)) + grads[i]
        for k, g in summed.items():
            cur = replica.get(k, np.full(DIM, 0.25, np.float32))
            replica[k] = cur - lr * g
    assert int(t.insert_failures) == 0


def test_probe_window_overflow_counted():
    """A table with capacity < distinct keys must fail some inserts, not hang
    or corrupt other rows."""
    opt = make_optimizer({"category": "sgd", "learning_rate": 1.0})
    t = ht.create_hash_table(META, opt, capacity=8)
    keys = jnp.arange(100, dtype=jnp.int32) * 7919
    grads = jnp.ones((100, DIM), dtype=jnp.float32)
    t = ht.apply_gradients(t, opt, INIT, keys, grads)
    assert int(t.num_used()) == 8
    assert int(t.insert_failures) == 100 - 8


def test_adam_state_on_hash_rows():
    """Optimizer slots ride along: two updates to one key accumulate state."""
    opt = make_optimizer({"category": "adam", "learning_rate": 0.1})
    t = ht.create_hash_table(META, opt, capacity=32)
    k = jnp.array([77], jnp.int32)
    g = jnp.ones((1, DIM), jnp.float32)
    t = ht.apply_gradients(t, opt, INIT, k, g)
    t = ht.apply_gradients(t, opt, INIT, k, g)
    slot = ht.find_rows(t.keys, k)
    b1 = float(t.slots["beta_1_t"][int(slot[0]), 0])
    np.testing.assert_allclose(b1, 0.9**2, rtol=1e-6)


@pytest.mark.parametrize("data,model", [(1, 8), (2, 4), (8, 1)])
def test_sharded_hash_matches_single(devices8, data, model):
    mesh = create_mesh(data, model, devices8)
    opt = make_optimizer({"category": "adagrad", "learning_rate": 0.1})
    spec = sh.make_hash_sharding_spec(mesh, total_capacity=1024)
    sharded = sh.create_sharded_hash_table(META, opt, mesh=mesh, spec=spec)
    single = ht.create_hash_table(META, opt, capacity=1024)

    rng = np.random.RandomState(2)
    B = 16
    for step in range(3):
        keys = rng.randint(0, 10**8, size=B).astype(np.int32)
        grads = rng.randn(B, DIM).astype(np.float32)
        jk, jg = jnp.asarray(keys), jnp.asarray(grads)

        got = sh.pull_sharded(sharded, jk, INIT, mesh=mesh, spec=spec)
        want = ht.pull(single, jk, INIT)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

        sharded = sh.apply_gradients_sharded(sharded, opt, INIT, jk, jg,
                                             mesh=mesh, spec=spec)
        single = ht.apply_gradients(single, opt, INIT, jk, jg)

    got = sh.pull_sharded(sharded, jnp.asarray(keys), INIT, mesh=mesh, spec=spec)
    want = ht.pull(single, jnp.asarray(keys), INIT)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
    assert int(sharded.insert_failures) == 0


def test_sharded_hash_batch_replicated(devices8):
    mesh = create_mesh(4, 2, devices8)
    opt = make_optimizer({"category": "sgd", "learning_rate": 0.5})
    spec = sh.make_hash_sharding_spec(mesh, total_capacity=256)
    t1 = sh.create_sharded_hash_table(META, opt, mesh=mesh, spec=spec)
    t2 = jax.tree.map(jnp.copy, t1)

    keys = jnp.arange(16, dtype=jnp.int32) * 101
    g = jnp.ones((16, DIM)) * jnp.arange(16)[:, None]

    t1 = sh.apply_gradients_sharded(t1, opt, INIT, keys, g, mesh=mesh,
                                    spec=spec, batch_sharded=True)
    t2 = sh.apply_gradients_sharded(t2, opt, INIT, keys, g, mesh=mesh,
                                    spec=spec, batch_sharded=False)
    r1 = sh.pull_sharded(t1, keys, INIT, mesh=mesh, spec=spec, batch_sharded=True)
    r2 = sh.pull_sharded(t2, keys, INIT, mesh=mesh, spec=spec, batch_sharded=False)
    np.testing.assert_allclose(np.asarray(r1), np.asarray(r2), rtol=1e-6)


@pytest.mark.slow
def test_int64_keys_full_width(devices8):
    """The reference's 2^62 key space: int64 keys end-to-end in a dedicated
    x64 process (the global flag changes dtypes program-wide, so the
    documented deployment shape is a dedicated interpreter)."""
    import os
    import subprocess
    import sys
    worker = os.path.join(os.path.dirname(__file__), "x64_worker.py")
    root = os.path.dirname(os.path.dirname(worker))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU child: skip tunnel plugin
    out = subprocess.run([sys.executable, worker], env=env,
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "x64 worker: ok" in out.stdout


def test_bucket_layout_and_rounding():
    """Capacity rounds to bucket granularity; layout covers small tables."""
    assert ht.round_capacity(1000) == 1024
    assert ht.round_capacity(8) == 8
    b, nb, chain = ht.table_layout(4096, ht.DEFAULT_MAX_PROBES)
    assert (b, nb, chain) == (128, 32, 2)
    b, nb, chain = ht.table_layout(8, ht.DEFAULT_MAX_PROBES)
    assert (b, nb, chain) == (8, 1, 1)
    with pytest.raises(ValueError):
        ht.table_layout(1000, ht.DEFAULT_MAX_PROBES)


def test_widen_ids_matches_split64():
    """widen_ids (the narrow->wide bridge every default-keyed lookup rides)
    must agree with the host split64 encoding for every sign, and map the
    narrow dtype's sentinel to the EMPTY pair (the invalid contract)."""
    ids32 = np.array([0, 1, -1, 7, -2**31 + 1, 2**31 - 1], np.int32)
    got = np.asarray(ht.widen_ids(jnp.asarray(ids32)))
    np.testing.assert_array_equal(got, ht.split64(ids32.astype(np.int64)))
    # int32 sentinel -> EMPTY pair (both words)
    s = np.asarray(ht.widen_ids(jnp.asarray([np.iinfo(np.int32).min],
                                            np.int32)))
    np.testing.assert_array_equal(s, ht.empty_key(jnp.int32))
    # shape is preserved with a trailing pair axis
    m = np.asarray(ht.widen_ids(jnp.asarray(ids32.reshape(2, 3))))
    assert m.shape == (2, 3, 2)
    # device int64 branch (x64 on): full width + int64 sentinel -> EMPTY
    import jax
    with jaxcompat.enable_x64(True):
        ids64 = np.array([2**33 + 7, -5, np.iinfo(np.int64).min], np.int64)
        got64 = np.asarray(ht.widen_ids(jnp.asarray(ids64)))
    np.testing.assert_array_equal(got64[:2], ht.split64(ids64[:2]))
    np.testing.assert_array_equal(got64[2], ht.empty_key(jnp.int32))


def test_pair_mod_matches_int64_mod():
    """pair_mod (the x64-off wide-key shard-owner rule) equals int64
    ``id % g`` for every sign/magnitude — the loader, in-process filter,
    and router all rely on this equivalence."""
    rng = np.random.RandomState(0)
    ids = np.concatenate([
        rng.randint(-2**62, 2**62, 5000).astype(np.int64),
        np.array([0, 1, -1, 2**62 - 1, -2**62, (3 << 60) + (5 << 32)])])
    pairs = jnp.asarray(ht.split64(ids))
    for g in (1, 2, 3, 7, 16, 1000, 32767):
        np.testing.assert_array_equal(
            np.asarray(ht.pair_mod(pairs, g)), ids % g)
    with pytest.raises(ValueError, match="shard count"):
        ht.pair_mod(pairs, 1 << 15)


@pytest.mark.slow
def test_pallas_probe_gather_parity():
    """Fused Pallas probe+gather (interpret mode) matches find_rows+take.

    Covers hits, misses, and the EMPTY sentinel. The kernel is the native
    form of the reference's probe-and-copy pull loop
    (EmbeddingPullOperator.cpp:149-252); on current v5e it is DMA-issue-rate
    bound and the bucket-row XLA probe is the default — the kernel stays as
    the measured alternative (see bench_suite.json pallas_probe note).
    """
    from openembedding_tpu.ops import pallas_hash as ph
    cap, dim = 2048, 128
    rng = np.random.RandomState(3)
    empty = ht.empty_key(jnp.int32)
    tk = jnp.full((cap,), empty, jnp.int32)
    nk = jnp.asarray(rng.randint(1, 1 << 30, size=700).astype(np.int32))
    tk, slot, ins, failed = ht.find_or_insert(tk, nk, nk != empty)
    assert int(failed.sum()) == 0
    weights = jnp.asarray(rng.randn(cap, dim).astype(np.float32))
    q = jnp.concatenate([
        nk[:300],
        jnp.asarray(rng.randint(1 << 30, 1 << 31, size=60, dtype=np.int32)),
        jnp.asarray([empty], jnp.int32)])
    bsz, nb, chain = ht.table_layout(cap, ht.DEFAULT_MAX_PROBES)
    starts = ht.probe_starts(q, cap, ht.DEFAULT_MAX_PROBES)
    rows, hit = ph.probe_gather(tk, weights, starts, q, chain=chain,
                                bucket=bsz, empty=empty, interpret=True)
    slots = ht.find_rows(tk, q)
    want_hit = np.asarray(slots) >= 0
    np.testing.assert_array_equal(np.asarray(hit), want_hit)
    want = np.where(want_hit[:, None],
                    np.asarray(weights)[np.maximum(np.asarray(slots), 0)],
                    0.0)
    np.testing.assert_array_equal(np.asarray(rows), want)


def test_wide_keys_full_width_without_x64():
    """64-bit key space in a DEFAULT (x64-off) process: keys are [n, 2]
    int32 (lo, hi) pairs, so ids that differ only above bit 31 must map to
    distinct rows — the aliasing an int32 table would silently commit.
    Covers the reference's 2^62 hashed key space
    (criteo_deepctr.py to_hash_bucket_fast(2**62)) without the global flag.
    """
    assert not jax.config.jax_enable_x64
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=2**63)
    opt = make_optimizer({"category": "sgd", "learning_rate": 1.0})
    init = {"category": "constant", "value": 0.0}
    t = ht.create_hash_table(meta, opt, capacity=1024, key_width=64)
    assert t.wide and t.keys.shape == (1024, 2)

    # keys congruent mod 2^32: identical lo words, distinct hi words
    base = np.asarray([12345, 12345 + (1 << 32), 12345 + (5 << 40),
                       -(7 << 35) + 12345], np.int64)
    pairs = jnp.asarray(ht.split64(base))
    assert np.asarray(pairs[:, 0]).tolist() == [np.int32(12345)] * 4
    g = jnp.asarray(np.arange(1, 5, dtype=np.float32))[:, None] * \
        jnp.ones((4, DIM), jnp.float32)
    t = ht.apply_gradients(t, opt, init, pairs, g)
    assert int(t.insert_failures) == 0
    assert int(t.num_used()) == 4  # four distinct rows, no aliasing
    rows = np.asarray(ht.pull(t, pairs, None))
    np.testing.assert_allclose(rows[:, 0], [-1.0, -2.0, -3.0, -4.0],
                               rtol=1e-6)

    # round-trip through the host helpers
    np.testing.assert_array_equal(ht.join64(ht.split64(base)), base)

    # duplicate pairs combine exactly once per key (pair dedup)
    dup = jnp.asarray(ht.split64(np.asarray(
        [99, 99 + (1 << 32), 99, 99 + (1 << 32)], np.int64)))
    t = ht.apply_gradients(t, opt, init, dup,
                           jnp.ones((4, DIM), jnp.float32))
    rows = np.asarray(ht.pull(t, dup[:2], None))
    # sgd with count semantics: grads summed per unique key
    np.testing.assert_allclose(rows[:, 0], -2.0, rtol=1e-6)

    # pull of an absent wide key returns the deterministic init row (zeros
    # under constant-0) and EMPTY-hi pairs return zeros
    probe = jnp.asarray(ht.split64(np.asarray([424242 + (9 << 33)],
                                              np.int64)))
    np.testing.assert_allclose(np.asarray(ht.pull(t, probe, init)), 0.0)

    # wide tables refuse narrow queries ([B, F] narrow-table indices are
    # legitimately any-shape, so only the wide side can police shapes)
    with pytest.raises(ValueError, match="key-shape mismatch"):
        ht.pull(t, jnp.asarray([1, 2], jnp.int32), None)


def test_wide_keys_insert_rows_and_find():
    """Load-path delivery + find on a wide-key table."""
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=2**63)
    opt = make_optimizer({"category": "default"})
    t = ht.create_hash_table(meta, opt, capacity=256, key_width=64)
    k64 = np.asarray([7, 7 + (1 << 32), (3 << 45) + 1], np.int64)
    pairs = jnp.asarray(ht.split64(k64))
    w = jnp.asarray(np.eye(3, DIM, dtype=np.float32) * 5.0)
    t = ht.insert_rows(t, pairs, w)
    assert int(t.insert_failures) == 0
    slots = ht.find_rows(t.keys, pairs)
    assert (np.asarray(slots) >= 0).all()
    assert len(set(np.asarray(slots).tolist())) == 3
    got = np.asarray(ht.pull(t, pairs, None))
    np.testing.assert_allclose(got, np.asarray(w), rtol=1e-6)
