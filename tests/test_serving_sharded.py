"""Sharded multi-process serving: shard groups x replicas.

The reference serves models LARGER than one process by placing shard x
replica over PS nodes: every variable's key space is partitioned across
server processes and a pull fans out per-shard requests
(/root/reference/openembedding/client/Model.cpp:153-186). Here: G serving
processes each load the slice ids/keys ≡ k (mod G) of the checkpoint, a
ShardedRoutingClient partitions lookups by owner and merges rows, and each
shard group carries its own replicas for HA (killing one replica of a group
keeps service alive via its peer — the chaos invariant per group).
"""

import signal
import socket
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
from openembedding_tpu import checkpoint as ckpt
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.serving import ha

DIM = 4
VOCAB = 64
SIGN = "sharded-model-1"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def sharded_model(tmp_path_factory, devices8):
    """Checkpoint with row-distinguishable values + the expected rows."""
    path = str(tmp_path_factory.mktemp("sharded") / "model")
    mesh = create_mesh(1, 1, jax.devices()[:1])
    specs = (
        EmbeddingSpec(name="emb", input_dim=VOCAB, output_dim=DIM,
                      initializer={"category": "normal", "stddev": 1.0}),
        EmbeddingSpec(name="hsh", input_dim=-1, output_dim=DIM,
                      hash_capacity=512,
                      initializer={"category": "constant", "value": 0.0},
                      optimizer={"category": "sgd", "learning_rate": 1.0}),
    )
    coll = EmbeddingCollection(specs, mesh)
    states = coll.init(jax.random.PRNGKey(3))
    # make hash rows exist with value -key/100 (sgd on constant grads)
    hkeys = jnp.asarray(np.arange(1, 41, dtype=np.int32))
    rows = coll.pull(states, {"hsh": hkeys}, batch_sharded=False)
    g = jnp.broadcast_to((np.arange(1, 41, dtype=np.float32) / 100.0)
                         [:, None], rows["hsh"].shape)
    states = coll.apply_gradients(states, {"hsh": hkeys}, {"hsh": g},
                                  batch_sharded=False)
    ckpt.save_checkpoint(path, coll, states, model_sign=SIGN)
    allv = jnp.arange(VOCAB, dtype=jnp.int32)
    want_emb = np.asarray(
        coll.pull(states, {"emb": allv}, batch_sharded=False)["emb"])
    want_hsh = np.asarray(
        coll.pull(states, {"hsh": hkeys}, batch_sharded=False,
                  read_only=True)["hsh"])
    return path, want_emb, want_hsh


def _cleanup(procs):
    for p in procs.values():
        if p and p.poll() is None:
            p.kill()


def _tail(proc, n=20):
    try:
        out = proc.stdout.read() if proc.poll() is not None else ""
    except Exception:  # noqa: BLE001
        out = ""
    return "\n".join((out or "").splitlines()[-n:])


def _lookup_retry(fn, deadline_s=60.0):
    deadline = time.time() + deadline_s
    while True:
        try:
            return fn()
        except ConnectionError as e:
            if "timed out" not in str(e) or time.time() >= deadline:
                raise
            time.sleep(0.5)


WSIGN = "sharded-wide-1"


@pytest.fixture(scope="module")
def wide_sharded_model(tmp_path_factory):
    """Checkpoint holding a WIDE (64-bit pair) hash variable with
    row-distinguishable values + the expected rows."""
    from openembedding_tpu import hash_table as hl
    path = str(tmp_path_factory.mktemp("wsharded") / "model")
    mesh = create_mesh(1, 1, jax.devices()[:1])
    spec = EmbeddingSpec(name="wh", input_dim=-1, output_dim=DIM,
                         hash_capacity=512, key_dtype="wide",
                         initializer={"category": "constant", "value": 0.0},
                         optimizer={"category": "sgd", "learning_rate": 1.0})
    coll = EmbeddingCollection((spec,), mesh)
    states = coll.init(jax.random.PRNGKey(7))
    # 2^62-scale keys, some differing ONLY in the hi word — with G=3 the
    # owner depends on both words (2^32 % 3 == 1), so routing must join
    keys64 = np.concatenate([
        (3 << 60) + np.arange(1, 21, dtype=np.int64),
        (3 << 60) + (np.arange(1, 21, dtype=np.int64) << 32)])
    pairs = jnp.asarray(hl.split64(keys64))
    rows = coll.pull(states, {"wh": pairs}, batch_sharded=False)
    g = jnp.broadcast_to((np.arange(1, 41, dtype=np.float32) / 100.0)
                         [:, None], rows["wh"].shape)
    states = coll.apply_gradients(states, {"wh": pairs}, {"wh": g},
                                  batch_sharded=False)
    ckpt.save_checkpoint(path, coll, states, model_sign=WSIGN)
    want = np.asarray(coll.pull(states, {"wh": pairs}, batch_sharded=False,
                                read_only=True)["wh"])
    return path, keys64, want


@pytest.mark.slow
def test_wide_key_shard_groups(wide_sharded_model):
    """Shard-sliced serving of a WIDE-key model: G=3 groups each load the
    slice ``joined_id % 3 == k`` of a 2^62-key-space dump; the router
    partitions pair queries by the same joined-owner rule and merges —
    the at-scale combination (full 64-bit key space AND model larger than
    one process; reference places ANY model sharded,
    client/Model.cpp:153-186)."""
    from openembedding_tpu import hash_table as hl
    path, keys64, want = wide_sharded_model
    G = 3
    ports = [_free_port() for _ in range(G)]
    eps = [f"127.0.0.1:{p}" for p in ports]
    procs = {}
    try:
        for k in range(G):
            procs[k] = ha.spawn_replica(
                ports[k], load=[f"{WSIGN}={path}"],
                shard_index=k, shard_count=G)
        for k in range(G):
            assert ha.wait_ready(eps[k], sign=WSIGN, timeout=180.0), \
                _tail(procs[k])

        router = ha.ShardedRoutingClient([[e] for e in eps], timeout=15.0)
        pairs = hl.split64(keys64)
        got = _lookup_retry(
            lambda: router.lookup(WSIGN, "wh", pairs, wide=True))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
        # batch-shaped pair queries keep their leading shape
        got2 = _lookup_retry(lambda: router.lookup(
            WSIGN, "wh", pairs.reshape(8, 5, 2), wide=True))
        np.testing.assert_allclose(got2, want.reshape(8, 5, DIM),
                                   rtol=1e-6, atol=1e-7)

        # every group holds a nonempty slice, and each process holds ONLY
        # its slice: probing group k directly with a non-owned pair gives
        # a zero row (the in-process joined-owner filter)
        owners = keys64 % G
        assert set(owners.tolist()) == set(range(G))
        for k in range(G):
            other = np.nonzero(owners != k)[0][0]
            solo = ha.RoutingClient([eps[k]], timeout=15.0)
            direct = _lookup_retry(
                lambda: solo.lookup(WSIGN, "wh", pairs[[other]]))
            np.testing.assert_array_equal(direct, 0.0)
            mine = np.nonzero(owners == k)[0][0]
            direct = _lookup_retry(
                lambda: solo.lookup(WSIGN, "wh", pairs[[mine]]))
            np.testing.assert_allclose(direct, want[[mine]], rtol=1e-6,
                                       atol=1e-7)

        # kill one group: ITS keys fail (outage, not silent zeros);
        # the surviving groups keep serving theirs
        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait()
        dead = np.nonzero(owners == 0)[0]
        live = np.nonzero(owners != 0)[0]
        with pytest.raises(ConnectionError):
            router.lookup(WSIGN, "wh", pairs[dead[:1]], wide=True)
        got3 = _lookup_retry(
            lambda: router.lookup(WSIGN, "wh", pairs[live], wide=True))
        np.testing.assert_allclose(got3, want[live], rtol=1e-6, atol=1e-7)
    finally:
        _cleanup(procs)


# slow: 4 replica subprocesses (~28s) — the cross-group routed lookup
# itself stays tier-1 via test_serving_trace.py's sharded-trace test
@pytest.mark.slow
def test_shard_groups_with_replicas(sharded_model):
    path, want_emb, want_hsh = sharded_model
    G, R = 2, 2
    ports = [[_free_port() for _ in range(R)] for _ in range(G)]
    eps = [[f"127.0.0.1:{p}" for p in row] for row in ports]
    procs = {}
    try:
        for k in range(G):
            for r in range(R):
                procs[(k, r)] = ha.spawn_replica(
                    ports[k][r], load=[f"{SIGN}={path}"],
                    shard_index=k, shard_count=G)
        for k in range(G):
            for r in range(R):
                assert ha.wait_ready(eps[k][r], sign=SIGN), \
                    _tail(procs[(k, r)])

        router = ha.ShardedRoutingClient(eps, timeout=15.0)

        # full-vocab lookup through the router == the source model
        got = _lookup_retry(
            lambda: router.lookup(SIGN, "emb", np.arange(VOCAB)))
        np.testing.assert_allclose(got, want_emb, rtol=1e-6, atol=1e-7)
        # hash variable: keys of both parities resolve through their owners
        hkeys = np.arange(1, 41, dtype=np.int32)
        got_h = _lookup_retry(lambda: router.lookup(SIGN, "hsh", hkeys))
        np.testing.assert_allclose(got_h, want_hsh, rtol=1e-6, atol=1e-7)

        # each process holds ONLY its slice: a direct probe of a group-1
        # endpoint with a group-0-owned id returns a zero row
        solo = ha.RoutingClient([eps[1][0]], timeout=15.0)
        direct = _lookup_retry(lambda: solo.lookup(SIGN, "emb", [2]))
        np.testing.assert_array_equal(direct, 0.0)
        # /health reports the shard geometry
        from openembedding_tpu.serving.rest import probe_health
        h = probe_health(eps[1][0], timeout=10.0)
        m = [x for x in h["models"] if x["model_sign"] == SIGN][0]
        assert (m["shard_index"], m["shard_count"]) == (1, G)

        # chaos: kill one replica of group 0 — its peer keeps the group
        # alive, service stays correct end-to-end
        procs[(0, 0)].send_signal(signal.SIGKILL)
        procs[(0, 0)].wait()
        for _ in range(3):
            got = _lookup_retry(
                lambda: router.lookup(SIGN, "emb", np.arange(VOCAB)))
            np.testing.assert_allclose(got, want_emb, rtol=1e-6, atol=1e-7)

        # kill the group's LAST replica: lookups hitting shard 0 now fail —
        # per-group replica exhaustion is an outage, not silent zeros
        procs[(0, 1)].send_signal(signal.SIGKILL)
        procs[(0, 1)].wait()
        with pytest.raises(ConnectionError):
            router.lookup(SIGN, "emb", np.asarray([0]))  # shard-0-owned
        # shard 1 ids still serve
        got1 = _lookup_retry(
            lambda: router.lookup(SIGN, "emb", np.asarray([1, 3])))
        np.testing.assert_allclose(got1, want_emb[[1, 3]], rtol=1e-6,
                                   atol=1e-7)
    finally:
        _cleanup(procs)


@pytest.mark.slow
def test_pooled_wide_spec_serves_rows(tmp_path_factory):
    """Regression (advisor r4): a POOLED wide spec must serve with ROW
    semantics. The routing plane always fans out flat ``[n, 2]`` pair
    queries (ShardedRoutingClient.lookup reshapes every wide query to
    ``[-1, 2]``); the training-side widen heuristic treats pairs on a
    pooled spec as pairs only at ndim >= 3, so without the serving
    override those queries were widened to ``[n, 2, 2]``, each 32-bit
    WORD looked up as an independent key, owner-filtered wrongly, and
    pooled — silently wrong embeddings. Here: per-pair rows must come
    back unpooled, shard-filtered by the JOINED id."""
    from openembedding_tpu import hash_table as hl
    from openembedding_tpu.serving.registry import ModelRegistry

    path = str(tmp_path_factory.mktemp("pooledwide") / "model")
    mesh = create_mesh(1, 1, jax.devices()[:1])
    psign = "pooled-wide-1"
    spec = EmbeddingSpec(
        name="seq", input_dim=-1, output_dim=DIM, hash_capacity=512,
        key_dtype="wide", pooling="mean",
        initializer={"category": "normal", "stddev": 1.0},
        optimizer={"category": "sgd", "learning_rate": 1.0})
    coll = EmbeddingCollection((spec,), mesh)
    states = coll.init(jax.random.PRNGKey(11))
    # 2^62-scale keys, some differing only in the hi word; materialize
    # their rows through the POOLED training pull ([B, L, 2] sequences)
    keys64 = np.concatenate([
        (3 << 60) + np.arange(1, 13, dtype=np.int64),
        (3 << 60) + (np.arange(1, 13, dtype=np.int64) << 32)])
    seq = jnp.asarray(hl.split64(keys64).reshape(4, 6, 2))
    pooled = coll.pull(states, {"seq": seq}, batch_sharded=False)["seq"]
    assert pooled.shape == (4, DIM)  # the training contract still pools
    # rows materialize on the UPDATE (deferred per-key init); pooled specs
    # push [B, dim] grads which the pooling VJP expands per slot
    g = jnp.asarray(np.arange(1, 4 * DIM + 1, dtype=np.float32)
                    .reshape(4, DIM))
    states = coll.apply_gradients(states, {"seq": seq}, {"seq": g},
                                  batch_sharded=False)
    ckpt.save_checkpoint(path, coll, states, model_sign=psign)

    # ground truth per-key rows via a non-pooled twin of the same dump
    twin = EmbeddingCollection(
        (EmbeddingSpec(name="seq", input_dim=-1, output_dim=DIM,
                       hash_capacity=512, key_dtype="wide",
                       initializer={"category": "constant", "value": 0.0},
                       optimizer={"category": "sgd", "learning_rate": 1.0}),),
        mesh)
    tstates = ckpt.load_checkpoint(path, twin)
    pairs = hl.split64(keys64)
    want = np.asarray(twin.pull(tstates, {"seq": jnp.asarray(pairs)},
                                batch_sharded=False, read_only=True)["seq"])
    assert float(np.abs(want).max()) > 0  # rows really exist

    # un-sharded serving: flat pair list -> one row per pair, no pooling
    reg = ModelRegistry(mesh, default_hash_capacity=512)
    reg.create_model(path, model_sign=psign)
    got = np.asarray(reg.find_model(psign).lookup("seq", pairs))
    assert got.shape == (len(keys64), DIM)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
    # ... while SEQUENCE-shaped queries ([B, L, 2]) keep the training
    # contract: pooled [B, dim] (all slots valid here)
    got_seq = np.asarray(reg.find_model(psign).lookup(
        "seq", pairs.reshape(4, 6, 2)))
    np.testing.assert_allclose(
        got_seq, want.reshape(4, 6, DIM).mean(axis=1), rtol=1e-5,
        atol=1e-6)

    # shard-sliced serving (G=3 exercises hi-word-dependent owners):
    # each slice returns ITS rows and zeros elsewhere; slices partition
    G = 3
    owners = keys64 % G
    total = np.zeros_like(want)
    for k in range(G):
        regk = ModelRegistry(mesh, default_hash_capacity=512)
        regk.create_model(path, model_sign=psign,
                          shard_index=k, shard_count=G)
        gotk = np.asarray(regk.find_model(psign).lookup("seq", pairs))
        assert gotk.shape == (len(keys64), DIM)
        np.testing.assert_allclose(gotk[owners == k], want[owners == k],
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_array_equal(gotk[owners != k], 0.0)
        total += gotk
    np.testing.assert_allclose(total, want, rtol=1e-6, atol=1e-7)
