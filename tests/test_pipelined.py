"""Pipelined exchange plane (``parallel/pipelined.py``).

``plane="a2a+pipelined"`` must be BIT-IDENTICAL to ``"a2a"``: the step
program re-cuts the schedule (dense on the prefetched buffer, push,
prefetch pull for the next batch) but the op order on every table is the
serial plane's order — the reference's per-batch version barrier as an
op dependency. The parity matrix drives full Trainers on identical data
+ seeds across zipf/uniform x array/hash32/wide x a pooled member, with
eval interleaved mid-run, a mid-epoch drain, and a lookahead miss (no
``next_batch``) inside every cell — every drain point must agree
exactly. The overlap contract tests pin the scheduling property
(``analysis/contracts.check_overlap``) positively and negatively.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from openembedding_tpu import EmbeddingCollection, EmbeddingSpec, Trainer
from openembedding_tpu import hash_table as hash_lib
from openembedding_tpu.analysis import contracts
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.utils import observability

OPT = {"category": "adagrad", "learning_rate": 0.1}
INIT = {"category": "constant", "value": 0.25}
B, L = 32, 4


class TinyModel(nn.Module):
    """Concat rows -> one Dense: real dots for the overlap schedule."""

    names: tuple

    @nn.compact
    def __call__(self, dense, rows):
        x = jnp.concatenate(
            [rows[n].reshape(rows[n].shape[0], -1) for n in self.names],
            axis=-1)
        if dense is not None:
            x = jnp.concatenate([x, dense], axis=-1)
        return nn.Dense(1)(x).reshape(-1)


def _specs(kind, plane):
    """Three tables: mixed dims + a pooled member, like the grouped
    plane's matrix — the pooled VJP and the dim variety both ride the
    prefetched buffer."""
    common = dict(optimizer=OPT, initializer=INIT, plane=plane)
    if kind == "array":
        return (
            EmbeddingSpec(name="t3", input_dim=64, output_dim=3, **common),
            EmbeddingSpec(name="t6", input_dim=48, output_dim=6, **common),
            EmbeddingSpec(name="tp", input_dim=64, output_dim=3,
                          pooling="mean", **common),
        )
    key_dtype = "int32" if kind == "hash32" else "wide"
    hk = dict(input_dim=-1, hash_capacity=4096, key_dtype=key_dtype,
              **common)
    return (
        EmbeddingSpec(name="t3", output_dim=3, **hk),
        EmbeddingSpec(name="t6", output_dim=6, **hk),
        EmbeddingSpec(name="tp", output_dim=3, pooling="sum", **hk),
    )


def _draw(rng, dist, hi, size):
    if dist == "uniform":
        return rng.randint(0, hi, size).astype(np.int64)
    ranks = np.arange(1, hi + 1, dtype=np.float64)
    probs = ranks ** -1.1
    probs /= probs.sum()
    return rng.choice(hi, size=size, p=probs).astype(np.int64)


def _batch(rng, kind, dist):
    """One labeled batch; array streams include out-of-range ids (the
    per-table path zero-rows them and the prefetched buffer must too).
    Hash ids stay < 2^31: Trainer.shard_batch narrows host columns to
    int32 before the on-device widening, identically on both planes."""
    if kind == "array":
        sparse = {"t3": _draw(rng, dist, 64, B).astype(np.int32),
                  "t6": _draw(rng, dist, 48, B).astype(np.int32)}
        sparse["t3"][::7] = -1
        sparse["t6"][1::9] = 48 + 5
        pool = _draw(rng, dist, 64, (B, L)).astype(np.int32)
        pool[:, -1] = -1
        sparse["tp"] = pool
    else:
        sparse = {n: _draw(rng, dist, 100_000, B).astype(np.int32)
                  for n in ("t3", "t6")}
        pool = _draw(rng, dist, 100_000, (B, L)).astype(np.int32)
        pool[:, -1] = np.int32(hash_lib.empty_key(np.int32))
        sparse["tp"] = pool
    return {"label": (rng.rand(B) > 0.5).astype(np.float32),
            "dense": rng.randn(B, 2).astype(np.float32),
            "sparse": sparse}


def _make_trainer(kind, plane, mesh):
    coll = EmbeddingCollection(_specs(kind, plane), mesh)
    return coll, Trainer(TinyModel(names=("t3", "t6", "tp")), coll,
                         optax.sgd(0.1))


def _assert_state_equal(sp, sa, kind, msg):
    for n in ("t3", "t6", "tp"):
        np.testing.assert_array_equal(
            np.asarray(sp[n].weights), np.asarray(sa[n].weights),
            err_msg=f"{msg}:{n}:weights")
        for slot in sp[n].slots:
            np.testing.assert_array_equal(
                np.asarray(sp[n].slots[slot]),
                np.asarray(sa[n].slots[slot]),
                err_msg=f"{msg}:{n}:{slot}")
        if kind != "array":
            assert int(sp[n].insert_failures) == \
                int(sa[n].insert_failures), n


def _run_plane(kind, plane, mesh, batches, evals):
    """Drive one Trainer over ``batches`` with the pipelined call
    pattern: lookahead next_batch, an eval interleaved after step 1 (no
    drain — the tables are authoritative every step), a DRAIN after
    step 2, and a lookahead MISS (no next_batch) on the last step."""
    coll, trainer = _make_trainer(kind, plane, mesh)
    state = trainer.init(jax.random.PRNGKey(1),
                         trainer.shard_batch(batches[0]))
    losses, scores = [], []
    for i, b in enumerate(batches):
        nxt = batches[i + 1] if i + 1 < len(batches) else None
        state, m = trainer.train_step(state, b, next_batch=nxt)
        losses.append(float(m["loss"]))
        if i == 1:
            scores.append(np.asarray(trainer.eval_step(state, evals[0])))
        if i == 2 and hasattr(trainer, "drain_pipeline"):
            state = trainer.drain_pipeline(state)
            assert state.pipe is None
    scores.append(np.asarray(trainer.eval_step(state, evals[1])))
    return losses, scores, state


# two cells ride tier-1 (the two exchange encodings); the re-compiled
# rest (same code paths, different key streams) rides the slow lane
_MATRIX = [("array", "zipf"), ("wide", "zipf"),
           pytest.param("hash32", "uniform", marks=pytest.mark.slow),
           pytest.param("array", "uniform", marks=pytest.mark.slow),
           pytest.param("hash32", "zipf", marks=pytest.mark.slow),
           pytest.param("wide", "uniform", marks=pytest.mark.slow)]


@pytest.mark.parametrize("kind,dist", _MATRIX)
def test_pipelined_matches_a2a(devices8, kind, dist):
    mesh = create_mesh(2, 4, devices8)
    rng = np.random.RandomState(7)
    batches = [_batch(rng, kind, dist) for _ in range(5)]
    evals = [_batch(rng, kind, dist) for _ in range(2)]
    la, ea, sa = _run_plane(kind, "a2a", mesh, batches, evals)
    lp, ep, sp = _run_plane(kind, "a2a+pipelined", mesh, batches, evals)
    assert lp == la, f"{kind}/{dist}: loss trajectories differ"
    for i, (p, a) in enumerate(zip(ep, ea)):
        np.testing.assert_array_equal(p, a, err_msg=f"eval[{i}]")
    _assert_state_equal(sp.emb, sa.emb, kind, f"{kind}/{dist}")


@pytest.mark.slow
def test_pipelined_composes_with_grouped(devices8):
    """``a2a+grouped+pipelined``: the prefetched exchange batches into
    ONE collective round per group. Pipelining adds NOTHING to the
    numbers: bit-identical to the serial grouped plane, and within the
    grouped plane's own documented float-summation-order tolerance of
    plain a2a."""
    mesh = create_mesh(2, 4, devices8)
    rng = np.random.RandomState(3)
    batches = [_batch(rng, "array", "zipf") for _ in range(4)]
    evals = [_batch(rng, "array", "zipf") for _ in range(2)]
    coll = EmbeddingCollection(_specs("array", "a2a+grouped+pipelined"),
                               mesh)
    assert coll.pipelined_names() == ("t3", "t6", "tp")
    assert coll.grouped_names() == ("t3", "t6", "tp")
    lg, eg, sg = _run_plane("array", "a2a+grouped", mesh, batches, evals)
    lp, ep, sp = _run_plane("array", "a2a+grouped+pipelined", mesh,
                            batches, evals)
    assert lp == lg, "pipelining changed the grouped plane's numbers"
    for p, g in zip(ep, eg):
        np.testing.assert_array_equal(p, g)
    _assert_state_equal(sp.emb, sg.emb, "array", "grouped+pipelined")
    la, _ea, sa = _run_plane("array", "a2a", mesh, batches, evals)
    np.testing.assert_allclose(lp, la, rtol=1e-5, atol=1e-6)
    for n in ("t3", "t6", "tp"):
        np.testing.assert_allclose(
            np.asarray(sp.emb[n].weights), np.asarray(sa.emb[n].weights),
            rtol=1e-5, atol=1e-6, err_msg=f"vs-a2a:{n}")


@pytest.mark.slow
def test_pipelined_mixed_with_serial_planes(devices8):
    """A model mixing pipelined, plain-a2a and psum variables: the
    pipelined members prefetch, the rest keep their in-step pull, and
    the whole model matches the all-a2a baseline exactly."""
    mesh = create_mesh(2, 4, devices8)
    rng = np.random.RandomState(5)
    batches = [_batch(rng, "array", "zipf") for _ in range(4)]
    evals = [_batch(rng, "array", "zipf") for _ in range(2)]

    def mixed_specs():
        a, b, c = _specs("array", "a2a")
        import dataclasses
        return (dataclasses.replace(a, plane="a2a+pipelined"),
                dataclasses.replace(b, plane="psum"), c)

    la, ea, sa = _run_plane("array", "a2a", mesh, batches, evals)
    coll = EmbeddingCollection(mixed_specs(), mesh)
    assert coll.pipelined_names() == ("t3",)
    trainer = Trainer(TinyModel(names=("t3", "t6", "tp")), coll,
                      optax.sgd(0.1))
    state = trainer.init(jax.random.PRNGKey(1),
                         trainer.shard_batch(batches[0]))
    losses = []
    for i, b in enumerate(batches):
        nxt = batches[i + 1] if i + 1 < len(batches) else None
        state, m = trainer.train_step(state, b, next_batch=nxt)
        losses.append(float(m["loss"]))
    # the psum member reduces duplicate grads in a different order than
    # the routed exchange — allclose like the plane_parity bench, while
    # the PIPELINED member stays exact by construction
    np.testing.assert_allclose(losses, la, rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(state.emb["t3"].weights),
        np.asarray(sa.emb["t3"].weights), err_msg="mixed:t3")
    np.testing.assert_allclose(
        np.asarray(state.emb["tp"].weights),
        np.asarray(sa.emb["tp"].weights), rtol=1e-5, atol=1e-6,
        err_msg="mixed:tp")
    # the psum member stores rows in a DIFFERENT physical shard order
    # (4 model shards vs 8 grid shards) — compare in logical id space
    # via a full-vocab probe pull, like the grouped plane's psum cell
    ca = EmbeddingCollection(_specs("array", "a2a"), mesh)
    probe = {"t6": np.arange(48, dtype=np.int32)}
    mine = coll.pull(state.emb, probe, batch_sharded=False)["t6"]
    ref = ca.pull(sa.emb, probe, batch_sharded=False)["t6"]
    np.testing.assert_allclose(np.asarray(mine), np.asarray(ref),
                               rtol=1e-5, atol=1e-6, err_msg="mixed:t6")


def test_pipelined_fit_is_compile_free_after_warmup(devices8):
    """RetraceGuard proof: the steady pipelined loop (fit's lookahead
    feeding the prefetch) compiles nothing after the 2-step warmup."""
    mesh = create_mesh(2, 4, devices8)
    rng = np.random.RandomState(2)
    batches = [_batch(rng, "array", "uniform") for _ in range(8)]
    coll, trainer = _make_trainer("array", "a2a+pipelined", mesh)
    state = trainer.init(jax.random.PRNGKey(1),
                         trainer.shard_batch(batches[0]))
    observability.GLOBAL.reset()
    state, last = trainer.fit(state, batches, retrace_budget=0)
    assert last is not None and np.isfinite(last["loss"])
    # the lookahead fed every step: exactly ONE prime (the warmup
    # prologue) — a growing count would mean identity-keyed misses
    # paying a double exchange per step
    snap = observability.GLOBAL.snapshot()
    assert snap.get("pipeline_primes", {}).get("count", 0) == 1
    observability.GLOBAL.reset()


def test_plane_timings_overlap_attribution(devices8):
    """Pipelined dispatch records WHOLE-STEP wall time (step_ms) — the
    in-program pull/push host timers must stay silent (no
    double-counting under the outer jit) — and overlap_hidden_ms joins
    once eager stage samples exist."""
    mesh = create_mesh(2, 4, devices8)
    rng = np.random.RandomState(4)
    batches = [_batch(rng, "array", "uniform") for _ in range(3)]
    coll, trainer = _make_trainer("array", "a2a+pipelined", mesh)
    state = trainer.init(jax.random.PRNGKey(1),
                         trainer.shard_batch(batches[0]))
    observability.GLOBAL.reset()
    observability.set_evaluate_performance(True)
    try:
        for i, b in enumerate(batches):
            nxt = batches[i + 1] if i + 1 < len(batches) else None
            state, _ = trainer.train_step(state, b, next_batch=nxt)
        t = observability.plane_timings()["a2a+pipelined"]
        # the warmup prologue primes ONCE (one eager pull per table);
        # the steady steps dispatch pull/push inside the jitted program
        # where the stage timers must not record
        assert t["step_calls"] == len(batches)
        assert t.get("pull_calls", 0) == 3
        assert "push_calls" not in t
        assert "overlap_hidden_ms" not in t
        # eager stage isolation (the bench measurement surface)
        # completes the split and unlocks the overlap estimate
        sb = trainer.shard_batch(batches[0])
        rows = coll.pull(state.emb, sb["sparse"])
        jax.block_until_ready(jax.tree.leaves(rows))
        emb2 = coll.apply_gradients(state.emb, sb["sparse"], rows)
        jax.block_until_ready(jax.tree.leaves(emb2))
        t = observability.plane_timings()["a2a+pipelined"]
        assert t["push_calls"] >= 1
        assert "overlap_hidden_ms" in t
        # the estimate is the per-step serial stage WALL (total across
        # every table's dispatch, normalized by step_calls — per-
        # dispatch averages alone would omit all tables but one) minus
        # the fused step: positive = exchange wall off the critical path
        stage_total = t["pull_ms"] * t["pull_calls"] \
            + t["push_ms"] * t["push_calls"]
        assert abs(t["stage_serial_ms"]
                   - stage_total / t["step_calls"]) < 1e-9
        assert abs(t["overlap_hidden_ms"]
                   - (t["stage_serial_ms"] - t["step_ms"])) < 1e-9
    finally:
        observability.set_evaluate_performance(False)
        observability.GLOBAL.reset()


# --- overlap contract --------------------------------------------------------

_SYNTHETIC_HLO = """\
HloModule jit_step, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias) }

%fused_dense (p0: f32[8,8], p1: f32[8,8]) -> f32[8,8] {
  %p0 = f32[8,8]{1,0} parameter(0)
  %p1 = f32[8,8]{1,0} parameter(1)
  ROOT %dot.1 = f32[8,8]{1,0} dot(f32[8,8]{1,0} %p0, f32[8,8]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}

ENTRY %main (arg0: f32[8,8], arg1: s32[64], arg2: f32[8,8]) -> (f32[8,8], f32[64,8]) {
  %arg0 = f32[8,8]{1,0} parameter(0)
  %arg1 = s32[64]{0} parameter(1)
  %arg2 = f32[8,8]{1,0} parameter(2)
  %keys = s32[64]{0} bitcast(s32[64]{0} %arg1)
  %a2a.pull = s32[64]{0} all-to-all(s32[64]{0} %keys), channel_id=1, metadata={op_name="jit(step)/jit(pull_a2a_pipelined)/all_to_all"}
  %dense = f32[8,8]{1,0} fusion(f32[8,8]{1,0} %arg0, f32[8,8]{1,0} %arg2), kind=kOutput, calls=%fused_dense, metadata={op_name="jit(step)/jit(main)/dot"}
  %a2a.push = f32[8,8]{1,0} all-to-all(f32[8,8]{1,0} %dense), channel_id=2, metadata={op_name="jit(step)/jit(push_a2a_pipelined)/all_to_all"}
  %rows = f32[64,8]{1,0} broadcast(f32[8,8]{1,0} %a2a.push), dimensions={0,1}
  ROOT %out = (f32[8,8]{1,0}, f32[64,8]{1,0}) tuple(f32[8,8]{1,0} %a2a.push, f32[64,8]{1,0} %rows)
}
"""


def test_analyze_overlap_synthetic():
    """Parser unit: scopes, taint and the violation axes on a
    hand-written module (no compile)."""
    r = contracts.analyze_overlap(_SYNTHETIC_HLO)
    assert r.pull_exchanges == 1 and r.free_pull_exchanges == 1
    assert r.push_exchanges == 1 and r.committed_push_exchanges == 1
    assert r.dense_nodes == 1 and r.dense_waiting_on_exchange == 0
    contracts.check_overlap(_SYNTHETIC_HLO, "synthetic")
    # dense consuming the pull = the serial shape
    serial = _SYNTHETIC_HLO.replace(
        "fusion(f32[8,8]{1,0} %arg0, f32[8,8]{1,0} %arg2)",
        "fusion(f32[8,8]{1,0} %arg0, f32[8,8]{1,0} %a2a.pull)")
    with pytest.raises(contracts.ContractViolation, match="wait on"):
        contracts.check_overlap(serial, "serial")
    # prefetch keys fed from the dense output = forced serialization
    forced = _SYNTHETIC_HLO.replace(
        "all-to-all(s32[64]{0} %keys)",
        "all-to-all(f32[8,8]{1,0} %dense)")
    with pytest.raises(contracts.ContractViolation,
                       match="serialized behind"):
        contracts.check_overlap(forced, "forced")
    # a lost push commit
    nopush = _SYNTHETIC_HLO.replace(
        "all-to-all(f32[8,8]{1,0} %dense)",
        "all-to-all(f32[8,8]{1,0} %arg2)")
    with pytest.raises(contracts.ContractViolation, match="commit"):
        contracts.check_overlap(nopush, "nopush")


def test_pipelined_step_overlap_contract(devices8):
    """THE plane's acceptance audit: the real compiled step program
    passes the registered overlap contract (free prefetch key legs,
    committed push, dense never waiting, donation honored)."""
    from openembedding_tpu.analysis import programs
    mesh = create_mesh(2, 4, devices8)
    # graftcheck's sizing: the table shard must dwarf legitimate
    # batch-scale copies for the copy bound to mean anything
    txt, params = programs.lower_pipelined_step(mesh, vocab=1 << 16,
                                                dim=16, batch=128)
    contracts.check_program(txt, "a2a+pipelined", "step", **params)
    r = contracts.analyze_overlap(txt)
    assert r.free_pull_exchanges >= 1
    assert r.committed_push_exchanges >= 1
    assert r.dense_waiting_on_exchange == 0
    # no shard-sized copy: donation of the tables actually honored
    shard = params["table_shard_bytes"]
    assert contracts.max_copy_bytes(txt) < shard


@pytest.mark.slow
def test_pipelined_step_negative_contracts(devices8):
    """Negative shapes on REAL compiled programs: the deliberately
    serialized pipelined step (loss routed into the prefetch indices)
    and the serial a2a step are both caught by the overlap contract."""
    from openembedding_tpu.analysis import programs
    mesh = create_mesh(2, 4, devices8)
    txt, _ = programs.lower_pipelined_step(mesh, vocab=2048, dim=8,
                                           batch=128,
                                           force_serialize=True)
    with pytest.raises(contracts.ContractViolation,
                       match="serialized behind"):
        contracts.check_overlap(txt, "forced")
    txt, _ = programs.lower_train_step(mesh, "a2a", vocab=2048, dim=8,
                                       batch=128)
    with pytest.raises(contracts.ContractViolation, match="wait on"):
        contracts.check_overlap(txt, "serial")


def test_offloaded_variable_rejects_pipelined_plane(devices8):
    """Offload host-prepare mutates tables between steps — the Trainer
    must refuse the combination loudly."""
    from openembedding_tpu import EmbeddingVariableMeta
    from openembedding_tpu.offload import ShardedOffloadedTable
    mesh = create_mesh(1, 8, jax.devices()[:8])
    t = ShardedOffloadedTable(
        "t3", EmbeddingVariableMeta(embedding_dim=4, vocabulary_size=256),
        OPT, INIT, vocab=256, cache_capacity=64, mesh=mesh)
    spec = t.embedding_spec()
    import dataclasses
    spec = dataclasses.replace(spec, plane="a2a+pipelined")
    coll = EmbeddingCollection((spec,), mesh)
    with pytest.raises(ValueError, match="pipelined"):
        Trainer(TinyModel(names=("t3",)), coll, optax.sgd(0.1),
                offload={"t3": t})
