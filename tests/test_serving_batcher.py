"""Micro-batched serving data plane (ISSUE 14 tentpole).

Batched responses must be BIT-identical to unbatched lookups — including
across a delta hot-swap landing mid-batch (one snapshot per flush,
pinned by PointGate/SerialSchedule replays of the graftproto
``serving_batcher`` schedules) — shutdown answers every queued request
exactly once, and a bounded queue degrades oversubscription to 429
rejections, never to errors on accepted requests.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
from openembedding_tpu import checkpoint as ckpt
from openembedding_tpu import checkpoint_delta as cd
from openembedding_tpu.analysis import scope
from openembedding_tpu.analysis.concurrency import (PointGate,
                                                    SerialSchedule,
                                                    clear_schedule,
                                                    install_schedule)
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.serving.batcher import (BusyError, LookupBatcher,
                                               dedup_keys)
from openembedding_tpu.serving.registry import ModelRegistry
from openembedding_tpu.utils import observability as obs

from test_delta_checkpoint import make_coll, train

VOCAB, DIM = 256, 4


@pytest.fixture(autouse=True)
def _no_leftover_schedule():
    yield
    clear_schedule()


@pytest.fixture()
def served(devices8, tmp_path):
    """A trained delta-armed model loaded into a BATCHED registry,
    plus the trainer-side collection/states for ground truth."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, model_sign="batch-1")
    states, _ = train(coll, states, seed=0)
    info = cd.save_delta(path, coll, states, step=1, return_payload=True)
    assert info["seq"] == 1
    reg = ModelRegistry(mesh, default_hash_capacity=2048)
    reg.enable_batching(max_batch_rows=64, max_wait_us=3000)
    sign = reg.create_model(path, block=True)
    yield reg, sign, coll, states, path
    reg.close()


# --- bit-identical parity ----------------------------------------------------

def test_batched_parity_bit_identical(served):
    """Concurrent flat lookups (duplicate keys, both dtypes) coalesce
    into shared flushes; every response must be EXACTLY the unbatched
    rows (`==`, not allclose — the pull is a pure gather)."""
    reg, sign, _coll, _states, _path = served
    model = reg.find_model(sign)
    rng = np.random.RandomState(7)
    queries = [("arr", rng.randint(0, VOCAB, 16).astype(np.int32)),
               ("arr", rng.randint(0, VOCAB, 5).astype(np.int64)),
               ("hsh", rng.randint(0, 2**20, 16).astype(np.int32)),
               ("arr", np.array([3, 3, 3, 9], np.int32)),
               ("hsh", np.array([12345, 12345], np.int32))]
    want = [np.asarray(model.lookup(v, q), np.float32)
            for v, q in queries]
    got = [None] * len(queries)

    def go(i, v, q):
        got[i] = np.asarray(reg.lookup(sign, v, q), np.float32)

    threads = [threading.Thread(target=go, args=(i, v, q))
               for i, (v, q) in enumerate(queries)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    for i, w in enumerate(want):
        np.testing.assert_array_equal(got[i], w, err_msg=f"query {i}")
    # coalescing actually happened: fewer flushes than requests
    assert obs.GLOBAL.snapshot().get("batch_flushes",
                                     {}).get("count", 0) >= 1


def test_sequence_queries_fall_through_unbatched(served):
    """Pooled/sequence-shaped queries are NOT batchable (concatenating
    key streams breaks their semantics) — they take the direct path and
    stay correct."""
    reg, sign, _coll, _states, _path = served
    model = reg.find_model(sign)
    assert model.batchable("arr", np.arange(4, dtype=np.int32)) == "arr"
    seq = np.arange(8, dtype=np.int32).reshape(2, 4)
    assert model.batchable("arr", seq) is None
    np.testing.assert_array_equal(
        np.asarray(reg.lookup(sign, "arr", seq)),
        np.asarray(model.lookup("arr", seq)))


def test_dedup_keys_unit():
    uniq, inv = dedup_keys(np.array([5, 1, 5, 9, 1], np.int64))
    np.testing.assert_array_equal(uniq, [1, 5, 9])
    np.testing.assert_array_equal(uniq[inv], [5, 1, 5, 9, 1])
    pairs = np.array([[1, 0], [2, 0], [1, 0]], np.int32)
    up, inv = dedup_keys(pairs)
    assert up.shape == (2, 2)
    np.testing.assert_array_equal(up[inv], pairs)


# --- swap-landing-mid-batch (PointGate schedule) -----------------------------

def test_swap_mid_batch_serves_exactly_one_version(served):
    """The acceptance schedule: a delta hot-swap lands while a batch is
    parked between its snapshot and its pulls. The batch must answer
    from its ONE snapshot (the pre-swap version, bit-identical to
    unbatched pre-swap lookups); the next lookup sees the new version
    whole."""
    reg, sign, coll, states, path = served
    model = reg.find_model(sign)
    probe = np.arange(0, 32, dtype=np.int32)
    want_old = np.asarray(model.lookup("arr", probe), np.float32)
    states2, idx2 = train(coll, states, seed=5,
                          arr_ids=np.arange(0, 16, dtype=np.int32))
    info = cd.save_delta(path, coll, states2, step=2,
                         return_payload=True)
    delta = info["delta"]
    want_new = np.asarray(coll.pull(
        states2, {"arr": jnp.asarray(probe)}, batch_sharded=False,
        read_only=True)["arr"], np.float32)
    assert not np.array_equal(want_old, want_new)

    gate = PointGate(["serving.batch.pull"], timeout=30)
    install_schedule(gate)
    out = {}

    def storm():
        out["rows"] = np.asarray(reg.lookup(sign, "arr", probe),
                                 np.float32)

    t = threading.Thread(target=storm, name="storm")
    t.start()
    # the flusher is parked AFTER its snapshot, before the pull — the
    # exact window the graftproto counterexample swaps in
    assert gate.wait_arrival("serving.batch.pull")
    res = reg.apply_delta(sign, delta)
    assert res["applied"] and res["version"] == 2
    gate.open("serving.batch.pull")
    t.join(30)
    clear_schedule()
    assert not t.is_alive()
    # the parked batch answered from its single pre-swap snapshot
    np.testing.assert_array_equal(out["rows"], want_old)
    # post-swap lookups (batched) see the new version whole
    np.testing.assert_array_equal(
        np.asarray(reg.lookup(sign, "arr", probe), np.float32), want_new)


def test_resnapshot_mutation_replay_mixes_versions(served):
    """The graftproto ``resnapshot_per_pull`` counterexample executed
    for real: with the one-line mutation (each group's pull re-reads
    the LIVE model reference instead of the flush snapshot), driving
    the exported schedule — enqueue x2 / collect / snapshot / pull /
    swap / pull — hands ONE batch rows from TWO versions. The
    unmutated batcher under the identical schedule serves both from
    the snapshot."""
    import shutil
    reg, sign, coll, states, path = served
    model = reg.find_model(sign)
    probe32 = np.arange(0, 16, dtype=np.int32)   # group A (int32)
    probe64 = np.arange(0, 16, dtype=np.int64)   # group B (int64)
    want_old = np.asarray(model.lookup("arr", probe32), np.float32)
    # version-1 snapshot of the dir: the control run reloads from it
    # (saving delta 2 below advances the REAL chain on disk)
    path_v1 = path + "_v1"
    shutil.copytree(path, path_v1)
    states2, _ = train(coll, states, seed=6,
                       arr_ids=np.arange(0, 16, dtype=np.int32))
    delta = cd.save_delta(path, coll, states2, step=2,
                          return_payload=True)["delta"]
    want_new = np.asarray(coll.pull(
        states2, {"arr": jnp.asarray(probe32)}, batch_sharded=False,
        read_only=True)["arr"], np.float32)

    def run(mutate, sign, model):
        b = reg._batcher_for(sign, model)
        if mutate:
            # the modeled bug: pulls read model.states LIVE, the
            # snapshot is ignored
            b._pull_unique = lambda _snap, name, uniq: np.asarray(
                model._lookup_impl(name, uniq, model.states,
                                   record=False), np.float32)
        # the exported counterexample order: swap lands BETWEEN the two
        # variable-group pulls of one batch
        sched = SerialSchedule(
            ["serving.batch.pull", "registry.find",
             "registry.swap.build", "registry.swap.commit",
             "serving.batch.pull"], timeout=30)
        install_schedule(sched)
        r1 = b.offer("arr", probe32)
        r2 = b.offer("arr", probe64)
        res = reg.apply_delta(sign, delta)
        assert res["applied"]
        rows1 = r1.wait(30)
        rows2 = r2.wait(30)
        clear_schedule()
        assert sched.done()
        return np.asarray(rows1, np.float32), np.asarray(rows2,
                                                         np.float32)

    rows1, rows2 = run(True, sign, model)
    np.testing.assert_array_equal(rows1, want_old)
    np.testing.assert_array_equal(rows2, want_new)   # the MIXED batch
    # the control model starts at version 1 (the pre-delta snapshot)
    reg.delete_model(sign)
    sign = reg.create_model(path_v1, model_sign="batch-ctl", block=True)
    model = reg.find_model(sign)
    assert model.version == 1
    rows1, rows2 = run(False, sign, model)
    np.testing.assert_array_equal(rows1, want_old)
    np.testing.assert_array_equal(rows2, want_old)   # one version
    reg.delete_model(sign)


# --- shutdown-with-queued-requests -------------------------------------------

def test_shutdown_drains_every_queued_request(served):
    """Every request accepted before shutdown gets exactly one response
    (the drain discipline the ``drop_queue_on_shutdown`` mutation
    deletes); offers after shutdown reject as busy."""
    reg, sign, _coll, _states, _path = served
    model = reg.find_model(sign)
    b = reg._batcher_for(sign, model)
    probe = np.arange(8, dtype=np.int32)
    want = np.asarray(model.lookup("arr", probe), np.float32)

    gate = PointGate(["serving.batch.pull"], timeout=30)
    install_schedule(gate)
    first = b.offer("arr", probe)
    assert gate.wait_arrival("serving.batch.pull")
    # flusher parked mid-flush: these QUEUE behind it
    queued = [b.offer("arr", probe) for _ in range(3)]
    closer = threading.Thread(target=b.close, name="closer")
    closer.start()
    gate.open("serving.batch.pull")
    closer.join(30)
    clear_schedule()
    assert not closer.is_alive()
    for req in [first] + queued:
        np.testing.assert_array_equal(
            np.asarray(req.wait(1.0), np.float32), want)
    with pytest.raises(BusyError):
        b.offer("arr", probe)


# --- backpressure ------------------------------------------------------------

def test_bounded_queue_rejects_never_collapses():
    """Oversubscription degrades to rejections: a storm past the queue
    bound gets 429-style BusyError, while every ACCEPTED request
    completes correctly (no error, no latency collapse). Pure-host
    batcher with a slow synthetic pull — no jax involved."""
    calls = []

    def slow_pull(_snap, _name, uniq):
        time.sleep(0.02)
        calls.append(uniq.size)
        return uniq[:, None].astype(np.float32) * np.ones(4, np.float32)

    rejected_before = obs.GLOBAL.snapshot().get(
        "serving_rejected", {}).get("count", 0)
    b = LookupBatcher("bp", lambda: None, slow_pull,
                      max_batch_rows=8, max_wait_us=0, max_queue_rows=16)
    try:
        accepted, rejected = [], 0
        for i in range(200):
            try:
                accepted.append(b.offer("v", np.arange(4, dtype=np.int64)))
            except BusyError:
                rejected += 1
        assert rejected > 0, "storm never hit the bound"
        assert accepted, "everything rejected"
        for req in accepted:
            rows = req.wait(30)
            np.testing.assert_array_equal(
                rows, np.arange(4)[:, None] * np.ones(4, np.float32))
    finally:
        b.close()
    after = obs.GLOBAL.snapshot()["serving_rejected"]["count"]
    assert after - rejected_before == rejected
    assert "oe_serving_rejected_total" in obs.prometheus_text()


def test_oversized_single_request_admitted_when_idle():
    """A single request larger than the whole queue bound can never
    satisfy the row arithmetic — an idle batcher must admit it alone
    (it flushes alone) instead of 429ing it forever; with work already
    queued it still gets the rejection."""
    release = threading.Event()

    def gated_pull(_snap, _name, uniq):
        release.wait(10)
        return uniq[:, None].astype(np.float32) * np.ones(2, np.float32)

    b = LookupBatcher("big", lambda: None, gated_pull,
                      max_batch_rows=8, max_wait_us=0, max_queue_rows=8)
    try:
        big = b.offer("v", np.arange(20, dtype=np.int64))  # idle: admitted
        # wait until the flusher popped the big request (it is now
        # parked inside the gated pull) so the small offer below is
        # judged against an empty queue, not the in-flight rows
        deadline = time.perf_counter() + 10
        while b.stats()["queue_rows"] and time.perf_counter() < deadline:
            time.sleep(0.001)
        # a second oversized offer while a small one occupies the
        # queue must still reject
        small = b.offer("v", np.arange(4, dtype=np.int64))
        with pytest.raises(BusyError):
            b.offer("v", np.arange(20, dtype=np.int64))
        release.set()
        np.testing.assert_array_equal(
            big.wait(10),
            np.arange(20)[:, None] * np.ones(2, np.float32))
        np.testing.assert_array_equal(
            small.wait(10),
            np.arange(4)[:, None] * np.ones(2, np.float32))
    finally:
        release.set()
        b.close()


def test_batcher_pull_error_reaches_every_group_member():
    def boom(_snap, _name, _uniq):
        raise RuntimeError("pull exploded")

    b = LookupBatcher("err", lambda: None, boom, max_wait_us=5000)
    try:
        r1 = b.offer("v", np.arange(3, dtype=np.int64))
        r2 = b.offer("v", np.arange(3, dtype=np.int64))
        for r in (r1, r2):
            with pytest.raises(RuntimeError, match="pull exploded"):
                r.wait(30)
    finally:
        b.close()


def test_flusher_survives_snapshot_error():
    """An exception OUTSIDE the per-group pull guard (e.g. the
    snapshot() hook) must not kill the flusher thread: the batch's
    waiters get the error, and the batcher keeps serving subsequent
    requests (a dead flusher would silently accept offers that then
    block their whole timeout)."""
    boom = [True]

    def snap():
        if boom[0]:
            raise RuntimeError("snapshot exploded")
        return None

    def pull(_snap, _name, uniq):
        return uniq[:, None].astype(np.float32) * np.ones(4, np.float32)

    b = LookupBatcher("snap-err", snap, pull, max_wait_us=0)
    try:
        with pytest.raises(RuntimeError, match="snapshot exploded"):
            b.lookup("v", np.arange(3, dtype=np.int64))
        boom[0] = False
        rows = b.lookup("v", np.arange(3, dtype=np.int64), timeout=10)
        np.testing.assert_array_equal(
            rows, np.arange(3)[:, None] * np.ones(4, np.float32))
        assert b._thread.is_alive()
    finally:
        b.close()


def test_same_sign_reload_rebinds_batcher(served):
    """A same-sign model RELOAD must not leave batched traffic bound to
    the replaced model: the stale batcher (whose closures capture the
    old ServingModel) is drained and a fresh one binds to the new
    object, so batched lookups serve the RELOADED rows."""
    reg, sign, coll, states, path = served
    model = reg.find_model(sign)
    b_old = reg._batcher_for(sign, model)
    probe = np.arange(8, dtype=np.int32)
    np.testing.assert_array_equal(
        np.asarray(reg.lookup(sign, "arr", probe), np.float32),
        np.asarray(model.lookup("arr", probe), np.float32))
    # advance the chain on disk, then RELOAD under the same sign
    states2, _ = train(coll, states, seed=9,
                       arr_ids=np.arange(0, 16, dtype=np.int32))
    cd.save_delta(path, coll, states2, step=2)
    reg.create_model(path, model_sign=sign, block=True)
    model2 = reg.find_model(sign)
    assert model2 is not model and model2.version == 2
    want2 = np.asarray(model2.lookup("arr", probe), np.float32)
    got = np.asarray(reg.lookup(sign, "arr", probe), np.float32)
    np.testing.assert_array_equal(got, want2)
    b_new = reg._batcher_for(sign, model2)
    assert b_new is not b_old
    # the stale batcher was closed: it rejects further offers
    with pytest.raises(BusyError):
        b_old.offer("arr", probe)


def test_rotate_surfaces_all_busy_as_429():
    """When EVERY replica rejects with batcher backpressure, the
    routing client raises the 429 itself (a defined rejection the load
    tools count apart), not a dead-replica ConnectionError; a mix of
    dead + busy still reports dead-replica semantics."""
    import io
    import urllib.error
    from openembedding_tpu.serving import ha

    router = ha.RoutingClient(["h1:1", "h2:1"])

    def busy(ep):
        raise urllib.error.HTTPError(f"http://{ep}/x", 429,
                                     "busy", {}, io.BytesIO(b""))

    with pytest.raises(urllib.error.HTTPError) as ei:
        router._rotate(busy)
    assert ei.value.code == 429

    def half_dead(ep):
        if ep == "h1:1":
            raise ConnectionError("down")
        raise urllib.error.HTTPError(f"http://{ep}/x", 503,
                                     "creating", {}, io.BytesIO(b""))

    with pytest.raises(ConnectionError):
        router._rotate(half_dead)

    # dead replica MIXED with a busy one (the chaos + backpressure
    # storm): the 429 must surface regardless of which replica the
    # randomized rotation probed last — a ConnectionError here would
    # count the defined rejection as a request error
    def dead_plus_busy(ep):
        if ep == "h1:1":
            raise ConnectionError("down")
        raise urllib.error.HTTPError(f"http://{ep}/x", 429,
                                     "busy", {}, io.BytesIO(b""))

    for _ in range(8):  # cover both rotation orders
        with pytest.raises(urllib.error.HTTPError) as ei:
            router._rotate(dead_plus_busy)
        assert ei.value.code == 429


def test_graftload_counts_rejections_apart_from_errors():
    """run_storm tallies RejectedError separately: rejections are not
    completions (achieved drops) and not errors (the chaos gate stays
    meaningful under deliberate backpressure)."""
    import sys as _sys
    import os as _os
    _sys.path.insert(0, _os.path.join(_os.path.dirname(
        _os.path.dirname(_os.path.abspath(__file__))), "tools"))
    from tools import graftload as gl

    def send(i):
        if i % 3 == 0:
            raise gl.RejectedError("busy")

    arrivals = np.linspace(0.0, 0.2, 30)
    res = gl.run_storm(send, arrivals, route="rest", offered_qps=150.0,
                       duration=0.2, workers=4)
    assert res.rejected == 10 and res.errors == 0
    assert res.calls == 30
    assert res.summary()["rejected"] == 10


# --- observability -----------------------------------------------------------

def test_batch_metrics_and_member_traces(served):
    """serving_batch_rows / serving_batch_wait_us histograms fill, the
    oe_batch_* counters land on the prometheus page, and the flush's
    member spans carry each request's trace id (the merged Perfetto
    story shows coalescing)."""
    reg, sign, _coll, _states, _path = served
    scope.set_tracing(True)
    scope.reset()
    try:
        rows_before = scope.HISTOGRAMS.count("serving_batch_rows")
        with scope.trace_context() as tid:
            reg.lookup(sign, "arr", np.arange(6, dtype=np.int32))
        assert scope.HISTOGRAMS.count("serving_batch_rows") \
            == rows_before + 1
        assert scope.HISTOGRAMS.count("serving_batch_wait_us") >= 1
        text = obs.prometheus_text()
        assert "oe_batch_rows_total" in text
        assert "oe_batch_flushes_total" in text
        assert "oe_serving_lookup_rows_bucket" in text
        # the member span carries the REQUEST's trace id
        trace = scope.export_chrome_trace()
        members = [e for e in trace["traceEvents"]
                   if e.get("name") == "serving.batch.member"
                   and e.get("args", {}).get("trace") == tid]
        assert members, "no member span with the request trace id"
    finally:
        scope.set_tracing(None)


# --- live knob retune (graftplan online mode) --------------------------------

def test_mid_storm_knob_flip_moves_next_flush():
    """Regression: the flusher must observe a ``set_knobs`` retune on
    its very NEXT flush decision (it once latched the knobs at thread
    start — the adaptive tuner would then adjust a dead copy). A flip
    from rows=8 to rows=32 while a flush is in flight must coalesce the
    backlog into ONE 32-row flush, not four 8-row ones."""
    entered = threading.Event()
    release = threading.Event()
    flush_sizes = []

    def gated_pull(_snap, _name, uniq):
        flush_sizes.append(uniq.size)
        entered.set()
        release.wait(10)
        return uniq[:, None].astype(np.float32) * np.ones(2, np.float32)

    b = LookupBatcher("flip", lambda: None, gated_pull,
                      max_batch_rows=8, max_wait_us=500_000,
                      max_queue_rows=1024)
    try:
        # 8 rows hit the row cap -> immediate flush, parked in the pull
        first = b.offer("v", np.arange(8, dtype=np.int64))
        assert entered.wait(10)
        # backlog four more 8-row requests behind the in-flight flush
        # (distinct keys per request so dedup keeps the row count)
        backlog = [b.offer("v", np.arange(8 * (i + 1), 8 * (i + 2),
                                          dtype=np.int64))
                   for i in range(4)]
        # the live accessor reflects the retune IMMEDIATELY, mid-pull
        assert b.set_knobs(max_batch_rows=32, max_wait_us=0) \
            == {"max_batch_rows": 32, "max_wait_us": 0,
                "max_queue_rows": 1024}
        assert b.knobs()["max_batch_rows"] == 32
        release.set()
        np.testing.assert_array_equal(
            first.wait(10),
            np.arange(8)[:, None] * np.ones(2, np.float32))
        for i, req in enumerate(backlog):
            want = np.arange(8 * (i + 1), 8 * (i + 2))[:, None] \
                * np.ones(2, np.float32)
            np.testing.assert_array_equal(req.wait(10), want)
        # the retune moved the very next flush: 8-row flush while the
        # old knobs ruled, then the whole 32-row backlog in ONE flush
        assert flush_sizes == [8, 32]
        assert b.stats()["flushes"] == 2
    finally:
        release.set()
        b.close()
