"""Delta checkpoint plane (base + dirty-chunk chain) + serving hot-swap.

Covers the acceptance criteria of the incremental-checkpoint round:
a ≤5%-dirty delta moves ≥10x fewer bytes than a full save (asserted via
the ``ckpt_delta_bytes`` counter), base+chain loads bit-identical to a
full save at the same step — including after a simulated torn final
delta and a writer killed mid-delta (PointGate crash lane) — chain
compaction folds back to a new base, and the SAME delta stream
hot-swaps into a serving replica (``ModelRegistry.apply_delta``) with
the swap-during-lookup interleaving schedule pinned.
"""

import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
from openembedding_tpu import checkpoint as ckpt
from openembedding_tpu import checkpoint_delta as cd
from openembedding_tpu.analysis.concurrency import (PointGate,
                                                    clear_schedule,
                                                    install_schedule)
from openembedding_tpu.dirty import DirtyTracker
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.utils import observability as obs

VOCAB, DIM = 256, 4


def make_coll(mesh, vocab=VOCAB, chunks=32, track=True):
    specs = (EmbeddingSpec(name="arr", input_dim=vocab, output_dim=DIM),
             EmbeddingSpec(name="hsh", input_dim=-1, output_dim=DIM,
                           hash_capacity=1024),)
    coll = EmbeddingCollection(
        specs, mesh,
        default_optimizer={"category": "adagrad", "learning_rate": 0.1})
    if track:
        coll.enable_dirty_tracking(target_chunks=chunks)
    return coll


def train(coll, states, seed, *, arr_ids=None, n=16, vocab=VOCAB):
    rng = np.random.RandomState(seed)
    if arr_ids is None:
        arr_ids = rng.randint(0, vocab, n)
    idx = {"arr": jnp.asarray(np.asarray(arr_ids, np.int32)),
           "hsh": jnp.asarray(rng.randint(0, 2**20, n).astype(np.int32))}
    rows = coll.pull(states, idx, batch_sharded=False)
    grads = {k: jnp.ones_like(v) * 0.2 for k, v in rows.items()}
    return coll.apply_gradients(states, idx, grads,
                                batch_sharded=False), idx


def assert_states_equal(coll, a, b, vocab=VOCAB, probe_keys=None):
    """Exact (==) comparison of two state dicts through pulls + slots."""
    allv = jnp.arange(vocab, dtype=jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(coll.pull(a, {"arr": allv}, batch_sharded=False)["arr"]),
        np.asarray(coll.pull(b, {"arr": allv}, batch_sharded=False)["arr"]))
    for s in a["arr"].slots:
        np.testing.assert_array_equal(np.asarray(a["arr"].slots[s]),
                                      np.asarray(b["arr"].slots[s]))
    if probe_keys is not None:
        pk = {"hsh": jnp.asarray(np.asarray(probe_keys, np.int32))}
        np.testing.assert_array_equal(
            np.asarray(coll.pull(a, pk, batch_sharded=False,
                                 read_only=True)["hsh"]),
            np.asarray(coll.pull(b, pk, batch_sharded=False,
                                 read_only=True)["hsh"]))


# --- DirtyTracker unit -------------------------------------------------------

def test_dirty_tracker_unit():
    t = DirtyTracker(16, rows_per_chunk=8, name="u")
    assert t.dirty_count == 0
    t.mark_rows([0, 7, 8, 127])           # chunks 0, 0, 1, 15
    assert t.dirty_count == 3
    assert list(t.dirty_chunks()) == [0, 1, 15]
    assert t[3] and t[8] and not t[16]
    assert list(t.mask_rows([0, 8, 64])) == [True, True, False]
    snap = t.snapshot_clear()
    assert t.dirty_count == 0 and list(snap) == [0, 1, 15]
    t.mark_rows([64])                     # landed "during the write"
    t.restore(snap)
    assert t.dirty_count == 4
    t.clear_chunks([0, 1, 8, 15])
    assert list(t.dirty_chunks()) == []
    # out-of-range marks are dropped, negative keys map to valid chunks
    t.mark_chunks([-1, 99])
    assert t.dirty_count == 0
    kt = DirtyTracker(16, name="k")
    kt.mark_keys(np.asarray([-5, 5, 21], np.int64))
    assert kt.dirty_count == 2            # -5 % 16 == 11, 5 and 21 -> 5
    assert set(kt.dirty_chunks()) == {5, 11}


def test_dirty_tracking_names_subset_rejects_unknown(devices8):
    """A typo'd `names=` entry must raise at arm time — silently
    skipping it would leave the intended variable untracked and its
    trained rows reverting to base on a delta restore."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh, track=False)
    with pytest.raises(ValueError, match="unknown variable.*'hshh'"):
        coll.enable_dirty_tracking(names={"arr", "hshh"})
    # valid subset arms only the named variable
    coll.enable_dirty_tracking(names={"arr"})
    assert set(coll._dirty_trackers) == {"arr"}


def test_delta_requires_tracking_and_matching_optimizer(devices8, tmp_path):
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh, track=False)
    states = coll.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="dirty tracking"):
        ckpt.save_checkpoint(str(tmp_path / "m"), coll, states,
                             mode="delta")
    coll.enable_dirty_tracking()
    info = ckpt.save_checkpoint(str(tmp_path / "m"), coll, states,
                                mode="delta", step=1)
    assert info["mode"] == "full" and info["forced_full"]
    with pytest.raises(ValueError, match="include_optimizer"):
        ckpt.save_checkpoint(str(tmp_path / "m"), coll, states,
                             mode="delta", include_optimizer=False)
    # clean tracker -> skipped delta, no new chain entry
    info = ckpt.save_checkpoint(str(tmp_path / "m"), coll, states,
                                mode="delta", step=2)
    assert info["skipped"] and info["seq"] == 0


def test_delta_bytes_ratio_10x(devices8, tmp_path):
    """A <=5%-dirty table's delta moves >=10x fewer bytes than the full
    save — via the ckpt_delta_bytes / ckpt_full_bytes counters."""
    mesh = create_mesh(2, 4, devices8)
    vocab = 8192
    coll = make_coll(mesh, vocab=vocab, chunks=256)   # 32 rows/chunk
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "m")
    c0 = obs.ckpt_stats()
    ckpt.save_checkpoint(path, coll, states, mode="delta", step=0)
    c1 = obs.ckpt_stats()
    full_bytes = c1["ckpt_full_bytes"] - c0["ckpt_full_bytes"]
    assert full_bytes > 0
    # dirty exactly 2 chunks = 64 rows = 0.8% of the table
    states, _ = train(coll, states, 1, arr_ids=np.arange(64), n=8,
                      vocab=vocab)
    info = ckpt.save_checkpoint(path, coll, states, mode="delta", step=1)
    c2 = obs.ckpt_stats()
    delta_bytes = c2["ckpt_delta_bytes"] - c1["ckpt_delta_bytes"]
    assert info["mode"] == "delta" and delta_bytes == info["bytes"]
    assert full_bytes >= 10 * delta_bytes, (full_bytes, delta_bytes)
    assert c2["ckpt_chain_len"] >= 1
    assert c2["ckpt_write_gbps"] > 0


def test_delta_roundtrip_bit_identical(devices8, tmp_path):
    """base + chain loads EXACTLY equal to the live states and to a
    fresh full save of the same states."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    states, _ = train(coll, states, 0)
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, mode="delta", step=0)
    keys = []
    for seed in (1, 2, 3):
        states, idx = train(coll, states, seed)
        keys.append(np.asarray(idx["hsh"]))
        info = ckpt.save_checkpoint(path, coll, states, mode="delta",
                                    step=seed)
        assert info["mode"] == "delta"
    cd.join_compactor(path)
    loaded = ckpt.load_checkpoint(path, coll)
    probe = np.concatenate(keys)
    assert_states_equal(coll, states, loaded, probe_keys=probe)
    # ... and equal to a FULL save of the same states
    full_path = str(tmp_path / "full")
    coll2 = make_coll(mesh, track=False)
    ckpt.save_checkpoint(full_path, coll2, states)
    full_loaded = ckpt.load_checkpoint(full_path, coll2)
    assert_states_equal(coll, full_loaded, loaded, probe_keys=probe)


def test_torn_final_delta_discarded(devices8, tmp_path):
    """A corrupt/truncated FINAL delta is dropped whole (recover to the
    previous complete delta, checksum-verified); the same damage
    MID-chain fails the load."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, mode="delta", step=0)
    # direct save_delta with the compaction budget parked: this test
    # needs the chain to stay on disk
    states, _ = train(coll, states, 1, arr_ids=np.arange(16))
    cd.save_delta(path, coll, states, step=1,
                  compact_bytes_ratio=1e9, background_compact=False)
    after_1 = states
    states, _ = train(coll, states, 2, arr_ids=np.arange(16, 48))
    cd.save_delta(path, coll, states, step=2,
                  compact_bytes_ratio=1e9, background_compact=False)
    manifest = cd.read_manifest(path)
    assert [e["seq"] for e in manifest["chain"]] == [1, 2]
    # flip a byte in the LAST delta's array payload
    last = manifest["chain"][-1]["vars"]["arr"]["file"]
    fp = os.path.join(path, last)
    raw = bytearray(open(fp, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(fp, "wb").write(bytes(raw))
    with pytest.warns(RuntimeWarning, match="torn"):
        loaded = ckpt.load_checkpoint(path, coll)
    assert_states_equal(coll, after_1, loaded)
    assert cd.applied_seq(path) == 1
    # the same corruption MID-chain (delete the FIRST delta) must raise
    first = manifest["chain"][0]["vars"]["arr"]["file"]
    os.remove(os.path.join(path, first))
    with pytest.raises(RuntimeError, match="mid-chain"):
        ckpt.load_checkpoint(path, coll)


def test_writer_killed_mid_delta_recovers(devices8, tmp_path):
    """Crash-consistency lane: writer threads die mid-delta (PointGate
    holds them at ckpt.writer.run until their gate times out). The save
    fails, the manifest never commits, the tracker claims are restored,
    and a load recovers to the last complete state; the NEXT save
    re-covers the same chunks and GCs the debris."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, mode="delta", step=0)
    before = states
    states, idx = train(coll, states, 1, arr_ids=np.arange(24))
    gate = PointGate(["ckpt.writer.run"], timeout=0.4)
    install_schedule(gate)
    try:
        with pytest.raises(TimeoutError):
            ckpt.save_checkpoint(path, coll, states, mode="delta", step=1)
    finally:
        clear_schedule()
    # no commit: manifest still the armed base, chain empty
    manifest = cd.read_manifest(path)
    assert manifest["chain"] == [] and manifest["last_seq"] == 0
    loaded = ckpt.load_checkpoint(path, coll)
    assert_states_equal(coll, before, loaded)
    # the failed claim was restored: the retry covers the same rows
    assert coll.dirty_trackers["arr"].dirty_count > 0
    info = ckpt.save_checkpoint(path, coll, states, mode="delta", step=1)
    assert info["mode"] == "delta" and info["seq"] == 1
    cd.join_compactor(path)
    loaded = ckpt.load_checkpoint(path, coll)
    assert_states_equal(coll, states, loaded,
                        probe_keys=np.asarray(idx["hsh"]))
    # no orphan delta files survive past the successful save's GC + commit
    manifest = cd.read_manifest(path)
    live = {i["file"] for e in manifest["chain"]
            for i in e["vars"].values()}
    on_disk = {f for f in os.listdir(path)
               if f.startswith("delta_") and f.endswith(".npz")}
    assert on_disk == live


def test_compaction_folds_chain(devices8, tmp_path):
    """Past the chain budget the compactor folds deltas into a new base
    on disk: chain resets, seq counter is preserved (burned, not
    reused), the folded base loads bit-identical, delta files are GC'd."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, mode="delta", step=0)
    keys = []
    for seed in (1, 2):
        states, idx = train(coll, states, seed)
        keys.append(np.asarray(idx["hsh"]))
        cd.save_delta(path, coll, states, step=seed,
                      compact_chain_len=2, compact_bytes_ratio=1e9,
                      background_compact=False)
    manifest = cd.read_manifest(path)
    assert manifest["chain"] == []            # folded at the 2nd delta
    assert manifest["last_seq"] == 2          # seqs burned, not reused
    assert not [f for f in os.listdir(path)
                if f.startswith("delta_") and f.endswith(".npz")]
    loaded = ckpt.load_checkpoint(path, coll)
    assert_states_equal(coll, states, loaded,
                        probe_keys=np.concatenate(keys))
    # the next delta continues the seq line
    states, idx = train(coll, states, 3)
    info = cd.save_delta(path, coll, states, step=3,
                         background_compact=False)
    assert info["seq"] == 3
    loaded = ckpt.load_checkpoint(path, coll)
    assert_states_equal(coll, states, loaded,
                        probe_keys=np.asarray(idx["hsh"]))


def test_full_save_resets_stale_chain(devices8, tmp_path):
    """A mode='full' save over a delta directory resets the chain: old
    deltas must never replay over the new base."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, mode="delta", step=0)
    states, _ = train(coll, states, 1)
    ckpt.save_checkpoint(path, coll, states, mode="delta", step=1)
    cd.join_compactor(path)
    states, idx = train(coll, states, 2)
    ckpt.save_checkpoint(path, coll, states, mode="full", step=2)
    manifest = cd.read_manifest(path)
    assert manifest is not None and manifest["chain"] == []
    loaded = ckpt.load_checkpoint(path, coll)
    assert_states_equal(coll, states, loaded,
                        probe_keys=np.asarray(idx["hsh"]))


def test_compressed_base_never_arms_chain(devices8, tmp_path):
    """A compressed (part-format) base has no raw .npy files for the
    compactor to fold, so it must NOT arm a delta chain; a delta save
    into that dir forces a fresh RAW full base and arms from there."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, compress="zlib")
    assert cd.read_manifest(path) is None
    info = ckpt.save_checkpoint(path, coll, states, mode="delta", step=1)
    assert info["forced_full"]
    assert cd.read_manifest(path) is not None
    # the forced-full rewrote the base raw: deltas now work end to end
    states, idx = train(coll, states, 1)
    info = cd.save_delta(path, coll, states, step=2,
                         background_compact=False)
    assert info["mode"] == "delta" and not info["skipped"]
    loaded = ckpt.load_checkpoint(path, coll)
    assert_states_equal(coll, states, loaded,
                        probe_keys=np.asarray(idx["hsh"]))


def test_dense_state_persists_through_skipped_delta(devices8, tmp_path):
    """dense params ride outside the chain: a delta save during a
    dense-only window (zero dirty chunks) is skipped for the tables but
    must still persist the caller's dense_state."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "m")
    dense_v1 = {"w": np.ones((3,), np.float32)}
    ckpt.save_checkpoint(path, coll, states, mode="delta", step=0,
                         dense_state=dense_v1)
    dense_v2 = {"w": np.full((3,), 7.0, np.float32)}
    info = ckpt.save_checkpoint(path, coll, states, mode="delta", step=1,
                                dense_state=dense_v2)
    assert info["skipped"]
    _, dense = ckpt.load_checkpoint(path, coll,
                                    dense_state_template=dense_v1)
    np.testing.assert_array_equal(dense["w"], dense_v2["w"])


def test_parallel_full_save_matches_serial(devices8, tmp_path,
                                           monkeypatch):
    """The parallel shard writers produce byte-identical dumps to the
    serialized (OE_CKPT_WRITERS=1) path — same files, same row order."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh, track=False)
    states = coll.init(jax.random.PRNGKey(0))
    states, _ = train(coll, states, 0)
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    monkeypatch.setenv("OE_CKPT_WRITERS", "1")
    ckpt.save_checkpoint(a, coll, states)
    monkeypatch.setenv("OE_CKPT_WRITERS", "6")
    ckpt.save_checkpoint(b, coll, states)
    for name in ("arr", "hsh"):
        vdir = ckpt._var_dir(coll.variable_id(name), name)
        files = sorted(os.listdir(os.path.join(a, vdir)))
        assert files == sorted(os.listdir(os.path.join(b, vdir)))
        for f in files:
            np.testing.assert_array_equal(
                np.load(os.path.join(a, vdir, f)),
                np.load(os.path.join(b, vdir, f)))


def test_delta_wire_roundtrip():
    """encode_delta/decode_delta frame payloads exactly (compressed and
    raw bodies)."""
    payload = {
        "arr": {"chunks": np.asarray([1, 3], np.int64),
                "rows_per_chunk": np.int64(8),
                "vocab": np.int64(64),
                "weights": np.random.RandomState(0)
                .randn(16, 4).astype(np.float32)},
        "hsh": {"keys": np.asarray([[1, 0], [2, 0]], np.int32),
                "chunks": np.asarray([0], np.int64),
                "num_chunks": np.int64(16),
                "weights": np.ones((2, 4), np.float32)},
    }
    delta = cd.Delta(seq=5, step=17, vars=payload)
    for codec in ("", "zlib"):
        out = cd.decode_delta(cd.encode_delta(delta, compress=codec))
        assert out.seq == 5 and out.step == 17
        assert set(out.vars) == {"arr", "hsh"}
        for name in payload:
            for f, arr in payload[name].items():
                np.testing.assert_array_equal(np.asarray(out.vars[name][f]),
                                              np.asarray(arr))
        assert out.rows == delta.rows == 18


def test_apply_delta_hot_swap_e2e(devices8, tmp_path):
    """train -> save delta -> apply_delta -> serving lookup: served rows
    EXACTLY equal trainer rows at the published version; stale deltas
    ack as no-ops, gaps are refused."""
    from openembedding_tpu.serving.registry import ModelRegistry
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    states, _ = train(coll, states, 0)
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, mode="delta", step=1)
    reg = ModelRegistry(mesh)
    sign = reg.create_model(path, model_sign="m-1")
    assert reg.find_model(sign).version == 0
    states, idx = train(coll, states, 7)
    info = cd.save_delta(path, coll, states, step=2,
                         compact_bytes_ratio=1e9,
                         background_compact=False, return_payload=True)
    assert info["seq"] == 1
    # the publish path carries the payload straight from memory; the
    # disk read of the committed entry must agree exactly
    delta = cd.read_delta(path)
    assert delta.seq == info["delta"].seq
    for name in delta.vars:
        for f, arr in delta.vars[name].items():
            np.testing.assert_array_equal(
                np.asarray(arr), np.asarray(info["delta"].vars[name][f]))
    res = reg.apply_delta(sign, delta)
    assert res == {"applied": True, "version": 1, "rows": delta.rows}
    model = reg.find_model(sign)
    assert model.version == 1
    for name in ("arr", "hsh"):
        want = np.asarray(coll.pull(states, {name: idx[name]},
                                    batch_sharded=False,
                                    read_only=True)[name])
        got = np.asarray(model.lookup(name, np.asarray(idx[name])))
        np.testing.assert_array_equal(got, want)
    # stale replay acks as a no-op (idempotent publisher retries); the
    # wire encoding applies identically
    res = reg.apply_delta(sign, cd.encode_delta(delta, compress="zlib"))
    assert res["applied"] is False and res["version"] == 1
    # a gap is refused — the skipped delta's rows would be lost
    with pytest.raises(RuntimeError, match="gap"):
        reg.apply_delta(sign, cd.Delta(seq=3, step=9, vars={}))


def test_peer_restore_carries_hot_swap_version(devices8, tmp_path):
    """A replica rebuilt from a living peer's rows must START at the
    peer's hot-swap version — its rows already reflect every applied
    delta, and version=0 would refuse the next published delta as a
    gap (it could never converge without a full reload)."""
    from openembedding_tpu.serving import ha
    from openembedding_tpu.serving.registry import ModelRegistry
    from openembedding_tpu.serving.rest import ControllerServer
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    states, _ = train(coll, states, 0)
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, mode="delta", step=1)
    reg_a = ModelRegistry(mesh)
    sign = reg_a.create_model(path, model_sign="m-1")
    states, idx = train(coll, states, 1)
    info = cd.save_delta(path, coll, states, step=2,
                         compact_bytes_ratio=1e18,
                         background_compact=False, return_payload=True)
    reg_a.apply_delta(sign, info["delta"])
    assert reg_a.find_model(sign).version == 1
    srv = ControllerServer(reg_a, port=0).start()
    try:
        reg_b = ModelRegistry(mesh)
        ha.restore_model_from_peer(reg_b, f"127.0.0.1:{srv.port}", sign)
        model_b = reg_b.find_model(sign)
        assert model_b.version == 1
        # the restored rows match the peer's post-delta state exactly
        want = np.asarray(coll.pull(states, {"arr": idx["arr"]},
                                    batch_sharded=False,
                                    read_only=True)["arr"])
        np.testing.assert_array_equal(
            np.asarray(model_b.lookup("arr", np.asarray(idx["arr"]))),
            want)
        # and the NEXT published delta applies without a gap error
        states2, _ = train(coll, states, 2)
        info2 = cd.save_delta(path, coll, states2, step=3,
                              compact_bytes_ratio=1e18,
                              background_compact=False,
                              return_payload=True)
        res = reg_b.apply_delta(sign, info2["delta"])
        assert res["applied"] and model_b.version == 2
    finally:
        srv.stop()


def test_swap_during_lookup_schedule(devices8, tmp_path):
    """Interleaving schedule: a lookup parked AFTER its states snapshot
    while apply_delta commits must return the OLD version whole — and a
    fresh lookup after the swap returns the NEW version whole. Readers
    never see a mixed version."""
    from openembedding_tpu.serving.registry import ModelRegistry
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    states, _ = train(coll, states, 0)
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, mode="delta", step=1)
    reg = ModelRegistry(mesh)
    sign = reg.create_model(path, model_sign="m-1")
    model = reg.find_model(sign)
    allv = np.arange(VOCAB, dtype=np.int32)
    old = np.asarray(model.lookup("arr", allv))
    # delta updates EVERY row (so any mix of versions is detectable)
    states, _ = train(coll, states, 1, arr_ids=np.arange(VOCAB),
                      vocab=VOCAB)
    info = cd.save_delta(path, coll, states, step=2,
                         compact_bytes_ratio=1e9,
                         background_compact=False, return_payload=True)
    delta = info["delta"]
    new = np.asarray(coll.pull(states, {"arr": jnp.asarray(allv)},
                               batch_sharded=False)["arr"])
    assert (np.abs(new - old) > 0).any()

    gate = PointGate(["reader/serving.lookup.snapshot"])
    install_schedule(gate)
    got: list = []
    try:
        t = threading.Thread(
            target=lambda: got.append(np.asarray(model.lookup("arr",
                                                              allv))),
            name="reader")
        t.start()
        assert gate.wait_arrival("reader/serving.lookup.snapshot")
        # the swap commits WHILE the reader is parked on its snapshot
        res = reg.apply_delta(sign, delta)
        assert res["applied"] and model.version == 1
        gate.open("reader/serving.lookup.snapshot")
        t.join(20)
        assert not t.is_alive()
    finally:
        clear_schedule()
    # the parked reader's rows are ENTIRELY the old version
    np.testing.assert_array_equal(got[0], old)
    # a post-swap lookup is ENTIRELY the new version
    np.testing.assert_array_equal(np.asarray(model.lookup("arr", allv)),
                                  new)


# --- graftproto-found divergences, pinned (ISSUE 13) -------------------------

def test_full_save_carries_burned_seqs(devices8, tmp_path):
    """graftproto `full_save_resets_seq` (pre-fix shipped behavior): a
    full save over an armed chain must carry ``last_seq`` — re-arming at
    0 hands the next delta a seq every replica already applied, which
    they ack as a stale no-op and silently stop updating."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, mode="delta", step=0)
    for seed in (1, 2):
        states, _ = train(coll, states, seed)
        cd.save_delta(path, coll, states, step=seed,
                      compact_bytes_ratio=1e9, background_compact=False)
    states, _ = train(coll, states, 3)
    ckpt.save_checkpoint(path, coll, states, mode="full", step=3)
    st = cd.chain_state(path)
    assert st["last_seq"] == 2 and st["content_seq"] == 2
    # the fresh base REFLECTS everything through seq 2: a loaded serving
    # model starts at version 2, so the next published delta (seq 3)
    # applies instead of being acked away as stale
    assert cd.applied_seq(path) == 2
    states, idx = train(coll, states, 4)
    info = cd.save_delta(path, coll, states, step=4,
                         background_compact=False, return_payload=True)
    assert info["seq"] == 3                   # burned seqs never reused
    from openembedding_tpu.serving.registry import ModelRegistry
    reg = ModelRegistry(mesh, default_hash_capacity=2048)
    sign = reg.create_model(path, block=True)
    model = reg.find_model(sign)
    assert model.version == 3


def test_applied_seq_survives_compaction(devices8, tmp_path):
    """graftproto `compact_zero_version` (pre-fix shipped behavior): a
    compaction folds the chain into the base; ``applied_seq`` must then
    report the folded content version, not 0 — a 0-versioned model
    refuses every later delta as a gap (hot-swap wedged until the next
    full save)."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, mode="delta", step=0)
    for seed in (1, 2):
        states, _ = train(coll, states, seed)
        cd.save_delta(path, coll, states, step=seed,
                      compact_chain_len=2, compact_bytes_ratio=1e9,
                      background_compact=False)
    manifest = cd.read_manifest(path)
    assert manifest["chain"] == [] and manifest["content_seq"] == 2
    assert cd.applied_seq(path) == 2
    from openembedding_tpu.serving.registry import ModelRegistry
    reg = ModelRegistry(mesh, default_hash_capacity=2048)
    sign = reg.create_model(path, block=True)
    model = reg.find_model(sign)
    assert model.version == 2
    # the next published delta continues seamlessly across the rebase
    states, idx = train(coll, states, 3)
    info = cd.save_delta(path, coll, states, step=3,
                         background_compact=False, return_payload=True)
    out = reg.apply_delta(sign, info["delta"])
    assert out["applied"] and model.version == 3
    want = np.asarray(coll.pull(states, {"arr": idx["arr"]},
                                batch_sharded=False,
                                read_only=True)["arr"])
    np.testing.assert_array_equal(
        want, np.asarray(model.lookup("arr", np.asarray(idx["arr"]))))


def test_compactor_refuses_torn_entry(devices8, tmp_path):
    """graftproto true positive: the compactor must NOT fold across a
    torn committed entry — compacting the verified prefix and GC'ing
    the torn file would turn the documented loud mid-chain refusal into
    silent permanent data loss (the torn delta's chunks were claim-
    cleared at its save; nothing re-covers them)."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, mode="delta", step=0)
    after = {}
    for seed in (1, 2):
        states, _ = train(coll, states, seed,
                          arr_ids=np.arange(seed * 16, seed * 16 + 8))
        cd.save_delta(path, coll, states, step=seed,
                      compact_bytes_ratio=1e9, background_compact=False)
        after[seed] = states
    manifest = cd.read_manifest(path)
    last = manifest["chain"][-1]["vars"]["arr"]["file"]
    fp = os.path.join(path, last)
    raw = bytearray(open(fp, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(fp, "wb").write(bytes(raw))
    with pytest.warns(RuntimeWarning, match="refusing to compact"):
        out = cd.compact(path)
    assert out == {"compacted": False, "torn_seq": 2}
    # directory untouched: chain intact, loads keep the documented
    # drop-the-tail recovery to seq 1
    manifest = cd.read_manifest(path)
    assert [e["seq"] for e in manifest["chain"]] == [1, 2]
    with pytest.warns(RuntimeWarning, match="torn"):
        loaded = ckpt.load_checkpoint(path, coll)
    assert_states_equal(coll, after[1], loaded)
    # once a later delta lands the tear is MID-chain: loads fail loudly
    # (never a silent fold-around) until a full save rebuilds the base
    states, _ = train(coll, states, 3, arr_ids=np.arange(96, 104))
    cd.save_delta(path, coll, states, step=3, background_compact=False)
    with pytest.raises(RuntimeError, match="mid-chain"):
        ckpt.load_checkpoint(path, coll)
    ckpt.save_checkpoint(path, coll, states, mode="full", step=4)
    loaded = ckpt.load_checkpoint(path, coll)
    assert_states_equal(coll, states, loaded)


def test_seq_line_survives_non_arming_full_save(devices8, tmp_path):
    """Review-found hole in the seq-carry fix: a full save whose layout
    cannot arm a chain (compressed/part format) resets the manifest and
    would drop the burn counter with it — the meta now records
    ``delta_last_seq`` so the NEXT arming save restores the line instead
    of restarting at 0 (which replicas would stale-ack)."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, mode="delta", step=0)
    for seed in (1, 2):
        states, _ = train(coll, states, seed)
        cd.save_delta(path, coll, states, step=seed,
                      compact_bytes_ratio=1e9, background_compact=False)
    # compressed full save: resets the chain, CANNOT re-arm (framed
    # streams have no memmap base for the compactor) — manifest gone
    ckpt.save_checkpoint(path, coll, states, mode="full", step=3,
                         compress="zlib")
    assert cd.read_manifest(path) is None
    # plain full save over the same dir: arms again, and must resume
    # the burned-seq line recorded in the meta, not restart at 0
    ckpt.save_checkpoint(path, coll, states, mode="full", step=4)
    st = cd.chain_state(path)
    assert st["last_seq"] == 2 and st["content_seq"] == 2, st
    states, _ = train(coll, states, 5)
    info = cd.save_delta(path, coll, states, step=5,
                         background_compact=False)
    assert info["seq"] == 3
