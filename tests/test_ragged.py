"""Ragged / sequence features: padding, pooling, gradient expansion.

The reference supports RaggedTensor lookups (exb.py:315-321); the TPU-native
contract is padded [B, L] ids + spec-declared pooling. A pooled feature must
behave exactly like pulling raw [B, L, dim] rows and pooling by hand —
including gradients.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import (EmbeddingCollection, EmbeddingSpec, Trainer,
                               pad_ragged, pad_id_for)
from openembedding_tpu import ragged
from openembedding_tpu.models import deepctr
from openembedding_tpu.parallel.mesh import create_mesh

VOCAB, DIM = 48, 4


def test_pad_ragged():
    out = pad_ragged([[1, 2, 3], [7], []], pad_id=-1)
    assert out.shape == (3, 3)
    np.testing.assert_array_equal(out[0], [1, 2, 3])
    np.testing.assert_array_equal(out[1], [7, -1, -1])
    np.testing.assert_array_equal(out[2], [-1, -1, -1])
    # truncation keeps the most recent ids
    out = pad_ragged([[1, 2, 3, 4]], max_len=2)
    np.testing.assert_array_equal(out[0], [3, 4])


@pytest.mark.parametrize("pooling", ["sum", "mean", "sqrtn"])
def test_pooled_pull_matches_manual(devices8, pooling):
    mesh = create_mesh(2, 4, devices8)
    raw = EmbeddingSpec(name="s", input_dim=VOCAB, output_dim=DIM,
                        initializer={"category": "normal", "stddev": 0.1})
    pooled = EmbeddingSpec(name="s", input_dim=VOCAB, output_dim=DIM,
                           initializer={"category": "normal", "stddev": 0.1},
                           pooling=pooling)
    coll_raw = EmbeddingCollection((raw,), mesh)
    coll_pool = EmbeddingCollection((pooled,), mesh)
    states = coll_raw.init(jax.random.PRNGKey(0))

    ids = jnp.asarray(pad_ragged([[1, 2, 2], [5], [], [40, 7]], max_len=4))
    ids = jnp.tile(ids, (2, 1))  # batch 8, divisible by data axis
    rows_raw = coll_raw.pull(states, {"s": ids})          # [8, 4, DIM]
    got = coll_pool.pull(states, {"s": ids})["s"]         # [8, DIM]

    lengths = np.maximum((np.asarray(ids) >= 0).sum(1), 1)[:, None]
    want = np.asarray(rows_raw["s"]).sum(axis=1)
    if pooling == "mean":
        want = want / lengths
    elif pooling == "sqrtn":
        want = want / np.sqrt(lengths)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("pooling", ["sum", "mean"])
def test_pooled_apply_matches_manual(devices8, pooling):
    """apply_gradients(pooled grads) == apply_gradients(hand-expanded)."""
    mesh = create_mesh(2, 4, devices8)
    kw = dict(input_dim=VOCAB, output_dim=DIM,
              initializer={"category": "constant", "value": 0.2},
              optimizer={"category": "adagrad", "learning_rate": 0.1})
    coll_raw = EmbeddingCollection((EmbeddingSpec(name="s", **kw),), mesh)
    coll_pool = EmbeddingCollection(
        (EmbeddingSpec(name="s", pooling=pooling, **kw),), mesh)
    s_raw = coll_raw.init(jax.random.PRNGKey(1))
    s_pool = jax.tree.map(lambda x: x, s_raw)

    ids = jnp.asarray(pad_ragged([[3, 3, 9], [12], [], [1, 2]], max_len=3))
    ids = jnp.tile(ids, (2, 1))
    g = jnp.asarray(np.random.RandomState(0).randn(8, DIM), jnp.float32)

    lengths = jnp.maximum((ids >= 0).sum(1), 1).astype(jnp.float32)[:, None]
    scaled = g if pooling == "sum" else g / lengths
    expanded = jnp.broadcast_to(scaled[:, None, :], (8, 3, DIM))

    s_raw = coll_raw.apply_gradients(s_raw, {"s": ids}, {"s": expanded})
    s_pool = coll_pool.apply_gradients(s_pool, {"s": ids}, {"s": g})
    np.testing.assert_allclose(np.asarray(s_pool["s"].weights),
                               np.asarray(s_raw["s"].weights),
                               rtol=1e-5, atol=1e-6)


def test_hash_sequence_feature(devices8):
    """Hash variables pool too; padding is the EMPTY sentinel."""
    mesh = create_mesh(2, 4, devices8)
    spec = EmbeddingSpec(name="h", input_dim=-1, output_dim=DIM,
                         hash_capacity=512, pooling="mean",
                         initializer={"category": "constant", "value": 0.5})
    pad = pad_id_for(spec)
    assert pad == np.iinfo(np.int32).min
    coll = EmbeddingCollection((spec,), mesh)
    states = coll.init(jax.random.PRNGKey(0))
    ids = jnp.asarray(pad_ragged([[101, 202], [303], []], max_len=2,
                                 pad_id=pad))
    ids = jnp.tile(ids, (4, 1))[:8]
    rows = coll.pull(states, {"h": ids})["h"]
    rows = np.asarray(rows)
    # missing keys -> init rows (0.5); mean over valid slots stays 0.5,
    # empty sequences are all-padding -> zeros
    np.testing.assert_allclose(rows[0], 0.5, rtol=1e-6)
    np.testing.assert_allclose(rows[2], 0.0)
    g = jnp.ones((8, DIM), jnp.float32)
    states = coll.apply_gradients(states, {"h": ids}, {"h": g})
    assert int(states["h"].insert_failures) == 0
    # only 3 distinct keys materialized
    assert int(jax.device_get(states["h"].num_used())) == 3


def test_wide_key_sequence_feature(devices8):
    """Pooling over WIDE (64-bit pair) hash keys: a DIN-style behavior
    history addressing the full 2^62 space in an x64-off process —
    reference RaggedTensor lookups over input_dim=-1 hash variables
    (exb.py:315-321 + 231-233). Padding is the (EMPTY, EMPTY) pair."""
    from openembedding_tpu import hash_table as hl
    mesh = create_mesh(2, 4, devices8)
    spec = EmbeddingSpec(name="h", input_dim=-1, output_dim=DIM,
                         hash_capacity=512, pooling="mean",
                         key_dtype="wide",
                         initializer={"category": "constant", "value": 0.5})
    coll = EmbeddingCollection((spec,), mesh)
    states = coll.init(jax.random.PRNGKey(0))
    big = 3 << 60
    ids = jnp.asarray(ragged.pad_ragged_wide(
        [[big + 1, big + 2], [big + 3], []], max_len=2))
    assert ids.shape == (3, 2, 2)
    ids = jnp.tile(ids, (4, 1, 1))[:8]
    rows = np.asarray(coll.pull(states, {"h": ids})["h"])
    assert rows.shape == (8, DIM)
    # missing keys -> init rows (0.5); mean over valid slots stays 0.5,
    # all-padding sequences pool to zeros
    np.testing.assert_allclose(rows[0], 0.5, rtol=1e-6)
    np.testing.assert_allclose(rows[2], 0.0)
    g = jnp.ones((8, DIM), jnp.float32)
    states = coll.apply_gradients(states, {"h": ids}, {"h": g})
    assert int(states["h"].insert_failures) == 0
    assert int(jax.device_get(states["h"].num_used())) == 3
    # the materialized keys are the true 64-bit ids, not truncations
    keys = np.asarray(jax.device_get(states["h"].keys))
    live = keys[keys[:, 1] != hl.empty_key(np.int32)]
    assert set(hl.join64(live)) == {big + 1, big + 2, big + 3}
    # gradient parity with the manually expanded raw-lookup update: row 0's
    # two history slots each got g/2 (mean pooling over 2 valid ids)
    raw = EmbeddingCollection(
        (EmbeddingSpec(name="h", input_dim=-1, output_dim=DIM,
                       hash_capacity=512, key_dtype="wide",
                       initializer={"category": "constant", "value": 0.5}),),
        mesh)
    s_raw = raw.init(jax.random.PRNGKey(0))
    lengths = np.maximum((np.asarray(ids)[..., 1]
                          != hl.empty_key(np.int32)).sum(1), 1)
    expanded = jnp.broadcast_to(
        (g / jnp.asarray(lengths, jnp.float32)[:, None])[:, None, :],
        (8, 2, DIM))
    s_raw = raw.apply_gradients(s_raw, {"h": ids}, {"h": expanded})
    got = coll.pull(states, {"h": ids})["h"]
    want = raw.pull(s_raw, {"h": ids})["h"]
    # pooled pull of pooled-updated table == pooled manual of raw-updated
    np.testing.assert_allclose(
        np.asarray(got),
        np.asarray(want).sum(1) / lengths[:, None], rtol=1e-5, atol=1e-6)


def test_invalid_pooling_rejected_at_construction(devices8):
    mesh = create_mesh(2, 4, devices8)
    with pytest.raises(ValueError, match="avg"):
        EmbeddingCollection(
            (EmbeddingSpec(name="x", input_dim=8, output_dim=DIM,
                           pooling="avg"),), mesh)


def test_pooled_dense_kept_feature(devices8):
    """sparse_as_dense carries pooling: small-vocab sequence features pool
    inside DenseEmbeddings too."""
    from openembedding_tpu.hybrid import to_dense_spec, DenseEmbeddings
    spec = EmbeddingSpec(name="hist", input_dim=16, output_dim=DIM,
                         initializer={"category": "constant", "value": 0.5},
                         pooling="mean")
    mod = DenseEmbeddings((to_dense_spec(spec),))
    ids = jnp.asarray(pad_ragged([[1, 2], [7], []], max_len=3))
    params = mod.init(jax.random.PRNGKey(0), {"hist": ids})
    rows = np.asarray(mod.apply(params, {"hist": ids})["hist"])
    assert rows.shape == (3, DIM)
    np.testing.assert_allclose(rows[0], 0.5, rtol=1e-6)  # mean of two 0.5s
    np.testing.assert_allclose(rows[2], 0.0)             # empty sequence


def test_pooling_survives_serving_round_trip(devices8, tmp_path):
    """A pooled spec checkpointed + rebuilt by the registry keeps pooling."""
    from openembedding_tpu import checkpoint as ckpt
    from openembedding_tpu.serving.registry import ModelRegistry
    mesh = create_mesh(2, 4, devices8)
    spec = EmbeddingSpec(name="hist", input_dim=VOCAB, output_dim=DIM,
                         initializer={"category": "constant", "value": 0.25},
                         pooling="mean")
    coll = EmbeddingCollection((spec,), mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, model_sign="pooled-1")
    reg = ModelRegistry(create_mesh(1, 1, mesh.devices.ravel()[:1]))
    sign = reg.create_model(path)
    model = reg.find_model(sign)
    ids = jnp.asarray(pad_ragged([[1, 2], []], max_len=2))
    rows = np.asarray(model.lookup("hist", ids))
    assert rows.shape == (2, DIM)  # pooled, not [2, 2, DIM]
    np.testing.assert_allclose(rows[0], 0.25, rtol=1e-6)


def test_pooled_feature_trains_in_model(devices8):
    """DIN-style: a behavior-history column pooled into DeepFM."""
    mesh = create_mesh(2, 4, devices8)
    names = ("item", "hist")
    specs = (
        EmbeddingSpec(name="item", input_dim=VOCAB, output_dim=DIM),
        EmbeddingSpec(name="hist", input_dim=VOCAB, output_dim=DIM,
                      pooling="mean"),
        EmbeddingSpec(name="item:linear", input_dim=VOCAB, output_dim=1),
        EmbeddingSpec(name="hist:linear", input_dim=VOCAB, output_dim=1,
                      pooling="sum"),
    )
    coll = EmbeddingCollection(specs, mesh)
    import optax
    trainer = Trainer(deepctr.DeepFM(feature_names=names), coll,
                      optax.adam(1e-3))
    rng = np.random.RandomState(0)

    def batch():
        item = rng.randint(0, VOCAB, 16).astype(np.int32)
        hist = pad_ragged([rng.randint(0, VOCAB, rng.randint(0, 5))
                           for _ in range(16)], max_len=6)
        return {"label": (item % 2).astype(np.float32), "dense": None,
                "sparse": {"item": item, "hist": hist,
                           "item:linear": item, "hist:linear": hist}}

    state = trainer.init(jax.random.PRNGKey(0), trainer.shard_batch(batch()))
    for _ in range(3):
        state, m = trainer.train_step(state, batch())
        assert np.isfinite(float(m["loss"]))
    scores = trainer.eval_step(state, batch())
    assert scores.shape == (16,)
