"""graftplan: stats window, calibration, planner determinism, the
cost-model audit hook, and the online tuner's hysteresis discipline."""

import copy
import json

import numpy as np
import pytest

from openembedding_tpu.analysis import contracts, scope
from openembedding_tpu.analysis import plan as plan_lib
from openembedding_tpu.serving.batcher import (AdaptiveBatchTuner,
                                               LookupBatcher)
from openembedding_tpu.utils import envconfig
from openembedding_tpu.utils import observability as obs

FP = "cpu8-test-c1"


def make_window(*, lookups=3000, p50=64.0, p95=64.0, skew=0.3,
                stall_p95=0.0, window_s=10.0, tables=2):
    """A hand-built stats window in the collect_window schema."""
    t = {}
    for i in range(tables):
        t[f"c{i}"] = {"pull_unique_ratio": 0.6, "pull_key_skew": skew,
                      "dim": 16, "pull_rows_count": 100,
                      "pull_rows_p50": 1024.0}
    total = lookups * p50
    return {
        "schema_version": plan_lib.STATS_SCHEMA_VERSION,
        "kind": plan_lib.STATS_KIND,
        "fingerprint": FP,
        "device": None,
        "window_s": window_s,
        "tables": t,
        "serving": {"lookup_rows": {"count": lookups, "p50": p50,
                                    "p95": p95, "p99": p95,
                                    "sum": total}},
        "cache": {},
        "ingest": {"pops": 200, "stall_ms_sum": 0.0,
                   "stall_ms_p95": stall_p95},
    }


# --- window schema -----------------------------------------------------------

def test_collect_window_round_trips_live_stats():
    """collect_window snapshots the live gauges/histograms into a dict
    that validates against its own schema and drives build_plan."""
    scope.HISTOGRAMS.reset()
    obs.set_evaluate_performance(True)
    try:
        rng = np.random.RandomState(0)
        for _ in range(4):
            obs.record_batch_stats(
                {"w0": rng.randint(0, 64, 512),
                 "w1": rng.randint(0, 8, 512)})  # heavy skew
    finally:
        obs.set_evaluate_performance(False)
    for _ in range(20):
        obs.record_serving_lookup("w0", 48)
    w = plan_lib.collect_window(window_s=5.0, fingerprint=FP,
                                table_dims={"w0": 16, "w1": 8})
    assert plan_lib.validate_window(w) == []
    assert json.loads(json.dumps(w)) == w       # JSON-serialisable
    assert set(w["tables"]) >= {"w0", "w1"}
    assert w["tables"]["w0"]["dim"] == 16
    assert 0 < w["tables"]["w1"]["pull_key_skew"] <= 1.0
    assert w["serving"]["lookup_rows"]["count"] == 20
    plan = plan_lib.build_plan(w)
    assert plan.config.serving.batch_rows > 0
    scope.HISTOGRAMS.reset()


def test_validate_window_rejects_junk():
    assert plan_lib.validate_window([]) != []
    assert plan_lib.validate_window({}) != []
    w = make_window()
    assert plan_lib.validate_window(w) == []
    bad = dict(w, kind="trace")
    assert any("kind" in p for p in plan_lib.validate_window(bad))
    bad = dict(w, window_s=0)
    assert any("window_s" in p for p in plan_lib.validate_window(bad))
    bad = dict(w)
    del bad["tables"]
    assert any("tables" in p for p in plan_lib.validate_window(bad))
    with pytest.raises(ValueError, match="invalid stats window"):
        plan_lib.build_plan(dict(w, schema_version=99))


# --- calibration -------------------------------------------------------------

def synth_records(per_byte, per_launch, planes=("a2a", "psum", "a2a+cache")):
    """Trajectory records whose eps encodes t = a*bytes + b*launches
    exactly, so calibrate() must recover (a, b)."""
    recs = []
    for plane in planes:
        for batch in (512, 1024, 2048):
            params = plan_lib._record_params(plane, batch, 16)
            if plane == "a2a+int8":
                nb = (contracts.declared_exchange_bytes(
                          plane, "pull", dict(params, wire_itemsize=2))
                      + contracts.declared_exchange_bytes(
                          plane, "push", params))
            else:
                nb = sum(contracts.declared_exchange_bytes(
                    plane, prog, params) for prog in ("pull", "push"))
            spec = contracts.PLANE_SPECS[plane]
            launches = spec.launches["pull"] + spec.launches["push"]
            t = per_byte * nb + per_launch * launches
            recs.append({"fingerprint": FP, "plane": plane,
                         "config": {"batch": batch, "dim": 16},
                         "eps": batch / t})
    return recs


def test_calibrate_recovers_planted_constants():
    a, b = 2.5e-10, 80e-6
    calib = plan_lib.calibrate(synth_records(a, b), FP)
    assert calib.source == "trajectory"
    assert calib.n_records == 9
    assert calib.per_byte_s == pytest.approx(a, rel=1e-6)
    assert calib.per_launch_s == pytest.approx(b, rel=1e-6)


def test_calibrate_falls_back_deterministically():
    # wrong fingerprint, junk records, too few records -> defaults
    for records in ([], [{"fingerprint": "other", "plane": "a2a",
                          "config": {"batch": 512, "dim": 16},
                          "eps": 1e4}],
                    [{"not": "a record"}, "noise", None]):
        calib = plan_lib.calibrate(records, FP)
        assert calib.source == "defaults"
        assert calib.per_byte_s == plan_lib.DEFAULT_PER_BYTE_S
        assert calib.per_launch_s == plan_lib.DEFAULT_PER_LAUNCH_S


# --- plane spec registry -----------------------------------------------------

def test_every_registered_plane_declares_costs():
    """The cost registry must cover exactly the pull/push planes in the
    contract registry — a new plane without declared cost terms would
    silently fall out of planner ranking."""
    contract_planes = {p for (p, prog) in contracts.REGISTRY
                       if prog in ("pull", "push")}
    assert set(contracts.PLANE_SPECS) == contract_planes
    for plane, spec in contracts.PLANE_SPECS.items():
        params = {"global_batch": 1024, "dim": 16, "itemsize": 4,
                  "wire_itemsize": 2, "cache_k": 0,
                  "num_tables": 3, "dim_bucket": 16}
        for prog in ("pull", "push"):
            assert spec.exchange_bytes[prog](params) > 0, plane
            assert spec.launches[prog] >= 1, plane
        assert spec.hbm_overhead_bytes(params) >= 0
        assert spec.host_step_units > 0
        assert spec.workload_factor({"unique_ratio": 0.5,
                                     "key_skew": 0.3,
                                     "cache_hit_ratio": 0.5}) > 0


def test_cost_model_negative_via_spec_override(devices8):
    """check_cost_model must FAIL a declaration that drifts from the
    compiled HLO — audited with a deliberately wrong PlaneSpec against
    a real lowering (the graftcheck cost-audit failure path)."""
    from openembedding_tpu.analysis import programs
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(1, 8, devices8)
    txt, params = programs.lower_pull(mesh, "a2a", batch=512, dim=16)
    good = contracts.check_cost_model(txt, "a2a", "pull", params)
    assert good["rel_err"] <= good["tolerance"]
    spec = contracts.PLANE_SPECS["a2a"]
    wrong = dataclasses_replace_bytes(spec, factor=3.0)
    with pytest.raises(contracts.ContractViolation,
                       match="cost model"):
        contracts.check_cost_model(txt, "a2a", "pull", params,
                                   spec=wrong)


def dataclasses_replace_bytes(spec, factor):
    import dataclasses
    forms = dict(spec.exchange_bytes)
    orig = forms["pull"]
    forms["pull"] = lambda p, _o=orig: _o(p) * factor
    return dataclasses.replace(spec, exchange_bytes=forms)


# --- planner determinism + rules ---------------------------------------------

def test_build_plan_byte_identical():
    w = make_window(skew=0.3)
    records = synth_records(2.5e-10, 80e-6)
    texts, rats = set(), set()
    for _ in range(3):
        plan = plan_lib.build_plan(copy.deepcopy(w),
                                   copy.deepcopy(records))
        texts.add(plan_lib.render_config(plan.config))
        rats.add(plan_lib.format_rationale(plan))
    assert len(texts) == 1 and len(rats) == 1
    # the artifact round-trips through the loader it feeds
    cfg = envconfig.EnvConfig.load(config=json.loads(texts.pop()),
                                   env={})
    plan = plan_lib.build_plan(w, records)
    assert cfg == plan.config


def test_serving_knobs_follow_the_window():
    w = make_window(lookups=3000, p50=64.0, p95=64.0, window_s=10.0)
    plan = plan_lib.build_plan(w)
    cfg = plan.config
    # rows = pow2ceil(4 x p95) = 256; queue = 8 flushes
    assert cfg.serving.batch_rows == 256
    assert cfg.serving.batch_queue_rows == 8 * 256
    # wait = 4 x mean interarrival (3000/10s -> 3333us), clamped to
    # the envelope ceiling
    assert cfg.serving.batch_wait_us == cfg.plan.wait_ceiling_us
    # adaptive envelope: floor pow2(p50), ceiling 4x the static rows
    assert cfg.plan.rows_floor == 64
    assert cfg.plan.rows_ceiling == 1024
    knobs = {d.knob for d in plan.decisions}
    assert {"plane", "serving.batch_rows", "plan.rows_envelope",
            "plan.readers"} <= knobs
    # an idle window leaves serving alone
    idle = plan_lib.build_plan(make_window(lookups=0, p95=None))
    assert idle.config.serving.batch_rows == \
        envconfig.ServingConfig().batch_rows


def test_ingest_stalls_widen_reader_pool():
    stalled = plan_lib.build_plan(make_window(stall_p95=25.0))
    assert stalled.config.plan.readers == 4
    healthy = plan_lib.build_plan(make_window(stall_p95=0.0))
    assert healthy.config.plan.readers == 0


def test_compressed_gate_and_skew_pricing():
    """--no-compressed keeps bf16/int8 out of selection; heavy skew
    plus a cache prices a2a+cache below plain a2a."""
    w = make_window(skew=0.6)
    open_plan = plan_lib.build_plan(w)
    gated = plan_lib.build_plan(w, allow_compressed=False)
    assert gated.decisions[0].knob == "plane"
    assert gated.decisions[0].value not in plan_lib._COMPRESSED_EXCHANGE
    # both still PRICE every plane
    assert set(gated.scores) == set(open_plan.scores)
    costs = gated.scores
    # the skewed stream discounts the cached plane's WIRE term (its
    # extra collective launches are priced separately, so the total
    # can still favor a2a on launch-dominated hardware)
    assert costs["a2a+cache"]["wire_s"] < costs["a2a"]["wire_s"]
    assert costs["a2a+cache"]["workload_factor"] < 1.0


# --- the online tuner (hysteresis discipline) --------------------------------

class StubBatcher:
    """Knob/stats surface of LookupBatcher without threads — the tuner
    is driven via sample() directly."""

    name = "stub"

    def __init__(self, rows=256, wait=500, queue=2048):
        self._knobs = {"max_batch_rows": rows, "max_wait_us": wait,
                       "max_queue_rows": queue}
        self._stats = {"queue_rows": 0.0, "queued_requests": 0.0,
                       "flushes": 0.0, "flush_rows": 0.0,
                       "rejects": 0.0}

    def knobs(self):
        return dict(self._knobs)

    def stats(self):
        return dict(self._stats)

    def set_knobs(self, **kw):
        self._knobs.update(kw)
        return dict(self._knobs)

    def push_window(self, *, flushes, occupancy, queue_rows=0.0,
                    rejects=0.0):
        """Advance the counters by one observation window."""
        self._stats["flushes"] += flushes
        self._stats["flush_rows"] += occupancy * flushes \
            * self._knobs["max_batch_rows"]
        self._stats["rejects"] += rejects
        self._stats["queue_rows"] = queue_rows


def make_tuner(b, **over):
    plan = envconfig.PlanConfig(
        online=True, rows_floor=64, rows_ceiling=1024,
        wait_floor_us=50, wait_ceiling_us=2000,
        adjust_interval_ms=3_600_000,   # thread effectively parked
        hysteresis=over.pop("hysteresis", 3), step_factor=2.0)
    t = AdaptiveBatchTuner(b, plan, **over)
    t._stop.set()                       # tests drive sample() directly
    return t


def plan_adjust_count(knob, direction):
    return scope.HISTOGRAMS.counter("plan_adjust", knob=knob,
                                    direction=direction)


def test_tuner_oscillation_at_threshold_never_flaps():
    """A load oscillating across the occupancy deadband every sample
    must produce ZERO knob moves — asserted on the knobs AND on the
    oe_plan_adjust_total counters (the hysteresis satellite)."""
    scope.HISTOGRAMS.reset()
    b = StubBatcher()
    t = make_tuner(b, hysteresis=3)
    before = b.knobs()
    for i in range(24):
        if i % 2 == 0:
            b.push_window(flushes=10, occupancy=0.95)   # pressure up
        else:
            b.push_window(flushes=10, occupancy=0.10)   # pressure down
        assert t.sample() == 0
    assert b.knobs() == before
    assert t.adjustments == 0
    assert plan_adjust_count("max_batch_rows", "up") == 0
    assert plan_adjust_count("max_batch_rows", "down") == 0


def test_tuner_sustained_pressure_steps_after_hysteresis():
    scope.HISTOGRAMS.reset()
    b = StubBatcher(rows=256, wait=500)
    t = make_tuner(b, hysteresis=3)
    b.push_window(flushes=10, occupancy=0.95)
    assert t.sample() == 0
    b.push_window(flushes=10, occupancy=0.95)
    assert t.sample() == 0
    b.push_window(flushes=10, occupancy=0.95)
    assert t.sample() == 1              # third consecutive sample steps
    assert b.knobs()["max_batch_rows"] == 512
    assert b.knobs()["max_wait_us"] == 1000
    assert plan_adjust_count("max_batch_rows", "up") == 1
    assert plan_adjust_count("max_wait_us", "up") == 1
    # a direction flip restarts the streak
    b.push_window(flushes=10, occupancy=0.95)
    assert t.sample() == 0
    b.push_window(flushes=10, occupancy=0.05)
    assert t.sample() == 0
    assert b.knobs()["max_batch_rows"] == 512


def test_tuner_envelope_edge_is_quiet_and_kill_switch_restores():
    """Pinned at the ceiling, sustained pressure must NOT count moves
    (edge flapping); stop() restores the configured statics."""
    scope.HISTOGRAMS.reset()
    b = StubBatcher(rows=1024, wait=2000)       # already at ceiling
    t = make_tuner(b, hysteresis=2)
    for _ in range(8):
        b.push_window(flushes=10, occupancy=0.99, queue_rows=4096)
        t.sample()
    assert b.knobs()["max_batch_rows"] == 1024
    assert t.adjustments == 0
    assert plan_adjust_count("max_batch_rows", "up") == 0
    # now from below the ceiling: rejects alone force pressure up
    b2 = StubBatcher(rows=512, wait=2000)
    t2 = make_tuner(b2, hysteresis=2)
    for _ in range(2):
        b2.push_window(flushes=0, occupancy=0.0, rejects=5)
        t2.sample()
    assert b2.knobs()["max_batch_rows"] == 1024
    t2.stop(restore=True)
    assert b2.knobs()["max_batch_rows"] == 512  # statics restored
    assert b2.knobs()["max_wait_us"] == 2000
    scope.HISTOGRAMS.reset()
