"""graftchaos fault-injection plane (analysis/chaos.py + tools/graftchaos.py).

Pure-host lanes: plan parsing/determinism, the one-shot firing protocol
over the existing sync-point slot, torn-write crash semantics through
``fs.open_atomic`` (old committed bytes must survive — the tmp+rename
protocol's whole promise), env/EnvConfig arming, counter visibility, and
the sweep tool's target map.
"""

import json
import os
import threading

import pytest

from openembedding_tpu.analysis import chaos
from openembedding_tpu.analysis import concurrency
from openembedding_tpu.analysis import scope
from openembedding_tpu.utils import fs


@pytest.fixture(autouse=True)
def _clean_slot():
    yield
    chaos.clear_plan()
    concurrency.clear_schedule()


# --- plan parsing ------------------------------------------------------------

def test_fault_spec_validates():
    chaos.FaultSpec(point="ckpt.delta.commit", action="raise")
    with pytest.raises(ValueError, match="action"):
        chaos.FaultSpec(point="p.q", action="explode")
    with pytest.raises(ValueError, match="hit"):
        chaos.FaultSpec(point="p.q", action="raise", hit=0)
    with pytest.raises(ValueError, match="point"):
        chaos.FaultSpec(point="", action="raise")


def test_plan_json_roundtrip():
    plan = chaos.FaultPlan(
        [chaos.FaultSpec(point="a.b", action="delay_ms", hit=3, ms=5.0),
         chaos.FaultSpec(point="c.d", action="kill_thread",
                         thread="oe-ckpt-*")],
        seed=7)
    clone = chaos.FaultPlan.from_json(plan.to_json())
    assert clone.to_json() == plan.to_json()
    assert clone.seed == 7 and len(clone.faults) == 2


def test_plan_json_rejects_unknown_keys():
    with pytest.raises(ValueError, match="unknown"):
        chaos.FaultPlan.from_json(
            {"faults": [{"point": "a.b", "action": "raise",
                         "blast_radius": 9}]})


def test_plan_from_text_inline_and_file(tmp_path):
    spec = {"faults": [{"point": "a.b", "action": "raise"}], "seed": 1}
    inline = chaos.plan_from_text(json.dumps(spec))
    p = tmp_path / "plan.json"
    p.write_text(json.dumps(spec))
    from_file = chaos.plan_from_text(f"@{p}")
    assert inline.to_json() == from_file.to_json()


# --- firing protocol ---------------------------------------------------------

def test_fires_on_nth_arrival_once():
    plan = chaos.FaultPlan(
        [chaos.FaultSpec(point="x.y", action="raise", hit=2)])
    with chaos.active_plan(plan):
        concurrency.sync_point("x.y")          # arrival 1: pass
        with pytest.raises(chaos.ChaosError):
            concurrency.sync_point("x.y")      # arrival 2: fire
        concurrency.sync_point("x.y")          # one-shot: pass again
    assert len(plan.injected) == 1
    assert plan.injected[0]["point"] == "x.y"
    assert plan.injected[0]["hit"] == 2


def test_other_points_and_threads_unaffected():
    plan = chaos.FaultPlan(
        [chaos.FaultSpec(point="x.y", action="raise",
                         thread="worker-*")])
    errs = []

    def arrive(name):
        try:
            concurrency.sync_point("x.y")
        except chaos.ChaosError as e:
            errs.append(name)

    with chaos.active_plan(plan):
        concurrency.sync_point("x.other")      # different point: pass
        arrive("main")                         # thread filter: pass
        t = threading.Thread(target=lambda: arrive("w"),
                             name="worker-0")
        t.start()
        t.join()
    assert errs == ["w"]


def test_deterministic_injection_sequence():
    def run():
        plan = chaos.FaultPlan(
            [chaos.FaultSpec(point="a.b", action="raise", hit=2),
             chaos.FaultSpec(point="c.d", action="delay_ms", ms=0.0)],
            seed=3)
        with chaos.active_plan(plan):
            for _ in range(3):
                try:
                    concurrency.sync_point("a.b")
                except chaos.ChaosError:
                    pass
                concurrency.sync_point("c.d")
        return [(i["point"], i["action"], i["hit"])
                for i in plan.injected]

    assert run() == run()


def test_kill_thread_unwinds_past_except_exception():
    plan = chaos.FaultPlan(
        [chaos.FaultSpec(point="x.y", action="kill_thread")])
    with chaos.active_plan(plan):
        with pytest.raises(chaos.ChaosKill):
            try:
                concurrency.sync_point("x.y")
            except Exception:  # noqa: BLE001 — must NOT swallow the kill
                pytest.fail("ChaosKill was caught by except Exception")
    assert not isinstance(chaos.ChaosKill("x"), Exception)


def test_drop_net_is_a_connection_error():
    plan = chaos.FaultPlan(
        [chaos.FaultSpec(point="x.y", action="drop_net")])
    with chaos.active_plan(plan):
        with pytest.raises(ConnectionError):
            concurrency.sync_point("x.y")


def test_injection_counted_and_rendered():
    plan = chaos.FaultPlan(
        [chaos.FaultSpec(point="ctr.pt", action="raise")])
    before = scope.HISTOGRAMS.counter(chaos.COUNTER, point="ctr.pt",
                                      action="raise")
    with chaos.active_plan(plan):
        with pytest.raises(chaos.ChaosError):
            concurrency.sync_point("ctr.pt")
    after = scope.HISTOGRAMS.counter(chaos.COUNTER, point="ctr.pt",
                                     action="raise")
    assert after == before + 1
    lines = "\n".join(scope.HISTOGRAMS.prometheus_lines())
    assert 'oe_chaos_injected_total{action="raise",point="ctr.pt"}' \
        in lines


def test_plan_nests_inner_schedule():
    seen = []

    class Rec:
        def sync(self, key, point):
            seen.append(point)

    plan = chaos.FaultPlan(
        [chaos.FaultSpec(point="x.y", action="raise")], inner=Rec())
    with chaos.active_plan(plan):
        concurrency.sync_point("a.b")
    # non-firing arrivals still flow into the nested schedule
    assert seen == ["a.b"]


# --- torn_write through the real atomic writer -------------------------------

def test_torn_write_keeps_old_committed_file(tmp_path):
    """The crash model: the armed thread's next atomic commit truncates
    its tmp and dies BEFORE the rename — the old committed bytes survive
    whole, the half-written tmp stays as debris."""
    target = str(tmp_path / "manifest.json")
    with fs.open_atomic(target) as f:
        f.write(b"OLD-COMMITTED-CONTENT")
    plan = chaos.FaultPlan(
        [chaos.FaultSpec(point="x.y", action="torn_write")])
    with chaos.active_plan(plan):
        concurrency.sync_point("x.y")          # arms, does not raise
        with pytest.raises(chaos.ChaosKill, match="rename never ran"):
            with fs.open_atomic(target) as f:
                f.write(b"NEW-CONTENT-THAT-NEVER-LANDS!")
        with open(target, "rb") as f:
            assert f.read() == b"OLD-COMMITTED-CONTENT"
        debris = [n for n in os.listdir(tmp_path)
                  if fs.ATOMIC_TMP_SUFFIX in n]
        assert debris, "expected the torn tmp file as debris"
        # the tear is consumed: the next commit goes through clean
        with fs.open_atomic(target) as f:
            f.write(b"SECOND-TRY")
        with open(target, "rb") as f:
            assert f.read() == b"SECOND-TRY"
    assert [i["action"] for i in plan.injected] == ["torn_write"]


def test_torn_write_is_per_thread(tmp_path):
    """A tear armed on one thread must not fire another thread's
    commit."""
    target = str(tmp_path / "f.bin")
    plan = chaos.FaultPlan(
        [chaos.FaultSpec(point="x.y", action="torn_write")])
    ok = []

    def other_commit():
        with fs.open_atomic(target) as f:
            f.write(b"bystander")
        ok.append(True)

    with chaos.active_plan(plan):
        concurrency.sync_point("x.y")          # arms THIS thread
        t = threading.Thread(target=other_commit)
        t.start()
        t.join()
        assert ok == [True]
        with open(target, "rb") as f:
            assert f.read() == b"bystander"


def test_commit_hook_cleared_with_plan(tmp_path):
    plan = chaos.FaultPlan(
        [chaos.FaultSpec(point="x.y", action="torn_write")])
    chaos.install_plan(plan)
    chaos.clear_plan()
    target = str(tmp_path / "f.bin")
    with fs.open_atomic(target) as f:
        f.write(b"clean")
    with open(target, "rb") as f:
        assert f.read() == b"clean"
    assert chaos.current_plan() is None


# --- arming from the environment --------------------------------------------

def test_install_from_env_inline():
    env = {"OE_CHAOS_PLAN": json.dumps(
        {"faults": [{"point": "x.y", "action": "raise"}]})}
    plan = chaos.install_from_env(env)
    try:
        assert plan is not None
        with pytest.raises(chaos.ChaosError):
            concurrency.sync_point("x.y")
    finally:
        chaos.clear_plan()
    assert chaos.install_from_env({}) is None


def test_envconfig_chaos_section_arms(tmp_path):
    from openembedding_tpu.utils.envconfig import EnvConfig
    spec = {"faults": [{"point": "x.y", "action": "raise"}]}
    cfg = EnvConfig.load(env={"OE_CHAOS_PLAN": json.dumps(spec)})
    assert cfg.chaos.plan
    plan = cfg.apply_chaos()
    try:
        assert plan is not None and len(plan.faults) == 1
        assert chaos.current_plan() is plan
    finally:
        chaos.clear_plan()
    # empty section is a no-op
    assert EnvConfig.load(env={}).apply_chaos() is None


def test_envconfig_rejects_malformed_plan():
    from openembedding_tpu.utils.envconfig import EnvConfig
    with pytest.raises(ValueError, match="ChaosConfig.plan"):
        EnvConfig.load(env={"OE_CHAOS_PLAN": "{not json"})


# --- the sweep tool's target map --------------------------------------------

def test_discovery_finds_the_load_bearing_points():
    points = chaos.discover_sync_points()
    for p in ("ckpt.delta.commit", "trainer.fit.step",
              "trainer.resume.restore", "ingest.ring.put",
              "routing.attempt", "registry.swap.commit"):
        assert p in points
    # dotted lower_snake names only — never doc-text artifacts
    assert all("." in p and " " not in p for p in points)


def test_sweep_targets_cover_every_swept_point():
    from tools import graftchaos as gc
    targets = gc.sweep_targets(["ckpt", "ingest", "serving"], "", None)
    covered = {p for p, _a, _s in targets}
    expect = {p for p in chaos.discover_sync_points()
              if chaos.subsystem_of(p) in ("ckpt", "ingest", "serving")}
    assert covered == expect
    # torn_write only where an atomic commit is downstream; drop_net
    # only where the failover client classifies network errors
    for p, a, _s in targets:
        if a == "torn_write":
            assert chaos.subsystem_of(p) == "ckpt"
        if a == "drop_net":
            assert p == "routing.attempt"
