"""graftrace static plane: every seeded violation fires, the package is
clean, and each rule's semantic edges hold on minimal sources.

The fixture (tests/fixtures/graftrace_violations.py) marks each intended
violation with ``# expect: JGxxx``; the analyzer must report EXACTLY
that set — nothing missed (rules work), nothing extra (sanctioned
patterns: guarded accesses, consistent lock order, joined non-daemon
workers, inline suppressions). The runtime detector and the
interleaving harness have their own lanes (test_traced_locks.py,
test_interleaving.py).
"""

import os
import re

from openembedding_tpu.analysis import concurrency

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
FIXTURE = os.path.join(HERE, "fixtures", "graftrace_violations.py")


def _expected(source):
    out = set()
    for i, line in enumerate(source.splitlines(), start=1):
        for rule in re.findall(r"# expect: (JG\d+)", line):
            out.add((i, rule))
    return out


def test_every_seeded_violation_fires():
    with open(FIXTURE) as fh:
        src = fh.read()
    expected = _expected(src)
    # JG100 (parse failure) cannot live in a parseable fixture; it has
    # its own unit test below
    assert {r for _ln, r in expected} == set(concurrency.RULES) - {"JG100"}
    got = {(v.line, v.rule) for v in concurrency.trace_source(src, FIXTURE)}
    assert got == expected, (
        f"missed: {expected - got}; spurious: {got - expected}")


def test_shipped_package_is_clean():
    """The CI gate, enforced from inside the suite as well: zero
    lock-discipline violations in openembedding_tpu/."""
    pkg = os.path.join(ROOT, "openembedding_tpu")
    violations = concurrency.trace_paths([pkg])
    assert violations == [], "\n".join(str(v) for v in violations)


def test_cli_exit_codes():
    from tools.graftrace import main
    assert main([os.path.join(ROOT, "openembedding_tpu")]) == 0
    assert main([FIXTURE]) == 1
    assert main([FIXTURE, "--rules", "JG102"]) == 1


def test_parse_failure_is_jg100_and_unfilterable(tmp_path):
    got = concurrency.trace_source("def broken(:\n", "bad.py")
    assert [v.rule for v in got] == ["JG100"]
    # inconsistent dedent raises IndentationError (a SyntaxError, NOT a
    # TokenError) out of tokenize inside the suppression scan — must
    # still land as JG100, not a traceback
    bad_indent = "def f():\n        x = 1\n    y = 2\n"
    got = concurrency.trace_source(bad_indent, "bad.py")
    assert [v.rule for v in got] == ["JG100"]
    from tools.graftrace import main
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    assert main([str(bad), "--rules", "JG104"]) == 1


# --- JG101 semantics ---------------------------------------------------------

_RACY = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def _run(self):
        self.count += 1

    def start(self):
        threading.Thread(target=self._run).start()

    def read(self):
        with self._lock:
            return self.count
"""


def test_jg101_fires_on_lockfree_write_in_thread():
    got = concurrency.trace_source(_RACY)
    assert [v.rule for v in got] == ["JG101"]
    assert "self.count" in got[0].message


def test_jg101_needs_a_thread_spawn():
    # same lockset inconsistency, but the class spawns nothing: callers'
    # threads are invisible to the static pass (the runtime plane's job)
    src = _RACY.replace("threading.Thread(target=self._run).start()",
                        "self._run()")
    assert concurrency.trace_source(src) == []


def test_jg101_spares_join_protocol_fields():
    # a field NEVER locked anywhere has no lockset discipline to violate
    # (offload's host store: guarded by thread joins, not locks)
    src = _RACY.replace("with self._lock:\n            return self.count",
                        "return self.count")
    assert concurrency.trace_source(src) == []


def test_jg101_no_common_lock():
    src = """
import threading

class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.n = 0

    def _run(self):
        with self._a:
            self.n += 1

    def start(self):
        threading.Thread(target=self._run).start()

    def read(self):
        with self._b:
            return self.n
"""
    got = concurrency.trace_source(src)
    assert [v.rule for v in got] == ["JG101"]
    assert "no COMMON lock" in got[0].message


def test_jg101_interprocedural_entry_held():
    # a method invoked ONLY from inside `with self._lock:` blocks is
    # analyzed with the lock held — the offload._evict pattern
    src = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def _bump(self):
        self.n += 1

    def _run(self):
        with self._lock:
            self._bump()

    def start(self):
        threading.Thread(target=self._run).start()

    def read(self):
        with self._lock:
            return self.n
"""
    assert concurrency.trace_source(src) == []


# --- JG102 / JG103 semantics -------------------------------------------------

def test_jg102_consistent_order_is_clean():
    src = """
import threading
A = threading.Lock()
B = threading.Lock()

def f():
    with A:
        with B:
            pass

def g():
    with A:
        with B:
            pass
"""
    assert concurrency.trace_source(src) == []
    bad = src.replace("def g():\n    with A:\n        with B:",
                      "def g():\n    with B:\n        with A:")
    got = concurrency.trace_source(bad)
    assert {v.rule for v in got} == {"JG102"}


def test_jg103_condition_wait_is_sanctioned():
    # Condition.wait RELEASES its lock while blocked — the one sanctioned
    # block-under-lock pattern (SerialSchedule uses it)
    src = """
import threading

class C:
    def __init__(self):
        self._cv = threading.Condition()

    def waiter(self):
        with self._cv:
            self._cv.wait(1.0)
"""
    assert concurrency.trace_source(src) == []


def test_jg103_thread_join_under_lock():
    src = """
import threading
LOCK = threading.Lock()

class C:
    def __init__(self):
        self._t = threading.Thread(target=print)

    def stop(self):
        with LOCK:
            self._t.join()
"""
    got = concurrency.trace_source(src)
    assert [v.rule for v in got] == ["JG103"]


def test_jg103_str_join_is_not_blocking():
    src = """
import threading
LOCK = threading.Lock()

def render(parts):
    with LOCK:
        return ", ".join(parts)
"""
    assert concurrency.trace_source(src) == []


# --- JG104 semantics / suppression -------------------------------------------

def test_jg104_joined_daemon_is_clean():
    src = """
import threading

class C:
    def __init__(self):
        self._t = threading.Thread(target=print, daemon=True)

    def close(self):
        self._t.join(5)
"""
    assert concurrency.trace_source(src) == []


def test_suppression_scopes():
    src = """
import threading
LOCK = threading.Lock()
import time

def f():
    with LOCK:
        time.sleep(1)  # graftrace: disable=JG103

def g():  # graftrace: disable
    with LOCK:
        time.sleep(1)

def h():
    with LOCK:
        time.sleep(1)
"""
    got = concurrency.trace_source(src)
    assert [(v.rule, v.line) for v in got] == [("JG103", 16)]


def test_suppression_rule_list_fails_closed():
    base = ("import threading\n"
            "LOCK = threading.Lock()\n"
            "import time\n"
            "def f():\n"
            "    with LOCK:\n"
            "        time.sleep(1)  # graftrace: disable={}\n")
    # lowercase rule names normalize (suppressed)
    assert concurrency.trace_source(base.format("jg103")) == []
    # a typo'd/unknown rule list must NOT widen into suppress-all:
    # the violation still fires and CI points at the bad comment
    for junk in ("jg1o3", "garbage", "", "JG103 because reasons"):
        got = concurrency.trace_source(base.format(junk))
        assert [v.rule for v in got] == ["JG103"], junk


# --- thread-spawning inventory ----------------------------------------------

def _package_inventory():
    """(relpath, class, lock fields) for every thread-spawning class,
    plus every thread name literal, straight from the analyzer index."""
    import ast

    pkg = os.path.join(ROOT, "openembedding_tpu")
    classes = {}
    names = set()
    for root, _dirs, files in os.walk(pkg):
        if "__pycache__" in root:
            continue
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            rel = os.path.relpath(path, ROOT)
            a = concurrency.Analyzer(path, src)
            a._index(ast.parse(src))
            for cls in a.classes:
                if cls.spawns_thread:
                    classes[(rel, cls.name)] = tuple(
                        sorted(cls.lock_fields))
            for n in ast.walk(ast.parse(src)):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "Thread":
                    for kw in n.keywords:
                        if kw.arg != "name":
                            continue
                        v = kw.value
                        if isinstance(v, ast.Constant):
                            names.add(v.value)
                        elif isinstance(v, ast.JoinedStr):
                            names.add("".join(
                                p.value if isinstance(p, ast.Constant)
                                else "*" for p in v.values))
    return classes, names


def test_thread_spawning_inventory_is_pinned():
    """Every class that spawns a thread is visible to the lockset audit
    (JG101's thread-reachability is keyed off this index) and carries
    the lock fields the audit reasons over. Pins in particular the two
    post-audit arrivals: the ``AdaptiveBatchTuner`` sampler (PR 17,
    ``_lock``-guarded decision rounds) and the chaos-armed checkpoint
    writer/compactor threads (PR 16 — module-function spawns, so they
    appear as named threads, not classes). A NEW spawn site failing
    this test is the point: extend the pin AND the lockset audit."""
    classes, names = _package_inventory()
    assert classes == {
        ("openembedding_tpu/data/stream.py", "ShardStream"): ("_cv",),
        ("openembedding_tpu/offload.py", "ShardedOffloadedTable"):
            ("_book",),
        ("openembedding_tpu/serving/batcher.py", "AdaptiveBatchTuner"):
            ("_lock",),
        ("openembedding_tpu/serving/batcher.py", "LookupBatcher"):
            ("_cv",),
        ("openembedding_tpu/serving/registry.py", "ModelRegistry"):
            ("_lock",),
        ("openembedding_tpu/serving/rest.py", "ControllerServer"): (),
        ("openembedding_tpu/training.py", "Trainer"): (),
        ("openembedding_tpu/utils/observability.py", "Reporter"):
            ("_lock",),
    }
    # every thread in the package is named (chaos pins faults to
    # thread-name patterns; an anonymous thread is untargetable)
    assert names == {
        "oe-ckpt-writer-*", "oe-ckpt-compact", "oe-writeback-*",
        "oe-persist-*", "oe-prep", "oe-ingest-*", "oe-batcher-*",
        "oe-plan-*", "oe-model-load-*", "oe-rest-*", "oe-reporter",
    }
