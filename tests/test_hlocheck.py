"""Unit tests for the HLO-text parsing core (analysis/contracts.py, the
absorbed utils/hlocheck.py) on SYNTHETIC HLO — previously this layer was
only exercised indirectly through test_alltoall's real lowered programs.

Covers the parsing contracts the real-program tests silently rely on:
async -start/-done pair dedup, while-body single-count, byte/bound
arithmetic, donation-header parsing, f64 and host-transfer detection.
"""

import pytest

from openembedding_tpu.analysis import contracts
from openembedding_tpu.utils import hlocheck  # the compat shim


SYNC = """
HloModule jit_pull
  %x = f32[128,16]{1,0} all-to-all(f32[128,16]{1,0} %a), replica_groups={}
  %y = f32[64,16]{1,0} all-gather(f32[8,16]{1,0} %b), dimensions={0}
  %z = f32[] add(f32[] %c, f32[] %d)
"""

ASYNC = """
HloModule jit_pull
  %ags = (f32[8,16]{1,0}, f32[64,16]{1,0}) all-gather-start(f32[8,16]{1,0} %b)
  %agd = f32[64,16]{1,0} all-gather-done((f32[8,16],f32[64,16]) %ags)
  %ars = f32[4]{0} all-reduce-start(f32[4]{0} %c)
  %ard = f32[4]{0} all-reduce-done(f32[4]{0} %ars)
"""

WHILE_BODY = """
HloModule jit_loop
%body (p: (s32[], f32[128,16])) -> (s32[], f32[128,16]) {
  %aa = f32[128,16]{1,0} all-to-all(f32[128,16]{1,0} %q)
  ROOT %t = (s32[], f32[128,16]) tuple(%i, %aa)
}
ENTRY %main {
  %w = (s32[], f32[128,16]) while((s32[], f32[128,16]) %init),
      condition=%cond, body=%body
}
"""


def test_collect_sync_ops_and_bytes():
    got = hlocheck.collect_collectives(SYNC)
    assert got == [("all-to-all", 128 * 16 * 4, 128 * 16 * 4),
                   ("all-gather", 64 * 16 * 4, 64 * 16 * 4)]
    assert hlocheck.summarize(SYNC) == {
        "all-to-all": (1, 8192), "all-gather": (1, 4096)}


def test_async_start_done_pairs_dedup():
    """-start counts once (with max SINGLE buffer, not the operand+result
    tuple sum), -done not at all — counting both would double every
    byte."""
    got = hlocheck.collect_collectives(ASYNC)
    assert [op for op, _b, _l in got] == ["all-gather", "all-reduce"]
    ag = got[0]
    # tuple type sums operand+result; the max single buffer is the result
    assert ag[1] == (8 * 16 + 64 * 16) * 4
    assert ag[2] == 64 * 16 * 4
    assert hlocheck.summarize(ASYNC)["all-gather"][0] == 1


def test_while_body_counts_once():
    """Static program size: one all-to-all in a while body is ONE op
    regardless of trip count — per-invocation shapes are the contract."""
    assert hlocheck.summarize(WHILE_BODY) == {"all-to-all": (1, 8192)}


def test_bound_arithmetic_and_slack():
    # bound = batch_slice * dim * itemsize * 1.0625; the SYNC gather is
    # 4096 bytes: passes at the bound, fails just under it
    hlocheck.check_a2a_pull_hlo(SYNC, batch_slice=64, dim=16)
    with pytest.raises(AssertionError, match="row-assembly bound"):
        hlocheck.check_a2a_pull_hlo(SYNC, batch_slice=60, dim=16)
    # slack: a gather 6% over the nominal size still passes
    assert int(64 * 16 * 4 * hlocheck.ROW_ASSEMBLY_SLACK) >= 4096


def test_missing_all_to_all_refused():
    no_a2a = SYNC.replace("all-to-all", "all-reduce")
    with pytest.raises(AssertionError, match="WITHOUT an all-to-all"):
        hlocheck.check_a2a_pull_hlo(no_a2a, batch_slice=64, dim=16)


def test_donation_header_parsing():
    header = ('HloModule jit_step, is_scheduled=true, '
              'input_output_alias={ {0}: (0, {}, may-alias), '
              '{1}: (3, {}, must-alias) }, '
              'entry_computation_layout={(f32[8])->f32[8]}\n')
    assert contracts.donated_params(header) == (0, 3)
    assert contracts.check_donation(header, 2) == (0, 3)
    with pytest.raises(contracts.ContractViolation, match="donation"):
        contracts.check_donation("HloModule jit_step\n%x = f32[] add()", 1)


def test_f64_detection():
    leak = SYNC + "  %bad = f64[256]{0} convert(f32[256]{0} %z)\n"
    assert not contracts.find_f64(SYNC)
    with pytest.raises(contracts.ContractViolation, match="f64"):
        contracts.check_no_f64(leak)


def test_host_transfer_detection():
    cb = SYNC + ('  %c = () custom-call(f32[] %r), '
                 'custom_call_target="xla_python_cpu_callback"\n')
    out = SYNC + "  %o = token[] outfeed(f32[] %r, token[] %t)\n"
    assert contracts.host_transfer_ops(SYNC) == []
    assert contracts.host_transfer_ops(cb) == ["host-callback"]
    assert contracts.host_transfer_ops(out) == ["outfeed"]


def test_host_transfer_tuple_result_types():
    """Real infeed/send ops carry TUPLE result types with spaces — the
    audit must still see them (regression: a \\S+ type capture silently
    skipped exactly these)."""
    inf = SYNC + ("  %i = ((f32[4096,16]{1,0}), token[]) "
                  "infeed(token[] %t)\n")
    snd = SYNC + ("  %s = (f32[4096]{0}, u32[], token[]) "
                  "send(f32[4096]{0} %x, token[] %t), channel_id=1, "
                  "is_host_transfer=true\n")
    assert contracts.host_transfer_ops(inf) == ["infeed"]
    assert contracts.host_transfer_ops(snd) == ["send"]
    with pytest.raises(contracts.ContractViolation, match="host"):
        contracts.check_no_host_transfers(inf)
    # device-to-device channel send/recv (collective-permute decomposed
    # by the SPMD partitioner) is NOT a host transfer
    d2d = SYNC + ("  %s = (f32[4096]{0}, u32[], token[]) "
                  "send(f32[4096]{0} %x, token[] %t), channel_id=1\n")
    assert contracts.host_transfer_ops(d2d) == []


def test_copy_bytes():
    prog = SYNC + "  %cp = f32[1024,16]{1,0} copy(f32[1024,16]{1,0} %w)\n"
    assert contracts.max_copy_bytes(SYNC) == 0
    assert contracts.max_copy_bytes(prog) == 1024 * 16 * 4
    # async copy-start: tuple result type (operand + result + context) —
    # max single buffer, not the tuple sum
    astart = SYNC + ("  %cs = (f32[65536,16]{1,0}, f32[65536,16]{1,0}, "
                     "u32[]) copy-start(f32[65536,16]{1,0} %w)\n")
    assert contracts.max_copy_bytes(astart) == 65536 * 16 * 4


def test_push_contract_requires_global_batch():
    """check_program must refuse to guess global_batch for push
    contracts (a batch_slice default understates the overflow-fallback
    bound on any data>1 mesh)."""
    with pytest.raises(KeyError, match="global_batch"):
        contracts.check_program(SYNC, "a2a", "push",
                                batch_slice=64, dim=16)
