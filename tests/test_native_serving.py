"""Native C++ serving runtime: lookups must match the Python registry.

The reference's serving data plane is a packed C++ library (libcexb_pack.so,
exb_* C ABI) loaded without Python; liboe_serving.so plays that role over
this framework's checkpoint format. Ground truth here is the Python
registry's read-only pull on the same checkpoint.
"""

import os
import shutil

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
from openembedding_tpu import checkpoint as ckpt
from openembedding_tpu.parallel.mesh import create_mesh

DIM = 4

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


@pytest.fixture(scope="module")
def native_lib():
    from openembedding_tpu.serving import native
    return native.build_library()


@pytest.fixture(scope="module")
def saved_model(tmp_path_factory, devices8):
    """A trained-ish checkpoint with one bounded and one hash variable."""
    mesh = create_mesh(2, 4, jax.devices()[:8])
    specs = (
        EmbeddingSpec(name="arr", input_dim=100, output_dim=DIM,
                      initializer={"category": "normal", "stddev": 0.3}),
        EmbeddingSpec(name="hsh:linear", input_dim=-1, output_dim=DIM,
                      hash_capacity=512,
                      initializer={"category": "normal", "stddev": 0.3}),
    )
    coll = EmbeddingCollection(
        specs, mesh, default_optimizer={"category": "adagrad",
                                        "learning_rate": 0.1})
    states = coll.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    hkeys = (rng.randint(1, 1 << 30, 40) * 7919).astype(np.int32)
    for _ in range(2):
        inputs = {"arr": jnp.asarray(rng.randint(0, 100, 32, dtype=np.int64)
                                     .astype(np.int32)),
                  "hsh:linear": jnp.asarray(rng.choice(hkeys, 32))}
        rows = coll.pull(states, inputs, batch_sharded=False)
        grads = {k: jnp.ones_like(v) for k, v in rows.items()}
        states = coll.apply_gradients(states, inputs, grads,
                                      batch_sharded=False)
    path = str(tmp_path_factory.mktemp("native") / "model")
    ckpt.save_checkpoint(path, coll, states, model_sign="native-1")
    return path, coll, states, hkeys


def test_native_matches_python_registry(native_lib, saved_model):
    from openembedding_tpu.serving.native import NativeModel
    path, coll, states, hkeys = saved_model
    with NativeModel(path, native_lib) as m:
        assert m.sign == "native-1"
        assert m.num_variables == 2
        assert m.variable_dim("arr") == DIM
        assert m.variable_vocab("arr") == 100
        assert m.variable_vocab("hsh:linear") == -1

        # bounded: all rows + invalid ids
        probe = np.concatenate([np.arange(100), [-1, 100, 10**7]])
        got = m.lookup("arr", probe)
        # ground truth: out-of-vocab ids are invalid (-1 -> zero rows)
        gt_ids = np.where((probe < 0) | (probe >= 100), -1, probe)
        want = np.asarray(coll.pull(
            states, {"arr": jnp.asarray(gt_ids.astype(np.int32))},
            batch_sharded=False, read_only=True)["arr"])
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)

        # hash: trained keys return their rows, unknown keys zeros
        got = m.lookup("hsh:linear", hkeys.astype(np.int64))
        want = np.asarray(coll.pull(
            states, {"hsh:linear": jnp.asarray(hkeys)},
            batch_sharded=False, read_only=True)["hsh:linear"])
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(
            m.lookup("hsh:linear", [123456789]), 0.0)

        # lookup by variable id too (exb_get_model_variable takes ids)
        got = m.lookup(0, np.arange(10))
        np.testing.assert_allclose(got, want := np.asarray(coll.pull(
            states, {"arr": jnp.arange(10, dtype=jnp.int32)},
            batch_sharded=False, read_only=True)["arr"]),
            rtol=1e-6, atol=1e-7)


def test_native_errors(native_lib, tmp_path, saved_model):
    from openembedding_tpu.serving.native import NativeModel
    with pytest.raises(RuntimeError, match="model_meta"):
        NativeModel(str(tmp_path / "nope"), native_lib)
    path = saved_model[0]
    with NativeModel(path, native_lib) as m:
        with pytest.raises(KeyError):
            m.lookup("missing_var", [0])


def test_native_bfloat16_rows(native_lib, tmp_path, devices8):
    """bf16 checkpoints serve real values (numpy stores them as '<V2')."""
    from openembedding_tpu.serving.native import NativeModel
    mesh = create_mesh(1, 1, jax.devices()[:1])
    spec = EmbeddingSpec(name="b", input_dim=32, output_dim=DIM,
                         dtype="bfloat16",
                         initializer={"category": "constant", "value": 0.5})
    coll = EmbeddingCollection(
        (spec,), mesh, default_optimizer={"category": "default"})
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "bf16")
    ckpt.save_checkpoint(path, coll, states, include_optimizer=False)
    with NativeModel(path, native_lib) as m:
        rows = m.lookup("b", [0, 31, 32])
        np.testing.assert_allclose(rows[0], 0.5, rtol=1e-2)
        np.testing.assert_allclose(rows[2], 0.0)


def test_native_loads_multihost_parts(native_lib, tmp_path, devices8):
    """Part-file dumps (multi-host layout) serve through the native lib.

    Simulated by renaming a single-host dump's files into two keyed parts,
    exactly the bytes a 2-process save writes."""
    from openembedding_tpu.serving.native import NativeModel
    mesh = create_mesh(1, 1, jax.devices()[:1])
    spec = EmbeddingSpec(name="arr", input_dim=64, output_dim=DIM,
                         initializer={"category": "normal", "stddev": 0.2})
    coll = EmbeddingCollection(
        (spec,), mesh, default_optimizer={"category": "default"})
    states = coll.init(jax.random.PRNGKey(2))
    path = str(tmp_path / "mh")
    ckpt.save_checkpoint(path, coll, states, include_optimizer=False)
    vdir = tmp_path / "mh" / ckpt._var_dir(0, "arr")
    full = np.load(vdir / "weights.npy")
    (vdir / "weights.npy").unlink()
    # part 0: even logical ids; part 1: odd — arbitrary per-host ownership
    for k, ids in enumerate([np.arange(0, 64, 2), np.arange(1, 64, 2)]):
        np.save(vdir / f"part{k}_ids.npy", ids.astype(np.int64))
        np.save(vdir / f"part{k}_weights.npy", full[ids])
    with NativeModel(path, native_lib) as m:
        assert m.variable_vocab("arr") == 64
        got = m.lookup("arr", np.arange(-1, 65))
        want = np.zeros((66, DIM), np.float32)
        want[1:65] = full
        want[65] = 0.0
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_native_wide_key_dump(native_lib, tmp_path, devices8):
    """Wide ([n, 2] int32 pair) hash dumps serve through the C++ lib:
    keys.npy rows are joined to 64-bit ids in the native index."""
    from openembedding_tpu import hash_table as hl
    from openembedding_tpu.serving.native import NativeModel
    mesh = create_mesh(2, 4, jax.devices()[:8])
    specs = (EmbeddingSpec(name="w", input_dim=-1, output_dim=DIM,
                           hash_capacity=512, key_dtype="wide",
                           initializer={"category": "constant",
                                        "value": 0.0},
                           optimizer={"category": "sgd",
                                      "learning_rate": 1.0}),)
    coll = EmbeddingCollection(specs, mesh)
    states = coll.init(jax.random.PRNGKey(0))
    k64 = np.asarray([17, 17 + (1 << 34), (5 << 45) + 3, -44], np.int64)
    pairs = jnp.asarray(hl.split64(k64))
    rows = coll.pull(states, {"w": pairs}, batch_sharded=False)
    g = jnp.asarray(np.arange(1, 5, dtype=np.float32))[:, None] * \
        jnp.ones_like(rows["w"])
    states = coll.apply_gradients(states, {"w": pairs}, {"w": g},
                                  batch_sharded=False)
    p = str(tmp_path / "m")
    ckpt.save_checkpoint(p, coll, states, model_sign="wide-native-1")
    m = NativeModel(p, lib_path=native_lib)
    got = m.lookup("w", k64)
    np.testing.assert_allclose(got[:, 0], [-1.0, -2.0, -3.0, -4.0],
                               rtol=1e-6)
    # the framework's [n, 2] pair representation works directly...
    got_pairs = m.lookup("w", hl.split64(k64))
    np.testing.assert_array_equal(got_pairs, got)
    # ...and so do [B, F, 2] fused-mapper-shaped batches
    got_bf = m.lookup("w", hl.split64(k64.reshape(2, 2)))
    assert got_bf.shape == (2, 2, DIM)
    np.testing.assert_array_equal(got_bf.reshape(4, DIM), got)
    # unknown 64-bit key -> zero row; lo-word collision stays distinct
    got2 = m.lookup("w", np.asarray([17 + (1 << 35)], np.int64))
    np.testing.assert_array_equal(got2, 0.0)


# --- delta-compacted dirs served directly (ISSUE 14 satellite) ---------------

def _delta_dir(tmp_path, devices8, steps=2, name="d"):
    """Armed chain + ``steps`` committed deltas (compaction budgets
    lifted — these tests need the CHAIN on disk; the tiny test base
    would otherwise trip the bytes-ratio fold immediately); returns
    (coll, per-step (states, hash-probe) list, path)."""
    import openembedding_tpu.checkpoint_delta as cd
    from test_delta_checkpoint import make_coll, train
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / name)
    ckpt.save_checkpoint(path, coll, states, model_sign=f"delta-{name}")
    per_step = []
    for i in range(steps):
        states, idx = train(coll, states, seed=i)
        info = cd.save_delta(path, coll, states, step=i + 1,
                             compact_chain_len=1000,
                             compact_bytes_ratio=1000.0)
        assert info["seq"] == i + 1
        per_step.append((states, np.asarray(idx["hsh"])))
    return coll, per_step, path


def _native_vs_python(m, coll, states, hkeys, vocab=256):
    """Native rows must EXACTLY match the python pull on ``states``."""
    probe = np.concatenate([np.arange(vocab), [-1, vocab, 10**7]])
    gt_ids = np.where((probe < 0) | (probe >= vocab), -1, probe)
    want = np.asarray(coll.pull(
        states, {"arr": jnp.asarray(gt_ids.astype(np.int32))},
        batch_sharded=False, read_only=True)["arr"], np.float32)
    np.testing.assert_array_equal(
        m.lookup("arr", probe).astype(np.float32), want)
    want_h = np.asarray(coll.pull(
        states, {"hsh": jnp.asarray(hkeys)}, batch_sharded=False,
        read_only=True)["hsh"], np.float32)
    np.testing.assert_array_equal(
        m.lookup("hsh", hkeys.astype(np.int64)).astype(np.float32),
        want_h)


def test_native_reads_delta_chain_directly(native_lib, tmp_path,
                                           devices8):
    """The zero-JAX mmap path resolves delta_manifest chains at open:
    rows equal the python ``load_checkpoint`` replay of the same chain
    (which is bit-identical to a full save of the live state), and the
    reported version is the applied chain seq."""
    from test_delta_checkpoint import make_coll
    from openembedding_tpu.serving.native import NativeModel
    coll, per_step, path = _delta_dir(tmp_path, devices8)
    states, hkeys = per_step[-1]
    with NativeModel(path, native_lib) as m:
        assert m.version == 2
        _native_vs_python(m, coll, states, hkeys)
        # ... and equal to the python loader's replay of the SAME chain
        coll2 = make_coll(create_mesh(2, 4, devices8), track=False)
        loaded = ckpt.load_checkpoint(path, coll2)
        want = np.asarray(coll2.pull(
            loaded, {"arr": jnp.arange(256, dtype=jnp.int32)},
            batch_sharded=False, read_only=True)["arr"], np.float32)
        np.testing.assert_array_equal(
            m.lookup("arr", np.arange(256)).astype(np.float32), want)


def test_native_delta_after_compaction(native_lib, tmp_path, devices8):
    """A compacted chain (folded base, empty chain, content_seq) serves
    the same rows at the same version."""
    import openembedding_tpu.checkpoint_delta as cd
    from openembedding_tpu.serving.native import NativeModel
    coll, per_step, path = _delta_dir(tmp_path, devices8)
    states, hkeys = per_step[-1]
    out = cd.compact(path, background=False)
    assert out["compacted"]
    with NativeModel(path, native_lib) as m:
        assert m.version == 2          # content_seq carries the version
        _native_vs_python(m, coll, states, hkeys)


def test_native_delta_torn_final_recovers(native_lib, tmp_path,
                                          devices8):
    """Torn FINAL delta: recover to the last complete delta (version
    and rows of seq 1 — matching load_checkpoint); torn MIDDLE: the
    load fails loudly."""
    import glob as glob_mod
    from openembedding_tpu.serving.native import NativeModel
    coll, per_step, path = _delta_dir(tmp_path, devices8)
    states1, hkeys1 = per_step[0]
    for f in glob_mod.glob(os.path.join(path, "delta_000002_*")):
        with open(f, "r+b") as fh:
            fh.seek(10)
            fh.write(b"\xde\xad\xbe\xef")
    with NativeModel(path, native_lib) as m:
        assert m.version == 1
        _native_vs_python(m, coll, states1, hkeys1)
    for f in glob_mod.glob(os.path.join(path, "delta_000001_*")):
        os.remove(f)                   # now the tear is MID-chain
    with pytest.raises(RuntimeError, match="mid-chain"):
        NativeModel(path, native_lib)


def test_native_delta_compressed_payload_refused(native_lib, tmp_path,
                                                 devices8):
    """Deflated delta payloads fail the load with a CLEAR message (the
    dependency-free reader trades codec support; the bytes are intact,
    so 'recovering' past them would silently drop data)."""
    import openembedding_tpu.checkpoint_delta as cd
    from test_delta_checkpoint import make_coll, train
    from openembedding_tpu.serving.native import NativeModel
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "z")
    ckpt.save_checkpoint(path, coll, states, model_sign="delta-z")
    states, _ = train(coll, states, seed=0)
    info = cd.save_delta(path, coll, states, step=1, compress="zlib")
    assert info["seq"] == 1
    with pytest.raises(RuntimeError, match="deflated|uncompressed"):
        NativeModel(path, native_lib)


def test_native_batched_gather_entry(native_lib, saved_model):
    """oe_pull_weights_gather: one probe per unique key, scattered rows
    equal per-request lookups; out-of-range gather -> zero rows; the
    native micro-batcher coalesces concurrent lookups through it."""
    import threading
    from openembedding_tpu.serving.native import NativeModel
    path, coll, states, hkeys = saved_model
    with NativeModel(path, native_lib) as m:
        reqs = [np.array([7, 3, 7, 90], np.int64),
                np.array([3, 11], np.int64)]
        outs = m.lookup_batched("arr", reqs)
        for r, o in zip(reqs, outs):
            np.testing.assert_array_equal(o, m.lookup("arr", r))
        # explicit gather: dangling index -> zeros
        rows = m.pull_gather("arr", np.array([7], np.int64),
                             np.array([0, 5, -1], np.int64))
        np.testing.assert_array_equal(rows[0], m.lookup("arr", [7])[0])
        np.testing.assert_array_equal(rows[1:], 0.0)
        # the native batcher: concurrent lookups, bit-equal responses
        with m.make_batcher(max_wait_us=2000) as b:
            got = {}

            def go(i, ids):
                got[i] = b.lookup("arr", ids)

            ts = [threading.Thread(target=go, args=(i, r))
                  for i, r in enumerate(reqs)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(30)
        for i, r in enumerate(reqs):
            np.testing.assert_array_equal(got[i], m.lookup("arr", r))
