"""graftproto replay lane: exported counterexample schedules executed
against the REAL implementation.

The model checker's mutations (tests/fixtures/graftproto_violations.py)
each name a protocol minus one load-bearing line; this lane pins the
models to the code by (a) asserting the exported counterexample
schedule's sync-point order is exactly what the real code traverses when
driven through the same interleaving, (b) applying the SAME one-line
mutation to the real code (monkeypatch / the crash the mutated order
permits) and reproducing the MODELED failure every run, and (c) showing
the unmutated code refuses or recovers under identical schedule
pressure — extends the ``tests/test_interleaving.py`` pattern (the
LossyCounter race realized) from one hand-picked schedule to schedules
the checker derived.

Also holds the regression for the graftproto-found registry divergence:
``model.version`` must come from the load's OWN chain replay, never a
second ``applied_seq`` read that can see a newer chain.
"""

import glob
import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import checkpoint as ckpt
from openembedding_tpu import checkpoint_delta as cd
from openembedding_tpu.analysis import protomodel as pm
from openembedding_tpu.analysis.concurrency import (PointGate,
                                                    clear_schedule,
                                                    install_schedule)
from openembedding_tpu.dirty import DirtyTracker
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.serving.registry import ModelRegistry

from test_delta_checkpoint import make_coll, train

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _no_leftover_schedule():
    yield
    clear_schedule()


def _mutation_schedule(name):
    """The exported counterexample schedule (sync-point order) of one
    seeded mutation — derived live from the checker, exactly what
    ``tools/graftproto.py --emit-schedules`` writes."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "graftproto_fixture",
        os.path.join(HERE, "fixtures", "graftproto_violations.py"))
    fixture = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fixture)
    model = fixture.build(pm, name)
    res = pm.check(model)
    assert res.counterexample is not None
    return pm.trace_schedule(model, res.counterexample.trace)


class RecordingGate(PointGate):
    """PointGate that also records every sync point it sees, so a test
    can assert the real code traversed the exported schedule's order."""

    def __init__(self, points, timeout=20.0):
        super().__init__(points, timeout)
        self.seen = []
        self._seen_lock = threading.Lock()

    def sync(self, key, point):
        with self._seen_lock:
            self.seen.append(point)
        super().sync(key, point)


def _subsequence(needle, haystack):
    it = iter(haystack)
    return all(p in it for p in needle)


def _setup(devices8, tmp_path, steps=2):
    """Armed delta dir + one committed delta per training step; returns
    (coll, states-after-last-step, path, per-step arr id arrays)."""
    mesh = create_mesh(2, 4, devices8)
    coll = make_coll(mesh)
    states = coll.init(jax.random.PRNGKey(0))
    path = str(tmp_path / "m")
    ckpt.save_checkpoint(path, coll, states, model_sign="sign-p")
    ids = [np.arange(i * 8, i * 8 + 8, dtype=np.int32)
           for i in range(steps)]
    for i in range(steps):
        states, _ = train(coll, states, seed=i, arr_ids=ids[i])
        info = ckpt.save_checkpoint(path, coll, states, mode="delta",
                                    step=i + 1)
        assert info["seq"] == i + 1
    return coll, states, path, ids


# --- mutation replay: manifest committed before payload bytes ----------------

def test_manifest_before_payload_replay_loses_commit(devices8, tmp_path):
    """The ``manifest_before_payload`` counterexample executed for real:
    the writer parks at the commit point (``ckpt.delta.commit``), the
    payload files vanish (the crash window the mutated order opens —
    commit first, bytes never land), the commit proceeds. The manifest
    now references a payload that was never written, and the modeled
    failure reproduces every run: the save reported the seq committed,
    but a load silently recovers WITHOUT it — and the checker's exported
    schedule is exactly the order the real code traversed."""
    sched = _mutation_schedule("manifest_before_payload")
    # the trainer_restart role gates every delta save on new trained
    # content (t_hi > committed cursor), so the minimal counterexample
    # leads with the step that produced the rows being saved; the
    # replay realizes that step with the low-level train() helper
    # (which does not route through Trainer.fit, hence no sync point)
    # and pins the writer/load suffix order against the gate below
    assert sched == ["trainer.fit.step", "ckpt.delta.commit",
                     "registry.load.start", "registry.load.commit"]
    coll, states, path, ids = _setup(devices8, tmp_path, steps=1)
    states, _ = train(coll, states, seed=7,
                      arr_ids=np.arange(64, 72, dtype=np.int32))

    gate = RecordingGate(["ckpt.delta.commit"])
    install_schedule(gate)
    out = {}
    t = threading.Thread(
        target=lambda: out.update(ckpt.save_checkpoint(
            path, coll, states, mode="delta", step=2)),
        name="delta-writer")
    t.start()
    assert gate.wait_arrival("ckpt.delta.commit")
    # payload files written (real order) — delete them to realize the
    # mutated order's crash window, then let the commit land
    removed = [f for f in glob.glob(os.path.join(path, "delta_000002_*"))]
    assert removed, "expected seq-2 payload files on disk pre-commit"
    for f in removed:
        os.remove(f)
    gate.open("ckpt.delta.commit")
    t.join(30)

    # the save believed seq 2 committed; the chain says so too
    assert out["seq"] == 2
    assert cd.chain_state(path)["last_seq"] == 2
    # ... but the committed entry has no bytes: a registry load silently
    # recovers to seq 1 — the modeled no_silent_commit_loss failure
    mesh = create_mesh(2, 4, devices8)
    with pytest.warns(RuntimeWarning, match="torn"):
        reg = ModelRegistry(mesh, default_hash_capacity=2048)
        sign = reg.create_model(path, block=True)
    model = reg.find_model(sign)
    assert model.version == 1
    # the real code traversed the exported schedule's writer/load
    # suffix in exact order (the leading trainer.fit.step is the
    # train() call above — content production, not part of the
    # commit-order crash window this mutation targets)
    assert _subsequence(sched[1:], gate.seen), gate.seen
    clear_schedule()


# --- mutation replay: failed writer drops its claim --------------------------

def test_skip_claim_restore_replay_loses_rows(devices8, tmp_path,
                                              monkeypatch):
    """The ``skip_claim_restore`` counterexample for real: mark ->
    snapshot (claim) -> writer fails -> restore SKIPPED (the mutation,
    as a monkeypatch on the real ``DirtyTracker.restore``). The claimed
    chunks' changes are lost to the chain every run: the next delta save
    skips, and a load misses the trained rows. The unmutated code under
    the identical failure re-covers everything."""
    sched = _mutation_schedule("skip_claim_restore")
    assert sched == ["dirty.mark", "dirty.snapshot", "ckpt.delta.write",
                     "dirty.restore"]

    def run(mutate):
        tmp = tmp_path / ("mut" if mutate else "ctl")
        tmp.mkdir()
        coll, states, path, ids = _setup(devices8, tmp, steps=1)
        rec = RecordingGate([])          # record-only, nothing gated
        install_schedule(rec)            # armed BEFORE the marking step
        ids2 = np.arange(32, 40, dtype=np.int32)
        states, _ = train(coll, states, seed=9, arr_ids=ids2)
        boom = {"left": 1}
        real_serialize = cd._serialize_payload

        def failing_serialize(payload, compress):
            if boom["left"]:
                boom["left"] -= 1
                raise RuntimeError("injected writer death")
            return real_serialize(payload, compress)

        monkeypatch.setattr(cd, "_serialize_payload", failing_serialize)
        if mutate:
            from openembedding_tpu.analysis.concurrency import sync_point

            def dropped_restore(self, chunks):
                sync_point("dirty.restore")   # reached, then DROPPED
            monkeypatch.setattr(DirtyTracker, "restore", dropped_restore)
        with pytest.raises(RuntimeError, match="injected writer death"):
            ckpt.save_checkpoint(path, coll, states, mode="delta", step=2)
        monkeypatch.setattr(cd, "_serialize_payload", real_serialize)
        if mutate:
            monkeypatch.undo()
        # the retry save: covers the restored claims — or nothing
        info = ckpt.save_checkpoint(path, coll, states, mode="delta",
                                    step=2)
        clear_schedule()
        assert _subsequence(sched, rec.seen), rec.seen
        loaded = ckpt.load_checkpoint(path, coll)
        want = np.asarray(coll.pull(
            states, {"arr": jnp.asarray(ids2)}, batch_sharded=False,
            read_only=True)["arr"])
        got = np.asarray(coll.pull(
            loaded, {"arr": jnp.asarray(ids2)}, batch_sharded=False,
            read_only=True)["arr"])
        return info, want, got

    info, want, got = run(mutate=True)
    assert info["skipped"], "mutated retry save saw no dirt"
    assert not np.array_equal(want, got), \
        "modeled lost-dirty failure did not reproduce"
    info, want, got = run(mutate=False)
    assert not info["skipped"] and info["rows"] > 0
    np.testing.assert_array_equal(want, got)


# --- mutation replay: seq gate dropped ---------------------------------------

def test_drop_seq_gate_replay_loses_skipped_delta(devices8, tmp_path):
    """The ``drop_seq_gate`` counterexample for real: a model at version
    1 receives delta 3. The REAL gate refuses the gap (and fires none of
    the swap schedule); with the gate neutered (the one-line mutation:
    the version check lied to), the real publish path runs the exported
    schedule and the modeled failure reproduces — version claims 3 while
    delta 2's rows are missing from the served states, every run."""
    sched = _mutation_schedule("drop_seq_gate")
    assert sched == ["registry.find", "registry.swap.build",
                     "registry.swap.commit"]
    coll, states, path, ids = _setup(devices8, tmp_path, steps=1)
    deltas = {}
    for seq in (2, 3):
        step_ids = np.arange(seq * 16, seq * 16 + 8, dtype=np.int32)
        ids.append(step_ids)
        states, _ = train(coll, states, seed=seq, arr_ids=step_ids)
        info = cd.save_delta(path, coll, states, step=seq,
                             return_payload=True)
        assert info["seq"] == seq
        deltas[seq] = info["delta"]

    mesh = create_mesh(2, 4, devices8)
    reg = ModelRegistry(mesh, default_hash_capacity=2048)
    # load the chain as of seq 1 only: reconstruct from the manifest by
    # applying deltas through the registry instead — load full dir gives
    # version 3; so rebuild a version-1 view from a COPY saved earlier.
    # Simpler and exact: load the dir (version 3), then rewind the model
    # to a version-1 snapshot taken before deltas 2/3 were applied.
    sign = reg.create_model(path, block=True)
    model = reg.find_model(sign)
    assert model.version == 3

    # build the version-1 model the counterexample starts from
    coll1 = make_coll(create_mesh(2, 4, devices8))
    states1 = coll1.init(jax.random.PRNGKey(0))
    path1 = str(tmp_path / "v1")
    ckpt.save_checkpoint(path1, coll1, states1, model_sign="sign-v1")
    states1, _ = train(coll1, states1, seed=0, arr_ids=ids[0])
    ckpt.save_checkpoint(path1, coll1, states1, mode="delta", step=1)
    sign1 = reg.create_model(path1, model_sign="v1", block=True)
    m1 = reg.find_model(sign1)
    assert m1.version == 1

    # REAL gate: the gapped delta is refused, and no swap sync fires
    rec = RecordingGate([])
    install_schedule(rec)
    with pytest.raises(RuntimeError, match="gap"):
        reg.apply_delta(sign1, deltas[3])
    assert not _subsequence(sched, rec.seen)
    # MUTATION: neuter the gate (the version check lied to) — the real
    # publish path then runs the exported schedule
    m1.version = 2
    out = reg.apply_delta(sign1, deltas[3])
    clear_schedule()
    assert out["applied"] and m1.version == 3
    assert _subsequence(sched, rec.seen), rec.seen
    # the modeled failure: version claims 3, but delta 2's rows are NOT
    # what the trainer has — the skipped delta is silently lost
    d2_ids = jnp.asarray(ids[1])
    want = np.asarray(coll.pull(states, {"arr": d2_ids},
                                batch_sharded=False,
                                read_only=True)["arr"])
    got = np.asarray(m1.lookup("arr", d2_ids))
    assert not np.array_equal(want, got), \
        "modeled lost-delta failure did not reproduce"
    # while the gated model (version 3 via the honest chain) serves them
    np.testing.assert_array_equal(
        want, np.asarray(model.lookup("arr", d2_ids)))


# --- regression: registry version coheres with the load's own replay ---------

def test_registry_version_coheres_with_replayed_chain(devices8, tmp_path,
                                                      monkeypatch):
    """graftproto-found divergence, pinned: a delta committed BETWEEN
    the registry load's chain replay and a separate ``applied_seq`` read
    must not advance the model's version past the rows it holds (the
    old code would then ack that delta's push as stale and silently
    lose it). The fix derives the version from the load's own verify
    pass; this test recreates the exact race window."""
    coll, states, path, ids = _setup(devices8, tmp_path, steps=1)
    ids2 = np.arange(40, 48, dtype=np.int32)
    states2, _ = train(coll, states, seed=3, arr_ids=ids2)

    real_load = ckpt.load_checkpoint
    raced = {"done": False}

    def racing_load(p, c, **kw):
        out = real_load(p, c, **kw)
        if not raced["done"]:
            raced["done"] = True
            # the racing trainer: delta 2 commits AFTER the replay but
            # BEFORE any later applied_seq read could run
            info = cd.save_delta(path, coll, states2, step=2,
                                 return_payload=True)
            assert info["seq"] == 2
            raced["delta"] = info["delta"]
        return out

    import openembedding_tpu.serving.registry as registry_mod
    monkeypatch.setattr(registry_mod.ckpt_lib, "load_checkpoint",
                        racing_load)
    mesh = create_mesh(2, 4, devices8)
    reg = ModelRegistry(mesh, default_hash_capacity=2048)
    sign = reg.create_model(path, block=True)
    model = reg.find_model(sign)
    # the model holds seq-1 rows, so it must SAY version 1 — a version-2
    # claim would stale-ack the racing delta below and lose ids2's rows
    assert model.version == 1
    out = reg.apply_delta(sign, raced["delta"])
    assert out["applied"] and model.version == 2
    want = np.asarray(coll.pull(states2, {"arr": jnp.asarray(ids2)},
                                batch_sharded=False,
                                read_only=True)["arr"])
    np.testing.assert_array_equal(
        want, np.asarray(model.lookup("arr", jnp.asarray(ids2))))


# --- mutation replay: elastic resume re-reads the stream from zero -----------

@pytest.mark.slow
def test_resume_cursor_from_zero_replay(devices8, tmp_path, monkeypatch):
    """The ``resume_cursor_from_zero`` counterexample executed against
    the REAL ``Trainer.fit`` resume path: train -> delta autosave
    commits (cursor rides the manifest extra) -> process dies ->
    restore. With the one-line mutation (the restored cursor forced to
    0 — the naive restart ``skip_batches`` exists to prevent), batches
    already folded into the committed checkpoint apply a SECOND time
    and the model diverges from the uninterrupted baseline every run —
    the modeled ``trainer_neither_reapplies_nor_skips_rows`` failure.
    The unmutated code under identical schedule pressure is
    bit-identical, and the real code traverses the checker's exported
    sync-point order exactly."""
    from test_trainer_elastic import (_assert_identical, _build_trainer,
                                      _fingerprint, _synthetic_batches)
    from openembedding_tpu.training import Trainer

    sched = _mutation_schedule("resume_cursor_from_zero")
    assert sched == ["trainer.fit.step", "ckpt.delta.write",
                     "ckpt.delta.commit", "trainer.resume.restore",
                     "trainer.fit.step"]

    mesh = create_mesh(2, 4, devices8)
    batches = _synthetic_batches(4)

    tr0 = _build_trainer(mesh)
    s0 = tr0.init(jax.random.PRNGKey(0), tr0.shard_batch(batches[0]))
    sA, _ = tr0.fit(s0, list(batches))
    baseline = _fingerprint(tr0, sA)

    # interrupted run, recording the schedule points the real code hits
    ck = str(tmp_path / "auto")
    rec = RecordingGate([])            # record-only, nothing gated
    install_schedule(rec)
    tr1 = _build_trainer(mesh)
    s1 = tr1.init(jax.random.PRNGKey(0), tr1.shard_batch(batches[0]))
    tr1.fit(s1, list(batches[:2]), autosave_every=1, autosave_dir=ck)
    clear_schedule()

    # MUTATED resume: the one line the model removes — the restored
    # stream cursor — zeroed, state restore left intact
    real_restore = Trainer._restore_fit

    def zero_cursor_restore(self, state, path):
        st, _cursor = real_restore(self, state, path)
        return st, 0

    monkeypatch.setattr(Trainer, "_restore_fit", zero_cursor_restore)
    tr2 = _build_trainer(mesh)
    s2 = tr2.init(jax.random.PRNGKey(0), tr2.shard_batch(batches[0]))
    s2b, _ = tr2.fit(s2, list(batches), resume_from=ck,
                     autosave_every=0)
    monkeypatch.undo()
    mutated = _fingerprint(tr2, s2b)
    # batches 0..1 applied twice: the step counter alone betrays it,
    # and the trained rows drift — the modeled silent re-application
    assert int(mutated[0]) == len(batches) + 2
    assert any(x.shape != y.shape or not np.array_equal(x, y)
               for x, y in zip(baseline, mutated))

    # CONTROL: the unmutated resume under the same schedule pressure
    # neither reapplies nor skips — bit-identical to the baseline
    rec2 = RecordingGate([])
    install_schedule(rec2)
    tr3 = _build_trainer(mesh)
    s3 = tr3.init(jax.random.PRNGKey(0), tr3.shard_batch(batches[0]))
    s3b, _ = tr3.fit(s3, list(batches), resume_from=ck,
                     autosave_every=1, autosave_dir=ck)
    clear_schedule()
    _assert_identical(baseline, _fingerprint(tr3, s3b))

    # the exported counterexample schedule is exactly the order the
    # real interrupted-run + resume traversed
    assert _subsequence(sched, rec.seen + rec2.seen), \
        (sched, rec.seen, rec2.seen)
