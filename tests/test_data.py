"""Data path: TSV/CSV parsing, hashing determinism, prefetch ordering."""

import numpy as np

from openembedding_tpu.data import criteo


def _write_tsv(path, rows):
    with open(path, "w") as f:
        for label, dense, sparse in rows:
            f.write("\t".join([str(label)]
                              + [str(d) for d in dense]
                              + list(sparse)) + "\n")


def test_tsv_reader(tmp_path):
    rows = []
    rng = np.random.RandomState(0)
    for i in range(10):
        dense = rng.randint(0, 100, criteo.NUM_DENSE).tolist()
        sparse = ["%08x" % rng.randint(0, 2**32) for _ in range(criteo.NUM_SPARSE)]
        rows.append((i % 2, dense, sparse))
    # one row with missing values
    rows.append((1, [""] * criteo.NUM_DENSE, [""] * criteo.NUM_SPARSE))
    p = tmp_path / "a.tsv"
    _write_tsv(p, rows)

    batches = list(criteo.read_criteo_tsv(str(p), 4, num_buckets=1000,
                                          drop_remainder=False))
    assert len(batches) == 3  # 11 rows -> 4+4+3
    b = batches[0]
    assert b["label"].shape == (4,)
    assert b["dense"].shape == (4, criteo.NUM_DENSE)
    assert set(b["sparse"]) == set(criteo.SPARSE_NAMES)
    for v in b["sparse"].values():
        assert v.dtype == np.int32
        assert (v >= 0).all() and (v < 1000).all()
    # missing categorical hashes to the 0-sentinel bucket deterministically
    last = batches[-1]["sparse"]["C1"][-1]
    assert last == criteo.hash_bucket(np.array([0], np.int64), 1000)[0]


def test_hash_bucket_deterministic_and_spread():
    x = np.arange(1000, dtype=np.int64)
    a = criteo.hash_bucket(x, 2**20)
    b = criteo.hash_bucket(x, 2**20)
    np.testing.assert_array_equal(a, b)
    # sequential inputs spread: no trivial collisions bunching
    assert len(np.unique(a)) > 990


def test_synthetic_and_linear_columns():
    it = criteo.add_linear_columns(criteo.synthetic_criteo(8, num_batches=2))
    batches = list(it)
    assert len(batches) == 2
    sp = batches[0]["sparse"]
    assert "C1" in sp and "C1:linear" in sp
    np.testing.assert_array_equal(sp["C1"], sp["C1:linear"])
    # deterministic under the same seed
    again = list(criteo.add_linear_columns(
        criteo.synthetic_criteo(8, num_batches=2)))
    np.testing.assert_array_equal(batches[1]["sparse"]["C7"],
                                  again[1]["sparse"]["C7"])


def test_prefetch_preserves_order_and_count():
    seen = []
    out = list(criteo.prefetch(range(7), lambda x: (seen.append(x), x * 10)[1],
                               depth=3))
    assert out == [0, 10, 20, 30, 40, 50, 60]
    assert seen == list(range(7))


def test_csv_reader(tmp_path):
    header = ["label"] + list(criteo.DENSE_NAMES) + list(criteo.SPARSE_NAMES)
    lines = [",".join(header)]
    for i in range(5):
        row = [str(i % 2)] + [f"{0.1 * j:.2f}" for j in range(13)] \
            + [str(i * 26 + j) for j in range(26)]
        lines.append(",".join(row))
    p = tmp_path / "a.csv"
    p.write_text("\n".join(lines) + "\n")
    batches = list(criteo.read_criteo_csv(str(p), 5))
    assert len(batches) == 1
    assert batches[0]["sparse"]["C26"][2] == 2 * 26 + 25


def test_preprocess_cli(tmp_path):
    """TSV -> CSV preprocessing: label encoding + scaling + repeat, and the
    output round-trips through read_criteo_csv."""
    from openembedding_tpu.data import criteo, preprocess
    tsv = tmp_path / "raw.tsv"
    rows = []
    for i in range(6):
        dense = "\t".join(str(i + j) for j in range(13))
        cats = "\t".join(f"v{(i + j) % 3:x}" for j in range(26))
        rows.append(f"{i % 2}\t{dense}\t{cats}")
    # a ragged line (missing trailing fields) must not crash
    rows.append("1\t5")
    tsv.write_text("\n".join(rows) + "\n")

    out = tmp_path / "out.csv"
    n = preprocess.preprocess(str(tsv), str(out), repeat=2)
    assert n == 7
    lines = out.read_text().strip().split("\n")
    assert lines[0].startswith("label,I1")
    assert len(lines) == 1 + 2 * 7
    batches = list(criteo.read_criteo_csv(str(out), 7))
    assert len(batches) == 2
    b = batches[0]
    assert b["label"].shape == (7,)
    assert b["dense"].shape == (7, 13)
    assert all(b["sparse"][c].shape == (7,) for c in criteo.SPARSE_NAMES)
    # label encoding: first-seen ids are dense and start at 0
    assert b["sparse"]["C1"].min() == 0

    # minmax variant stays within [0, 1]
    out2 = tmp_path / "mm.csv"
    preprocess.preprocess(str(tsv), str(out2), minmax=True)
    b2 = next(iter(criteo.read_criteo_csv(str(out2), 7)))
    assert float(b2["dense"].min()) >= 0.0
    assert float(b2["dense"].max()) <= 1.0


def test_tfrecord_crc32c_vector():
    """crc32c against the canonical test vector (RFC 3720 appendix)."""
    from openembedding_tpu.data import tfrecord as tfr
    assert tfr.crc32c(b"123456789") == 0xE3069283
    assert tfr._crc32c_py(b"123456789") == 0xE3069283


def test_tfrecord_crc32c_native_matches_python():
    """The native (google-crc32c) path and the fallback table loop agree on
    arbitrary payloads — whichever is active, files verify identically."""
    from openembedding_tpu.data import tfrecord as tfr
    rng = np.random.RandomState(7)
    for n in (0, 1, 3, 255, 4096):
        data = rng.bytes(n)
        assert tfr.crc32c(data) == tfr._crc32c_py(data)


def test_tfrecord_roundtrip(tmp_path):
    """Criteo TFRecord fixture round trip: writer -> framed file -> parsed
    batches identical to the source rows (the reference's layout:
    label/C* int64, I* float — criteo_tfrecord.py:8-18)."""
    from openembedding_tpu.data import tfrecord as tfr
    rng = np.random.RandomState(0)
    rows = []
    path = tmp_path / "tf-part.00001"
    with open(path, "wb") as f:
        for i in range(103):
            feats = {"label": [int(rng.randint(0, 2))]}
            for j in range(1, 14):
                feats[f"I{j}"] = [float(np.float32(rng.randn()))]
            for j in range(1, 27):
                feats[f"C{j}"] = [int(rng.randint(0, 1 << 62))]
            rows.append(feats)
            tfr.write_record(f, tfr.make_example(feats))
    batches = list(tfr.read_criteo_tfrecord(str(path), batch_size=32))
    assert [b["label"].shape[0] for b in batches] == [32, 32, 32, 7]
    got_labels = np.concatenate([b["label"] for b in batches])
    np.testing.assert_array_equal(
        got_labels, [r["label"][0] for r in rows])
    got_i3 = np.concatenate([b["dense"][:, 2] for b in batches])
    np.testing.assert_array_equal(
        got_i3, np.asarray([r["I3"][0] for r in rows], np.float32))
    got_c7 = np.concatenate([b["sparse"]["C7"] for b in batches])
    np.testing.assert_array_equal(got_c7, [r["C7"][0] for r in rows])
    # directory-of-parts layout resolves too
    batches2 = list(tfr.read_criteo_tfrecord(str(tmp_path), batch_size=64))
    assert sum(b["label"].shape[0] for b in batches2) == 103


def test_tfrecord_corruption_detected(tmp_path):
    import pytest
    from openembedding_tpu.data import tfrecord as tfr
    path = tmp_path / "rec"
    with open(path, "wb") as f:
        tfr.write_record(f, b"payload-bytes")
    raw = bytearray(path.read_bytes())
    raw[14] ^= 0xFF  # flip a data byte
    path.write_bytes(bytes(raw))
    with pytest.raises(IOError, match="CRC mismatch"):
        list(tfr.read_records(str(path)))
