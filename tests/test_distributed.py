"""2-process multi-host test — the reference's MultiProcess simulation.

Spawns two worker processes (each a "host" with 2 virtual CPU devices) that
join one jax.distributed cluster and run a REAL cross-process training step:
a 2x2 mesh spanning both processes, per-process batch shards, gradients that
must cross the process boundary to land. Mirrors the reference's fork-based
N-node tests (core::MultiProcess, entry/c_api_test.h:194,285).
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_training_step(tmp_path, devices8):
    port = _free_port()
    ckpt_dir = str(tmp_path / "mh_ckpt")
    root = os.path.dirname(os.path.dirname(_WORKER))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    env.pop("XLA_FLAGS", None)  # workers set their own device counts
    env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU child: skip tunnel plugin
    procs = [subprocess.Popen(
        [sys.executable, _WORKER, str(r), str(port), ckpt_dir], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {r} failed:\n{out}"
        assert f"worker {r}: ok" in out
        assert f"worker {r}: multihost checkpoint ok" in out

    # the 2-host dump (part files per process) reloads in THIS single
    # process on a different mesh — cross-topology like the reference's
    # re-sharding load
    import jax
    import numpy as np
    from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
    from openembedding_tpu import checkpoint as ckpt
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(2, 4, jax.devices()[:8])
    specs = (
        EmbeddingSpec(name="t", input_dim=32, output_dim=4,
                      initializer={"category": "constant", "value": 0.0},
                      optimizer={"category": "sgd", "learning_rate": 1.0}),
        EmbeddingSpec(name="h", input_dim=-1, output_dim=4,
                      hash_capacity=256,
                      initializer={"category": "constant", "value": 0.25},
                      optimizer={"category": "sgd", "learning_rate": 1.0}),
    )
    coll = EmbeddingCollection(specs, mesh)
    loaded = ckpt.load_checkpoint(ckpt_dir, coll)
    import jax.numpy as jnp
    rows = np.asarray(coll.pull(
        loaded, {"t": jnp.asarray([5, 6, 7], jnp.int32)},
        batch_sharded=False)["t"])
    np.testing.assert_allclose(rows[:, 0], [-8.0, 0.0, 0.0],
                               rtol=1e-6, atol=1e-6)
    hrows = np.asarray(coll.pull(
        loaded, {"h": jnp.asarray([1002, 1004, 77], jnp.int32)},
        batch_sharded=False, read_only=True)["h"])
    np.testing.assert_allclose(hrows[:2], 0.25 - 1.0, rtol=1e-6)
    np.testing.assert_allclose(hrows[2], 0.0)
