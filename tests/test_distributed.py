"""2-process multi-host test — the reference's MultiProcess simulation.

Spawns two worker processes (each a "host" with 2 virtual CPU devices) that
join one jax.distributed cluster and run a REAL cross-process training step:
a 2x2 mesh spanning both processes, per-process batch shards, gradients that
must cross the process boundary to land. Mirrors the reference's fork-based
N-node tests (core::MultiProcess, entry/c_api_test.h:194,285).
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_training_step():
    port = _free_port()
    root = os.path.dirname(os.path.dirname(_WORKER))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    env.pop("XLA_FLAGS", None)  # workers set their own device counts
    procs = [subprocess.Popen(
        [sys.executable, _WORKER, str(r), str(port)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {r} failed:\n{out}"
        assert f"worker {r}: ok" in out
