"""graftscope: spans, histograms, Chrome-trace export, byte ledger.

Covers the ISSUE-6 acceptance surface: histogram quantile error bounded
by one bucket ratio, span nesting/thread attribution in the exported
trace, the under-jit guard (spans inside a traced fn record once, at
trace time, and never pollute the latency histograms), Chrome-trace
schema validation on a captured 5-step cpu run, and expected collective
bytes agreeing with the ``analysis/contracts.py`` bounds.
"""

import json
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu.analysis import scope


@pytest.fixture(autouse=True)
def _clean_scope():
    scope.reset()
    scope.HISTOGRAMS.reset()
    scope.set_tracing(True)
    yield
    scope.set_tracing(None)
    scope.reset()
    scope.HISTOGRAMS.reset()


def test_histogram_quantiles_bounded_error():
    """Log-bucket quantiles of a known distribution stay within one
    bucket ratio of the true value."""
    reg = scope.HistogramRegistry()
    vals = np.linspace(0.001, 1.0, 1000)
    for v in vals:
        reg.observe("lat", float(v))
    assert reg.count("lat") == 1000
    assert abs(reg.sum("lat") - vals.sum()) < 1e-6
    for q in (0.5, 0.95, 0.99):
        true = float(np.quantile(vals, q))
        est = reg.quantile("lat", q)
        assert true / scope.BUCKET_RATIO <= est \
            <= true * scope.BUCKET_RATIO, (q, true, est)
    p50, p95, p99 = (reg.quantile("lat", q) for q in (0.5, 0.95, 0.99))
    assert p50 <= p95 <= p99


def test_histogram_constant_distribution_and_labels():
    reg = scope.HistogramRegistry()
    for _ in range(100):
        reg.observe("lat", 0.25, plane="a2a")
    est = reg.quantile("lat", 0.5, plane="a2a")
    assert 0.25 / scope.BUCKET_RATIO <= est <= 0.25 * scope.BUCKET_RATIO
    # label sets are distinct series
    assert reg.count("lat", plane="psum") == 0
    assert np.isnan(reg.quantile("lat", 0.5, plane="psum"))
    # counters render with escaped label values
    reg.inc("errs", kind='we"ird\nname')
    lines = reg.prometheus_lines()
    assert any('kind="we\\"ird\\nname"' in ln for ln in lines)


def test_span_records_histogram_and_ring():
    with scope.span("unit.demo", plane="a2a"):
        time.sleep(0.005)
    assert scope.HISTOGRAMS.count("span_unit_demo_seconds",
                                  plane="a2a") == 1
    assert scope.HISTOGRAMS.quantile("span_unit_demo_seconds", 0.5,
                                     plane="a2a") > 1e-4
    events = [e for e in scope.export_chrome_trace()["traceEvents"]
              if e.get("name") == "unit.demo"]
    assert len(events) == 1
    assert events[0]["ph"] == "X" and events[0]["dur"] >= 5e3 * 0.5
    assert events[0]["args"]["plane"] == "a2a"


def test_span_error_exit_recorded_and_reraised():
    with pytest.raises(ValueError):
        with scope.span("unit.err", plane="a2a"):
            raise ValueError("boom")
    # latency sample still lands, tagged via the error counter
    assert scope.HISTOGRAMS.count("span_unit_err_seconds",
                                  plane="a2a") == 1
    ev = [e for e in scope.export_chrome_trace()["traceEvents"]
          if e.get("name") == "unit.err"]
    assert ev[0]["args"]["error"] == "ValueError"
    assert any("span_errors_total" in ln and 'kind="unit.err"' in ln
               for ln in scope.HISTOGRAMS.prometheus_lines())


def test_span_nesting_and_thread_attribution():
    with scope.span("outer"):
        with scope.span("inner"):
            time.sleep(0.002)

    def other():
        with scope.span("worker.span"):
            time.sleep(0.002)

    t = threading.Thread(target=other, name="oe-test-worker")
    t.start()
    t.join()
    trace = scope.export_chrome_trace()
    by_name = {e["name"]: e for e in trace["traceEvents"]
               if e.get("ph") == "X"}
    outer, inner = by_name["outer"], by_name["inner"]
    # Chrome-trace nesting is containment per tid
    assert outer["tid"] == inner["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1.0
    worker = by_name["worker.span"]
    assert worker["tid"] != outer["tid"]
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    assert "oe-test-worker" in names


def test_under_jit_guard_records_once_not_per_call():
    """A span inside a traced fn runs at TRACE time: it must land in the
    ring exactly once (tagged trace_time), not once per call, and must
    never feed the latency histograms (compile time is not step time)."""

    def f(x):
        with scope.span("under.jit"):
            return x * 2

    jf = jax.jit(f)
    for _ in range(3):
        jf(jnp.ones((4,)))
    events = [e for e in scope.export_chrome_trace()["traceEvents"]
              if e.get("name") == "under.jit"]
    assert len(events) == 1
    assert events[0]["args"].get("trace_time") is True
    assert scope.HISTOGRAMS.count("span_under_jit_seconds") == 0


def test_chrome_trace_schema_on_captured_run(devices8, tmp_path):
    """5-step eager pull/push capture on the 8-device mesh: the written
    JSON is Perfetto-loadable (schema-valid) and carries nonzero
    pull/push spans with plane labels."""
    from openembedding_tpu.embedding import EmbeddingCollection, \
        EmbeddingSpec
    from openembedding_tpu.parallel.mesh import create_mesh, DATA_AXIS
    from openembedding_tpu.utils import observability as obs
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = create_mesh(2, 4)
    coll = EmbeddingCollection(
        (EmbeddingSpec(name="t", input_dim=512, output_dim=4,
                       plane="a2a"),), mesh)
    states = coll.init(jax.random.PRNGKey(0))
    sh = NamedSharding(mesh, P(DATA_AXIS))
    rng = np.random.RandomState(0)
    obs.set_evaluate_performance(True)
    try:
        for _ in range(5):
            idx = jax.device_put(
                jnp.asarray(rng.randint(0, 512, size=64)
                            .astype(np.int32)), sh)
            rows = coll.pull(states, {"t": idx})
            states = coll.apply_gradients(states, {"t": idx},
                                          {"t": rows["t"]})
    finally:
        obs.set_evaluate_performance(False)

    out = tmp_path / "trace.json"
    scope.export_chrome_trace(str(out))
    trace = json.loads(out.read_text())
    assert isinstance(trace["traceEvents"], list) and trace["traceEvents"]
    for e in trace["traceEvents"]:
        assert {"ph", "name", "pid", "tid"} <= set(e)
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0
            assert e.get("cat") == "graftscope"
    pulls = [e for e in trace["traceEvents"]
             if e.get("name") == "pull" and e["ph"] == "X"]
    pushes = [e for e in trace["traceEvents"]
              if e.get("name") == "push" and e["ph"] == "X"]
    assert len(pulls) == 5 and len(pushes) == 5
    assert all(e["args"]["plane"] == "a2a" for e in pulls + pushes)
    assert scope.HISTOGRAMS.count("span_pull_seconds", plane="a2a") == 5


def test_expected_bytes_matches_contracts(devices8):
    """The ledger's expected bytes come from the same compiled HLO the
    contract registry audits — ``check=True`` runs that audit, so this
    passing means the numbers sit inside the contracts.py bounds."""
    from openembedding_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(2, 4)
    e = scope.plane_expected_bytes(mesh, "a2a", "pull", batch=512, dim=8,
                                   check=True)
    assert e.total > 0
    assert "all-to-all" in e.per_op          # the owner exchange
    count, nbytes = e.per_op["all-to-all"]
    assert count >= 1 and nbytes > 0
    # the memory ledger rides along: same compiled program, per-device
    # argument/temp/peak bytes (ISSUE 7 satellite — the graftscope table
    # shows latency, bytes, and memory in one place)
    assert e.memory is not None
    assert e.memory["argument_bytes"] > 0
    assert e.memory["peak_bytes"] >= e.memory["argument_bytes"]
    rows = scope.ledger_rows([e])
    assert rows[0]["expected_bytes"] == e.total
    assert rows[0]["calls"] == 0             # nothing measured yet
    assert rows[0]["hbm_peak_bytes"] == e.memory["peak_bytes"]
    table = scope.format_ledger(rows)
    assert "a2a" in table and "pull" in table
    assert "HBM_MiB" in table and "n/a" not in table


@pytest.mark.slow
def test_graftscope_cli_smoke(tmp_path):
    """The CI smoke invocation end-to-end: ledger table for every
    registered plane, traced train run, valid trace JSON, exit 0."""
    from tools import graftscope
    out = tmp_path / "trace.json"
    # batch 256 — BELOW the old 512 pin: the grouped launch-count unit
    # is now counted at the audited stream size, so any batch audits
    # clean (ISSUE 7 satellite dropped the CI pin)
    rc = graftscope.main(["--steps", "2", "--batch", "256", "--dim", "8",
                          "--mesh", "2x4", "--plane", "a2a+grouped",
                          "--out", str(out)])
    assert rc == 0
    trace = json.loads(out.read_text())
    assert any(e.get("name") == "step" for e in trace["traceEvents"])
