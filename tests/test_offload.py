"""Host-offload tier: cache residency, writeback, incremental persist/restore
— the reference's PMem test matrix (pmem_embedding_table_test.cpp: set/get
across work_ids, checkpoint commit, cache eviction with tiny budgets,
load_pmem_pool recovery; pmem_c_api_test.cpp: train/persist/restore loop)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import EmbeddingVariableMeta, make_optimizer
from openembedding_tpu.offload import HostOffloadedTable

DIM = 4
META = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=1000)


def make_table(**kw):
    kw.setdefault("vocab", 1000)
    kw.setdefault("cache_capacity", 256)
    return HostOffloadedTable(
        META, {"category": "sgd", "learning_rate": 1.0},
        {"category": "constant", "value": 0.5}, **kw)


def test_pull_through_cache_matches_host():
    t = make_table()
    ids = np.array([1, 500, 999], np.int32)
    t.prepare(ids)
    rows = np.asarray(t.pull(jnp.asarray(ids)))
    np.testing.assert_allclose(rows, t.host_weights[ids], rtol=1e-6)


def test_update_flush_writeback():
    t = make_table()
    ids = np.array([7, 8, 9], np.int32)
    t.prepare(ids)
    t.apply_gradients(jnp.asarray(ids), jnp.ones((3, DIM), jnp.float32))
    # host copy still stale until flush
    np.testing.assert_allclose(t.host_weights[ids], 0.5)
    flushed = t.flush()
    assert flushed == 3
    np.testing.assert_allclose(t.host_weights[ids], 0.5 - 1.0, rtol=1e-6)
    assert (t.host_work_id[ids] > 0).all()
    # state round-trips: rows come back with their values after re-prepare
    t.prepare(ids)
    np.testing.assert_allclose(np.asarray(t.pull(jnp.asarray(ids))),
                               0.5 - 1.0, rtol=1e-6)


def test_tiny_cache_eviction_cycle():
    """Cache smaller than the id stream: prepare must flush-and-refill, and
    values stay exact across evictions (the 1-5 item cache-budget tests)."""
    t = make_table(cache_capacity=64)
    rng = np.random.RandomState(0)
    host_replica = t.host_weights.copy()
    for step in range(8):
        ids = rng.randint(0, 1000, 40).astype(np.int32)
        uniq = np.unique(ids)
        t.prepare(ids)
        t.apply_gradients(jnp.asarray(uniq),
                          jnp.ones((uniq.size, DIM), jnp.float32) * 0.1)
        host_replica[uniq] -= 0.1
    t.flush()
    np.testing.assert_allclose(t.host_weights, host_replica, rtol=1e-5,
                               atol=1e-6)


def test_incremental_persist_restore(tmp_path):
    t = make_table()
    p = str(tmp_path / "off")
    ids1 = np.array([1, 2, 3], np.int32)
    t.prepare(ids1)
    t.apply_gradients(jnp.asarray(ids1), jnp.ones((3, DIM), jnp.float32))
    info = t.persist(p)
    assert info["file"].startswith("base_")

    ids2 = np.array([10, 11], np.int32)
    t.prepare(ids2)
    t.apply_gradients(jnp.asarray(ids2),
                      jnp.ones((2, DIM), jnp.float32) * 2.0)
    info2 = t.persist(p)
    assert info2["file"].startswith("inc_")
    assert info2["rows"] == 2  # only the changed rows hit disk

    # fresh process restores base + increment
    t2 = make_table()
    t2.restore(p)
    np.testing.assert_allclose(t2.host_weights[ids1], 0.5 - 1.0, rtol=1e-6)
    np.testing.assert_allclose(t2.host_weights[ids2], 0.5 - 2.0, rtol=1e-6)
    np.testing.assert_allclose(t2.host_weights[20], 0.5)
    # optimizer state slots restored too
    assert set(t2.host_slots) == set(t.host_slots)
    # restore continues past the persisted watermark
    assert t2.work_id > t2.persisted_work


def test_persist_chain_compaction(tmp_path):
    """A long run's incremental chain rebases instead of growing forever.

    The reference's incremental-commit protocol periodically rebases
    (PmemEmbeddingTable.h:297-328); without it the file count, meta size,
    and restore replay time grow unboundedly.
    """
    import os
    from openembedding_tpu import offload as off
    t = make_table()
    p = str(tmp_path / "off")
    for step in range(off.COMPACT_CHAIN_LEN + 3):
        ids = np.array([step % 16, 16 + step % 7], np.int32)
        t.prepare(ids)
        t.apply_gradients(jnp.asarray(ids),
                          jnp.ones((2, DIM), jnp.float32) * (step + 1))
        t.persist(p)
    import json
    with open(os.path.join(p, off.OFFLOAD_META_FILE)) as f:
        chain = json.load(f)["checkpoints"]
    assert len(chain) <= off.COMPACT_CHAIN_LEN
    # superseded files are deleted, listed files exist
    files = {e["file"] for e in chain}
    on_disk = {f for f in os.listdir(p) if f.endswith(".npz")}
    assert on_disk == files
    # restore parity with the uncompacted writer's state
    t2 = make_table()
    t2.restore(p)
    np.testing.assert_allclose(t2.host_weights, t.host_weights, rtol=1e-6)


def test_should_persist_window():
    t = make_table(persist_pending_window=3)
    ids = np.array([1], np.int32)
    assert not t.should_persist
    for _ in range(3):
        t.prepare(ids)
        t.apply_gradients(jnp.asarray(ids), jnp.ones((1, DIM), jnp.float32))
    assert t.should_persist


def test_restore_vocab_mismatch(tmp_path):
    t = make_table()
    p = str(tmp_path / "off")
    t.persist(p)
    t2 = HostOffloadedTable(
        EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=500),
        {"category": "sgd", "learning_rate": 1.0}, vocab=500,
        cache_capacity=64)
    with pytest.raises(ValueError, match="vocab"):
        t2.restore(p)


# --- sharded offload tier ----------------------------------------------------

class TestShardedOffload:
    def _make(self, mesh, vocab=1024, cache=128, **kw):
        from openembedding_tpu.offload import ShardedOffloadedTable
        meta = EmbeddingVariableMeta(embedding_dim=4, vocabulary_size=vocab)
        return ShardedOffloadedTable(
            "off", meta, {"category": "adagrad", "learning_rate": 0.1},
            {"category": "constant", "value": 0.25},
            vocab=vocab, cache_capacity=cache, mesh=mesh, **kw)

    def _ground_truth_steps(self, batches):
        """Plain in-HBM array table trained on the same stream."""
        from openembedding_tpu import create_table, apply_gradients, pull
        meta = EmbeddingVariableMeta(embedding_dim=4, vocabulary_size=1024)
        opt = make_optimizer({"category": "adagrad", "learning_rate": 0.1})
        t = create_table(meta, opt,
                         {"category": "constant", "value": 0.25})
        for ids, grads in batches:
            t = apply_gradients(t, opt, jnp.asarray(ids), jnp.asarray(grads))
        return t

    def _stream(self, steps, seed=0):
        rng = np.random.RandomState(seed)
        out = []
        for i in range(steps):
            # rotate through id ranges so the small cache must evict
            lo = (i * 160) % 800
            ids = rng.randint(lo, lo + 200, 64).astype(np.int32)
            out.append((ids, rng.randn(64, 4).astype(np.float32)))
        return out

    def test_eviction_parity_with_plain_table(self, devices8):
        from openembedding_tpu.parallel.mesh import create_mesh
        from openembedding_tpu.parallel import sharded_hash as sh
        mesh = create_mesh(2, 4, devices8)
        table = self._make(mesh, cache=256)
        cache = table.create_cache()
        stream = self._stream(8)
        for ids, grads in stream:
            cache = table.prepare(cache, ids)
            rows = sh.pull_sharded(cache, jnp.asarray(ids), None,
                                   mesh=mesh, spec=table.spec,
                                   batch_sharded=False)
            cache = sh.apply_gradients_sharded(
                cache, table.optimizer, table.initializer,
                jnp.asarray(ids), jnp.asarray(grads),
                mesh=mesh, spec=table.spec, batch_sharded=False)
            table.note_update(ids)
        want = self._ground_truth_steps(stream)
        # flush everything and compare host store to ground truth
        table.flush(cache)
        table._join_writeback()
        from openembedding_tpu import pull
        probe = np.arange(1024, dtype=np.int32)
        np.testing.assert_allclose(
            table.host_weights, np.asarray(pull(want, jnp.asarray(probe))),
            rtol=1e-5, atol=1e-6)

    def test_persist_kill_restore_continue(self, devices8, tmp_path):
        """The reference's pmem_c_api_test.cpp:7-37 flow: train, persist,
        crash, restore, continue — equals an uninterrupted run."""
        from openembedding_tpu.parallel.mesh import create_mesh
        from openembedding_tpu.parallel import sharded_hash as sh
        mesh = create_mesh(2, 4, devices8)
        pdir = str(tmp_path / "persist")
        stream = self._stream(6, seed=3)

        def run(table, cache, items):
            for ids, grads in items:
                cache = table.prepare(cache, ids)
                cache = sh.apply_gradients_sharded(
                    cache, table.optimizer, table.initializer,
                    jnp.asarray(ids), jnp.asarray(grads),
                    mesh=mesh, spec=table.spec, batch_sharded=False)
                table.note_update(ids)
            return cache

        t1 = self._make(mesh, cache=256)
        c1 = run(t1, t1.create_cache(), stream[:3])
        t1.persist(c1, pdir)              # base checkpoint
        c1 = run(t1, c1, stream[3:])
        t1.persist(c1, pdir)              # incremental delta
        t1.flush(c1); t1._join_writeback()
        want = t1.host_weights.copy()

        # crash: a FRESH process-equivalent restores and replays nothing —
        # the persisted state must already be complete
        t2 = self._make(mesh, cache=256)
        c2 = t2.restore(pdir)
        np.testing.assert_allclose(t2.host_weights, want,
                                   rtol=1e-6, atol=1e-7)
        # restore resumes at the batch AFTER the persisted watermark
        assert t2.work_id == t1.work_id + 1
        assert t2.persisted_work == t1.persisted_work
        # continue training from the restored state: both runs agree
        more = self._stream(2, seed=9)
        c1 = run(t1, c1, more)
        c2 = run(t2, c2, more)
        t1.flush(c1); t1._join_writeback()
        t2.flush(c2); t2._join_writeback()
        np.testing.assert_allclose(t2.host_weights, t1.host_weights,
                                   rtol=1e-6, atol=1e-7)

    def test_trainer_integration(self, devices8):
        """Offloaded variable trains through Trainer.fit + eval path."""
        import optax
        from openembedding_tpu import EmbeddingCollection, Trainer
        from openembedding_tpu.models import deepctr
        from openembedding_tpu.parallel.mesh import create_mesh
        mesh = create_mesh(2, 4, devices8)
        table = self._make(mesh, vocab=4096, cache=256)
        spec = table.embedding_spec()
        lin = table.embedding_spec(name="off:linear", output_dim=1)
        coll = EmbeddingCollection((spec, lin), mesh)
        trainer = Trainer(
            deepctr.LogisticRegression(feature_names=("off",)),
            coll, optax.sgd(0.1), offload={"off": table})
        rng = np.random.RandomState(0)

        def batch():
            ids = rng.randint(0, 4096, 32).astype(np.int32)
            return {"label": (ids % 2).astype(np.float32), "dense": None,
                    "sparse": {"off": ids, "off:linear": ids}}

        state = trainer.init(jax.random.PRNGKey(0),
                             trainer.shard_batch(batch()))
        for _ in range(3):
            b = batch()
            state, m = trainer.train_step(state, b)
            assert np.isfinite(float(m["loss"]))
        assert table.work_id > 1
        b = batch()
        state = trainer.prepare_offload(state, b)
        scores = trainer.eval_step(state, b)
        assert scores.shape == (32,)


class TestPipelinedOffload:
    """The prepare-ahead pipeline (host gather of batch N+1 overlapping
    step N) and async persist must be bit-identical to the serial path —
    overlap is a scheduling change, not a numerics change (the reference's
    prefetch_pull_weights contract, exb_ops.cpp:109-205)."""

    def _trainer(self, mesh, vocab=2048, cache=256, depth=2):
        import optax
        from openembedding_tpu import EmbeddingCollection, Trainer
        from openembedding_tpu.models import deepctr
        from openembedding_tpu.offload import ShardedOffloadedTable
        meta = EmbeddingVariableMeta(embedding_dim=4, vocabulary_size=vocab)
        table = ShardedOffloadedTable(
            "off", meta, {"category": "adagrad", "learning_rate": 0.1},
            {"category": "constant", "value": 0.25},
            vocab=vocab, cache_capacity=cache, mesh=mesh,
            persist_pending_window=2)
        lin = ShardedOffloadedTable(
            "off:linear",
            EmbeddingVariableMeta(embedding_dim=1, vocabulary_size=vocab),
            {"category": "adagrad", "learning_rate": 0.1},
            {"category": "constant", "value": 0.25},
            vocab=vocab, cache_capacity=cache, mesh=mesh,
            persist_pending_window=2)
        coll = EmbeddingCollection(
            (table.embedding_spec(), lin.embedding_spec()), mesh)
        trainer = Trainer(
            deepctr.LogisticRegression(feature_names=("off",)),
            coll, optax.sgd(0.1),
            offload={"off": table, "off:linear": lin},
            pipeline_depth=depth)
        return trainer, table, lin

    def _batches(self, n, vocab=2048, seed=0):
        rng = np.random.RandomState(seed)
        out = []
        for i in range(n):
            lo = (i * 300) % (vocab - 400)
            ids = rng.randint(lo, lo + 400, 64).astype(np.int32)
            out.append({"label": (ids % 2).astype(np.float32),
                        "dense": None,
                        "sparse": {"off": ids, "off:linear": ids}})
        return out

    @pytest.mark.slow
    def test_packed_insert_matches_unpacked_fallback(self, devices8):
        """The one-transfer packed insert (keys bitcast into an f32
        column) must land bit-identical rows/slots to the generic
        per-array path — the fallback non-f32 tables take in production
        must not drift from the default path every f32 test exercises."""
        from openembedding_tpu.parallel.mesh import create_mesh
        mesh = create_mesh(2, 4, devices8)
        batches = self._batches(6)

        t_packed, tab_p, lin_p = self._trainer(mesh, cache=4096)
        assert tab_p._packed_layout(np.dtype(np.int32)) is not None
        s_p = t_packed.init(jax.random.PRNGKey(0),
                            t_packed.shard_batch(batches[0]))
        for b in batches:
            s_p, m_p = t_packed.train_step(s_p, b)

        t_plain, tab_u, lin_u = self._trainer(mesh, cache=4096)
        tab_u._packed_layout = lambda *_a, **_k: None   # force fallback
        lin_u._packed_layout = lambda *_a, **_k: None
        s_u = t_plain.init(jax.random.PRNGKey(0),
                           t_plain.shard_batch(batches[0]))
        for b in batches:
            s_u, m_u = t_plain.train_step(s_u, b)

        assert float(m_p["loss"]) == float(m_u["loss"])
        for name in ("off", "off:linear"):
            a, b_ = s_p.emb[name], s_u.emb[name]
            np.testing.assert_array_equal(np.asarray(a.keys),
                                          np.asarray(b_.keys))
            np.testing.assert_array_equal(np.asarray(a.weights),
                                          np.asarray(b_.weights))
            for sname in a.slots:
                np.testing.assert_array_equal(
                    np.asarray(a.slots[sname]),
                    np.asarray(b_.slots[sname]))
        for t in (tab_p, lin_p, tab_u, lin_u):
            t.finish()

    def test_steady_state_makes_no_per_step_device_reads(self, devices8):
        """The pipeline's steady state must never block on a device read:
        one blocking device_get per table per step is what serialized the
        tier on the tunneled bench chip (each read is a synchronous round
        trip; rounds 3-5 measured 466/242 ms steps from exactly this —
        `python -m tools.offload_diag pipeline`). Overflow counters are cumulative on
        device and may be read ONLY at join points (flush/persist/
        restore/finish)."""
        from openembedding_tpu.parallel.mesh import create_mesh
        mesh = create_mesh(2, 4, devices8)
        # cache large enough that nothing evicts: eviction is a JOIN
        # (flush + rebuild) and is allowed to read the device
        trainer, table, lin = self._trainer(mesh, cache=4096)
        batches = self._batches(10)
        state = trainer.init(jax.random.PRNGKey(0),
                             trainer.shard_batch(batches[0]))
        # warm past compiles and the first inserts
        for b in batches[:2]:
            state, _ = trainer.train_step(state, b)

        # intercept every blocking-read spelling the codebase could use:
        # jax.device_get, jax.block_until_ready, and np.asarray/int(arr)
        # (both route through ArrayImpl.__array__)
        reads = []
        orig_get, orig_block = jax.device_get, jax.block_until_ready
        from jax._src import array as _jarray
        orig_arr = _jarray.ArrayImpl.__array__

        def counting_get(x):
            reads.append(f"device_get:{type(x).__name__}")
            return orig_get(x)

        def counting_block(x):
            reads.append(f"block_until_ready:{type(x).__name__}")
            return orig_block(x)

        def counting_array(self, *a, **kw):
            reads.append("ArrayImpl.__array__")
            return orig_arr(self, *a, **kw)

        jax.device_get = counting_get
        jax.block_until_ready = counting_block
        _jarray.ArrayImpl.__array__ = counting_array
        try:
            for i, b in enumerate(batches[2:]):
                nxt = batches[3 + i] if 3 + i < len(batches) else None
                state, _ = trainer.train_step(state, b, next_batch=nxt)
        finally:
            jax.device_get = orig_get
            jax.block_until_ready = orig_block
            _jarray.ArrayImpl.__array__ = orig_arr
        assert reads == [], \
            f"steady-state step made blocking device reads: {reads}"
        # the join point DOES read (and drains the overflow counter)
        table.flush(state.emb["off"])
        table._join_writeback()
        table.finish(); lin.finish()

    @pytest.mark.parametrize("depth", [
        pytest.param(1, marks=pytest.mark.slow),
        pytest.param(2, marks=pytest.mark.slow), 4])
    def test_pipelined_fit_matches_serial_steps(self, devices8, tmp_path,
                                                depth):
        """Bit-identical at EVERY lookahead depth: the planned-residency
        chain must make K prepares in flight equivalent to the serial
        order (the reference's prefetch ``steps`` budget is likewise a
        pure scheduling knob, exb_ops.cpp:148-156)."""
        from openembedding_tpu.parallel.mesh import create_mesh
        mesh = create_mesh(2, 4, devices8)
        batches = self._batches(8)

        # serial: explicit steps, no lookahead, blocking persist
        t_ser, tab_ser, lin_ser = self._trainer(mesh)
        s_ser = t_ser.init(jax.random.PRNGKey(0),
                           t_ser.shard_batch(batches[0]))
        for b in batches:
            s_ser, m_ser = t_ser.train_step(s_ser, b)
        tab_ser.flush(s_ser.emb["off"]); tab_ser._join_writeback()

        # pipelined: fit with lookahead + background persist
        t_pipe, tab_pipe, lin_pipe = self._trainer(mesh, depth=depth)
        s_pipe = t_pipe.init(jax.random.PRNGKey(0),
                             t_pipe.shard_batch(batches[0]))
        s_pipe, m_pipe = t_pipe.fit(s_pipe, batches,
                                    persist_dir=str(tmp_path / "p"))
        tab_pipe._join_persist()
        tab_pipe.flush(s_pipe.emb["off"]); tab_pipe._join_writeback()

        assert float(m_ser["loss"]) == pytest.approx(float(m_pipe["loss"]),
                                                     rel=1e-6)
        np.testing.assert_array_equal(tab_ser.host_weights,
                                      tab_pipe.host_weights)
        assert tab_ser.work_id == tab_pipe.work_id

        # the background persists committed a restorable chain
        tab_r = self._trainer(mesh)[1]
        c = tab_r.restore(str(tmp_path / "p" / "off"))
        assert tab_r.persisted_work > 0
        assert c.keys.shape[0] == tab_r.cache_capacity

    @pytest.mark.parametrize("depth", [
        2, pytest.param(4, marks=pytest.mark.slow)])
    def test_pipeline_survives_eviction_batches(self, devices8, depth):
        """A lookahead batch that would overflow the cache falls back to
        the synchronous evict path mid-pipeline, values staying exact —
        including the generation-bump recompute of the (depth-1) prepares
        that were in flight when the eviction rebuilt the cache."""
        from openembedding_tpu.parallel.mesh import create_mesh
        mesh = create_mesh(2, 4, devices8)
        batches = self._batches(10, seed=5)
        t_small, tab_small, _ = self._trainer(mesh, cache=256,
                                              depth=depth)  # evicts
        s = t_small.init(jax.random.PRNGKey(0),
                         t_small.shard_batch(batches[0]))
        s, _ = t_small.fit(s, batches)
        tab_small.flush(s.emb["off"]); tab_small._join_writeback()
        assert tab_small._gen > 0  # eviction really hit the pipeline

        t_big, tab_big, _ = self._trainer(mesh, cache=2048)  # never evicts
        s2 = t_big.init(jax.random.PRNGKey(0),
                        t_big.shard_batch(batches[0]))
        s2, _ = t_big.fit(s2, batches)
        tab_big.flush(s2.emb["off"]); tab_big._join_writeback()
        np.testing.assert_allclose(tab_small.host_weights,
                                   tab_big.host_weights,
                                   rtol=1e-5, atol=1e-6)


_KILL_CHILD = r"""
import os, signal, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
sys.path.insert(0, {root!r})
from openembedding_tpu import EmbeddingVariableMeta
from openembedding_tpu.offload import HostOffloadedTable
from openembedding_tpu.utils import fs

t = HostOffloadedTable(
    EmbeddingVariableMeta(embedding_dim=4, vocabulary_size=1000),
    {{"category": "sgd", "learning_rate": 1.0}},
    {{"category": "constant", "value": 0.5}},
    vocab=1000, cache_capacity=256)
p = {pdir!r}
ids1 = np.array([1, 2, 3], np.int32)
t.prepare(ids1)
t.apply_gradients(jnp.asarray(ids1), jnp.ones((3, 4), jnp.float32))
t.persist(p)                               # committed base checkpoint
ids2 = np.array([10, 11], np.int32)
t.prepare(ids2)
t.apply_gradients(jnp.asarray(ids2), jnp.ones((2, 4), jnp.float32) * 2.0)

mode = {mode!r}
if mode == "mid_file":
    # SIGKILL while the incremental chain file's bytes are mid-write
    orig_write = fs._AtomicFile.write
    def dying_write(self, data):
        orig_write(self, bytes(data)[: max(1, len(data) // 2)])
        self._f.flush()
        os.kill(os.getpid(), signal.SIGKILL)
    fs._AtomicFile.write = dying_write
else:
    # chain file fully committed, SIGKILL before the meta commit point
    def dying_json(path, obj):
        os.kill(os.getpid(), signal.SIGKILL)
    fs.write_json_atomic = dying_json
    import openembedding_tpu.offload as off
    off.fs.write_json_atomic = dying_json
print("persisting", flush=True)
t.persist(p)                               # never returns
"""


@pytest.mark.parametrize("mode", [
    pytest.param("mid_file", marks=pytest.mark.slow), "pre_meta"])
def test_kill_mid_persist_restores_watermark(tmp_path, mode):
    """SIGKILL INSIDE persist (mid chain-file write / before the meta
    commit) must leave a restorable checkpoint at the PREVIOUS watermark —
    the reference's transactional pool-root commit
    (PmemEmbeddingItemPool.h:236-296). Restore ignores the debris; the
    next persist (the directory's single writer) GCs it."""
    import os
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pdir = str(tmp_path / "off")
    code = _KILL_CHILD.format(root=root, pdir=pdir, mode=mode)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == -9, (out.returncode, out.stdout, out.stderr)
    assert "persisting" in out.stdout

    # a fresh process restores the BASE state (pre-second-persist watermark)
    t2 = make_table()
    t2.restore(pdir)
    np.testing.assert_allclose(t2.host_weights[[1, 2, 3]], 0.5 - 1.0,
                               rtol=1e-6)
    # the second batch's update was never committed
    np.testing.assert_allclose(t2.host_weights[[10, 11]], 0.5)
    # the survivor trains on and persists: the writer-side sweep GCs the
    # crash debris, and the new chain is fully consistent
    ids3 = np.array([42], np.int32)
    t2.prepare(ids3)
    t2.apply_gradients(jnp.asarray(ids3), jnp.ones((1, DIM), jnp.float32))
    t2.persist(pdir)
    from openembedding_tpu import offload as off
    left = sorted(os.listdir(pdir))
    assert off.OFFLOAD_META_FILE in left
    import json
    with open(os.path.join(pdir, off.OFFLOAD_META_FILE)) as f:
        chain = {e["file"] for e in json.load(f)["checkpoints"]}
    assert set(left) == chain | {off.OFFLOAD_META_FILE}, (left, chain)
    t3 = make_table()
    t3.restore(pdir)
    np.testing.assert_allclose(t3.host_weights[42], 0.5 - 1.0, rtol=1e-6)


def test_persist_restore_remote_uri(tmp_path):
    """Offload persistence streams to fsspec URIs like the checkpoint dump
    (memory:// stands in for gs://; the reference persists its PMem pool
    through the same remote-capable file layer)."""
    import uuid
    uri = f"memory://off-{uuid.uuid4().hex}"
    t = make_table()
    ids = np.array([1, 2, 3], np.int32)
    t.prepare(ids)
    t.apply_gradients(jnp.asarray(ids), jnp.ones((3, DIM), jnp.float32))
    t.persist(uri)
    ids2 = np.array([7], np.int32)
    t.prepare(ids2)
    t.apply_gradients(jnp.asarray(ids2), jnp.ones((1, DIM), jnp.float32))
    info = t.persist(uri)
    assert info["file"].startswith("inc_")
    t2 = make_table()
    t2.restore(uri)
    np.testing.assert_allclose(t2.host_weights, t.host_weights, rtol=1e-6)


_PIPELINE_KILL_CHILD = r"""
import sys
sys.path.insert(0, {root!r})
import jax
from openembedding_tpu.utils.jaxcompat import set_num_cpu_devices
jax.config.update("jax_platforms", "cpu")
set_num_cpu_devices(8)
import numpy as np
import optax
from openembedding_tpu import (EmbeddingCollection, EmbeddingVariableMeta,
                               Trainer)
from openembedding_tpu.models import deepctr
from openembedding_tpu.offload import ShardedOffloadedTable
from openembedding_tpu.parallel.mesh import create_mesh

mesh = create_mesh(2, 4)
table = ShardedOffloadedTable(
    "off", EmbeddingVariableMeta(embedding_dim=1, vocabulary_size=2048),
    {{"category": "adagrad", "learning_rate": 0.1}},
    {{"category": "constant", "value": 0.25}},
    vocab=2048, cache_capacity=256, mesh=mesh, persist_pending_window=2)
coll = EmbeddingCollection((table.embedding_spec(name="off:linear"),),
                           mesh)
trainer = Trainer(deepctr.LogisticRegression(feature_names=("off",)),
                  coll, optax.sgd(0.1), offload={{"off:linear": table}},
                  pipeline_depth=3)
rng = np.random.RandomState(11)
batches = []
for i in range(40):
    lo = (i * 300) % 1600
    ids = rng.randint(lo, lo + 400, 64).astype(np.int32)
    batches.append({{"label": (ids % 2).astype(np.float32), "dense": None,
                   "sparse": {{"off:linear": ids}}}})
state = trainer.init(jax.random.PRNGKey(0), trainer.shard_batch(batches[0]))
trainer.fit(state, batches, log_every=1, persist_dir={pdir!r})
print("FINISHED", flush=True)
"""


@pytest.mark.slow
def test_kill_mid_pipelined_fit_resume_exact(tmp_path):
    """SIGKILL a child mid-``fit`` with the WHOLE pipeline in flight —
    depth-3 lookahead prepares, async writeback, async incremental
    persist — then restore from the committed chain and RESUME from the
    committed watermark: the resumed run must land bit-identical to an
    uninterrupted serial run of the same batches (the reference's
    restore-and-continue contract around its transactional PMem commits,
    PmemEmbeddingItemPool.h:236-296)."""
    import os
    import signal as signal_mod
    import subprocess
    import sys
    import jax
    import optax
    from openembedding_tpu import (EmbeddingCollection, Trainer)
    from openembedding_tpu.models import deepctr
    from openembedding_tpu.offload import ShardedOffloadedTable
    from openembedding_tpu.parallel.mesh import create_mesh

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pdir = str(tmp_path / "p")
    code = _PIPELINE_KILL_CHILD.format(root=root, pdir=pdir)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # -u: the child's fit() log lines must stream UNBUFFERED — with the
    # default block buffering every line arrives only at exit and the
    # SIGKILL would land on an already-finished child (a vacuous test)
    proc = subprocess.Popen([sys.executable, "-u", "-c", code], env=env,
                            stdout=subprocess.PIPE, text=True)
    # kill mid-run: after step 15 the depth-3 window is full, the async
    # persister has fired ~7 times, and writebacks ride evictions
    killed = False
    for line in proc.stdout:
        if line.startswith("step 15:"):
            proc.send_signal(signal_mod.SIGKILL)
            killed = True
            break
        assert not line.startswith("FINISHED"), "child outran the kill"
    assert killed, "child died before step 15"
    assert proc.wait() == -9, "child was not killed mid-run"

    def make_parts(depth):
        mesh = create_mesh(2, 4, jax.devices()[:8])
        from openembedding_tpu import EmbeddingVariableMeta
        table = ShardedOffloadedTable(
            "off", EmbeddingVariableMeta(embedding_dim=1,
                                         vocabulary_size=2048),
            {"category": "adagrad", "learning_rate": 0.1},
            {"category": "constant", "value": 0.25},
            vocab=2048, cache_capacity=256, mesh=mesh,
            persist_pending_window=2)
        coll = EmbeddingCollection(
            (table.embedding_spec(name="off:linear"),), mesh)
        trainer = Trainer(
            deepctr.LogisticRegression(feature_names=("off",)),
            coll, optax.sgd(0.1), offload={"off:linear": table},
            pipeline_depth=depth)
        return trainer, table

    rng = np.random.RandomState(11)
    batches = []
    for i in range(40):
        lo = (i * 300) % 1600
        ids = rng.randint(lo, lo + 400, 64).astype(np.int32)
        batches.append({"label": (ids % 2).astype(np.float32),
                        "dense": None, "sparse": {"off:linear": ids}})

    # serial reference: snapshot (host store, params) after every batch
    t_ref, tab_ref = make_parts(1)
    s_ref = t_ref.init(jax.random.PRNGKey(0),
                       t_ref.shard_batch(batches[0]))
    snaps = {}
    for b in batches:
        s_ref, _ = t_ref.train_step(s_ref, b)
        tab_ref.flush(s_ref.emb["off:linear"])
        tab_ref._join_writeback()
        snaps[tab_ref.work_id] = (
            tab_ref.host_weights.copy(),
            {k: v.copy() for k, v in tab_ref.host_slots.items()},
            jax.tree.map(lambda x: np.asarray(x).copy(), s_ref.params))

    # restore: the chain must be consistent at SOME committed watermark
    t_res, tab_res = make_parts(3)
    cache = tab_res.restore(os.path.join(pdir, "off:linear"))
    w = tab_res.persisted_work
    assert w in snaps and w >= 3, f"watermark {w} not a batch boundary"
    # the kill landed MID-run: there must be committed-but-incomplete
    # progress, i.e. real batches left for the resume to replay
    assert w <= 20, f"watermark {w}: child finished before the kill"
    ref_weights, ref_slots, ref_params = snaps[w]
    np.testing.assert_array_equal(tab_res.host_weights, ref_weights)
    for k in ref_slots:
        np.testing.assert_array_equal(tab_res.host_slots[k], ref_slots[k])

    # resume from the watermark with the reference's dense params: the
    # continued run must land exactly where the uninterrupted run did
    s2 = t_res.init(jax.random.PRNGKey(0), t_res.shard_batch(batches[0]))
    s2 = s2.replace(emb={"off:linear": cache},
                    params=jax.tree.map(jnp.asarray, ref_params))
    done = w - 1    # work_id w  <=>  w-1 batches committed
    s2, _ = t_res.fit(s2, batches[done:])
    tab_res.flush(s2.emb["off:linear"])
    tab_res._join_writeback()
    np.testing.assert_array_equal(tab_res.host_weights,
                                  tab_ref.host_weights)
    for k in tab_ref.host_slots:
        np.testing.assert_array_equal(tab_res.host_slots[k],
                                      tab_ref.host_slots[k])


@pytest.mark.slow
def test_hand_driven_prefetch_matches_fit(devices8):
    """The PUBLIC prefetch API (the bench's hand-driven pattern:
    ``prefetch(window); train_step(batch)``) is the same pipeline fit
    wires — bit-identical results."""
    inst = TestPipelinedOffload()
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(2, 4, devices8)
    batches = inst._batches(8, seed=9)

    t_fit, tab_fit, _ = inst._trainer(mesh, depth=2)
    s = t_fit.init(jax.random.PRNGKey(0), t_fit.shard_batch(batches[0]))
    s, _ = t_fit.fit(s, batches)
    tab_fit.flush(s.emb["off"]); tab_fit._join_writeback()

    t_hand, tab_hand, _ = inst._trainer(mesh, depth=2)
    s2 = t_hand.init(jax.random.PRNGKey(0), t_hand.shard_batch(batches[0]))
    for i in range(len(batches)):
        t_hand.prefetch(batches[i:i + 3])
        s2, _ = t_hand.train_step(s2, batches[i])
    tab_hand.finish()
    tab_hand.flush(s2.emb["off"]); tab_hand._join_writeback()
    np.testing.assert_array_equal(tab_fit.host_weights,
                                  tab_hand.host_weights)


def test_persist_compress_chain(tmp_path, devices8):
    """A zlib persist chain restores identically to a raw one, raw and
    compressed entries can share a chain, and the compressed files are
    smaller on compressible (constant-init) stores."""
    import os
    from openembedding_tpu.offload import ShardedOffloadedTable
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(2, 4, devices8)

    def mk(compress):
        return ShardedOffloadedTable(
            "t", EmbeddingVariableMeta(embedding_dim=4,
                                       vocabulary_size=512),
            {"category": "sgd", "learning_rate": 1.0},
            {"category": "constant", "value": 0.5},
            vocab=512, cache_capacity=128, mesh=mesh,
            persist_compress=compress)

    stores = {}
    for codec in ("", "zlib"):
        t = mk(codec)
        c = t.create_cache()
        ids = np.arange(0, 40, dtype=np.int32)
        c = t.prepare(c, ids)
        t.note_update(ids)
        c2 = t.prepare(c, np.arange(40, 60, dtype=np.int32))
        t.note_update(np.arange(40, 60, dtype=np.int32))
        d = str(tmp_path / f"chain{codec}")
        t.persist(c2, d)                       # base
        ids3 = np.arange(60, 70, dtype=np.int32)
        c3 = t.prepare(c2, ids3)
        t.note_update(ids3)
        t.persist(c3, d)                       # delta
        stores[codec] = d

    # compressed chain is materially smaller (constant-init rows)
    size = {c: sum(os.path.getsize(os.path.join(d, f))
                   for f in os.listdir(d)) for c, d in stores.items()}
    assert size["zlib"] < size[""] * 0.5, size

    r_raw, r_z = mk(""), mk("")
    r_raw.restore(stores[""])
    r_z.restore(stores["zlib"])
    np.testing.assert_array_equal(r_raw.host_weights, r_z.host_weights)
    assert r_raw.persisted_work == r_z.persisted_work

    # mixed chain: a raw table appends a raw delta onto the zlib chain
    t2 = mk("")
    c = t2.restore(stores["zlib"])
    ids4 = np.arange(70, 80, dtype=np.int32)
    c = t2.prepare(c, ids4)
    t2.note_update(ids4)
    t2.persist(c, stores["zlib"])
    t3 = mk("zlib")
    t3.restore(stores["zlib"])
    assert t3.persisted_work == t2.work_id


@pytest.mark.slow
def test_pipeline_parity_under_timing_fuzz(devices8):
    """Randomized host-gather delays shift every prepare/apply/evict
    interleaving; results must stay bit-identical to serial regardless
    (the planned-residency books + generation protocol, not luck, carry
    the correctness). Small cache so evictions and stale-generation
    recomputes fire mid-window."""
    import time as time_mod
    inst = TestPipelinedOffload()
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(2, 4, devices8)
    batches = inst._batches(12, seed=13)

    t_ser, tab_ser, lin_ser = inst._trainer(mesh, cache=256)
    s = t_ser.init(jax.random.PRNGKey(0), t_ser.shard_batch(batches[0]))
    for b in batches:
        s, _ = t_ser.train_step(s, b)
    tab_ser.flush(s.emb["off"]); tab_ser._join_writeback()
    lin_ser.flush(s.emb["off:linear"]); lin_ser._join_writeback()

    fuzz = np.random.RandomState(99)
    t_f, tab_f, lin_f = inst._trainer(mesh, cache=256, depth=4)
    for t in (tab_f, lin_f):
        orig = t._gather_host

        def jittery(ids, _orig=orig):
            time_mod.sleep(float(fuzz.uniform(0, 0.03)))
            return _orig(ids)

        t._gather_host = jittery
    s2 = t_f.init(jax.random.PRNGKey(0), t_f.shard_batch(batches[0]))
    s2, _ = t_f.fit(s2, batches)
    tab_f.flush(s2.emb["off"]); tab_f._join_writeback()
    lin_f.flush(s2.emb["off:linear"]); lin_f._join_writeback()
    assert tab_f.evictions > 0
    # NOTE: the generation-RETRY paths rarely fire here — the budget check
    # runs against resident+planned, so once the window overflows, later
    # prepares degrade to needs_evict instead of gathering at a soon-stale
    # generation. The deterministic tests below force those paths.
    np.testing.assert_array_equal(tab_ser.host_weights, tab_f.host_weights)
    np.testing.assert_array_equal(lin_ser.host_weights, lin_f.host_weights)


def _mk_sharded(mesh, vocab=2048, cache=256):
    from openembedding_tpu.offload import ShardedOffloadedTable
    return ShardedOffloadedTable(
        "t", EmbeddingVariableMeta(embedding_dim=4, vocabulary_size=vocab),
        {"category": "sgd", "learning_rate": 1.0},
        {"category": "constant", "value": 0.25},
        vocab=vocab, cache_capacity=cache, mesh=mesh)


def test_stale_prepare_recomputed_at_apply(devices8):
    """A prepare computed before an eviction must be RECOMPUTED at its
    apply (generation mismatch), in batch-order priority over any
    lookahead claims — applying it verbatim would insert rows the
    rebuild dropped and resurrect pre-eviction host values."""
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(2, 4, devices8)
    t = _mk_sharded(mesh)
    cache = t.create_cache()
    # in-budget prepare at generation 0 (planned marks set)
    ids_a = np.arange(0, 50, dtype=np.int32)
    prep_a = t.host_prepare(ids_a)
    assert not prep_a.needs_evict and prep_a.gen == 0
    # a second prepare overflows the budget -> needs_evict; applying it
    # FIRST (out of order, table-level API permits it) rebuilds the cache
    ids_b = np.arange(100, 100 + 160, dtype=np.int32)
    prep_b = t.host_prepare(ids_b)
    assert prep_b.needs_evict
    cache = t.apply_prepared(cache, prep_b)
    assert t.evictions == 1 and t._gen == 1
    # prep_a is now stale: its apply must take the recompute path
    cache = t.apply_prepared(cache, prep_a)
    assert t.gen_retries >= 1
    assert bool(t._resident[ids_a].all())
    # values: cache rows for ids_a equal host rows (insert really landed)
    from openembedding_tpu.parallel import sharded_hash as sh
    got = np.asarray(sh.pull_sharded(cache, jnp.asarray(ids_a), None,
                                     mesh=mesh, spec=t.spec,
                                     batch_sharded=False))
    np.testing.assert_array_equal(got, t.host_weights[ids_a])


def test_gather_retry_when_evicted_mid_gather(devices8):
    """An eviction landing while a lookahead gather is in flight must
    force that host_prepare to retry at the new generation (the torn
    read would otherwise mark planned rows against dropped residency)."""
    import threading
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(2, 4, devices8)
    t = _mk_sharded(mesh)
    cache = t.create_cache()
    # sized against budget 0.7*256=179 with keep_fraction 0.5 (keep 89):
    # 135 warm + 40 prep = 175 fits (the prep GATHERS, parking in the
    # patch); 135 + 45 big = 180 overflows (big evicts); post-evict
    # 89 kept + 45 + 40 retried = 174 fits (the retry lands in-budget)
    warm = np.arange(0, 135, dtype=np.int32)
    cache = t.prepare(cache, warm)
    t.note_update(warm)

    in_gather = threading.Event()
    release = threading.Event()
    orig = t._gather_host
    fired = []

    def blocking_gather(ids):
        if not fired:
            fired.append(True)
            in_gather.set()
            release.wait(timeout=30)
        return orig(ids)

    t._gather_host = blocking_gather
    out = {}

    def prep_thread():
        out["prep"] = t.host_prepare(np.arange(200, 240, dtype=np.int32))

    th = threading.Thread(target=prep_thread)
    th.start()
    assert in_gather.wait(timeout=30)
    # eviction on the main thread while the gather is parked
    big = t.host_prepare(np.arange(300, 345, dtype=np.int32))
    assert big.needs_evict
    ev = threading.Thread(target=lambda: out.update(
        cache2=t.apply_prepared(cache, big)))
    ev.start()
    # the evictor never needs the parked gather (the prep thread holds no
    # lock while parked), so it can run to completion first — POLL for
    # the generation bump instead of racing a sleep against JIT/IO time
    import time as time_mod
    deadline = time_mod.time() + 60
    while t._gen == 0 and time_mod.time() < deadline:
        time_mod.sleep(0.01)
    assert t._gen == 1, "eviction did not complete"
    release.set()
    th.join(timeout=60); ev.join(timeout=60)
    assert not th.is_alive() and not ev.is_alive()
    prep = out["prep"]
    # the parked gather's generation went stale; the retry recomputed at
    # the post-eviction generation
    assert t.gen_retries >= 1
    assert prep.gen == t._gen and not prep.needs_evict
    t.cancel_prepared(prep)
    assert t._planned_count == 0


def test_overflow_check_every_n_batches(devices8):
    """Bounded-lag overflow detection (ADVICE r5): with the knob set, a
    deferred insert overflow surfaces within N note_update calls — not
    only at finish() — so hand-driven loops and fit() without persist_dir
    keep a bounded detection lag."""
    from openembedding_tpu.offload import ShardedOffloadedTable
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(2, 4, devices8)
    t = ShardedOffloadedTable(
        "t", EmbeddingVariableMeta(embedding_dim=4, vocabulary_size=512),
        {"category": "sgd", "learning_rate": 1.0},
        {"category": "constant", "value": 0.25},
        vocab=512, cache_capacity=256, mesh=mesh,
        overflow_check_every_n_batches=3)
    t._overflow_latest = jnp.asarray(1, jnp.int32)  # deferred evidence
    ids = np.array([1, 2], np.int32)
    t.note_update(ids)
    t.note_update(ids)  # lag stays below N: no device read yet
    with pytest.raises(RuntimeError, match="insert overflow"):
        t.note_update(ids)
    # evidence drained by the raise; the run can unwind through finish()
    t.finish()


def test_check_overflow_prefers_live_cache_counter(devices8):
    """flush (and _evict/persist) read the LIVE cache.insert_failures
    (ADVICE r5): failures accumulated by the jitted step's gradient-apply
    auto-insert AFTER the last host-side insert are caught even though
    the _overflow_latest copy never saw them."""
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(2, 4, devices8)
    t = _mk_sharded(mesh)
    cache = t.create_cache(jax.random.PRNGKey(0))
    assert t._overflow_latest is None  # no host-side insert happened
    poisoned = cache.replace(insert_failures=jnp.asarray(2, jnp.int32))
    with pytest.raises(RuntimeError, match="insert overflow"):
        t.flush(poisoned)
    # a clean cache passes, and the copy (None) is not consulted
    assert t.flush(cache) == 0
    t._join_writeback()
