"""Host-offload tier: cache residency, writeback, incremental persist/restore
— the reference's PMem test matrix (pmem_embedding_table_test.cpp: set/get
across work_ids, checkpoint commit, cache eviction with tiny budgets,
load_pmem_pool recovery; pmem_c_api_test.cpp: train/persist/restore loop)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import EmbeddingVariableMeta
from openembedding_tpu.offload import HostOffloadedTable

DIM = 4
META = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=1000)


def make_table(**kw):
    kw.setdefault("vocab", 1000)
    kw.setdefault("cache_capacity", 256)
    return HostOffloadedTable(
        META, {"category": "sgd", "learning_rate": 1.0},
        {"category": "constant", "value": 0.5}, **kw)


def test_pull_through_cache_matches_host():
    t = make_table()
    ids = np.array([1, 500, 999], np.int32)
    t.prepare(ids)
    rows = np.asarray(t.pull(jnp.asarray(ids)))
    np.testing.assert_allclose(rows, t.host_weights[ids], rtol=1e-6)


def test_update_flush_writeback():
    t = make_table()
    ids = np.array([7, 8, 9], np.int32)
    t.prepare(ids)
    t.apply_gradients(jnp.asarray(ids), jnp.ones((3, DIM), jnp.float32))
    # host copy still stale until flush
    np.testing.assert_allclose(t.host_weights[ids], 0.5)
    flushed = t.flush()
    assert flushed == 3
    np.testing.assert_allclose(t.host_weights[ids], 0.5 - 1.0, rtol=1e-6)
    assert (t.host_work_id[ids] > 0).all()
    # state round-trips: rows come back with their values after re-prepare
    t.prepare(ids)
    np.testing.assert_allclose(np.asarray(t.pull(jnp.asarray(ids))),
                               0.5 - 1.0, rtol=1e-6)


def test_tiny_cache_eviction_cycle():
    """Cache smaller than the id stream: prepare must flush-and-refill, and
    values stay exact across evictions (the 1-5 item cache-budget tests)."""
    t = make_table(cache_capacity=64)
    rng = np.random.RandomState(0)
    host_replica = t.host_weights.copy()
    for step in range(8):
        ids = rng.randint(0, 1000, 40).astype(np.int32)
        uniq = np.unique(ids)
        t.prepare(ids)
        t.apply_gradients(jnp.asarray(uniq),
                          jnp.ones((uniq.size, DIM), jnp.float32) * 0.1)
        host_replica[uniq] -= 0.1
    t.flush()
    np.testing.assert_allclose(t.host_weights, host_replica, rtol=1e-5,
                               atol=1e-6)


def test_incremental_persist_restore(tmp_path):
    t = make_table()
    p = str(tmp_path / "off")
    ids1 = np.array([1, 2, 3], np.int32)
    t.prepare(ids1)
    t.apply_gradients(jnp.asarray(ids1), jnp.ones((3, DIM), jnp.float32))
    info = t.persist(p)
    assert info["file"].startswith("base_")

    ids2 = np.array([10, 11], np.int32)
    t.prepare(ids2)
    t.apply_gradients(jnp.asarray(ids2),
                      jnp.ones((2, DIM), jnp.float32) * 2.0)
    info2 = t.persist(p)
    assert info2["file"].startswith("inc_")
    assert info2["rows"] == 2  # only the changed rows hit disk

    # fresh process restores base + increment
    t2 = make_table()
    t2.restore(p)
    np.testing.assert_allclose(t2.host_weights[ids1], 0.5 - 1.0, rtol=1e-6)
    np.testing.assert_allclose(t2.host_weights[ids2], 0.5 - 2.0, rtol=1e-6)
    np.testing.assert_allclose(t2.host_weights[20], 0.5)
    # optimizer state slots restored too
    assert set(t2.host_slots) == set(t.host_slots)
    # restore continues past the persisted watermark
    assert t2.work_id > t2.persisted_work


def test_should_persist_window():
    t = make_table(persist_pending_window=3)
    ids = np.array([1], np.int32)
    assert not t.should_persist
    for _ in range(3):
        t.prepare(ids)
        t.apply_gradients(jnp.asarray(ids), jnp.ones((1, DIM), jnp.float32))
    assert t.should_persist


def test_restore_vocab_mismatch(tmp_path):
    t = make_table()
    p = str(tmp_path / "off")
    t.persist(p)
    t2 = HostOffloadedTable(
        EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=500),
        {"category": "sgd", "learning_rate": 1.0}, vocab=500,
        cache_capacity=64)
    with pytest.raises(ValueError, match="vocab"):
        t2.restore(p)
