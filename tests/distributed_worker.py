"""Worker body for the 2-process distributed test (run by test_distributed).

Each process is one "host" with 2 virtual CPU devices; the 2x2 global mesh
spans both. This is the JAX-native version of the reference's fork-based
multi-node simulation (core::MultiProcess, entry/c_api_test.h:194): real
cross-process collectives, one box.
"""

import os
import sys


def main() -> int:
    rank = int(sys.argv[1])
    port = sys.argv[2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax
    from openembedding_tpu.utils.jaxcompat import set_num_cpu_devices
    jax.config.update("jax_platforms", "cpu")
    set_num_cpu_devices(2)

    from openembedding_tpu import distributed
    distributed.initialize(master_endpoint=f"127.0.0.1:{port}",
                           num_workers=2, worker_rank=rank)
    assert distributed.num_workers() == 2
    assert distributed.worker_rank() == rank
    assert len(jax.devices()) == 4, jax.devices()
    assert len(jax.local_devices()) == 2

    import numpy as np
    import jax.numpy as jnp
    from openembedding_tpu import EmbeddingCollection, EmbeddingSpec

    # reference Communication parity: barrier + broadcast
    distributed.barrier("boot")
    v = distributed.broadcast(np.asarray([123.0 + rank], np.float32))
    assert float(v[0]) == 123.0, v  # rank 0's value everywhere

    mesh = distributed.create_global_mesh(data=2, model=2)
    spec = EmbeddingSpec(name="t", input_dim=32, output_dim=4,
                         initializer={"category": "constant", "value": 0.0},
                         optimizer={"category": "sgd", "learning_rate": 1.0})
    coll = EmbeddingCollection((spec,), mesh)
    states = coll.init(jax.random.PRNGKey(0))

    # each process contributes ITS OWN batch slice: 4 rows each, global 8.
    # Every entry hits row 5 with grad 1.0 -> after one step w[5] = -8
    # only if gradients crossed the process boundary.
    local_ids = np.full((4,), 5, np.int32)
    gbatch = distributed.local_batch_to_global(
        {"t": local_ids}, mesh)
    rows = coll.pull(states, gbatch)
    assert rows["t"].shape == (8, 4)
    g = jnp.ones_like(rows["t"])
    states = coll.apply_gradients(states, gbatch, {"t": g})

    from jax.experimental import multihost_utils
    probe = distributed.local_batch_to_global(
        {"t": np.asarray([5, 6], np.int32) if rank == 0
         else np.asarray([5, 7], np.int32)}, mesh)
    out = coll.pull(states, probe)["t"]
    full = np.asarray(multihost_utils.process_allgather(out, tiled=True))
    # global probe order: rank0 ids [5, 6] then rank1 ids [5, 7]
    np.testing.assert_allclose(full[:, 0], [-8.0, 0.0, -8.0, 0.0],
                               rtol=1e-6, atol=1e-6)

    # multi-host checkpoint: each process writes its part files; reload on
    # the same cluster reproduces the table (per-node dump layout)
    if len(sys.argv) > 3:
        from openembedding_tpu import checkpoint as ckpt
        ckpt_dir = sys.argv[3]
        hspec = EmbeddingSpec(name="h", input_dim=-1, output_dim=4,
                              hash_capacity=256,
                              initializer={"category": "constant",
                                           "value": 0.25},
                              optimizer={"category": "sgd",
                                         "learning_rate": 1.0})
        coll2 = EmbeddingCollection((spec, hspec), mesh)
        st2 = coll2.init(jax.random.PRNGKey(0))
        st2["t"] = states["t"]  # the trained table from above
        hkeys = distributed.local_batch_to_global(
            {"h": np.asarray([1001, 1002], np.int32) if rank == 0
             else np.asarray([1003, 1004], np.int32)}, mesh)
        st2 = coll2.apply_gradients(
            st2, hkeys, {"h": jnp.ones((4, 4), jnp.float32)})
        ckpt.save_checkpoint(ckpt_dir, coll2, st2, model_sign="mh-1")
        loaded = ckpt.load_checkpoint(ckpt_dir, coll2)
        got = coll2.pull(loaded, probe)["t"]
        lfull = np.asarray(multihost_utils.process_allgather(
            got, tiled=True))
        np.testing.assert_allclose(lfull, full, rtol=1e-6, atol=1e-6)
        hprobe = distributed.local_batch_to_global(
            {"h": np.asarray([1001, 1003], np.int32) if rank == 0
             else np.asarray([1004, 9999], np.int32)}, mesh)
        hrows = np.asarray(multihost_utils.process_allgather(
            coll2.pull(loaded, hprobe, read_only=True)["h"], tiled=True))
        np.testing.assert_allclose(hrows[:3], 0.25 - 1.0, rtol=1e-6)
        np.testing.assert_allclose(hrows[3], 0.0)  # unseen key
        print(f"worker {rank}: multihost checkpoint ok", flush=True)

    distributed.barrier("done")
    print(f"worker {rank}: ok", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
