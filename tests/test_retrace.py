"""Retrace guard: compile counting, budgets, and Trainer.fit wiring."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from openembedding_tpu.analysis import retrace


def test_counts_compiles_and_cache_hits():
    @jax.jit
    def f(x):
        return x * 2 + 1

    n_first = retrace.compile_count(f, jnp.ones((16,)))
    assert n_first >= 1
    # cached: same shape compiles nothing
    assert retrace.compile_count(f, jnp.ones((16,))) == 0
    # new shape retraces
    assert retrace.compile_count(f, jnp.ones((17,))) >= 1


def test_guard_trips_on_budget():
    @jax.jit
    def g(x):
        return x + 1

    g(jnp.ones((4,)))                       # warm
    with pytest.raises(retrace.RetraceBudgetExceeded, match="budget"):
        with retrace.RetraceGuard(budget=0, name="wobble"):
            for n in (5, 6, 7):             # shape wobble: 3 compiles
                g(jnp.ones((n,)))

    with retrace.RetraceGuard(budget=0):
        g(jnp.ones((4,)))                   # cached: stays quiet


def test_guard_warn_mode_and_properties():
    @jax.jit
    def h(x):
        return x - 1

    with pytest.warns(RuntimeWarning, match="retrace budget"):
        with retrace.RetraceGuard(budget=0, on_exceed="warn") as guard:
            h(jnp.ones((31,)))
    assert guard.compiles >= 1 and guard.exceeded
    with pytest.raises(ValueError, match="on_exceed"):
        retrace.RetraceGuard(on_exceed="explode")


def test_guard_does_not_mask_inner_error():
    with pytest.raises(KeyError):
        with retrace.RetraceGuard(budget=0):
            jax.jit(lambda x: x * 3)(jnp.ones((9,)))
            raise KeyError("the original error is the story")


def test_assert_no_recompiles_helper():
    @jax.jit
    def f(x):
        return x @ x.T

    retrace.assert_no_recompiles(f, jnp.ones((8, 4)))

    calls = []

    def shapeshifter(x):
        calls.append(x)
        return jax.jit(lambda v: v + len(calls))(x)  # new closure/step

    with pytest.raises(retrace.RetraceBudgetExceeded):
        retrace.assert_no_recompiles(shapeshifter, jnp.ones((4,)))


def test_fit_retrace_budget_wiring(devices8):
    """Trainer.fit(retrace_budget=...): a steady fixed-shape loop passes
    a zero post-warmup budget; a shape-wobbling loop trips it."""
    from openembedding_tpu import EmbeddingCollection, Trainer
    from openembedding_tpu.models import deepctr
    from openembedding_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(2, 4, devices8)

    def make(batch_sizes, budget):
        specs = deepctr.make_feature_specs(("f",), 64, 4)
        coll = EmbeddingCollection(
            specs, mesh,
            default_optimizer={"category": "sgd", "learning_rate": 0.1})
        trainer = Trainer(
            deepctr.LogisticRegression(feature_names=("f",)), coll,
            optax.sgd(1e-2))
        rng = np.random.RandomState(0)

        def batches():
            for b in batch_sizes:
                ids = rng.randint(0, 64, b).astype(np.int32)
                yield {"label": (ids % 2).astype(np.float32),
                       "dense": None,
                       "sparse": {"f": ids, "f:linear": ids}}

        it = batches()
        first = next(it)
        state = trainer.init(jax.random.PRNGKey(0),
                             trainer.shard_batch(first))
        return trainer.fit(state, [first] + list(it),
                           retrace_budget=budget)

    state, metrics = make([16] * 6, budget=0)
    assert metrics is not None

    with pytest.raises(retrace.RetraceBudgetExceeded):
        make([16, 16, 24, 32], budget=0)
