"""Per-plane compiled-program contracts (analysis/contracts.py).

The registry must (a) PASS on every shipped plane's pull/push program on
an 8-device virtual mesh — the contracts describe reality — and (b)
CATCH a deliberately broken sharding annotation — the contracts have
teeth. The whole-train-step audit proves donation is honored and no
host transfer hides inside the jitted step.
"""

import pytest

from openembedding_tpu.analysis import contracts, programs
from openembedding_tpu.parallel.mesh import create_mesh

B, DIM = 1024, 16


@pytest.mark.parametrize(
    "plane",
    ["psum", "a2a", "a2a+cache",
     # the pipelined plane's per-table programs ARE the a2a programs
     # (pipelining lives in the Trainer schedule) — the fallback must
     # keep honoring the a2a exchange contract; slow lane like hash
     # (graftcheck + tests/test_pipelined.py cover it in tier-1)
     pytest.param("a2a+pipelined", marks=pytest.mark.slow)])
def test_pull_push_contracts_array(devices8, plane):
    mesh = create_mesh(2, 4, devices8)
    txt, params = programs.lower_pull(mesh, plane, batch=B, dim=DIM)
    summary = contracts.check_program(txt, plane, "pull", **params)
    if plane != "psum":
        assert summary["all-to-all"][0] >= 1
    else:
        assert "all-to-all" not in summary

    txt, params = programs.lower_push(mesh, plane, batch=B, dim=DIM)
    contracts.check_program(txt, plane, "push", **params)


@pytest.mark.slow
@pytest.mark.parametrize("plane", ["a2a", "a2a+cache"])
def test_pull_push_contracts_hash(devices8, plane):
    """Slow lane (tier-1 budget): the hash planes recompile everything
    from scratch (~25 s); tier-1 keeps the array matrix above and
    `tools/graftcheck` covers hash in CI."""
    mesh = create_mesh(2, 4, devices8)
    txt, params = programs.lower_pull(mesh, plane, batch=B, dim=DIM,
                                      use_hash=True)
    contracts.check_program(txt, plane, "pull", **params)
    txt, params = programs.lower_push(mesh, plane, batch=B, dim=DIM,
                                      use_hash=True)
    contracts.check_program(txt, plane, "push", **params)


def test_grouped_one_exchange_set_per_group(devices8):
    """THE grouped-plane claim: a 3-table collection compiles to exactly
    ONE exchange collective set (num_groups == 1), not one per table —
    the all-to-all inventory equals a single-table a2a program's, where
    the per-table loop would compile 3x that."""
    mesh = create_mesh(2, 4, devices8)
    a2a_ops = programs.count_exchange_a2a(mesh, "pull", batch=B, dim=DIM)
    txt, params = programs.lower_grouped_pull(mesh, tables=3, batch=B,
                                              dim=DIM, a2a_ops=a2a_ops)
    assert params["num_groups"] == 1 and params["num_tables"] == 3
    summary = contracts.check_program(txt, "a2a+grouped", "pull", **params)
    assert summary["all-to-all"][0] == a2a_ops
    assert summary["all-to-all"][0] < params["num_tables"] * a2a_ops


@pytest.mark.slow
def test_grouped_push_contract(devices8):
    """Push half of the launch-count claim (tier-1 keeps the pull half;
    `tools/graftcheck` audits both in CI)."""
    mesh = create_mesh(2, 4, devices8)
    push_ops = programs.count_exchange_a2a(mesh, "push", batch=B, dim=DIM)
    txt, params = programs.lower_grouped_push(mesh, tables=3, batch=B,
                                              dim=DIM, a2a_ops=push_ops)
    summary = contracts.check_program(txt, "a2a+grouped", "push", **params)
    assert summary["all-to-all"][0] == push_ops


def test_grouped_exchange_unit_counted_at_stream_size(devices8,
                                                      monkeypatch):
    """Calibration fix (ISSUE 7 satellite): the launch-count cap's
    per-exchange unit must be counted at the group's CONCATENATED
    stream size (num_tables * batch), not the per-table batch — XLA's
    all-to-all split count depends on the exchanged buffer size, so the
    per-table unit undercounts below the split threshold (batch 256
    compiled 8 grouped ops against a cap of 4, forcing CI to pin batch
    512). graftcheck/graftscope at batch 256 cover the compiled end;
    this pins the counting rule itself."""
    mesh = create_mesh(2, 4, devices8)
    asked = []

    def fake_count(mesh, program, **kw):
        asked.append(kw["batch"])
        return 8

    monkeypatch.setattr(programs, "count_exchange_a2a", fake_count)
    coll = programs._grouped_collection(mesh, tables=3, vocab=1 << 14,
                                        dim=16, use_hash=False)
    params = programs.grouped_params(mesh, coll, tuple(coll.specs),
                                     batch=256, dim=16, program="pull")
    assert asked == [3 * 256]
    assert params["a2a_ops_per_exchange"] == 8
    # an explicit a2a_ops skips the count entirely (test/CLI callers)
    asked.clear()
    programs.grouped_params(mesh, coll, tuple(coll.specs), batch=256,
                            dim=16, program="pull", a2a_ops=4)
    assert asked == []
    # MULTI-group plan: the unit counts at the WIDEST group's stream,
    # not the whole collection's — num_tables * batch would inflate the
    # unit past what any one group exchanges and slacken the cap
    from openembedding_tpu.embedding import (EmbeddingCollection,
                                             EmbeddingSpec)
    specs = tuple(
        EmbeddingSpec(name=f"m{i}", input_dim=(1 << 14) + 64 * i,
                      output_dim=dim, plane="a2a+grouped")
        for i, dim in enumerate((16, 16, 16, 64)))
    multi = EmbeddingCollection(specs, mesh)
    asked.clear()
    params = programs.grouped_params(mesh, multi, tuple(multi.specs),
                                     batch=256, dim=16, program="pull")
    assert params["num_groups"] == 2 and params["num_tables"] == 4
    assert asked == [3 * 256]           # widest group has 3 members


def test_grouped_broken_annotation_caught(devices8):
    """Replicating the grouped pull output re-gathers each table's rows
    in a separate buffer — each below the single-buffer bound, so the
    TOTAL-bytes budget is what must catch it."""
    mesh = create_mesh(2, 4, devices8)
    txt, params = programs.lower_grouped_pull(mesh, tables=3, batch=B,
                                              dim=DIM, a2a_ops=8,
                                              out_replicated=True)
    with pytest.raises(contracts.ContractViolation, match="total"):
        contracts.check_program(txt, "a2a+grouped", "pull", **params)


@pytest.mark.slow
def test_grouped_contracts_hash(devices8):
    """Hash groups carry an explicit (key..., tag) column stream; same
    launch-count contract. Slow lane like the other hash lowerings."""
    mesh = create_mesh(2, 4, devices8)
    a2a_ops = programs.count_exchange_a2a(mesh, "pull", batch=B, dim=DIM)
    txt, params = programs.lower_grouped_pull(mesh, tables=3, batch=B,
                                              dim=DIM, use_hash=True,
                                              a2a_ops=a2a_ops)
    contracts.check_program(txt, "a2a+grouped", "pull", **params)
    push_ops = programs.count_exchange_a2a(mesh, "push", batch=B, dim=DIM)
    txt, params = programs.lower_grouped_push(mesh, tables=3, batch=B,
                                              dim=DIM, use_hash=True,
                                              a2a_ops=push_ops)
    contracts.check_program(txt, "a2a+grouped", "push", **params)


def test_broken_sharding_annotation_caught(devices8):
    """Replicating the pull output (a one-line sharding regression)
    forces a global-batch gather — the contract must fail it."""
    mesh = create_mesh(2, 4, devices8)
    txt, params = programs.lower_pull(mesh, "a2a", batch=B, dim=DIM,
                                      out_replicated=True)
    with pytest.raises(contracts.ContractViolation, match="all-gather"):
        contracts.check_program(txt, "a2a", "pull", **params)


def test_train_step_contract(devices8):
    """The whole jitted step: donation honored (tables updated in
    place), no f64, no host transfer, and no table-sized copy.

    vocab/dim are sized so each table shard (vocab*dim*4/8 = 512 KiB)
    dwarfs every dense buffer — a copy at or above shard size can only
    be a table that lost its donation.
    """
    mesh = create_mesh(2, 4, devices8)
    vocab, dim = 1 << 16, 16
    txt, params = programs.lower_train_step(mesh, "a2a", vocab=vocab,
                                            dim=dim, batch=256)
    contracts.check_program(txt, "any", "step", **params)
    aliased = contracts.donated_params(txt)
    assert len(aliased) >= 4, aliased   # tables + slots + dense + opt
    table_shard_bytes = vocab * dim * 4 // mesh.size
    assert contracts.max_copy_bytes(txt) < table_shard_bytes


def test_step_with_record_stats_contains_callback(devices8):
    """Sanity for the host-transfer audit: when the observability gate
    is ON the pull program legitimately carries a host callback — the
    audit must SEE it (and the default program must not have one)."""
    from openembedding_tpu.utils import observability as obs
    mesh = create_mesh(2, 4, devices8)
    txt, _ = programs.lower_pull(mesh, "a2a", batch=B, dim=DIM)
    assert contracts.host_transfer_ops(txt) == []
    obs.set_evaluate_performance(True)
    try:
        txt_rec, _ = programs.lower_pull(mesh, "a2a", batch=B, dim=DIM)
    finally:
        obs.set_evaluate_performance(False)
    assert "host-callback" in contracts.host_transfer_ops(txt_rec)
    with pytest.raises(contracts.ContractViolation, match="host"):
        contracts.check_no_host_transfers(txt_rec)


def test_registry_unknown_key():
    with pytest.raises(KeyError, match="no contract registered"):
        contracts.check_program("", "nope", "pull", batch_slice=1, dim=1)
