"""Request-scoped serving traces (ISSUE 11 tentpole leg 1).

An IN-PROCESS 2-replica cluster (two registries + REST controllers in
this process) so every side of a request — client span, router
fan-out spans, HTTP handler spans, registry lookup spans — lands in
the same graftscope rings: one Perfetto trace, one trace id per
request, across client/router/server. Plus the keep-alive satellite
(connections opened once, reused across lookups) and the
failover-under-load interleaving schedule: a replica killed while the
client is parked mid-rotation; the lookup must not error and the
failover spans must carry the SAME trace id.
"""

import threading

import numpy as np
import pytest

import jax

from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
from openembedding_tpu import checkpoint as ckpt
from openembedding_tpu.analysis import scope
from openembedding_tpu.analysis.concurrency import (
    PointGate, clear_schedule, install_schedule)
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.serving import ha
from openembedding_tpu.serving.registry import ModelRegistry
from openembedding_tpu.serving.rest import ControllerServer

DIM = 4
VOCAB = 64
SIGN = "trace-model-1"


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory, devices8):
    path = str(tmp_path_factory.mktemp("trace") / "model")
    mesh = create_mesh(1, 1, jax.devices()[:1])
    spec = EmbeddingSpec(
        name="emb", input_dim=VOCAB, output_dim=DIM,
        initializer={"category": "constant", "value": 0.5})
    coll = EmbeddingCollection((spec,), mesh)
    states = coll.init(jax.random.PRNGKey(0))
    ckpt.save_checkpoint(path, coll, states, model_sign=SIGN)
    return path


def _boot(model_dir, *, shard_index=0, shard_count=1):
    mesh = create_mesh(1, 1, jax.devices()[:1])
    reg = ModelRegistry(mesh)
    reg.create_model(model_dir, model_sign=SIGN, block=True,
                     shard_index=shard_index, shard_count=shard_count)
    srv = ControllerServer(reg, port=0).start()
    return reg, srv


@pytest.fixture()
def tracing():
    scope.set_tracing(True)
    scope.reset()
    yield
    scope.set_tracing(None)


@pytest.fixture(autouse=True)
def _no_leftover_schedule():
    yield
    clear_schedule()


def _events_for(trace, tid):
    return [e for e in trace["traceEvents"]
            if e.get("ph") == "X" and e.get("args", {}).get("trace") == tid]


def _wait_events(tid, names, timeout=10.0):
    """Export-and-poll until every span kind in ``names`` has landed
    for ``tid``: the server handler closes its span a hair AFTER the
    client read the response bytes, so an immediate export can race it
    (same discipline as the /metrics second-scrape poll)."""
    import time as _time
    deadline = _time.time() + timeout
    while True:
        evs = _events_for(scope.export_chrome_trace(), tid)
        if names <= {e["name"] for e in evs} or _time.time() > deadline:
            return evs
        _time.sleep(0.05)


# --- one trace id across client / router / server ---------------------------

def test_trace_stitches_client_router_server(model_dir, tracing):
    """Acceptance criterion: one lookup against a 2-replica cluster ->
    ONE trace containing client, router fan-out, and server-side spans
    sharing one trace id, exported through export_chrome_trace."""
    _regA, srvA = _boot(model_dir)
    _regB, srvB = _boot(model_dir)
    router = ha.RoutingClient([f"127.0.0.1:{srvA.port}",
                               f"127.0.0.1:{srvB.port}"], timeout=15.0)
    try:
        with scope.trace_context() as tid:
            rows = router.lookup(SIGN, "emb", [1, 7, 63])
        np.testing.assert_allclose(rows, 0.5, rtol=1e-6)
        # client leg, router fan-out leg, HTTP server leg, registry leg
        want = {"client.lookup", "serving.rpc", "http", "serving.lookup"}
        evs = _wait_events(tid, want)
        assert want <= {e["name"] for e in evs}, evs
        rpc = [e for e in evs if e["name"] == "serving.rpc"]
        assert rpc[0]["args"]["outcome"] == "ok"
        assert rpc[0]["args"]["replica"].startswith("127.0.0.1:")
        http = [e for e in evs if e["name"] == "http"][0]
        assert http["args"]["route"] == "/models/lookup_bin"
        assert http["args"]["status"] == "200"
        # a SECOND lookup gets a DIFFERENT trace id (per-request scope)
        with scope.trace_context() as tid2:
            router.lookup(SIGN, "emb", [2])
        assert tid2 != tid
        assert _wait_events(tid2, {"client.lookup"})
    finally:
        router.close()
        srvA.stop()
        srvB.stop()


def test_trace_header_reaches_server_verbatim(model_dir, tracing):
    """The wire contract: the client's X-OE-Trace header value IS the
    id the server stamps on its spans (not a re-mint)."""
    _reg, srv = _boot(model_dir)
    router = ha.RoutingClient([f"127.0.0.1:{srv.port}"])
    try:
        with scope.trace_context("cafef00dcafef00d"):
            router.lookup(SIGN, "emb", [3])
        evs = _wait_events("cafef00dcafef00d",
                           {"http", "serving.lookup"})
        assert {"http", "serving.lookup"} <= {e["name"] for e in evs}
    finally:
        router.close()
        srv.stop()


# --- keep-alive satellite ----------------------------------------------------

def test_keepalive_reuses_one_connection(model_dir):
    """The keep-alive pin: N lookups from one thread open exactly ONE
    connection (per endpoint) — per-request TCP setup used to inflate
    every measured latency."""
    _reg, srv = _boot(model_dir)
    ep = f"127.0.0.1:{srv.port}"
    router = ha.RoutingClient([ep])
    before = scope.HISTOGRAMS.counter("serving_client_connections",
                                      endpoint=ep)
    try:
        for _ in range(5):
            rows = router.lookup(SIGN, "emb", [1, 2])
            assert rows.shape == (2, DIM)
        opened = scope.HISTOGRAMS.counter("serving_client_connections",
                                          endpoint=ep) - before
        assert opened == 1, f"expected 1 connection for 5 lookups, " \
                            f"opened {opened}"
    finally:
        router.close()
        srv.stop()


def test_keepalive_survives_server_side_idle_close(model_dir):
    """A stale pooled connection (server closed it) is retried on a
    fresh one instead of reading as a dead replica."""
    _reg, srv = _boot(model_dir)
    ep = f"127.0.0.1:{srv.port}"
    router = ha.RoutingClient([ep])
    try:
        router.lookup(SIGN, "emb", [1])
        # simulate the server-side idle close: kill the pooled socket
        conn = router._tls.conns[ep]
        conn.sock.close()
        rows = router.lookup(SIGN, "emb", [5])     # must NOT raise
        np.testing.assert_allclose(rows, 0.5, rtol=1e-6)
    finally:
        router.close()
        srv.stop()


# --- failover-under-load interleaving schedule -------------------------------

def test_failover_mid_lookup_keeps_trace_id(model_dir, tracing,
                                            monkeypatch):
    """The failover-under-load lane: the client thread is parked at the
    rotation sync point, the replica it is ABOUT to query is stopped,
    then released — the lookup must ride over to the live replica with
    NO error, and the failover + success spans must carry the same
    trace id (the trace shows the reroute)."""
    _regA, srvA = _boot(model_dir)
    _regB, srvB = _boot(model_dir)
    router = ha.RoutingClient([f"127.0.0.1:{srvA.port}",
                               f"127.0.0.1:{srvB.port}"], timeout=15.0)
    # deterministic rotation: always start at replica A
    monkeypatch.setattr(ha.random, "randrange", lambda n: 0)
    out, errs = [], []

    def storm():
        try:
            with scope.trace_context() as tid:
                out.append((tid, router.lookup(SIGN, "emb", [1, 7])))
        except Exception as e:  # noqa: BLE001 — the assertion below
            errs.append(e)

    try:
        # warmup: pooled connection to A established (the kill must
        # also exercise the stale-conn path, like a real mid-storm kill)
        router.lookup(SIGN, "emb", [0])
        router.close()

        gate = PointGate(["storm/routing.attempt"], timeout=30)
        install_schedule(gate)
        t = threading.Thread(target=storm, name="storm")
        t.start()
        assert gate.wait_arrival("storm/routing.attempt")
        # the client is parked about to query replica A: kill A now
        srvA.stop()
        gate.open("storm/routing.attempt")
        t.join(60)
        clear_schedule()
        assert not t.is_alive()
        assert not errs, f"reads must never error while a replica " \
                         f"lives: {errs}"
        tid, rows = out[0]
        np.testing.assert_allclose(rows, 0.5, rtol=1e-6)

        evs = _wait_events(tid, {"serving.rpc", "http",
                                 "serving.lookup"})
        rpc = [e for e in evs if e["name"] == "serving.rpc"]
        outcomes = [e["args"]["outcome"] for e in rpc]
        assert outcomes == ["failover", "ok_failover"], outcomes
        assert rpc[0]["args"]["replica"] == f"127.0.0.1:{srvA.port}"
        assert rpc[1]["args"]["replica"] == f"127.0.0.1:{srvB.port}"
        # the SERVER-side spans of the surviving replica share the id
        assert {"http", "serving.lookup"} <= {e["name"] for e in evs}
        assert scope.HISTOGRAMS.counter("serving_request_failovers") >= 1
    finally:
        clear_schedule()
        router.close()
        srvB.stop()
        srvA.stop()


# --- sharded fan-out ---------------------------------------------------------

def test_sharded_fanout_shares_one_trace(model_dir, tracing):
    """A ShardedRoutingClient lookup spanning both shard groups: ONE
    trace id across the sharded client span, each group's rpc + server
    spans, and the fan-out width counter."""
    _regA, srvA = _boot(model_dir, shard_index=0, shard_count=2)
    _regB, srvB = _boot(model_dir, shard_index=1, shard_count=2)
    router = ha.ShardedRoutingClient(
        [[f"127.0.0.1:{srvA.port}"], [f"127.0.0.1:{srvB.port}"]],
        timeout=15.0)
    fan_before = scope.HISTOGRAMS.counter("serving_request_fanout")
    try:
        with scope.trace_context() as tid:
            rows = router.lookup(SIGN, "emb", [0, 1, 2, 3])
        np.testing.assert_allclose(rows, 0.5, rtol=1e-6)
        assert scope.HISTOGRAMS.counter("serving_request_fanout") \
            - fan_before == 2
        evs = _wait_events(tid, {"serving.rpc", "serving.lookup"})
        protos = {e["args"].get("proto") for e in evs
                  if e["name"] == "client.lookup"}
        assert protos == {"sharded", "bin"}     # outer span + both legs
        # one rpc + one server-side lookup PER shard group, same id
        assert len([e for e in evs if e["name"] == "serving.rpc"]) == 2
        assert len([e for e in evs
                    if e["name"] == "serving.lookup"]) == 2
    finally:
        router.close()
        srvA.stop()
        srvB.stop()


def test_serving_lookup_size_histogram(model_dir):
    """Satellite: ServingModel.lookup feeds the per-variable
    lookup-size distribution — on /metrics as _bucket series."""
    import urllib.request
    _reg, srv = _boot(model_dir)
    router = ha.RoutingClient([f"127.0.0.1:{srv.port}"])
    before = scope.HISTOGRAMS.count("serving_lookup_rows", table="emb")
    try:
        router.lookup(SIGN, "emb", list(range(8)))
        assert scope.HISTOGRAMS.count("serving_lookup_rows",
                                      table="emb") == before + 1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as r:
            body = r.read().decode()
        assert 'oe_serving_lookup_rows_bucket{table="emb",' in body
        assert "oe_serving_lookup_requests_total" in body
    finally:
        router.close()
        srv.stop()
