"""Hot-row replica cache ("a2a+cache" plane): exact equivalence + policy.

The cache is a pure optimization — the acceptance bar is that the cached
plane's parameters stay allclose to the uncached "a2a" plane on identical
streams (Zipf and uniform, mod and div layouts, array and hash tables),
with the admission/refresh machinery (frequency sketch, static-shape
batch partition) unit-tested on its own.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import EmbeddingVariableMeta, make_optimizer
from openembedding_tpu import hash_table as ht
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.parallel import sharded_table as st
from openembedding_tpu.parallel import sharded_hash as sh
from openembedding_tpu.parallel import hot_cache as hot
from openembedding_tpu.utils import observability as obs

VOCAB, DIM, B, K = 64, 4, 16, 16
OPT = {"category": "adagrad", "learning_rate": 0.1}
INIT = {"category": "constant", "value": 0.25}


def _streams(rng, n):
    """(zipf, uniform) id streams over [0, VOCAB) — the skew the cache
    exists for, and the skew-free regression control."""
    zipf = np.minimum(rng.zipf(1.3, size=(n, B)) - 1, VOCAB - 1)
    uni = rng.randint(0, VOCAB, size=(n, B))
    return zipf.astype(np.int32), uni.astype(np.int32)


def _assert_tables_close(a, b):
    np.testing.assert_allclose(np.asarray(a.weights), np.asarray(b.weights),
                               rtol=1e-5, atol=1e-6)
    for name in a.slots:
        np.testing.assert_allclose(np.asarray(a.slots[name]),
                                   np.asarray(b.slots[name]),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("layout", ["mod", "div"])
@pytest.mark.parametrize("stream", ["zipf", "uniform"])
def test_array_cached_plane_matches_a2a(devices8, layout, stream):
    """Same seeds -> allclose params after M steps, across a mid-run
    admission refresh (array tables)."""
    mesh = create_mesh(2, 4, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=VOCAB)
    opt = make_optimizer(OPT)
    spec_a = st.make_sharding_spec(meta, mesh, layout=layout, plane="a2a")
    spec_c = st.make_sharding_spec(meta, mesh, layout=layout,
                                   plane="a2a+cache", cache_k=K)
    sa = st.create_sharded_table(meta, opt, INIT, mesh=mesh, spec=spec_a)
    sc = st.create_sharded_table(meta, opt, INIT, mesh=mesh, spec=spec_c)
    assert isinstance(sc, hot.CachedState)

    rng = np.random.RandomState(0)
    zipf, uni = _streams(rng, 8)
    ids = zipf if stream == "zipf" else uni
    grads = rng.randn(8, B, DIM).astype(np.float32)
    mgr = hot.HotCacheManager(mesh=mesh, spec=spec_c, k=K, refresh_every=3)

    for s in range(8):
        idx, g = jnp.asarray(ids[s]), jnp.asarray(grads[s])
        ra = st.pull_sharded(sa, idx, mesh=mesh, spec=spec_a)
        rc = st.pull_sharded(sc, idx, mesh=mesh, spec=spec_c)
        np.testing.assert_allclose(np.asarray(ra), np.asarray(rc),
                                   rtol=1e-5, atol=1e-6)
        sa = st.apply_gradients_sharded(sa, opt, idx, g, mesh=mesh,
                                        spec=spec_a)
        sc = st.apply_gradients_sharded(sc, opt, idx, g, mesh=mesh,
                                        spec=spec_c)
        mgr.observe(ids[s])
        if mgr.due:
            sc = mgr.refresh(sc)
    assert mgr.refreshes >= 2
    _assert_tables_close(sa, sc.table)
    # the replica itself must mirror the authoritative rows it covers
    ck = np.asarray(sc.cache.keys)
    live = ck >= 0
    if live.any():
        want = np.asarray(st.pull_sharded(
            sa, jnp.asarray(ck[live]), mesh=mesh, spec=spec_a,
            batch_sharded=False))
        np.testing.assert_allclose(np.asarray(sc.cache.rows)[live], want,
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("key_width", [32, 64])
def test_hash_cached_plane_matches_a2a(devices8, key_width):
    """Hash tables (int32 and wide pair keys): allclose across refresh."""
    mesh = create_mesh(2, 4, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=2**63)
    opt = make_optimizer(OPT)
    spec_a = sh.make_hash_sharding_spec(mesh, 1024, plane="a2a",
                                        key_width=key_width)
    spec_c = sh.make_hash_sharding_spec(mesh, 1024, plane="a2a+cache",
                                        key_width=key_width, cache_k=K)
    sa = sh.create_sharded_hash_table(meta, opt, mesh=mesh, spec=spec_a)
    sc = sh.create_sharded_hash_table(meta, opt, mesh=mesh, spec=spec_c)

    rng = np.random.RandomState(1)
    keys64 = (np.minimum(rng.zipf(1.3, size=(8, B)), 500) * 7919
              ).astype(np.int64)
    grads = rng.randn(8, B, DIM).astype(np.float32)

    def to_idx(a):
        if key_width == 64:
            return jnp.asarray(ht.split64(a))
        return jnp.asarray(a.astype(np.int32))

    mgr = hot.HotCacheManager(mesh=mesh, spec=spec_c, k=K, refresh_every=3)
    for s in range(8):
        idx, g = to_idx(keys64[s]), jnp.asarray(grads[s])
        ra = sh.pull_sharded(sa, idx, INIT, mesh=mesh, spec=spec_a)
        rc = sh.pull_sharded(sc, idx, INIT, mesh=mesh, spec=spec_c)
        np.testing.assert_allclose(np.asarray(ra), np.asarray(rc),
                                   rtol=1e-5, atol=1e-6)
        sa = sh.apply_gradients_sharded(sa, opt, INIT, idx, g, mesh=mesh,
                                        spec=spec_a)
        sc = sh.apply_gradients_sharded(sc, opt, INIT, idx, g, mesh=mesh,
                                        spec=spec_c)
        mgr.observe(keys64[s])
        if mgr.due:
            sc = mgr.refresh(sc)
    assert mgr.refreshes >= 2
    # all seen keys must read back identically on both planes
    seen = np.unique(keys64.ravel())
    ra = sh.pull_sharded(sa, to_idx(seen), None, mesh=mesh, spec=spec_a,
                         batch_sharded=False)
    rc = sh.pull_sharded(sc, to_idx(seen), None, mesh=mesh, spec=spec_c,
                         batch_sharded=False)
    np.testing.assert_allclose(np.asarray(ra), np.asarray(rc),
                               rtol=1e-5, atol=1e-6)


def test_cache_counters_zipf_hits_uniform_exact(devices8):
    """observability exposes cache_hits / cache_misses / ici_bytes_saved;
    the Zipf stream reports > 0 hits; the uniform stream stays numerically
    exact (the regression criterion — hits are fine, wrong rows are not).
    """
    mesh = create_mesh(2, 4, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=VOCAB)
    opt = make_optimizer(OPT)
    spec_c = st.make_sharding_spec(meta, mesh, plane="a2a+cache", cache_k=K)
    sc = st.create_sharded_table(meta, opt, INIT, mesh=mesh, spec=spec_c)

    rng = np.random.RandomState(2)
    zipf, uni = _streams(rng, 4)
    mgr = hot.HotCacheManager(mesh=mesh, spec=spec_c, k=K, refresh_every=1)
    for s in range(3):
        mgr.observe(zipf[s])
    sc = mgr.refresh(sc)

    obs.GLOBAL.reset()
    obs.set_evaluate_performance(True)
    try:
        _ = st.pull_sharded(sc, jnp.asarray(zipf[3]), mesh=mesh,
                            spec=spec_c)
        sc = st.apply_gradients_sharded(
            sc, opt, jnp.asarray(zipf[3]),
            jnp.ones((B, DIM), jnp.float32), mesh=mesh, spec=spec_c)
        jax.effects_barrier()
        stats = obs.cache_stats()
    finally:
        obs.set_evaluate_performance(False)
    assert stats["cache_hits"] > 0
    assert stats["ici_bytes_saved"] > 0
    assert stats["cache_hits"] + stats["cache_misses"] == 2 * B
    assert 0.0 < stats["cache_hit_rate"] <= 1.0

    # uniform stream: rows must match the uncached plane exactly even when
    # some uniform ids happen to hit the cached set
    spec_a = st.make_sharding_spec(meta, mesh, plane="a2a")
    sa = st.create_sharded_table(meta, opt, INIT, mesh=mesh, spec=spec_a)
    # bring the uncached twin to the same table state
    sa = st.apply_gradients_sharded(
        sa, opt, jnp.asarray(zipf[3]), jnp.ones((B, DIM), jnp.float32),
        mesh=mesh, spec=spec_a)
    ra = st.pull_sharded(sa, jnp.asarray(uni[0]), mesh=mesh, spec=spec_a)
    rc = st.pull_sharded(sc, jnp.asarray(uni[0]), mesh=mesh, spec=spec_c)
    np.testing.assert_allclose(np.asarray(ra), np.asarray(rc),
                               rtol=1e-5, atol=1e-6)


def test_freq_sketch_decay_and_admission():
    """Decayed counts rank recent-hot over stale-hot; pruning bounds size."""
    sk = hot.FreqSketch(decay=0.5, prune_below=0.4)
    sk.update(np.array([1, 1, 1, 1, 2, 2, 3]))
    assert sk.topk(2).tolist() == [1, 2]
    # decay twice: old mass shrinks 4x; key 3 (count 1 -> 0.25) prunes out
    sk.decay()
    sk.decay()
    assert 3 not in set(sk.topk(10).tolist())
    # a newly-hot key overtakes the decayed old head
    sk.update(np.array([9] * 5))
    assert sk.topk(1).tolist() == [9]
    # ties break deterministically (by key) so refreshes are stable
    sk2 = hot.FreqSketch()
    sk2.update(np.array([7, 5, 7, 5]))
    assert sk2.topk(2).tolist() == [5, 7]


def test_freq_sketch_max_entries_bound():
    sk = hot.FreqSketch(decay=1.0, max_entries=100)
    sk.update(np.repeat(np.arange(50), 3))        # the hot half
    sk.update(np.arange(1000, 1101))              # cold tail trips the cap
    assert len(sk) <= 100
    assert set(sk.topk(50).tolist()) == set(range(50))


def test_lookup_partition_static_shapes(devices8):
    """The cached/uncached batch partition: hit mask + sentinel masking
    reconstruct the batch exactly, narrow and wide, in-graph."""
    # narrow: sorted keys with pad sentinels
    keys = np.full(8, np.iinfo(np.int32).min, np.int32)
    keys[:4] = [3, 7, 11, 40]
    keys.sort()
    q = jnp.asarray(np.array([7, 5, 40, -1, 3, 63], np.int32))
    valid = (q >= 0) & (q < VOCAB)
    pos, hit = hot.lookup(jnp.asarray(keys), q, valid)
    np.testing.assert_array_equal(np.asarray(hit),
                                  [True, False, True, False, True, False])
    got = np.asarray(jnp.asarray(keys)[np.asarray(pos)])[np.asarray(hit)]
    np.testing.assert_array_equal(got, [7, 40, 3])
    resid = hot.mask_hits(q, hit, -1)
    np.testing.assert_array_equal(np.asarray(resid), [-1, 5, -1, -1, -1, 63])

    # wide: unsigned-u64 sort order, [n, 2] pair queries
    cand = np.array([2**40 + 5, -3 & (2**64 - 1), 17, 2**33], np.uint64)
    keys64 = np.sort(cand).astype(np.int64)
    pad = np.int64(np.uint64(0x8000000080000000))
    full = np.concatenate([keys64, [pad] * 4])
    full = full[np.argsort(full.view(np.uint64))]
    wkeys = jnp.asarray(ht.split64(full))
    queries = np.array([17, 99, 2**40 + 5, -3], np.int64)
    wq = jnp.asarray(ht.split64(queries))
    wvalid = jnp.asarray(np.ones(4, bool))
    _, whit = hot.lookup(wkeys, wq, wvalid)
    np.testing.assert_array_equal(np.asarray(whit),
                                  [True, False, True, True])
    wres = hot.mask_hits(wq, whit, ht.empty_key(np.int32))
    assert np.asarray(wres)[1, 1] != ht.empty_key(np.int32)   # miss kept
    assert (np.asarray(wres)[[0, 2, 3], 1]
            == ht.empty_key(np.int32)).all()                  # hits masked


def test_build_cache_rejects_absent_hash_keys(devices8):
    """Admission must drop candidates not present in the hash table — a
    replica row would otherwise shadow the deterministic-init contract."""
    mesh = create_mesh(2, 4, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=2**63)
    opt = make_optimizer(OPT)
    spec_c = sh.make_hash_sharding_spec(mesh, 1024, plane="a2a+cache",
                                        key_width=32, cache_k=8)
    sc = sh.create_sharded_hash_table(meta, opt, mesh=mesh, spec=spec_c)
    present = np.array([5, 9, 13], np.int64)
    sc = sh.apply_gradients_sharded(
        sc, opt, INIT, jnp.asarray(present.astype(np.int32)),
        jnp.ones((3, DIM), jnp.float32), mesh=mesh, spec=spec_c,
        batch_sharded=False)
    cache = hot.build_cache(sc.table, np.array([5, 9, 777, 888], np.int64),
                            8, mesh=mesh, spec=spec_c)
    live = np.asarray(cache.keys) != np.iinfo(np.int32).min
    assert set(np.asarray(cache.keys)[live].tolist()) == {5, 9}


def test_hot_cache_tests_run_in_tier1_lane():
    """Tier-1 marker check: this module must ride the standard
    ``pytest -m 'not slow'`` lane — no module/class-level slow marks."""
    import sys
    mod = sys.modules[__name__]
    marks = getattr(mod, "pytestmark", [])
    assert not any(getattr(m, "name", "") == "slow" for m in marks)
    for obj in vars(mod).values():
        own = getattr(obj, "pytestmark", None)
        if own:
            assert not any(getattr(m, "name", "") == "slow" for m in own), \
                f"{obj} is marked slow — hot-cache coverage is tier-1"


def test_freq_sketch_dense_mode_matches_dict():
    """The vectorized dense backing (bounded vocabs) ranks identically to
    the dict sketch, including decay and deterministic tie order."""
    dense = hot.FreqSketch(decay=0.5, dense_vocab=100)
    sparse = hot.FreqSketch(decay=0.5)
    rng = np.random.RandomState(3)
    for _ in range(5):
        ks = rng.randint(0, 100, 64)
        dense.update(ks)
        sparse.update(ks)
    assert dense.topk(10).tolist() == sparse.topk(10).tolist()
    dense.decay()
    sparse.decay()
    assert dense.topk(10).tolist() == sparse.topk(10).tolist()
    # zero-count keys never qualify even when k exceeds the live set
    tiny = hot.FreqSketch(dense_vocab=8)
    tiny.update(np.array([3, 3, 5]))
    assert set(tiny.topk(8).tolist()) == {3, 5}


def test_cached_plane_checkpoint_roundtrip(devices8, tmp_path):
    """Checkpoint dumps only the authoritative table (the replica is
    derived state); load re-attaches an all-pad replica that the next
    refresh re-populates."""
    from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
    from openembedding_tpu import checkpoint as ckpt
    mesh = create_mesh(2, 4, devices8)
    specs = (EmbeddingSpec(name="v", input_dim=VOCAB, output_dim=DIM,
                           plane="a2a+cache", cache_k=K, optimizer=OPT,
                           initializer=INIT),)
    coll = EmbeddingCollection(specs, mesh)
    states = coll.init(jax.random.PRNGKey(0))
    assert isinstance(states["v"], hot.CachedState)
    sspec = coll.sharding_spec("v")
    idx = jnp.arange(16, dtype=jnp.int32)
    states["v"] = st.apply_gradients_sharded(
        states["v"], coll.optimizer("v"), idx,
        jnp.ones((16, DIM), jnp.float32), mesh=mesh, spec=sspec)
    mgr = coll.make_hot_cache_manager("v")
    mgr.observe(np.arange(16, dtype=np.int32))
    states["v"] = mgr.refresh(states["v"])

    ckpt.save_checkpoint(str(tmp_path / "c"), coll, states)
    loaded = ckpt.load_checkpoint(str(tmp_path / "c"), coll)
    assert isinstance(loaded["v"], hot.CachedState)
    np.testing.assert_allclose(np.asarray(loaded["v"].table.weights),
                               np.asarray(states["v"].table.weights),
                               rtol=1e-6, atol=1e-6)
    assert (np.asarray(loaded["v"].cache.keys)
            == np.iinfo(np.int32).min).all()
    # and the reloaded state trains on the cached plane unchanged
    out = st.pull_sharded(loaded["v"], idx, mesh=mesh, spec=sspec,
                          batch_sharded=False)
    want = st.pull_sharded(states["v"], idx, mesh=mesh, spec=sspec,
                           batch_sharded=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_trainer_wires_hot_cache(devices8):
    """The Trainer auto-builds managers for a2a+cache variables, feeds the
    sketch every step, and refreshes in place — the whole wiring the
    plane-level tests drive by hand."""
    import optax
    from openembedding_tpu import EmbeddingCollection, Trainer
    from openembedding_tpu.models import deepctr
    mesh = create_mesh(2, 4, devices8)
    feats = ("u",)
    specs = deepctr.make_feature_specs(
        feats, VOCAB, DIM, plane="a2a+cache", cache_k=K,
        cache_refresh_every=2, optimizer=OPT)
    coll = EmbeddingCollection(specs, mesh)
    tr = Trainer(deepctr.build_model("lr", feats), coll, optax.sgd(0.1))
    rng = np.random.RandomState(4)
    zipf, _ = _streams(rng, 5)
    batches = [{"label": (rng.rand(B) > 0.5).astype(np.float32),
                "dense": rng.randn(B, 3).astype(np.float32),
                "sparse": {"u": z, "u:linear": z}} for z in zipf]
    state = tr.init(jax.random.PRNGKey(0), tr.shard_batch(batches[0]))
    for b in batches:
        state, _m = tr.train_step(state, b)
    assert set(tr._hot) == {"u", "u:linear"}
    assert all(m.refreshes >= 2 for m in tr._hot.values())
    for name in tr._hot:
        cached = state.emb[name]
        assert isinstance(cached, hot.CachedState)
        live = np.asarray(cached.cache.keys) >= 0
        assert live.any(), "refresh admitted nothing from the zipf stream"


def test_export_dense_unwraps_cached_plane(devices8):
    """export_dense must read through the replica wrapper (the derived
    cache is not part of the dense export)."""
    from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
    from openembedding_tpu import checkpoint as ckpt
    mesh = create_mesh(2, 4, devices8)
    specs = (EmbeddingSpec(name="v", input_dim=VOCAB, output_dim=DIM,
                           plane="a2a+cache", cache_k=K, optimizer=OPT,
                           initializer=INIT),)
    coll = EmbeddingCollection(specs, mesh)
    states = coll.init(jax.random.PRNGKey(0))
    dense = ckpt.export_dense(coll, states)
    assert dense["v"].shape == (VOCAB, DIM)
    np.testing.assert_allclose(dense["v"], 0.25, rtol=1e-6)


def test_freq_sketch_sampling_covers_structured_layouts():
    """Stride sampling must not alias with a [B, F] batch's feature
    period: over a refresh window every feature column gets observed."""
    sk = hot.FreqSketch(decay=1.0, dense_vocab=64)
    cap = hot.FreqSketch.SAMPLE_CAP
    F = 26
    B = (cap // F) + 200          # big enough that sampling kicks in
    batch = np.tile(np.arange(F, dtype=np.int64)[None, :], (B, 1))
    for _ in range(F):            # one refresh window of updates
        sk.update(batch)
    seen = set(sk.topk(F).tolist())
    assert seen == set(range(F)), sorted(set(range(F)) - seen)
