"""graftrace runtime plane: TracedLock lock-order-cycle detection,
contention/hold counters, opt-in gating, and the observability surface.

All synthetic — no jax, no mesh. The deterministic interleaving lane
(test_interleaving.py) drives the REAL instrumented objects.
"""

import threading

import pytest

from openembedding_tpu.analysis import concurrency
from openembedding_tpu.utils import observability


@pytest.fixture()
def traced():
    concurrency.set_trace_locks(True)
    concurrency.reset_runtime()
    yield
    concurrency.set_trace_locks(None)
    concurrency.reset_runtime()


def test_make_lock_is_plain_when_disabled():
    concurrency.set_trace_locks(False)
    try:
        lk = concurrency.make_lock("x")
        assert not isinstance(lk, concurrency.TracedLock)
        rlk = concurrency.make_rlock("y")
        assert not isinstance(rlk, concurrency.TracedLock)
        # nothing recorded: production paths pay nothing
        assert concurrency.lock_stats() == {}
    finally:
        concurrency.set_trace_locks(None)


def test_env_var_arms_tracing(monkeypatch):
    concurrency.set_trace_locks(None)
    monkeypatch.setenv("OE_REPORT_TRACE_LOCKS", "1")
    assert concurrency.trace_locks_enabled()
    assert isinstance(concurrency.make_lock("z"), concurrency.TracedLock)
    monkeypatch.setenv("OE_REPORT_TRACE_LOCKS", "0")
    assert not concurrency.trace_locks_enabled()


def test_lock_order_cycle_is_reported(traced):
    a = concurrency.TracedLock("A")
    b = concurrency.TracedLock("B")
    # the A->B order, then the inverse — no two threads needed: a
    # POTENTIAL deadlock is an order inversion, reported even though
    # this schedule never wedged
    with a:
        with b:
            pass
    assert concurrency.potential_deadlocks() == []
    with b:
        with a:
            pass
    reports = concurrency.potential_deadlocks()
    assert len(reports) == 1 and "A" in reports[0] and "B" in reports[0]
    # the same inversion again does not spam a second report
    with b:
        with a:
            pass
    assert len(concurrency.potential_deadlocks()) == 1


def test_consistent_order_is_silent(traced):
    a = concurrency.TracedLock("A")
    b = concurrency.TracedLock("B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert concurrency.potential_deadlocks() == []


def test_contention_and_hold_counters(traced):
    lk = concurrency.TracedLock("hot")
    held = threading.Event()
    release = threading.Event()

    def holder():
        with lk:
            held.set()
            release.wait(10)

    t = threading.Thread(target=holder, name="holder")
    t.start()
    assert held.wait(10)
    # guaranteed contended: the holder provably has the lock right now
    releaser = threading.Timer(0.05, release.set)
    releaser.start()
    with lk:
        pass
    t.join(10)
    releaser.join()
    st = concurrency.lock_stats()["hot"]
    assert st["acquires"] == 2
    assert st["contended"] == 1
    assert st["wait_s"] > 0
    assert st["hold_s"] > 0


def test_rlock_reentrancy_counts_outermost_only(traced):
    lk = concurrency.TracedRLock("re")
    with lk:
        with lk:
            assert lk._depth_get() == 2
    st = concurrency.lock_stats()["re"]
    assert st["acquires"] == 1
    # fully released: another thread can take (and release) it
    ok = []

    def grab():
        ok.append(lk.acquire(timeout=1))
        if ok[0]:
            lk.release()

    t = threading.Thread(target=grab)
    t.start()
    t.join(10)
    assert ok == [True]
    assert concurrency.lock_stats()["re"]["acquires"] == 2


def test_rlock_locked_is_portable(traced):
    # threading.RLock has no .locked() before Python 3.14 — the traced
    # wrapper must still answer (offload._book advertises it)
    lk = concurrency.TracedRLock("probe")
    assert lk.locked() is False
    with lk:
        assert lk.locked() is True
        with lk:
            assert lk.locked() is True
    assert lk.locked() is False
    held = threading.Event()
    release = threading.Event()

    def holder():
        with lk:
            held.set()
            release.wait(10)

    t = threading.Thread(target=holder)
    t.start()
    assert held.wait(10)
    assert lk.locked() is True          # held by ANOTHER thread
    release.set()
    t.join(10)
    assert lk.locked() is False


def test_observability_surface(traced):
    with concurrency.TracedLock("obs.demo"):
        pass
    stats = observability.lock_stats()
    assert stats["obs.demo"]["acquires"] == 1
    text = observability.prometheus_text()
    assert "oe_lock_obs_demo_acquires_total 1" in text
    assert "oe_lock_obs_demo_contended_total 0" in text
    assert concurrency.potential_deadlocks() == \
        observability.potential_deadlocks()


def test_cross_thread_release_closes_acquirer_entry(traced):
    # threading.Lock may legally be released by a thread other than the
    # acquirer (handoff/signaling patterns). The acquirer's held-stack
    # entry must be closed anyway — left stale it would fabricate an
    # order edge for every lock that thread acquires next
    h = concurrency.TracedLock("H")
    a = concurrency.TracedLock("A")
    h.acquire()
    t = threading.Thread(target=h.release)
    t.start()
    t.join(10)
    assert concurrency.lock_stats()["H"]["hold_s"] > 0
    with a:                           # would record a phantom H->A edge
        pass                          # if the stale entry survived
    assert "H" not in concurrency._ORDER
    assert concurrency.potential_deadlocks() == []


def test_reset_runtime_clears_everything(traced):
    a, b = concurrency.TracedLock("A"), concurrency.TracedLock("B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert concurrency.potential_deadlocks()
    concurrency.reset_runtime()
    assert concurrency.potential_deadlocks() == []
    assert concurrency.lock_stats() == {}
