"""Serving HA: replica daemons, failover routing, SIGKILL chaos, restore.

Mirrors the reference's HA test (entry/c_api_ha_test.cpp:150-210): N replica
processes serve one model; replicas are SIGKILLed mid-lookup; the routing
client must keep answering while >= 1 replica lives; killed replicas respawn
with --peers and restore the catalog from a living replica.
"""

import os
import signal
import socket
import time

import numpy as np
import pytest

import jax

from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
from openembedding_tpu import checkpoint as ckpt
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.serving import ha

DIM = 4
SIGN = "ha-model-1"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory, devices8):
    """A small checkpoint with recognizable values."""
    path = str(tmp_path_factory.mktemp("ha") / "model")
    mesh = create_mesh(1, 1, jax.devices()[:1])
    spec = EmbeddingSpec(
        name="emb", input_dim=64, output_dim=DIM,
        initializer={"category": "constant", "value": 0.5})
    coll = EmbeddingCollection((spec,), mesh)
    states = coll.init(jax.random.PRNGKey(0))
    ckpt.save_checkpoint(path, coll, states, model_sign=SIGN)
    return path


def _cleanup(procs):
    for p in procs.values():
        if p and p.poll() is None:
            p.kill()


def _assert_lookup(router, deadline_s: float = 60.0):
    """Lookup with a retry deadline: under CPU starvation (full-suite runs)
    a LIVE replica can miss the router timeout — the reference's serving
    test retries at 500 ms for the same reason (c_api_test.h:117-121)."""
    deadline = time.time() + deadline_s
    while True:
        try:
            rows = router.lookup(SIGN, "emb", [1, 7, 63])
            break
        except ConnectionError as e:
            # retry ONLY timeout-flavored exhaustion: a live-but-starved
            # replica times out, while a failover-rotation regression shows
            # up as "Connection refused" from the dead one — that must
            # still fail the chaos invariant immediately
            if "timed out" not in str(e) or time.time() >= deadline:
                raise
            time.sleep(0.5)
    assert rows.shape == (3, DIM)
    np.testing.assert_allclose(rows, 0.5, rtol=1e-6)


def test_restore_from_peer_and_chaos(model_dir):
    ports = [_free_port() for _ in range(3)]
    eps = [f"127.0.0.1:{p}" for p in ports]
    procs = {}
    try:
        # boot replica 0 with the model; 1 and 2 restore from peers —
        # the reference's `server --restore` replacement-node path
        procs[0] = ha.spawn_replica(ports[0], load=[f"{SIGN}={model_dir}"])
        assert ha.wait_ready(eps[0], sign=SIGN), _tail(procs[0])
        for i in (1, 2):
            procs[i] = ha.spawn_replica(ports[i], peers=[eps[0]])
            assert ha.wait_ready(eps[i], sign=SIGN), _tail(procs[i])

        router = ha.RoutingClient(eps, timeout=15.0)
        nodes = router.nodes()
        assert all(n["alive"] for n in nodes)
        assert all(SIGN in n["models"] for n in nodes)
        _assert_lookup(router)

        # GET /cluster through one replica reflects peer liveness
        import urllib.request, json
        with urllib.request.urlopen(
                f"http://{eps[1]}/cluster", timeout=5) as r:
            cluster = json.loads(r.read())
        assert {c["endpoint"] for c in cluster} == {eps[0]}
        assert all(c["alive"] for c in cluster)

        # chaos round 1: SIGKILL one replica mid-service
        procs[1].send_signal(signal.SIGKILL)
        procs[1].wait()
        for _ in range(5):
            _assert_lookup(router)  # service continues on live replicas
        nodes = router.nodes()
        assert sum(n["alive"] for n in nodes) == 2

        # chaos round 2: kill a second — one survivor still serves
        procs[2].send_signal(signal.SIGKILL)
        procs[2].wait()
        for _ in range(5):
            _assert_lookup(router)

        # respawn both with --peers pointing at the OTHER endpoints (a
        # replica must not list itself): catalog restored from the living
        # replica, service returns to full strength
        for i in (1, 2):
            others = [e for j, e in enumerate(eps) if j != i]
            procs[i] = ha.spawn_replica(ports[i], peers=others)
            assert ha.wait_ready(eps[i], sign=SIGN), _tail(procs[i])
        nodes = router.nodes()
        assert all(n["alive"] for n in nodes)
        _assert_lookup(router)

        # kill the ORIGINAL source replica: restored replicas keep serving
        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait()
        for _ in range(5):
            _assert_lookup(router)
    finally:
        _cleanup(procs)


def test_router_propagates_http_errors(model_dir):
    """A 404 (unknown model) must surface as HTTPError, not as a dead
    cluster — HTTPError subclasses URLError and must be caught first."""
    import urllib.error
    port = _free_port()
    proc = ha.spawn_replica(port, load=[f"{SIGN}={model_dir}"])
    try:
        ep = f"127.0.0.1:{port}"
        assert ha.wait_ready(ep, sign=SIGN), _tail(proc)
        router = ha.RoutingClient([ep], timeout=10.0)
        with pytest.raises(urllib.error.HTTPError):
            router.lookup("no-such-model", "emb", [0])
    finally:
        proc.kill()


def test_router_raises_when_all_dead(model_dir):
    router = ha.RoutingClient([f"127.0.0.1:{_free_port()}"], timeout=2.0)
    with pytest.raises(ConnectionError, match="no live replica"):
        router.lookup(SIGN, "emb", [0])


def _tail(proc, n=20):
    try:
        out = proc.stdout.read() if proc.poll() is not None else ""
    except Exception:  # noqa: BLE001
        out = ""
    return "\n".join((out or "").splitlines()[-n:])


def test_binary_lookup_parity(model_dir):
    """The binary plane (now the DEFAULT: lookup == lookup_bin) returns
    the same rows as the JSON debug path, and its shape header round-trips
    multi-dim batch queries exactly — the serving-grade protocol,
    reference zero-copy RpcView (server/RpcView.h:63-105)."""
    port = _free_port()
    proc = ha.spawn_replica(port, load=[f"{SIGN}={model_dir}"])
    try:
        ep = f"127.0.0.1:{port}"
        assert ha.wait_ready(ep, sign=SIGN), _tail(proc)
        router = ha.RoutingClient([ep], timeout=15.0)
        idx = np.asarray([1, 7, 63], np.int32)
        a = router.lookup_json(SIGN, "emb", idx)
        b = router.lookup_bin(SIGN, "emb", idx)
        c = router.lookup(SIGN, "emb", idx)  # default == binary
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(b, c)
        # multi-dim batch shape survives the wire (the header carries it;
        # a flat view would silently collapse [2, 3] to [6])
        m = router.lookup(SIGN, "emb", idx.reshape(1, 3).repeat(2, 0))
        assert m.shape == (2, 3, a.shape[-1])
        np.testing.assert_array_equal(m[0], a)
        # int64 ids keep their width end-to-end (dtype rides the header)
        d = router.lookup(SIGN, "emb", np.asarray([1, 7, 63], np.int64))
        np.testing.assert_array_equal(d, a)
    finally:
        proc.kill()


@pytest.mark.slow
def test_peer_row_restore_without_dump(model_dir, tmp_path):
    """The dump store dies AFTER boot; a respawned replica must rebuild
    purely from a living peer's memory (the reference's coordinated-restore
    iterator, EmbeddingRestoreOperator.cpp:12-106) — catalog hand-off alone
    is not enough when the URI is unreadable."""
    import shutil
    # work on a private copy of the model dir so other tests keep theirs
    mdir = str(tmp_path / "model")
    shutil.copytree(model_dir, mdir)
    ports = [_free_port() for _ in range(2)]
    eps = [f"127.0.0.1:{p}" for p in ports]
    procs = {}
    try:
        procs[0] = ha.spawn_replica(ports[0], load=[f"{SIGN}={mdir}"])
        assert ha.wait_ready(eps[0], sign=SIGN), _tail(procs[0])
        # the checkpoint store is lost
        shutil.rmtree(mdir)
        # a replacement replica boots with only a living peer
        procs[1] = ha.spawn_replica(ports[1], peers=[eps[0]])
        assert ha.wait_ready(eps[1], sign=SIGN, timeout=180.0), \
            _tail(procs[1])
        # the restored replica serves the right rows BY ITSELF
        solo = ha.RoutingClient([eps[1]], timeout=15.0)
        rows = solo.lookup(SIGN, "emb", [1, 7, 63])
        np.testing.assert_allclose(rows, 0.5, rtol=1e-6)
        # and survives the original dying (it holds real state, not a proxy)
        procs[0].kill()
        procs[0].wait()
        rows = solo.lookup(SIGN, "emb", [0, 2])
        np.testing.assert_allclose(rows, 0.5, rtol=1e-6)
    finally:
        _cleanup(procs)


@pytest.mark.slow
def test_peer_row_restore_wide_keys(tmp_path, devices8):
    """Peer-to-peer restore of a WIDE-key model: /rows pages carry joined
    int64 ids, the restorer re-splits them into pairs."""
    import shutil
    import jax as _jax
    import jax.numpy as _jnp
    from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
    from openembedding_tpu import checkpoint as _ckpt
    from openembedding_tpu import hash_table as hl
    from openembedding_tpu.parallel.mesh import create_mesh as _cm

    sign = "wide-ha-1"
    mesh = _cm(1, 1, jax.devices()[:1])
    specs = (EmbeddingSpec(name="w", input_dim=-1, output_dim=DIM,
                           hash_capacity=512, key_dtype="wide",
                           initializer={"category": "constant",
                                        "value": 0.0},
                           optimizer={"category": "sgd",
                                      "learning_rate": 1.0}),)
    coll = EmbeddingCollection(specs, mesh)
    states = coll.init(_jax.random.PRNGKey(0))
    k64 = np.asarray([3, 3 + (1 << 35), (9 << 40) + 1], np.int64)
    pairs = _jnp.asarray(hl.split64(k64))
    rows = coll.pull(states, {"w": pairs}, batch_sharded=False)
    g = _jnp.asarray(np.arange(1, 4, dtype=np.float32))[:, None] * \
        _jnp.ones_like(rows["w"])
    states = coll.apply_gradients(states, {"w": pairs}, {"w": g},
                                  batch_sharded=False)
    mdir = str(tmp_path / "model")
    _ckpt.save_checkpoint(mdir, coll, states, model_sign=sign)

    ports = [_free_port() for _ in range(2)]
    eps = [f"127.0.0.1:{p}" for p in ports]
    procs = {}
    try:
        procs[0] = ha.spawn_replica(ports[0], load=[f"{sign}={mdir}"])
        assert ha.wait_ready(eps[0], sign=sign), _tail(procs[0])
        shutil.rmtree(mdir)  # dump store gone: force the peer-row path
        procs[1] = ha.spawn_replica(ports[1], peers=[eps[0]])
        assert ha.wait_ready(eps[1], sign=sign, timeout=180.0), \
            _tail(procs[1])
        solo = ha.RoutingClient([eps[1]], timeout=15.0)
        got = solo.lookup(sign, "w", hl.split64(k64).tolist())
        np.testing.assert_allclose(got[:, 0], [-1.0, -2.0, -3.0],
                                   rtol=1e-6)
    finally:
        _cleanup(procs)


# --- RetryPolicy: the ONE deadline-budgeted policy for every verb ------------

def test_retry_policy_validates():
    ha.RetryPolicy()  # defaults are valid
    with pytest.raises(ValueError, match="deadline_s"):
        ha.RetryPolicy(deadline_s=0.0)
    with pytest.raises(ValueError, match="backoff"):
        ha.RetryPolicy(base_backoff_s=-0.1)
    with pytest.raises(ValueError, match="backoff"):
        ha.RetryPolicy(max_backoff_s=-1.0)
    with pytest.raises(ValueError, match="multiplier"):
        ha.RetryPolicy(multiplier=0.5)
    with pytest.raises(ValueError, match="jitter"):
        ha.RetryPolicy(jitter=1.5)


def test_retry_policy_backoff_bounds():
    """Exponential growth, hard cap, jitter only ever SHORTENS the
    sleep (never lengthens past the raw exponential — a herd must not
    drift later and later)."""
    p = ha.RetryPolicy(base_backoff_s=0.1, max_backoff_s=1.0,
                       multiplier=2.0, jitter=0.5)
    for rnd in range(8):
        raw = min(1.0, 0.1 * 2.0 ** rnd)
        for _ in range(25):
            s = p.backoff(rnd)
            assert raw * (1.0 - 0.5) <= s <= raw, (rnd, s, raw)
    # zero jitter is exactly the exponential, capped
    p0 = ha.RetryPolicy(base_backoff_s=0.1, max_backoff_s=1.0,
                        multiplier=2.0, jitter=0.0)
    assert p0.backoff(0) == pytest.approx(0.1)
    assert p0.backoff(1) == pytest.approx(0.2)
    assert p0.backoff(10) == pytest.approx(1.0)


def test_retry_budget_exhausts_at_deadline():
    """A dead fleet burns the per-REQUEST deadline, not one socket
    timeout per attempt — then surfaces ConnectionError and bumps the
    budget-exhausted counter."""
    from openembedding_tpu.analysis import scope
    dead = f"127.0.0.1:{_free_port()}"   # bound-then-closed: refused
    client = ha.RoutingClient(
        [dead], timeout=5.0,
        policy=ha.RetryPolicy(deadline_s=0.3, base_backoff_s=0.02,
                              max_backoff_s=0.05))
    exhausted0 = scope.HISTOGRAMS.counter("serving_retry_budget_exhausted")
    t0 = time.monotonic()
    with pytest.raises(ConnectionError, match="round"):
        client.lookup(SIGN, "emb", [1])
    dt = time.monotonic() - t0
    client.close()
    # well under the 5 s per-connection timeout: the deadline governs
    assert dt < 4.0, dt
    assert scope.HISTOGRAMS.counter("serving_retry_budget_exhausted") \
        == exhausted0 + 1
