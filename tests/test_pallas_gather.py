"""Pallas sparse-gather kernel: parity with the XLA pull contract.

Runs in interpret mode on the CPU mesh (the kernel compiles to a Mosaic
pipeline on real TPUs; bench records 546 GB/s vs XLA gather's 1331 GB/s on
the round's chip — XLA remains the default pull path, the kernel is the
native-op scaffold)."""

import numpy as np

import jax
import jax.numpy as jnp

import pytest

from openembedding_tpu.ops.pallas_gather import (ROWS_PER_STEP, gather_rows,
                                                 pad_table)


def test_gather_parity_and_invalid_ids(devices8):
    rng = np.random.RandomState(0)
    table = pad_table(jnp.asarray(rng.randn(100, 9).astype(np.float32)))
    idx = jnp.asarray([0, 5, 99, -1, 100, 5, 42], jnp.int32)
    got = np.asarray(gather_rows(table, idx, interpret=True))[:, :9]
    want = np.zeros((7, 9), np.float32)
    for i, v in enumerate([0, 5, 99, -1, -1, 5, 42]):
        if v >= 0:
            want[i] = np.asarray(table)[v, :9]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_gather_rejects_ragged_dim(devices8):
    table = jnp.zeros((16, 9), jnp.float32)
    with pytest.raises(ValueError, match="lane-aligned"):
        gather_rows(table, jnp.zeros((4,), jnp.int32), interpret=True)


def test_gather_lane_aligned_and_step_multiple(devices8):
    """dim already lane-aligned + batch an exact multiple of the DMA depth."""
    rng = np.random.RandomState(1)
    table = jnp.asarray(rng.randn(64, 128).astype(np.float32))
    idx = jnp.asarray(rng.randint(0, 64, 4 * ROWS_PER_STEP), jnp.int32)
    got = np.asarray(gather_rows(table, idx, interpret=True))
    np.testing.assert_allclose(got, np.asarray(table)[np.asarray(idx)],
                               rtol=1e-6)
