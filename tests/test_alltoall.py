"""Owner-routed all-to-all data plane: parity + routing invariants.

The a2a plane must be numerically indistinguishable from the psum plane (and
from the single-device core) — same contract the reference enforces between
its one-node and N-node paths (c_api_test.h matrix). Routing internals
(bucketing, grid transpose, overflow accounting) are checked separately.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import (EmbeddingCollection, EmbeddingSpec,
                               EmbeddingVariableMeta, apply_gradients,
                               create_table, make_optimizer, pull)
from openembedding_tpu import hash_table as hash_lib
from openembedding_tpu.parallel import alltoall as a2a
from openembedding_tpu.parallel import sharded_hash as sh
from openembedding_tpu.parallel import sharded_table as st
from openembedding_tpu.parallel.mesh import create_mesh

VOCAB, DIM = 64, 4


# --- routing primitives -----------------------------------------------------

def test_bucketize_assigns_dense_slots():
    owner = jnp.asarray([2, 0, 2, 5, 0, 2], jnp.int32)  # 5 >= num_shards: drop
    dest, ok = a2a.bucketize(owner, num_shards=4, capacity=2)
    dest, ok = np.asarray(dest), np.asarray(ok)
    assert not ok[3] and dest[3] == 4 * 2
    # owner 0 entries fill slots 0..1 of bucket 0; owner 2 fills bucket 2,
    # third owner-2 entry overflows capacity 2
    assert sorted(dest[[1, 4]].tolist()) == [0, 1]
    in2 = dest[[0, 2, 5]]
    assert sorted(in2.tolist())[:2] == [2 * 2, 2 * 2 + 1]
    assert ok.sum() == 4  # one dropped by owner, one by capacity


def test_bucket_capacity_floors_and_exact():
    # small slices are exact (capacity == slice size)
    assert a2a.bucket_capacity(16, 8) == 16
    # large slices get slack * mean rounded to 8
    c = a2a.bucket_capacity(4096, 8, slack=2.0)
    assert c >= 2 * (4096 // 8) and c % 8 == 0
    # explicit override wins
    assert a2a.bucket_capacity(4096, 8, capacity=128) == 128


def test_residue_accumulators_gated(devices8):
    """Structured-skew overflow is exact AND observable via gated counters."""
    from openembedding_tpu.utils import observability as obs
    mesh = create_mesh(1, 8, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=8 * 512)
    opt = make_optimizer({"category": "sgd", "learning_rate": 0.1})
    # capacity 4 per destination + 64 keys all owned by shard 0 => the
    # residue loop must run extra rounds (and the counters must see them)
    spec = st.make_sharding_spec(meta, mesh, plane="a2a", a2a_capacity=4)
    state = st.create_sharded_table(
        meta, opt, {"category": "constant", "value": 0.5}, mesh=mesh,
        spec=spec)
    idx = jnp.asarray(np.arange(0, 8 * 64, 8, dtype=np.int32))  # all ≡ 0 mod 8
    obs.GLOBAL.reset()
    obs.set_evaluate_performance(True)
    try:
        rows = st.pull_sharded(state, idx, mesh=mesh, spec=spec,
                               batch_sharded=False)
        # exactness despite 16x overflow of the per-round capacity
        np.testing.assert_allclose(np.asarray(rows), 0.5, rtol=1e-6)
        jax.effects_barrier()
        snap = obs.GLOBAL.snapshot()
        assert snap.get("a2a_extra_entries_pull", {}).get("count", 0) > 0
    finally:
        obs.set_evaluate_performance(False)
        obs.GLOBAL.reset()


def test_routing_overflow_counts(devices8):
    # 1 hot owner: every key lands on shard 0 => overflow for small capacity
    idx = np.arange(0, 8 * 64, 8, dtype=np.int32)  # all ≡ 0 mod 8
    n = a2a.routing_overflow(idx, num_shards=8, slice_parts=1,
                             owner_of=lambda u: u % 8, capacity=16)
    assert n == 64 - 16
    # uniform keys with auto capacity: no overflow
    idx = np.arange(512, dtype=np.int32)
    assert a2a.routing_overflow(idx, 8, 1, lambda u: u % 8) == 0


# --- array-table parity ------------------------------------------------------

@pytest.mark.parametrize("data,model", [(1, 8), (2, 4), (8, 1)])
def test_a2a_matches_single_and_psum(devices8, data, model):
    mesh = create_mesh(data, model, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=VOCAB)
    opt = make_optimizer({"category": "adam", "learning_rate": 0.05})
    init = {"category": "constant", "value": 0.5}
    spec = st.make_sharding_spec(meta, mesh, plane="a2a")
    pspec = st.make_sharding_spec(meta, mesh, plane="psum")
    assert spec.num_shards == mesh.size
    assert pspec.num_shards == mesh.shape["model"]

    sharded = st.create_sharded_table(meta, opt, init, mesh=mesh, spec=spec)
    psharded = st.create_sharded_table(meta, opt, init, mesh=mesh, spec=pspec)
    single = create_table(meta, opt, init, capacity=spec.padded_vocab)

    rng = np.random.RandomState(0)
    B = 32
    for step in range(3):
        # include invalid ids (negative / out of range): zero rows + dropped
        idx = rng.randint(-3, VOCAB + 3, size=B).astype(np.int32)
        grads = rng.randn(B, DIM).astype(np.float32)
        jidx, jg = jnp.asarray(idx), jnp.asarray(grads)

        got = st.pull_sharded(sharded, jidx, mesh=mesh, spec=spec)
        shard, local = spec.shard_and_local(jidx)
        phys = jnp.where((jidx >= 0) & (jidx < VOCAB),
                         shard * spec.rows_per_shard + local, -1)
        want = pull(single, phys)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)
        # replicated-batch (serving) path agrees
        got_r = st.pull_sharded(sharded, jidx, mesh=mesh, spec=spec,
                                batch_sharded=False)
        np.testing.assert_allclose(np.asarray(got_r), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)
        # psum plane agrees
        got_p = st.pull_sharded(psharded, jidx, mesh=mesh, spec=pspec)
        np.testing.assert_allclose(np.asarray(got_p), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)

        sharded = st.apply_gradients_sharded(sharded, opt, jidx, jg,
                                             mesh=mesh, spec=spec)
        single = apply_gradients(single, opt, phys, jg)
        psharded = st.apply_gradients_sharded(psharded, opt, jidx, jg,
                                              mesh=mesh, spec=pspec)

    np.testing.assert_allclose(np.asarray(sharded.weights),
                               np.asarray(single.weights),
                               rtol=1e-5, atol=1e-5)
    for k in single.slots:
        np.testing.assert_allclose(np.asarray(sharded.slots[k]),
                                   np.asarray(single.slots[k]),
                                   rtol=1e-5, atol=1e-5)


def test_a2a_replicated_batch_apply(devices8):
    """batch_sharded=False apply: updates land once, not once per device."""
    mesh = create_mesh(2, 4, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=VOCAB)
    opt = make_optimizer({"category": "sgd", "learning_rate": 1.0})
    init = {"category": "constant", "value": 0.0}
    spec = st.make_sharding_spec(meta, mesh, plane="a2a")
    state = st.create_sharded_table(meta, opt, init, mesh=mesh, spec=spec)
    idx = jnp.asarray([3, 3, 7], jnp.int32)
    g = jnp.ones((3, DIM), jnp.float32)
    state = st.apply_gradients_sharded(state, opt, idx, g, mesh=mesh,
                                       spec=spec, batch_sharded=False)
    rows = st.pull_sharded(state, jnp.asarray([3, 7], jnp.int32), mesh=mesh,
                           spec=spec, batch_sharded=False)
    np.testing.assert_allclose(np.asarray(rows)[0], -2.0, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(rows)[1], -1.0, rtol=1e-6)


# --- hash-table parity -------------------------------------------------------

@pytest.mark.parametrize("data,model", [(2, 4), (8, 1)])
def test_a2a_hash_matches_single(devices8, data, model):
    mesh = create_mesh(data, model, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=2**63)
    opt = make_optimizer({"category": "adagrad", "learning_rate": 0.1})
    init = {"category": "constant", "value": 0.25}
    spec = sh.make_hash_sharding_spec(mesh, total_capacity=2048, plane="a2a")
    state = sh.create_sharded_hash_table(meta, opt, mesh=mesh, spec=spec)
    # ground truth: one big single-device table with the same base rng
    single = hash_lib.create_hash_table(meta, opt, capacity=2048,
                                        rng=jax.random.PRNGKey(0))

    rng = np.random.RandomState(7)
    B = 32
    for step in range(3):
        keys = (rng.randint(0, 1 << 30, size=B) * 2654435761 % (1 << 31)
                ).astype(np.int32)
        keys[1] = keys[0]  # duplicates combine
        g = rng.randn(B, DIM).astype(np.float32)
        jk, jg = jnp.asarray(keys), jnp.asarray(g)
        got = sh.pull_sharded(state, jk, init, mesh=mesh, spec=spec)
        want = hash_lib.pull(single, jk, init)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)
        state = sh.apply_gradients_sharded(state, opt, init, jk, jg,
                                           mesh=mesh, spec=spec)
        single = hash_lib.apply_gradients(single, opt, init, jk, jg)
        assert int(state.insert_failures) == 0

    got = sh.pull_sharded(state, jk, None, mesh=mesh, spec=spec)
    want = hash_lib.pull(single, jk, None)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


# --- adversarial skew: the exchange must be exact for ANY distribution ------

@pytest.mark.slow
@pytest.mark.parametrize("skew", ["congruent", "hotkey", "one_owner_hash"])
def test_a2a_exact_under_adversarial_skew(devices8, skew):
    """Bit-exact a2a/psum parity at DEFAULT settings under structured skew.

    The reference's exchange is exact for any key distribution
    (variable-size RPCs, EmbeddingPullOperator.cpp:60-112); the residue loop
    must make the fixed-capacity TPU exchange match: ids all congruent mod
    the shard count (every unique routed to ONE owner), hot-key floods, and
    a batch >> capacity heuristics were tuned for.
    """
    mesh = create_mesh(2, 4, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=4096)
    opt = make_optimizer({"category": "adam", "learning_rate": 0.05})
    init = {"category": "constant", "value": 0.5}
    spec = st.make_sharding_spec(meta, mesh, plane="a2a")
    pspec = st.make_sharding_spec(meta, mesh, plane="psum")
    sharded = st.create_sharded_table(meta, opt, init, mesh=mesh, spec=spec)
    psharded = st.create_sharded_table(meta, opt, init, mesh=mesh, spec=pspec)

    rng = np.random.RandomState(13)
    B = 512
    for step in range(2):
        if skew == "congruent":
            # every id ≡ 0 mod num_shards: all uniques owned by shard 0
            idx = (rng.randint(0, 4096 // spec.num_shards, size=B)
                   * spec.num_shards).astype(np.int32)
        elif skew == "hotkey":
            idx = np.where(rng.rand(B) < 0.9, 8,
                           rng.randint(0, 4096, size=B)).astype(np.int32)
        else:
            # after dedup, >capacity uniques all map to one owner via the
            # div-free mod layout: stride by num_shards from a random base
            idx = (np.arange(B) * spec.num_shards % 4096).astype(np.int32)
        grads = rng.randn(B, DIM).astype(np.float32)
        jidx, jg = jnp.asarray(idx), jnp.asarray(grads)

        got = st.pull_sharded(sharded, jidx, mesh=mesh, spec=spec)
        want = st.pull_sharded(psharded, jidx, mesh=mesh, spec=pspec)
        # planes reduce in different shard orders -> ULP-level float
        # reassociation; routing exactness (no dropped entries) is asserted
        # bit-exactly in the constant-init tests below/above
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-7)

        sharded = st.apply_gradients_sharded(sharded, opt, jidx, jg,
                                             mesh=mesh, spec=spec)
        psharded = st.apply_gradients_sharded(psharded, opt, jidx, jg,
                                              mesh=mesh, spec=pspec)

    # final weights identical (a2a shards over 8 devices, psum over 4 —
    # compare through a full pull of the whole vocab)
    allv = jnp.arange(4096, dtype=jnp.int32)
    wa = st.pull_sharded(sharded, allv, mesh=mesh, spec=spec,
                         batch_sharded=False)
    wp = st.pull_sharded(psharded, allv, mesh=mesh, spec=pspec,
                         batch_sharded=False)
    np.testing.assert_allclose(np.asarray(wa), np.asarray(wp),
                               rtol=1e-6, atol=1e-7)


def test_a2a_hash_exact_under_skew(devices8):
    """Hash plane: keys all congruent mod num_shards still train exactly."""
    mesh = create_mesh(2, 4, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=2**63)
    opt = make_optimizer({"category": "sgd", "learning_rate": 1.0})
    init = {"category": "constant", "value": 0.0}
    spec = sh.make_hash_sharding_spec(mesh, total_capacity=4096, plane="a2a")
    state = sh.create_sharded_hash_table(meta, opt, mesh=mesh, spec=spec)
    single = hash_lib.create_hash_table(meta, opt, capacity=4096,
                                        rng=jax.random.PRNGKey(0))
    B = 256
    # all keys owned by shard 3: key % 8 == 3, far more uniques than the
    # default bucket capacity for a 256-entry slice over 8 shards
    keys = (np.arange(B, dtype=np.int32) * spec.num_shards + 3)
    g = np.ones((B, DIM), np.float32)
    jk, jg = jnp.asarray(keys), jnp.asarray(g)
    state = sh.apply_gradients_sharded(state, opt, init, jk, jg,
                                       mesh=mesh, spec=spec)
    single = hash_lib.apply_gradients(single, opt, init, jk, jg)
    got = sh.pull_sharded(state, jk, None, mesh=mesh, spec=spec)
    want = hash_lib.pull(single, jk, None)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    np.testing.assert_allclose(np.asarray(got), -1.0, rtol=1e-6)


# --- end-to-end through the collection ---------------------------------------

def test_collection_planes_agree(devices8):
    """Same model trained on a2a and psum planes: identical states."""
    mesh = create_mesh(2, 4, devices8)

    def run(plane):
        specs = (
            EmbeddingSpec(name="bounded", input_dim=VOCAB, output_dim=DIM,
                          initializer={"category": "constant", "value": 0.1},
                          plane=plane),
            EmbeddingSpec(name="hashed", input_dim=-1, output_dim=DIM,
                          hash_capacity=1024, plane=plane),
        )
        coll = EmbeddingCollection(specs, mesh)
        states = coll.init(jax.random.PRNGKey(3))
        rng = np.random.RandomState(11)
        for _ in range(2):
            inputs = {
                "bounded": jnp.asarray(
                    rng.randint(0, VOCAB, size=16).astype(np.int32)),
                "hashed": jnp.asarray(
                    (rng.randint(0, 1 << 28, size=16) * 7919).astype(np.int32)),
            }
            rows = coll.pull(states, inputs)
            grads = {k: jnp.ones_like(v) * 0.5 for k, v in rows.items()}
            states = coll.apply_gradients(states, inputs, grads)
        rows = coll.pull(states, inputs)
        return {k: np.asarray(v) for k, v in rows.items()}

    got_a2a = run("a2a")
    got_psum = run("psum")
    for k in got_a2a:
        np.testing.assert_allclose(got_a2a[k], got_psum[k],
                                   rtol=1e-5, atol=1e-6)


def test_a2a_wide_keys_sharded_matches_single(devices8):
    """WIDE (64-bit pair, x64-off) keys through the sharded a2a plane:
    parity with a single wide table, keys spanning >2^32 with colliding
    lo words — the default-configuration full-width key space (the
    reference's 2^62 hashed ids) without a dedicated x64 process."""
    mesh = create_mesh(2, 4, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=2**63)
    opt = make_optimizer({"category": "adagrad", "learning_rate": 0.1})
    init = {"category": "constant", "value": 0.25}
    spec = sh.make_hash_sharding_spec(mesh, total_capacity=4096,
                                      plane="a2a", key_width=64)
    assert spec.wide
    state = sh.create_sharded_hash_table(meta, opt, mesh=mesh, spec=spec)
    assert state.keys.ndim == 2
    single = hash_lib.create_hash_table(meta, opt, capacity=4096,
                                        rng=jax.random.PRNGKey(0),
                                        key_width=64)

    rng = np.random.RandomState(7)
    B = 64
    for step in range(3):
        lo = rng.randint(0, 1 << 16, size=B).astype(np.int64)
        hi = rng.randint(0, 1 << 28, size=B).astype(np.int64)
        k64 = lo + (hi << 32)           # heavy lo-word collisions
        k64[1] = k64[0]                 # duplicates combine
        pairs = jnp.asarray(hash_lib.split64(k64))
        g = rng.randn(B, DIM).astype(np.float32)
        jg = jnp.asarray(g)
        got = sh.pull_sharded(state, pairs, init, mesh=mesh, spec=spec,
                              batch_sharded=False)
        want = hash_lib.pull(single, pairs, init)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)
        state = sh.apply_gradients_sharded(state, opt, init, pairs, jg,
                                           mesh=mesh, spec=spec,
                                           batch_sharded=False)
        single = hash_lib.apply_gradients(single, opt, init, pairs, jg)
        assert int(state.insert_failures) == 0

    got = sh.pull_sharded(state, pairs, None, mesh=mesh, spec=spec,
                          batch_sharded=False)
    want = hash_lib.pull(single, pairs, None)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    # distinct rows for keys sharing lo words: no mod-2^32 aliasing
    probe = jnp.asarray(hash_lib.split64(
        np.asarray([42, 42 + (1 << 32)], np.int64)))
    r = sh.pull_sharded(state, probe, init, mesh=mesh, spec=spec,
                        batch_sharded=False)
    w = hash_lib.pull(single, probe, init)
    np.testing.assert_allclose(np.asarray(r), np.asarray(w), rtol=1e-6)


def test_a2a_wide_keys_exact_under_skew(devices8):
    """Wide pair keys + structured owner skew: the residue/fallback
    machinery must stay exact when every unique is owned by one shard."""
    mesh = create_mesh(2, 4, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=DIM, vocabulary_size=2**63)
    opt = make_optimizer({"category": "sgd", "learning_rate": 1.0})
    init = {"category": "constant", "value": 0.0}
    spec = sh.make_hash_sharding_spec(mesh, total_capacity=4096,
                                      plane="a2a", key_width=64)
    state = sh.create_sharded_hash_table(meta, opt, mesh=mesh, spec=spec)
    single = hash_lib.create_hash_table(meta, opt, capacity=4096,
                                        rng=jax.random.PRNGKey(0),
                                        key_width=64)
    B = 256
    # craft keys all landing on ONE owner under the (hi*2^32+lo) mod 8
    # rule: lo = 8*i, hi = 0  ->  key mod 8 == 0 for all
    k64 = np.arange(B, dtype=np.int64) * 8
    pairs = jnp.asarray(hash_lib.split64(k64))
    owners = np.asarray(spec.owner_shard(pairs))
    assert (owners == owners[0]).all()
    g = jnp.ones((B, DIM), jnp.float32)
    state = sh.apply_gradients_sharded(state, opt, init, pairs, g,
                                       mesh=mesh, spec=spec)
    single = hash_lib.apply_gradients(single, opt, init, pairs, g)
    assert int(state.insert_failures) == 0
    got = sh.pull_sharded(state, pairs, None, mesh=mesh, spec=spec)
    want = hash_lib.pull(single, pairs, None)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    np.testing.assert_allclose(np.asarray(got), -1.0, rtol=1e-6)


# --- compiled-HLO ICI contract ----------------------------------------------

def _lower_pull(mesh, plane, *, vocab=1 << 16, dim=16, batch=1024,
                use_hash=False):
    """One lowering recipe for the whole repo: delegate to the shipped
    helper (analysis/programs.py) so this file and the contract gate can
    never drift apart and audit different programs."""
    from openembedding_tpu.analysis import programs
    txt, _params = programs.lower_pull(mesh, plane, vocab=vocab, dim=dim,
                                       batch=batch, use_hash=use_hash)
    return txt


@pytest.mark.parametrize("mesh_shape", [
    (2, 4), pytest.param((1, 8), marks=pytest.mark.slow)])
@pytest.mark.parametrize("use_hash", [False, True])
def test_a2a_pull_ici_contract(devices8, mesh_shape, use_hash):
    """The compiled a2a pull program's ICI contract: the owner exchange is
    an all-to-all, and NO all-gather beyond the O(batch_slice * dim) row
    re-assembly exists — per-device bytes O(slack * slice * dim), never
    O(global_batch * dim) or O(table). Guarded in the COMPILED HLO so a
    sharding-annotation regression (XLA re-materializing tables or the
    global batch) fails loudly. Reference analogue: the exchange-not-
    broadcast design of EmbeddingPullOperator.cpp:60-112."""
    from openembedding_tpu.utils import hlocheck
    B, dim = 1024, 16
    mesh = create_mesh(*mesh_shape, devices8)
    txt = _lower_pull(mesh, "a2a", dim=dim, batch=B, use_hash=use_hash)
    summary = hlocheck.check_a2a_pull_hlo(
        txt, batch_slice=B // mesh_shape[0], dim=dim)
    assert summary["all-to-all"][0] >= 1

    # the psum baseline CARRIES the O(batch_slice * dim) broadcast-style
    # signature the a2a bound excludes — proves the bound is meaningful
    txt_psum = _lower_pull(mesh, "psum", dim=dim, batch=B,
                           use_hash=use_hash)
    psum_summary = hlocheck.summarize(txt_psum)
    assert "all-to-all" not in psum_summary
    big = [b for op, b, _largest in hlocheck.collect_collectives(txt_psum)
           if op in ("all-reduce", "all-gather")
           and b >= (B // mesh_shape[0]) * dim * 4]
    assert big, f"psum plane lost its broadcast signature: {psum_summary}"


@pytest.mark.slow
def test_a2a_pull_ici_contract_16dev():
    """Same contract on a 16-device virtual mesh (a child process: this
    process's backend is pinned to 8 devices) — the scaling regime the
    plane exists for. Slow lane: the child recompiles 8 programs from
    scratch (~several min on CPU); tier-1 keeps the same contract on the
    8-device mesh here and in test_analysis_contracts.py."""
    import os
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = f"""
import sys
sys.path.insert(0, {root!r})
import jax
from openembedding_tpu.utils.jaxcompat import set_num_cpu_devices
jax.config.update("jax_platforms", "cpu")
set_num_cpu_devices(16)
sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})
import jax.numpy as jnp
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.utils import hlocheck
import test_alltoall as t
for shape in ((4, 4), (2, 8)):
    mesh = create_mesh(*shape)
    for use_hash in (False, True):
        txt = t._lower_pull(mesh, "a2a", dim=16, batch=2048,
                            use_hash=use_hash)
        s = hlocheck.check_a2a_pull_hlo(txt, batch_slice=2048 // shape[0],
                                        dim=16)
        print(shape, use_hash, dict(s))
print("ok")
"""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "ok" in out.stdout
