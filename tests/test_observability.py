"""Timers, accumulators, batch stats gating, reporter, streaming AUC."""

import time

import numpy as np

from openembedding_tpu.utils import observability as obs


def test_accumulator_and_vtimer():
    acc = obs.Accumulator()
    acc.add("pulls", 5)
    acc.add("pulls", 3)
    with obs.vtimer("step", acc):
        time.sleep(0.01)
    snap = acc.snapshot()
    assert snap["pulls"]["count"] == 8
    assert snap["step"]["calls"] == 1
    assert snap["step"]["seconds"] >= 0.01
    acc.reset()
    assert acc.snapshot() == {}


def test_batch_stats_gated():
    acc = obs.Accumulator()
    sparse = {"c": np.array([1, 1, 2, 3])}
    obs.record_batch_stats(sparse, acc)          # gate off -> no-op
    assert acc.snapshot() == {}
    obs.set_evaluate_performance(True)
    try:
        obs.record_batch_stats(sparse, acc)
        snap = acc.snapshot()
        assert snap["pull_indices"]["count"] == 4
        assert snap["pull_unique"]["count"] == 3
    finally:
        obs.set_evaluate_performance(False)


def test_plane_timed_and_timings():
    """Per-plane pull/push wall-time split: gated off -> no record, on ->
    timings land under <verb>/<plane> and read back via plane_timings."""
    obs.GLOBAL.reset()
    out = obs.plane_timed("pull", "a2a", False, lambda x: x + 1, 1)
    assert out == 2 and obs.plane_timings() == {}
    obs.plane_timed("pull", "a2a+grouped", True,
                    lambda: np.arange(4))
    obs.plane_timed("pull", "a2a+grouped", True,
                    lambda: np.arange(4))
    obs.plane_timed("push", "a2a+grouped", True,
                    lambda: np.arange(4))
    obs.GLOBAL.add_time("not_a_plane_timer", 1.0)   # must be ignored
    t = obs.plane_timings()
    assert set(t) == {"a2a+grouped"}
    assert t["a2a+grouped"]["pull_calls"] == 2
    assert t["a2a+grouped"]["push_calls"] == 1
    assert t["a2a+grouped"]["pull_ms"] >= 0.0
    obs.GLOBAL.reset()


def test_plane_timed_skips_recording_under_trace():
    """Inside an outer jit the dispatch body runs once per COMPILE, so a
    wall-time record there would report trace time as a step figure —
    the under_trace guard must skip recording (the compiled fn still
    computes)."""
    import jax
    import jax.numpy as jnp

    obs.GLOBAL.reset()

    def f(x):
        return obs.plane_timed("pull", "a2a", True, lambda y: y * 2, x)

    out = jax.jit(f)(jnp.ones((4,)))
    assert float(out[0]) == 2.0
    assert obs.plane_timings() == {}
    obs.GLOBAL.reset()


def test_reporter_periodic():
    acc = obs.Accumulator()
    acc.add("x", 1)
    lines = []
    rep = obs.Reporter(0.05, acc, sink=lines.append).start()
    time.sleep(0.2)
    rep.stop()
    assert lines and "x[count=1]" in lines[0]


def test_streaming_auc_exact_cases():
    auc = obs.StreamingAUC(bins=1000)
    # perfectly separable
    auc.update([1, 1, 0, 0], [0.9, 0.8, 0.2, 0.1])
    assert abs(auc.result() - 1.0) < 1e-9
    # random scores over many updates -> ~0.5
    auc2 = obs.StreamingAUC()
    rng = np.random.RandomState(0)
    for _ in range(20):
        labels = rng.randint(0, 2, 1000)
        auc2.update(labels, rng.rand(1000))
    assert abs(auc2.result() - 0.5) < 0.02
    # agreement with exact pairwise AUC on a small mixed case
    labels = rng.randint(0, 2, 500)
    scores = np.clip(rng.rand(500) * 0.6 + labels * 0.2, 0, 1)
    auc3 = obs.StreamingAUC()
    auc3.update(labels, scores)
    pos, neg = scores[labels > 0], scores[labels <= 0]
    exact = np.mean(pos[:, None] > neg[None, :]) \
        + 0.5 * np.mean(pos[:, None] == neg[None, :])
    assert abs(auc3.result() - exact) < 5e-3
    # degenerate: single class
    auc4 = obs.StreamingAUC()
    auc4.update([1, 1], [0.5, 0.6])
    assert auc4.result() == 0.5


def test_prometheus_text_and_endpoint(devices8):
    """Accumulator -> prometheus text, scrapeable via the REST controller
    (the reference PS daemon's --enable_metrics exposer, server.cc:32-36)."""
    import urllib.request
    import jax
    from openembedding_tpu.utils import observability as obs
    from openembedding_tpu.serving.registry import ModelRegistry
    from openembedding_tpu.serving.rest import ControllerServer
    from openembedding_tpu.parallel.mesh import create_mesh

    obs.GLOBAL.reset()
    obs.GLOBAL.add("pull_indices", 512)
    with obs.vtimer("train_step"):
        pass
    text = obs.prometheus_text()
    assert "# TYPE oe_pull_indices_total counter" in text
    assert "oe_pull_indices_total 512" in text
    assert "oe_train_step_seconds_total" in text
    assert "oe_train_step_calls_total 1" in text

    reg = ModelRegistry(create_mesh(1, 1, jax.devices()[:1]))
    srv = ControllerServer(reg, port=0).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            body = r.read().decode()
        assert "oe_pull_indices_total 512" in body
    finally:
        srv.stop()
        obs.GLOBAL.reset()
