"""Timers, accumulators, batch stats gating, reporter, streaming AUC."""

import time

import numpy as np
import pytest

from openembedding_tpu.analysis import scope
from openembedding_tpu.utils import observability as obs


def test_accumulator_and_vtimer():
    acc = obs.Accumulator()
    acc.add("pulls", 5)
    acc.add("pulls", 3)
    with obs.vtimer("step", acc):
        time.sleep(0.01)
    snap = acc.snapshot()
    assert snap["pulls"]["count"] == 8
    assert snap["step"]["calls"] == 1
    assert snap["step"]["seconds"] >= 0.01
    acc.reset()
    assert acc.snapshot() == {}


def test_batch_stats_gated():
    acc = obs.Accumulator()
    sparse = {"c": np.array([1, 1, 2, 3])}
    obs.record_batch_stats(sparse, acc)          # gate off -> no-op
    assert acc.snapshot() == {}
    obs.set_evaluate_performance(True)
    try:
        obs.record_batch_stats(sparse, acc)
        snap = acc.snapshot()
        assert snap["pull_indices"]["count"] == 4
        assert snap["pull_unique"]["count"] == 3
    finally:
        obs.set_evaluate_performance(False)


def test_plane_timed_and_timings():
    """Per-plane pull/push wall-time split: gated off -> no record, on ->
    timings land under <verb>/<plane> and read back via plane_timings."""
    obs.GLOBAL.reset()
    out = obs.plane_timed("pull", "a2a", False, lambda x: x + 1, 1)
    assert out == 2 and obs.plane_timings() == {}
    obs.plane_timed("pull", "a2a+grouped", True,
                    lambda: np.arange(4))
    obs.plane_timed("pull", "a2a+grouped", True,
                    lambda: np.arange(4))
    obs.plane_timed("push", "a2a+grouped", True,
                    lambda: np.arange(4))
    obs.GLOBAL.add_time("not_a_plane_timer", 1.0)   # must be ignored
    t = obs.plane_timings()
    assert set(t) == {"a2a+grouped"}
    assert t["a2a+grouped"]["pull_calls"] == 2
    assert t["a2a+grouped"]["push_calls"] == 1
    assert t["a2a+grouped"]["pull_ms"] >= 0.0
    obs.GLOBAL.reset()


def test_plane_timed_records_span_on_error_and_reraises():
    """Regression (ISSUE 6 satellite): a raising dispatch used to DROP
    its timing entirely — it must record the span with an error tag and
    re-raise."""
    obs.GLOBAL.reset()
    scope.HISTOGRAMS.reset()

    def boom():
        raise RuntimeError("dispatch died")

    with pytest.raises(RuntimeError, match="dispatch died"):
        obs.plane_timed("pull", "a2a", True, boom)
    t = obs.plane_timings()
    assert t["a2a"]["pull_calls"] == 1          # wall time not dropped
    assert scope.HISTOGRAMS.count("span_pull_seconds", plane="a2a") == 1
    lines = scope.HISTOGRAMS.prometheus_lines()
    assert any("span_errors_total" in ln and 'kind="pull"' in ln
               for ln in lines)
    obs.GLOBAL.reset()
    scope.HISTOGRAMS.reset()


def test_plane_timed_skips_recording_under_trace():
    """Inside an outer jit the dispatch body runs once per COMPILE, so a
    wall-time record there would report trace time as a step figure —
    the under_trace guard must skip recording (the compiled fn still
    computes)."""
    import jax
    import jax.numpy as jnp

    obs.GLOBAL.reset()

    def f(x):
        return obs.plane_timed("pull", "a2a", True, lambda y: y * 2, x)

    out = jax.jit(f)(jnp.ones((4,)))
    assert float(out[0]) == 2.0
    assert obs.plane_timings() == {}
    obs.GLOBAL.reset()


def test_reporter_periodic():
    acc = obs.Accumulator()
    acc.add("x", 1)
    lines = []
    rep = obs.Reporter(0.05, acc, sink=lines.append).start()
    time.sleep(0.2)
    rep.stop()
    assert lines and "x[count=1]" in lines[0]
    assert rep.ticks == len(lines)


def test_reporter_interleaving_harness_coverage():
    """The reporter daemon is schedulable like the other host threads:
    PointGate parks it at ``reporter.tick`` BEFORE any report lands, and
    opening the gate releases the (named) thread."""
    import threading
    from openembedding_tpu.analysis import concurrency

    acc = obs.Accumulator()
    acc.add("x", 1)
    lines = []
    gate = concurrency.PointGate(["reporter.tick"])
    concurrency.install_schedule(gate)
    rep = obs.Reporter(0.01, acc, sink=lines.append)
    try:
        rep.start()
        assert gate.wait_arrival("reporter.tick", timeout=10)
        assert rep.ticks == 0 and not lines      # parked pre-report
        assert any(t.name == "oe-reporter"
                   for t in threading.enumerate())
        gate.open("reporter.tick")
        deadline = time.time() + 10
        while rep.ticks == 0 and time.time() < deadline:
            time.sleep(0.01)
    finally:
        rep.stop()
        concurrency.clear_schedule()
    assert rep.ticks >= 1 and lines


def test_streaming_auc_exact_cases():
    auc = obs.StreamingAUC(bins=1000)
    # perfectly separable
    auc.update([1, 1, 0, 0], [0.9, 0.8, 0.2, 0.1])
    assert abs(auc.result() - 1.0) < 1e-9
    # random scores over many updates -> ~0.5
    auc2 = obs.StreamingAUC()
    rng = np.random.RandomState(0)
    for _ in range(20):
        labels = rng.randint(0, 2, 1000)
        auc2.update(labels, rng.rand(1000))
    assert abs(auc2.result() - 0.5) < 0.02
    # agreement with exact pairwise AUC on a small mixed case
    labels = rng.randint(0, 2, 500)
    scores = np.clip(rng.rand(500) * 0.6 + labels * 0.2, 0, 1)
    auc3 = obs.StreamingAUC()
    auc3.update(labels, scores)
    pos, neg = scores[labels > 0], scores[labels <= 0]
    exact = np.mean(pos[:, None] > neg[None, :]) \
        + 0.5 * np.mean(pos[:, None] == neg[None, :])
    assert abs(auc3.result() - exact) < 5e-3
    # degenerate: single class
    auc4 = obs.StreamingAUC()
    auc4.update([1, 1], [0.5, 0.6])
    assert auc4.result() == 0.5


def test_prometheus_text_golden():
    """Golden exposition output: every series carries # HELP/# TYPE, the
    graftscope histograms render as _bucket/_sum/_count, and label
    values are escaped — the page must stay parseable by a real
    Prometheus scraper (satellite: metric hygiene)."""
    acc = obs.Accumulator()
    acc.add("pull_indices", 512)
    acc.add_time("train_step", 0.5)
    scope.HISTOGRAMS.reset()
    scope.HISTOGRAMS.observe("span_pull_seconds", 0.25, plane="a2a")
    got = obs.prometheus_text(acc)
    want = """\
# HELP oe_pull_indices_total accumulated count of `pull_indices`
# TYPE oe_pull_indices_total counter
oe_pull_indices_total 512
# HELP oe_train_step_seconds_total accumulated wall seconds of `train_step`
# TYPE oe_train_step_seconds_total counter
oe_train_step_seconds_total 0.5
# HELP oe_train_step_calls_total timed calls of `train_step`
# TYPE oe_train_step_calls_total counter
oe_train_step_calls_total 1
# HELP oe_span_pull_seconds graftscope histogram `span_pull_seconds` (log-spaced buckets)
# TYPE oe_span_pull_seconds histogram
oe_span_pull_seconds_bucket{plane="a2a",le="0.3162"} 1
oe_span_pull_seconds_bucket{plane="a2a",le="+Inf"} 1
oe_span_pull_seconds_sum{plane="a2a"} 0.25
oe_span_pull_seconds_count{plane="a2a"} 1
"""
    assert got == want
    # minimal scraper-side parse: every non-comment line is
    # `name{labels} value` with a float value
    for ln in got.strip().splitlines():
        if ln.startswith("#"):
            continue
        name_part, value = ln.rsplit(" ", 1)
        float(value)
        assert name_part.startswith("oe_")
    scope.HISTOGRAMS.reset()


def test_prometheus_text_and_endpoint(devices8):
    """Accumulator -> prometheus text, scrapeable via the REST controller
    (the reference PS daemon's --enable_metrics exposer, server.cc:32-36)."""
    import urllib.request
    import jax
    from openembedding_tpu.utils import observability as obs
    from openembedding_tpu.serving.registry import ModelRegistry
    from openembedding_tpu.serving.rest import ControllerServer
    from openembedding_tpu.parallel.mesh import create_mesh

    obs.GLOBAL.reset()
    obs.GLOBAL.add("pull_indices", 512)
    with obs.vtimer("train_step"):
        pass
    text = obs.prometheus_text()
    assert "# TYPE oe_pull_indices_total counter" in text
    assert "oe_pull_indices_total 512" in text
    assert "oe_train_step_seconds_total" in text
    assert "oe_train_step_calls_total 1" in text

    reg = ModelRegistry(create_mesh(1, 1, jax.devices()[:1]))
    srv = ControllerServer(reg, port=0).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            body = r.read().decode()
        assert "oe_pull_indices_total 512" in body
        # the scrape itself ran under a request span — the SECOND scrape
        # must expose the http latency histogram series
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as r:
            body2 = r.read().decode()
        assert "# TYPE oe_span_http_seconds histogram" in body2
        assert 'oe_span_http_seconds_bucket{method="GET",' \
               'route="/metrics",le="+Inf"}' in body2
        assert 'oe_span_http_seconds_count{method="GET",' \
               'route="/metrics"}' in body2
    finally:
        srv.stop()
        obs.GLOBAL.reset()
        scope.HISTOGRAMS.reset()
