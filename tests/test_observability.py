"""Timers, accumulators, batch stats gating, reporter, streaming AUC."""

import time

import numpy as np
import pytest

from openembedding_tpu.analysis import scope
from openembedding_tpu.utils import observability as obs


def test_accumulator_and_vtimer():
    acc = obs.Accumulator()
    acc.add("pulls", 5)
    acc.add("pulls", 3)
    with obs.vtimer("step", acc):
        time.sleep(0.01)
    snap = acc.snapshot()
    assert snap["pulls"]["count"] == 8
    assert snap["step"]["calls"] == 1
    assert snap["step"]["seconds"] >= 0.01
    acc.reset()
    assert acc.snapshot() == {}


def test_batch_stats_gated():
    acc = obs.Accumulator()
    sparse = {"c": np.array([1, 1, 2, 3])}
    obs.record_batch_stats(sparse, acc)          # gate off -> no counters
    assert acc.snapshot() == {}
    obs.set_evaluate_performance(True)
    try:
        obs.record_batch_stats(sparse, acc)
        snap = acc.snapshot()
        assert snap["pull_indices"]["count"] == 4
        assert snap["pull_unique"]["count"] == 3
    finally:
        obs.set_evaluate_performance(False)


def test_batch_stats_always_on_gauges_and_throttle(monkeypatch):
    """The graftplan split: per-table last-value gauges record with the
    debug gate OFF (throttled to one scan per table per interval; the
    first batch of a table always lands), while the counters/histograms
    stay behind set_evaluate_performance."""
    monkeypatch.setattr(obs, "_BATCH_GAUGE_LAST", {})
    monkeypatch.setattr(obs, "_LABELED_GAUGES", {})
    acc = obs.Accumulator()
    key = (("table", "g0"),)
    obs.record_batch_stats({"g0": np.array([1, 1, 2, 3])}, acc)
    assert acc.snapshot() == {}                  # counters stay gated
    g = obs.labeled_gauges()
    assert g["pull_unique_ratio_last"][key] == 0.75
    assert g["pull_key_skew_last"][key] == 0.5
    # a second batch inside the throttle interval is skipped...
    obs.record_batch_stats({"g0": np.array([5, 5, 5, 5])}, acc)
    assert obs.labeled_gauges()["pull_unique_ratio_last"][key] == 0.75
    # ...but a NEW table's first batch always records
    obs.record_batch_stats({"g1": np.array([7, 7])}, acc)
    assert obs.labeled_gauges()["pull_key_skew_last"][
        (("table", "g1"),)] == 1.0
    # the gate bypasses the throttle (per-batch fidelity when armed)
    obs.set_evaluate_performance(True)
    try:
        obs.record_batch_stats({"g0": np.array([5, 5, 5, 5])}, acc)
    finally:
        obs.set_evaluate_performance(False)
    assert obs.labeled_gauges()["pull_unique_ratio_last"][key] == 0.25
    assert acc.snapshot()["pull_indices"]["count"] == 4


def test_plane_timed_and_timings():
    """Per-plane pull/push wall-time split: gated off -> no record, on ->
    timings land under <verb>/<plane> and read back via plane_timings."""
    obs.GLOBAL.reset()
    out = obs.plane_timed("pull", "a2a", False, lambda x: x + 1, 1)
    assert out == 2 and obs.plane_timings() == {}
    obs.plane_timed("pull", "a2a+grouped", True,
                    lambda: np.arange(4))
    obs.plane_timed("pull", "a2a+grouped", True,
                    lambda: np.arange(4))
    obs.plane_timed("push", "a2a+grouped", True,
                    lambda: np.arange(4))
    obs.GLOBAL.add_time("not_a_plane_timer", 1.0)   # must be ignored
    t = obs.plane_timings()
    assert set(t) == {"a2a+grouped"}
    assert t["a2a+grouped"]["pull_calls"] == 2
    assert t["a2a+grouped"]["push_calls"] == 1
    assert t["a2a+grouped"]["pull_ms"] >= 0.0
    obs.GLOBAL.reset()


def test_plane_timed_records_span_on_error_and_reraises():
    """Regression (ISSUE 6 satellite): a raising dispatch used to DROP
    its timing entirely — it must record the span with an error tag and
    re-raise."""
    obs.GLOBAL.reset()
    scope.HISTOGRAMS.reset()

    def boom():
        raise RuntimeError("dispatch died")

    with pytest.raises(RuntimeError, match="dispatch died"):
        obs.plane_timed("pull", "a2a", True, boom)
    t = obs.plane_timings()
    assert t["a2a"]["pull_calls"] == 1          # wall time not dropped
    assert scope.HISTOGRAMS.count("span_pull_seconds", plane="a2a") == 1
    lines = scope.HISTOGRAMS.prometheus_lines()
    assert any("span_errors_total" in ln and 'kind="pull"' in ln
               for ln in lines)
    obs.GLOBAL.reset()
    scope.HISTOGRAMS.reset()


def test_plane_timed_skips_recording_under_trace():
    """Inside an outer jit the dispatch body runs once per COMPILE, so a
    wall-time record there would report trace time as a step figure —
    the under_trace guard must skip recording (the compiled fn still
    computes)."""
    import jax
    import jax.numpy as jnp

    obs.GLOBAL.reset()

    def f(x):
        return obs.plane_timed("pull", "a2a", True, lambda y: y * 2, x)

    out = jax.jit(f)(jnp.ones((4,)))
    assert float(out[0]) == 2.0
    assert obs.plane_timings() == {}
    obs.GLOBAL.reset()


def test_reporter_periodic():
    acc = obs.Accumulator()
    acc.add("x", 1)
    lines = []
    rep = obs.Reporter(0.05, acc, sink=lines.append).start()
    time.sleep(0.2)
    rep.stop()
    assert lines and "x[count=1]" in lines[0]
    assert rep.ticks == len(lines)


def test_reporter_interleaving_harness_coverage():
    """The reporter daemon is schedulable like the other host threads:
    PointGate parks it at ``reporter.tick`` BEFORE any report lands, and
    opening the gate releases the (named) thread."""
    import threading
    from openembedding_tpu.analysis import concurrency

    acc = obs.Accumulator()
    acc.add("x", 1)
    lines = []
    gate = concurrency.PointGate(["reporter.tick"])
    concurrency.install_schedule(gate)
    rep = obs.Reporter(0.01, acc, sink=lines.append)
    try:
        rep.start()
        assert gate.wait_arrival("reporter.tick", timeout=10)
        assert rep.ticks == 0 and not lines      # parked pre-report
        assert any(t.name == "oe-reporter"
                   for t in threading.enumerate())
        gate.open("reporter.tick")
        deadline = time.time() + 10
        while rep.ticks == 0 and time.time() < deadline:
            time.sleep(0.01)
    finally:
        rep.stop()
        concurrency.clear_schedule()
    assert rep.ticks >= 1 and lines


def test_streaming_auc_exact_cases():
    auc = obs.StreamingAUC(bins=1000)
    # perfectly separable
    auc.update([1, 1, 0, 0], [0.9, 0.8, 0.2, 0.1])
    assert abs(auc.result() - 1.0) < 1e-9
    # random scores over many updates -> ~0.5
    auc2 = obs.StreamingAUC()
    rng = np.random.RandomState(0)
    for _ in range(20):
        labels = rng.randint(0, 2, 1000)
        auc2.update(labels, rng.rand(1000))
    assert abs(auc2.result() - 0.5) < 0.02
    # agreement with exact pairwise AUC on a small mixed case
    labels = rng.randint(0, 2, 500)
    scores = np.clip(rng.rand(500) * 0.6 + labels * 0.2, 0, 1)
    auc3 = obs.StreamingAUC()
    auc3.update(labels, scores)
    pos, neg = scores[labels > 0], scores[labels <= 0]
    exact = np.mean(pos[:, None] > neg[None, :]) \
        + 0.5 * np.mean(pos[:, None] == neg[None, :])
    assert abs(auc3.result() - exact) < 5e-3
    # degenerate: single class
    auc4 = obs.StreamingAUC()
    auc4.update([1, 1], [0.5, 0.6])
    assert auc4.result() == 0.5


def test_prometheus_text_golden(monkeypatch):
    """Golden exposition output: every series carries # HELP/# TYPE, the
    graftscope histograms render as _bucket/_sum/_count, the graftwatch
    host-memory ledger renders as oe_mem_* gauges, and label values are
    escaped — the page must stay parseable by a real Prometheus scraper
    (satellite: metric hygiene)."""
    acc = obs.Accumulator()
    acc.add("pull_indices", 512)
    acc.add_time("train_step", 0.5)
    scope.HISTOGRAMS.reset()
    scope.HISTOGRAMS.observe("span_pull_seconds", 0.25, plane="a2a")
    # deterministic memory section: only the span-ring source (emptied),
    # no leftover registered tables from earlier tests in the session;
    # gauges likewise start clean (earlier checkpoint saves in the
    # session set ckpt_* gauges), with one known value for the section
    scope.reset()
    monkeypatch.setattr(obs, "_MEM_SOURCES", {})
    monkeypatch.setattr(obs, "_GAUGES", {})
    monkeypatch.setattr(obs, "_LABELED_GAUGES", {})
    obs.set_gauge("ckpt_chain_len", 3)
    obs.set_labeled_gauge("pull_unique_ratio_last", 0.625,
                          table="clicks")
    got = obs.prometheus_text(acc)
    want = """\
# HELP oe_pull_indices_total accumulated count of `pull_indices`
# TYPE oe_pull_indices_total counter
oe_pull_indices_total 512
# HELP oe_train_step_seconds_total accumulated wall seconds of `train_step`
# TYPE oe_train_step_seconds_total counter
oe_train_step_seconds_total 0.5
# HELP oe_train_step_calls_total timed calls of `train_step`
# TYPE oe_train_step_calls_total counter
oe_train_step_calls_total 1
# HELP oe_ckpt_chain_len last-value gauge `ckpt_chain_len`
# TYPE oe_ckpt_chain_len gauge
oe_ckpt_chain_len 3
# HELP oe_pull_unique_ratio_last last-value gauge `pull_unique_ratio_last` (labeled)
# TYPE oe_pull_unique_ratio_last gauge
oe_pull_unique_ratio_last{table="clicks"} 0.625
# HELP oe_span_pull_seconds graftscope histogram `span_pull_seconds` (log-spaced buckets)
# TYPE oe_span_pull_seconds histogram
oe_span_pull_seconds_bucket{plane="a2a",le="0.3162"} 1
oe_span_pull_seconds_bucket{plane="a2a",le="+Inf"} 1
oe_span_pull_seconds_sum{plane="a2a"} 0.25
oe_span_pull_seconds_count{plane="a2a"} 1
# HELP oe_mem_approx_bytes graftwatch host-memory ledger gauge `approx_bytes` (labeled by source)
# TYPE oe_mem_approx_bytes gauge
oe_mem_approx_bytes{source="scope/rings"} 0
# HELP oe_mem_dropped graftwatch host-memory ledger gauge `dropped` (labeled by source)
# TYPE oe_mem_dropped gauge
oe_mem_dropped{source="scope/rings"} 0
# HELP oe_mem_events graftwatch host-memory ledger gauge `events` (labeled by source)
# TYPE oe_mem_events gauge
oe_mem_events{source="scope/rings"} 0
"""
    assert got == want
    # minimal scraper-side parse: every non-comment line is
    # `name{labels} value` with a float value
    for ln in got.strip().splitlines():
        if ln.startswith("#"):
            continue
        name_part, value = ln.rsplit(" ", 1)
        float(value)
        assert name_part.startswith("oe_")
    scope.HISTOGRAMS.reset()


def test_memory_stats_registry_and_weakrefs():
    """Sources register weakly: a live object's gauges appear under
    kind/name, duplicate names disambiguate, and a collected object
    falls out of the snapshot instead of being kept alive."""
    import gc

    class Src:
        def __init__(self, b):
            self.b = b

        def memory_stats(self):
            return {"bytes": self.b}

    a, b = Src(10.0), Src(20.0)
    obs.register_memory_source("test", "dup", a)
    obs.register_memory_source("test", "dup", b)
    try:
        ms = obs.memory_stats()
        assert "scope/rings" in ms
        vals = sorted(v["bytes"] for k, v in ms.items()
                      if k.startswith("test/dup"))
        assert vals == [10.0, 20.0]
        del a
        gc.collect()
        ms = obs.memory_stats()
        vals = [v["bytes"] for k, v in ms.items()
                if k.startswith("test/dup")]
        assert vals == [20.0]

        class Broken:
            def memory_stats(self):
                raise RuntimeError("mid-teardown")

        c = Broken()
        obs.register_memory_source("test", "broken", c)
        ms = obs.memory_stats()             # never raises out of a scrape
        assert not any(k.startswith("test/broken") for k in ms)
    finally:
        del b
        gc.collect()
        obs.memory_stats()                  # prune the dead refs


def test_memory_stats_offload_monotone(devices8):
    """Offload-table gauges (ISSUE 7 satellite): store/book bytes exact
    at construction, resident/planned row counters monotone-sane across
    the prepare -> apply -> evict cycle."""
    import numpy as np
    from openembedding_tpu import EmbeddingVariableMeta
    from openembedding_tpu.offload import ShardedOffloadedTable
    from openembedding_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(2, 4, devices8)
    vocab, cache = 2048, 256
    t = ShardedOffloadedTable(
        "memt", EmbeddingVariableMeta(embedding_dim=4,
                                      vocabulary_size=vocab),
        {"category": "sgd", "learning_rate": 1.0},
        {"category": "constant", "value": 0.25},
        vocab=vocab, cache_capacity=cache, mesh=mesh)
    ms = t.memory_stats()
    # store = weights + optimizer slots + int64 work ids, exactly
    assert ms["store_bytes"] == t.host_weights.nbytes \
        + t.host_work_id.nbytes \
        + sum(a.nbytes for a in t.host_slots.values())
    assert ms["store_bytes"] >= vocab * 4 * 4 + vocab * 8
    assert ms["store_memmap"] == 0.0
    assert ms["book_bytes"] == t._resident.nbytes + t._planned.nbytes \
        + t._dirty.nbytes + t._last_touch.nbytes
    assert ms["resident_rows"] == 0.0 and ms["planned_rows"] == 0.0
    assert ms["cache_capacity_rows"] == float(cache)
    # prepare marks planned rows; cancel returns them
    prep = t.host_prepare(np.arange(0, 50, dtype=np.int32))
    assert t.memory_stats()["planned_rows"] == 50.0
    t.cancel_prepared(prep)
    assert t.memory_stats()["planned_rows"] == 0.0
    # apply moves planned -> resident; an over-budget prepare evicts
    cachestate = t.create_cache()
    prep = t.host_prepare(np.arange(0, 50, dtype=np.int32))
    cachestate = t.apply_prepared(cachestate, prep)
    ms = t.memory_stats()
    assert ms["resident_rows"] == 50.0 and ms["planned_rows"] == 0.0
    prep = t.host_prepare(np.arange(100, 100 + 260, dtype=np.int32))
    assert prep.needs_evict
    cachestate = t.apply_prepared(cachestate, prep)
    ms = t.memory_stats()
    assert ms["evictions"] >= 1.0
    # eviction kept the cache bounded: the pre-evict books would hold
    # 50 + 260 rows; post-evict residency stays within one batch of the
    # nominal capacity (hash occupancy is threshold-managed, not exact)
    assert 0.0 < ms["resident_rows"] < 310.0
    # the ledger sees this table under offload/<name>
    snap = obs.memory_stats()
    key = next(k for k in snap if k.startswith("offload/memt"))
    assert snap[key]["resident_rows"] == ms["resident_rows"]


def test_memory_stats_hot_cache_refresh(devices8):
    """Hot-cache gauges: the admission sketch accounts its host RAM and
    a refresh records the replica bytes it just built."""
    import numpy as np
    import jax
    from openembedding_tpu.embedding import (EmbeddingCollection,
                                             EmbeddingSpec)
    from openembedding_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(2, 4, devices8)
    coll = EmbeddingCollection(
        (EmbeddingSpec(name="hc", input_dim=512, output_dim=4,
                       plane="a2a+cache", cache_k=16),), mesh)
    states = coll.init(jax.random.PRNGKey(0))
    mgr = coll.make_hot_cache_manager("hc")
    ms = mgr.memory_stats()
    assert ms["replica_bytes"] == 0.0 and ms["refreshes"] == 0.0
    assert ms["sketch_bytes"] > 0.0          # dense backing preallocates
    mgr.observe(np.arange(64, dtype=np.int32))
    assert mgr.memory_stats()["sketch_keys"] == 64.0
    new_state = mgr.refresh(states["hc"])
    ms = mgr.memory_stats()
    assert ms["refreshes"] == 1.0
    expect = new_state.cache.keys.nbytes + new_state.cache.rows.nbytes \
        + sum(v.nbytes for v in new_state.cache.slots.values())
    assert ms["replica_bytes"] == float(expect) > 0.0
    snap = obs.memory_stats()
    key = next(k for k in snap if k.startswith("hot_cache/hc"))
    assert snap[key]["replica_bytes"] == ms["replica_bytes"]


def test_prometheus_text_and_endpoint(devices8):
    """Accumulator -> prometheus text, scrapeable via the REST controller
    (the reference PS daemon's --enable_metrics exposer, server.cc:32-36)."""
    import urllib.request
    import jax
    from openembedding_tpu.utils import observability as obs
    from openembedding_tpu.serving.registry import ModelRegistry
    from openembedding_tpu.serving.rest import ControllerServer
    from openembedding_tpu.parallel.mesh import create_mesh

    obs.GLOBAL.reset()
    obs.GLOBAL.add("pull_indices", 512)
    with obs.vtimer("train_step"):
        pass
    text = obs.prometheus_text()
    assert "# TYPE oe_pull_indices_total counter" in text
    assert "oe_pull_indices_total 512" in text
    assert "oe_train_step_seconds_total" in text
    assert "oe_train_step_calls_total 1" in text

    reg = ModelRegistry(create_mesh(1, 1, jax.devices()[:1]))
    srv = ControllerServer(reg, port=0).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            body = r.read().decode()
        assert "oe_pull_indices_total 512" in body
        # the scrape itself ran under a request span — a LATER scrape
        # must expose the http latency histogram series. The span's
        # histogram sample lands a hair after the response bytes (the
        # handler thread exits its span after writing), so poll briefly
        # instead of racing it on a loaded box
        import time as _time
        body2 = ""
        for _ in range(40):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as r:
                body2 = r.read().decode()
            if "# TYPE oe_span_http_seconds histogram" in body2:
                break
            _time.sleep(0.05)
        assert "# TYPE oe_span_http_seconds histogram" in body2
        # the STATUS label (ISSUE 11 satellite): 4xx/5xx latency must be
        # a separate series from success latency
        assert 'oe_span_http_seconds_bucket{method="GET",' \
               'route="/metrics",status="200",le="+Inf"}' in body2
        assert 'oe_span_http_seconds_count{method="GET",' \
               'route="/metrics",status="200"}' in body2
        # per route x status request counter rides along
        assert 'oe_serving_requests_total{method="GET",' \
               'route="/metrics",status="200"}' in body2
        # a 404 lands in its OWN status series (and its own counter)
        import urllib.error
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/models/nope", timeout=5)
        for _ in range(40):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as r:
                body2 = r.read().decode()
            if 'status="404"' in body2:
                break
            _time.sleep(0.05)
        assert 'oe_span_http_seconds_count{method="GET",' \
               'route="/models",status="404"}' in body2
        assert 'oe_serving_requests_total{method="GET",' \
               'route="/models",status="404"}' in body2
        # graftwatch host-memory gauges are on the page and parse
        # scraper-side: the registry this server fronts accounts its
        # loaded models (zero here), span rings always report
        assert "# TYPE oe_mem_events gauge" in body2
        assert 'oe_mem_events{source="scope/rings"}' in body2
        assert 'oe_mem_loaded_models{source="serving/registry"} 0' \
            in body2
        for ln in body2.strip().splitlines():
            if ln.startswith("oe_mem_"):
                float(ln.rsplit(" ", 1)[1])
    finally:
        srv.stop()
        obs.GLOBAL.reset()
        scope.HISTOGRAMS.reset()
