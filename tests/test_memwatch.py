"""graftwatch memory ledger: jaxcompat shim, peak-temp contract, rows.

Covers the ISSUE-7 tentpole surface: ``compiled_memory_stats`` yields
normalized per-device numbers (and None, never a crash, on backends
without the analysis), the peak-temp bound arithmetic (pull = batch
scratch only; push earns exactly one declined-donation state
materialization; honored donation collapses the allowance), a synthetic
shard-sized-materialization injection caught at the calibrated audit
sizes, and a real lowered plane program's ledger row enforced end to
end. The full plane matrix runs in ``tools/graftcheck`` (CI).
"""

import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu.analysis import contracts, memwatch
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.utils import jaxcompat


def test_compiled_memory_stats_shim():
    compiled = jax.jit(lambda x: x * 2 + 1).lower(
        jnp.zeros((256, 64), jnp.float32)).compile()
    mem = jaxcompat.compiled_memory_stats(compiled)
    assert mem is not None
    for key in ("argument_bytes", "output_bytes", "temp_bytes",
                "alias_bytes", "generated_code_bytes", "peak_bytes"):
        assert isinstance(mem[key], int) and mem[key] >= 0, key
    assert mem["argument_bytes"] == 256 * 64 * 4
    assert mem["peak_bytes"] == max(
        0, mem["argument_bytes"] + mem["output_bytes"]
        + mem["temp_bytes"] - mem["alias_bytes"])


def test_compiled_memory_stats_degrades_to_none():
    """Backends without the analysis (or API drift that raises) must
    read as absent data, never crash an instrumented path."""

    class Raises:
        def memory_analysis(self):
            raise NotImplementedError("backend has no memory analysis")

    class ReturnsNone:
        def memory_analysis(self):
            return None

    class NoMethod:
        pass

    assert jaxcompat.compiled_memory_stats(Raises()) is None
    assert jaxcompat.compiled_memory_stats(ReturnsNone()) is None
    assert jaxcompat.compiled_memory_stats(NoMethod()) is None


_AUDIT_PARAMS = {"global_batch": 512, "dim": 16, "itemsize": 4,
                 "num_shards": 8, "num_tables": 1,
                 "table_shard_bytes": 8 << 20,
                 "state_shard_bytes": 16 << 20}


def test_peak_temp_bound_arithmetic():
    pull = contracts.peak_temp_bound(_AUDIT_PARAMS, "pull")
    push = contracts.peak_temp_bound(_AUDIT_PARAMS, "push")
    batch_scratch = contracts.TEMP_BATCH_FACTOR * 512 * 18 * 4 * 8
    assert pull == contracts.TEMP_FLOOR_BYTES + batch_scratch
    # push earns exactly one (slack-padded) unaliased state copy on top
    assert push == pull + int(contracts.TEMP_STATE_SLACK * (16 << 20))
    # donation honored (alias covers the state) -> the allowance is gone
    assert contracts.peak_temp_bound(
        _AUDIT_PARAMS, "push", alias_bytes=16 << 20) == pull


def test_peak_temp_catches_shard_sized_materialization():
    """At the calibrated audit sizes an extra table-shard-sized buffer
    in temp busts the bound for both program kinds — the memory-level
    twin of the max_copy_bytes audit."""
    shard = _AUDIT_PARAMS["table_shard_bytes"]
    # pull: legit scratch passes, scratch + one shard fails
    ok_pull = {"temp_bytes": 64 << 10, "alias_bytes": 0}
    contracts.check_peak_temp_bytes(ok_pull, _AUDIT_PARAMS,
                                    program="pull")
    with pytest.raises(contracts.ContractViolation, match="peak-temp"):
        contracts.check_peak_temp_bytes(
            {"temp_bytes": (64 << 10) + shard, "alias_bytes": 0},
            _AUDIT_PARAMS, program="pull")
    # push: the one declined-donation state copy passes, a second
    # shard-sized materialization on top fails
    state = _AUDIT_PARAMS["state_shard_bytes"]
    contracts.check_peak_temp_bytes(
        {"temp_bytes": state + (64 << 10), "alias_bytes": 0},
        _AUDIT_PARAMS, program="push")
    with pytest.raises(contracts.ContractViolation, match="peak-temp"):
        contracts.check_peak_temp_bytes(
            {"temp_bytes": state + (64 << 10) + shard, "alias_bytes": 0},
            _AUDIT_PARAMS, program="push")


def test_registered_planes_cover_the_registry():
    planes = memwatch.registered_planes()
    assert {"psum", "a2a", "a2a+cache", "a2a+grouped"} <= set(planes)


def test_plane_memory_row_enforced(devices8):
    """One real lowering end to end: the a2a pull/push ledger rows carry
    per-device numbers and PASS the enforced peak-temp contract (the
    push row exercises the declined-donation state term — the CPU
    backend never aliases)."""
    mesh = create_mesh(2, 4, devices8)
    pull = memwatch.plane_memory(mesh, "a2a", "pull", batch=256, dim=8,
                                 vocab=1 << 16, check=True)
    assert pull.mem is not None and pull.temp_bound is not None
    assert pull.mem["argument_bytes"] > 0
    # read-only pull: temp is batch scratch, far under one weights shard
    assert pull.mem["temp_bytes"] < pull.params["table_shard_bytes"]
    push = memwatch.plane_memory(mesh, "a2a", "push", batch=256, dim=8,
                                 vocab=1 << 16, check=True)
    assert push.mem is not None
    assert push.mem["temp_bytes"] <= push.temp_bound
    # the params carry the audit inputs the bound consumed
    assert push.params["state_shard_bytes"] > 0
    table = memwatch.format_memory_table([pull, push])
    assert "a2a" in table and "temp_cap" in table


def test_memory_row_without_analysis_reports_absent():
    """A backend without memory analysis yields mem=None rows (absence
    reported, not punished) — graftcheck's CLI is what escalates a
    blind ledger to a failure."""
    row = memwatch.MemoryRow(plane="a2a", program="pull", kind="array",
                             mem=None, params={})
    out = memwatch.format_memory_table([row])
    assert "n/a" in out
    assert row.as_dict()["plane"] == "a2a"
