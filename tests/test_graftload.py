"""graftload: Poisson scheduler + coordinated-omission accounting.

Pure-host lanes (no servers, no jax): the open-loop property is pinned
against a synthetic slow service — when the service stalls, the
measured quantiles must GROW (latency from intended send time), where
a closed-loop driver's clock would have flattered them — plus the
serving trajectory record schema and the p99/QPS regression gate.
"""

import threading
import time

import numpy as np
import pytest

from tools import graftload as gl
from tools import graftwatch as gw


# --- Poisson scheduler -------------------------------------------------------

def test_poisson_arrivals_shape_and_rate():
    rate, duration = 500.0, 2.0
    a = gl.poisson_arrivals(rate, duration, seed=3)
    assert a.ndim == 1 and a.size > 0
    assert float(a[0]) >= 0.0 and float(a[-1]) < duration
    assert (np.diff(a) >= 0).all()          # sorted intended times
    # count ~ Poisson(1000): 5 sigma ~ 160
    assert 840 < a.size < 1160
    # gaps are exponential with mean 1/rate (loose 15% tolerance)
    gaps = np.diff(a)
    assert abs(float(gaps.mean()) - 1.0 / rate) < 0.15 / rate
    # a Poisson process bursts: the gap cv is ~1, a metronome's is 0
    assert float(gaps.std() / gaps.mean()) > 0.7


def test_poisson_arrivals_deterministic_and_degenerate():
    a = gl.poisson_arrivals(100, 1.0, seed=7)
    b = gl.poisson_arrivals(100, 1.0, seed=7)
    np.testing.assert_array_equal(a, b)
    assert gl.poisson_arrivals(0, 1.0).size == 0
    assert gl.poisson_arrivals(100, 0.0).size == 0


# --- coordinated-omission accounting -----------------------------------------

SERVICE_S = 0.02


def _slow_send(i):
    time.sleep(SERVICE_S)


def test_open_loop_latency_measured_from_intended_time():
    """THE coordinated-omission pin: a 20 ms service stormed at 100/s
    through ONE worker can complete only ~50/s — the backlog must land
    in the measured latency (minutes-scale p99 at steady state; here
    the window bounds it), not silently slow the arrival clock. A
    closed-loop driver would report ~20 ms p99 here, flat and wrong."""
    rate, duration = 100.0, 0.5
    arrivals = gl.poisson_arrivals(rate, duration, seed=0)
    res = gl.run_storm(_slow_send, arrivals, route="synthetic",
                       offered_qps=arrivals.size / duration,
                       duration=duration, workers=1)
    assert res.errors == 0
    assert res.calls == arrivals.size
    # the LAST request waited behind ~half the backlog: far above the
    # 20 ms service time a closed-loop driver would have reported
    assert res.quantile_ms(0.99) > 5 * SERVICE_S * 1e3
    assert res.quantile_ms(0.50) > 2 * SERVICE_S * 1e3
    # and the achieved rate honestly reports the saturation
    assert res.achieved_qps < 0.75 * res.offered_qps


def test_open_loop_keeps_up_with_headroom():
    """With worker headroom and a fast service, achieved tracks offered
    and the quantiles sit near the service time."""
    rate, duration = 50.0, 0.6
    arrivals = gl.poisson_arrivals(rate, duration, seed=1)
    res = gl.run_storm(lambda i: time.sleep(0.001), arrivals,
                       route="synthetic",
                       offered_qps=arrivals.size / duration,
                       duration=duration, workers=8)
    assert res.errors == 0
    assert res.achieved_qps > 0.8 * res.offered_qps
    # generous bound: CI boxes jitter, but nothing should queue
    assert res.quantile_ms(0.50) < 100.0


def test_storm_counts_errors_without_crashing():
    def flaky(i):
        if i % 3 == 0:
            raise RuntimeError("boom")

    arrivals = gl.poisson_arrivals(200, 0.2, seed=2)
    res = gl.run_storm(flaky, arrivals, route="synthetic",
                       offered_qps=arrivals.size / 0.2, duration=0.2,
                       workers=4)
    assert res.errors > 0
    assert res.calls == arrivals.size
    assert res.latencies_ms.size == arrivals.size - res.errors
    assert 0.0 < res.error_rate < 1.0
    assert "boom" in getattr(res, "first_error", "")


def test_storm_runs_concurrently_from_worker_pool():
    """The pool really overlaps requests: 8 workers on a 20 ms service
    must beat the serial wall by a wide margin."""
    seen = []
    lock = threading.Lock()

    def send(i):
        with lock:
            seen.append(threading.current_thread().name)
        time.sleep(SERVICE_S)

    arrivals = np.linspace(0.0, 0.1, 32)
    t0 = time.perf_counter()
    res = gl.run_storm(send, arrivals, route="synthetic",
                       offered_qps=320.0, duration=0.1, workers=8)
    wall = time.perf_counter() - t0
    assert res.errors == 0
    assert wall < 32 * SERVICE_S * 0.8          # serial would be 640 ms
    assert len({n for n in seen}) > 1           # >1 worker actually sent


def test_find_knee():
    # built via the real accounting (achieved ~ samples/duration), so
    # the knee rule is tested against StormResult itself
    def real(offered, n, errors=0):
        lat = np.full(n, 1.0)
        arr = np.linspace(0, 0.99, n)
        return gl.StormResult("rest", offered, 1.0, lat, arr, errors)

    rs = [real(100, 100), real(200, 198), real(400, 220)]
    knee = gl.find_knee(rs)
    assert knee is not None and knee.offered_qps == 200
    # errors disqualify a rate outright
    rs = [real(100, 100, errors=1)]
    assert gl.find_knee(rs) is None


# --- serving trajectory records + the latency gate ---------------------------

_FP = "cpu8-test-c2"
_DEV = {"platform": "cpu", "n_devices": 8, "device_kind": "cpu"}


def _serving_record(ts, qps=200.0, p99=8.0, batched=False):
    kwargs = {}
    config = {"source": "graftload", "qps": 200.0, "duration": 5.0,
              "batch": 16, "workers": 32, "path": "both",
              "replicas": 2, "sweep": False, "chaos": False}
    if batched:
        config["batched"] = True
        kwargs = {"rejected": 3,
                  "batch_stats": {"batch_flushes": 120.0,
                                  "batch_requests": 400.0,
                                  "batch_rows": 6400.0,
                                  "batch_unique_rows": 5200.0}}
    return gw.make_serving_record(
        routes={"rest": {"calls": 400, "p50_ms": 2.0, "p95_ms": 5.0,
                         "p99_ms": p99},
                "native": {"calls": 400, "p50_ms": 0.5, "p95_ms": 1.0,
                           "p99_ms": 2.0}},
        offered_qps=qps * 1.02, achieved_qps=qps, errors=0, replicas=2,
        qps_band=(qps * 0.9, qps * 1.1), config=config,
        fingerprint=_FP, device=_DEV, ts=ts, **kwargs)


def test_serving_record_schema_roundtrip():
    rec = _serving_record("2026-08-01T00:00:00+00:00")
    assert gw.validate_record(rec) == []
    assert rec["plane"] == "serving"
    assert rec["serving"]["replicas"] == 2
    assert rec["scope"]["rest"]["p99_ms"] == 8.0


def test_serving_record_batched_stats_roundtrip():
    """The batched arm's record carries the backpressure/coalescing
    stats and stays schema-valid; its config keys a SEPARATE baseline
    group from the unbatched arm."""
    rec = _serving_record("2026-08-01T00:00:00+00:00", batched=True)
    assert gw.validate_record(rec) == []
    assert rec["serving"]["rejected"] == 3
    assert rec["serving"]["batch"]["batch_flushes"] == 120.0
    plain = _serving_record("2026-08-01T00:00:00+00:00")
    assert gw._group_key(rec) != gw._group_key(plain)


@pytest.mark.parametrize("mutate,fragment", [
    (lambda r: r["serving"].pop("achieved_qps"), "achieved_qps"),
    (lambda r: r["serving"].update(offered_qps=-1), "offered_qps"),
    (lambda r: r["serving"].update(errors=-2), "errors"),
    (lambda r: r["serving"].update(replicas=0), "replicas"),
    (lambda r: r["scope"]["rest"].update(p99_ms="fast"), "p99_ms"),
    (lambda r: r["serving"].update(rejected=-1), "rejected"),
    (lambda r: r["serving"].update(rejected=True), "rejected"),
    (lambda r: r["serving"].update(batch="lots"), "batch"),
    (lambda r: r["serving"].update(batch={"batch_rows": -4.0}),
     "batch.batch_rows"),
    (lambda r: r["serving"].update(batch={"batch_rows": "many"}),
     "batch.batch_rows"),
])
def test_serving_record_schema_lists_problems(mutate, fragment):
    rec = _serving_record("2026-08-01T00:00:00+00:00")
    mutate(rec)
    problems = gw.validate_record(rec)
    assert problems and any(fragment in p for p in problems), problems


def test_gate_fails_on_2x_p99_regression(tmp_path):
    """THE acceptance-criterion negative: same sustained QPS, p99
    doubled -> the serving group regresses (latency quantiles gate like
    throughput) and the CLI exits 1. Dropping the injected record
    gates clean again."""
    import json
    records = [_serving_record(f"2026-08-0{d}T00:00:00+00:00")
               for d in (1, 2, 3)]
    records.append(_serving_record("2026-08-04T00:00:00+00:00",
                                   p99=16.0))
    failures, lines = gw.gate(records)
    assert failures >= 1
    assert any("REGRESSION" in ln and "rest_p99_ms" in ln
               for ln in lines), lines
    path = tmp_path / "t.jsonl"
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    assert gw.main(["--gate", "--trajectory", str(path)]) == 1
    with open(path, "w") as f:
        for r in records[:-1]:
            f.write(json.dumps(r) + "\n")
    assert gw.main(["--gate", "--trajectory", str(path)]) == 0


def test_gate_fails_on_sustained_qps_drop():
    records = [_serving_record(f"2026-08-0{d}T00:00:00+00:00")
               for d in (1, 2, 3)]
    records.append(_serving_record("2026-08-04T00:00:00+00:00",
                                   qps=90.0))
    failures, lines = gw.gate(records)
    assert failures >= 1
    assert any("REGRESSION" in ln and "/eps" in ln for ln in lines), lines
