"""Streaming ingest: shard pool determinism, bad-row/crash lanes, ring
bounds + ledger, stall accounting, and the pipelined-plane integration
(prime-once + bit-identical vs in-memory)."""

import threading
import time
import warnings

import numpy as np
import pytest

from openembedding_tpu.data import criteo, stream, tfrecord
from openembedding_tpu.utils import observability


def _shards(tmp_path, **kw):
    d = str(tmp_path / "shards")
    kw.setdefault("num_shards", 4)
    kw.setdefault("rows_per_shard", 512)
    return d, stream.write_synthetic_shards(d, **kw)


# --- synthetic source --------------------------------------------------------

def test_synthetic_shards_are_real_criteo_tsv(tmp_path):
    """The generated shards parse through the PORTABLE reference reader
    (same row grammar as raw Criteo TSV) and carry zipf-skewed ids."""
    d, paths = _shards(tmp_path, num_shards=2, rows_per_shard=600, seed=3)
    assert [p.endswith(".tsv") for p in paths] == [True, True]
    batches = list(criteo.read_criteo_tsv(paths[0], 100,
                                          num_buckets=1 << 16))
    assert len(batches) == 6
    b = batches[0]
    assert b["dense"].shape == (100, criteo.NUM_DENSE)
    assert set(b["sparse"]) == set(criteo.SPARSE_NAMES)
    # zipf marginals: the top key of a column owns far more than a
    # uniform draw would (600 rows over 2^16 buckets ~ all-unique)
    col = np.concatenate([bb["sparse"]["C1"] for bb in batches])
    _, counts = np.unique(col, return_counts=True)
    assert counts.max() >= 20   # zipf(1.2): id 1 alone is ~35% of draws
    # deterministic per (seed, shard)
    d2 = str(tmp_path / "again")
    paths2 = stream.write_synthetic_shards(d2, num_shards=2,
                                           rows_per_shard=600, seed=3)
    assert open(paths[1]).read() == open(paths2[1]).read()


def test_synthetic_tfrecord_shards_roundtrip(tmp_path):
    d = str(tmp_path)
    paths = stream.write_synthetic_shards(d, num_shards=1,
                                          rows_per_shard=40,
                                          fmt="tfrecord", seed=1)
    recs = list(tfrecord.read_records(paths[0]))
    assert len(recs) == 40
    ex = tfrecord.parse_example(recs[0])
    assert set(ex) == {"label"} | set(criteo.DENSE_NAMES) \
        | set(criteo.SPARSE_NAMES)


# --- determinism + parity with the reference reader --------------------------

def test_stream_deterministic_across_runs(tmp_path):
    d, _ = _shards(tmp_path, num_shards=4, rows_per_shard=300, seed=5)

    def collect():
        s = stream.ShardStream(d, batch_size=64, readers=3,
                               ring_batches=6, epochs=1,
                               num_buckets=1 << 12)
        try:
            return list(s)
        finally:
            s.close()

    a, b = collect(), collect()
    assert len(a) == len(b) > 0
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x["label"], y["label"])
        np.testing.assert_array_equal(x["dense"], y["dense"])
        for n in criteo.SPARSE_NAMES:
            np.testing.assert_array_equal(x["sparse"][n], y["sparse"][n])


def test_single_reader_matches_reference_reader(tmp_path):
    """readers=1 over one shard == criteo.read_criteo_tsv exactly (the
    stream is the reference reader's parallel form, not a new format)."""
    d, paths = _shards(tmp_path, num_shards=1, rows_per_shard=500, seed=7)
    ref = list(criteo.read_criteo_tsv(paths[0], 128,
                                      num_buckets=1 << 14))
    s = stream.ShardStream(paths, batch_size=128, readers=1, epochs=1,
                           num_buckets=1 << 14)
    try:
        got = list(s)
    finally:
        s.close()
    assert len(got) == len(ref)
    for x, y in zip(got, ref):
        np.testing.assert_array_equal(x["dense"], y["dense"])
        for n in criteo.SPARSE_NAMES:
            np.testing.assert_array_equal(x["sparse"][n], y["sparse"][n])


def test_add_linear_and_transform_run_on_worker(tmp_path):
    d, _ = _shards(tmp_path, num_shards=1, rows_per_shard=128, seed=2)
    tids = []

    def xform(b):
        tids.append(threading.get_ident())
        return {**b, "tag": True}

    s = stream.ShardStream(d, batch_size=64, epochs=1, add_linear=True,
                           transform=xform, num_buckets=1 << 12)
    try:
        batches = list(s)
    finally:
        s.close()
    assert batches and all(b.get("tag") for b in batches)
    np.testing.assert_array_equal(batches[0]["sparse"]["C3"],
                                  batches[0]["sparse"]["C3:linear"])
    assert threading.get_ident() not in tids   # parsed off the consumer


# --- bad rows (satellite bugfix) ---------------------------------------------

def test_tsv_reader_skips_bad_rows_with_counter_and_warning(tmp_path):
    """The portable reader survives a corrupted shard: short lines and
    non-hex categoricals are skipped + counted (`ingest_bad_rows`),
    with one loud threshold warning — previously `int(v, 16)` crashed
    the whole stream on the first non-hex value."""
    d, paths = _shards(tmp_path, num_shards=1, rows_per_shard=500,
                       seed=1, bad_rows_per_shard=40)
    observability.GLOBAL.reset()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        batches = list(criteo.read_criteo_tsv(paths[0], 100,
                                              num_buckets=1 << 12,
                                              drop_remainder=False))
    rows = sum(b["label"].shape[0] for b in batches)
    assert rows == 500 - 40
    snap = observability.GLOBAL.snapshot()
    assert snap["ingest_bad_rows"]["count"] == 40
    # the warning fires ONCE, as soon as the cumulative bad fraction
    # crosses the threshold with >= 32 bad rows seen
    msgs = [str(x.message) for x in w
            if issubclass(x.category, RuntimeWarning)]
    assert len(msgs) == 1 and "unparseable" in msgs[0]


def test_stream_bad_rows_counted_not_fatal(tmp_path):
    d, _ = _shards(tmp_path, num_shards=2, rows_per_shard=400, seed=4,
                   bad_rows_per_shard=30)
    s = stream.ShardStream(d, batch_size=64, readers=2, epochs=1,
                           num_buckets=1 << 12)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            batches = list(s)
        assert s.bad_rows() == 60
    finally:
        s.close()
    # reader-local batching: each reader drops its own remainder
    assert sum(b["label"].shape[0] for b in batches) \
        == 2 * ((400 - 30) // 64) * 64


def test_clean_fixture_never_warns(tmp_path):
    d, paths = _shards(tmp_path, num_shards=1, rows_per_shard=64, seed=9)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        list(criteo.read_criteo_tsv(paths[0], 16, num_buckets=1 << 10))
    assert not [x for x in w if issubclass(x.category, RuntimeWarning)]


# --- reader crash / truncation lanes -----------------------------------------

def _truncate(path, frac=0.5, extra=7):
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:int(len(raw) * frac) + extra])


def test_dead_reader_fails_epoch_loudly_never_hangs(tmp_path):
    """Mid-file TFRecord truncation: the reader dies, the NEXT consumer
    pop raises (naming reader + shard) within a bounded wait — never a
    hang, never a silently short epoch — and the stream stays failed."""
    d = str(tmp_path)
    paths = stream.write_synthetic_shards(d, num_shards=2,
                                          rows_per_shard=200,
                                          fmt="tfrecord", seed=2)
    _truncate(paths[1])
    s = stream.ShardStream(d, fmt="tfrecord", batch_size=32, readers=2,
                           epochs=1, num_buckets=1 << 12)
    t0 = time.time()
    with pytest.raises(RuntimeError, match="reader 1 .* failed"):
        for _ in s:
            pass
    assert time.time() - t0 < 30
    with pytest.raises(RuntimeError, match="already failed"):
        next(s)
    s.close()


def test_short_read_header_truncation_fails(tmp_path):
    """A TFRecord cut inside the 12-byte header is container damage:
    IOError out of the frame reader -> loud epoch failure."""
    d = str(tmp_path)
    paths = stream.write_synthetic_shards(d, num_shards=1,
                                          rows_per_shard=50,
                                          fmt="tfrecord", seed=6)
    raw = open(paths[0], "rb").read()
    open(paths[0], "wb").write(raw + b"\x07\x00\x00")   # dangling header
    s = stream.ShardStream(paths, fmt="tfrecord", batch_size=16,
                           readers=1, epochs=1, drop_remainder=False,
                           num_buckets=1 << 12)
    with pytest.raises(RuntimeError, match="truncated TFRecord"):
        for _ in s:
            pass
    s.close()


def test_missing_shard_dir_fails_at_construction(tmp_path):
    with pytest.raises(FileNotFoundError):
        stream.discover_shards(str(tmp_path), "tsv")


def test_close_mid_stream_joins_readers(tmp_path):
    d, _ = _shards(tmp_path, num_shards=2, rows_per_shard=400, seed=8)
    s = stream.ShardStream(d, batch_size=32, readers=2, epochs=None,
                           ring_batches=4, num_buckets=1 << 12)
    next(s)
    s.close()
    assert all(not t.is_alive() for t in s._threads)
    with pytest.raises(StopIteration):
        next(s)


# --- ring bounds, ledger, stall accounting -----------------------------------

def test_ring_bounded_and_memory_ledger(tmp_path):
    d, _ = _shards(tmp_path, num_shards=2, rows_per_shard=600, seed=3)
    s = stream.ShardStream(d, batch_size=50, readers=2, ring_batches=4,
                           epochs=None, num_buckets=1 << 12,
                           name="ledger_test")
    try:
        next(s)
        time.sleep(0.5)   # paused consumer: readers fill to the bound
        st = s.memory_stats()
        assert st["ring_batches"] <= st["ring_capacity_batches"] == 4.0
        assert st["ring_bytes"] > 0
        # registered as an oe_mem_* source for /metrics
        mem = observability.memory_stats()
        assert "ingest/ledger_test" in mem
        assert mem["ingest/ledger_test"]["ring_capacity_batches"] == 4.0
    finally:
        s.close()


def test_stall_accounting_exact_zero_when_ready(tmp_path):
    """A pop that finds data ready records EXACTLY 0.0 (the p95==0
    claim is over literal zeros); a pop that waits records the wait."""
    d, _ = _shards(tmp_path, num_shards=1, rows_per_shard=300, seed=5)
    # slow producer: the transform sleeps on the worker
    s = stream.ShardStream(d, batch_size=100, readers=1, epochs=1,
                           num_buckets=1 << 12,
                           transform=lambda b: (time.sleep(0.05), b)[1])
    try:
        list(s)
        stalled = s.stall_summary()
        assert stalled["stalled"] >= 1 and stalled["max_ms"] > 0
    finally:
        s.close()
    # fast producer + slow consumer: zero stalls, exactly
    s2 = stream.ShardStream(d, batch_size=100, readers=1, epochs=1,
                            num_buckets=1 << 12)
    try:
        time.sleep(0.3)
        out = []
        for b in s2:
            out.append(b)
            time.sleep(0.02)
        st = s2.stall_stats()
        assert st.size == len(out) and (st == 0.0).all()
        assert s2.stall_summary()["p95_ms"] == 0.0
    finally:
        s2.close()
    # reset drops history
    s2.reset_stall_stats()
    assert s2.stall_stats().size == 0


def test_record_ingest_stall_counter_and_histogram():
    from openembedding_tpu.analysis import scope
    acc = observability.Accumulator()
    before = scope.HISTOGRAMS.count("ingest_stall_ms")
    observability.record_ingest_stall(0.002, accumulator=acc)
    observability.record_ingest_stall(0.0, accumulator=acc)
    snap = acc.snapshot()
    assert snap["ingest_stall"]["calls"] == 2
    assert abs(snap["ingest_stall"]["seconds"] - 0.002) < 1e-9
    assert scope.HISTOGRAMS.count("ingest_stall_ms") == before + 2
    assert stream.ShardStream.ingest_accounted is True


# --- pipelined-plane integration (slow: two full fit runs) -------------------

@pytest.mark.slow
def test_streamed_batches_prime_pipeline_once_and_train_bit_identical(
        tmp_path):
    """The tentpole contract: identity-stable streamed batches prime
    the pipelined plane EXACTLY once over a steady fit
    (`pipeline_primes` == 1 — a rebuilding driver would re-prime every
    step and pay a double exchange), and live-streamed training is
    BIT-identical to the same shard data materialized in memory."""
    import jax
    import optax
    from openembedding_tpu import EmbeddingCollection, Trainer
    from openembedding_tpu.fused import make_fused_specs
    from openembedding_tpu.models import deepctr
    from openembedding_tpu.parallel.mesh import create_mesh

    d, _ = _shards(tmp_path, num_shards=2, rows_per_shard=512, seed=11)
    mesh = create_mesh(2, 4)

    def run(live):
        specs, mapper = make_fused_specs(
            tuple(criteo.SPARSE_NAMES), 1 << 12, 4,
            optimizer={"category": "adagrad", "learning_rate": 0.01},
            plane="a2a+pipelined")
        coll = EmbeddingCollection(specs, mesh)
        tr = Trainer(deepctr.build_model("deepfm",
                                         tuple(criteo.SPARSE_NAMES)),
                     coll, optax.adagrad(0.01))
        s = stream.ShardStream(d, batch_size=128, readers=2, epochs=1,
                               num_buckets=1 << 12,
                               transform=mapper.fuse_batch)
        try:
            if live:
                import itertools
                it = iter(s)
                first = next(it)
                src = itertools.chain([first], it)
            else:
                src = list(s)
                first = src[0]
            state = tr.init(jax.random.PRNGKey(0),
                            tr.shard_batch(first))
            observability.GLOBAL.reset()
            state, m = tr.fit(state, src)
            snap = observability.GLOBAL.snapshot()
            primes = snap["pipeline_primes"]["count"]
            stall_calls = snap.get("ingest_stall", {}).get("calls", 0)
            stalls = s.stall_summary()
        finally:
            s.close()
        return (tr.drain_pipeline(state), float(m["loss"]), primes,
                stalls, stall_calls, tr.pipeline_depth)

    st_mem, loss_mem, primes_mem, _, _, _ = run(live=False)
    (st_live, loss_live, primes_live, stalls, stall_calls,
     depth) = run(live=True)
    assert primes_mem == primes_live == 1.0
    assert loss_mem == loss_live
    # no double-counting through the chain wrapper: the stream records
    # each pop itself; fit may add at most one ~0 record per drain step
    # after the stream exhausts (plus the post-prime refill) — a 2x
    # count here means fit re-timed waits the stream already accounted
    assert stall_calls <= stalls["pops"] + depth + 1, \
        (stall_calls, stalls["pops"], depth)
    for a, b in zip(jax.tree.leaves(st_mem.emb),
                    jax.tree.leaves(st_live.emb)):
        assert bool((np.asarray(a) == np.asarray(b)).all())
    # every pop recorded a stall sample (0.0 when ready)
    assert stalls["pops"] == 8
