"""Grouped multi-table exchange plane (``parallel/grouped.py``).

``plane="a2a+grouped"`` must be EXACTLY equivalent to the per-table
``"a2a"`` loop — the grouping only changes how many collective rounds a
step launches (one per GROUP of same-shape tables, not one per table).
The parity matrix drives both planes through the public collection API
on identical data + seeds: zipf/uniform streams x array/hash32/hash-wide
tables x mixed dims in one group x a pooled member, pulls and optimizer
state compared allclose every step. Planner unit tests pin the static
grouping key; counter tests pin the observability surface.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
from openembedding_tpu import hash_table as hash_lib
from openembedding_tpu.parallel import grouped
from openembedding_tpu.parallel.mesh import create_mesh
from openembedding_tpu.utils import observability

OPT = {"category": "adagrad", "learning_rate": 0.1}
INIT = {"category": "constant", "value": 0.25}
B, L = 32, 4


def _specs(kind, plane):
    """Four tables: dims 3+4 share one bucket (mixed dims in ONE group),
    dim 6 forms a second bucket, plus a pooled dim-3 member riding the
    first group — every satellite axis inside one collection."""
    common = dict(optimizer=OPT, initializer=INIT, plane=plane)
    if kind == "array":
        return (
            EmbeddingSpec(name="t3", input_dim=64, output_dim=3, **common),
            EmbeddingSpec(name="t4", input_dim=96, output_dim=4, **common),
            EmbeddingSpec(name="t6", input_dim=48, output_dim=6, **common),
            EmbeddingSpec(name="tp", input_dim=64, output_dim=3,
                          pooling="mean", **common),
        )
    key_dtype = "int32" if kind == "hash32" else "wide"
    hk = dict(input_dim=-1, hash_capacity=4096, key_dtype=key_dtype,
              **common)
    return (
        EmbeddingSpec(name="t3", output_dim=3, **hk),
        EmbeddingSpec(name="t4", output_dim=4, **hk),
        EmbeddingSpec(name="t6", output_dim=6, **hk),
        EmbeddingSpec(name="tp", output_dim=3, pooling="sum", **hk),
    )


def _draw(rng, dist, hi, size):
    if dist == "uniform":
        return rng.randint(0, hi, size).astype(np.int64)
    ranks = np.arange(1, hi + 1, dtype=np.float64)
    probs = ranks ** -1.1
    probs /= probs.sum()
    return rng.choice(hi, size=size, p=probs).astype(np.int64)


def _batch(rng, kind, dist):
    """Flat id columns for t3/t4/t6 + a padded [B, L] matrix for tp.
    Array streams include OUT-OF-RANGE ids (negative and beyond vocab):
    the per-table path zero-rows/drops them and grouped must too."""
    if kind == "array":
        vocab = {"t3": 64, "t4": 96, "t6": 48}
        out = {n: _draw(rng, dist, v, B).astype(np.int32)
               for n, v in vocab.items()}
        out["t4"][::7] = -1
        out["t4"][1::9] = 96 + 5
        pool = _draw(rng, dist, 64, (B, L)).astype(np.int32)
        pool[:, -1] = -1          # ragged padding
        out["tp"] = pool
        return out
    out = {n: _draw(rng, dist, 100_000, B) for n in ("t3", "t4", "t6")}
    pad = hash_lib.empty_key(np.int64)
    pool = _draw(rng, dist, 100_000, (B, L))
    pool[:, -1] = pad
    out["tp"] = pool
    return out


def _assert_state_close(sg, sa, kind, msg):
    for n in ("t3", "t4", "t6", "tp"):
        np.testing.assert_allclose(
            np.asarray(sg[n].weights), np.asarray(sa[n].weights),
            rtol=1e-5, atol=1e-6, err_msg=f"{msg}:{n}:weights")
        for slot in sg[n].slots:
            np.testing.assert_allclose(
                np.asarray(sg[n].slots[slot]),
                np.asarray(sa[n].slots[slot]),
                rtol=1e-5, atol=1e-6, err_msg=f"{msg}:{n}:{slot}")
        if kind != "array":
            assert int(sg[n].insert_failures) == \
                int(sa[n].insert_failures), n


# the full 6-cell matrix; two cells ride tier-1 (one array, one wide
# hash — the two exchange encodings), the re-compiled rest (same code
# paths, different key streams/dtypes) rides the slow lane for budget
_MATRIX = [("array", "zipf"), ("wide", "zipf"),
           pytest.param("hash32", "uniform", marks=pytest.mark.slow),
           pytest.param("array", "uniform", marks=pytest.mark.slow),
           pytest.param("hash32", "zipf", marks=pytest.mark.slow),
           pytest.param("wide", "uniform", marks=pytest.mark.slow)]


@pytest.mark.parametrize("kind,dist", _MATRIX)
def test_grouped_matches_per_table(devices8, kind, dist):
    mesh = create_mesh(2, 4, devices8)
    cg = EmbeddingCollection(_specs(kind, "a2a+grouped"), mesh)
    ca = EmbeddingCollection(_specs(kind, "a2a"), mesh)
    assert cg.grouped_names() == ("t3", "t4", "t6", "tp")
    sg = cg.init(jax.random.PRNGKey(3))
    sa = ca.init(jax.random.PRNGKey(3))
    rng = np.random.RandomState(7)
    for step in range(2):
        inp = _batch(rng, kind, dist)
        rg, ra = cg.pull(sg, inp), ca.pull(sa, inp)
        for n in inp:
            np.testing.assert_allclose(
                np.asarray(rg[n]), np.asarray(ra[n]),
                rtol=1e-5, atol=1e-6, err_msg=f"pull:{n}")
        grads = {n: jnp.asarray(
            rng.randn(*np.asarray(ra[n]).shape).astype(np.float32))
            for n in inp}
        sg = cg.apply_gradients(sg, inp, grads)
        sa = ca.apply_gradients(sa, inp, grads)
    _assert_state_close(sg, sa, kind, f"{kind}/{dist}")
    if kind != "array":
        # read-only (serving) contract: missing keys -> zeros, grouped too
        probe = {"t3": np.arange(50, 150).astype(np.int64)}
        pg = cg.pull(sg, probe, read_only=True)["t3"]
        pa = ca.pull(sa, probe, read_only=True)["t3"]
        np.testing.assert_allclose(np.asarray(pg), np.asarray(pa),
                                   rtol=1e-5, atol=1e-6)


def test_plan_groups_static_key(devices8):
    """The planner's grouping key: dim BUCKET (3 and 4 share 4; 6 takes
    8), array vs hash, key width — members keep registration order and
    array groups carry fused-style offset bases over padded vocabs."""
    mesh = create_mesh(2, 4, devices8)
    coll = EmbeddingCollection(_specs("array", "a2a+grouped"), mesh)
    plans = grouped.plan_groups(coll, ("t3", "t4", "t6", "tp"))
    shape = [(p.kind, p.bucket_dim, tuple(m.name for m in p.members))
             for p in plans]
    assert shape == [("array", 4, ("t3", "t4", "tp")),
                     ("array", 8, ("t6",))]
    # bases = exclusive prefix sums of PADDED vocabs (64 -> 64, 96 -> 96
    # on 8 shards, 64 -> 64)
    assert plans[0].bases == (0, 64, 160, 224)

    mixed = EmbeddingCollection(
        _specs("hash32", "a2a+grouped")[:2]
        + _specs("wide", "a2a+grouped")[2:], mesh)
    plans = grouped.plan_groups(mixed, ("t3", "t4", "t6", "tp"))
    key = {tuple(m.name for m in p.members): (p.kind, p.key_dtype)
           for p in plans}
    # int32 and wide keys can never share a stream; dim 6 buckets apart
    assert key == {("t3", "t4"): ("hash", "int32"),
                   ("t6",): ("hash", "wide"),
                   ("tp",): ("hash", "wide")}


def test_plan_groups_offset_span_split(devices8):
    """Array groups split when the concatenated padded vocabs would
    overflow the int32 offset space — no silent aliasing at scale."""
    mesh = create_mesh(2, 4, devices8)
    specs = tuple(
        EmbeddingSpec(name=f"big{i}", input_dim=1 << 30, output_dim=4,
                      optimizer=OPT, initializer=INIT, plane="a2a+grouped")
        for i in range(3))
    coll = EmbeddingCollection(specs, mesh)
    plans = grouped.plan_groups(coll, tuple(s.name for s in specs))
    assert [len(p.members) for p in plans] == [1, 1, 1]
    assert all(p.bases[-1] <= 2**31 - 1 for p in plans)


def test_plan_groups_rejects_other_planes(devices8):
    mesh = create_mesh(2, 4, devices8)
    coll = EmbeddingCollection(_specs("array", "a2a"), mesh)
    with pytest.raises(ValueError, match="a2a\\+grouped"):
        grouped.plan_groups(coll, ("t3",))


def test_grouped_composes_with_cache_plane(devices8):
    """Mixed-plane collection: grouped tables batch, a cached table keeps
    its replica path, a psum table keeps its ablation program — state
    parity vs the all-a2a baseline on every variable."""
    mesh = create_mesh(2, 4, devices8)

    def specs(planes):
        return tuple(
            EmbeddingSpec(name=n, input_dim=64, output_dim=4,
                          optimizer=OPT, initializer=INIT, plane=p)
            for n, p in planes.items())

    mixed = {"g1": "a2a+grouped", "g2": "a2a+grouped",
             "hot": "a2a+cache", "base": "psum"}
    cm = EmbeddingCollection(specs(mixed), mesh)
    ca = EmbeddingCollection(specs({n: "a2a" for n in mixed}), mesh)
    assert cm.grouped_names() == ("g1", "g2")
    sm, sa = cm.init(jax.random.PRNGKey(5)), ca.init(jax.random.PRNGKey(5))
    rng = np.random.RandomState(11)
    for _ in range(2):
        inp = {n: rng.randint(0, 64, B).astype(np.int32) for n in mixed}
        rm, ra = cm.pull(sm, inp), ca.pull(sa, inp)
        for n in mixed:
            np.testing.assert_allclose(np.asarray(rm[n]),
                                       np.asarray(ra[n]),
                                       rtol=1e-5, atol=1e-6, err_msg=n)
        grads = {n: jnp.asarray(rng.randn(B, 4).astype(np.float32))
                 for n in mixed}
        sm = cm.apply_gradients(sm, inp, grads)
        sa = ca.apply_gradients(sa, inp, grads)
    # final-state parity via a full-vocab probe pull: the psum member
    # stores rows in a different physical shard interleaving (4 model
    # shards vs 8 whole-mesh shards), so raw weights are not comparable
    # across planes — logical rows are
    probe = {n: np.arange(64, dtype=np.int32) for n in mixed}
    pm, pa = cm.pull(sm, probe), ca.pull(sa, probe)
    for n in mixed:
        np.testing.assert_allclose(np.asarray(pm[n]), np.asarray(pa[n]),
                                   rtol=1e-5, atol=1e-6, err_msg=n)


def test_per_table_fallback_on_grouped_spec(devices8):
    """A grouped-plane table addressed PER TABLE (serving probes, the
    checkpoint loader, hot-cache style direct calls) takes the plain a2a
    program — same rows, same updates as an a2a-spec table."""
    from openembedding_tpu.meta import EmbeddingVariableMeta
    from openembedding_tpu.parallel import sharded_table as st

    mesh = create_mesh(2, 4, devices8)
    meta = EmbeddingVariableMeta(embedding_dim=4, vocabulary_size=64)
    states, specs = {}, {}
    for plane in ("a2a", "a2a+grouped"):
        specs[plane] = st.make_sharding_spec(meta, mesh, plane=plane)
        states[plane] = st.create_sharded_table(
            meta, OPT, INIT, mesh=mesh, spec=specs[plane],
            rng=jax.random.PRNGKey(2))
    idx = np.arange(64, dtype=np.int32)
    rows = {p: st.pull_sharded(states[p], idx, mesh=mesh, spec=specs[p])
            for p in specs}
    np.testing.assert_allclose(np.asarray(rows["a2a+grouped"]),
                               np.asarray(rows["a2a"]), rtol=1e-5)
    g = jnp.asarray(np.random.RandomState(0)
                    .randn(64, 4).astype(np.float32))
    from openembedding_tpu.optim.optimizers import make_optimizer
    for p in specs:
        states[p] = st.apply_gradients_sharded(
            states[p], make_optimizer(OPT), idx, g, mesh=mesh,
            spec=specs[p])
    np.testing.assert_allclose(np.asarray(states["a2a+grouped"].weights),
                               np.asarray(states["a2a"].weights),
                               rtol=1e-5, atol=1e-6)


def test_grouped_counters_and_plane_timings(devices8):
    """Gated observability: grouped_groups / grouped_exchange_bytes count
    per dispatch, and the per-plane pull/push wall-time split lands under
    pull/a2a+grouped so A/B runs attribute time to the exchange."""
    mesh = create_mesh(2, 4, devices8)
    coll = EmbeddingCollection(_specs("array", "a2a+grouped"), mesh)
    states = coll.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    inp = _batch(rng, "array", "uniform")
    observability.GLOBAL.reset()
    rows = coll.pull(states, inp)          # gate off: nothing recorded
    assert "grouped_groups" not in observability.GLOBAL.snapshot()
    observability.set_evaluate_performance(True)
    try:
        rows = coll.pull(states, inp)
        grads = {n: jnp.asarray(
            rng.randn(*np.asarray(rows[n]).shape).astype(np.float32))
            for n in inp}
        coll.apply_gradients(states, inp, grads)
    finally:
        observability.set_evaluate_performance(False)
    snap = observability.GLOBAL.snapshot()
    # 2 groups per dispatch (bucket 4 + bucket 8), pull + push = 4
    assert snap["grouped_groups"]["count"] == 4
    assert snap["grouped_exchange_bytes"]["count"] > 0
    timings = observability.plane_timings()
    assert timings["a2a+grouped"]["pull_calls"] == 2
    assert timings["a2a+grouped"]["push_calls"] == 2
    assert timings["a2a+grouped"]["pull_ms"] >= 0.0
    observability.GLOBAL.reset()


@pytest.mark.slow
def test_trainer_loss_parity_grouped_vs_a2a(devices8):
    """End-to-end: a DeepFM Trainer on the grouped plane reproduces the
    per-table plane's loss trajectory exactly (sgd + constant init)."""
    import optax
    from openembedding_tpu import Trainer
    from openembedding_tpu.models import deepctr

    mesh = create_mesh(2, 4, devices8)
    feats = ("c0", "c1")
    rng = np.random.RandomState(0)
    vocab = 512
    batches = []
    for _ in range(5):
        sparse = {f: rng.randint(0, vocab, 64).astype(np.int32)
                  for f in feats}
        for f in feats:
            sparse[f + deepctr.LINEAR_SUFFIX] = sparse[f]
        batches.append({
            "label": (rng.rand(64) > 0.5).astype(np.float32),
            "dense": rng.randn(64, 4).astype(np.float32),
            "sparse": sparse})
    losses = {}
    for plane in ("a2a", "a2a+grouped"):
        specs = deepctr.make_feature_specs(
            feats, vocab, 8, plane=plane,
            optimizer={"category": "sgd", "learning_rate": 0.1},
            initializer={"category": "constant", "value": 0.0})
        coll = EmbeddingCollection(specs, mesh)
        trainer = Trainer(deepctr.DeepFM(feature_names=feats), coll,
                          optax.sgd(0.1))
        state = trainer.init(jax.random.PRNGKey(1),
                             trainer.shard_batch(batches[0]))
        curve = []
        for b in batches:
            state, m = trainer.train_step(state, b)
            curve.append(float(m["loss"]))
        losses[plane] = curve
    np.testing.assert_allclose(losses["a2a+grouped"], losses["a2a"],
                               rtol=1e-5, atol=1e-6)
