"""Examples ARE the integration suite — the reference runs every
examples/run/*.sh in CI (build.sh:95-151). Here the canonical CLI runs for a
few steps in-process (fast: shares the warmed JAX runtime) across the main
configuration axes."""

import sys

import pytest


def _run(argv):
    from examples import criteo_deepctr
    assert criteo_deepctr.main(argv) == 0


BASE = ["--num_buckets", "2048", "--batch_size", "128", "--steps", "4",
        "--embedding_dim", "4", "--data_parallel", "2", "--log_every", "0"]


@pytest.mark.slow
def test_example_fused_deepfm(devices8, tmp_path):
    _run(["--model", "deepfm", *BASE,
          "--save", str(tmp_path / "ck")])
    _run(["--model", "deepfm", *BASE, "--steps", "0",
          "--load", str(tmp_path / "ck"), "--eval_steps", "2"])


def test_example_wdl_psum_plane(devices8):
    _run(["--model", "wdl", *BASE, "--plane", "psum"])


@pytest.mark.slow
def test_example_lr_hybrid_and_history(devices8):
    _run(["--model", "lr", *BASE, "--no-fused",
          "--sparse_as_dense", "2048", "--hist_len", "4"])


@pytest.mark.slow
def test_example_tfrecord_input(devices8, tmp_path):
    """--format tfrecord: the dependency-free TFRecord reader feeds the
    training pipeline (the reference's criteo_tfrecord.py data path)."""
    import numpy as np
    from openembedding_tpu.data import tfrecord as tfr
    rng = np.random.RandomState(0)
    path = tmp_path / "tf-part.00001"
    with open(path, "wb") as f:
        for _ in range(300):
            feats = {"label": [int(rng.randint(0, 2))]}
            for j in range(1, 14):
                feats[f"I{j}"] = [float(np.float32(rng.randn()))]
            for j in range(1, 27):
                feats[f"C{j}"] = [int(rng.randint(0, 2048))]
            tfr.write_record(f, tfr.make_example(feats))
    _run(["--model", "deepfm", *BASE, "--data", str(path),
          "--format", "tfrecord"])


@pytest.mark.slow
def test_example_sharded_serving_cluster(devices8):
    """serving_cluster --shards 2: the shard-group demo boots a 2x1 grid
    and serves through the ShardedRoutingClient."""
    from examples import serving_cluster
    assert serving_cluster.main(["--shards", "2", "--replicas", "1",
                                 "--steps", "2", "--lookups", "1"]) == 0
