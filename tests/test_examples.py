"""Examples ARE the integration suite — the reference runs every
examples/run/*.sh in CI (build.sh:95-151). Here the canonical CLI runs for a
few steps in-process (fast: shares the warmed JAX runtime) across the main
configuration axes."""

import sys

import pytest


def _run(argv):
    from examples import criteo_deepctr
    assert criteo_deepctr.main(argv) == 0


BASE = ["--num_buckets", "2048", "--batch_size", "128", "--steps", "4",
        "--embedding_dim", "4", "--data_parallel", "2", "--log_every", "0"]


def test_example_fused_deepfm(devices8, tmp_path):
    _run(["--model", "deepfm", *BASE,
          "--save", str(tmp_path / "ck")])
    _run(["--model", "deepfm", *BASE, "--steps", "0",
          "--load", str(tmp_path / "ck"), "--eval_steps", "2"])


def test_example_wdl_psum_plane(devices8):
    _run(["--model", "wdl", *BASE, "--plane", "psum"])


def test_example_lr_hybrid_and_history(devices8):
    _run(["--model", "lr", *BASE, "--no-fused",
          "--sparse_as_dense", "2048", "--hist_len", "4"])
