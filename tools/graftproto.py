"""graftproto CLI: exhaustive protocol model checking gate.

    python -m tools.graftproto                     # check shipped models
    python -m tools.graftproto --model delta_chain
    python -m tools.graftproto --mutations         # seeded mutations must
                                                   # ALL counterexample
    python -m tools.graftproto --emit-schedules out.json

Fourth leg of the static-analysis gate (graftlint / graftrace /
graftcheck / graftproto): checks the shipped host-protocol models —
the delta-checkpoint chain (+compactor, crash/tear budgets, racing
loads), serving hot-swap seq gating, the DirtyTracker claim discipline,
the HA registry CREATING window under replica kills, and the serving
lookup micro-batcher (enqueue/flush/swap/shutdown) — EXHAUSTIVELY
by BFS, printing per-model explored-state counts. Exit 0 only when every
model's frontier is exhausted with all invariants green and no deadlock.

``--mutations`` runs the seeded mutation models
(``tests/fixtures/graftproto_violations.py``) and prints each minimal
counterexample — exit 1 when any fire (they all must; the pytest lane
asserts the exact invariant names). ``--emit-schedules`` writes every
model's sampled sync-point schedules plus every mutation's
counterexample schedule as JSON — the SerialSchedule/PointGate replays
``tests/test_graftproto_replay.py`` executes against the real
implementation, pinning the models to the code they describe.

Models and semantics live in ``openembedding_tpu/analysis/protomodel.py``
(stdlib-only; loaded standalone here so the gate never pays a jax
import).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, _ROOT)

_FIXTURE = os.path.join(_ROOT, "tests", "fixtures",
                        "graftproto_violations.py")


def _load_standalone(name: str, path: str):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod   # dataclasses resolves __module__
    spec.loader.exec_module(mod)
    return mod


protomodel = _load_standalone(
    "_graftproto_impl",
    os.path.join(_ROOT, "openembedding_tpu", "analysis", "protomodel.py"))


def _schedule_entry(model, trace):
    return {"actions": [label for label, _s in trace if label != "<init>"],
            "syncs": protomodel.trace_schedule(model, trace)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="exhaustive protocol model checking (delta chain / "
                    "hot-swap / dirty tracker / HA registry / "
                    "serving batcher)")
    ap.add_argument("--model", default="",
                    help="check one shipped model by name (default: all)")
    ap.add_argument("--max-states", type=int, default=500_000,
                    help="exploration budget; hitting it FAILS a shipped "
                         "model (an unexplored protocol is unchecked)")
    ap.add_argument("--mutations", nargs="?", const=_FIXTURE, default=None,
                    metavar="FIXTURE",
                    help="run the seeded mutation models instead; every "
                         "one must produce a counterexample (exit 1 when "
                         "any fire — mirrors the graftlint fixture runs)")
    ap.add_argument("--emit-schedules", default="", metavar="OUT",
                    help="also write sampled + counterexample sync-point "
                         "schedules as JSON for the real-code replays")
    args = ap.parse_args(argv)

    models = protomodel.shipped_models()
    if args.model:
        models = [m for m in models if m.name == args.model]
        if not models:
            print(f"graftproto: unknown model {args.model!r} (have: "
                  f"{[m.name for m in protomodel.shipped_models()]})",
                  file=sys.stderr)
            return 2

    out = {"models": {}, "mutations": {}}
    failed = 0

    if args.mutations is None or args.emit_schedules:
        for model in models:
            res = protomodel.check(model, max_states=args.max_states)
            print(protomodel.format_result(res, model))
            if not (res.ok and res.complete):
                failed += 1
                continue
            if args.emit_schedules:
                out["models"][model.name] = {
                    "explored": res.explored,
                    "transitions": res.transitions,
                    "invariants": [n for n, _p in model.invariants],
                    "schedules": [
                        _schedule_entry(model, t)
                        for t in protomodel.sample_traces(model)],
                }

    if args.mutations is not None or args.emit_schedules:
        fixture = _load_standalone("_graftproto_fixture",
                                   args.mutations or _FIXTURE)
        for name, builder, kwargs, expect_inv, why in fixture.MUTATIONS:
            model = getattr(protomodel, builder)(**kwargs)
            res = protomodel.check(model, max_states=args.max_states)
            cex = res.counterexample
            if cex is None:
                print(f"[mutation {name}] NO counterexample — the "
                      f"checker missed a seeded bug ({why})")
                failed += 1
                continue
            print(f"[mutation {name}] counterexample "
                  f"({len(cex.trace) - 1} steps, invariant "
                  f"{cex.invariant!r}, expected {expect_inv!r})")
            if args.mutations is not None:
                print(protomodel.format_result(res, model))
                failed += 1          # mutations firing IS the exit-1 path
            if cex.invariant != expect_inv:
                print(f"[mutation {name}] WRONG invariant fired",
                      file=sys.stderr)
                failed += 1
            if args.emit_schedules:
                out["mutations"][name] = {
                    "model": model.name,
                    "invariant": cex.invariant,
                    "why": why,
                    **_schedule_entry(model, cex.trace),
                }

    if args.emit_schedules:
        with open(args.emit_schedules, "w", encoding="utf-8") as fh:
            json.dump(out, fh, indent=1, sort_keys=True)
        print(f"graftproto: schedules -> {args.emit_schedules}")

    if failed:
        print(f"graftproto: {failed} failing check(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
