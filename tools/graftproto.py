"""graftproto CLI: exhaustive protocol model checking gate.

    python -m tools.graftproto                     # check shipped models
    python -m tools.graftproto --model delta_chain
    python -m tools.graftproto --mutations         # seeded mutations must
                                                   # ALL counterexample
    python -m tools.graftproto --check-sync        # model<->code drift
    python -m tools.graftproto --json out.json     # machine-readable gate
    python -m tools.graftproto --cross-check --model delta_chain
    python -m tools.graftproto --emit-schedules out.json

Fourth leg of the static-analysis gate (graftlint / graftrace /
graftcheck / graftproto): checks the protocol models — five shipped
roles (the delta-checkpoint chain with compactor, crash/tear budgets
and racing loads; serving hot-swap seq gating; the DirtyTracker claim
discipline; the HA registry CREATING window; the serving lookup
micro-batcher) plus the three models-first multi-host designs
(per-host delta writers + cross-host commit, elastic training
membership, N->M reshard) — EXHAUSTIVELY, with the v2 reductions ON
(symmetry canonicalization, ample-set partial order, quiescent-payload
collapse) and bounded-liveness obligations checked on the full graph.
Exit 0 only when every model's frontier is exhausted with all
invariants green, no deadlock, every obligation met, every state-count
floor held and every wall-time ceiling respected.

``--check-sync`` is the model<->code drift gate: exit 1 when any model
action names a ``sync_point`` the package source does not emit
(reserved design-only points are reported separately and do not fail).
``--json OUT`` writes per-model explored counts, reduction stats and
wall time for the CI artifact. ``--no-reduce`` forces full expansion;
``--cross-check`` runs reduced AND full expansion and fails unless the
verdicts are identical (the weekly reduction-soundness lane).

``--mutations`` runs the seeded mutation models
(``tests/fixtures/graftproto_violations.py``) and prints each minimal
counterexample — exit 1 when any fire (they all must; the pytest lane
asserts the exact invariant names). ``--emit-schedules`` writes every
model's sampled sync-point schedules plus every mutation's
counterexample schedule as JSON — the SerialSchedule/PointGate replays
``tests/test_graftproto_replay.py`` executes against the real
implementation, pinning the models to the code they describe.

Models and semantics live in ``openembedding_tpu/analysis/protomodel.py``
(stdlib-only; loaded standalone here so the gate never pays a jax
import).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, _ROOT)

_FIXTURE = os.path.join(_ROOT, "tests", "fixtures",
                        "graftproto_violations.py")

# Exploration tripwires, both directions. Floors: a guard refactor that
# silently hollows out the reachable space must fail loudly — each
# floor sits ~10% under the current REDUCED exhaustive count (the
# default gate runs with reductions ON; --no-reduce runs are gated by
# the same floors, which full expansion clears by construction).
# Ceilings: a reduction regression (footprint loss, symmetry breakage)
# that silently re-inflates the search must fail before it blows the
# gate's budget — wall-clock seconds, sized ~6x the local runtime to
# absorb CI jitter.
STATE_FLOORS = {
    "delta_chain": 58_000, "hot_swap": 120, "dirty_tracker": 70,
    "ha_registry": 210, "serving_batcher": 3_000,
    "multihost_delta": 140, "training_membership": 160, "reshard": 60,
}
WALL_CEILINGS_S = {
    "delta_chain": 120.0, "hot_swap": 15.0, "dirty_tracker": 15.0,
    "ha_registry": 15.0, "serving_batcher": 20.0,
    "multihost_delta": 20.0, "training_membership": 20.0,
    "reshard": 15.0,
}


def _load_standalone(name: str, path: str):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod   # dataclasses resolves __module__
    spec.loader.exec_module(mod)
    return mod


protomodel = _load_standalone(
    "_graftproto_impl",
    os.path.join(_ROOT, "openembedding_tpu", "analysis", "protomodel.py"))


def _schedule_entry(model, trace):
    return {"actions": [label for label, _s in trace if label != "<init>"],
            "syncs": protomodel.trace_schedule(model, trace)}


def _check_sync(models) -> int:
    """The model<->code drift gate: every sync point a model claims
    must be emitted by the package source, or explicitly reserved."""
    failed = 0
    for model in models:
        missing = protomodel.missing_sync_points(model)
        reserved = protomodel.reserved_sync_points(model)
        ok = "DRIFT" if missing else "ok"
        print(f"[{model.name}] sync points: {ok}"
              + (f" — missing from package source: {missing}"
                 if missing else "")
              + (f" (reserved, design-only: {reserved})"
                 if reserved else ""))
        if missing:
            failed += 1
    if failed:
        print(f"graftproto --check-sync: {failed} model(s) reference "
              f"sync points the package does not emit (rename drift or "
              f"a dropped sync_point call)", file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="exhaustive protocol model checking (shipped roles "
                    "+ the multi-host models), reductions on")
    ap.add_argument("--model", default="",
                    help="check one shipped model by name (default: all)")
    ap.add_argument("--max-states", type=int, default=500_000,
                    help="exploration budget; hitting it FAILS a shipped "
                         "model (an unexplored protocol is unchecked)")
    ap.add_argument("--no-reduce", action="store_true",
                    help="disable symmetry/partial-order/collapse "
                         "reductions (full plain-BFS expansion)")
    ap.add_argument("--cross-check", action="store_true",
                    help="run reduced AND full expansion per model and "
                         "fail unless invariant verdicts are identical "
                         "(the weekly reduction-soundness lane)")
    ap.add_argument("--check-sync", action="store_true",
                    help="model<->code sync-point drift gate only: exit "
                         "1 when a model action names a sync point the "
                         "package source does not emit")
    ap.add_argument("--json", default="", metavar="OUT",
                    help="write per-model state counts, reduction "
                         "stats and wall time as JSON (the CI artifact)")
    ap.add_argument("--mutations", nargs="?", const=_FIXTURE, default=None,
                    metavar="FIXTURE",
                    help="run the seeded mutation models instead; every "
                         "one must produce a counterexample (exit 1 when "
                         "any fire — mirrors the graftlint fixture runs)")
    ap.add_argument("--emit-schedules", default="", metavar="OUT",
                    help="also write sampled + counterexample sync-point "
                         "schedules as JSON for the real-code replays")
    args = ap.parse_args(argv)

    models = protomodel.shipped_models()
    if args.model:
        models = [m for m in models if m.name == args.model]
        if not models:
            print(f"graftproto: unknown model {args.model!r} (have: "
                  f"{[m.name for m in protomodel.shipped_models()]})",
                  file=sys.stderr)
            return 2

    if args.check_sync:
        return _check_sync(models)

    out = {"models": {}, "mutations": {}}
    report = {"models": {}, "max_states": args.max_states,
              "reduce": not args.no_reduce}
    failed = 0

    if args.mutations is None or args.emit_schedules:
        for model in models:
            res = protomodel.check(model, max_states=args.max_states,
                                   reduce=not args.no_reduce)
            print(protomodel.format_result(res, model))
            entry = {
                "explored": res.explored,
                "transitions": res.transitions,
                "elapsed_s": round(res.elapsed_s, 3),
                "ok": res.ok, "complete": res.complete,
                "stats": res.stats,
            }
            if not (res.ok and res.complete):
                failed += 1
                report["models"][model.name] = entry
                continue
            floor = STATE_FLOORS.get(model.name)
            if floor is not None and res.explored < floor:
                print(f"[{model.name}] STATE FLOOR TRIPPED: explored "
                      f"{res.explored} < floor {floor} — a guard "
                      f"refactor hollowed out the exploration",
                      file=sys.stderr)
                failed += 1
            ceiling = WALL_CEILINGS_S.get(model.name)
            if ceiling is not None and res.elapsed_s > ceiling:
                print(f"[{model.name}] WALL-TIME CEILING TRIPPED: "
                      f"{res.elapsed_s:.2f}s > {ceiling}s — a "
                      f"reduction regression re-inflated the search",
                      file=sys.stderr)
                failed += 1
            if args.cross_check:
                xc = protomodel.cross_check(model,
                                            max_states=args.max_states)
                entry["cross_check"] = {
                    "reduced_explored": xc["reduced"].explored,
                    "full_explored": xc["full"].explored,
                    "ratio": round(xc["ratio"], 3)}
                print(f"[{model.name}] cross-check: reduced "
                      f"{xc['reduced'].explored} vs full "
                      f"{xc['full'].explored} "
                      f"({xc['ratio']:.2f}x), verdicts identical")
            if model.obligations:
                lres = protomodel.check_liveness(
                    model, max_states=args.max_states)
                entry["liveness_ok"] = lres.ok
                if not (lres.ok and lres.complete):
                    print(protomodel.format_result(lres, model))
                    failed += 1
                else:
                    print(f"[{model.name}] "
                          f"{len(model.obligations)} bounded-liveness "
                          f"obligation(s) hold on the full graph")
            report["models"][model.name] = entry
            if args.emit_schedules:
                out["models"][model.name] = {
                    "explored": res.explored,
                    "transitions": res.transitions,
                    "invariants": [n for n, _p in model.invariants],
                    "schedules": [
                        _schedule_entry(model, t)
                        for t in protomodel.sample_traces(model)],
                }

    if args.mutations is not None or args.emit_schedules:
        fixture = _load_standalone("_graftproto_fixture",
                                   args.mutations or _FIXTURE)
        for mut in fixture.iter_mutations():
            name = mut["name"]
            expect_inv = mut["expected_invariant"]
            model = getattr(protomodel, mut["builder"])(**mut["kwargs"])
            if mut["kind"] == "liveness":
                res = protomodel.check_liveness(
                    model, max_states=args.max_states)
            else:
                res = protomodel.check(model, max_states=args.max_states)
            cex = res.counterexample
            if cex is None:
                print(f"[mutation {name}] NO counterexample — the "
                      f"checker missed a seeded bug ({mut['why']})")
                failed += 1
                continue
            print(f"[mutation {name}] counterexample "
                  f"({len(cex.trace) - 1} steps, {mut['kind']} "
                  f"{cex.invariant!r}, expected {expect_inv!r})")
            if args.mutations is not None:
                print(protomodel.format_result(res, model))
                failed += 1          # mutations firing IS the exit-1 path
            if cex.invariant != expect_inv:
                print(f"[mutation {name}] WRONG property fired",
                      file=sys.stderr)
                failed += 1
            if args.emit_schedules:
                out["mutations"][name] = {
                    "model": model.name,
                    "invariant": cex.invariant,
                    "kind": mut["kind"],
                    "why": mut["why"],
                    **_schedule_entry(model, cex.trace),
                }

    if args.emit_schedules:
        with open(args.emit_schedules, "w", encoding="utf-8") as fh:
            json.dump(out, fh, indent=1, sort_keys=True)
        print(f"graftproto: schedules -> {args.emit_schedules}")

    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
        print(f"graftproto: gate report -> {args.json}")

    if failed:
        print(f"graftproto: {failed} failing check(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
