"""graftchaos — walk every sync point, inject every fault class, assert
the published recovery invariants hold.

The chaos plane (``analysis/chaos.py``) turns each named sync point into
a deterministic fault site. This tool is the harness around it:

``--list``
    Print every ``sync_point`` marker in the package, grouped by
    subsystem, with the fault classes the sweep would inject there.

``--plan``
    Validate a plan (inline JSON or ``@file``) and echo its canonical
    form — the pre-flight for ``OE_CHAOS_PLAN``.

``--sweep``
    For each (point, action) pair, run the subsystem's scenario with a
    one-shot :class:`FaultPlan` armed, then clear the plan and assert
    the subsystem's published invariant:

    * **ckpt** — a trainer fits with delta autosaves while the fault
      lands anywhere in the save/compact/restore pipeline; afterwards a
      FRESH trainer must resume from the directory to the bit-identical
      uninterrupted baseline (loads recover to a committed version;
      ``torn_write`` must never surface a half-written commit). One
      carve-out, straight from the checkpoint contract: a fault landing
      INSIDE the delta-save window (``ckpt.delta.write`` /
      ``ckpt.delta.commit``) may leave the dense file one save ahead of
      the chain — the documented last-writer-wins divergence (chain
      guarantees cover the sparse tables). There the invariant is that
      recovery replays cleanly to the full step count and the resulting
      chain round-trips bit-identically, not baseline identity.
    * **ingest** — a ShardStream is consumed under the fault; the
      consumer must either finish or fail LOUDLY within a deadline
      (rings never hang — a dead reader surfaces at ``__next__``).
    * **serving** — an in-process registry + REST replica + routing
      client runs load/lookup/hot-swap/peer-restore under the fault;
      afterwards lookups must succeed and every response must be a
      single committed version, never a mix of old and new rows.

    Every fired injection must also be visible on /metrics as
    ``oe_chaos_injected_total{point=,action=}`` — an uncounted fault is
    itself a violation. Faults whose scenario never reaches the point
    report ``skipped`` (no_fire). Exit status is nonzero iff any
    violation was found.

Scenarios run the REAL code paths (Trainer.fit autosave/resume,
checkpoint_delta save/compact/replay, ModelRegistry hot-swap, the HTTP
serving stack) on tiny models over the in-process CPU mesh.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import sys
import tempfile
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional

if __package__ in (None, ""):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

from openembedding_tpu.analysis import chaos  # noqa: E402
from openembedding_tpu.analysis import scope  # noqa: E402


# --- scenario scale (tiny on purpose: the sweep is O(points x actions)) -----

FEATURES = ("c0", "c1", "c2")
VOCAB, DIM, B = 48, 4, 8
N_BATCHES, INTERRUPT, AUTOSAVE = 8, 5, 2
SERVE_VOCAB, SERVE_DIM = 32, 4
SERVE_SIGN = "chaos-serve"
HANG_DEADLINE_S = 60.0

# fault classes the sweep injects per subsystem: torn_write needs an
# atomic-commit site downstream (checkpoint writes), drop_net needs a
# network classifier upstream (the routing client's failover)
_BASE_ACTIONS = ("raise", "delay_ms", "kill_thread")

# faults that abort save_delta between its dense-file commit and the
# manifest commit leave dense one save AHEAD of the chain — the
# checkpoint contract's documented last-writer-wins divergence (chain
# guarantees cover the sparse tables), so recovery from that mixed
# state is not baseline-identical by design
_DENSE_AHEAD_POINTS = frozenset({"ckpt.delta.write",
                                 "ckpt.delta.commit"})


def actions_for(point: str) -> List[str]:
    acts = list(_BASE_ACTIONS)
    if chaos.subsystem_of(point) == "ckpt":
        acts.append("torn_write")
    if point == "routing.attempt":
        acts.append("drop_net")
    return acts


def _result(point: str, action: str, status: str, detail: str = "",
            fired: int = 0, dt: float = 0.0) -> Dict[str, Any]:
    return {"point": point, "action": action,
            "subsystem": chaos.subsystem_of(point), "status": status,
            "detail": detail, "fired": int(fired),
            "duration_s": round(dt, 3)}


def _staged(errors: List[str], stage: str, fn: Callable[[], Any]) -> Any:
    """Run one scenario stage under an armed plan. Any exception —
    including ChaosKill — is the fault surfacing, which is expected;
    record it and keep going so later stages still execute."""
    try:
        return fn()
    except BaseException as e:  # noqa: BLE001 — chaos is the point
        errors.append(f"{stage}: {type(e).__name__}: {e}")
        return None


# --- shared lazy world (mesh + batches + baseline are chaos-free) -----------

class _World:
    def __init__(self) -> None:
        self.mesh = None
        self.batches: Optional[List[Dict[str, Any]]] = None
        self.baseline: Optional[List[Any]] = None
        self.serve_dir: Optional[str] = None
        self._tmp: Optional[tempfile.TemporaryDirectory] = None

    def ensure_trainer(self):
        import jax
        if self.mesh is None:
            from openembedding_tpu.parallel.mesh import create_mesh
            self.mesh = create_mesh(2, 4, jax.devices())
        if self.batches is None:
            self.batches = _synthetic_batches(N_BATCHES)
        if self.baseline is None:
            tr = _build_trainer(self.mesh)
            s0 = tr.init(jax.random.PRNGKey(0),
                         tr.shard_batch(self.batches[0]))
            s1, _ = tr.fit(s0, list(self.batches))
            self.baseline = _fingerprint(tr, s1)
        return self

    def ensure_serving(self) -> str:
        """A tiny served checkpoint dir (one bounded var ``emb``)."""
        import jax
        import numpy as np
        self.ensure_trainer()
        if self.serve_dir is None:
            from openembedding_tpu import (EmbeddingCollection,
                                           EmbeddingSpec)
            from openembedding_tpu import checkpoint as ckpt
            self._tmp = tempfile.TemporaryDirectory(prefix="graftchaos-")
            d = os.path.join(self._tmp.name, "model")
            specs = (EmbeddingSpec(name="emb", input_dim=SERVE_VOCAB,
                                   output_dim=SERVE_DIM),)
            coll = EmbeddingCollection(specs, self.mesh)
            states = coll.init(jax.random.PRNGKey(7))
            ckpt.save_checkpoint(d, coll, states, model_sign=SERVE_SIGN,
                                 include_optimizer=False)
            self.serve_dir = d
        return self.serve_dir


WORLD = _World()


def _synthetic_batches(n: int, seed: int = 0) -> List[Dict[str, Any]]:
    import numpy as np
    from openembedding_tpu.models import deepctr
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        sparse: Dict[str, Any] = {}
        raw: Dict[str, Any] = {}
        for f in FEATURES:
            ids = rng.randint(0, VOCAB, size=B).astype(np.int32)
            raw[f] = ids
            sparse[f] = ids
            sparse[f + deepctr.LINEAR_SUFFIX] = ids
        label = ((raw["c0"] + raw["c1"]) % 2).astype(np.float32)
        dense = rng.randn(B, 4).astype(np.float32)
        out.append({"label": label, "dense": dense, "sparse": sparse})
    return out


def _build_trainer(mesh):
    import optax
    from openembedding_tpu import EmbeddingCollection, Trainer
    from openembedding_tpu.models import deepctr
    specs = deepctr.make_feature_specs(FEATURES, VOCAB, DIM)
    coll = EmbeddingCollection(
        specs, mesh,
        default_optimizer={"category": "adagrad", "learning_rate": 0.1})
    coll.enable_dirty_tracking(target_chunks=8)
    model = deepctr.build_model("deepfm", FEATURES)
    return Trainer(model, coll, optax.adam(1e-2))


def _fingerprint(tr, state) -> List[Any]:
    """Bit-exact identity through the LOGICAL id space: step + dense
    params/opt leaves + a full-vocab pull per embedding var (physical
    padding rows re-init from a fresh rng stream on load and are not
    comparable)."""
    import jax
    import numpy as np
    out = [np.asarray(int(state.step))]
    for leaf in jax.tree.leaves((state.params, state.opt_state)):
        out.append(np.asarray(jax.device_get(leaf)))
    allv = np.arange(VOCAB, dtype=np.int32)
    names = list(tr.collection.specs)
    pulls = tr.collection.pull(state.emb, {n: allv for n in names},
                               batch_sharded=False)
    for n in names:
        out.append(np.asarray(pulls[n]))
    return out


def _fingerprint_diff(a: List[Any], b: List[Any]) -> str:
    import numpy as np
    if len(a) != len(b):
        return f"leaf count {len(a)} != {len(b)}"
    for i, (x, y) in enumerate(zip(a, b)):
        if x.shape != y.shape:
            return f"leaf {i}: shape {x.shape} != {y.shape}"
        if not np.array_equal(x, y):
            return (f"leaf {i}: max abs diff "
                    f"{float(np.max(np.abs(x - y)))}")
    return ""


# --- ckpt scenario ----------------------------------------------------------

def run_ckpt_scenario(point: str, action: str, seed: int
                      ) -> Dict[str, Any]:
    import jax
    from openembedding_tpu import checkpoint as ckpt
    from openembedding_tpu import checkpoint_delta as cd
    t0 = time.perf_counter()
    w = WORLD.ensure_trainer()
    plan = chaos.FaultPlan(
        [chaos.FaultSpec(point=point, action=action)], seed=seed)
    c0 = scope.HISTOGRAMS.counter(chaos.COUNTER, point=point,
                                  action=action)
    errors: List[str] = []
    with tempfile.TemporaryDirectory(prefix="graftchaos-ckpt-") as d:
        ck = os.path.join(d, "auto")
        full = os.path.join(d, "full")
        with warnings.catch_warnings():
            # torn-tail discards on resume warn by design
            warnings.simplefilter("ignore", RuntimeWarning)
            with chaos.active_plan(plan):
                # 1. interrupted fit with delta autosaves
                tr1 = _build_trainer(w.mesh)
                s1 = tr1.init(jax.random.PRNGKey(0),
                              tr1.shard_batch(w.batches[0]))

                def _fit1():
                    return tr1.fit(s1, list(w.batches[:INTERRUPT]),
                                   autosave_every=AUTOSAVE,
                                   autosave_dir=ck)
                fit_out = _staged(errors, "fit", _fit1)
                # 2. foreground compaction of whatever chain committed
                # (join the autosave's background compactor first — two
                # compactors racing over one dir is not a scenario)
                if os.path.isdir(ck):
                    _staged(errors, "compact.join",
                            lambda: cd.join_compactor(ck))
                    _staged(errors, "compact", lambda: cd.compact(ck))
                # 3. full saves (arm + reset paths, writer pool); the
                # fit DONATES its input buffers, so save the returned
                # state — and when fit died mid-run (donated AND gone),
                # re-init so the full-save path still runs under plan
                if fit_out:
                    emb_states = fit_out[0].emb
                else:
                    emb_states = tr1.init(
                        jax.random.PRNGKey(0),
                        tr1.shard_batch(w.batches[0])).emb
                _staged(errors, "fullsave", lambda: ckpt.save_checkpoint(
                    full, tr1.collection, emb_states,
                    model_sign="chaos-f", include_optimizer=False))
                _staged(errors, "fullsave2", lambda: ckpt.save_checkpoint(
                    full, tr1.collection, emb_states,
                    model_sign="chaos-f", include_optimizer=False))
                # 4. resume attempt UNDER the plan (restore-side points)
                tr2 = _build_trainer(w.mesh)
                s2 = tr2.init(jax.random.PRNGKey(0),
                              tr2.shard_batch(w.batches[0]))

                def _fit2():
                    tr2.fit(s2, list(w.batches), resume_from=ck,
                            autosave_every=AUTOSAVE, autosave_dir=ck)
                _staged(errors, "resume", _fit2)
            # the plan is cleared: simulate the process restart — drain
            # any background thread the kill left poisoned
            try:
                cd.join_compactor(ck)
            except BaseException:  # noqa: BLE001 — poisoned by design
                pass
            dt = time.perf_counter() - t0
            if not plan.injected:
                return _result(point, action, "skipped", "no_fire",
                               dt=dt)
            fired = len(plan.injected)
            c1 = scope.HISTOGRAMS.counter(chaos.COUNTER, point=point,
                                          action=action)
            if c1 <= c0:
                return _result(point, action, "violation",
                               "fault fired but oe_chaos_injected_total "
                               "did not move", fired, dt)
            # RECOVERY INVARIANT: a fresh trainer resumes from whatever
            # the faulted run committed and lands bit-identical on the
            # uninterrupted baseline. Carve-out: at _DENSE_AHEAD_POINTS
            # the dense file may be one save ahead of the chain, so the
            # check there is clean replay to the full step count plus a
            # bit-identical restore round-trip of the recovered chain.
            note = ""
            tr3 = _build_trainer(w.mesh)
            s3 = tr3.init(jax.random.PRNGKey(0),
                          tr3.shard_batch(w.batches[0]))
            try:
                s3b, _ = tr3.fit(s3, list(w.batches), resume_from=ck,
                                 autosave_every=AUTOSAVE,
                                 autosave_dir=ck)
            except BaseException as e:  # noqa: BLE001 — any raise fails
                return _result(
                    point, action, "violation",
                    f"recovery resume failed: {type(e).__name__}: {e} "
                    f"(faulted stages: {errors})", fired,
                    time.perf_counter() - t0)
            fp3 = _fingerprint(tr3, s3b)
            bad = _fingerprint_diff(w.baseline, fp3)
            if bad and point in _DENSE_AHEAD_POINTS:
                if int(fp3[0]) != int(w.baseline[0]):
                    return _result(
                        point, action, "violation",
                        f"recovery replayed to step {int(fp3[0])}, "
                        f"expected {int(w.baseline[0])} — batches were "
                        f"skipped or reapplied (faulted stages: "
                        f"{errors})", fired, time.perf_counter() - t0)
                tr4 = _build_trainer(w.mesh)
                s4 = tr4.init(jax.random.PRNGKey(0),
                              tr4.shard_batch(w.batches[0]))
                try:
                    s4b, _ = tr4.fit(s4, list(w.batches),
                                     resume_from=ck,
                                     autosave_every=AUTOSAVE,
                                     autosave_dir=ck)
                except BaseException as e:  # noqa: BLE001
                    return _result(
                        point, action, "violation",
                        f"post-recovery restore failed: "
                        f"{type(e).__name__}: {e}", fired,
                        time.perf_counter() - t0)
                bad2 = _fingerprint_diff(fp3, _fingerprint(tr4, s4b))
                if bad2:
                    return _result(
                        point, action, "violation",
                        f"post-recovery restore did not round-trip: "
                        f"{bad2}", fired, time.perf_counter() - t0)
                bad = ""
                note = ("recovered to committed chain version; dense "
                        "file rode one save ahead (documented "
                        "last-writer-wins divergence)")
    dt = time.perf_counter() - t0
    if bad:
        return _result(point, action, "violation",
                       f"recovery diverged from baseline: {bad} "
                       f"(faulted stages: {errors})", fired, dt)
    detail = "; ".join(errors) if errors else "fault absorbed"
    if note:
        detail = f"{note}; {detail}" if errors else note
    return _result(point, action, "ok", detail, fired, dt)


# --- ingest scenario --------------------------------------------------------

def run_ingest_scenario(point: str, action: str, seed: int
                        ) -> Dict[str, Any]:
    from openembedding_tpu.data import stream as stream_lib
    t0 = time.perf_counter()
    plan = chaos.FaultPlan(
        [chaos.FaultSpec(point=point, action=action)], seed=seed)
    c0 = scope.HISTOGRAMS.counter(chaos.COUNTER, point=point,
                                  action=action)
    with tempfile.TemporaryDirectory(prefix="graftchaos-ingest-") as d:
        stream_lib.write_synthetic_shards(d, num_shards=4,
                                          rows_per_shard=64)
        done: List[str] = []
        err: List[BaseException] = []

        def _consume():
            try:
                s = stream_lib.ShardStream(d, batch_size=16, readers=2,
                                           epochs=1)
                n = 0
                try:
                    for _ in s:
                        n += 1
                finally:
                    s.close()
                done.append(f"consumed {n} batches")
            except BaseException as e:  # noqa: BLE001 — loud is fine
                err.append(e)

        with chaos.active_plan(plan):
            worker = threading.Thread(target=_consume, daemon=True,
                                      name="chaos-ingest-consumer")
            worker.start()
            worker.join(HANG_DEADLINE_S)
            hung = worker.is_alive()
        dt = time.perf_counter() - t0
        if hung:
            # leave the daemon thread behind; the ring is hung, which is
            # exactly the violation
            return _result(point, action, "violation",
                           f"ring hung: consumer still alive after "
                           f"{HANG_DEADLINE_S:.0f}s", len(plan.injected),
                           dt)
        if not plan.injected:
            return _result(point, action, "skipped", "no_fire", dt=dt)
        c1 = scope.HISTOGRAMS.counter(chaos.COUNTER, point=point,
                                      action=action)
        if c1 <= c0:
            return _result(point, action, "violation",
                           "fault fired but oe_chaos_injected_total "
                           "did not move", len(plan.injected), dt)
        outcome = done[0] if done else \
            f"failed loudly: {type(err[0]).__name__}: {err[0]}"
        return _result(point, action, "ok", outcome,
                       len(plan.injected), dt)


# --- serving scenario -------------------------------------------------------

def _constant_delta(seq: int, value: float):
    """A full-vocab constant delta for ``emb`` in the chunked array
    payload form ``apply_delta`` expects (one chunk spanning the whole
    table)."""
    import numpy as np
    from openembedding_tpu.checkpoint_delta import Delta
    payload = {
        "weights": np.full((SERVE_VOCAB, SERVE_DIM), value, np.float32),
        "chunks": np.array([0], np.int64),
        "rows_per_chunk": np.array(SERVE_VOCAB, np.int64),
        "vocab": np.array(SERVE_VOCAB, np.int64),
    }
    return Delta(seq=seq, step=seq, vars={"emb": payload})


def _classify_rows(rows, new_value: float) -> str:
    """'old' / 'new' / 'mixed' for one lookup response under the
    constant-delta scheme (baseline rows are random init floats that are
    never exactly ``new_value``)."""
    import numpy as np
    rows = np.asarray(rows)
    is_new = rows == new_value
    if bool(np.all(is_new)):
        return "new"
    if not bool(np.any(is_new)):
        return "old"
    return "mixed"


def run_serving_scenario(point: str, action: str, seed: int
                        ) -> Dict[str, Any]:
    import numpy as np
    from openembedding_tpu.parallel.mesh import create_mesh
    from openembedding_tpu.serving import ha
    from openembedding_tpu.serving.registry import ModelRegistry
    from openembedding_tpu.serving.rest import ControllerServer
    import jax

    t0 = time.perf_counter()
    model_dir = WORLD.ensure_serving()
    plan = chaos.FaultPlan(
        [chaos.FaultSpec(point=point, action=action)], seed=seed)
    c0 = scope.HISTOGRAMS.counter(chaos.COUNTER, point=point,
                                  action=action)
    errors: List[str] = []
    NEW = 2.0
    mesh = create_mesh(1, len(jax.devices()))
    registry = ModelRegistry(mesh, default_hash_capacity=1024)
    if point.startswith("serving.batch."):
        registry.enable_batching(max_batch_rows=64, max_wait_us=200)
    server = ControllerServer(registry, port=0)
    server.start()
    ep = f"127.0.0.1:{server.port}"
    client = ha.RoutingClient(
        [ep], timeout=10.0,
        policy=ha.RetryPolicy(deadline_s=8.0, base_backoff_s=0.02,
                              max_backoff_s=0.2))
    ids = np.arange(SERVE_VOCAB, dtype=np.int64)
    mixed: List[str] = []
    try:
        with chaos.active_plan(plan):
            # 1. load (registry.load.*, registry.find)
            _staged(errors, "create_model",
                    lambda: registry.create_model(
                        model_dir, model_sign=SERVE_SIGN, block=True))
            # kill_thread mid-load strands the status row in CREATING —
            # the in-process stand-in for a replica dying mid-boot; the
            # operator move is delete + reload, still under the plan
            if SERVE_SIGN not in registry._models:
                _staged(errors, "reload.delete",
                        lambda: registry.delete_model(SERVE_SIGN))
                _staged(errors, "reload",
                        lambda: registry.create_model(
                            model_dir, model_sign=SERVE_SIGN,
                            block=True))
            # 2. lookups through the full HTTP + routing path
            for i in range(3):
                rows = _staged(errors, f"lookup{i}",
                               lambda: client.lookup(SERVE_SIGN, "emb",
                                                     ids))
                if rows is not None:
                    mixed.append(_classify_rows(rows, NEW))
            # 3. hot-swap a constant delta (registry.swap.*), racing a
            # concurrent reader thread against the swap
            reader_rows: List[Any] = []

            def _reader():
                try:
                    for _ in range(4):
                        reader_rows.append(
                            registry.lookup(SERVE_SIGN, "emb", ids))
                except Exception:  # noqa: BLE001 — chaos may break it
                    pass
            rt = threading.Thread(target=_reader, daemon=True,
                                  name="chaos-serving-reader")
            rt.start()
            _staged(errors, "push_delta",
                    lambda: client.push_delta(SERVE_SIGN,
                                              _constant_delta(1, NEW)))
            rt.join(HANG_DEADLINE_S)
            if rt.is_alive():
                return _result(point, action, "violation",
                               "reader hung against hot-swap",
                               len(plan.injected),
                               time.perf_counter() - t0)
            for rows in reader_rows:
                mixed.append(_classify_rows(rows, NEW))
            # 4. peer restore (ha.restore.*): a second registry
            # reconstructs the catalog from the live replica
            if point.startswith("ha."):
                reg2 = ModelRegistry(mesh, default_hash_capacity=1024)
                _staged(errors, "restore_from_peers",
                        lambda: ha.restore_from_peers(reg2, [ep],
                                                      wait=5.0))
                reg2.close()
        # plan cleared — RECOVERY INVARIANTS
        dt = time.perf_counter() - t0
        if not plan.injected:
            return _result(point, action, "skipped", "no_fire", dt=dt)
        fired = len(plan.injected)
        c1 = scope.HISTOGRAMS.counter(chaos.COUNTER, point=point,
                                      action=action)
        if c1 <= c0:
            return _result(point, action, "violation",
                           "fault fired but oe_chaos_injected_total "
                           "did not move", fired, dt)
        if "mixed" in mixed:
            return _result(point, action, "violation",
                           f"lookup saw a MIXED version: {mixed} "
                           f"(faulted stages: {errors})", fired, dt)
        # the fleet must converge: load if the faulted load never
        # committed, re-push the delta (idempotent), then lookups must
        # answer with one whole committed version
        if SERVE_SIGN not in registry._models:
            try:
                registry.delete_model(SERVE_SIGN)
            except Exception:  # noqa: BLE001 — absent is fine
                pass
            try:
                registry.create_model(model_dir, model_sign=SERVE_SIGN,
                                      block=True)
            except BaseException as e:  # noqa: BLE001
                return _result(point, action, "violation",
                               f"recovery load failed: "
                               f"{type(e).__name__}: {e}", fired,
                               time.perf_counter() - t0)
        try:
            client.push_delta(SERVE_SIGN, _constant_delta(1, NEW))
            rows = client.lookup(SERVE_SIGN, "emb", ids)
        except BaseException as e:  # noqa: BLE001
            return _result(point, action, "violation",
                           f"recovery lookup failed: "
                           f"{type(e).__name__}: {e} "
                           f"(faulted stages: {errors})", fired,
                           time.perf_counter() - t0)
        kind = _classify_rows(rows, NEW)
        dt = time.perf_counter() - t0
        if kind != "new":
            return _result(point, action, "violation",
                           f"recovery lookup returned {kind!r} rows, "
                           f"expected the committed delta version",
                           fired, dt)
        return _result(point, action, "ok",
                       "; ".join(errors) if errors else "fault absorbed",
                       fired, dt)
    finally:
        chaos.clear_plan()
        try:
            client.close()
        except Exception:  # noqa: BLE001
            pass
        server.stop()
        registry.close()


_SCENARIOS: Dict[str, Callable[[str, str, int], Dict[str, Any]]] = {
    "ckpt": run_ckpt_scenario,
    "ingest": run_ingest_scenario,
    "serving": run_serving_scenario,
}


# --- sweep driver -----------------------------------------------------------

def sweep_targets(subsystems: List[str], points_glob: str,
                  actions: Optional[List[str]]) -> List[tuple]:
    targets = []
    for point in chaos.discover_sync_points():
        sub = chaos.subsystem_of(point)
        if sub not in subsystems or sub not in _SCENARIOS:
            continue
        if points_glob and not fnmatch.fnmatch(point, points_glob):
            continue
        for action in actions_for(point):
            if actions and action not in actions:
                continue
            targets.append((point, action, sub))
    return targets


def run_sweep(subsystems: List[str], points_glob: str,
              actions: Optional[List[str]], seed: int,
              progress: bool = True) -> Dict[str, Any]:
    targets = sweep_targets(subsystems, points_glob, actions)
    results: List[Dict[str, Any]] = []
    for i, (point, action, sub) in enumerate(targets):
        if progress:
            print(f"[{i + 1}/{len(targets)}] {sub}: {point} x {action} "
                  "...", flush=True)
        try:
            res = _SCENARIOS[sub](point, action, seed)
        except BaseException as e:  # noqa: BLE001 — harness crash
            res = _result(point, action, "violation",
                          f"scenario harness crashed: "
                          f"{type(e).__name__}: {e}")
        finally:
            chaos.clear_plan()
        if progress:
            print(f"    -> {res['status']}"
                  + (f" ({res['detail']})" if res["detail"] else ""),
                  flush=True)
        results.append(res)
    counts = {"ok": 0, "skipped": 0, "violation": 0}
    for r in results:
        counts[r["status"]] = counts.get(r["status"], 0) + 1
    return {
        "seed": seed,
        "subsystems": subsystems,
        "targets": len(targets),
        "counts": counts,
        "injected_total": int(sum(r["fired"] for r in results)),
        "results": results,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftchaos",
        description="deterministic sync-point fault injection: list "
                    "points, validate plans, sweep fault classes")
    ap.add_argument("--list", action="store_true",
                    help="print every sync point grouped by subsystem")
    ap.add_argument("--plan", metavar="JSON_OR_@FILE",
                    help="validate a fault plan and echo canonical JSON")
    ap.add_argument("--sweep", action="store_true",
                    help="inject every fault class at every swept point "
                         "and assert recovery invariants")
    ap.add_argument("--subsystems", default="ckpt,ingest,serving",
                    help="comma list of subsystems to sweep "
                         "(default: ckpt,ingest,serving)")
    ap.add_argument("--points", default="",
                    help="fnmatch glob filtering swept points "
                         "(e.g. 'ckpt.*')")
    ap.add_argument("--actions", default="",
                    help="comma list restricting injected fault classes")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="",
                    help="write the sweep report JSON here")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    if args.plan:
        plan = chaos.plan_from_text(args.plan)
        print(json.dumps(plan.to_json(), indent=2))
        return 0

    if args.list:
        by_sub: Dict[str, List[str]] = {}
        for p in chaos.discover_sync_points():
            by_sub.setdefault(chaos.subsystem_of(p), []).append(p)
        for sub in sorted(by_sub):
            swept = "swept" if sub in _SCENARIOS else "not swept"
            print(f"{sub} ({len(by_sub[sub])} points, {swept}):")
            for p in by_sub[sub]:
                print(f"  {p}  [{', '.join(actions_for(p))}]")
        return 0

    if not args.sweep:
        ap.print_help()
        return 2

    subsystems = [s.strip() for s in args.subsystems.split(",")
                  if s.strip()]
    actions = [a.strip() for a in args.actions.split(",") if a.strip()] \
        or None
    for a in actions or []:
        if a not in chaos.ACTIONS:
            ap.error(f"unknown action {a!r} (one of {chaos.ACTIONS})")
    report = run_sweep(subsystems, args.points, actions, args.seed,
                       progress=not args.quiet)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    counts = report["counts"]
    print(f"graftchaos sweep: {report['targets']} target(s), "
          f"{counts['ok']} ok, {counts['skipped']} skipped (no_fire), "
          f"{counts['violation']} violation(s), "
          f"{report['injected_total']} fault(s) injected")
    for r in report["results"]:
        if r["status"] == "violation":
            print(f"  VIOLATION {r['point']} x {r['action']}: "
                  f"{r['detail']}")
    return 1 if counts["violation"] else 0


if __name__ == "__main__":
    sys.exit(main())
