"""Chaos smoke for CI: one REAL kill-mid-fit -> resume -> bit-identity
check, plus a budgeted sync-point sweep subset.

The kill lane is cross-process end-to-end: a CHILD python process arms
a ``kill_thread`` fault at ``trainer.fit.step`` from ``OE_CHAOS_PLAN``
(the production wire — exactly how a replica daemon or trainer job
would be armed), trains with delta autosaves, and DIES mid-fit. The
parent then resumes a fresh trainer from the orphaned autosave
directory and requires bit-identity with the uninterrupted baseline —
the elastic-trainer contract (graftproto ``trainer_restart``: neither
reapply nor skip). MTTR, steps lost past the last committed cursor,
and chain bytes replayed are measured and assembled into a graftwatch
``recovery`` record (``eps = 1/MTTR`` so the rolling gate treats a
slower recovery like a throughput regression).

The sweep lane reuses ``tools.graftchaos.run_sweep`` on a small
(point-glob x action) subset — the full matrix is the offline
``graftchaos --sweep``; CI keeps a canary within the tier-1 window.

Exits nonzero if the child survives, the chain does not commit, resume
diverges, or the sweep reports a violation. Writes a JSON summary (CI
artifact) with --out; --trajectory optionally appends the recovery
record to a trajectory file.

    python -m tools.chaos_smoke --out /tmp/chaos_smoke.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

if __package__ in (None, ""):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

KILL_HIT = 5          # fit dies training batch 5 of N_BATCHES


def _dir_bytes(path: str) -> int:
    total = 0
    for dp, _dn, fn in os.walk(path):
        total += sum(os.path.getsize(os.path.join(dp, f)) for f in fn)
    return total


def _run_child(autosave_dir: str) -> subprocess.CompletedProcess:
    """Spawn the doomed trainer with the fault armed over the env —
    the cross-process OE_CHAOS_PLAN wire, not an in-process plan."""
    from tools.graftchaos import N_BATCHES  # noqa: F401 — doc anchor
    env = dict(os.environ)
    env["OE_CHAOS_PLAN"] = json.dumps({
        "faults": [{"point": "trainer.fit.step",
                    "action": "kill_thread", "hit": KILL_HIT}],
        "seed": 0})
    return subprocess.run(
        [sys.executable, "-m", "tools.chaos_smoke", "--child",
         "--dir", autosave_dir],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _child_main(autosave_dir: str) -> int:
    """The doomed trainer: arm from env, fit with autosaves, die."""
    from openembedding_tpu.analysis import chaos
    import jax
    from openembedding_tpu.parallel.mesh import create_mesh
    from tools.graftchaos import (AUTOSAVE, N_BATCHES, _build_trainer,
                                  _synthetic_batches)
    plan = chaos.install_from_env()
    if plan is None:
        print("chaos_smoke --child: OE_CHAOS_PLAN not set",
              file=sys.stderr)
        return 3
    mesh = create_mesh(2, 4, jax.devices())
    batches = _synthetic_batches(N_BATCHES)
    tr = _build_trainer(mesh)
    s0 = tr.init(jax.random.PRNGKey(0), tr.shard_batch(batches[0]))
    tr.fit(s0, batches, autosave_every=AUTOSAVE,
           autosave_dir=autosave_dir)
    # reachable only if the armed kill never fired
    print("chaos_smoke --child: fit SURVIVED the armed kill",
          file=sys.stderr)
    return 4


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="", help="JSON summary path")
    ap.add_argument("--trajectory", default="",
                    help="append the recovery record here (JSONL)")
    ap.add_argument("--sweep-points", default="trainer.*",
                    help="fnmatch glob for the sweep-subset lane")
    ap.add_argument("--sweep-actions", default="raise,kill_thread",
                    help="comma list of fault classes for the subset")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--dir", default="", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.child:
        return _child_main(args.dir)

    import jax
    from openembedding_tpu import checkpoint_delta as cd
    from tools import graftchaos as gc
    from tools import graftwatch as gw

    summary = {"ok": False, "kill": {}, "resume": {}, "sweep": {}}
    failures = []

    with tempfile.TemporaryDirectory(prefix="chaos-smoke-") as d:
        ck = os.path.join(d, "auto")

        # --- lane 1: cross-process kill-mid-fit ------------------------
        t0 = time.perf_counter()
        child = _run_child(ck)
        child_s = time.perf_counter() - t0
        killed = child.returncode != 0 and "ChaosKill" in child.stderr
        summary["kill"] = {"returncode": child.returncode,
                           "killed_by_chaos": killed,
                           "duration_s": round(child_s, 3)}
        if not killed:
            failures.append(
                f"child was not killed by the armed fault "
                f"(rc={child.returncode}): {child.stderr[-800:]}")
        manifest = cd.read_manifest(ck) if os.path.isdir(ck) else None
        if manifest is None:
            failures.append("no committed delta manifest after kill")
            cursor = 0
        else:
            verified, _dropped = cd.verify_chain(ck, manifest)
            cursor = int(cd.resume_extra(manifest, verified)
                         ["fit"]["cursor"])
        summary["kill"]["committed_cursor"] = cursor

        # --- lane 2: resume -> bit-identity + MTTR ---------------------
        if manifest is not None:
            w = gc.WORLD.ensure_trainer()
            bytes_replayed = _dir_bytes(ck)
            t0 = time.perf_counter()
            tr = gc._build_trainer(w.mesh)
            s0 = tr.init(jax.random.PRNGKey(0),
                         tr.shard_batch(w.batches[0]))
            s1, fit_info = tr.fit(s0, list(w.batches), resume_from=ck,
                                  autosave_every=gc.AUTOSAVE,
                                  autosave_dir=ck)
            mttr_s = time.perf_counter() - t0
            diff = gc._fingerprint_diff(w.baseline,
                                        gc._fingerprint(tr, s1))
            steps_lost = max(0, KILL_HIT - 1 - cursor)
            summary["resume"] = {
                "mttr_s": round(mttr_s, 3),
                "steps_lost": steps_lost,
                "bytes_replayed": bytes_replayed,
                "bit_identical": diff == "",
            }
            if diff:
                failures.append(f"resume diverged from baseline: {diff}")
            else:
                rec = gw.make_recovery_record(
                    mttr_s=mttr_s, steps_lost=steps_lost,
                    bytes_replayed=bytes_replayed,
                    config={"source": "chaos_smoke",
                            "lane": "kill-mid-fit",
                            "autosave_every": gc.AUTOSAVE,
                            "batches": gc.N_BATCHES})
                summary["resume"]["record"] = rec
                if args.trajectory:
                    gw.append_record(args.trajectory, rec)

    # --- lane 3: sweep subset ------------------------------------------
    actions = [a.strip() for a in args.sweep_actions.split(",")
               if a.strip()]
    report = gc.run_sweep(["ckpt", "ingest", "serving"],
                          args.sweep_points, actions, args.seed,
                          progress=True)
    summary["sweep"] = report
    if report["counts"]["violation"]:
        failures.append(
            f"sweep subset found {report['counts']['violation']} "
            f"violation(s)")

    summary["ok"] = not failures
    summary["failures"] = failures
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)
            f.write("\n")
    print(json.dumps({"ok": summary["ok"],
                      "killed_by_chaos":
                          summary["kill"].get("killed_by_chaos"),
                      "committed_cursor":
                          summary["kill"].get("committed_cursor"),
                      "mttr_s": summary["resume"].get("mttr_s"),
                      "bit_identical":
                          summary["resume"].get("bit_identical"),
                      "sweep": summary["sweep"].get("counts"),
                      "failures": failures}, indent=1))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
