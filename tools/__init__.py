"""Operational CLIs: lint/contract gates and device diagnostics.

A package so the gates run module-style from the repo root (the tier-1
lane invokes ``python -m tools.graftlint openembedding_tpu/``).
"""
