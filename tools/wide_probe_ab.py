"""A/B the wide-pair probe: current two-word compare vs an int64-bitcast
single-word compare, at the hash-bench shapes, on the live backend.

If the bitcast variant wins >=10% the probe gets the optimization;
otherwise the ~28% wide-vs-int32 gap is gather-bandwidth (2x key bytes),
not compare cost, and the README statement stands as measured.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from openembedding_tpu import hash_table as hl  # noqa: E402


def find_rows_bitcast(table_keys, query, max_probes=hl.DEFAULT_MAX_PROBES):
    """find_rows for wide tables with pairs bitcast to int64: the probe
    gathers the same bytes but matches on ONE word."""
    query = hl.check_key_dtype(table_keys, query)
    capacity = table_keys.shape[0]
    n = query.shape[0]
    bsz, nb, chain = hl.table_layout(capacity, max_probes)
    h = hl.probe_starts(query, capacity, max_probes)
    b0 = h // bsz
    bkts = b0[:, None] + jnp.arange(chain, dtype=jnp.int32)[None, :]
    empty = hl.empty_key(table_keys.dtype)
    t64 = lax.bitcast_convert_type(table_keys, jnp.int64)      # [cap]
    q64 = lax.bitcast_convert_type(query, jnp.int64)           # [n]
    probed = jnp.take(t64.reshape(nb, bsz), bkts, axis=0)
    match = probed.reshape(n, chain * bsz) == q64[:, None]
    valid = query[:, 1] != empty
    hit = jnp.any(match, axis=1)
    first = jnp.argmax(match, axis=1).astype(jnp.int32)
    slot = h + first
    return jnp.where(hit & valid, slot, -1)


def bench(fn, args, steps=30):
    f = jax.jit(fn)
    r = f(*args)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(steps):
        r = f(*args)
    jax.block_until_ready(r)
    return (time.perf_counter() - t0) / steps * 1e6


def main():
    # Under the framework's x64-off baseline JAX TRUNCATES int64 bitcasts
    # to int32 (shape preserved, a warning emitted) — the single-word
    # compare simply does not exist there. Detect and report cleanly;
    # JAX_ENABLE_X64=1 in the environment runs the actual A/B.
    probe = lax.bitcast_convert_type(
        jnp.zeros((2, 2), jnp.int32), jnp.int64)
    if probe.dtype != jnp.int64 or probe.shape != (2,):
        print("int64 bitcast unavailable: jax_enable_x64 is off (the "
              "framework baseline), so JAX truncates the bitcast to "
              "int32 — the wide pair probe has no single-word-compare "
              "variant here. Re-run with JAX_ENABLE_X64=1 to measure "
              "the hypothetical x64 path.")
        return
    cap = hl.round_capacity(1 << 22)
    batch = 32768
    rng = np.random.RandomState(0)
    meta_keys = rng.randint(0, 1 << 62, size=cap, dtype=np.int64)
    table_keys = jnp.asarray(hl.split64(meta_keys))   # [cap, 2] int32
    # queries: half present, half absent
    q64 = np.concatenate([meta_keys[rng.randint(0, cap, batch // 2)],
                          rng.randint(0, 1 << 62, batch // 2,
                                      dtype=np.int64)])
    query = jnp.asarray(hl.split64(q64))

    a = jnp.asarray(np.asarray(
        jax.jit(hl.find_rows)(table_keys, query)))
    b = jnp.asarray(np.asarray(
        jax.jit(find_rows_bitcast)(table_keys, query)))
    same = bool(jnp.all(a == b))
    print(f"agreement: {same}")
    assert same

    us_pair = bench(hl.find_rows, (table_keys, query))
    us_bit = bench(find_rows_bitcast, (table_keys, query))
    print(f"two-word compare: {us_pair:8.1f} us/batch")
    print(f"int64 bitcast:    {us_bit:8.1f} us/batch "
          f"({us_pair/us_bit:.2f}x)")


if __name__ == "__main__":
    main()
