"""Second-stage offload diagnosis: device-blocked timings per piece.

offload_diag.py showed ~177 ms per prepared-apply cycle while the
isolated insert loop showed 13 ms — but that loop blocked only at the
end, so async dispatch hid the device program time. Here every piece is
block_until_ready'd per call:

  a) the device insert program alone (1700 new rows, uid table)
  b) the jitted train step, fully-resident batch
  c) shard_batch h2d alone
  d) apply_prepared with ZERO misses (pure bookkeeping + overflow read)
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main():
    import optax
    from openembedding_tpu import (EmbeddingCollection, EmbeddingSpec,
                                   EmbeddingVariableMeta, Trainer)
    from openembedding_tpu.models import deepctr
    from openembedding_tpu.offload import ShardedOffloadedTable
    from openembedding_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(1, len(jax.devices()))
    vocab, cache_cap, dim, batch = 2_000_000, 1 << 22, 8, 4096
    opt = {"category": "adagrad", "learning_rate": 0.01}
    init = {"category": "constant", "value": 0.01}
    table = ShardedOffloadedTable(
        "uid", EmbeddingVariableMeta(embedding_dim=dim,
                                     vocabulary_size=vocab),
        opt, init, vocab=vocab, cache_capacity=cache_cap, mesh=mesh)
    lin = ShardedOffloadedTable(
        "uid:linear", EmbeddingVariableMeta(embedding_dim=1,
                                            vocabulary_size=vocab),
        opt, init, vocab=vocab, cache_capacity=cache_cap, mesh=mesh)
    specs = (table.embedding_spec(), lin.embedding_spec(),
             EmbeddingSpec(name="ctx", input_dim=100_000, output_dim=dim,
                           optimizer=opt),
             EmbeddingSpec(name="ctx:linear", input_dim=100_000,
                           output_dim=1, optimizer=opt))
    coll = EmbeddingCollection(specs, mesh)
    trainer = Trainer(deepctr.build_model("deepfm", ("uid", "ctx")),
                      coll, optax.adagrad(0.01),
                      offload={"uid": table, "uid:linear": lin},
                      pipeline_depth=2)
    rng = np.random.RandomState(0)
    uid0 = rng.randint(0, 50_000, batch).astype(np.int32)

    def mk(uid):
        ctx = (uid * 7 % 100_000).astype(np.int32)
        return {"label": (uid % 4 == 0).astype(np.float32),
                "dense": np.tile((uid % 13).astype(np.float32)[:, None],
                                 (1, 13)),
                "sparse": {"uid": uid, "uid:linear": uid,
                           "ctx": ctx, "ctx:linear": ctx}}
    state = trainer.init(jax.random.PRNGKey(0),
                         trainer.shard_batch(mk(uid0)))
    # make [0, 50k) resident
    for i in range(14):
        state, m = trainer.train_step(
            state, mk(rng.randint(0, 50_000, batch).astype(np.int32)))
    jax.block_until_ready(m["loss"])
    table.check_overflow()
    lin.check_overflow()

    # a) device insert program alone, blocked per call
    emb = dict(state.emb)
    n = 16
    t0 = time.perf_counter()
    for i in range(n):
        ids = np.arange(100_000 + i * 1700, 100_000 + (i + 1) * 1700,
                        dtype=np.int32)
        emb["uid"] = table._insert_from_host(emb["uid"], ids)
        jax.block_until_ready(emb["uid"].keys)
    per = (time.perf_counter() - t0) / n
    print(f"a) insert 1700 rows, device-blocked:    {per*1e3:8.2f} ms")
    table._overflow_latest = None

    # b) jitted step, fully-resident, blocked per call (the jitted step
    # donates its state arg, so thread the returned state through)
    bt = [mk(rng.randint(0, 50_000, batch).astype(np.int32))
          for _ in range(8)]
    sb = [trainer.shard_batch(b) for b in bt]
    t0 = time.perf_counter()
    for i in range(16):
        state, m = trainer._train_step(state, sb[i % 8])
        jax.block_until_ready(m["loss"])
    per = (time.perf_counter() - t0) / 16
    print(f"b) jitted step, presharded, blocked:    {per*1e3:8.2f} ms")
    # b2) same but pipelined (block only at the end)
    t0 = time.perf_counter()
    for i in range(16):
        state, m = trainer._train_step(state, sb[i % 8])
    jax.block_until_ready(m["loss"])
    per = (time.perf_counter() - t0) / 16
    print(f"b2) jitted step, presharded, async:     {per*1e3:8.2f} ms")

    # c) shard_batch h2d alone
    t0 = time.perf_counter()
    for i in range(16):
        out = trainer.shard_batch(bt[i % 8])
        jax.block_until_ready(jax.tree.leaves(out))
    per = (time.perf_counter() - t0) / 16
    print(f"c) shard_batch h2d, blocked:            {per*1e3:8.2f} ms")

    # d) apply_prepared with zero misses
    t0 = time.perf_counter()
    for i in range(16):
        prep = table.host_prepare(bt[i % 8]["sparse"]["uid"])
        emb2 = table.apply_prepared(state.emb["uid"], prep)
        jax.block_until_ready(jax.tree.leaves(emb2))
    per = (time.perf_counter() - t0) / 16
    print(f"d) prepare+apply, zero misses, blocked: {per*1e3:8.2f} ms")


if __name__ == "__main__":
    main()
