"""Sixth stage: per-transfer fixed overhead in the tunnel's degraded
mode. Does N small puts cost ~N x the one-big-put price?

Phase 1 enters the degraded mode the way the trainer does (big sharded
state + a few donating steps). Then:
  a) 12 fresh small arrays per iter, one device_put each, block at end
  b) 1 fresh array of the same total bytes per iter, block at end
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import jax  # noqa: E402


def main():
    import optax
    from openembedding_tpu import (EmbeddingCollection, EmbeddingSpec,
                                   EmbeddingVariableMeta, Trainer)
    from openembedding_tpu.models import deepctr
    from openembedding_tpu.offload import ShardedOffloadedTable
    from openembedding_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(1, len(jax.devices()))
    vocab, cache_cap, dim, batch = 2_000_000, 1 << 22, 8, 4096
    opt = {"category": "adagrad", "learning_rate": 0.01}
    init = {"category": "constant", "value": 0.01}
    table = ShardedOffloadedTable(
        "uid", EmbeddingVariableMeta(embedding_dim=dim,
                                     vocabulary_size=vocab),
        opt, init, vocab=vocab, cache_capacity=cache_cap, mesh=mesh)
    lin = ShardedOffloadedTable(
        "uid:linear", EmbeddingVariableMeta(embedding_dim=1,
                                            vocabulary_size=vocab),
        opt, init, vocab=vocab, cache_capacity=cache_cap, mesh=mesh)
    specs = (table.embedding_spec(), lin.embedding_spec(),
             EmbeddingSpec(name="ctx", input_dim=100_000, output_dim=dim,
                           optimizer=opt),
             EmbeddingSpec(name="ctx:linear", input_dim=100_000,
                           output_dim=1, optimizer=opt))
    coll = EmbeddingCollection(specs, mesh)
    trainer = Trainer(deepctr.build_model("deepfm", ("uid", "ctx")),
                      coll, optax.adagrad(0.01),
                      offload={"uid": table, "uid:linear": lin},
                      pipeline_depth=2)
    rng = np.random.RandomState(0)

    def mk():
        uid = rng.randint(0, 30_000, batch).astype(np.int32)
        ctx = (uid * 7 % 100_000).astype(np.int32)
        return {"label": (uid % 4 == 0).astype(np.float32),
                "dense": np.tile((uid % 13).astype(np.float32)[:, None],
                                 (1, 13)),
                "sparse": {"uid": uid, "uid:linear": uid,
                           "ctx": ctx, "ctx:linear": ctx}}
    state = trainer.init(jax.random.PRNGKey(0), trainer.shard_batch(mk()))
    for i in range(3):
        state, m = trainer.train_step(state, mk())
    jax.block_until_ready(m["loss"])
    table.check_overflow(); lin.check_overflow()
    print("degraded-mode entered (trainer warm)", flush=True)

    kb = 40  # ~12 arrays x 40 KB = the offload step's transfer profile
    for label, n_arrays in (("12 x 40KB", 12), ("1 x 480KB", 1),
                            ("3 x 160KB", 3)):
        per_bytes = kb * 1024 * 12 // n_arrays
        times = []
        for it in range(8):
            bufs = [np.random.randint(0, 1 << 30, per_bytes // 4)
                    .astype(np.int32) for _ in range(n_arrays)]
            t0 = time.perf_counter()
            out = [jax.device_put(b) for b in bufs]
            jax.block_until_ready(out)
            times.append(time.perf_counter() - t0)
        times.sort()
        print(f"{label}: median {1e3*times[len(times)//2]:7.2f} ms "
              f"(min {1e3*times[0]:.2f}, max {1e3*times[-1]:.2f})",
              flush=True)

    # async pipelining test: 24 puts dispatched, ONE block at the end
    bufs = [np.random.randint(0, 1 << 30, kb * 256).astype(np.int32)
            for _ in range(24)]
    t0 = time.perf_counter()
    out = [jax.device_put(b) for b in bufs]
    jax.block_until_ready(out)
    print(f"24 x 40KB async batch: {1e3*(time.perf_counter()-t0):7.2f} ms "
          f"total", flush=True)


if __name__ == "__main__":
    main()
