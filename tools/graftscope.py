"""graftscope CLI: traced capture + expected-vs-measured byte ledger.

    python -m tools.graftscope [--mesh 2x4] [--steps 10]
                               [--plane a2a+grouped] [--out trace.json]

Builds a virtual CPU mesh and makes the device bench round honest in
three moves (``openembedding_tpu/analysis/scope.py``):

1. **Expected bytes** — lower every registered plane's pull/push
   program exactly as the training path runs it and cost-account its
   collectives from the compiled HLO (the same numbers
   ``analysis/contracts.py`` bounds; each program is audited against
   its contract here too, so the printed bytes provably sit inside the
   enforced bounds).
2. **Measured spans** — run ``--steps`` eager pull/push dispatches per
   plane (compile warmed up outside the measured window) so every
   exchange lands in the graftscope latency histograms, then print the
   per-plane/per-stage table: calls, p50/p95 latency, expected
   collective bytes, achieved GB/s at the p50, and the program's
   expected per-device HBM peak (graftwatch memory ledger) — latency,
   bytes, and memory in one artifact.
3. **Traced train run** — ``--steps`` real ``Trainer.train_step`` calls
   on ``--plane`` (step spans, lookahead spans) captured into the span
   rings and written as Chrome-trace/Perfetto JSON (``--out``; open at
   https://ui.perfetto.dev).

Exit 0 when every contract holds, the trace round-trips as JSON, and
every plane recorded nonzero pull AND push spans — the CI smoke
invocation relies on that.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="span capture + per-plane byte ledger")
    ap.add_argument("--mesh", default="2x4",
                    help="DATAxMODEL virtual mesh shape (default 2x4)")
    ap.add_argument("--steps", type=int, default=10,
                    help="measured dispatches per plane/stage AND train "
                         "steps in the traced run")
    ap.add_argument("--plane", default="a2a",
                    help="plane for the traced train-step run; the "
                         "ledger always covers every registered plane")
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--out", default="graftscope_trace.json",
                    help="Chrome-trace/Perfetto JSON output path")
    ap.add_argument("--skip-train", action="store_true",
                    help="skip the traced Trainer run (ledger only)")
    ap.add_argument("--export-stats", default="",
                    help="also dump the capture's observed-stats window "
                         "(per-table pull uniqueness/skew, serving "
                         "lookup sizes, cache + ingest counters) as "
                         "JSON in the tools/graftplan input schema")
    args = ap.parse_args(argv)
    data, model = (int(x) for x in args.mesh.split("x"))

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")
    from openembedding_tpu.utils.jaxcompat import set_num_cpu_devices
    set_num_cpu_devices(data * model)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from openembedding_tpu.analysis import contracts, scope
    from openembedding_tpu.analysis import programs
    from openembedding_tpu.parallel.mesh import create_mesh, DATA_AXIS
    from openembedding_tpu.utils import observability

    mesh = create_mesh(data, model)
    scope.set_tracing(True)
    failures = 0

    planes = sorted({p for (p, prog) in contracts.REGISTRY
                     if prog in ("pull", "push")})

    # --- 1. expected bytes from compiled HLO (contract-audited) ------------
    expected = []
    for plane in planes:
        for program in ("pull", "push"):
            try:
                expected.append(scope.plane_expected_bytes(
                    mesh, plane, program, batch=args.batch, dim=args.dim))
            except Exception as e:  # noqa: BLE001 — report every program
                failures += 1
                print(f"FAIL expected-bytes {plane}/{program}: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
    print(f"expected bytes computed for {len(expected)} programs "
          f"(contract-audited against analysis/contracts.py bounds)")

    # --- 2. measured pull/push rounds per plane ----------------------------
    # build + warm every plane first (the dispatch program cache keys on
    # the evaluate_performance flag, so warmup must run with the SAME
    # flag as measurement), then drop the warmup samples and measure
    rng = np.random.RandomState(0)
    sh = NamedSharding(mesh, P(DATA_AXIS))
    observability.set_evaluate_performance(True)

    def _vocab(plane: str) -> int:
        return (1 << 14) if plane == "a2a+grouped" else (1 << 16)

    def _batches(coll, vocab):
        names = tuple(coll.specs)
        idxs = {n: jax.device_put(
            jnp.asarray(rng.randint(0, vocab, size=args.batch)
                        .astype(np.int32)), sh) for n in names}
        grads = {n: jax.device_put(
            jnp.zeros((args.batch, args.dim), jnp.float32), sh)
            for n in names}
        return idxs, grads

    worlds = {}
    for plane in planes:
        vocab = _vocab(plane)
        if plane == "a2a+grouped":
            coll = programs._grouped_collection(
                mesh, tables=3, vocab=vocab, dim=args.dim, use_hash=False)
        else:
            coll = programs._collection(mesh, plane, vocab=vocab,
                                        dim=args.dim, use_hash=False)
        states = coll.init(jax.random.PRNGKey(0))
        idxs, grads = _batches(coll, vocab)
        jax.block_until_ready(coll.pull(states, idxs))       # compile pull
        states = coll.apply_gradients(states, idxs, grads)   # compile push
        jax.block_until_ready(jax.tree.leaves(states))
        worlds[plane] = (coll, states)
    scope.HISTOGRAMS.reset()     # drop compile-inclusive warmup samples
    scope.reset()
    window_t0 = time.perf_counter()   # stats window starts post-warmup

    for plane in planes:
        coll, states = worlds[plane]
        vocab = _vocab(plane)
        for _ in range(args.steps):
            idxs, grads = _batches(coll, vocab)
            coll.pull(states, idxs)      # plane_timed blocks + records
            states = coll.apply_gradients(states, idxs, grads)
        worlds[plane] = (coll, states)
    # evaluate_performance stays ON through the traced Trainer run so
    # record_batch_stats feeds the per-table distributions printed
    # below (the host-side stats run outside the jitted step)

    rows = scope.ledger_rows(expected)
    print()
    print(scope.format_ledger(rows))
    print()
    for r in rows:
        ops = ", ".join(f"{op}: {c}x/{b}B"
                        for op, (c, b) in sorted(r["per_op"].items()))
        print(f"  {r['plane']}/{r['stage']}: {ops or 'no collectives'}")

    for r in rows:
        if r["calls"] < args.steps:
            failures += 1
            print(f"FAIL {r['plane']}/{r['stage']}: {r['calls']} span(s) "
                  f"recorded < {args.steps} dispatched", file=sys.stderr)


    # --- 3. traced train-step run on --plane -------------------------------
    table_dims = {}
    if not args.skip_train:
        import optax
        from openembedding_tpu.embedding import EmbeddingCollection
        from openembedding_tpu.models import deepctr
        from openembedding_tpu.training import Trainer
        features = ("c0", "c1")
        vocab, dim, batch = 4096, 8, 256
        specs = deepctr.make_feature_specs(features, vocab, dim,
                                           plane=args.plane)
        table_dims = {s.name: s.output_dim for s in specs}
        coll = EmbeddingCollection(
            specs, mesh,
            default_optimizer={"category": "adagrad",
                               "learning_rate": 0.1})
        trainer = Trainer(deepctr.build_model("deepfm", features), coll,
                          optax.adam(1e-2))
        brng = np.random.RandomState(1)
        batch_data = {
            "label": brng.randint(0, 2, size=batch).astype(np.float32),
            "dense": brng.randn(batch, 4).astype(np.float32),
            "sparse": {f: brng.randint(0, vocab, size=batch)
                       .astype(np.int32) for f in features},
        }
        for f in features:
            batch_data["sparse"][f + deepctr.LINEAR_SUFFIX] = \
                batch_data["sparse"][f]
        state = trainer.init(jax.random.PRNGKey(0),
                             trainer.shard_batch(batch_data))
        for _ in range(args.steps):
            state, _metrics = trainer.train_step(state, batch_data)
        n = scope.HISTOGRAMS.count("span_step_seconds")
        p50 = scope.HISTOGRAMS.quantile("span_step_seconds", 0.5)
        p95 = scope.HISTOGRAMS.quantile("span_step_seconds", 0.95)
        print(f"\ntraced run ({args.plane}, deepfm, {args.steps} steps): "
              f"{n} step spans, p50 {p50 * 1e3:.1f} ms, "
              f"p95 {p95 * 1e3:.1f} ms (first step includes compile — "
              "deliberately kept: the trace should show it)")
        if n < args.steps:
            failures += 1
            print(f"FAIL traced run: {n} step spans < {args.steps}",
                  file=sys.stderr)
    observability.set_evaluate_performance(False)

    # batch-shape distribution series recorded this capture: the
    # per-table pull stats (traced run, evaluate_performance on) and —
    # when a serving path ran in-process — the per-variable serving
    # lookup-size histogram (ISSUE 11: the input the micro-batching
    # scheduler will be sized from)
    dist_names = ("pull_rows", "pull_unique_ratio", "pull_key_skew",
                  "serving_lookup_rows")
    dist = [(n, lb) for (n, lb) in scope.HISTOGRAMS.series()
            if n in dist_names]
    if dist:
        print("\ndistributions (count / p50 / p95):")
        for name, labels in dist:
            lab = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            print(f"  {name}{{{lab}}}: "
                  f"{scope.HISTOGRAMS.count(name, **labels)} / "
                  f"{scope.HISTOGRAMS.quantile(name, 0.5, **labels):.4g}"
                  f" / "
                  f"{scope.HISTOGRAMS.quantile(name, 0.95, **labels):.4g}")

    # --- observed-stats window export (tools/graftplan input) --------------
    if args.export_stats:
        from tools.graftwatch import device_fingerprint
        from openembedding_tpu.analysis import plan as plan_lib
        fp, device = device_fingerprint()
        window = plan_lib.collect_window(
            window_s=time.perf_counter() - window_t0,
            fingerprint=fp, device=device, table_dims=table_dims)
        problems = plan_lib.validate_window(window)
        if problems:
            failures += 1
            print("FAIL stats window does not validate against its own "
                  "schema: " + "; ".join(problems), file=sys.stderr)
        else:
            with open(args.export_stats, "w", encoding="utf-8") as f:
                json.dump(window, f, indent=2, sort_keys=True)
                f.write("\n")
            print(f"wrote {args.export_stats}: stats window "
                  f"({len(window['tables'])} tables, "
                  f"{window['serving']['lookup_rows']['count']} serving "
                  f"lookups, fingerprint {fp})")

    # --- trace export + validation -----------------------------------------
    scope.export_chrome_trace(args.out)
    try:
        with open(args.out, "r", encoding="utf-8") as f:
            trace = json.load(f)
        n_events = sum(1 for e in trace["traceEvents"]
                       if e.get("ph") == "X")
        if n_events == 0:
            raise ValueError("trace has no span events")
        print(f"wrote {args.out}: {n_events} span events "
              f"(open in https://ui.perfetto.dev)")
    except Exception as e:  # noqa: BLE001 — a broken trace must fail CI
        failures += 1
        print(f"FAIL trace export: {type(e).__name__}: {e}",
              file=sys.stderr)

    if failures:
        print(f"graftscope: {failures} failure(s)", file=sys.stderr)
        return 1
    print("graftscope: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
