"""graftwatch CLI: versioned bench trajectory + perf-regression gate.

    python -m tools.graftwatch --record --quick     # cpu8 micro-bench
    python -m tools.graftwatch --gate               # regression gate
    python -m tools.graftwatch --validate-bench     # bench-file audit

Bench entries used to be schemaless one-off JSON blobs: no git sha, no
hardware fingerprint, nothing consuming them — a perf regression
between PRs was undetectable until someone eyeballed numbers. This
tool closes the loop (the reference's own benchmark discipline is
reproducible per-config records, ``documents/en/benchmark.md``):

* ``--record`` runs a small per-plane pull/push micro-bench on a
  virtual cpu mesh (``--quick`` for the CI-sized variant) and appends
  ONE schema-versioned record per registered plane to
  ``BENCH_trajectory.jsonl``: git sha, jax/jaxlib versions, hardware
  fingerprint, eps with min/max band, graftscope span percentiles,
  HLO-derived expected collective bytes, and the graftwatch memory
  ledger (``analysis/memwatch.py``) for the same programs.
* ``--gate`` compares the NEWEST record of each (plane, fingerprint,
  config) group against the trailing baseline (median of the previous
  ``--window`` records) with a noise band derived from each record's
  own eps_min/eps_max spread. No baseline -> soft pass with a warning
  (the first record on new hardware cannot regress against anything);
  baseline present + any metric worse than the band -> exit 1.
* ``--validate-bench`` audits every entry of ``bench_suite.json`` and
  the ``BENCH_r0*.json`` attempt logs against the bench-entry schema:
  entries either pass or are explicitly grandfathered with their
  missing fields listed — no silently unreadable history.

``bench.py --trajectory <path>`` appends its own throughput entries
through :func:`record_from_bench`, so real device rounds land in the
same trajectory as the CI micro-bench. ``tools/graftload.py`` appends
``serving`` records (:func:`make_serving_record`: offered/achieved
QPS, coordinated-omission-free per-route p50/p95/p99, error + replica
counts) to the same file, and the gate covers their latency quantiles:
a serving regression is **p99 up OR sustained QPS down** beyond the
noise band.

Gate/validate modes import no jax — they run anywhere, instantly.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time
from typing import Any, Dict, List, Mapping, Optional, Tuple

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

SCHEMA_VERSION = 1
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAJECTORY_FILE = os.path.join(REPO_ROOT, "BENCH_trajectory.jsonl")

# gate tuning: the band is derived from measured eps spread, floored at
# MIN_BAND (2-core CI boxes jitter ~20% between blocks) and widened by
# SAFETY; a genuine 2x regression (50% drop) always clears the band,
# block-to-block noise never should
MIN_BAND = 0.25
BAND_SAFETY = 1.4
BASELINE_WINDOW = 5
# tail quantiles (p99) carry far more sampling variance than medians:
# an O(500)-sample serving storm's p99 is its handful of worst
# requests, which on an oversubscribed CI box measure scheduler
# preemption as much as the server (observed ±50% run-to-run at a
# stable p50). The band doubles for *_p99_ms metrics — a sustained 2x
# tail shift (+100% > 2 x 35%) still fails, scheduler flutter passes.
TAIL_BAND_MULT = 2.0


# --- provenance --------------------------------------------------------------

def git_info() -> Tuple[str, bool]:
    """(sha, dirty) of the repo, or ("unknown", False) outside git."""
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=REPO_ROOT,
            capture_output=True, text=True, timeout=10).stdout.strip()
        dirty = bool(subprocess.run(
            ["git", "status", "--porcelain"], cwd=REPO_ROOT,
            capture_output=True, text=True, timeout=10).stdout.strip())
        return (sha or "unknown"), dirty
    except Exception:  # noqa: BLE001 — provenance is best-effort
        return "unknown", False


def _cpu_model_slug() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    return re.sub(r"[^a-z0-9]+", "_",
                                  model.lower()).strip("_")[:40]
    except OSError:
        pass
    import platform as _platform
    return re.sub(r"[^a-z0-9]+", "_",
                  (_platform.processor() or _platform.machine() or
                   "unknown").lower())[:40]


def device_fingerprint() -> Tuple[str, Dict[str, Any]]:
    """(fingerprint string, device dict) of the LIVE jax backend.

    The fingerprint keys baseline grouping: records from different
    hardware must never gate each other (a GH runner regressing against
    a workstation record is noise, not signal), so it folds in platform,
    device count, device kind, and the host CPU model + core count.
    """
    import jax
    devs = jax.devices()
    platform = devs[0].platform
    kind = getattr(devs[0], "device_kind", "") or platform
    device = {"platform": platform, "n_devices": len(devs),
              "device_kind": kind}
    fp = (f"{platform}{len(devs)}-{_cpu_model_slug()}"
          f"-c{os.cpu_count() or 0}")
    return fp, device


def make_record(*, plane: str, config: Mapping[str, Any], eps: float,
                eps_min: float, eps_max: float,
                scope: Optional[Mapping[str, Any]] = None,
                memory: Optional[Mapping[str, Any]] = None,
                host_memory: Optional[Mapping[str, Any]] = None,
                fingerprint: Optional[str] = None,
                device: Optional[Mapping[str, Any]] = None,
                ts: Optional[str] = None) -> Dict[str, Any]:
    """Assemble one schema-valid trajectory record (provenance fields
    computed live when not supplied)."""
    import datetime
    if fingerprint is None or device is None:
        fingerprint, device = device_fingerprint()
    sha, dirty = git_info()
    try:
        import jax
        jax_v = jax.__version__
    except Exception:  # noqa: BLE001
        jax_v = "unknown"
    try:
        import jaxlib
        jaxlib_v = jaxlib.__version__
    except Exception:  # noqa: BLE001
        jaxlib_v = "unknown"
    return {
        "schema_version": SCHEMA_VERSION,
        "ts": ts or datetime.datetime.now(datetime.timezone.utc)
        .isoformat(timespec="seconds"),
        "git_sha": sha, "git_dirty": dirty,
        "jax": jax_v, "jaxlib": jaxlib_v,
        "fingerprint": fingerprint, "device": dict(device),
        "plane": plane, "config": dict(config),
        "eps": float(eps), "eps_min": float(eps_min),
        "eps_max": float(eps_max),
        "scope": dict(scope) if scope else None,
        "memory": dict(memory) if memory else None,
        "host_memory": dict(host_memory) if host_memory else None,
    }


# --- schema validation -------------------------------------------------------

_NUM = (int, float)


def validate_record(rec: Any) -> List[str]:
    """Problems with one trajectory record ([] == schema-valid)."""
    if not isinstance(rec, dict):
        return ["record is not a JSON object"]
    p: List[str] = []

    def need(key, types):
        v = rec.get(key)
        tt = types if isinstance(types, tuple) else (types,)
        # bool is an int subclass — only accept it where bool is asked
        if not isinstance(v, tt) or (isinstance(v, bool)
                                     and bool not in tt):
            p.append(f"{key}: expected "
                     f"{'/'.join(t.__name__ for t in tt)}, "
                     f"got {type(v).__name__}")
            return None
        return v

    if rec.get("schema_version") != SCHEMA_VERSION:
        p.append(f"schema_version: expected {SCHEMA_VERSION}, "
                 f"got {rec.get('schema_version')!r}")
    for key in ("ts", "git_sha", "jax", "jaxlib", "fingerprint", "plane"):
        need(key, str)
    need("git_dirty", bool)
    need("config", dict)
    dev = need("device", dict)
    if dev is not None:
        if not isinstance(dev.get("platform"), str):
            p.append("device.platform: expected str")
        if not isinstance(dev.get("n_devices"), int):
            p.append("device.n_devices: expected int")
    for key in ("eps", "eps_min", "eps_max"):
        v = need(key, _NUM)
        if v is not None and (isinstance(v, bool) or v <= 0):
            p.append(f"{key}: must be a positive number, got {v!r}")
    if not p and not (rec["eps_min"] <= rec["eps"] <= rec["eps_max"]):
        p.append("eps band violated: need eps_min <= eps <= eps_max")
    scope = rec.get("scope")
    if scope is not None:
        if not isinstance(scope, dict):
            p.append("scope: expected object or null")
        else:
            for stage, entry in scope.items():
                if not isinstance(entry, dict):
                    p.append(f"scope.{stage}: expected object")
                    continue
                for k in ("p50_ms", "p95_ms"):
                    if not isinstance(entry.get(k), _NUM):
                        p.append(f"scope.{stage}.{k}: expected number")
                # p99 is optional (serving records carry it; the
                # micro-bench's 12-sample windows cannot estimate one)
                if "p99_ms" in entry and \
                        not isinstance(entry["p99_ms"], _NUM):
                    p.append(f"scope.{stage}.p99_ms: expected number")
                if not isinstance(entry.get("calls"), int):
                    p.append(f"scope.{stage}.calls: expected int")
                if not isinstance(entry.get("expected_bytes"), int):
                    p.append(f"scope.{stage}.expected_bytes: expected int")
    mem = rec.get("memory")
    if mem is not None and not isinstance(mem, dict):
        p.append("memory: expected object or null")
    ingest = rec.get("ingest")
    if ingest is not None:
        # streaming-ingest records (bench.py run_ingest_ab -> plane
        # "ingest"): eps is the streamed examples/s the gate covers;
        # this section carries the stall/bad-row evidence
        if not isinstance(ingest, dict):
            p.append("ingest: expected object or null")
        else:
            for k in ("stall_p95_ms", "stall_p99_ms"):
                v = ingest.get(k)
                if not isinstance(v, _NUM) or isinstance(v, bool) \
                        or v < 0:
                    p.append(f"ingest.{k}: expected number >= 0")
            for k in ("bad_rows", "pops"):
                v = ingest.get(k)
                if not isinstance(v, int) or isinstance(v, bool) \
                        or v < 0:
                    p.append(f"ingest.{k}: expected int >= 0")
            v = ingest.get("stream_vs_mem")
            if not isinstance(v, _NUM) or isinstance(v, bool) or v <= 0:
                p.append("ingest.stream_vs_mem: expected positive "
                         "number")
    recovery = rec.get("recovery")
    if recovery is not None:
        # fault-recovery records (graftload --respawn, chaos_smoke):
        # eps is recoveries/s (1/MTTR) so the rolling gate catches
        # recovery-time regressions; this section carries the evidence
        if not isinstance(recovery, dict):
            p.append("recovery: expected object or null")
        else:
            v = recovery.get("mttr_s")
            if not isinstance(v, _NUM) or isinstance(v, bool) or v <= 0:
                p.append("recovery.mttr_s: expected positive number")
            for k in ("steps_lost", "bytes_replayed"):
                v = recovery.get(k)
                if not isinstance(v, int) or isinstance(v, bool) \
                        or v < 0:
                    p.append(f"recovery.{k}: expected int >= 0")
    serving = rec.get("serving")
    if serving is not None:
        if not isinstance(serving, dict):
            p.append("serving: expected object or null")
        else:
            for k in ("offered_qps", "achieved_qps"):
                v = serving.get(k)
                if not isinstance(v, _NUM) or isinstance(v, bool) \
                        or v <= 0:
                    p.append(f"serving.{k}: expected positive number")
            if not isinstance(serving.get("errors"), int) \
                    or serving.get("errors", 0) < 0:
                p.append("serving.errors: expected int >= 0")
            if not isinstance(serving.get("replicas"), int) \
                    or serving.get("replicas", 0) < 1:
                p.append("serving.replicas: expected int >= 1")
            # batched-serving fields (optional: pre-batching records
            # carry neither): rejected offers and the scraped
            # server-side coalescing counters
            if "rejected" in serving and (
                    not isinstance(serving["rejected"], int)
                    or isinstance(serving["rejected"], bool)
                    or serving["rejected"] < 0):
                p.append("serving.rejected: expected int >= 0")
            batch = serving.get("batch")
            if batch is not None:
                if not isinstance(batch, dict):
                    p.append("serving.batch: expected object or null")
                else:
                    for k, v in batch.items():
                        if not isinstance(v, _NUM) \
                                or isinstance(v, bool) or v < 0:
                            p.append(f"serving.batch.{k}: expected "
                                     "number >= 0")
    return p


# bench_suite.json entry schema (the pre-trajectory record shape every
# runner in bench.py emits); honest error records are first-class
_BENCH_REQUIRED: Tuple[Tuple[str, Any], ...] = (
    ("value", _NUM), ("unit", str), ("vs_baseline", _NUM),
    ("config", dict), ("ts", str))


def classify_bench_entry(entry: Any) -> Tuple[str, List[str]]:
    """("ok" | "grandfathered" | "invalid", missing-field list).

    ``ok``: a well-formed bench record or an honest error record.
    ``grandfathered``: readable history predating a field (listed) —
    the legacy ``BENCH_r0*.json`` driver attempt logs land here whole.
    ``invalid``: unreadable as bench history at all.
    """
    if not isinstance(entry, dict):
        return "invalid", ["entry is not a JSON object"]
    if {"n", "cmd", "rc"} <= set(entry):
        return "grandfathered", [
            "legacy driver attempt log (n/cmd/rc/tail) — predates the "
            "bench-entry schema; kept as wedge-history provenance"]
    if not isinstance(entry.get("metric"), str):
        return "invalid", ["metric: required str"]
    if isinstance(entry.get("error"), str):
        return "ok", []
    missing = [key for key, types in _BENCH_REQUIRED
               if not isinstance(entry.get(key), types)]
    return ("ok" if not missing else "grandfathered"), missing


def validate_bench_files(root: str = REPO_ROOT) -> Tuple[int, List[str]]:
    """Audit bench_suite.json + BENCH_r0*.json; returns (invalid count,
    report lines)."""
    import glob
    lines: List[str] = []
    invalid = 0
    paths = [os.path.join(root, "bench_suite.json")]
    paths += sorted(glob.glob(os.path.join(root, "BENCH_r0*.json")))
    for path in paths:
        name = os.path.basename(path)
        try:
            with open(path) as f:
                data = json.load(f)
        except FileNotFoundError:
            continue
        except (OSError, json.JSONDecodeError) as e:
            invalid += 1
            lines.append(f"INVALID {name}: unreadable JSON ({e})")
            continue
        entries = data if isinstance(data, list) else [data]
        for i, entry in enumerate(entries):
            status, missing = classify_bench_entry(entry)
            label = entry.get("metric", f"entry[{i}]") \
                if isinstance(entry, dict) else f"entry[{i}]"
            if status == "invalid":
                invalid += 1
                lines.append(f"INVALID {name}:{label}: {missing}")
            elif status == "grandfathered":
                lines.append(f"grandfathered {name}:{label}: "
                             f"missing {missing}")
            else:
                lines.append(f"ok   {name}:{label}")
    return invalid, lines


# --- trajectory IO -----------------------------------------------------------

def load_trajectory(path: str) -> List[Dict[str, Any]]:
    """Schema-valid records from a JSONL trajectory (raises ValueError
    listing every invalid line — a half-corrupt trajectory must not
    silently gate on the readable half)."""
    records: List[Dict[str, Any]] = []
    problems: List[str] = []
    try:
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    problems.append(f"line {lineno}: bad JSON ({e})")
                    continue
                bad = validate_record(rec)
                if bad:
                    problems.append(f"line {lineno}: {'; '.join(bad)}")
                else:
                    records.append(rec)
    except FileNotFoundError:
        return []
    if problems:
        raise ValueError(
            f"{path}: {len(problems)} invalid record(s): "
            + " | ".join(problems[:5]))
    return records


def append_record(path: str, rec: Dict[str, Any]) -> None:
    bad = validate_record(rec)
    if bad:
        raise ValueError(f"refusing to append a schema-invalid record: "
                         f"{bad}")
    with open(path, "a") as f:
        f.write(json.dumps(rec, sort_keys=True) + "\n")


def record_from_bench(result: Mapping[str, Any], *,
                      fingerprint: Optional[str] = None,
                      device: Optional[Mapping[str, Any]] = None
                      ) -> Optional[Dict[str, Any]]:
    """Convert one bench.py result dict into a trajectory record:
    throughput entries (examples/s with an eps band) and checkpoint
    write-rate entries (GB/s with a gbps band, recorded under the
    synthetic ``ckpt`` plane so checkpoint perf gates like step perf);
    None for inconvertible entries."""
    if not isinstance(result, dict) or "error" in result:
        return None
    cfg = dict(result.get("config") or {})
    cfg["source"] = "bench"
    cfg["metric"] = result.get("metric", "")
    if result.get("unit") == "GB/s" \
            and all(isinstance(result.get(k), _NUM)
                    for k in ("value", "gbps_min", "gbps_max")):
        return make_record(
            plane="ckpt", config=cfg,
            eps=result["value"], eps_min=result["gbps_min"],
            eps_max=max(result["gbps_max"], result["value"]),
            fingerprint=fingerprint, device=device,
            ts=result.get("ts"))
    if result.get("unit") != "examples/s":
        return None
    if not all(isinstance(result.get(k), _NUM)
               for k in ("value", "eps_min", "eps_max")):
        return None
    if isinstance(result.get("ingest"), dict):
        # streaming-ingest A/B entries land under the synthetic
        # "ingest" plane (their own baseline group, like "ckpt" and
        # "serving") with the stall/bad-row evidence attached; the
        # gate covers the streamed eps exactly like step throughput
        ing = result["ingest"]
        rec = make_record(
            plane="ingest", config=cfg,
            eps=result["value"], eps_min=result["eps_min"],
            eps_max=result["eps_max"], fingerprint=fingerprint,
            device=device, ts=result.get("ts"))
        # NO defaults: a missing stall/bad-row/A-B measurement must
        # fail schema validation below, not masquerade as a perfect one
        # (stall_p95_ms=0.0 or stream_vs_mem=1.0 are exactly the values
        # the gate exists to verify)
        rec["ingest"] = {
            "stall_p95_ms": ing.get("stall_p95_ms"),
            "stall_p99_ms": ing.get("stall_p99_ms"),
            "bad_rows": ing.get("bad_rows"),
            "pops": ing.get("pops"),
            "stream_vs_mem": result.get("stream_vs_mem"),
        }
        bad = validate_record(rec)
        if bad:
            raise ValueError(
                f"assembled ingest record is schema-invalid: {bad}")
        return rec
    return make_record(
        plane=str(cfg.get("plane", "a2a")), config=cfg,
        eps=result["value"], eps_min=result["eps_min"],
        eps_max=result["eps_max"], fingerprint=fingerprint,
        device=device, ts=result.get("ts"))


def make_serving_record(*, routes: Mapping[str, Mapping[str, Any]],
                        offered_qps: float, achieved_qps: float,
                        errors: int, replicas: int,
                        qps_band: Tuple[float, float],
                        config: Mapping[str, Any],
                        rejected: int = 0,
                        batch_stats: Optional[Mapping[str, Any]] = None,
                        fingerprint: Optional[str] = None,
                        device: Optional[Mapping[str, Any]] = None,
                        ts: Optional[str] = None) -> Dict[str, Any]:
    """One ``serving`` trajectory record (``tools/graftload.py``).

    ``routes`` maps route name (``rest`` / ``native``) to its measured
    latency summary (``calls``, ``p50_ms``, ``p95_ms``, ``p99_ms`` —
    coordinated-omission-free, from intended send time); the quantiles
    land in the record's ``scope`` section so the rolling-baseline gate
    covers them exactly like pull/push stage latencies, with the p99
    gated explicitly. ``eps`` is the sustained (achieved) QPS with
    ``qps_band`` as its per-second spread, so "sustained QPS down"
    gates like step throughput. The ``serving`` section carries the
    open-loop accounting (offered vs achieved, error count, replica
    count) plus — batched storms — the backpressure/coalescing stats:
    ``rejected`` (429-busy offers; a DEFINED response distinct from
    errors) and ``batch`` (the replicas' ``oe_batch_*`` counters:
    flushes / requests / rows / unique rows, scraped off /metrics).
    Raises on a schema-invalid assembly."""
    scope_section = {
        str(route): {"calls": int(r["calls"]),
                     "p50_ms": round(float(r["p50_ms"]), 4),
                     "p95_ms": round(float(r["p95_ms"]), 4),
                     "p99_ms": round(float(r["p99_ms"]), 4),
                     # serving latencies have no HLO-derived byte
                     # expectation — 0 keeps the shared scope schema
                     "expected_bytes": 0, "gbps_p50": 0.0}
        for route, r in routes.items()}
    lo, hi = qps_band
    rec = make_record(
        plane="serving", config=dict(config),
        eps=float(achieved_qps),
        eps_min=min(float(lo), float(achieved_qps)),
        eps_max=max(float(hi), float(achieved_qps)),
        scope=scope_section, fingerprint=fingerprint, device=device,
        ts=ts)
    rec["serving"] = {
        "offered_qps": float(offered_qps),
        "achieved_qps": float(achieved_qps),
        "errors": int(errors), "replicas": int(replicas),
        "rejected": int(rejected)}
    if batch_stats:
        rec["serving"]["batch"] = {str(k): float(v)
                                   for k, v in batch_stats.items()}
    bad = validate_record(rec)
    if bad:
        raise ValueError(f"assembled serving record is schema-invalid: "
                         f"{bad}")
    return rec


def make_recovery_record(*, mttr_s: float, steps_lost: int,
                         bytes_replayed: int,
                         config: Mapping[str, Any],
                         fingerprint: Optional[str] = None,
                         device: Optional[Mapping[str, Any]] = None,
                         ts: Optional[str] = None) -> Dict[str, Any]:
    """One ``recovery`` trajectory record (``tools/graftload.py
    --respawn`` kill-and-respawn lane; ``tools/chaos_smoke.py``
    kill-mid-fit + resume lane).

    ``eps`` is recoveries/second (``1 / mttr_s``) so the rolling
    baseline gate — including ``--strict`` — treats a slower recovery
    exactly like a throughput regression. The ``recovery`` section
    carries the evidence: ``mttr_s`` (kill to serving/trained-again),
    ``steps_lost`` (training steps past the last autosave that had to
    be retrained; 0 for serving respawns), ``bytes_replayed``
    (checkpoint/delta-chain bytes re-read to rebuild the state). Raises
    on a schema-invalid assembly."""
    if mttr_s <= 0:
        raise ValueError(f"mttr_s must be > 0, got {mttr_s}")
    eps = 1.0 / float(mttr_s)
    rec = make_record(plane="recovery", config=dict(config),
                      eps=eps, eps_min=eps, eps_max=eps,
                      fingerprint=fingerprint, device=device, ts=ts)
    rec["recovery"] = {"mttr_s": round(float(mttr_s), 4),
                       "steps_lost": int(steps_lost),
                       "bytes_replayed": int(bytes_replayed)}
    bad = validate_record(rec)
    if bad:
        raise ValueError(f"assembled recovery record is schema-invalid: "
                         f"{bad}")
    return rec


# --- the regression gate -----------------------------------------------------

def _rel_spread(rec: Mapping[str, Any]) -> float:
    eps = float(rec["eps"]) or 1e-9
    return max(0.0, (float(rec["eps_max"]) - float(rec["eps_min"])) / eps)


def _gate_metrics(rec: Mapping[str, Any]) -> Dict[str, Tuple[float, bool]]:
    """metric -> (value, higher_is_better) for one record.

    ``eps`` (examples/s, GB/s, or — serving records — sustained QPS)
    gates higher-is-better; the per-stage/per-route latency quantiles
    gate lower-is-better, so a serving regression is "p50/p99 up OR
    sustained QPS down" beyond the noise band."""
    out: Dict[str, Tuple[float, bool]] = {
        "eps": (float(rec["eps"]), True)}
    for stage, entry in (rec.get("scope") or {}).items():
        for q in ("p50_ms", "p99_ms"):
            v = entry.get(q)
            if isinstance(v, _NUM) and v > 0:
                out[f"{stage}_{q}"] = (float(v), False)
    return out


def _group_key(rec: Mapping[str, Any]) -> Tuple[str, str, str]:
    return (str(rec["plane"]), str(rec["fingerprint"]),
            json.dumps(rec.get("config") or {}, sort_keys=True))


def _median(xs: List[float]) -> float:
    xs = sorted(xs)
    return xs[len(xs) // 2]


def gate(records: List[Dict[str, Any]], *, window: int = BASELINE_WINDOW,
         min_band: float = MIN_BAND, safety: float = BAND_SAFETY,
         strict_fingerprint: Optional[str] = None
         ) -> Tuple[int, List[str]]:
    """(regressions, report lines): for each (plane, fingerprint,
    config) group, the newest record vs the trailing-median baseline
    with a spread-derived noise band. Groups without a baseline warn
    and pass (first run on new hardware — "soft-fail" mode) — unless
    ``strict_fingerprint`` is set (the ``--strict`` ARMED mode): then a
    no-baseline group on THAT fingerprint fails loudly — with baselines
    committed for the hardware the gate runs on, a missing one means
    the record/commit pipeline broke, not a new machine. Other
    machines' historical single-record groups stay soft (their
    baselines are not this runner's to demand)."""
    groups: Dict[Tuple[str, str, str], List[Dict[str, Any]]] = {}
    for rec in records:
        groups.setdefault(_group_key(rec), []).append(rec)
    failures = 0
    lines: List[str] = []
    for key in sorted(groups):
        plane, fp, _cfg = key
        seq = sorted(groups[key], key=lambda r: r["ts"])
        newest, base = seq[-1], seq[:-1][-window:]
        if not base:
            if strict_fingerprint is not None \
                    and fp == strict_fingerprint:
                failures += 1
                lines.append(
                    f"NO-BASELINE {plane} [{fp}]: strict gate — commit "
                    "a baseline record for this fingerprint (run "
                    "--record twice) or drop --strict on new hardware")
            else:
                lines.append(f"warn {plane} [{fp}]: no baseline record "
                             "yet — soft pass (gate arms once this "
                             "record lands in the trajectory)")
            continue
        band = safety * max([min_band, _rel_spread(newest)]
                            + [_rel_spread(r) for r in base])
        new_metrics = _gate_metrics(newest)
        for metric, (value, higher) in sorted(new_metrics.items()):
            base_vals = []
            for r in base:
                bm = _gate_metrics(r).get(metric)
                if bm is not None:
                    base_vals.append(bm[0])
            if not base_vals:
                continue
            baseline = _median(base_vals)
            if baseline <= 0:
                continue
            mband = band * (TAIL_BAND_MULT if metric.endswith("_p99_ms")
                            else 1.0)
            delta = (value - baseline) / baseline
            worse = -delta if higher else delta
            verdict = "REGRESSION" if worse > mband else "ok"
            if verdict == "REGRESSION":
                failures += 1
            lines.append(
                f"{verdict:<10} {plane}/{metric} [{fp}]: new={value:.4g} "
                f"baseline={baseline:.4g} ({len(base_vals)} rec) "
                f"delta={delta * 100:+.1f}% band=±{mband * 100:.1f}%")
    if not groups:
        lines.append("warn: trajectory is empty — nothing to gate")
    return failures, lines


# --- the cpu micro-bench (--record) ------------------------------------------

def run_record(args) -> List[Dict[str, Any]]:
    """Per-plane pull/push micro-bench on a virtual CPU mesh: measured
    span percentiles + contract-audited expected bytes + the memory
    ledger, one trajectory record per registered plane."""
    data, model = (int(x) for x in args.mesh.split("x"))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")
    from openembedding_tpu.utils.jaxcompat import set_num_cpu_devices
    set_num_cpu_devices(data * model)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from openembedding_tpu.analysis import memwatch, programs, scope
    from openembedding_tpu.parallel.mesh import create_mesh, DATA_AXIS
    from openembedding_tpu.utils import observability

    mesh = create_mesh(data, model)
    planes = memwatch.registered_planes()
    fingerprint, device = device_fingerprint()
    rng = np.random.RandomState(0)
    sh = NamedSharding(mesh, P(DATA_AXIS))

    def _vocab(plane: str) -> int:
        return (1 << 14) if plane == "a2a+grouped" else (1 << 16)

    def _batches(coll, vocab):
        names = tuple(coll.specs)
        idxs = {n: jax.device_put(
            jnp.asarray(rng.randint(0, vocab, size=args.batch)
                        .astype(np.int32)), sh) for n in names}
        grads = {n: jax.device_put(
            jnp.zeros((args.batch, args.dim), jnp.float32), sh)
            for n in names}
        return idxs, grads

    # expected bytes + memory ledger per plane/program (contract-audited
    # lowering — a plane whose ledger cannot be produced fails --record)
    expected: Dict[str, Dict[str, Any]] = {}
    for plane in planes:
        expected[plane] = {}
        for program in ("pull", "push"):
            expected[plane][program] = scope.plane_expected_bytes(
                mesh, plane, program, batch=args.batch, dim=args.dim)

    # warm every plane's eager dispatch programs with the SAME
    # evaluate_performance flag as measurement (it keys the jit cache)
    observability.set_evaluate_performance(True)
    try:
        worlds = {}
        for plane in planes:
            vocab = _vocab(plane)
            if plane == "a2a+grouped":
                coll = programs._grouped_collection(
                    mesh, tables=3, vocab=vocab, dim=args.dim,
                    use_hash=False)
            else:
                coll = programs._collection(mesh, plane, vocab=vocab,
                                            dim=args.dim, use_hash=False)
            states = coll.init(jax.random.PRNGKey(0))
            idxs, grads = _batches(coll, vocab)
            jax.block_until_ready(coll.pull(states, idxs))
            states = coll.apply_gradients(states, idxs, grads)
            jax.block_until_ready(jax.tree.leaves(states))
            worlds[plane] = (coll, states)
        scope.HISTOGRAMS.reset()      # drop compile-inclusive samples
        scope.reset()

        records = []
        for plane in planes:
            coll, states = worlds[plane]
            vocab = _vocab(plane)
            block_eps = []
            for _ in range(args.blocks):
                t0 = time.perf_counter()
                for _ in range(args.steps):
                    idxs, grads = _batches(coll, vocab)
                    coll.pull(states, idxs)          # plane_timed blocks
                    states = coll.apply_gradients(states, idxs, grads)
                dt = time.perf_counter() - t0
                block_eps.append(args.steps * args.batch / dt)
            worlds[plane] = (coll, states)
            rows = scope.ledger_rows(
                [expected[plane]["pull"], expected[plane]["push"]])
            scope_section = {
                r["stage"]: {"calls": int(r["calls"]),
                             "p50_ms": round(r["p50_ms"], 4),
                             "p95_ms": round(r["p95_ms"], 4),
                             "expected_bytes": int(r["expected_bytes"]),
                             "gbps_p50": round(r["gbps_p50"], 4)
                             if r["gbps_p50"] == r["gbps_p50"] else 0.0}
                for r in rows}
            for r in rows:
                if r["calls"] < args.blocks * args.steps:
                    raise RuntimeError(
                        f"{plane}/{r['stage']}: {r['calls']} span(s) "
                        f"recorded < {args.blocks * args.steps} "
                        "dispatched — the measurement instrumentation "
                        "is broken")
            memory_section = {
                program: dict(expected[plane][program].memory or {})
                or None for program in ("pull", "push")}
            host_mem = {
                src: {k: round(v, 1) for k, v in fields.items()}
                for src, fields in observability.memory_stats().items()}
            records.append(make_record(
                plane=plane,
                config={"mesh": args.mesh, "batch": args.batch,
                        "dim": args.dim, "steps": args.steps,
                        "blocks": args.blocks,
                        "source": "graftwatch-quick" if args.quick
                        else "graftwatch"},
                eps=_median(block_eps), eps_min=min(block_eps),
                eps_max=max(block_eps), scope=scope_section,
                memory=memory_section, host_memory=host_mem,
                fingerprint=fingerprint, device=device))
    finally:
        observability.set_evaluate_performance(False)
    return records


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="bench trajectory recorder + perf-regression gate")
    ap.add_argument("--record", action="store_true",
                    help="run the per-plane micro-bench and append one "
                         "record per plane to the trajectory")
    ap.add_argument("--gate", action="store_true",
                    help="compare newest records against the trailing "
                         "baseline; exit 1 on regression beyond band")
    ap.add_argument("--strict", action="store_true",
                    help="armed gate: a group with no baseline FAILS "
                         "instead of soft-passing (use once baselines "
                         "for this fingerprint are committed)")
    ap.add_argument("--validate-bench", action="store_true",
                    help="audit bench_suite.json + BENCH_r0*.json "
                         "against the bench-entry schema")
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized micro-bench (fewer/smaller blocks)")
    ap.add_argument("--trajectory", default=TRAJECTORY_FILE,
                    help=f"JSONL path (default {TRAJECTORY_FILE})")
    ap.add_argument("--mesh", default="2x4")
    ap.add_argument("--batch", type=int, default=0, help="0 = auto")
    ap.add_argument("--dim", type=int, default=0, help="0 = auto")
    ap.add_argument("--steps", type=int, default=0, help="0 = auto")
    ap.add_argument("--blocks", type=int, default=0, help="0 = auto")
    ap.add_argument("--window", type=int, default=BASELINE_WINDOW,
                    help="trailing records per baseline median")
    ap.add_argument("--min-band", type=float, default=MIN_BAND)
    ap.add_argument("--safety", type=float, default=BAND_SAFETY)
    args = ap.parse_args(argv)
    args.batch = args.batch or (256 if args.quick else 1024)
    args.dim = args.dim or (8 if args.quick else 16)
    args.steps = args.steps or (4 if args.quick else 10)
    args.blocks = args.blocks or (3 if args.quick else 5)

    if not (args.record or args.gate or args.validate_bench):
        ap.error("pick at least one of --record / --gate "
                 "/ --validate-bench")
    rc = 0

    if args.validate_bench:
        invalid, lines = validate_bench_files()
        for ln in lines:
            print(ln)
        if invalid:
            print(f"graftwatch: {invalid} unreadable bench entr(ies)",
                  file=sys.stderr)
            rc = 1
        else:
            print("graftwatch: bench history readable "
                  "(schema-valid or explicitly grandfathered)")

    if args.record:
        try:
            records = run_record(args)
        except Exception as e:  # noqa: BLE001 — a plane whose ledger or
            # spans cannot be produced must fail the recorder loudly
            print(f"graftwatch: --record failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 1
        for rec in records:
            append_record(args.trajectory, rec)
            sc = rec["scope"]
            print(json.dumps({
                "plane": rec["plane"], "eps": round(rec["eps"], 1),
                "eps_band": [round(rec["eps_min"], 1),
                             round(rec["eps_max"], 1)],
                "pull_p50_ms": sc["pull"]["p50_ms"],
                "push_p50_ms": sc["push"]["p50_ms"],
                "fingerprint": rec["fingerprint"]}), flush=True)
        print(f"graftwatch: appended {len(records)} record(s) to "
              f"{args.trajectory}")

    if args.gate:
        try:
            records = load_trajectory(args.trajectory)
        except ValueError as e:
            print(f"graftwatch: {e}", file=sys.stderr)
            return 2
        strict_fp = device_fingerprint()[0] if args.strict else None
        failures, lines = gate(records, window=args.window,
                               min_band=args.min_band,
                               safety=args.safety,
                               strict_fingerprint=strict_fp)
        for ln in lines:
            print(ln)
        if failures:
            print(f"graftwatch: {failures} perf regression(s) beyond "
                  "the noise band", file=sys.stderr)
            rc = 1
        else:
            print("graftwatch: gate clean")
    return rc


if __name__ == "__main__":
    sys.exit(main())
