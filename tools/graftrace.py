"""graftrace CLI: lock-discipline gate over the threaded host planes.

    python -m tools.graftrace openembedding_tpu/ [more paths...]

Exit 0 when clean, 1 with one ``path:line: RULE message`` per violation
otherwise — CI runs this next to graftlint/graftcheck, and
``tests/test_graftrace.py`` enforces a clean package from inside the
suite as well. Rules (JG101-JG104), the per-class lockset semantics, and
the inline ``# graftrace: disable=`` suppression syntax are documented
in ``openembedding_tpu/analysis/concurrency.py`` (which also holds the
runtime TracedLock detector and the interleaving harness this static
pass complements).
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys

_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, _ROOT)


def _load_concurrency():
    """Load analysis/concurrency.py standalone (stdlib-only by design):
    going through `import openembedding_tpu` would pull jax in for a
    pure AST walk and turn a sub-second CI gate into a multi-second one."""
    path = os.path.join(_ROOT, "openembedding_tpu", "analysis",
                        "concurrency.py")
    spec = importlib.util.spec_from_file_location("_graftrace_impl", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod   # dataclasses resolves __module__
    spec.loader.exec_module(mod)
    return mod


concurrency = _load_concurrency()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="lock-discipline linter (rules JG101-JG104)")
    ap.add_argument("paths", nargs="+",
                    help=".py files or directories to analyze")
    ap.add_argument("--rules", default="",
                    help="comma-separated subset of rules to enforce "
                         "(default: all)")
    args = ap.parse_args(argv)
    only = {r.strip() for r in args.rules.split(",") if r.strip()}
    violations = concurrency.trace_paths(args.paths)
    if only:
        # JG100 (unparseable file) is never filterable: a gate that
        # "passes" a file it analyzed zero lines of is no gate
        violations = [v for v in violations
                      if v.rule in only or v.rule == "JG100"]
    for v in violations:
        print(v)
    if violations:
        print(f"graftrace: {len(violations)} violation(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
