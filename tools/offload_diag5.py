"""Fifth stage: isolate the ~105 ms per-device-call collapse.

Runs three loops, each 20 iters, printing per-iter times:
  A) shard_batch h2d of FRESH ~500 KB batches only (no compute)
  B) jitted train step only, REUSED presharded inputs, fixed state
     (re-init state each iter is impossible with donation; we rebuild
     from a kept template via device_put each time -- that cost is
     reported separately)
  C) the insert program only, fresh 1700-key chunks (as diag3 but
     alternating with a 500 KB h2d to mimic the bench's mix)
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import jax  # noqa: E402


def main():
    import optax
    from openembedding_tpu import (EmbeddingCollection, EmbeddingSpec,
                                   EmbeddingVariableMeta, Trainer)
    from openembedding_tpu.models import deepctr
    from openembedding_tpu.offload import ShardedOffloadedTable
    from openembedding_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(1, len(jax.devices()))
    vocab, cache_cap, dim, batch = 2_000_000, 1 << 22, 8, 4096
    opt = {"category": "adagrad", "learning_rate": 0.01}
    init = {"category": "constant", "value": 0.01}
    table = ShardedOffloadedTable(
        "uid", EmbeddingVariableMeta(embedding_dim=dim,
                                     vocabulary_size=vocab),
        opt, init, vocab=vocab, cache_capacity=cache_cap, mesh=mesh)
    lin = ShardedOffloadedTable(
        "uid:linear", EmbeddingVariableMeta(embedding_dim=1,
                                            vocabulary_size=vocab),
        opt, init, vocab=vocab, cache_capacity=cache_cap, mesh=mesh)
    specs = (table.embedding_spec(), lin.embedding_spec(),
             EmbeddingSpec(name="ctx", input_dim=100_000, output_dim=dim,
                           optimizer=opt),
             EmbeddingSpec(name="ctx:linear", input_dim=100_000,
                           output_dim=1, optimizer=opt))
    coll = EmbeddingCollection(specs, mesh)
    trainer = Trainer(deepctr.build_model("deepfm", ("uid", "ctx")),
                      coll, optax.adagrad(0.01),
                      offload={"uid": table, "uid:linear": lin},
                      pipeline_depth=2)
    rng = np.random.RandomState(0)

    def mk():
        uid = rng.randint(0, 30_000, batch).astype(np.int32)
        ctx = (uid * 7 % 100_000).astype(np.int32)
        return {"label": (uid % 4 == 0).astype(np.float32),
                "dense": np.tile((uid % 13).astype(np.float32)[:, None],
                                 (1, 13)),
                "sparse": {"uid": uid, "uid:linear": uid,
                           "ctx": ctx, "ctx:linear": ctx}}
    state = trainer.init(jax.random.PRNGKey(0), trainer.shard_batch(mk()))
    for i in range(3):
        state, m = trainer.train_step(state, mk())
    jax.block_until_ready(m["loss"])
    table.check_overflow(); lin.check_overflow()

    print("A) fresh-batch h2d only:", flush=True)
    for i in range(20):
        b = mk()
        t0 = time.perf_counter()
        sb = trainer.shard_batch(b)
        jax.block_until_ready(jax.tree.leaves(sb))
        print(f"  {i:2d}: {1e3*(time.perf_counter()-t0):7.2f} ms",
              flush=True)

    print("B) step only, reused presharded batch:", flush=True)
    sb = trainer.shard_batch(mk())
    for i in range(20):
        t0 = time.perf_counter()
        state, m = trainer._train_step(state, sb)
        jax.block_until_ready(m["loss"])
        print(f"  {i:2d}: {1e3*(time.perf_counter()-t0):7.2f} ms",
              flush=True)

    print("C) insert only, fresh keys + fresh 500KB h2d:", flush=True)
    emb = dict(state.emb)
    for i in range(20):
        ids = np.arange(50_000 + i * 1700, 50_000 + (i + 1) * 1700,
                        dtype=np.int32)
        filler = np.random.rand(4096, 32).astype(np.float32)
        t0 = time.perf_counter()
        d = jax.device_put(filler)
        emb["uid"] = table._insert_from_host(emb["uid"], ids)
        jax.block_until_ready([d, emb["uid"].keys])
        print(f"  {i:2d}: {1e3*(time.perf_counter()-t0):7.2f} ms",
              flush=True)
    table._overflow_latest = None


if __name__ == "__main__":
    main()
