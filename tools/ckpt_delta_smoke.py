"""Delta-checkpoint round-trip smoke for CI (beside the graftscope smoke).

save base -> train -> save delta (x2) -> load -> BIT-compare against the
live states, then simulate a torn final delta and assert the load
recovers to the previous complete delta. Exits nonzero on any mismatch;
writes a JSON summary (uploaded as a CI artifact).

    python -m tools.ckpt_delta_smoke [--out /tmp/ckpt_delta_smoke.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import warnings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="", help="JSON summary path")
    ap.add_argument("--vocab", type=int, default=4096)
    ap.add_argument("--dim", type=int, default=8)
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
    from openembedding_tpu import checkpoint as ckpt
    from openembedding_tpu import checkpoint_delta as cd
    from openembedding_tpu.parallel.mesh import create_mesh
    from openembedding_tpu.utils import observability as obs

    mesh = create_mesh(2, 4, jax.devices()[:8])
    coll = EmbeddingCollection(
        (EmbeddingSpec(name="arr", input_dim=args.vocab,
                       output_dim=args.dim),
         EmbeddingSpec(name="hsh", input_dim=-1, output_dim=args.dim,
                       hash_capacity=2048)),
        mesh, default_optimizer={"category": "adagrad",
                                 "learning_rate": 0.1})
    coll.enable_dirty_tracking(target_chunks=128)
    states = coll.init(jax.random.PRNGKey(0))

    def train(states, seed):
        rng = np.random.RandomState(seed)
        idx = {"arr": jnp.asarray(
            rng.randint(0, args.vocab, 64).astype(np.int32)),
            "hsh": jnp.asarray(rng.randint(0, 2**20, 64)
                               .astype(np.int32))}
        rows = coll.pull(states, idx, batch_sharded=False)
        grads = {k: jnp.ones_like(v) * 0.1 for k, v in rows.items()}
        return coll.apply_gradients(states, idx, grads,
                                    batch_sharded=False), idx

    summary = {"ok": False, "checks": []}

    def check(name, cond):
        summary["checks"].append({"name": name, "ok": bool(cond)})
        if not cond:
            print(f"ckpt_delta_smoke: FAIL {name}", file=sys.stderr)
        return bool(cond)

    def states_equal(a, b, probe):
        allv = jnp.arange(args.vocab, dtype=jnp.int32)
        eq = (np.asarray(coll.pull(a, {"arr": allv},
                                   batch_sharded=False)["arr"])
              == np.asarray(coll.pull(b, {"arr": allv},
                                      batch_sharded=False)["arr"])).all()
        pk = {"hsh": jnp.asarray(probe)}
        eq &= (np.asarray(coll.pull(a, pk, batch_sharded=False,
                                    read_only=True)["hsh"])
               == np.asarray(coll.pull(b, pk, batch_sharded=False,
                                       read_only=True)["hsh"])).all()
        return bool(eq)

    d = tempfile.mkdtemp(prefix="ckpt_delta_smoke_")
    ok = True
    states, _ = train(states, 0)
    info = ckpt.save_checkpoint(d, coll, states, mode="delta", step=0)
    ok &= check("base forced_full", info.get("forced_full"))
    probes = []
    after = {}
    for seed in (1, 2):
        states, idx = train(states, seed)
        probes.append(np.asarray(idx["hsh"]))
        info = cd.save_delta(d, coll, states, step=seed,
                             compact_bytes_ratio=1e18,
                             background_compact=False)
        ok &= check(f"delta seq {seed}", info["seq"] == seed
                    and not info["skipped"])
        after[seed] = states
    probe = np.concatenate(probes)
    loaded = ckpt.load_checkpoint(d, coll)
    ok &= check("base+chain bit-identical",
                states_equal(states, loaded, probe))
    # torn final delta: corrupt it, the load must recover to seq 1
    manifest = cd.read_manifest(d)
    last = manifest["chain"][-1]["vars"]["arr"]["file"]
    fp = os.path.join(d, last)
    raw = bytearray(open(fp, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(fp, "wb").write(bytes(raw))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        recovered = ckpt.load_checkpoint(d, coll)
    ok &= check("torn final delta recovers to previous",
                states_equal(after[1], recovered, probes[0]))
    summary["ckpt_stats"] = obs.ckpt_stats()
    summary["ok"] = bool(ok)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)
    print(json.dumps({"ok": summary["ok"],
                      "checks": len(summary["checks"])}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
