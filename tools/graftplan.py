"""graftplan CLI: observed-stats planner — stats window -> EnvConfig.

    python -m tools.graftplan --stats window.json \
        [--trajectory BENCH_trajectory.jsonl] [--out plan_env.json] \
        [--rationale plan_rationale.txt] [--no-compressed]

Reads a stats window captured by ``tools/graftscope --export-stats``
(per-table pull uniqueness/skew gauges, the serving_lookup_rows
histogram, cache hit counters, ingest stall accounting), calibrates
the per-byte/per-launch hardware constants from fingerprint-matched
``tools/graftwatch`` trajectory records, and emits:

* a VALIDATED EnvConfig JSON (round-tripped through
  ``EnvConfig.load`` before writing — a plan that does not parse as a
  config is a bug, not an artifact), byte-identical for identical
  inputs;
* a per-decision rationale table (chosen plane with the full score
  table, cache K, serving batcher knobs, the adaptive envelope, the
  ingest reader width) on stdout and optionally ``--rationale``.

Pure offline arithmetic — no mesh, no jax, no clock. Exit 0 on a
written plan, 1 on an invalid window or a round-trip mismatch.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="observed-stats planner: window -> EnvConfig")
    ap.add_argument("--stats", required=True,
                    help="stats window JSON (tools/graftscope "
                         "--export-stats)")
    ap.add_argument("--trajectory", default="",
                    help="graftwatch trajectory jsonl for hardware "
                         "calibration (fingerprint-matched records "
                         "only; optional)")
    ap.add_argument("--out", default="plan_env.json",
                    help="EnvConfig JSON to write (default "
                         "plan_env.json)")
    ap.add_argument("--rationale", default="",
                    help="also write the rationale table here")
    ap.add_argument("--base", default="",
                    help="EnvConfig JSON to start from (default: "
                         "library defaults)")
    ap.add_argument("--no-compressed", action="store_true",
                    help="keep the bf16/int8 rungs out of plane "
                         "selection (workloads that cannot take the "
                         "precision hit)")
    args = ap.parse_args(argv)

    from openembedding_tpu.analysis import plan as plan_lib
    from openembedding_tpu.utils import envconfig

    try:
        window = plan_lib.load_window(args.stats)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"graftplan: {e}", file=sys.stderr)
        return 1

    records = plan_lib.load_trajectory(args.trajectory) \
        if args.trajectory else []

    base = None
    if args.base:
        try:
            with open(args.base, "r", encoding="utf-8") as f:
                base = envconfig.EnvConfig.load(config=json.load(f),
                                                env={})
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"graftplan: --base {args.base}: {e}",
                  file=sys.stderr)
            return 1

    try:
        plan = plan_lib.build_plan(
            window, records, base=base,
            allow_compressed=not args.no_compressed)
    except ValueError as e:
        print(f"graftplan: {e}", file=sys.stderr)
        return 1

    text = plan_lib.render_config(plan.config)
    # the plan must round-trip through the config loader it claims to
    # feed — validated BEFORE the artifact exists
    reloaded = envconfig.EnvConfig.load(config=json.loads(text), env={})
    if reloaded != plan.config:
        print("graftplan: emitted config does not round-trip through "
              "EnvConfig.load — refusing to write", file=sys.stderr)
        return 1

    rationale = plan_lib.format_rationale(plan)
    print(rationale)
    with open(args.out, "w", encoding="utf-8") as f:
        f.write(text)
    print(f"graftplan: wrote {args.out} "
          f"({len(plan.decisions)} decisions, calibration "
          f"{plan.calibration.source})")
    if args.rationale:
        with open(args.rationale, "w", encoding="utf-8") as f:
            f.write(rationale)
    return 0


if __name__ == "__main__":
    sys.exit(main())
