"""Fourth stage: reproduce the bench's insert+step alternation through
the Trainer and log recompiles. Times each phase per iteration."""
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import jax  # noqa: E402

jax.config.update("jax_log_compiles", True)
logging.basicConfig(level=logging.WARNING)
logging.getLogger("jax._src.dispatch").setLevel(logging.WARNING)


def main():
    import optax
    from openembedding_tpu import (EmbeddingCollection, EmbeddingSpec,
                                   EmbeddingVariableMeta, Trainer)
    from openembedding_tpu.models import deepctr
    from openembedding_tpu.offload import ShardedOffloadedTable
    from openembedding_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(1, len(jax.devices()))
    vocab, cache_cap, dim, batch = 2_000_000, 1 << 22, 8, 4096
    opt = {"category": "adagrad", "learning_rate": 0.01}
    init = {"category": "constant", "value": 0.01}
    table = ShardedOffloadedTable(
        "uid", EmbeddingVariableMeta(embedding_dim=dim,
                                     vocabulary_size=vocab),
        opt, init, vocab=vocab, cache_capacity=cache_cap, mesh=mesh)
    lin = ShardedOffloadedTable(
        "uid:linear", EmbeddingVariableMeta(embedding_dim=1,
                                            vocabulary_size=vocab),
        opt, init, vocab=vocab, cache_capacity=cache_cap, mesh=mesh)
    specs = (table.embedding_spec(), lin.embedding_spec(),
             EmbeddingSpec(name="ctx", input_dim=100_000, output_dim=dim,
                           optimizer=opt),
             EmbeddingSpec(name="ctx:linear", input_dim=100_000,
                           output_dim=1, optimizer=opt))
    coll = EmbeddingCollection(specs, mesh)
    trainer = Trainer(deepctr.build_model("deepfm", ("uid", "ctx")),
                      coll, optax.adagrad(0.01),
                      offload={"uid": table, "uid:linear": lin},
                      pipeline_depth=2)
    rng = np.random.RandomState(0)

    def mk(i):
        # ~1700 new ids per batch on top of a resident hot head
        hot = rng.randint(0, 30_000, batch - 1700).astype(np.int32)
        new = np.arange(40_000 + i * 1700, 40_000 + (i + 1) * 1700,
                        dtype=np.int32)
        uid = np.concatenate([hot, new])
        ctx = (uid * 7 % 100_000).astype(np.int32)
        return {"label": (uid % 4 == 0).astype(np.float32),
                "dense": np.tile((uid % 13).astype(np.float32)[:, None],
                                 (1, 13)),
                "sparse": {"uid": uid, "uid:linear": uid,
                           "ctx": ctx, "ctx:linear": ctx}}
    state = trainer.init(jax.random.PRNGKey(0),
                         trainer.shard_batch(mk(0)))
    for i in range(6):   # warm compiles
        state, m = trainer.train_step(state, mk(i + 1))
    jax.block_until_ready(m["loss"])
    print("--- warmup done; per-phase timing (serial path) ---",
          flush=True)

    for i in range(8):
        b = mk(100 + i)
        t0 = time.perf_counter()
        state2, uniqs = trainer._apply_prepared_offload(state, b)
        jax.block_until_ready(
            jax.tree.leaves(state2.emb["uid"].keys))
        t1 = time.perf_counter()
        sb = trainer.shard_batch(b)
        jax.block_until_ready(jax.tree.leaves(sb))
        t2 = time.perf_counter()
        state3, m = trainer._train_step(state2, sb)
        jax.block_until_ready(m["loss"])
        t3 = time.perf_counter()
        for name, t in trainer.offload.items():
            t.note_update(b["sparse"][name], uniq=uniqs.get(name))
        t4 = time.perf_counter()
        state = state3
        print(f"iter {i}: apply={1e3*(t1-t0):7.2f}  h2d={1e3*(t2-t1):6.2f} "
              f" step={1e3*(t3-t2):7.2f}  note={1e3*(t4-t3):6.2f} ms",
              flush=True)


if __name__ == "__main__":
    main()
