"""Streaming-ingest CI smoke: shards -> reader pool -> pipelined steps,
with the zero-post-warmup-stall and prime-once contracts ASSERTED.

    python -m tools.ingest_smoke --out /tmp/ingest_smoke.json

Generates a small synthetic shard set (real TSV files, zipf marginals,
hex categoricals — ``data.stream.write_synthetic_shards``), streams it
through the parallel reader pool into a pipelined-plane deepfm Trainer
for ``--steps`` steps on the virtual CPU mesh, and exits nonzero
unless:

* post-warmup ingest stalls are ZERO (every measured pop found its
  batch ready — the stream records literal 0.0 for ready pops, so the
  assertion is exact, not a histogram approximation);
* the pipelined plane primed exactly once (identity-stable batch
  dicts: a rebuilding driver would re-prime per step);
* no rows were dropped as bad and no reader died;
* the ingest spans (``ingest.read`` / ``ingest.hash``) actually
  recorded — a silent instrumentation regression must fail the smoke,
  not pass it vacuously (the graftscope span-coverage contract).

Writes a one-line JSON summary to ``--out`` for the CI artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--shard-rows", type=int, default=4096)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--readers", type=int, default=2)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")
    from openembedding_tpu.utils.jaxcompat import set_num_cpu_devices
    set_num_cpu_devices(args.devices)

    import optax
    from openembedding_tpu import EmbeddingCollection, Trainer
    from openembedding_tpu.analysis import scope
    from openembedding_tpu.data import criteo, stream
    from openembedding_tpu.fused import make_fused_specs
    from openembedding_tpu.models import deepctr
    from openembedding_tpu.parallel.mesh import create_mesh
    from openembedding_tpu.utils import observability

    n_dev = len(jax.devices())
    mesh = create_mesh(2 if n_dev % 2 == 0 else 1,
                       n_dev // (2 if n_dev % 2 == 0 else 1))
    shard_dir = tempfile.mkdtemp(prefix="ingest_smoke_")
    problems = []
    summary = {}
    try:
        stream.write_synthetic_shards(shard_dir, num_shards=args.shards,
                                      rows_per_shard=args.shard_rows,
                                      fmt="tsv", seed=0)
        specs, mapper = make_fused_specs(
            tuple(criteo.SPARSE_NAMES), 1 << 14, 8,
            optimizer={"category": "adagrad", "learning_rate": 0.01},
            plane="a2a+pipelined")
        coll = EmbeddingCollection(specs, mesh)
        trainer = Trainer(deepctr.build_model(
            "deepfm", tuple(criteo.SPARSE_NAMES)), coll,
            optax.adagrad(0.01))
        src = stream.ShardStream(shard_dir, batch_size=args.batch,
                                 readers=args.readers, epochs=None,
                                 num_buckets=1 << 14,
                                 transform=mapper.fuse_batch,
                                 name="smoke")
        try:
            it = iter(src)
            cur = next(it)
            state = trainer.init(jax.random.PRNGKey(0),
                                 trainer.shard_batch(cur))
            observability.GLOBAL.reset()
            t0 = time.perf_counter()
            for i in range(args.steps):
                nxt = next(it)
                state, m = trainer.train_step(state, cur,
                                              next_batch=nxt)
                cur = nxt
                if i + 1 == args.warmup:
                    jax.block_until_ready(m["loss"])
                    src.reset_stall_stats()
            jax.block_until_ready(m["loss"])
            dt = time.perf_counter() - t0
            stalls = src.stall_summary()
            primes = observability.GLOBAL.snapshot().get(
                "pipeline_primes", {}).get("count", 0.0)
            mem = src.memory_stats()
            summary = {
                "steps": args.steps,
                "eps": round(args.steps * args.batch / dt, 1),
                "stall_p95_ms": stalls["p95_ms"],
                "stall_max_ms": stalls["max_ms"],
                "stalled_pops": stalls["stalled"],
                "measured_pops": stalls["pops"],
                "pipeline_primes": int(primes),
                "bad_rows": int(src.bad_rows()),
                "rows_read": int(mem["rows_read"]),
                "ring_capacity_batches":
                    int(mem["ring_capacity_batches"]),
                "read_spans": scope.HISTOGRAMS.count(
                    "span_ingest_read_seconds", stream="smoke",
                    fmt="tsv"),
                "hash_spans": scope.HISTOGRAMS.count(
                    "span_ingest_hash_seconds", stream="smoke"),
            }
            if stalls["stalled"] or stalls["max_ms"] > 0.0:
                problems.append(
                    f"{stalls['stalled']} post-warmup stall(s), max "
                    f"{stalls['max_ms']:.3f} ms — the ring fell behind "
                    "the step rate")
            if primes != 1:
                problems.append(
                    f"pipeline_primes == {primes}, expected 1 — the "
                    "batch identity contract broke (rebuilt dicts?)")
            if src.bad_rows():
                problems.append(f"{src.bad_rows()} bad row(s) in a "
                                "clean synthetic shard set")
            if not summary["read_spans"] or not summary["hash_spans"]:
                problems.append("ingest.read/ingest.hash spans missing "
                                "— instrumentation regression")
        finally:
            src.close()
    finally:
        shutil.rmtree(shard_dir, ignore_errors=True)

    summary["problems"] = problems
    print(json.dumps(summary), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=2)
    if problems:
        for p in problems:
            print(f"ingest_smoke: {p}", file=sys.stderr)
        print("ingest_smoke: FAILED", file=sys.stderr)
        return 1
    print("ingest_smoke: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
