"""graftlint CLI: jit-purity lint gate over package source.

    python -m tools.graftlint openembedding_tpu/ [more paths...]

Exit 0 when clean, 1 with one ``path:line: RULE message`` per violation
otherwise — the tier-1 lane runs this before pytest (ROADMAP verify
line) and ``tests/test_graftlint.py`` enforces a clean package from
inside the suite as well. Rules, marking semantics, and the inline
suppression syntax are documented in
``openembedding_tpu/analysis/lint.py``.
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys

_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, _ROOT)


def _load_lint():
    """Load analysis/lint.py standalone (stdlib-only by design): going
    through `import openembedding_tpu` would pull jax in for a pure AST
    walk and turn a sub-second CI gate into a multi-second one."""
    path = os.path.join(_ROOT, "openembedding_tpu", "analysis", "lint.py")
    spec = importlib.util.spec_from_file_location("_graftlint_impl", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod   # dataclasses resolves __module__
    spec.loader.exec_module(mod)
    return mod


lint = _load_lint()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="jit-purity AST linter (rules JG001-JG004)")
    ap.add_argument("paths", nargs="+",
                    help=".py files or directories to lint")
    ap.add_argument("--rules", default="",
                    help="comma-separated subset of rules to enforce "
                         "(default: all)")
    args = ap.parse_args(argv)
    only = {r.strip() for r in args.rules.split(",") if r.strip()}
    violations = lint.lint_paths(args.paths)
    if only:
        # JG000 (unparseable file) is never filterable: a gate that
        # "passes" a file it linted zero lines of is no gate
        violations = [v for v in violations
                      if v.rule in only or v.rule == "JG000"]
    for v in violations:
        print(v)
    if violations:
        print(f"graftlint: {len(violations)} violation(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
