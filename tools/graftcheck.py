"""graftcheck CLI: per-plane compiled-program contract gate for CI.

    python -m tools.graftcheck [--mesh 2x4] [--batch 1024] [--dim 16]

Builds a virtual CPU mesh, lowers every registered plane's pull/push
program (array AND hash tables) plus the whole jitted train step, and
audits them against ``openembedding_tpu/analysis/contracts.py``:
collective inventory + byte bounds, no f64, no host transfers, step
donation honored — plus the graftwatch MEMORY ledger
(``analysis/memwatch.py``): every plane's compiled temp allocation
audited against the peak-temp-bytes contract at sizes where one table
shard dwarfs batch scratch. Exit 0 when every contract holds, 1 with
the first violation per program otherwise.

This is the compile-audit-time version of the scaling guarantee: a
sharding/plane regression fails HERE, on a laptop, instead of as a
silent 10x ICI blowup on a real mesh. ``tests/test_analysis_contracts.py``
runs the same registry inside the tier-1 lane.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="compiled-program contract gate")
    ap.add_argument("--mesh", default="2x4",
                    help="DATAxMODEL virtual mesh shape (default 2x4)")
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--skip-step", action="store_true",
                    help="skip the (slower) whole-train-step audit")
    ap.add_argument("--skip-mem", action="store_true",
                    help="skip the graftwatch memory-ledger audit")
    args = ap.parse_args(argv)
    data, model = (int(x) for x in args.mesh.split("x"))

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")
    from openembedding_tpu.utils.jaxcompat import set_num_cpu_devices
    set_num_cpu_devices(data * model)

    from openembedding_tpu.parallel.mesh import create_mesh
    from openembedding_tpu.analysis import contracts, programs

    mesh = create_mesh(data, model)
    failures = 0

    def audit(label, fn):
        nonlocal failures
        try:
            summary = fn()
            print(f"ok   {label}: {summary}")
        except contracts.ContractViolation as e:
            failures += 1
            print(f"FAIL {label}: {e}", file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — a gate must report,
            # not die on the first broken lowering: the remaining
            # programs still get audited and the summary still prints
            failures += 1
            print(f"FAIL {label}: {type(e).__name__}: {e}",
                  file=sys.stderr)

    for plane in ("psum", "a2a", "a2a+cache", "a2a+pipelined"):
        for use_hash in (False, True):
            kind = "hash" if use_hash else "array"
            for prog, lower in (("pull", programs.lower_pull),
                                ("push", programs.lower_push)):
                def run(plane=plane, prog=prog, lower=lower,
                        use_hash=use_hash):
                    txt, params = lower(mesh, plane, batch=args.batch,
                                        dim=args.dim, use_hash=use_hash)
                    return contracts.check_program(txt, plane, prog,
                                                   **params)
                audit(f"{plane}/{prog} ({kind})", run)

    # compressed-exchange planes (parallel/precision.py): inventory
    # bounds at the WIRE itemsize plus the byte-halving ratio vs the
    # f32 a2a plane's compiled program — exchange collective bytes must
    # be <= 0.55x, measured on BOTH compiled HLOs, pull and push
    # separately. Audited at dim 64 where the ratio binds (keys/counts
    # stay int32, so the ratio asymptotes to 0.5 from above as dim
    # grows; at the default dim 16 the int32 legs alone push bf16 past
    # 0.55 — the contract pins the audit shape, see contracts.py).
    COMPRESSED_DIM = 64
    for use_hash in (False, True):
        kind = "hash" if use_hash else "array"
        baselines = {}
        for prog, lower in (("pull", programs.lower_pull),
                            ("push", programs.lower_push)):
            try:
                baselines[prog], _ = lower(mesh, "a2a", batch=args.batch,
                                           dim=COMPRESSED_DIM,
                                           use_hash=use_hash)
            except Exception as e:  # noqa: BLE001 — keep auditing
                failures += 1
                print(f"FAIL a2a baseline {prog} ({kind}, dim "
                      f"{COMPRESSED_DIM}): {type(e).__name__}: {e}",
                      file=sys.stderr)
        for plane in ("a2a+bf16", "a2a+int8"):
            for prog, lower in (("pull", programs.lower_pull),
                                ("push", programs.lower_push)):
                if prog not in baselines:
                    continue

                def run(plane=plane, prog=prog, lower=lower,
                        use_hash=use_hash):
                    txt, params = lower(mesh, plane, batch=args.batch,
                                        dim=COMPRESSED_DIM,
                                        use_hash=use_hash)
                    res = contracts.check_compressed_program(
                        txt, baselines[prog], plane, prog, **params)
                    return (f"exchange {res['exchange_bytes']}B = "
                            f"{res['ratio']:.3f}x f32 "
                            f"(<= {res['max_ratio']:.2f})")
                audit(f"{plane}/{prog} ({kind}, byte-halving vs a2a)",
                      run)

    # grouped plane: collection-level lowering over 3 heterogeneous
    # same-dim tables (one exchange group) — the contract caps the
    # all-to-all launch count at num_groups * per-exchange ops, which a
    # per-table-loop regression (3x the ops) fails
    for use_hash in (False, True):
        kind = "hash" if use_hash else "array"
        for prog, lower in (("pull", programs.lower_grouped_pull),
                            ("push", programs.lower_grouped_push)):
            def run(prog=prog, lower=lower, use_hash=use_hash):
                txt, params = lower(mesh, tables=3, batch=args.batch,
                                    dim=args.dim, use_hash=use_hash)
                return contracts.check_program(txt, "a2a+grouped", prog,
                                               **params)
            audit(f"a2a+grouped/{prog} ({kind}, 3 tables)", run)

    # graftplan cost audit: every registered PlaneSpec's DECLARED
    # exchange bytes (analysis/contracts.py cost registry) against the
    # compiled HLO's actual collective bytes, within
    # COST_MODEL_TOLERANCE. Audited at batch >= 512 — the regime the
    # closed forms are calibrated in (below it XLA elides the
    # residue/overflow legs and the additive terms drift, see the
    # registry comment) on the 1 x N layout where the exchange spans
    # every device — mixed data-parallel layouts split the per-device
    # bytes differently, which is a property of the LAYOUT, not the
    # plane, and the planner only consumes the plane ranking. A stale
    # or wrong declaration fails HERE, so the offline planner can
    # never rank planes off fiction.
    cost_batch = max(args.batch, 512)
    cost_mesh = create_mesh(1, data * model)
    for plane in sorted(contracts.PLANE_SPECS):
        if plane == "a2a+grouped":
            lowers = (("pull", programs.lower_grouped_pull),
                      ("push", programs.lower_grouped_push))
        else:
            lowers = (("pull", programs.lower_pull),
                      ("push", programs.lower_push))
        for prog, lower in lowers:
            def run(plane=plane, prog=prog, lower=lower):
                if plane == "a2a+grouped":
                    txt, params = lower(cost_mesh, tables=3,
                                        batch=cost_batch,
                                        dim=args.dim, use_hash=False)
                else:
                    txt, params = lower(cost_mesh, plane,
                                        batch=cost_batch,
                                        dim=args.dim, use_hash=False)
                res = contracts.check_cost_model(txt, plane, prog,
                                                 params)
                return (f"declared {res['declared']}B vs HLO "
                        f"{res['actual']}B (err "
                        f"{res['rel_err'] * 100:.1f}% <= "
                        f"{res['tolerance'] * 100:.0f}%)")
            audit(f"{plane}/{prog} (graftplan cost model)", run)

    # graftwatch memory ledger: peak-temp contract per plane at the
    # calibrated audit sizes (memwatch.AUDIT_*, deliberately independent
    # of --batch: detection power needs the table shard to dwarf batch
    # scratch, exactly like the step audit's copy bound below)
    if not args.skip_mem:
        from openembedding_tpu.analysis import memwatch

        def run_mem():
            rows = memwatch.memory_ledger(mesh)
            print(memwatch.format_memory_table(rows))
            missing = [f"{r.plane}/{r.program}" for r in rows
                       if r.mem is None]
            if missing:
                raise RuntimeError(
                    f"no compiled memory analysis for {missing} — the "
                    "backend stopped exposing memory_analysis(); the "
                    "ledger (and every HBM claim downstream) is blind")
            return f"{len(rows)} programs, peak-temp bounds hold"
        audit("memory ledger (all planes, peak-temp contract)", run_mem)

    if not args.skip_step:
        # pipelined STEP program: the overlap contract (prefetch key
        # legs free of the dense dots, push committed in-program, dense
        # never waiting on an exchange, donation honored) plus the
        # no-shard-sized-copy bound and — unless --skip-mem — the
        # step's peak-temp audit (one extra pulled-row buffer + one
        # post-push weights shard per table, nothing else table-sized)
        def run_pipelined_step():
            vocab, dim = 1 << 16, 16
            txt, params = programs.lower_pipelined_step(
                mesh, vocab=vocab, dim=dim, batch=args.batch // 4)
            summary = contracts.check_program(txt, "a2a+pipelined",
                                              "step", **params)
            shard_bytes = vocab * dim * 4 // mesh.size
            worst = contracts.max_copy_bytes(txt)
            if worst >= shard_bytes:
                raise contracts.ContractViolation(
                    f"pipelined step copies a {worst}-byte buffer >= "
                    f"table shard size {shard_bytes} — donation "
                    "silently declined for a table")
            report = contracts.analyze_overlap(txt)
            return {"collectives": summary, "overlap": report}
        audit("a2a+pipelined/step (deepfm, overlap contract)",
              run_pipelined_step)
        if not args.skip_mem:
            from openembedding_tpu.analysis import memwatch as mw

            def run_pipelined_mem():
                row = mw.pipelined_step_memory(mesh)
                print(mw.format_memory_table([row]))
                if row.mem is None:
                    raise RuntimeError(
                        "no compiled memory analysis for the pipelined "
                        "step — the peak-temp audit is blind")
                return "pipelined step peak-temp bound holds"
            audit("a2a+pipelined/step memory (peak-temp contract)",
                  run_pipelined_mem)

        def run_step():
            # vocab/dim sized so each table shard dwarfs every dense
            # buffer: a copy at/above shard size can only be a table
            # that lost its donation (see contracts.max_copy_bytes)
            vocab, dim = 1 << 16, 16
            txt, params = programs.lower_train_step(mesh, "a2a",
                                                    vocab=vocab, dim=dim,
                                                    batch=args.batch // 4)
            summary = contracts.check_program(txt, "any", "step",
                                              **params)
            shard_bytes = vocab * dim * 4 // mesh.size
            worst = contracts.max_copy_bytes(txt)
            if worst >= shard_bytes:
                raise contracts.ContractViolation(
                    f"step program copies a {worst}-byte buffer >= table "
                    f"shard size {shard_bytes} — donation silently "
                    "declined for a table")
            return summary
        audit("any/step (deepfm, a2a)", run_step)

    if failures:
        print(f"graftcheck: {failures} contract violation(s)",
              file=sys.stderr)
        return 1
    print("graftcheck: all contracts hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
