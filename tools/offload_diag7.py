"""Seventh stage: find the host call that stalls ~105 ms per step in
the REAL offload loop (no explicit blocks — only the natural ones)."""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import jax  # noqa: E402


def main():
    import optax
    from openembedding_tpu import (EmbeddingCollection, EmbeddingSpec,
                                   EmbeddingVariableMeta, Trainer)
    from openembedding_tpu.models import deepctr
    from openembedding_tpu.offload import ShardedOffloadedTable
    from openembedding_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(1, len(jax.devices()))
    vocab, cache_cap, dim, batch = 2_000_000, 1 << 22, 8, 4096
    opt = {"category": "adagrad", "learning_rate": 0.01}
    init = {"category": "constant", "value": 0.01}
    table = ShardedOffloadedTable(
        "uid", EmbeddingVariableMeta(embedding_dim=dim,
                                     vocabulary_size=vocab),
        opt, init, vocab=vocab, cache_capacity=cache_cap, mesh=mesh)
    lin = ShardedOffloadedTable(
        "uid:linear", EmbeddingVariableMeta(embedding_dim=1,
                                            vocabulary_size=vocab),
        opt, init, vocab=vocab, cache_capacity=cache_cap, mesh=mesh)
    specs = (table.embedding_spec(), lin.embedding_spec(),
             EmbeddingSpec(name="ctx", input_dim=100_000, output_dim=dim,
                           optimizer=opt),
             EmbeddingSpec(name="ctx:linear", input_dim=100_000,
                           output_dim=1, optimizer=opt))
    coll = EmbeddingCollection(specs, mesh)
    trainer = Trainer(deepctr.build_model("deepfm", ("uid", "ctx")),
                      coll, optax.adagrad(0.01),
                      offload={"uid": table, "uid:linear": lin},
                      pipeline_depth=1)
    rng = np.random.RandomState(0)

    def mk(i):
        hot = rng.randint(0, 30_000, batch - 1700).astype(np.int32)
        new = np.arange(40_000 + i * 1700, 40_000 + (i + 1) * 1700,
                        dtype=np.int32)
        uid = np.concatenate([hot, new])
        ctx = (uid * 7 % 100_000).astype(np.int32)
        return {"label": (uid % 4 == 0).astype(np.float32),
                "dense": np.tile((uid % 13).astype(np.float32)[:, None],
                                 (1, 13)),
                "sparse": {"uid": uid, "uid:linear": uid,
                           "ctx": ctx, "ctx:linear": ctx}}
    state = trainer.init(jax.random.PRNGKey(0),
                         trainer.shard_batch(mk(0)))
    for i in range(12):  # past the overflow-check depth: steady state
        state, m = trainer.train_step(state, mk(i + 1))
    jax.block_until_ready(m["loss"])
    print("steady state reached; timing host calls (NO explicit blocks)",
          flush=True)

    timed = [mk(100 + i) for i in range(24)]
    t_total0 = time.perf_counter()
    rows = []
    for i in range(len(timed)):
        b = timed[i]
        t0 = time.perf_counter()
        trainer.prefetch(timed[i:i + 2])
        t1 = time.perf_counter()
        state, uniqs = trainer._apply_prepared_offload(state, b)
        t2 = time.perf_counter()
        sb = trainer.shard_batch(b)
        t3 = time.perf_counter()
        state, m = trainer._train_step(state, sb)
        t4 = time.perf_counter()
        for name, t in trainer.offload.items():
            t.note_update(b["sparse"][name], uniq=uniqs.get(name))
        t5 = time.perf_counter()
        rows.append((t1 - t0, t2 - t1, t3 - t2, t4 - t3, t5 - t4))
    jax.block_until_ready(m["loss"])
    total = time.perf_counter() - t_total0
    print("  prefetch   apply    h2d   stepdisp  note  (ms)")
    for i, r in enumerate(rows):
        print("  " + "  ".join(f"{1e3*x:7.2f}" for x in r))
    print(f"TOTAL {1e3*total/len(timed):.2f} ms/step", flush=True)

    # breakdown inside apply_prepared: time host_prepare vs apply for uid
    import openembedding_tpu.offload as off
    orig_apply = off.ShardedOffloadedTable.apply_prepared
    orig_co = off.ShardedOffloadedTable.check_overflow

    def timed_apply(self, cache, prep):
        t0 = time.perf_counter()
        out = orig_apply(self, cache, prep)
        print(f"    apply_prepared[{self.name}]: "
              f"{1e3*(time.perf_counter()-t0):.2f} ms", flush=True)
        return out

    def timed_co(self, **kw):
        t0 = time.perf_counter()
        out = orig_co(self, **kw)
        print(f"      check_overflow[{self.name}] drain={kw.get('drain')}"
              f": {1e3*(time.perf_counter()-t0):.2f} ms", flush=True)
        return out
    off.ShardedOffloadedTable.apply_prepared = timed_apply
    off.ShardedOffloadedTable.check_overflow = timed_co
    print("--- per-call breakdown, 4 steps ---", flush=True)
    extra = [mk(200 + i) for i in range(4)]
    for i, b in enumerate(extra):
        trainer.prefetch(extra[i:i + 2])
        state, m = trainer.train_step(state, b)
    jax.block_until_ready(m["loss"])


if __name__ == "__main__":
    main()
