"""graftload: open-loop serving load generator + latency-SLO sweep.

    # storm an existing cluster over REST
    python -m tools.graftload --endpoints 127.0.0.1:8010,127.0.0.1:8011 \
        --sign model-1 --variable emb --vocab 64 --qps 200 --duration 5

    # self-contained: boot a 2-replica cluster, storm REST + native,
    # kill one replica mid-storm, record + trace (the CI smoke)
    python -m tools.graftload --demo --replicas 2 --qps 40 --duration 4 \
        --path both --chaos --trace /tmp/graftload_trace.json \
        --trajectory BENCH_trajectory.jsonl

    # sweep offered QPS to find the sustained knee
    python -m tools.graftload --demo --sweep 50,100,200,400,800

Open-loop discipline: arrivals are a Poisson process at the OFFERED
rate and every request's latency is measured from its INTENDED send
time, not from when a worker got around to sending it. A closed-loop
driver slows its own clock when the server stalls — the stall eats the
arrivals that would have observed it, and p99 comes out flat exactly
when it matters (coordinated omission). Here a backlog shows up AS
latency: if all workers are busy when an arrival comes due, the wait
lands in that request's measured latency. The worker pool bounds
concurrency, not the accounting.

Output: per-route p50/p95/p99 (ms), achieved vs offered QPS, error
rate. ``--trace`` writes the storm's request-scoped spans (client,
router fan-out, server-side — one trace id per request) as a
Perfetto-loadable JSON; ``--trajectory`` appends a schema-versioned
``serving`` record that ``tools.graftwatch --gate`` regression-gates
(p99 up OR sustained QPS down) exactly like step throughput.

Exit nonzero on request errors (the chaos invariant: reads never fail
while >= 1 replica per group lives) or a broken record/trace.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

DEMO_SIGN = "graftload-demo"
DEMO_VOCAB = 1024
DEMO_DIM = 8


class RejectedError(Exception):
    """The request was REJECTED by batcher backpressure (HTTP 429 from
    every replica): counted separately from errors — an oversubscribed
    offer is SUPPOSED to degrade to rejections, never to failures on
    accepted requests."""


# --- open-loop scheduling ----------------------------------------------------

def poisson_arrivals(rate: float, duration: float,
                     seed: int = 0) -> np.ndarray:
    """Intended send times (seconds from storm start) of a Poisson
    arrival process at ``rate``/s over ``duration`` s: i.i.d.
    exponential gaps, so bursts and lulls occur like real traffic
    instead of a metronome that never tests queueing."""
    if rate <= 0 or duration <= 0:
        return np.zeros((0,), np.float64)
    rng = np.random.RandomState(seed)
    out: List[np.ndarray] = []
    t = 0.0
    while t < duration:
        gaps = rng.exponential(1.0 / rate,
                               size=max(64, int(rate * duration * 0.5)))
        ts = t + np.cumsum(gaps)
        out.append(ts)
        t = float(ts[-1])
    arrivals = np.concatenate(out)
    return arrivals[arrivals < duration]


class StormResult:
    """One storm's coordinated-omission-free accounting."""

    def __init__(self, route: str, offered_qps: float, duration: float,
                 latencies_ms: np.ndarray, arrival_s: np.ndarray,
                 errors: int, rejected: int = 0):
        self.route = route
        self.offered_qps = float(offered_qps)
        self.duration = float(duration)
        self.latencies_ms = np.asarray(latencies_ms, np.float64)
        self.arrival_s = np.asarray(arrival_s, np.float64)
        self.errors = int(errors)
        # 429-busy rejections (batcher backpressure): not completions,
        # not errors — the bounded queue doing its job under an offer
        # past capacity
        self.rejected = int(rejected)

    @property
    def calls(self) -> int:
        return int(self.latencies_ms.size) + self.errors + self.rejected

    @property
    def achieved_qps(self) -> float:
        """Completed-ok requests over the OFFERED window. When the
        server cannot keep up, completions spill past the window and
        this honestly under-reports the offered rate — the knee
        detector keys off exactly that."""
        n = self.latencies_ms.size
        if not n:
            return 0.0
        # wall time from storm start to last completion, floored at the
        # offered window (a fast server must not report > offered)
        wall = max(self.duration,
                   float((self.arrival_s + self.latencies_ms / 1e3).max()))
        return n / wall

    @property
    def error_rate(self) -> float:
        return self.errors / max(1, self.calls)

    def quantile_ms(self, q: float) -> float:
        if not self.latencies_ms.size:
            return float("nan")
        return float(np.percentile(self.latencies_ms, q * 100.0))

    def per_chunk_qps(self, chunks: int = 4) -> Tuple[float, float]:
        """(min, max) achieved QPS over ``chunks`` equal slices of the
        offered window — the noise band the regression gate widens by."""
        if not self.latencies_ms.size:
            return 0.0, 0.0
        done = self.arrival_s + self.latencies_ms / 1e3
        edges = np.linspace(0.0, max(self.duration, float(done.max())),
                            chunks + 1)
        counts, _ = np.histogram(done, bins=edges)
        width = edges[1] - edges[0]
        qps = counts / max(width, 1e-9)
        return float(qps.min()), float(qps.max())

    def summary(self) -> Dict[str, Any]:
        return {"route": self.route,
                "offered_qps": round(self.offered_qps, 2),
                "achieved_qps": round(self.achieved_qps, 2),
                "calls": self.calls, "errors": self.errors,
                "rejected": self.rejected,
                "error_rate": round(self.error_rate, 4),
                "p50_ms": round(self.quantile_ms(0.50), 3),
                "p95_ms": round(self.quantile_ms(0.95), 3),
                "p99_ms": round(self.quantile_ms(0.99), 3)}


def run_storm(send: Callable[[int], None], arrivals: np.ndarray, *,
              route: str, offered_qps: float, duration: float,
              workers: int = 16) -> StormResult:
    """Fire ``send(i)`` at each intended arrival time from a worker
    pool; latency is completion minus INTENDED time (see module
    docstring). ``send`` raises on error; errors are counted, their
    latency excluded (an error is not a service time)."""
    workers = max(1, min(int(workers), max(1, arrivals.size)))
    lock = threading.Lock()
    state = {"next": 0, "errors": 0, "rejected": 0}
    lat: List[float] = []
    arr: List[float] = []
    err_first: List[BaseException] = []
    # small lead-in so worker startup cannot eat the first arrivals
    t0 = time.perf_counter() + 0.05

    def worker():
        while True:
            with lock:
                i = state["next"]
                state["next"] += 1
            if i >= arrivals.size:
                return
            target = t0 + arrivals[i]
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            try:
                send(i)
            except RejectedError:
                # 429 backpressure: a rejection is a DEFINED response,
                # not a failure — tallied apart from errors so the
                # never-error chaos invariant stays meaningful
                with lock:
                    state["rejected"] += 1
                continue
            except Exception as e:  # noqa: BLE001 — counted, not fatal
                with lock:
                    state["errors"] += 1
                    if not err_first:
                        err_first.append(e)
                continue
            done = time.perf_counter()
            with lock:
                lat.append((done - target) * 1e3)
                arr.append(float(arrivals[i]))

    threads = [threading.Thread(target=worker, daemon=True,
                                name=f"graftload-{k}")
               for k in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    res = StormResult(route, offered_qps, duration,
                      np.asarray(lat), np.asarray(arr), state["errors"],
                      state["rejected"])
    if err_first:
        res.first_error = repr(err_first[0])  # type: ignore[attr-defined]
    return res


def find_knee(results: List[StormResult], *, sustain: float = 0.9
              ) -> Optional[StormResult]:
    """Highest offered rate the cluster SUSTAINED: achieved/offered >=
    ``sustain`` with zero errors. None when even the lowest rate
    saturated."""
    ok = [r for r in results
          if r.errors == 0 and r.achieved_qps >= sustain * r.offered_qps]
    return max(ok, key=lambda r: r.offered_qps) if ok else None


# --- request senders ---------------------------------------------------------

def make_rest_sender(router, sign: str, variable: str, vocab: int,
                     batch: int, seed: int = 1) -> Callable[[int], None]:
    """Per-request REST lookup through the routing client: fresh random
    ids per request (pre-drawn — the storm loop must not pay RNG time),
    each under its own trace id so the Perfetto story is per-request."""
    import urllib.error
    from openembedding_tpu.analysis import scope
    rng = np.random.RandomState(seed)
    pool = rng.randint(0, vocab, size=(256, batch)).astype(np.int32)

    def send(i: int) -> None:
        ids = pool[i % pool.shape[0]]
        try:
            with scope.trace_context():
                rows = router.lookup(sign, variable, ids)
        except urllib.error.HTTPError as e:
            if e.code == 429:
                # every replica's bounded batcher queue was full: the
                # request was REJECTED, by design — not a failure
                raise RejectedError(str(e)) from e
            raise
        if rows.shape[0] != batch:
            raise RuntimeError(f"short read: {rows.shape}")

    return send


def make_native_sender(model, variable: str, vocab: int, batch: int,
                       seed: int = 2,
                       batcher=None) -> Callable[[int], None]:
    """Per-request native (zero-JAX mmap) lookup — the latency floor.
    With ``batcher`` (a ``NativeModel.make_batcher`` scheduler),
    concurrent sends coalesce into one ``oe_pull_weights_gather`` per
    flush instead of serializing on the ctypes handle."""
    from openembedding_tpu.analysis import scope
    from openembedding_tpu.serving.batcher import BusyError
    rng = np.random.RandomState(seed)
    pool = rng.randint(0, vocab, size=(256, batch)).astype(np.int64)
    lock = threading.Lock()   # one ctypes handle; serialize calls

    def send(i: int) -> None:
        ids = pool[i % pool.shape[0]]
        if batcher is not None:
            try:
                with scope.trace_context():
                    rows = batcher.lookup(variable, ids)
            except BusyError as e:
                # bounded-queue backpressure: a DEFINED rejection,
                # tallied apart from errors (mirrors the REST 429 path)
                raise RejectedError(str(e)) from e
        else:
            with scope.trace_context(), lock:
                rows = model.lookup(variable, ids)
        if rows.shape[0] != batch:
            raise RuntimeError(f"short read: {rows.shape}")

    return send


def scrape_batch_stats(endpoints) -> Dict[str, float]:
    """Sum the replicas' ``oe_batch_*`` / ``oe_serving_rejected_*``
    counters off /metrics — the server-side coalescing evidence a
    --batched storm reports (flushes vs requests = the batching
    factor). Dead replicas (chaos kills) contribute nothing."""
    import re as re_mod
    import urllib.request
    want = ("oe_batch_flushes_total", "oe_batch_requests_total",
            "oe_batch_rows_total", "oe_batch_unique_rows_total",
            "oe_serving_rejected_total")
    out: Dict[str, float] = {}
    for ep in endpoints:
        try:
            with urllib.request.urlopen(f"http://{ep}/metrics",
                                        timeout=3) as r:
                body = r.read().decode()
        except Exception:  # noqa: BLE001 — a killed replica is expected
            continue
        for name in want:
            m = re_mod.search(rf"^{name} ([0-9.e+]+)$", body,
                              re_mod.MULTILINE)
            if m:
                key = name[len("oe_"):-len("_total")] \
                    if name.endswith("_total") else name[len("oe_"):]
                out[key] = out.get(key, 0.0) + float(m.group(1))
    return out


def run_replica_sweep(args) -> int:
    """Replica scale-out storm (ROADMAP item 4's remaining half): for
    each count in ``--replica-sweep``, boot a fresh demo cluster, drive
    it through :class:`ShardedRoutingClient` (ONE shard group of N
    replicas — the client's per-request random replica start spreads
    reads across the fleet, the production read-scale story) with a
    knee sweep, and compare the sustained knees. On the 1-core cpu
    window a single replica's capacity is bounded by its own bounded
    batcher queue + flush cadence (idle wait windows), so additional
    replica processes genuinely overlap — the scaling measured here is
    the per-host-capacity story, stated honestly in the record notes.
    Appends one ``serving`` record for the TOP count's knee (its own
    baseline group: config carries ``replica_sweep``); exits nonzero
    when scaling falls below ``--scale-floor`` or any storm errored.
    """
    import shutil
    import tempfile
    from openembedding_tpu.serving import ha
    from tools import graftwatch

    counts = sorted({int(x) for x in args.replica_sweep.split(",") if x})
    if len(counts) < 2:
        print("graftload: --replica-sweep needs >= 2 counts",
              file=sys.stderr)
        return 2
    rates = ([float(x) for x in args.sweep.split(",") if x]
             if args.sweep else [200.0, 400.0, 800.0, 1600.0, 2400.0])
    tmp_dir = tempfile.mkdtemp(prefix="graftload_rsweep_")
    knees: Dict[int, StormResult] = {}
    errors = 0
    try:
        model_dir = build_demo_checkpoint(os.path.join(tmp_dir, "model"))
        head = (f"{'replicas':>9}{'offered':>9}{'achieved':>10}"
                f"{'calls':>7}{'err':>5}{'rej':>6}{'p50_ms':>9}"
                f"{'p99_ms':>9}")
        print("\n" + head + "\n" + "-" * len(head))
        for n in counts:
            endpoints, procs, _tr = boot_demo_cluster(
                model_dir, n,
                batch_rows=args.batch_rows if args.batched else 0,
                batch_wait_us=args.batch_wait_us,
                batch_queue_rows=args.batch_queue_rows)
            client = ha.ShardedRoutingClient([endpoints],
                                             timeout=args.timeout)
            try:
                results = []
                for ri, rate in enumerate(rates):
                    send = make_rest_sender(client, DEMO_SIGN, "emb",
                                            DEMO_VOCAB, args.batch,
                                            seed=ri)
                    res = _storm_once(args, "rest", send, rate,
                                      seed=300 + 10 * n + ri)
                    results.append(res)
                    s = res.summary()
                    print(f"{n:>9}{s['offered_qps']:>9}"
                          f"{s['achieved_qps']:>10}{s['calls']:>7}"
                          f"{s['errors']:>5}{s['rejected']:>6}"
                          f"{s['p50_ms']:>9}{s['p99_ms']:>9}",
                          flush=True)
                knee = find_knee(results)
                if knee is None:
                    # even the lowest rate saturated: the highest
                    # achieved-QPS storm with zero errors is the
                    # honest sustained number
                    ok = [r for r in results if r.errors == 0]
                    knee = max(ok, key=lambda r: r.achieved_qps) \
                        if ok else results[0]
                knees[n] = knee
                # errors count against the sweep only at/below the
                # knee: rates ABOVE it are saturation probes, where an
                # overloaded single replica sheds load however it can
                # (429s from the bounded queue, kernel accept-backlog
                # overflow past that) — the never-error invariant is
                # the capacity-bounded chaos lane's, not a promise
                # about 8x overload probes (printed, not fatal)
                errors += sum(r.errors for r in results
                              if r.offered_qps <= knee.offered_qps)
                sat_errors = sum(r.errors for r in results
                                 if r.offered_qps > knee.offered_qps)
                if sat_errors:
                    print(f"  ({n} replica(s): {sat_errors} error(s) "
                          "in saturation probes above the knee — "
                          "reported, not gated)", flush=True)
            finally:
                client.close()
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:
                    p.wait()
    finally:
        shutil.rmtree(tmp_dir, ignore_errors=True)

    lo_n, hi_n = counts[0], counts[-1]
    lo, hi = knees[lo_n].achieved_qps, knees[hi_n].achieved_qps
    scaling = hi / max(lo, 1e-9)
    print(f"\nreplica scale-out: {lo_n} replica(s) sustained {lo:.1f} "
          f"QPS -> {hi_n} replica(s) sustained {hi:.1f} QPS = "
          f"{scaling:.2f}x (floor {args.scale_floor}x)")
    rc = 0
    if errors:
        print(f"graftload: {errors} request error(s) at or below the "
              "sustained knee — reads must not fail under capacity",
              file=sys.stderr)
        rc = 1
    if args.scale_floor and scaling < args.scale_floor:
        print(f"graftload: scaling {scaling:.2f}x below the "
              f"{args.scale_floor}x floor", file=sys.stderr)
        rc = 1
    if args.trajectory and rc == 0:
        knee = knees[hi_n]
        config = {"source": "graftload", "replica_sweep": counts,
                  "batch": args.batch, "workers": args.workers,
                  "duration": args.duration, "path": "rest",
                  "client": "sharded", "batched": bool(args.batched)}
        rec = graftwatch.make_serving_record(
            routes={"rest": knee.summary()},
            offered_qps=knee.offered_qps,
            achieved_qps=knee.achieved_qps, errors=errors,
            replicas=hi_n, qps_band=knee.per_chunk_qps(),
            rejected=sum(k.rejected for k in knees.values()),
            config=config)
        # per-run measurements ride the serving section, NOT config —
        # config keys the gate's baseline group and must be stable
        # across runs of the same sweep
        rec["serving"]["scaling_vs_min_replicas"] = round(scaling, 3)
        rec["serving"]["min_replicas_qps"] = round(lo, 1)
        graftwatch.append_record(args.trajectory, rec)
        print(f"graftload: appended replica-sweep serving record to "
              f"{args.trajectory} ({hi_n} replicas, "
              f"{knee.achieved_qps:.1f} QPS sustained)")
    print("graftload: ok" if rc == 0 else "graftload: FAILED",
          flush=True)
    return rc


def scrape_plan_adjustments(endpoints) -> Dict[str, float]:
    """Sum the replicas' ``oe_plan_adjust_total{knob=,direction=}``
    counters off /metrics — every knob move the online tuner made,
    labeled. Dead replicas contribute nothing."""
    import re as re_mod
    import urllib.request
    out: Dict[str, float] = {}
    pat = re_mod.compile(
        r"^oe_plan_adjust_total\{([^}]*)\} ([0-9.e+]+)$",
        re_mod.MULTILINE)
    for ep in endpoints:
        try:
            with urllib.request.urlopen(f"http://{ep}/metrics",
                                        timeout=3) as r:
                body = r.read().decode()
        except Exception:  # noqa: BLE001 — a dead replica is expected
            continue
        for m in pat.finditer(body):
            out[m.group(1)] = out.get(m.group(1), 0.0) \
                + float(m.group(2))
    return out


# calm fraction of the drift window: the calm phase exists to force a
# real mid-run shift (the tuner must START from the calm knobs); the
# storm phase is where adaptation pays, so it gets the larger share
DRIFT_CALM_FRACTION = 1.0 / 3.0


def drift_arrivals(lo: float, hi: float, duration: float,
                   seed: int) -> np.ndarray:
    """Open-loop arrival schedule with a mid-run load shift: Poisson at
    ``lo`` QPS for the first third of the window, ``hi`` QPS for the
    rest — the drifting-load scenario the online tuner exists for."""
    calm = duration * DRIFT_CALM_FRACTION
    a1 = poisson_arrivals(lo, calm, seed=seed)
    a2 = poisson_arrivals(hi, duration - calm, seed=seed + 1)
    return np.concatenate([a1, calm + a2])


def run_drift_ab(args) -> int:
    """Drifting-load A/B (the graftplan online-mode claim): one storm
    schedule with a mid-run QPS shift (``--drift lo,hi``) driven at
    three single-replica arms —

    * ``static-calm``: the knobs the offline planner emits from a
      window captured in the CALM phase (flush width from the request
      shape, wait from the lo arrival rate);
    * ``static-storm``: the planner's answer for a window captured
      AFTER the shift (same flush-width rule — it is a function of
      request shape, not load — wait from the hi arrival rate). The
      point of this arm: even a perfectly timed re-plan cannot size
      flushes for saturation from a request-size histogram;
    * ``adaptive``: starts from the calm knobs with the graftplan
      online tuner armed — it must detect the shift (occupancy /
      rejects) and walk rows+wait up inside the plan envelope, whose
      ceiling (4x the static choice) the planner emitted alongside
      the statics.

    Gate (``--ab-floor``): adaptive sustained QPS >= floor x the
    better static arm's, at equal-or-lower p99. Every tuner move is
    counted (``oe_plan_adjust_total``) and reported; a zero-adjustment
    pass would be vacuous, so that also fails the gate. Appends ONE
    ``serving`` record for the adaptive arm (its own baseline group:
    config carries ``drift`` + ``adaptive``) when gating passes.
    """
    import shutil
    import tempfile
    from openembedding_tpu.analysis import plan as plan_lib
    from openembedding_tpu.serving import ha
    from tools import graftwatch

    lo, hi = (float(x) for x in args.drift.split(","))

    # planner knobs for a window captured in each phase — the SAME
    # rules analysis/plan.build_plan applies (rows from the request
    # shape, wait from the phase's arrival rate, envelope ceiling 4x
    # rows), so the static arms are exactly what tools/graftplan
    # would ship, not strawmen
    # queue depth is deliberately PINNED to the library default across
    # all three arms: an arm that sheds most of the storm gets a
    # flattering p99 on the survivors, so varying rejection policy
    # would confound the latency comparison — the arms must differ
    # ONLY in the flush knobs the tuner moves
    def planner_knobs(rate: float):
        rows = plan_lib._pow2ceil(
            max(64, plan_lib.ROWS_PER_FLUSH_P95 * args.batch))
        wait = min(2000, max(50, int(round(
            plan_lib.WAIT_INTERARRIVALS * 1e6 / max(rate, 1.0)
            / 10.0)) * 10))
        return rows, wait

    calm_rows, calm_wait = planner_knobs(lo)
    storm_rows, storm_wait = planner_knobs(hi)
    ceiling = min(8192, plan_lib._pow2ceil(4 * calm_rows))
    arms = (
        ("static-calm", dict(batch_rows=calm_rows,
                             batch_wait_us=calm_wait,
                             adaptive=False)),
        ("static-storm", dict(batch_rows=storm_rows,
                              batch_wait_us=storm_wait,
                              adaptive=False)),
        ("adaptive", dict(batch_rows=calm_rows,
                          batch_wait_us=calm_wait, adaptive=True)),
    )
    tmp_dir = tempfile.mkdtemp(prefix="graftload_drift_")
    results: Dict[str, StormResult] = {}
    adjustments: Dict[str, float] = {}
    try:
        model_dir = build_demo_checkpoint(os.path.join(tmp_dir, "model"))
        head = (f"{'arm':>15}{'offered':>9}{'achieved':>10}{'calls':>7}"
                f"{'err':>5}{'rej':>6}{'p50_ms':>9}{'p99_ms':>10}")
        print(f"\ndrift storm: {lo:g} -> {hi:g} QPS at "
              f"{DRIFT_CALM_FRACTION:.0%} of the window "
              f"({args.duration:g}s total, batch {args.batch})")
        print(head + "\n" + "-" * len(head))
        for ai, (name, kw) in enumerate(arms):
            env = {"OE_PLAN_ADJUST_INTERVAL_MS": "100",
                   "OE_PLAN_ROWS_CEILING": str(ceiling)} \
                if kw["adaptive"] else None
            endpoints, procs, _tr = boot_demo_cluster(
                model_dir, 1, batch_rows=kw["batch_rows"],
                batch_wait_us=kw["batch_wait_us"],
                adaptive=kw["adaptive"], env=env)
            client = ha.RoutingClient(endpoints, timeout=args.timeout)
            try:
                send = make_rest_sender(client, DEMO_SIGN, "emb",
                                        DEMO_VOCAB, args.batch,
                                        seed=40 + ai)
                arrivals = drift_arrivals(lo, hi, args.duration,
                                          seed=700 + 10 * ai)
                offered = arrivals.size / args.duration
                res = run_storm(send, arrivals, route=name,
                                offered_qps=offered,
                                duration=args.duration,
                                workers=args.workers)
                results[name] = res
                if kw["adaptive"]:
                    adjustments = scrape_plan_adjustments(endpoints)
                s = res.summary()
                print(f"{name:>15}{s['offered_qps']:>9}"
                      f"{s['achieved_qps']:>10}{s['calls']:>7}"
                      f"{s['errors']:>5}{s['rejected']:>6}"
                      f"{s['p50_ms']:>9}{s['p99_ms']:>10}", flush=True)
            finally:
                client.close()
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:
                    p.wait()
    finally:
        shutil.rmtree(tmp_dir, ignore_errors=True)

    statics = {n: r for n, r in results.items() if n != "adaptive"}
    best_name = max(statics, key=lambda n: statics[n].achieved_qps)
    best = statics[best_name]
    adaptive = results["adaptive"]
    ratio = adaptive.achieved_qps / max(best.achieved_qps, 1e-9)
    n_moves = int(sum(adjustments.values()))
    moves = ", ".join(f"{k}: {int(v)}"
                      for k, v in sorted(adjustments.items())) \
        or "none"
    print(f"\nadaptive sustained {adaptive.achieved_qps:.1f} QPS vs "
          f"better static ({best_name}) {best.achieved_qps:.1f} QPS "
          f"= {ratio:.2f}x (floor {args.ab_floor}x); p99 "
          f"{adaptive.quantile_ms(0.99):.1f} ms vs "
          f"{best.quantile_ms(0.99):.1f} ms")
    print(f"tuner adjustments: {n_moves} ({moves})")
    rc = 0
    errors = sum(r.errors for r in results.values())
    if errors:
        print(f"graftload: {errors} request error(s) — drift overload "
              "must degrade to 429 rejections, never failures",
              file=sys.stderr)
        rc = 1
    if args.ab_floor and ratio < args.ab_floor:
        print(f"graftload: adaptive/static ratio {ratio:.2f}x below "
              f"the {args.ab_floor}x floor", file=sys.stderr)
        rc = 1
    if adaptive.quantile_ms(0.99) > best.quantile_ms(0.99):
        print("graftload: adaptive p99 "
              f"{adaptive.quantile_ms(0.99):.1f} ms above the better "
              f"static arm's {best.quantile_ms(0.99):.1f} ms — the "
              "claim is MORE throughput at equal-or-lower tail",
              file=sys.stderr)
        rc = 1
    if n_moves == 0:
        print("graftload: the online tuner made ZERO knob moves over "
              "a 4x load shift — adaptation is not happening "
              "(oe_plan_adjust_total stayed 0)", file=sys.stderr)
        rc = 1
    if args.trajectory and rc == 0:
        config = {"source": "graftload", "drift": [lo, hi],
                  "adaptive": True, "batch": args.batch,
                  "workers": args.workers, "duration": args.duration,
                  "path": "rest", "batched": True}
        rec = graftwatch.make_serving_record(
            routes={"rest": adaptive.summary()},
            offered_qps=adaptive.offered_qps,
            achieved_qps=adaptive.achieved_qps, errors=errors,
            replicas=1, qps_band=adaptive.per_chunk_qps(),
            rejected=adaptive.rejected, config=config)
        # per-run measurements ride the serving section, NOT config
        rec["serving"]["vs_static_ratio"] = round(ratio, 3)
        rec["serving"]["best_static_arm"] = best_name
        rec["serving"]["best_static_qps"] = round(best.achieved_qps, 1)
        rec["serving"]["best_static_p99_ms"] = round(
            best.quantile_ms(0.99), 3)
        rec["serving"]["plan_adjustments"] = n_moves
        graftwatch.append_record(args.trajectory, rec)
        print(f"graftload: appended drift-A/B serving record to "
              f"{args.trajectory} ({ratio:.2f}x vs {best_name})")
    print("graftload: ok" if rc == 0 else "graftload: FAILED",
          flush=True)
    return rc


# --- demo cluster ------------------------------------------------------------

def build_demo_checkpoint(out_dir: str) -> str:
    """Train-free tiny checkpoint the demo replicas serve (constant
    0.5 rows — lookups are value-checkable)."""
    import jax
    from openembedding_tpu import EmbeddingCollection, EmbeddingSpec
    from openembedding_tpu import checkpoint as ckpt
    from openembedding_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(1, 1, jax.devices()[:1])
    spec = EmbeddingSpec(
        name="emb", input_dim=DEMO_VOCAB, output_dim=DEMO_DIM,
        initializer={"category": "constant", "value": 0.5})
    coll = EmbeddingCollection((spec,), mesh)
    states = coll.init(jax.random.PRNGKey(0))
    ckpt.save_checkpoint(out_dir, coll, states, model_sign=DEMO_SIGN)
    return out_dir


def boot_demo_cluster(model_dir: str, replicas: int,
                      trace_dir: str = "", batch_rows: int = 0,
                      batch_wait_us: Optional[int] = None,
                      batch_queue_rows: Optional[int] = None,
                      adaptive: bool = False,
                      env: Optional[Dict[str, str]] = None):
    """Spawn ``replicas`` replica daemons serving the demo checkpoint;
    returns (endpoints, procs, trace_paths). With ``trace_dir`` each
    replica records spans and exports them on graceful (SIGTERM)
    shutdown — the server-side half of the merged Perfetto story.
    ``batch_rows > 0`` arms each replica's micro-batching scheduler
    (the --batched A/B arm); ``adaptive`` arms the graftplan online
    tuner on top of it (``env`` can carry OE_PLAN_* envelope
    overrides)."""
    import socket
    from openembedding_tpu.serving import ha

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    ports = [free_port() for _ in range(replicas)]
    eps = [f"127.0.0.1:{p}" for p in ports]
    traces = [os.path.join(trace_dir, f"replica_{i}.json") if trace_dir
              else "" for i in range(replicas)]
    procs = [ha.spawn_replica(p, load=[f"{DEMO_SIGN}={model_dir}"],
                              trace_out=tr, batch_rows=batch_rows,
                              batch_wait_us=batch_wait_us,
                              batch_queue_rows=batch_queue_rows,
                              adaptive=adaptive, env=env)
             for p, tr in zip(ports, traces)]
    for ep, proc in zip(eps, procs):
        if not ha.wait_ready(ep, sign=DEMO_SIGN):
            tail = ""
            if proc.poll() is not None:
                tail = (proc.stdout.read() or "")[-2000:]
            raise RuntimeError(f"replica {ep} never became ready: {tail}")
    return eps, procs, [t for t in traces if t]


# --- CLI ---------------------------------------------------------------------

def _storm_once(args, route: str, send, rate: float,
                seed: int) -> StormResult:
    arrivals = poisson_arrivals(rate, args.duration, seed=seed)
    # offered = the rate actually DRAWN (short windows make the Poisson
    # count itself noisy; achieved must compare against what was sent)
    offered = arrivals.size / args.duration
    return run_storm(send, arrivals, route=route, offered_qps=offered,
                     duration=args.duration, workers=args.workers)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop serving load generator + SLO sweep")
    ap.add_argument("--endpoints", default="",
                    help="comma-separated replica endpoints (one shard "
                         "group); omit with --demo")
    ap.add_argument("--sign", default=DEMO_SIGN)
    ap.add_argument("--variable", default="emb")
    ap.add_argument("--vocab", type=int, default=DEMO_VOCAB,
                    help="id range for the random lookup batches")
    ap.add_argument("--batch", type=int, default=16,
                    help="ids per lookup request")
    ap.add_argument("--qps", type=float, default=100.0,
                    help="offered rate (open-loop Poisson arrivals)")
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--workers", type=int, default=32,
                    help="max in-flight requests (bounds concurrency, "
                         "NOT the accounting — a full pool shows up as "
                         "latency, never as a slower arrival clock)")
    ap.add_argument("--sweep", default="",
                    help="comma-separated offered rates; reports the "
                         "sustained knee (achieved >= 0.9 x offered, "
                         "zero errors)")
    ap.add_argument("--path", choices=("rest", "native", "both"),
                    default="rest")
    ap.add_argument("--demo", action="store_true",
                    help="boot a --replicas local cluster on a tiny "
                         "generated checkpoint, storm it, tear it down")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--drift", default="",
                    help="LO,HI QPS: run the drifting-load A/B (load "
                         "shifts LO->HI at half-window) over "
                         "static-calm / static-default / adaptive "
                         "arms and gate the adaptive arm's sustained "
                         "QPS against the better static (graftplan "
                         "online mode)")
    ap.add_argument("--ab-floor", type=float, default=1.15,
                    help="drift A/B gate: adaptive sustained QPS must "
                         "be >= this x the better static arm's "
                         "(0 disables)")
    ap.add_argument("--replica-sweep", default="",
                    help="comma-separated replica counts (e.g. 1,3): "
                         "boot a fresh demo cluster per count, drive it "
                         "through ShardedRoutingClient with a per-count "
                         "knee sweep (--sweep rates or a default "
                         "ladder), report sustained-QPS scaling from "
                         "the lowest to the highest count, and append "
                         "ONE serving record for the top count's knee. "
                         "Exit nonzero when scaling < --scale-floor. "
                         "ROADMAP item 4's scale-out half; pair with "
                         "--batched for the batched serving plane")
    ap.add_argument("--scale-floor", type=float, default=1.6,
                    help="minimum sustained-QPS scaling the "
                         "--replica-sweep must show from its lowest to "
                         "highest replica count (0 disables the gate)")
    ap.add_argument("--model-dir", default="",
                    help="checkpoint dir for --path native (implied by "
                         "--demo)")
    ap.add_argument("--chaos", action="store_true",
                    help="SIGKILL one replica halfway through the REST "
                         "storm (demo mode): reads must never error "
                         "while a replica lives, and the trace shows "
                         "the reroute")
    ap.add_argument("--respawn", action="store_true",
                    help="with --chaos: immediately respawn the killed "
                         "replica with --peers pointing at the "
                         "survivors and MEASURE the recovery time "
                         "(kill -> /health NORMAL again); emits a "
                         "'recovery' trajectory record when "
                         "--trajectory is set")
    ap.add_argument("--batched", action="store_true",
                    help="arm each demo replica's micro-batching "
                         "lookup scheduler (serving/batcher.py) — the "
                         "A/B arm against the default unbatched path; "
                         "replica oe_batch_* counters are scraped off "
                         "/metrics after the storms")
    ap.add_argument("--batch-rows", type=int, default=None,
                    help="per-flush row cap for --batched replicas "
                         "(default: envconfig.DEFAULT_BATCH_ROWS)")
    ap.add_argument("--batch-wait-us", type=int, default=None,
                    help="adaptive flush wait for --batched replicas "
                         "(default: envconfig.DEFAULT_BATCH_WAIT_US)")
    ap.add_argument("--batch-queue-rows", type=int, default=None,
                    help="bounded queue depth (rows) for --batched "
                         "replicas; offers past it return 429-busy "
                         "(counted as REJECTED, never as errors)")
    ap.add_argument("--trace", default="",
                    help="write the storm's request-scoped spans as "
                         "Perfetto-loadable JSON")
    ap.add_argument("--trajectory", default="",
                    help="append a `serving` record to this "
                         "BENCH_trajectory.jsonl (graftwatch --gate "
                         "covers it)")
    ap.add_argument("--devices", type=int, default=8,
                    help="virtual CPU devices for THIS process (keys "
                         "the hardware fingerprint; replicas always "
                         "run 1)")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)

    # fingerprint parity with the committed cpu8 baselines: force the
    # virtual device count BEFORE jax initializes
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")
    from openembedding_tpu.utils.jaxcompat import set_num_cpu_devices
    set_num_cpu_devices(args.devices)

    from openembedding_tpu.analysis import scope
    from openembedding_tpu.serving import ha
    from openembedding_tpu.utils import envconfig
    from tools import graftwatch

    # the batcher knobs' single home is envconfig (imported after the
    # jax env setup above — the package pulls jax at import)
    if args.batch_rows is None:
        args.batch_rows = envconfig.DEFAULT_BATCH_ROWS
    if args.batch_wait_us is None:
        args.batch_wait_us = envconfig.DEFAULT_BATCH_WAIT_US
    if args.batch_queue_rows is None:
        args.batch_queue_rows = envconfig.DEFAULT_BATCH_QUEUE_ROWS

    if args.drift:
        return run_drift_ab(args)
    if args.replica_sweep:
        return run_replica_sweep(args)

    rc = 0
    procs: List[Any] = []
    replica_traces: List[str] = []
    router = None
    native_model = None
    native_batcher = None
    tmp_dir = None
    try:
        # --- target selection ---------------------------------------------
        if args.demo:
            import tempfile
            tmp_dir = tempfile.mkdtemp(prefix="graftload_demo_")
            model_dir = build_demo_checkpoint(
                os.path.join(tmp_dir, "model"))
            args.sign, args.variable = DEMO_SIGN, "emb"
            args.vocab = DEMO_VOCAB
            print(f"graftload: demo checkpoint at {model_dir}",
                  flush=True)
            endpoints, procs, replica_traces = boot_demo_cluster(
                model_dir, args.replicas,
                trace_dir=tmp_dir if args.trace else "",
                batch_rows=args.batch_rows if args.batched else 0,
                batch_wait_us=args.batch_wait_us,
                batch_queue_rows=args.batch_queue_rows)
            print(f"graftload: {len(endpoints)} replica(s) ready: "
                  f"{endpoints}", flush=True)
        else:
            endpoints = [e for e in args.endpoints.split(",") if e]
            model_dir = args.model_dir
            if not endpoints and args.path != "native":
                ap.error("--endpoints required without --demo")
        if args.path in ("rest", "both"):
            router = ha.RoutingClient(endpoints, timeout=args.timeout)
        if args.path in ("native", "both"):
            if not model_dir:
                ap.error("--model-dir required for --path native")
            from openembedding_tpu.serving.native import NativeModel
            native_model = NativeModel(model_dir)
            if args.batched:
                native_batcher = native_model.make_batcher(
                    max_batch_rows=args.batch_rows,
                    max_wait_us=args.batch_wait_us,
                    max_queue_rows=args.batch_queue_rows)

        if args.trace:
            scope.set_tracing(True)

        # --- storms --------------------------------------------------------
        rates = ([float(x) for x in args.sweep.split(",") if x]
                 if args.sweep else [args.qps])
        by_route: Dict[str, StormResult] = {}
        all_storms: List[StormResult] = []
        sweep_results: List[StormResult] = []
        head = (f"{'route':<8}{'offered':>9}{'achieved':>10}{'calls':>7}"
                f"{'err':>5}{'rej':>6}{'p50_ms':>9}{'p95_ms':>9}"
                f"{'p99_ms':>9}")
        print("\n" + head + "\n" + "-" * len(head))

        recovery_info: Dict[str, Any] = {}
        recovery_done = threading.Event()

        def _kill_victim():
            procs[-1].kill()
            procs[-1].wait()

        def _kill_and_respawn():
            """The kill-AND-respawn chaos lane: SIGKILL a replica, boot
            its replacement against the survivors (restore-from-peer),
            and measure MTTR = kill -> /health NORMAL with the model."""
            try:
                t0 = time.perf_counter()
                _kill_victim()
                survivors = endpoints[:-1]
                port = int(endpoints[-1].rsplit(":", 1)[1])
                procs[-1] = ha.spawn_replica(
                    port, peers=survivors,
                    batch_rows=args.batch_rows if args.batched else 0,
                    batch_wait_us=args.batch_wait_us,
                    batch_queue_rows=args.batch_queue_rows)
                ok = ha.wait_ready(endpoints[-1], sign=args.sign,
                                   timeout=180.0)
                recovery_info["mttr_s"] = time.perf_counter() - t0
                recovery_info["ok"] = ok
                h = ha.probe_health(endpoints[-1]) or {}
                recovery_info["applied_seq"] = h.get("applied_seq", 0)
            finally:
                recovery_done.set()

        def run_and_print(route: str, send, rate: float,
                          seed: int) -> StormResult:
            kill_at = None
            if args.chaos and route == "rest" and len(procs) > 1 \
                    and not (args.respawn
                             and recovery_info.get("started")):
                # respawn measures ONE kill->recover cycle; the plain
                # kill lane keeps its per-storm behavior (re-killing a
                # dead process is a no-op)
                recovery_info["started"] = True
                kill_at = threading.Timer(
                    args.duration / 2.0,
                    _kill_and_respawn if args.respawn else _kill_victim)
                kill_at.start()
            res = _storm_once(args, route, send, rate, seed)
            if kill_at is not None:
                kill_at.cancel()
            all_storms.append(res)
            s = res.summary()
            print(f"{route:<8}{s['offered_qps']:>9}{s['achieved_qps']:>10}"
                  f"{s['calls']:>7}{s['errors']:>5}{s['rejected']:>6}"
                  f"{s['p50_ms']:>9}{s['p95_ms']:>9}{s['p99_ms']:>9}"
                  + ("   CHAOS: killed 1 replica mid-storm"
                     if kill_at is not None else ""), flush=True)
            return res

        for ri, rate in enumerate(rates):
            if router is not None:
                send = make_rest_sender(router, args.sign, args.variable,
                                        args.vocab, args.batch, seed=ri)
                res = run_and_print("rest", send, rate, seed=100 + ri)
                by_route["rest"] = res
                sweep_results.append(res)
            if native_model is not None:
                send = make_native_sender(native_model, args.variable,
                                          args.vocab, args.batch,
                                          seed=50 + ri,
                                          batcher=native_batcher)
                res = run_and_print("native", send, rate, seed=200 + ri)
                by_route["native"] = res
                if router is None:
                    sweep_results.append(res)

        if args.sweep:
            knee = find_knee(sweep_results)
            if knee is not None:
                print(f"\nknee: sustained {knee.achieved_qps:.1f} QPS at "
                      f"offered {knee.offered_qps:.0f} "
                      f"(p99 {knee.quantile_ms(0.99):.1f} ms)")
                # the record below reflects ONLY the knee: every other
                # route/rate in the sweep ran at rates chosen to find
                # saturation, and saturated quantiles are not a
                # latency baseline
                by_route = {knee.route: knee}
            else:
                print("\nknee: NOT FOUND — even the lowest offered rate "
                      "saturated or errored")
                by_route = {}

        # errors are judged over EVERY storm run, not just the ones the
        # record keeps — a chaos-kill error in an early sweep rate must
        # fail the invariant even when later rates ran clean
        errors = sum(r.errors for r in all_storms)
        if errors:
            for r in all_storms:
                if getattr(r, "first_error", ""):
                    print(f"graftload: first {r.route} error: "
                          f"{r.first_error}", file=sys.stderr)
                    break
            print(f"graftload: {errors} request error(s) — the chaos "
                  "invariant is reads NEVER error while a replica "
                  "lives", file=sys.stderr)
            rc = 1

        # client-side request counters (also on /metrics when the
        # client is in-process with a server)
        for name in ("serving_client_connections",
                     "serving_request_retries",
                     "serving_request_failovers"):
            v = scope.HISTOGRAMS.counter(name)
            if v:
                print(f"  {name}: {v:.0f}")

        # server-side coalescing evidence: the replicas' oe_batch_*
        # counters (scraped while they still live — the trace branch
        # SIGTERMs them below)
        rejected = sum(r.rejected for r in all_storms)
        batch_stats: Dict[str, float] = {}
        if args.batched:
            batch_stats = scrape_batch_stats(endpoints)
            if batch_stats.get("batch_flushes"):
                factor = batch_stats.get("batch_requests", 0.0) \
                    / batch_stats["batch_flushes"]
                dedup = batch_stats.get("batch_unique_rows", 0.0) \
                    / max(1.0, batch_stats.get("batch_rows", 0.0))
                print(f"  batching: {batch_stats['batch_flushes']:.0f} "
                      f"flushes, {factor:.2f} requests/flush, "
                      f"unique/rows {dedup:.2f}")
        if rejected:
            print(f"  rejected (429 backpressure): {rejected}")

        # --- kill-and-respawn recovery verdict -----------------------------
        if args.respawn and recovery_info.get("started"):
            # the respawn runs on the chaos timer's thread; the storm
            # usually outlives it, but join explicitly before judging
            if not recovery_done.wait(timeout=240.0):
                print("graftload: respawned replica never recovered "
                      "(timeout)", file=sys.stderr)
                rc = 1
            elif not recovery_info.get("ok"):
                print("graftload: respawned replica came up without "
                      f"the model (applied_seq "
                      f"{recovery_info.get('applied_seq')})",
                      file=sys.stderr)
                rc = 1
            else:
                mttr = recovery_info["mttr_s"]
                print(f"  CHAOS: killed + respawned 1 replica — "
                      f"recovery {mttr:.2f}s, applied_seq "
                      f"{recovery_info.get('applied_seq')}")
                if args.trajectory:
                    model_bytes = 0
                    if model_dir and os.path.isdir(model_dir):
                        for dp, _dn, fn in os.walk(model_dir):
                            model_bytes += sum(
                                os.path.getsize(os.path.join(dp, f))
                                for f in fn)
                    rec = graftwatch.make_recovery_record(
                        mttr_s=mttr, steps_lost=0,
                        bytes_replayed=model_bytes,
                        config={"source": "graftload",
                                "kind": "respawn",
                                "replicas": args.replicas,
                                "batched": bool(args.batched)})
                    graftwatch.append_record(args.trajectory, rec)
                    print(f"graftload: appended recovery record "
                          f"(MTTR {mttr:.2f}s)")

        # --- artifacts -----------------------------------------------------
        if args.trace:
            client_trace = scope.export_chrome_trace(
                process_name="graftload")
            # fold the replicas' server-side spans in: SIGTERM each
            # daemon (its --trace-out export runs in the shutdown
            # path), then merge every process onto the client timeline
            server_traces: List[Dict[str, Any]] = []
            if replica_traces:
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:
                    p.wait()
                for path in replica_traces:
                    try:
                        with open(path, encoding="utf-8") as f:
                            server_traces.append(json.load(f))
                    except (OSError, json.JSONDecodeError):
                        # a chaos-killed replica (SIGKILL) never wrote
                        # its trace — expected, not a failure
                        pass
            trace = scope.merge_chrome_traces(client_trace,
                                              server_traces, args.trace)
            n = sum(1 for e in trace["traceEvents"]
                    if e.get("ph") == "X")
            traced = {e["args"]["trace"] for e in trace["traceEvents"]
                      if e.get("args", {}).get("trace")}
            sides = {e.get("pid") for e in trace["traceEvents"]}
            print(f"wrote {args.trace}: {n} span events across "
                  f"{len(sides)} process(es), {len(traced)} request "
                  "traces (open in https://ui.perfetto.dev)")
            if not traced:
                print("graftload: trace carries no request ids",
                      file=sys.stderr)
                rc = 1

        if args.trajectory:
            primary = by_route.get("rest") or by_route.get("native")
            if primary is None or primary.achieved_qps <= 0:
                # nothing sustainable to record (every request errored,
                # or the sweep found no knee): refuse the record, fail
                # the run — never die on the schema validator's
                # positive-QPS check with a traceback
                print("graftload: no successful storm to record — "
                      "skipping the trajectory record", file=sys.stderr)
                rc = 1
            else:
                config = {"source": "graftload", "qps": args.qps,
                          "duration": args.duration,
                          "batch": args.batch,
                          "workers": args.workers, "path": args.path,
                          "replicas": args.replicas,
                          "sweep": bool(args.sweep),
                          "chaos": bool(args.chaos)}
                if args.batched:
                    # only the BATCHED arm adds these keys: the config
                    # dict keys the gate's baseline group, and the
                    # unbatched arm must keep matching its committed
                    # pre-batching baselines
                    config["batched"] = True
                    config["batch_rows"] = args.batch_rows
                    config["batch_wait_us"] = args.batch_wait_us
                rec = graftwatch.make_serving_record(
                    routes={k: v.summary()
                            for k, v in by_route.items()},
                    offered_qps=primary.offered_qps,
                    achieved_qps=primary.achieved_qps,
                    errors=errors, replicas=max(1, len(endpoints)),
                    qps_band=primary.per_chunk_qps(),
                    rejected=rejected,
                    batch_stats=batch_stats or None,
                    config=config)
                graftwatch.append_record(args.trajectory, rec)
                print(f"graftload: appended serving record to "
                      f"{args.trajectory} (achieved "
                      f"{rec['eps']:.1f} QPS, rest p99 "
                      f"{rec['scope'].get('rest', {}).get('p99_ms')} "
                      "ms)")
    finally:
        if router is not None:
            router.close()
        if native_batcher is not None:
            native_batcher.close()
        if native_model is not None:
            native_model.close()
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait()
        if tmp_dir:
            import shutil
            shutil.rmtree(tmp_dir, ignore_errors=True)

    print("graftload: ok" if rc == 0 else "graftload: FAILED",
          flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
