"""graftfuzz CLI: differential fuzzing + sanitizer gate.

    python -m tools.graftfuzz --seed 0                 # one full sweep
    python -m tools.graftfuzz --seed 0 --iters 60      # longer run
    python -m tools.graftfuzz --lanes wire,ingest      # no native builds
    python -m tools.graftfuzz --regress                # pinned corpus
    python -m tools.graftfuzz --json out.json          # CI artifact

Fifth leg of the static-analysis gate (graftlint / graftrace /
graftcheck / graftproto / graftfuzz): where the first four reason about
the package's OWN code and models, this leg attacks the parsers that
consume bytes the package did not write — the native checkpoint reader
(under ASan AND UBSan builds, each probe contained in a subprocess),
the Python delta/checkpoint readers, the ``encode_delta`` wire codec
behind ``POST /models/<sign>/delta``, and the TFRecord/TSV ingest
framers. Structure-aware mutators (bit flips, truncations, zip
central-directory/local-header field surgery, manifest field fuzz,
wire-header fuzz, TFRecord length/crc corruption) run from a seeded
PRNG: **two runs with the same --seed produce byte-identical reports**
(no wall-clock, no absolute paths in the output).

Oracle = differential trichotomy: every reader must load-and-bit-agree,
refuse TYPED, or recover to the same documented version — never
SIGSEGV, never UB, never hang past --deadline, never an untyped Python
exception, never a silent Python-vs-native divergence.

Exit is nonzero on ANY violation OR any declared mutation class that
never fired (a run that looks green must actually have explored every
class — the graftproto no-hollow-exploration discipline). ``--regress``
instead replays the pinned corpus (tests/fixtures/fuzz_corpus.py):
known-bad shapes from PR 12 (crafted name_len / offset overflow),
graftchaos torn writes, compaction, codec refusals — each must produce
EXACTLY its pinned per-reader disposition under plain, ASan and UBSan
native builds.

Implementation lives in ``openembedding_tpu/analysis/fuzz.py``; this
wrapper only parses flags, prints the coverage table and sets exit
status. Unlike the other gate legs this one necessarily imports the
package (the Python probes ARE the system under test), so it pins
JAX_PLATFORMS=cpu before the first package import.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, _ROOT)

LANES = ("ckpt", "wire", "ingest")


def _print_coverage(report) -> None:
    classes = report["classes"]
    w = max(len(n) for n in classes) if classes else 10
    print(f"\n{'class':<{w}}  fired  viol  outcomes")
    for name in sorted(classes):
        c = classes[name]
        ocs = ", ".join(f"{k}x{v}" for k, v in sorted(c["outcomes"].items()))
        print(f"{name:<{w}}  {c['fired']:>5}  {c['violations']:>4}  {ocs}")
    if report["silent_classes"]:
        print(f"\nSILENT (never fired): "
              f"{', '.join(report['silent_classes'])}", file=sys.stderr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="differential fuzzing over the untrusted-bytes "
                    "surface (checkpoint/delta/wire/ingest), native "
                    "probes under ASan+UBSan")
    ap.add_argument("--seed", type=int, default=0,
                    help="PRNG seed; the whole run replays from it")
    ap.add_argument("--iters", type=int, default=None,
                    help="iterations (default: one per declared class; "
                         "classes fire round-robin, so >= the class "
                         "count guarantees full coverage)")
    ap.add_argument("--lanes", default="ckpt,wire,ingest",
                    help="comma-separated lane subset (ckpt,wire,ingest)")
    ap.add_argument("--deadline", type=float, default=30.0,
                    help="per-probe hang deadline in seconds")
    ap.add_argument("--no-build", action="store_true",
                    help="reuse existing sanitizer .so's instead of "
                         "rebuilding (local iteration only; CI builds)")
    ap.add_argument("--regress", action="store_true",
                    help="replay the pinned regression corpus "
                         "(tests/fixtures/fuzz_corpus.py) instead of "
                         "fuzzing: every entry must produce exactly its "
                         "pinned per-reader disposition")
    ap.add_argument("--json", default="", metavar="OUT",
                    help="write the full deterministic report as JSON "
                         "(the CI artifact)")
    ap.add_argument("--emit-corpus", default="", metavar="DIR",
                    help="also materialize every pinned corpus entry "
                         "as a mutated checkpoint dir under DIR (the "
                         "weekly CI corpus artifact)")
    args = ap.parse_args(argv)

    lanes = tuple(x for x in args.lanes.split(",") if x)
    bad_lanes = [x for x in lanes if x not in LANES]
    if bad_lanes or not lanes:
        print(f"graftfuzz: unknown lanes {bad_lanes} (have: {LANES})",
              file=sys.stderr)
        return 2

    from openembedding_tpu.analysis import fuzz

    if args.emit_corpus:
        import tempfile
        os.makedirs(args.emit_corpus, exist_ok=True)
        with tempfile.TemporaryDirectory(prefix="graftfuzz-seed-") as tmp:
            ctx = fuzz.SeedContext(os.path.join(tmp, "ctx"))
            for name in sorted(fuzz.CORPUS_BUILDERS):
                fuzz.build_corpus_dir(name, ctx, args.emit_corpus)
        print(f"graftfuzz: {len(fuzz.CORPUS_BUILDERS)} corpus dirs -> "
              f"{args.emit_corpus}")

    failed = 0
    if args.regress:
        import shutil
        import tempfile
        tmp = tempfile.mkdtemp(prefix="graftfuzz-regress-")
        try:
            ctx = fuzz.SeedContext(os.path.join(tmp, "ctx"))
            libs = fuzz.sanitizer_libs(build=not args.no_build)
            report = fuzz.run_regress(ctx, libs, os.path.join(tmp, "w"),
                                      deadline=args.deadline, log=print)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        for f in report["failures"]:
            print(f"[{f['entry']}] {f['detail']}", file=sys.stderr)
        failed = len(report["failures"])
        print(f"graftfuzz --regress: {report['entries']} corpus entries, "
              f"{failed} disposition failure(s)")
    else:
        report = fuzz.run_fuzz(seed=args.seed, iters=args.iters,
                               lanes=lanes, deadline=args.deadline,
                               build=not args.no_build, log=print)
        _print_coverage(report)
        for v in report["violations"]:
            print(f"[iter {v['iter']} {v['class']}] {v['detail']}",
                  file=sys.stderr)
        failed = len(report["violations"]) + len(report["silent_classes"])
        n_cls = len(report["classes"])
        print(f"\ngraftfuzz: seed {report['seed']}, "
              f"{report['iters']} iteration(s) over {n_cls} class(es) "
              f"[{','.join(report['lanes'])}], sanitizers "
              f"{report['sanitizers'] or ['-']}: "
              f"{len(report['violations'])} violation(s), "
              f"{len(report['silent_classes'])} silent class(es)")

    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
        print(f"graftfuzz: gate report -> {args.json}")

    if failed:
        print(f"graftfuzz: {failed} failing check(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
