"""Third-stage: is the 107 ms insert program genuine device cost or
per-call recompilation? Print per-iteration times + jax compile logs."""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import jax  # noqa: E402


def main():
    from openembedding_tpu import EmbeddingVariableMeta
    from openembedding_tpu.offload import ShardedOffloadedTable
    from openembedding_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(1, len(jax.devices()))
    vocab, cache_cap, dim = 2_000_000, 1 << 22, 8
    opt = {"category": "adagrad", "learning_rate": 0.01}
    init = {"category": "constant", "value": 0.01}
    table = ShardedOffloadedTable(
        "uid", EmbeddingVariableMeta(embedding_dim=dim,
                                     vocabulary_size=vocab),
        opt, init, vocab=vocab, cache_capacity=cache_cap, mesh=mesh)
    cache = table.create_cache()
    jax.block_until_ready(cache.keys)

    for i in range(12):
        ids = np.arange(1000 + i * 1700, 1000 + (i + 1) * 1700,
                        dtype=np.int32)
        t0 = time.perf_counter()
        cache = table._insert_from_host(cache, ids)
        jax.block_until_ready(cache.keys)
        print(f"iter {i:2d}: {1e3*(time.perf_counter()-t0):8.2f} ms")
    table._overflow_latest = None

    # same ids resubmitted (all already present -> pure probe, no insert)
    ids = np.arange(1000, 1000 + 1700, dtype=np.int32)
    t0 = time.perf_counter()
    cache = table._insert_from_host(cache, ids)
    jax.block_until_ready(cache.keys)
    print(f"resubmit (all present): {1e3*(time.perf_counter()-t0):8.2f} ms")
    table._overflow_latest = None


if __name__ == "__main__":
    main()
