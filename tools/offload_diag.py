"""Localize the offload tier's per-step cost on the real chip.

HISTORICAL NOTE (kept as the diagnosis trail): the "all-hit" labels in
steps 3/4 are wrong — the 16-batch warmup covers only ~28% of the
200k-id hot set, so the "fresh batches" loop still missed ~70% of ids
and includes insert traffic. The fresh-vs-reused 30x gap it exposed was
the first signal of the real story (diag5-7): on a degraded tunnel every
HOST-BLOCKING call costs ~105 ms regardless of payload, and the per-step
deferred-overflow reads were the tier's per-step blocker.

The r5 suite measured offload steps at ~242-335 ms with only ~25 ms of
host prepare — so the budget is device-side or transfer-side. This
script times each candidate in isolation on the live backend:

  1. h2d bandwidth (fresh numpy -> device, sizes 64K..8M)
  2. d2h round-trip latency (tiny counter read, the deferred-overflow op)
  3. plain train_step on a resident working set (all cache hits, fresh
     batches each step -- isolates batch-transfer + program cost)
  4. the same with REUSED batches (isolates whether fresh h2d is the gap)
  5. insert_rows_sharded alone at the bench's steady-state miss count

Run: python tools/offload_diag.py   (needs the TPU tunnel healthy)
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import jax
import jax.numpy as jnp


def timeit(fn, n=20, warmup=3):
    for _ in range(warmup):
        r = fn()
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    jax.block_until_ready(r)
    return (time.perf_counter() - t0) / n


def main():
    dev = jax.devices()[0]
    print(f"platform={dev.platform}")

    # 1. h2d bandwidth, fresh arrays each call (no buffer reuse)
    for mb in (0.0625, 0.5, 4.0):
        nbytes = int(mb * (1 << 20))
        bufs = [np.random.rand(nbytes // 8).astype(np.float64)
                for _ in range(8)]
        i = [0]

        def put():
            i[0] += 1
            return jax.device_put(bufs[i[0] % len(bufs)], dev)
        dt = timeit(put)
        print(f"h2d {mb:7.4f} MB: {dt*1e3:8.2f} ms  "
              f"{mb/1024/dt:8.3f} GB/s")

    # 2. d2h round trip on a tiny value
    c = jnp.int32(7) + 1

    def get():
        return int(jax.device_get(c))
    dt = timeit(lambda: jnp.asarray(get()))
    print(f"d2h tiny round trip: {dt*1e3:.2f} ms")

    # 3/4. offload-shaped train step, all-hit working set
    import optax
    from openembedding_tpu import (EmbeddingCollection, EmbeddingSpec,
                                   EmbeddingVariableMeta, Trainer)
    from openembedding_tpu.models import deepctr
    from openembedding_tpu.offload import ShardedOffloadedTable
    from openembedding_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(1, len(jax.devices()))
    vocab, cache_cap, dim, batch = 2_000_000, 1 << 22, 8, 4096
    opt = {"category": "adagrad", "learning_rate": 0.01}
    init = {"category": "constant", "value": 0.01}
    table = ShardedOffloadedTable(
        "uid", EmbeddingVariableMeta(embedding_dim=dim,
                                     vocabulary_size=vocab),
        opt, init, vocab=vocab, cache_capacity=cache_cap, mesh=mesh)
    lin = ShardedOffloadedTable(
        "uid:linear", EmbeddingVariableMeta(embedding_dim=1,
                                            vocabulary_size=vocab),
        opt, init, vocab=vocab, cache_capacity=cache_cap, mesh=mesh)
    specs = (table.embedding_spec(), lin.embedding_spec(),
             EmbeddingSpec(name="ctx", input_dim=100_000, output_dim=dim,
                           optimizer=opt),
             EmbeddingSpec(name="ctx:linear", input_dim=100_000,
                           output_dim=1, optimizer=opt))
    coll = EmbeddingCollection(specs, mesh)
    trainer = Trainer(deepctr.build_model("deepfm", ("uid", "ctx")),
                      coll, optax.adagrad(0.01),
                      offload={"uid": table, "uid:linear": lin},
                      pipeline_depth=2)
    rng = np.random.RandomState(0)
    hot = rng.randint(0, 200_000, size=(64, batch)).astype(np.int32)

    def mk(i):
        uid = hot[i % len(hot)]
        ctx = (uid * 7 % 100_000).astype(np.int32)
        return {"label": (uid % 4 == 0).astype(np.float32),
                "dense": np.tile((uid % 13).astype(np.float32)[:, None],
                                 (1, 13)),
                "sparse": {"uid": uid, "uid:linear": uid,
                           "ctx": ctx, "ctx:linear": ctx}}
    state = trainer.init(jax.random.PRNGKey(0), trainer.shard_batch(mk(0)))
    # warm the cache with the whole hot set (inserts happen here)
    for i in range(16):
        state, m = trainer.train_step(state, mk(i))
    jax.block_until_ready(m["loss"])

    # fresh batches, all hits (no inserts left in the hot set)
    fresh = [mk(i) for i in range(16, 48)]
    t0 = time.perf_counter()
    for b in fresh:
        state, m = trainer.train_step(state, b)
    jax.block_until_ready(m["loss"])
    per = (time.perf_counter() - t0) / len(fresh)
    print(f"all-hit step, fresh batches:  {per*1e3:8.2f} ms "
          f"({batch/per:,.0f} ex/s)")

    # reused batches (same np arrays round robin)
    reuse = fresh[:4]
    t0 = time.perf_counter()
    for i in range(32):
        state, m = trainer.train_step(state, reuse[i % 4])
    jax.block_until_ready(m["loss"])
    per = (time.perf_counter() - t0) / 32
    print(f"all-hit step, reused batches: {per*1e3:8.2f} ms "
          f"({batch/per:,.0f} ex/s)")

    # 5. insert cost alone at the bench's steady-state miss count (~1700)
    from openembedding_tpu import hash_table as hash_lib  # noqa: F401
    miss = 1700
    cold = np.arange(1_000_000, 1_000_000 + 64 * miss,
                     dtype=np.int32).reshape(64, miss)
    emb = state.emb
    t0 = time.perf_counter()
    for i in range(32):
        ids = cold[i % 64]
        emb["uid"] = table._insert_from_host(emb["uid"], ids)
    jax.block_until_ready(emb["uid"].keys)
    per = (time.perf_counter() - t0) / 32
    print(f"insert {miss} rows (uid table): {per*1e3:8.2f} ms")
    table.check_overflow()

    # 6. prepared-batch apply path (insert via apply_prepared, both tables)
    t0 = time.perf_counter()
    n = 16
    for i in range(n):
        ids = cold[(i + 32) % 64]
        for t in (table, lin):
            prep = t.host_prepare(ids)
            emb[t.name] = t.apply_prepared(emb[t.name], prep)
    jax.block_until_ready(emb["uid"].keys)
    per = (time.perf_counter() - t0) / n
    print(f"host_prepare+apply both tables ({miss} misses): "
          f"{per*1e3:8.2f} ms")
    table.check_overflow()
    lin.check_overflow()


if __name__ == "__main__":
    main()
