"""Offload-tier diagnosis suite: one harness, one subcommand per probe.

Consolidates the seven-stage diagnosis trail (offload_diag.py +
offload_diag2-7.py) behind a single CLI; each subcommand reproduces one
stage's measurement on the live backend:

    python -m tools.offload_diag transfers   # h2d bandwidth + tiny-d2h RTT
    python -m tools.offload_diag steps       # all-hit step: fresh vs reused batches
    python -m tools.offload_diag inserts     # insert program cost, per-iter + resubmit
    python -m tools.offload_diag phases      # device-blocked per-piece timings
    python -m tools.offload_diag serial      # serial path: apply/h2d/step/note per iter
    python -m tools.offload_diag isolate     # A/B/C loops: h2d-only / step-only / insert+put
    python -m tools.offload_diag puts        # N-small-puts vs one-big-put fixed overhead
    python -m tools.offload_diag pipeline    # steady-state host-call stalls + breakdown

HISTORICAL NOTE (the diagnosis story these stages told, r5): the r5
suite measured offload steps at ~242-335 ms with only ~25 ms of host
prepare. Stage by stage the gap localized NOT to payload bytes but to
per-call fixed overhead: on a degraded tunnel every HOST-BLOCKING device
call cost ~105 ms regardless of size (``puts``), and the per-step
deferred-overflow reads were the tier's per-step blocker (fixed since:
join-point-only overflow reads + ``overflow_check_every_n_batches``).
The early "all-hit" labels in ``steps`` were wrong — a 16-batch warmup
covers only ~28% of the 200k-id hot set, so that loop still carried
insert traffic; the fresh-vs-reused 30x gap it exposed was the first
signal of the fixed-overhead story.

Run with the TPU tunnel healthy; every subcommand also runs on CPU for
plumbing checks (numbers are then about the CPU backend, not the tier).
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

VOCAB, CACHE_CAP, DIM, BATCH = 2_000_000, 1 << 22, 8, 4096
MISS = 1700   # the bench's steady-state per-batch miss count


def timeit(fn, n=20, warmup=3):
    for _ in range(warmup):
        r = fn()
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    jax.block_until_ready(r)
    return (time.perf_counter() - t0) / n


class Harness:
    """The shared fixture: 2M-row offloaded uid (+:linear) tables plus an
    in-HBM ctx pair under a deepfm Trainer — the bench's offload shape."""

    def __init__(self, pipeline_depth=2):
        import optax
        from openembedding_tpu import (EmbeddingCollection, EmbeddingSpec,
                                       EmbeddingVariableMeta, Trainer)
        from openembedding_tpu.models import deepctr
        from openembedding_tpu.offload import ShardedOffloadedTable
        from openembedding_tpu.parallel.mesh import create_mesh

        self.mesh = create_mesh(1, len(jax.devices()))
        opt = {"category": "adagrad", "learning_rate": 0.01}
        init = {"category": "constant", "value": 0.01}
        self.table = ShardedOffloadedTable(
            "uid", EmbeddingVariableMeta(embedding_dim=DIM,
                                         vocabulary_size=VOCAB),
            opt, init, vocab=VOCAB, cache_capacity=CACHE_CAP,
            mesh=self.mesh)
        self.lin = ShardedOffloadedTable(
            "uid:linear", EmbeddingVariableMeta(embedding_dim=1,
                                                vocabulary_size=VOCAB),
            opt, init, vocab=VOCAB, cache_capacity=CACHE_CAP,
            mesh=self.mesh)
        specs = (self.table.embedding_spec(), self.lin.embedding_spec(),
                 EmbeddingSpec(name="ctx", input_dim=100_000,
                               output_dim=DIM, optimizer=opt),
                 EmbeddingSpec(name="ctx:linear", input_dim=100_000,
                               output_dim=1, optimizer=opt))
        coll = EmbeddingCollection(specs, self.mesh)
        self.trainer = Trainer(
            deepctr.build_model("deepfm", ("uid", "ctx")), coll,
            optax.adagrad(0.01),
            offload={"uid": self.table, "uid:linear": self.lin},
            pipeline_depth=pipeline_depth)
        self.rng = np.random.RandomState(0)

    def batch_from(self, uid):
        ctx = (uid * 7 % 100_000).astype(np.int32)
        return {"label": (uid % 4 == 0).astype(np.float32),
                "dense": np.tile((uid % 13).astype(np.float32)[:, None],
                                 (1, 13)),
                "sparse": {"uid": uid, "uid:linear": uid,
                           "ctx": ctx, "ctx:linear": ctx}}

    def hot_batch(self, hi=30_000):
        return self.batch_from(
            self.rng.randint(0, hi, BATCH).astype(np.int32))

    def miss_batch(self, i, hot_hi=30_000, cold_base=40_000):
        """~MISS new ids per batch on top of a resident hot head."""
        hot = self.rng.randint(0, hot_hi, BATCH - MISS).astype(np.int32)
        new = np.arange(cold_base + i * MISS, cold_base + (i + 1) * MISS,
                        dtype=np.int32)
        return self.batch_from(np.concatenate([hot, new]))

    def warm(self, steps=3, mk=None):
        mk = mk or self.hot_batch
        state = self.trainer.init(jax.random.PRNGKey(0),
                                  self.trainer.shard_batch(mk()))
        m = None
        for _ in range(steps):
            state, m = self.trainer.train_step(state, mk())
        if m is not None:
            jax.block_until_ready(m["loss"])
        self.table.check_overflow()
        self.lin.check_overflow()
        return state


# --- subcommands -------------------------------------------------------------

def cmd_transfers(_args):
    """Stage 1-2: raw h2d bandwidth (fresh buffers) + tiny-d2h latency."""
    dev = jax.devices()[0]
    print(f"platform={dev.platform}")
    for mb in (0.0625, 0.5, 4.0):
        nbytes = int(mb * (1 << 20))
        bufs = [np.random.rand(nbytes // 8).astype(np.float64)
                for _ in range(8)]
        i = [0]

        def put():
            i[0] += 1
            return jax.device_put(bufs[i[0] % len(bufs)], dev)
        dt = timeit(put)
        print(f"h2d {mb:7.4f} MB: {dt*1e3:8.2f} ms  "
              f"{mb/1024/dt:8.3f} GB/s")
    c = jnp.int32(7) + 1

    def get():
        return int(jax.device_get(c))
    dt = timeit(lambda: jnp.asarray(get()))
    print(f"d2h tiny round trip: {dt*1e3:.2f} ms")


def cmd_steps(_args):
    """Stage 3-4: train step over a resident working set, fresh batches
    vs reused np arrays (isolates fresh-h2d cost). NOTE the all-hit
    label is approximate: warmup covers ~28% of the 200k hot set."""
    h = Harness()
    hot = h.rng.randint(0, 200_000, size=(64, BATCH)).astype(np.int32)

    def mk(i):
        return h.batch_from(hot[i % len(hot)])
    state = h.trainer.init(jax.random.PRNGKey(0),
                           h.trainer.shard_batch(mk(0)))
    m = None
    for i in range(16):
        state, m = h.trainer.train_step(state, mk(i))
    jax.block_until_ready(m["loss"])

    fresh = [mk(i) for i in range(16, 48)]
    t0 = time.perf_counter()
    for b in fresh:
        state, m = h.trainer.train_step(state, b)
    jax.block_until_ready(m["loss"])
    per = (time.perf_counter() - t0) / len(fresh)
    print(f"all-hit step, fresh batches:  {per*1e3:8.2f} ms "
          f"({BATCH/per:,.0f} ex/s)")

    reuse = fresh[:4]
    t0 = time.perf_counter()
    for i in range(32):
        state, m = h.trainer.train_step(state, reuse[i % 4])
    jax.block_until_ready(m["loss"])
    per = (time.perf_counter() - t0) / 32
    print(f"all-hit step, reused batches: {per*1e3:8.2f} ms "
          f"({BATCH/per:,.0f} ex/s)")


def cmd_inserts(_args):
    """Stage 5 + diag3: the device insert program alone — batch cost at
    the steady-state miss count, per-iteration trace (recompile check),
    and an all-present resubmit (pure probe, no insert)."""
    h = Harness()
    cache = h.table.create_cache()
    jax.block_until_ready(cache.keys)
    for i in range(12):
        ids = np.arange(1000 + i * MISS, 1000 + (i + 1) * MISS,
                        dtype=np.int32)
        t0 = time.perf_counter()
        cache = h.table._insert_from_host(cache, ids)
        jax.block_until_ready(cache.keys)
        print(f"iter {i:2d}: {1e3*(time.perf_counter()-t0):8.2f} ms")
    ids = np.arange(1000, 1000 + MISS, dtype=np.int32)
    t0 = time.perf_counter()
    cache = h.table._insert_from_host(cache, ids)
    jax.block_until_ready(cache.keys)
    print(f"resubmit (all present): "
          f"{1e3*(time.perf_counter()-t0):8.2f} ms")
    h.table._overflow_latest = None

    # prepared-batch path through both tables (host_prepare + apply)
    state = h.warm(steps=3)
    emb = dict(state.emb)
    cold = np.arange(1_000_000, 1_000_000 + 64 * MISS,
                     dtype=np.int32).reshape(64, MISS)
    t0 = time.perf_counter()
    n = 16
    for i in range(n):
        ids = cold[i % 64]
        for t in (h.table, h.lin):
            prep = t.host_prepare(ids)
            emb[t.name] = t.apply_prepared(emb[t.name], prep)
    jax.block_until_ready(emb["uid"].keys)
    per = (time.perf_counter() - t0) / n
    print(f"host_prepare+apply both tables ({MISS} misses): "
          f"{per*1e3:8.2f} ms")
    h.table.check_overflow()
    h.lin.check_overflow()


def cmd_phases(_args):
    """Stage diag2: every piece device-blocked per call — insert program,
    jitted step (blocked + async), shard_batch h2d, zero-miss apply."""
    h = Harness()

    def mk():
        return h.batch_from(
            h.rng.randint(0, 50_000, BATCH).astype(np.int32))
    state = h.trainer.init(jax.random.PRNGKey(0),
                           h.trainer.shard_batch(mk()))
    m = None
    for _ in range(14):   # make [0, 50k) resident
        state, m = h.trainer.train_step(state, mk())
    jax.block_until_ready(m["loss"])
    h.table.check_overflow()
    h.lin.check_overflow()

    emb = dict(state.emb)
    n = 16
    t0 = time.perf_counter()
    for i in range(n):
        ids = np.arange(100_000 + i * MISS, 100_000 + (i + 1) * MISS,
                        dtype=np.int32)
        emb["uid"] = h.table._insert_from_host(emb["uid"], ids)
        jax.block_until_ready(emb["uid"].keys)
    per = (time.perf_counter() - t0) / n
    print(f"a) insert {MISS} rows, device-blocked:    {per*1e3:8.2f} ms")
    h.table._overflow_latest = None

    bt = [mk() for _ in range(8)]
    sb = [h.trainer.shard_batch(b) for b in bt]
    t0 = time.perf_counter()
    for i in range(16):
        state, m = h.trainer._train_step(state, sb[i % 8])
        jax.block_until_ready(m["loss"])
    per = (time.perf_counter() - t0) / 16
    print(f"b) jitted step, presharded, blocked:    {per*1e3:8.2f} ms")
    t0 = time.perf_counter()
    for i in range(16):
        state, m = h.trainer._train_step(state, sb[i % 8])
    jax.block_until_ready(m["loss"])
    per = (time.perf_counter() - t0) / 16
    print(f"b2) jitted step, presharded, async:     {per*1e3:8.2f} ms")

    t0 = time.perf_counter()
    for i in range(16):
        out = h.trainer.shard_batch(bt[i % 8])
        jax.block_until_ready(jax.tree.leaves(out))
    per = (time.perf_counter() - t0) / 16
    print(f"c) shard_batch h2d, blocked:            {per*1e3:8.2f} ms")

    t0 = time.perf_counter()
    for i in range(16):
        prep = h.table.host_prepare(bt[i % 8]["sparse"]["uid"])
        emb2 = h.table.apply_prepared(state.emb["uid"], prep)
        jax.block_until_ready(jax.tree.leaves(emb2))
    per = (time.perf_counter() - t0) / 16
    print(f"d) prepare+apply, zero misses, blocked: {per*1e3:8.2f} ms")


def cmd_serial(_args):
    """Stage diag4: the serial path per-phase — apply_prepared /
    shard_batch / jitted step / note_update, per iteration (run with
    jax_log_compiles to spot recompiles)."""
    h = Harness()
    state = h.trainer.init(jax.random.PRNGKey(0),
                           h.trainer.shard_batch(h.miss_batch(0)))
    m = None
    for i in range(6):
        state, m = h.trainer.train_step(state, h.miss_batch(i + 1))
    jax.block_until_ready(m["loss"])
    print("--- warmup done; per-phase timing (serial path) ---",
          flush=True)
    for i in range(8):
        b = h.miss_batch(100 + i)
        t0 = time.perf_counter()
        state2, uniqs = h.trainer._apply_prepared_offload(state, b)
        jax.block_until_ready(jax.tree.leaves(state2.emb["uid"].keys))
        t1 = time.perf_counter()
        sb = h.trainer.shard_batch(b)
        jax.block_until_ready(jax.tree.leaves(sb))
        t2 = time.perf_counter()
        state3, m = h.trainer._train_step(state2, sb)
        jax.block_until_ready(m["loss"])
        t3 = time.perf_counter()
        for name, t in h.trainer.offload.items():
            t.note_update(b["sparse"][name], uniq=uniqs.get(name))
        t4 = time.perf_counter()
        state = state3
        print(f"iter {i}: apply={1e3*(t1-t0):7.2f}  h2d={1e3*(t2-t1):6.2f}"
              f"  step={1e3*(t3-t2):7.2f}  note={1e3*(t4-t3):6.2f} ms",
              flush=True)


def cmd_isolate(_args):
    """Stage diag5: three loops isolating the ~105 ms per-device-call
    collapse — fresh-batch h2d only, step only (reused presharded),
    insert only alternating with a 500 KB put."""
    h = Harness()
    state = h.warm(steps=3)
    print("A) fresh-batch h2d only:", flush=True)
    for i in range(20):
        b = h.hot_batch()
        t0 = time.perf_counter()
        sb = h.trainer.shard_batch(b)
        jax.block_until_ready(jax.tree.leaves(sb))
        print(f"  {i:2d}: {1e3*(time.perf_counter()-t0):7.2f} ms",
              flush=True)
    print("B) step only, reused presharded batch:", flush=True)
    sb = h.trainer.shard_batch(h.hot_batch())
    for i in range(20):
        t0 = time.perf_counter()
        state, m = h.trainer._train_step(state, sb)
        jax.block_until_ready(m["loss"])
        print(f"  {i:2d}: {1e3*(time.perf_counter()-t0):7.2f} ms",
              flush=True)
    print("C) insert only, fresh keys + fresh 500KB h2d:", flush=True)
    emb = dict(state.emb)
    for i in range(20):
        ids = np.arange(50_000 + i * MISS, 50_000 + (i + 1) * MISS,
                        dtype=np.int32)
        filler = np.random.rand(4096, 32).astype(np.float32)
        t0 = time.perf_counter()
        d = jax.device_put(filler)
        emb["uid"] = h.table._insert_from_host(emb["uid"], ids)
        jax.block_until_ready([d, emb["uid"].keys])
        print(f"  {i:2d}: {1e3*(time.perf_counter()-t0):7.2f} ms",
              flush=True)
    h.table._overflow_latest = None


def cmd_puts(_args):
    """Stage diag6: per-transfer fixed overhead — do N small puts cost
    ~N x one big put of the same total bytes? (Enter the trainer's
    degraded mode first, then measure.)"""
    h = Harness()
    h.warm(steps=3)
    print("degraded-mode entered (trainer warm)", flush=True)
    kb = 40  # ~12 arrays x 40 KB = the offload step's transfer profile
    for label, n_arrays in (("12 x 40KB", 12), ("1 x 480KB", 1),
                            ("3 x 160KB", 3)):
        per_bytes = kb * 1024 * 12 // n_arrays
        times = []
        for _it in range(8):
            bufs = [np.random.randint(0, 1 << 30, per_bytes // 4)
                    .astype(np.int32) for _ in range(n_arrays)]
            t0 = time.perf_counter()
            out = [jax.device_put(b) for b in bufs]
            jax.block_until_ready(out)
            times.append(time.perf_counter() - t0)
        times.sort()
        print(f"{label}: median {1e3*times[len(times)//2]:7.2f} ms "
              f"(min {1e3*times[0]:.2f}, max {1e3*times[-1]:.2f})",
              flush=True)
    bufs = [np.random.randint(0, 1 << 30, kb * 256).astype(np.int32)
            for _ in range(24)]
    t0 = time.perf_counter()
    out = [jax.device_put(b) for b in bufs]
    jax.block_until_ready(out)
    print(f"24 x 40KB async batch: {1e3*(time.perf_counter()-t0):7.2f} ms "
          f"total", flush=True)


def cmd_pipeline(_args):
    """Stage diag7: the REAL loop with no explicit blocks — which host
    call stalls? Plus a per-call apply_prepared/check_overflow
    breakdown via monkeypatched timers."""
    h = Harness(pipeline_depth=1)
    state = h.trainer.init(jax.random.PRNGKey(0),
                           h.trainer.shard_batch(h.miss_batch(0)))
    m = None
    for i in range(12):  # past the overflow-check depth: steady state
        state, m = h.trainer.train_step(state, h.miss_batch(i + 1))
    jax.block_until_ready(m["loss"])
    print("steady state reached; timing host calls (NO explicit blocks)",
          flush=True)
    timed = [h.miss_batch(100 + i) for i in range(24)]
    t_total0 = time.perf_counter()
    rows = []
    for i, b in enumerate(timed):
        t0 = time.perf_counter()
        h.trainer.prefetch(timed[i:i + 2])
        t1 = time.perf_counter()
        state, uniqs = h.trainer._apply_prepared_offload(state, b)
        t2 = time.perf_counter()
        sb = h.trainer.shard_batch(b)
        t3 = time.perf_counter()
        state, m = h.trainer._train_step(state, sb)
        t4 = time.perf_counter()
        for name, t in h.trainer.offload.items():
            t.note_update(b["sparse"][name], uniq=uniqs.get(name))
        t5 = time.perf_counter()
        rows.append((t1 - t0, t2 - t1, t3 - t2, t4 - t3, t5 - t4))
    jax.block_until_ready(m["loss"])
    total = time.perf_counter() - t_total0
    print("  prefetch   apply    h2d   stepdisp  note  (ms)")
    for r in rows:
        print("  " + "  ".join(f"{1e3*x:7.2f}" for x in r))
    print(f"TOTAL {1e3*total/len(timed):.2f} ms/step", flush=True)

    import openembedding_tpu.offload as off
    orig_apply = off.ShardedOffloadedTable.apply_prepared
    orig_co = off.ShardedOffloadedTable.check_overflow

    def timed_apply(self, cache, prep):
        t0 = time.perf_counter()
        out = orig_apply(self, cache, prep)
        print(f"    apply_prepared[{self.name}]: "
              f"{1e3*(time.perf_counter()-t0):.2f} ms", flush=True)
        return out

    def timed_co(self, cache=None):
        t0 = time.perf_counter()
        out = orig_co(self, cache)
        print(f"      check_overflow[{self.name}] live={cache is not None}"
              f": {1e3*(time.perf_counter()-t0):.2f} ms", flush=True)
        return out
    off.ShardedOffloadedTable.apply_prepared = timed_apply
    off.ShardedOffloadedTable.check_overflow = timed_co
    try:
        print("--- per-call breakdown, 4 steps ---", flush=True)
        extra = [h.miss_batch(200 + i) for i in range(4)]
        for i, b in enumerate(extra):
            h.trainer.prefetch(extra[i:i + 2])
            state, m = h.trainer.train_step(state, b)
        jax.block_until_ready(m["loss"])
    finally:
        off.ShardedOffloadedTable.apply_prepared = orig_apply
        off.ShardedOffloadedTable.check_overflow = orig_co


COMMANDS = {
    "transfers": cmd_transfers,
    "steps": cmd_steps,
    "inserts": cmd_inserts,
    "phases": cmd_phases,
    "serial": cmd_serial,
    "isolate": cmd_isolate,
    "puts": cmd_puts,
    "pipeline": cmd_pipeline,
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="offload-tier per-step cost localization")
    ap.add_argument("command", choices=sorted(COMMANDS),
                    help="which probe to run (see module docstring)")
    ap.add_argument("--log_compiles", action="store_true",
                    help="enable jax_log_compiles during the probe")
    args = ap.parse_args(argv)
    if args.log_compiles:
        import logging
        jax.config.update("jax_log_compiles", True)
        logging.basicConfig(level=logging.WARNING)
    COMMANDS[args.command](args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
