"""Merge re-measured config entries into bench_suite.json.

Used when individual configs are re-run (``bench.py --configs NAME``)
after a suite pass — e.g. entries captured while the device tunnel was
still recovering from a wedge, or deviceless entries skewed by host CPU
contention. Each merged entry is stamped with the merge time and a note
naming what it replaces, so provenance stays explicit.

Usage: python tools/merge_suite.py <lines.jsonl> [note]
  lines.jsonl: one bench JSON line per re-measured config (``=== name``
  separator lines and non-JSON noise are ignored).
"""
import datetime
import json
import os
import sys


def main():
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "bench_suite.json")
    note = sys.argv[2] if len(sys.argv) > 2 else "re-measured"
    with open(sys.argv[1]) as f:
        fresh = [json.loads(ln) for ln in f
                 if ln.strip().startswith("{")]
    with open(path) as f:
        suite = json.load(f)
    now = datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds")
    def key(e):
        # metric names carry a platform suffix; key on the config block
        # (unique per suite entry) so a cpu-rerun can replace a tpu entry;
        # entries without one (older formats) fall back to the metric name
        return json.dumps(e.get("config") or e.get("metric", "?"),
                          sort_keys=True)

    by_config = {}
    for e in fresh:
        by_config[key(e)] = e
    merged, replaced = [], []
    for e in suite:
        k = key(e)
        if k in by_config:
            new = by_config.pop(k)
            new.setdefault("ts", now)
            new["note"] = f"{note}; replaces entry measured {e.get('ts')}"
            merged.append(new)
            replaced.append(new.get("metric", "?"))
        else:
            merged.append(e)
    for e in by_config.values():  # configs not present before
        e.setdefault("ts", now)
        e["note"] = note
        merged.append(e)
        replaced.append(e.get("metric", "?"))
    with open(path, "w") as f:
        json.dump(merged, f, indent=2)
    print(f"replaced/added {len(replaced)}: {replaced}")


if __name__ == "__main__":
    main()
